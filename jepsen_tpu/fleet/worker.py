"""The fleet worker: claim → execute → renew → complete, over HTTP.

``cli fleet work --coordinator URL`` runs one of these until the
coordinator reports the campaign finished.  Execution is exactly
`campaign.core.execute_run` — shrink-on-invalid, telemetry streaming,
crash→attributable-record semantics all included — so a distributed
cell's index record is indistinguishable from a single-process one
(modulo the ``fleet-worker`` stamp the coordinator adds).

Resilience contract:

- every control-plane call goes through `resilience.device_call` with
  a seeded `RetryPolicy` and the :func:`~.policy.is_transient_http`
  classifier — connection refusals (a coordinator restarting),
  timeouts, 502/503/504, and injected `FaultInjected` transients are
  ridden out with bounded backoff; 4xx protocol errors propagate.
  The call sites are the ``fleet.*`` fault-plan family, so a plan
  installed in the worker process (``JEPSEN_FAULTS`` env in the chaos
  soak) drops/stalls the client side of the same seams the
  coordinator guards server-side.
- a renewer thread heartbeats + renews the lease at ``lease/3`` while
  a cell runs; a LOST lease (the coordinator expired it — e.g. after a
  partition) is noted but execution continues: the completion is then
  either the first verdict (accepted) or a zombie duplicate the
  coordinator discards.  Renewer failures never kill the run.
- SIGTERM (``cli fleet work`` installs the handler) drains gracefully:
  the in-flight cell finishes and uploads, a claimed-but-unstarted
  cell is released back to the queue, and the loop exits.
"""

from __future__ import annotations

import json
import logging
import os
import random
import socket
import threading
import time
import urllib.request
from typing import Any, Dict, List, Optional

from jepsen_tpu import resilience, store
from jepsen_tpu.campaign.plan import RunSpec
from jepsen_tpu.campaign.scheduler import crash_record
from jepsen_tpu.resilience import RetryPolicy
from jepsen_tpu.resilience.policy import is_transient_http

logger = logging.getLogger("jepsen.fleet")

__all__ = ["FleetWorker"]


class FleetWorker:
    """One remote executor against a fleet coordinator."""

    def __init__(self, coordinator: str, base: Optional[str] = None, *,
                 name: Optional[str] = None, device_slots: int = 1,
                 backend: Optional[str] = None, mesh: Any = None,
                 poll_s: float = 0.5,
                 lease_s: float = 15.0,
                 retry: Optional[RetryPolicy] = None,
                 timeout_s: float = 10.0,
                 claim_budget_s: float = 120.0):
        self.url = coordinator.rstrip("/")
        self.base = base or store.BASE
        self.name = name or f"{socket.gethostname()}-{os.getpid()}"
        self.device_slots = int(device_slots)
        self.backend = backend
        self.mesh = mesh
        self.poll_s = float(poll_s)
        self.lease_s = float(lease_s)  # server value adopted at register
        self.timeout_s = float(timeout_s)
        #: how long claim outages are ridden out before giving up —
        #: spent in seeded-jittered backoff sleeps (ISSUE 11 satellite:
        #: each worker's delay stream is seeded from its own name, so a
        #: fleet recovering from a coordinator outage doesn't
        #: synchronize its re-poll storm)
        self.claim_budget_s = float(claim_budget_s)
        self._backoff_rng = random.Random(f"{self.name}|claim-backoff")
        # generous by default: the retry window must cover a
        # coordinator kill -9 + restart (a few seconds of ECONNREFUSED)
        self.retry = retry or RetryPolicy(
            max_attempts=8, base_delay_s=0.2, multiplier=2.0,
            max_delay_s=2.0, classify=is_transient_http)
        #: SIGTERM drain flag (cli fleet work sets it from the handler)
        self.stop = threading.Event()
        self.cells_done = 0
        self.duplicates = 0
        #: the last installed window set (digest + descriptors) — what
        #: heartbeat ticks report while a scheduled cell runs
        self.installed_windows: Optional[Dict[str, Any]] = None

    # -- transport -----------------------------------------------------------

    def _post(self, site: str, path: str,
              doc: Dict[str, Any]) -> Dict[str, Any]:
        """One guarded control-plane POST: the active fault plan fires
        at `site` (client-side chaos), transients retry per the
        policy."""
        body = json.dumps(doc).encode()

        def send() -> Dict[str, Any]:
            req = urllib.request.Request(
                self.url + path, data=body,
                headers={"Content-Type": "application/json"},
                method="POST")
            with urllib.request.urlopen(req,
                                        timeout=self.timeout_s) as r:
                return json.loads(r.read().decode() or "{}")

        return resilience.device_call(site, send, policy=self.retry)

    # -- protocol ------------------------------------------------------------

    def register(self) -> Dict[str, Any]:
        r = self._post("fleet.register", "/fleet/register", {
            "worker": self.name, "host": socket.gethostname(),
            "backend": self.backend, "mesh": self.mesh,
            "device-slots": self.device_slots})
        if isinstance(r.get("lease-s"), (int, float)):
            self.lease_s = float(r["lease-s"])
        logger.info("fleet worker %s registered with %s (campaign %s, "
                    "lease %.1fs)", self.name, self.url,
                    r.get("campaign"), self.lease_s)
        return r

    def _claim_backoff(self, fails: int) -> float:
        """One seeded-jittered backoff delay for the `fails`-th
        consecutive claim outage: exponential from `poll_s`, capped,
        each draw scaled by a per-worker random factor — two workers
        with the same poll settings still desynchronize their re-poll
        storms against a recovering coordinator."""
        base = min(self.poll_s * (2.0 ** max(0, fails - 1)), 5.0)
        return base * self._backoff_rng.uniform(0.5, 1.5)

    def run(self) -> int:
        """Claim-execute until the campaign finishes (or SIGTERM
        drains); returns the number of cells this worker completed."""
        self.register()
        claim_fails = 0
        claim_waited = 0.0
        while not self.stop.is_set():
            try:
                r = self._post("fleet.claim", "/fleet/claim",
                               {"worker": self.name})
            except Exception as e:  # noqa: BLE001 — outage outlasting
                # the retry budget: keep polling under seeded jittered
                # backoff (a daemon rides out long partitions), give up
                # only once the configured budget is spent
                claim_fails += 1
                delay = self._claim_backoff(claim_fails)
                if claim_waited + delay > self.claim_budget_s:
                    logger.error(
                        "fleet worker %s: claim outage outlasted the "
                        "%.1fs budget (%d attempts); giving up",
                        self.name, self.claim_budget_s, claim_fails)
                    raise
                claim_waited += delay
                logger.warning("fleet worker %s: claim failed (%s); "
                               "re-polling in %.2fs", self.name, e,
                               delay)
                time.sleep(delay)
                continue
            claim_fails = 0
            claim_waited = 0.0
            spec = r.get("spec")
            if not spec:
                if r.get("finished"):
                    break
                time.sleep(self.poll_s)
                continue
            if self.stop.is_set():
                # drained between claim and start: give the cell back
                # instead of sitting on the lease until it lapses
                self._post("fleet.release", "/fleet/release",
                           {"worker": self.name, "run": spec["run_id"]})
                break
            self._run_cell(spec, r.get("windows"))
        logger.info("fleet worker %s done: %d cells completed "
                    "(%d duplicates discarded upstream)",
                    self.name, self.cells_done, self.duplicates)
        return self.cells_done

    def _install_windows(self, rs: RunSpec,
                         windows: Optional[Dict[str, Any]]) -> None:
        """Install the claim response's synchronized window set before
        `execute_run` (ISSUE 11 tentpole).  The claim broadcast is
        authoritative: it overrides whatever the ledger's serialized
        spec carried (a cell enqueued before the schedule existed, or
        by an older coordinator), so every host's cell for generation
        *g* runs the same seeded windows at the same schedule
        positions.  The worker's name rides along as the executing
        host, the attribution the cross-host fault-window ddmin
        surfaces."""
        from jepsen_tpu.campaign.plan import windows_digest

        rs.opts["_fleet-host"] = self.name
        wins = (windows or {}).get("set")
        if wins is not None:
            rs.opts["nemesis-windows"] = wins
        wins = rs.opts.get("nemesis-windows")
        if wins:
            self.installed_windows = {
                "gen": int(rs.seed),
                "digest": windows_digest(wins),
                "set": wins,
            }
            want = (windows or {}).get("digest")
            if want and want != self.installed_windows["digest"]:
                logger.warning(
                    "fleet worker %s: installed window digest %s != "
                    "coordinator's %s for gen %s", self.name,
                    self.installed_windows["digest"], want, rs.seed)
        else:
            self.installed_windows = None

    def _window_ticks(self, t0: float) -> Optional[Dict[str, Any]]:
        """The heartbeat's chaos-clock payload: installed digest plus
        which schedule positions are open right now (derived from the
        deterministic window offsets and the cell's elapsed wall
        clock) — lease renewal doubles as window open/close tick
        sync."""
        iw = self.installed_windows
        if not iw:
            return None
        elapsed = time.monotonic() - t0
        open_: List[Dict[str, Any]] = [
            {"pos": w.get("pos"), "fault": w.get("fault")}
            for w in iw["set"]
            if w["at_s"] <= elapsed < w["at_s"] + w["dur_s"]]
        return {"gen": iw["gen"], "digest": iw["digest"],
                "n": len(iw["set"]), "open": open_,
                "elapsed": round(elapsed, 3)}

    def _run_cell(self, spec: Dict[str, Any],
                  windows: Optional[Dict[str, Any]] = None) -> None:
        from jepsen_tpu.campaign.core import execute_run

        rs = RunSpec.from_dict(spec)
        rs.opts["_base"] = self.base
        self._install_windows(rs, windows)
        run_id = rs.run_id
        state = {"run": run_id, "workload": rs.workload_label,
                 "fault": rs.fault_label, "seed": rs.seed,
                 "slot": None, "worker-host": socket.gethostname()}
        if self.installed_windows:
            state["windows-digest"] = self.installed_windows["digest"]
        stop_renew = threading.Event()
        lease_lost = threading.Event()
        t0 = time.monotonic()

        def renew_loop() -> None:
            # heartbeat + renew at lease/3; failures are logged, never
            # fatal — a lapsed lease just makes the completion racy,
            # which the coordinator's at-most-once rule resolves
            while not stop_renew.wait(max(0.2, self.lease_s / 3.0)):
                try:
                    r = self._post("fleet.heartbeat", "/fleet/heartbeat",
                                   {"worker": self.name, "state": state,
                                    "windows": self._window_ticks(t0),
                                    "renew": [run_id]})
                    if run_id in (r.get("lost") or []):
                        lease_lost.set()
                        logger.warning(
                            "fleet worker %s: lease on %s LOST "
                            "(requeued elsewhere); finishing anyway",
                            self.name, run_id)
                    want = r.get("windows-digest")
                    if want and self.installed_windows and \
                            want != self.installed_windows["digest"]:
                        logger.warning(
                            "fleet worker %s: window desync on %s "
                            "(installed %s, coordinator %s); will "
                            "reinstall at next claim", self.name,
                            run_id, self.installed_windows["digest"],
                            want)
                except Exception as e:  # noqa: BLE001 — best-effort
                    logger.warning("fleet worker %s: heartbeat failed "
                                   "(%s)", self.name, e)

        # announce the claim before execution so the live dashboard
        # names the in-flight cell even if the run wedges instantly
        try:
            self._post("fleet.heartbeat", "/fleet/heartbeat",
                       {"worker": self.name, "state": state,
                        "windows": self._window_ticks(t0),
                        "renew": [run_id]})
        except Exception:  # noqa: BLE001
            pass
        renewer = threading.Thread(target=renew_loop, daemon=True,
                                   name=f"fleet-renew-{self.name}")
        renewer.start()
        t0 = time.monotonic()  # the window tick clock: workload start
        # mesh capability -> default-mesh shard count (PR 10 follow-on,
        # ISSUE 12 satellite): a cell pinning opts["mesh"] — or a worker
        # advertising one — runs its device checks sharded over exactly
        # that many devices.  The pin is THREAD-LOCAL
        # (slots.set_forced_shards): several workers may share one
        # process, and a process-global env pin would leak across their
        # concurrently executing cells
        import math

        from jepsen_tpu.fleet.queue import _norm_mesh
        from jepsen_tpu.parallel import slots as slots_mod

        want_mesh = _norm_mesh(rs.opts.get("mesh")) or \
            _norm_mesh(self.mesh)
        if want_mesh:
            slots_mod.set_forced_shards(math.prod(want_mesh))
        try:
            rec = execute_run(rs, self.base)
        except Exception as e:  # noqa: BLE001 — same contract as the
            # scheduler: whatever escapes execute_run becomes an
            # attributable unknown record, never a worker crash
            rec = crash_record(rs, f"{type(e).__name__}: {e}", 1,
                               time.monotonic() - t0)
        finally:
            if want_mesh:
                slots_mod.set_forced_shards(None)
            stop_renew.set()
            renewer.join(timeout=5)
        try:
            r = self._post("fleet.complete", "/fleet/complete",
                           {"worker": self.name, "run": run_id,
                            "record": rec})
            if r.get("duplicate"):
                self.duplicates += 1
                logger.warning("fleet worker %s: completion of %s was "
                               "a duplicate (cell finished elsewhere)",
                               self.name, run_id)
            else:
                self.cells_done += 1
        except Exception as e:  # noqa: BLE001 — an upload outage
            # outlasting the retries loses THIS attempt, not the cell:
            # the lease lapses, the cell requeues, and another worker
            # (or this one, next claim) re-executes it — exactly-one
            # still holds because this record never landed
            logger.warning("fleet worker %s: complete(%s) failed "
                           "beyond retries (%s); cell will requeue on "
                           "lease expiry", self.name, run_id, e)
        finally:
            self.installed_windows = None
            try:
                self._post("fleet.heartbeat", "/fleet/heartbeat",
                           {"worker": self.name, "state": None,
                            "windows": None})
            except Exception:  # noqa: BLE001
                pass
