"""The fleet autopilot (ISSUE 17): continuous verification as a
self-healing, self-scaling service.

Everything below it already runs forever — the leased `WorkQueue`,
live checks, federated metrics, the regression gate — but campaigns
are batch jobs a human starts.  The autopilot is the driver: a loop
that expands a spec template into **generations** (template ×
rotating seed order, ``opts["autopilot-gen"] = "gNNNN"``), streams
each generation into the coordinator's queue, waits for the fleet to
drain it, runs the Mann-Whitney gate (`telemetry.gate`) against the
previous generation, and reacts:

- gate rc 1 (**regression**): the offending cell key is attributed
  (largest per-key p95 delta on the regressing span), **quarantined**
  — never enqueued again, ``fleet-quarantined-cells`` gauge — and
  **auto-shrunk** through `minimize.shrink` to a witness appended to
  the campaign index, next to an ``obs diff`` forensics artifact;
- gate rc 2 (**cannot evaluate**): degrade gracefully — keep
  streaming, never quarantine on missing evidence.

Durability: autopilot state (generation ledger, quarantine set, last
verdicts, shrink outcomes) lives in an fsync'd torn-line-tolerant
jsonl journal (`AutopilotJournal`) with the same
replay-to-identical-digest discipline as `fleet/queue.py`.  The
crash-window contract: a generation is journaled (``gen-open``)
BEFORE its cells are enqueued, enqueue is idempotent on the stable
run ids, and construction re-admits every journaled generation — so
``kill -9`` anywhere (including between the journal append and the
queue enqueue) resumes with zero duplicate cells and an identical
journal digest.

Chaos: every decision seam is a guarded `resilience.device_call`
fault site — ``autopilot.enqueue``, ``autopilot.gate``,
``autopilot.shrink``, ``autopilot.scale`` — so an installed
`FaultPlan` injects into the loop's own decisions.  A failed seam
never wedges the loop: enqueue retries (idempotent), a dead gate
closes the generation with an attributable ``gate-error`` verdict, a
dead shrink journals its error, a dead scale tick is skipped.

Elasticity (second leg): `Autopilot` owns a scaler that reads the
two signals the coordinator publishes — queue depth and claim-latency
p95 — and spawns/drains local ``fleet work`` subprocesses between
``min_workers``/``max_workers`` (drain = SIGTERM: PR 8's
finish-in-flight semantics make it lossless).  Workers stamp a
``version`` at register/heartbeat; when ``worker_version`` changes
mid-campaign the scaler performs a **rolling upgrade** — spawn one
replacement, wait until it is alive at the new version, then drain
exactly one old worker — so every cell lands and /metrics cardinality
stays flat throughout.

See ``docs/AUTOPILOT.md``.
"""

from __future__ import annotations

import hashlib
import json
import logging
import math
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple, Union

from jepsen_tpu import store
from jepsen_tpu.campaign import plan as plan_mod

from .coordinator import ALIVE_LEASES, FleetCoordinator

logger = logging.getLogger("jepsen.fleet.autopilot")

__all__ = ["Autopilot", "AutopilotJournal", "autopilot_path", "GATE_RC",
           "scenario_rotation"]

#: gate status -> the ``cli obs gate`` exit-code convention the loop
#: reacts to: 1 quarantines, 2 degrades gracefully (never quarantine
#: on missing evidence)
GATE_RC = {"pass": 0, "regression": 1}


def autopilot_path(name: str, base: Optional[str] = None) -> str:
    """The autopilot journal for campaign `name` —
    ``<store>/fleet/<name>.autopilot.jsonl``, next to the queue
    ledger."""
    return os.path.join(base or store.BASE, "fleet",
                        store.sanitize(name) + ".autopilot.jsonl")


def _cell_label(cell: Any) -> str:
    """The name a rotation pivot matches against: a cell's explicit
    ``label`` if it has one, else its workload ``name``."""
    if isinstance(cell, dict):
        return str(cell.get("label") or cell.get("name") or "")
    return str(cell)


def scenario_rotation(*, pivot: Tuple[str, ...] = (),
                      slots: int = 1) -> Callable[[int, dict], dict]:
    """A deterministic ``Autopilot(mutate=...)`` that rotates
    SCENARIOS, not just seeds (ROADMAP 5c).

    Each generation keeps the **pivot** cells — the workloads the
    cross-generation gate tracks continuously, matched by cell label
    or workload name (the template's first cell when ``pivot`` is
    empty) — and fills ``slots`` extra slots by walking the remaining
    template cells in order, ``slots`` at a time, wrapping around.
    Over ``ceil(len(rest) / slots)`` generations every scenario in the
    template has run, while the pivot's span stays gate-comparable
    generation over generation.

    Pure in ``(i, template)`` — no ambient state — which is what the
    journal's replay-to-identical-digest discipline requires: resume
    after kill -9 re-derives byte-identical generation specs.
    Quarantine keys stay meaningful because rotation re-admits a cell
    with the SAME key every time its slot comes around."""
    pivots = tuple(str(p) for p in pivot)
    n_slots = max(1, int(slots))

    def mutate(i: int, sp: dict) -> dict:
        cells = list(sp.get("workloads") or [])
        if len(cells) <= 1:
            return sp
        if pivots:
            keep = [c for c in cells if _cell_label(c) in pivots]
            rest = [c for c in cells if _cell_label(c) not in pivots]
        else:
            keep, rest = [cells[0]], cells[1:]
        if not rest:
            return sp
        k = (i * n_slots) % len(rest)
        take = [rest[(k + j) % len(rest)]
                for j in range(min(n_slots, len(rest)))]
        sp["workloads"] = keep + take
        return sp

    return mutate


class AutopilotJournal:
    """The autopilot's durable brain: an append-only fsync'd jsonl
    ledger with the exact `queue.WorkQueue` discipline — in-memory
    state is a pure function of the event sequence, a torn final line
    (crash mid-append) is ignored on replay and healed by the writer
    before its first append, and `digest` pins the replayed state so
    kill -9 tests can compare independent replays.

    Events: ``gen-open`` (a generation's durable intent — written
    BEFORE its cells are enqueued), ``gen-close`` (the gate verdicts),
    ``quarantine``, ``parole`` (re-admission after clean neighbor
    generations — ROADMAP 5d; a re-quarantine of a paroled key
    archives the prior stint under ``history``), ``shrink``,
    ``scale``.  Scale events are an audit trail, not state: like the
    queue's requeue/duplicate counters they are derived telemetry and
    excluded from the digest."""

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.RLock()
        #: label -> {seeds, runs, closed, verdicts, opened-ts}
        self.gens: Dict[str, Dict[str, Any]] = {}
        #: generation labels in open order
        self.order: List[str] = []
        #: key -> {gen, span, rel-delta, ts}
        self.quarantined: Dict[str, Dict[str, Any]] = {}
        #: key -> {gen, outcome}
        self.shrinks: Dict[str, Dict[str, Any]] = {}
        #: derived audit counter (digest-excluded)
        self.scale_events = 0
        self._good_bytes = 0
        self._healed = False
        self._load()

    # -- replay --------------------------------------------------------------

    def _load(self) -> None:
        if not os.path.exists(self.path):
            return
        with open(self.path, "rb") as f:
            data = f.read()
        good = 0
        for line in data.splitlines(keepends=True):
            if not line.endswith(b"\n"):
                break  # torn tail: crash mid-append — ignore
            try:
                ev = json.loads(line.decode("utf-8"))
            except ValueError:
                break
            self._apply(ev)
            good += len(line)
        self._good_bytes = good

    def _apply(self, ev: Dict[str, Any]) -> None:
        kind = ev.get("ev")
        if kind == "gen-open":
            label = str(ev.get("gen"))
            if label not in self.gens:
                self.order.append(label)
            self.gens[label] = {
                "seeds": ev.get("seeds"), "runs": ev.get("runs"),
                "closed": False, "verdicts": None,
                "opened-ts": ev.get("ts")}
        elif kind == "gen-close":
            label = str(ev.get("gen"))
            g = self.gens.get(label)
            if g is None:
                g = self.gens[label] = {"seeds": None, "runs": None,
                                        "opened-ts": None}
                self.order.append(label)
            g["closed"] = True
            g["verdicts"] = ev.get("verdicts") or []
        elif kind == "quarantine":
            key = str(ev.get("key"))
            cur = self.quarantined.get(key)
            fresh = {"gen": ev.get("gen"), "span": ev.get("span"),
                     "rel-delta": ev.get("rel-delta"),
                     "ts": ev.get("ts")}
            if cur is None:
                self.quarantined[key] = fresh
            elif "paroled-gen" in cur:
                # a paroled key regressed again: archive the prior
                # stint so old-generation replays still exclude it
                hist = list(cur.get("history") or [])
                hist.append({"gen": cur.get("gen"),
                             "paroled-gen": cur.get("paroled-gen")})
                fresh["history"] = hist
                self.quarantined[key] = fresh
            # an active quarantine absorbs duplicate events
        elif kind == "parole":
            v = self.quarantined.get(str(ev.get("key")))
            if v is not None and "paroled-gen" not in v:
                v["paroled-gen"] = ev.get("gen")
        elif kind == "shrink":
            self.shrinks[str(ev.get("key"))] = {
                "gen": ev.get("gen"), "outcome": ev.get("outcome")}
        elif kind == "scale":
            self.scale_events += 1

    # -- append --------------------------------------------------------------

    def _event(self, ev: Dict[str, Any]) -> Dict[str, Any]:
        ev = dict(ev)
        ev["ts"] = round(time.time(), 3)
        with self._lock:
            os.makedirs(os.path.dirname(self.path) or ".",
                        exist_ok=True)
            if not self._healed:
                # only the writer heals: truncate a torn tail right
                # before the first append so readers of a crashed
                # journal replay the same prefix we extend
                if os.path.exists(self.path) and \
                        os.path.getsize(self.path) > self._good_bytes:
                    with open(self.path, "rb+") as f:
                        f.truncate(self._good_bytes)
                self._healed = True
            with open(self.path, "ab") as f:
                f.write((json.dumps(ev, sort_keys=True) + "\n")
                        .encode("utf-8"))
                f.flush()
                os.fsync(f.fileno())
            self._apply(ev)
        return ev

    def open_gen(self, label: str, *, seeds: Any = None,
                 runs: Any = None) -> None:
        self._event({"ev": "gen-open", "gen": label, "seeds": seeds,
                     "runs": runs})

    def close_gen(self, label: str,
                  verdicts: List[Dict[str, Any]]) -> None:
        self._event({"ev": "gen-close", "gen": label,
                     "verdicts": verdicts})

    def quarantine(self, key: str, *, gen: str, span: Any = None,
                   rel_delta: Any = None) -> None:
        self._event({"ev": "quarantine", "key": key, "gen": gen,
                     "span": span, "rel-delta": rel_delta})

    def parole(self, key: str, *, gen: str,
               twin: Any = None) -> None:
        """Re-admit a quarantined key: durable as of generation
        `gen`'s close — the key re-enters the plan from the NEXT
        generation on.  ``twin`` records the host-twin re-check that
        justified the parole (ISSUE 20 satellite); it is audit
        payload only — ``_apply`` reads key/gen alone, so journals
        with and without it replay to the same state."""
        ev = {"ev": "parole", "key": key, "gen": gen}
        if twin is not None:
            ev["twin"] = twin
        self._event(ev)

    def shrink(self, key: str, *, gen: str,
               outcome: Dict[str, Any]) -> None:
        self._event({"ev": "shrink", "key": key, "gen": gen,
                     "outcome": outcome})

    def scale(self, action: str, **fields: Any) -> None:
        self._event(dict({"ev": "scale", "action": action}, **fields))

    # -- state ---------------------------------------------------------------

    def closed_labels(self) -> List[str]:
        with self._lock:
            return [l for l in self.order
                    if self.gens[l].get("closed")]

    def digest(self) -> str:
        """Replayed-state digest (scale audit events excluded — they
        are derived counters, same rule as the queue's requeues)."""
        with self._lock:
            state = {
                "gens": [(l, bool(self.gens[l].get("closed")),
                          self.gens[l].get("runs"),
                          self.gens[l].get("verdicts"))
                         for l in self.order],
                "quarantined": sorted(
                    (k, v.get("gen"), v.get("span"),
                     v.get("paroled-gen"),
                     json.dumps(v.get("history") or [],
                                sort_keys=True))
                    for k, v in self.quarantined.items()),
                "shrinks": sorted(
                    (k, json.dumps(v, sort_keys=True, default=str))
                    for k, v in self.shrinks.items()),
            }
        blob = json.dumps(state, sort_keys=True, default=str)
        return hashlib.sha256(blob.encode("utf-8")).hexdigest()[:16]


class Autopilot:
    """Stream generations of `template` into a fleet forever (or for
    ``generations``), gate each one, quarantine + auto-shrink
    regressions, and keep the worker pool sized to the queue.

    The constructor owns a `FleetCoordinator` built from generation
    0's spec (mount it on `web.serve` to give workers the HTTP plane)
    and immediately **re-admits every journaled generation** — the
    crash-recovery seam: enqueue is idempotent on run ids, indexed
    cells are recognized as done, so a restart never duplicates work.
    """

    def __init__(self, template: Union[str, dict],
                 base: Optional[str] = None, *,
                 lease_s: float = 15.0,
                 run_deadline_s: Optional[float] = None,
                 generations: Optional[int] = None,
                 spans: Tuple[str, ...] = ("workload", "check:*"),
                 alpha: float = 0.05, threshold: float = 0.25,
                 min_runs: int = 3,
                 parole_after: Optional[int] = None,
                 mutate: Optional[Callable[[int, dict], dict]] = None,
                 on_generation: Optional[
                     Callable[["Autopilot", dict], None]] = None,
                 coordinator_url: Optional[str] = None,
                 min_workers: int = 0, max_workers: int = 0,
                 worker_version: str = "dev",
                 depth_per_worker: int = 2,
                 p95_high_s: float = 5.0,
                 scale_interval_s: float = 1.0,
                 worker_poll_s: float = 0.1,
                 worker_extra: Tuple[str, ...] = (),
                 shrink_knobs: Optional[Dict[str, Any]] = None,
                 alert_rules: Optional[list] = None,
                 alert_sinks: Optional[list] = None,
                 poll_s: float = 0.2):
        if isinstance(template, str):
            with open(template) as f:
                template = json.load(f)
        #: the RAW template — generation specs are json-copies of it,
        #: mutated (seed rotation + autopilot-gen opt) then normalized
        self.template = json.loads(json.dumps(template))
        self._norm = plan_mod.load_spec(self.template)
        self.name = self._norm["name"]
        self.base = base or store.BASE
        self.generations = generations
        self.spans = tuple(spans)
        self.alpha, self.threshold = float(alpha), float(threshold)
        self.min_runs = int(min_runs)
        self.parole_after = int(parole_after) if parole_after \
            else None
        self.mutate = mutate
        self.on_generation = on_generation
        self.coordinator_url = coordinator_url
        self.min_workers = int(min_workers)
        self.max_workers = int(max_workers)
        self.worker_version = str(worker_version)
        self.depth_per_worker = max(1, int(depth_per_worker))
        self.p95_high_s = float(p95_high_s)
        self.scale_interval_s = float(scale_interval_s)
        self.worker_poll_s = float(worker_poll_s)
        self.worker_extra = tuple(worker_extra or ())
        self.shrink_knobs = dict(shrink_knobs or {})
        self.poll_s = float(poll_s)
        self.stop = threading.Event()
        from jepsen_tpu.resilience import RetryPolicy, \
            is_transient

        self._seam_policy = RetryPolicy(
            max_attempts=3, base_delay_s=0.05, max_delay_s=0.5,
            classify=is_transient)
        self.journal = AutopilotJournal(
            autopilot_path(self.name, self.base))
        #: managed worker subprocesses:
        #: name -> {proc, version, spawned, draining}
        self.workers: Dict[str, Dict[str, Any]] = {}
        self._wseq = 0
        self._upgrading: Optional[Tuple[str, str]] = None
        self._last_scale = 0.0
        #: witness digest -> (parole allowed, twin audit doc) — the
        #: host-twin re-check is deterministic, so one verdict per
        #: digest serves every parole tick (ISSUE 20 satellite)
        self._twin_cache: Dict[str, Tuple[bool, Any]] = {}
        from jepsen_tpu.telemetry.alerts import AlertEngine

        #: the watchtower (ISSUE 20): evaluated on the scale cadence
        #: while awaiting a generation, and once after each gate
        self.alerts = AlertEngine(self.base, rules=alert_rules,
                                  sinks=alert_sinks)
        self.coordinator = FleetCoordinator(
            self._gen_spec(0), self.base, lease_s=lease_s,
            run_deadline_s=run_deadline_s)
        #: the /fleet page's autopilot panel reads status_doc via this
        self.coordinator.autopilot = self
        self._readmit()
        self._update_gauges()
        logger.info("autopilot %s: journal %s (%d gen(s) journaled, "
                    "%d quarantined), digest %s", self.name,
                    self.journal.path, len(self.journal.order),
                    len(self.journal.quarantined),
                    self.journal.digest())

    # -- generation planning -------------------------------------------------

    @staticmethod
    def _label(i: int) -> str:
        return "g%04d" % i

    @staticmethod
    def _gen_index(label: Any) -> int:
        try:
            return int(str(label).lstrip("g"))
        except (TypeError, ValueError):
            return -1

    def _gen_spec(self, i: int) -> dict:
        """Generation i's spec: a copy of the template with the seed
        ORDER rotated (same seed set — cell keys stay stable across
        generations, which is what makes quarantine keys and the
        cross-generation gate meaningful) and the generation label in
        the base opts (in the cells' run-id digests but NOT their
        keys, so every generation gets fresh idempotent run ids)."""
        sp = json.loads(json.dumps(self.template))
        if self.mutate is not None:
            sp = self.mutate(i, sp) or sp
        seeds = [int(s) for s in
                 (sp.get("seeds") or self._norm["seeds"])]
        k = i % max(1, len(seeds))
        sp["seeds"] = seeds[k:] + seeds[:k]
        sp.setdefault("opts", {})["autopilot-gen"] = self._label(i)
        return sp

    def _quarantined_at(self, v: Dict[str, Any], i: int) -> bool:
        """Was this key out of the plan at generation i?  A key is
        excluded during every quarantine STINT — from the generation
        after its quarantine through its parole generation inclusive
        (re-admission starts the generation after the parole), with
        prior stints preserved under ``history`` so old-generation
        replays stay byte-identical after a re-quarantine."""
        for stint in list(v.get("history") or []) + [v]:
            q = self._gen_index(stint.get("gen"))
            p = stint.get("paroled-gen")
            if q < i and (p is None or self._gen_index(p) >= i):
                return True
        return False

    def _plan(self, i: int) -> list:
        """Generation i's cells, minus keys quarantined by an EARLIER
        generation's gate and not yet paroled — a replay of an old
        generation applies the quarantine/parole state as of that
        generation, so resume re-admits byte-identical cell sets."""
        specs = plan_mod.expand(plan_mod.load_spec(self._gen_spec(i)))
        quarantined = {k for k, v in self.journal.quarantined.items()
                       if self._quarantined_at(v, i)}
        return [rs for rs in specs if rs.key not in quarantined]

    def _next_index(self) -> int:
        for i, label in enumerate(self.journal.order):
            if not self.journal.gens[label].get("closed"):
                return i
        return len(self.journal.order)

    def _readmit(self) -> None:
        """Re-admit every journaled generation on boot — heals the
        crash window between a ``gen-open`` append and the queue
        enqueue (idempotent: already-queued cells are duplicates the
        queue refuses, indexed cells count done immediately)."""
        for i, label in enumerate(self.journal.order):
            try:
                out = self.coordinator.admit(self._plan(i), gen=label)
                logger.info("autopilot %s: re-admitted %s (%s)",
                            self.name, label, out)
            except Exception:  # noqa: BLE001 — step() retries via seam
                logger.warning("autopilot %s: re-admit of %s failed",
                               self.name, label, exc_info=True)

    # -- the loop ------------------------------------------------------------

    def _seam(self, site: str, fn: Callable, *args: Any
              ) -> Tuple[bool, Any]:
        """Run one decision through its guarded fault site.  The loop
        never dies on a seam failure — callers get (False, error) and
        degrade per the quarantine policy."""
        from jepsen_tpu import resilience

        try:
            return True, resilience.device_call(
                site, fn, *args, policy=self._seam_policy)
        except Exception as e:  # noqa: BLE001 — survives own chaos
            logger.warning("autopilot seam %s failed: %s", site, e)
            return False, f"{type(e).__name__}: {e}"

    def step(self) -> Dict[str, Any]:
        """Run ONE generation end to end: journal intent, admit,
        await drain (scaling while waiting), gate, journal verdicts,
        quarantine + shrink regressions.  Returns a summary doc."""
        i = self._next_index()
        label = self._label(i)
        specs = self._plan(i)
        if label not in self.journal.gens:
            # durable intent FIRST: the journal append is the commit
            # point, the enqueue below is its idempotent replay arm
            self.journal.open_gen(
                label, seeds=self._gen_spec(i).get("seeds"),
                runs=len(specs))
        while not self.stop.is_set():
            ok, _ = self._seam("autopilot.enqueue",
                               self.coordinator.admit, specs, label)
            if ok:
                break
            self.stop.wait(0.2)
        summary: Dict[str, Any] = {"gen": label, "runs": len(specs)}
        if not self._await([rs.run_id for rs in specs]):
            summary["stopped"] = True
            return summary
        ok, verdicts = self._seam("autopilot.gate", self._gate,
                                  i, label)
        if not ok:
            # the gate itself died: close the generation with an
            # attributable error verdict — rc 2 semantics, never
            # quarantine on missing evidence
            verdicts = [{"span": None, "status": "gate-error",
                         "rc": 2, "reason": verdicts,
                         "to-gen": label}]
        self.journal.close_gen(label, verdicts)
        summary["verdicts"] = verdicts
        quarantined = []
        for v in verdicts:
            if v.get("status") != "regression":
                continue
            key = v.get("key")
            cur = self.journal.quarantined.get(str(key)) \
                if key else None
            if not key or (cur is not None
                           and "paroled-gen" not in cur):
                continue  # active quarantine — nothing new to do
            self.journal.quarantine(
                str(key), gen=label, span=v.get("span"),
                rel_delta=v.get("key-rel-delta"))
            quarantined.append(str(key))
            self._update_gauges()
            ok, out = self._seam("autopilot.shrink", self._shrink,
                                 str(key), label, v)
            self.journal.shrink(
                str(key), gen=label,
                outcome=out if ok else {"error": out})
        if quarantined:
            summary["quarantined"] = quarantined
        paroled = self._parole_tick(label)
        if paroled:
            summary["paroled"] = paroled
        self._update_gauges()
        # the gate's verdicts just changed the alertable state
        # (gate-regression / rc2-streak / quarantine census): evaluate
        # now instead of waiting for the next await tick
        self._seam("alerts.evaluate", self._alert_tick)
        return summary

    def _parole_tick(self, label: str) -> List[str]:
        """Quarantine parole (ROADMAP 5d): once ``parole_after``
        closed generations SINCE a key's quarantine came back with no
        regression anywhere — its neighbors ran clean without it —
        the key is re-admitted starting with the next generation.  A
        paroled key that regresses again is re-quarantined (prior
        stint archived), so parole is a retrial, not an acquittal."""
        if not self.parole_after:
            return []
        clean = []
        for l in self.journal.closed_labels():
            vs = self.journal.gens[l].get("verdicts") or []
            if all(v.get("rc") != 1 for v in vs):
                clean.append(self._gen_index(l))
        out = []
        for key, v in sorted(self.journal.quarantined.items()):
            if "paroled-gen" in v:
                continue
            q = self._gen_index(v.get("gen"))
            n = sum(1 for ci in clean if ci > q)
            if n < self.parole_after:
                continue
            allowed, twin = self._witness_twin_check(key)
            if not allowed:
                logger.info(
                    "autopilot %s: parole of %s DENIED by host-twin "
                    "re-check (%s)", self.name, key, twin)
                continue
            self.journal.parole(key, gen=label, twin=twin)
            out.append(key)
            logger.info(
                "autopilot %s: paroled %s after %d clean "
                "generation(s) (quarantined at %s, twin %s)",
                self.name, key, n, v.get("gen"), twin)
        return out

    def _witness_twin_check(self, key: str) -> Tuple[bool, Any]:
        """Parole on twin-pass (ROADMAP 5d remainder): a quarantined
        key whose auto-shrink produced a WITNESS may only be paroled
        if that witness's shrunken history re-checks VALID through its
        host twin — the device-independent oracle.  Twin-valid means
        the archived anomaly was a device-path false positive and the
        neighbors-ran-clean evidence stands; twin-invalid means the
        anomaly is real and clean neighbor generations prove nothing
        (denied until the witness changes).  A missing/unreadable
        witness denies conservatively; a shrink with NO witness (perf
        regressions have nothing to re-check) keeps the plain
        clean-generations criterion."""
        outcome = (self.journal.shrinks.get(key) or {}).get(
            "outcome") or {}
        digest = outcome.get("digest")
        if not digest:
            return True, None
        cached = self._twin_cache.get(digest)
        if cached is not None:
            return cached
        res = self._twin_recheck(key, str(digest))
        self._twin_cache[digest] = res
        return res

    def _twin_recheck(self, key: str, digest: str) -> Tuple[bool, Any]:
        from jepsen_tpu.minimize import probe
        from jepsen_tpu.minimize import witness as witness_mod

        with self.coordinator._lock:
            recs = [r for r in self.coordinator.idx.records
                    if str(r.get("key")) == key and r.get("dir")
                    and isinstance(r.get("witness"), dict)
                    and r["witness"].get("digest") == digest]
        if not recs:
            return False, {"digest": digest,
                           "error": "witness-record-missing"}
        run_dir = os.path.join(self.base, str(recs[-1]["dir"]))
        try:
            w = witness_mod.load_witness(run_dir)
            if w is None or w.get("digest") != digest:
                return False, {"digest": digest,
                               "error": "witness-artifact-missing"}
            hist = w["history"]
            chk = probe.resolve_checker(None, hist)
            twin = probe.host_equivalent(chk) or chk
            res = twin.check({}, hist, {})
            valid = res.get("valid?") if isinstance(res, dict) else None
        except Exception as e:  # noqa: BLE001 — deny conservatively
            return False, {"digest": digest,
                           "error": f"{type(e).__name__}: {e}"}
        doc = {"digest": digest,
               "checker": str(getattr(twin, "name",
                                      type(twin).__name__)),
               "valid?": valid}
        return (valid is True), doc

    def _alert_tick(self) -> Dict[str, Any]:
        return self.alerts.evaluate(autopilot=self)

    def run(self) -> Dict[str, Any]:
        """The unattended loop: generations until ``generations`` (or
        forever), then drain the managed workers."""
        out: Dict[str, Any] = {}
        try:
            while not self.stop.is_set():
                if self.generations is not None and \
                        len(self.journal.closed_labels()) >= \
                        self.generations:
                    break
                out = self.step()
                if self.on_generation is not None:
                    try:
                        self.on_generation(self, out)
                    except Exception:  # noqa: BLE001 — hook is advisory
                        logger.warning("on_generation hook failed",
                                       exc_info=True)
                if out.get("stopped"):
                    break
        finally:
            self.drain_workers()
        return {"generations": len(self.journal.closed_labels()),
                "quarantined": sorted(self.journal.quarantined),
                "digest": self.journal.digest(), "last": out}

    def _await(self, run_ids: List[str]) -> bool:
        wanted = set(run_ids)
        while not self.stop.is_set():
            self.coordinator.queue.expire()
            with self.coordinator._lock:
                done = wanted <= self.coordinator._done_ids
            if done:
                return True
            now = time.monotonic()
            if now - self._last_scale >= self.scale_interval_s:
                self._last_scale = now
                self._seam("autopilot.scale", self._scale_tick)
                self._seam("alerts.evaluate", self._alert_tick)
            self.stop.wait(self.poll_s)
        return False

    # -- gate + quarantine + shrink ------------------------------------------

    def _prev_closed(self, label: str) -> Optional[str]:
        prev = None
        for l in self.journal.order:
            if l == label:
                break
            if self.journal.gens[l].get("closed"):
                prev = l
        return prev

    def _gate(self, i: int, label: str) -> List[Dict[str, Any]]:
        from jepsen_tpu.telemetry import forensics
        from jepsen_tpu.telemetry import gate as gate_mod

        prev = self._prev_closed(label)
        if prev is None:
            return [{"span": None, "status": "insufficient-data",
                     "rc": 2, "reason": "first-generation",
                     "to-gen": label}]
        with self.coordinator._lock:
            recs = list(self.coordinator.idx.records)
        known = sorted({
            n for r in recs if str(r.get("gen")) in (prev, label)
            for n, d in (r.get("spans") or {}).items()
            if isinstance(d, (int, float))})
        wanted = forensics.resolve_spans(known, list(self.spans))
        if not wanted:
            return [{"span": None, "status": "insufficient-data",
                     "rc": 2, "to-gen": label,
                     "reason": f"no spans matching {list(self.spans)} "
                               f"in {prev}..{label}"}]
        out = []
        for span in wanted:
            res = gate_mod.run_gate(
                self.base, self.name, span, from_gen=prev,
                to_gen=label, alpha=self.alpha,
                threshold=self.threshold, min_runs=self.min_runs)
            status = str(res.get("status"))
            v = {"span": span, "status": status,
                 "rc": GATE_RC.get(status, 2),
                 "from-gen": prev, "to-gen": label,
                 "reason": res.get("reason"),
                 "rel-delta": res.get("rel_delta"),
                 "p-value": res.get("p_value")}
            if status == "regression":
                att = self._attribute(span, prev, label, recs)
                if att is not None:
                    v["key"], v["key-rel-delta"] = att
            out.append(v)
        return out

    def _attribute(self, span: str, prev: str, label: str,
                   recs: List[Dict[str, Any]]
                   ) -> Optional[Tuple[str, float]]:
        """The regressing CELL: the key with the largest relative
        mean delta on the regressing span between the two
        generations."""
        by_key: Dict[str, Dict[str, List[float]]] = {}
        for r in recs:
            key, g = r.get("key"), str(r.get("gen"))
            d = (r.get("spans") or {}).get(span)
            if not key or g not in (prev, label) or \
                    not isinstance(d, (int, float)):
                continue
            by_key.setdefault(str(key), {})[g] = \
                by_key.setdefault(str(key), {}).get(g, []) + [float(d)]
        best: Optional[Tuple[str, float]] = None
        for key, groups in by_key.items():
            a, b = groups.get(prev), groups.get(label)
            if not a or not b:
                continue
            ma = sum(a) / len(a)
            if ma <= 0:
                continue
            rel = (sum(b) / len(b) - ma) / ma
            if best is None or rel > best[1]:
                best = (key, round(rel, 4))
        return best

    def _artifacts_dir(self) -> str:
        return os.path.join(self.base, "fleet",
                            store.sanitize(self.name) + ".autopilot")

    def _diff_artifact(self, label: str, key: str,
                       verdict: Dict[str, Any]) -> Optional[str]:
        """The ``obs diff`` forensics report for a quarantine, written
        next to the journal (best-effort — forensics never blocks the
        quarantine itself)."""
        from jepsen_tpu.telemetry import forensics

        try:
            rep = forensics.run_diff(
                self.base, self.name,
                from_gen=verdict.get("from-gen"), to_gen=label,
                spans=[verdict["span"]] if verdict.get("span")
                else None,
                alpha=self.alpha, threshold=self.threshold,
                min_runs=self.min_runs)
            d = self._artifacts_dir()
            os.makedirs(d, exist_ok=True)
            path = os.path.join(
                d, f"{label}-{store.sanitize(str(key))}.diff.json")
            with open(path, "w") as f:
                json.dump(rep, f, indent=1, sort_keys=True,
                          default=str)
            return os.path.relpath(path, self.base)
        except Exception:  # noqa: BLE001 — forensics is best-effort
            logger.warning("autopilot %s: diff artifact for %s "
                           "failed", self.name, key, exc_info=True)
            return None

    def _shrink(self, key: str, label: str,
                verdict: Dict[str, Any]) -> Dict[str, Any]:
        """Auto-shrink the quarantined cell's latest run to an
        attributed witness, append the witness record to the campaign
        index (the same surface `run_campaign`'s auto-shrink feeds),
        and drop the ``obs diff`` forensics artifact."""
        from jepsen_tpu import minimize

        art = self._diff_artifact(label, key, verdict)
        with self.coordinator._lock:
            recs = [r for r in self.coordinator.idx.records
                    if str(r.get("key")) == key and r.get("dir")]
        cand = ([r for r in recs if str(r.get("gen")) == label]
                or recs)
        if not cand:
            return {"error": "no-run-dir", "forensics": art}
        last = cand[-1]
        run_dir = os.path.join(self.base, str(last["dir"]))
        k = self.shrink_knobs
        try:
            s = minimize.shrink(
                run_dir, rounds=k.get("rounds"),
                probe_deadline_s=float(
                    k.get("probe-deadline", 30.0)),
                workers=int(k.get("workers", 2)),
                device_slots=int(k.get("device-slots", 1)),
                host_oracle=bool(k.get("host-oracle", True)))
        except Exception as e:  # noqa: BLE001 — journal the failure
            return {"run": last.get("run"), "forensics": art,
                    "error": f"{type(e).__name__}: {e}"}
        if s.get("error"):
            # e.g. "not-invalid": a perf-only regression has no
            # anomaly to shrink — the quarantine + forensics artifact
            # are the whole story
            return {"run": last.get("run"), "forensics": art,
                    "error": s["error"]}
        witness = {kk: s[kk] for kk in
                   ("ops", "source-ops", "digest", "anomaly-types",
                    "probes", "cached", "fault-windows") if kk in s}
        rec = {"run": last.get("run"), "key": key,
               "campaign": self.name,
               "workload": last.get("workload"),
               "fault": last.get("fault"), "seed": last.get("seed"),
               "gen": label, "dir": last.get("dir"),
               "valid?": last.get("valid?"), "witness": witness,
               "autopilot": {"quarantined": label,
                             "span": verdict.get("span"),
                             "forensics": art}}
        with self.coordinator._lock:
            self.coordinator.idx.append(rec)
        return {"run": last.get("run"), "forensics": art,
                "witness-ops": witness.get("ops"),
                "digest": witness.get("digest"),
                "anomaly-types": witness.get("anomaly-types")}

    # -- elasticity ----------------------------------------------------------

    def _reap(self) -> None:
        for name in list(self.workers):
            proc = self.workers[name]["proc"]
            rc = proc.poll()
            if rc is not None:
                self.journal.scale("exit", worker=name, rc=rc)
                del self.workers[name]

    def _live_workers(self) -> List[str]:
        return [n for n, w in self.workers.items()
                if w["proc"].poll() is None]

    def _worker_alive(self, name: str) -> bool:
        """Alive per the COORDINATOR's view (registered + heartbeat
        fresh) — the rolling upgrade's hand-over criterion."""
        with self.coordinator._lock:
            c = self.coordinator.workers.get(name)
            if not c:
                return False
            fresh = time.time() - c["last-seen"] <= \
                ALIVE_LEASES * self.coordinator.lease_s
            return fresh and \
                c.get("version") == self.workers.get(
                    name, {}).get("version")

    def _spawn_worker(self) -> Optional[str]:
        import subprocess
        import sys

        if not self.coordinator_url:
            return None
        self._wseq += 1
        name = f"ap-{os.getpid()}-{self._wseq}"
        env = dict(os.environ,
                   JEPSEN_WORKER_VERSION=self.worker_version)
        cmd = [sys.executable, "-m", "jepsen_tpu",
               "--store-dir", self.base, "fleet", "work",
               "--coordinator", self.coordinator_url,
               "--name", name, "--poll", str(self.worker_poll_s)]
        cmd += list(self.worker_extra)
        proc = subprocess.Popen(cmd, env=env,
                                stdout=subprocess.DEVNULL,
                                stderr=subprocess.DEVNULL)
        self.workers[name] = {"proc": proc,
                              "version": self.worker_version,
                              "spawned": round(time.time(), 3),
                              "draining": False}
        self.journal.scale("spawn", worker=name,
                           version=self.worker_version)
        return name

    def _drain_worker(self, name: str, reason: str = "scale-down"
                      ) -> None:
        w = self.workers.get(name)
        if w is None or w["draining"]:
            return
        w["draining"] = True
        if w["proc"].poll() is None:
            w["proc"].terminate()  # SIGTERM: finish-in-flight drain
        self.journal.scale("drain", worker=name, reason=reason,
                           version=w["version"])

    def _scale_tick(self) -> Dict[str, Any]:
        """One scaler decision: size the pool to queue depth and
        claim-latency p95 (the coordinator's two federated signals),
        then advance the rolling upgrade one worker at a time."""
        self._reap()
        if self.max_workers <= 0 or not self.coordinator_url:
            self._update_gauges()
            return {"workers": 0, "managed": False}
        counts = self.coordinator.queue.counts()
        depth = counts["queued"]
        p95 = self.coordinator.queue.claim_latency_p95()
        active = [n for n in self._live_workers()
                  if not self.workers[n]["draining"]]
        want = max(self.min_workers,
                   min(self.max_workers,
                       math.ceil(depth / self.depth_per_worker)
                       if depth else self.min_workers))
        if depth and p95 is not None and p95 > self.p95_high_s:
            want = min(self.max_workers, max(want, len(active) + 1))
        if len(active) < want:
            self._spawn_worker()
        elif len(active) > want and self._upgrading is None:
            self._drain_worker(active[0])
        self._upgrade_tick()
        self._update_gauges()
        return {"workers": len(self._live_workers()), "want": want,
                "depth": depth, "p95": p95}

    def _upgrade_tick(self) -> None:
        """The rolling version upgrade: at most ONE replacement in
        flight — spawn the new-version worker, wait until the
        coordinator sees it alive at the new version, only then
        SIGTERM its predecessor (finish-in-flight: zero lost cells)."""
        if self._upgrading is not None:
            old, new = self._upgrading
            if new not in self.workers or \
                    self.workers[new]["proc"].poll() is not None:
                self._upgrading = None  # replacement died: retry later
            elif self._worker_alive(new):
                self._drain_worker(old, reason="upgrade")
                self.journal.scale("upgraded", worker=old,
                                   replacement=new,
                                   version=self.worker_version)
                self._upgrading = None
            return
        for name in self._live_workers():
            w = self.workers[name]
            if w["draining"] or w["version"] == self.worker_version:
                continue
            new = self._spawn_worker()  # transient max+1 by design
            if new:
                self._upgrading = (name, new)
            return

    def drain_workers(self, timeout_s: float = 30.0) -> None:
        """SIGTERM every managed worker and wait for the drain;
        stragglers past the timeout are killed."""
        for name in list(self.workers):
            self._drain_worker(name, reason="shutdown")
        deadline = time.time() + timeout_s
        for name, w in list(self.workers.items()):
            left = max(0.1, deadline - time.time())
            try:
                w["proc"].wait(timeout=left)
            except Exception:  # noqa: BLE001 — straggler
                w["proc"].kill()
        self._reap()

    def close(self) -> None:
        self.stop.set()
        self.drain_workers()
        self.coordinator.close()

    # -- surfaces ------------------------------------------------------------

    def _update_gauges(self) -> None:
        try:
            from jepsen_tpu import telemetry

            reg = telemetry.registry()
            active = [k for k, v in
                      self.journal.quarantined.items()
                      if "paroled-gen" not in v]
            reg.gauge("fleet-quarantined-cells").set(len(active))
            reg.gauge("fleet-paroled-cells").set(
                len(self.journal.quarantined) - len(active))
            reg.gauge("fleet-autopilot-generations").set(
                len(self.journal.closed_labels()))
        except Exception:  # noqa: BLE001 — observability only
            logger.debug("autopilot gauges failed", exc_info=True)

    def status_doc(self) -> Dict[str, Any]:
        """The /fleet panel + ``cli fleet status`` document."""
        closed = self.journal.closed_labels()
        last = (self.journal.gens[closed[-1]].get("verdicts")
                if closed else None)
        workers = {}
        for name, w in self.workers.items():
            workers[name] = {"version": w["version"],
                             "pid": w["proc"].pid,
                             "running": w["proc"].poll() is None,
                             "draining": w["draining"]}
        return {
            "campaign": self.name,
            "generation": (self.journal.order[-1]
                           if self.journal.order else None),
            "generations-closed": len(closed),
            "worker-version": self.worker_version,
            "quarantined": {k: dict(v) for k, v in
                            self.journal.quarantined.items()},
            "shrinks": {k: dict(v) for k, v in
                        self.journal.shrinks.items()},
            "last-verdicts": last or [],
            "workers": workers,
            "journal-digest": self.journal.digest(),
            "alerts": self.alerts.status_doc(),
        }
