"""Store federation: run-dir artifact uploads (ISSUE 13 tentpole b).

Fleet workers no longer need a shared store filesystem.  After
executing a cell a worker tars its run dir and streams it to the
coordinator's ``POST /fleet/artifact/<run-id>`` endpoint in
digest-verified, byte-offset-addressed chunks; the coordinator lands
the unpacked dir at the ordinary store location, so every downstream
surface (web run pages, warehouse ingest, `cli shrink`, witness diff)
works on a distributed campaign exactly as on a local one.

Crash discipline, mirroring the journal/ledger conventions:

- the staged upload lives under ``<store>/fleet/staging/`` (a subtree
  `store.tests` already skips) as ``<run-id>.tar`` + a sidecar meta
  json; the part file's SIZE is the resume cursor — a ``kill -9`` on
  either side mid-upload leaves a resumable partial, and the client
  probes (empty POST) for ``received`` and resends from there;
- chunks are idempotent: a resend below the received cursor is
  overlap-skipped, a gap is a 409 carrying the cursor (exactly the
  verifier journal's contract);
- landing is atomic: the tar is sha256-verified against the digest
  the client declared, unpacked into a dot-prefixed staging dir
  NEXT TO the final location (same filesystem), then ``os.replace``\\ d
  into place — a crash anywhere leaves either no run dir or a whole
  one, never a torn one (`store.tests` / the warehouse skip the
  dot-prefixed intermediates; ISSUE 13 satellite);
- re-uploading a landed run id acks ``{"landed": true, "already":
  true}`` — at-most-once landing keyed on the run dir path, so a
  zombie worker's late upload is harmless.
"""

from __future__ import annotations

import hashlib
import io
import json
import logging
import os
import tarfile
import threading
import time
from typing import Any, BinaryIO, Dict, Optional, Tuple

from jepsen_tpu import store

logger = logging.getLogger("jepsen.fleet")

__all__ = ["ArtifactStore", "pack_run_dir", "pack_run_dir_file",
           "STAGING_DIR"]

STAGING_DIR = os.path.join("fleet", "staging")

#: refuse absurd uploads (a run dir is logs + json + telemetry)
MAX_ARTIFACT_BYTES = 512 * 1024 * 1024


def _registry():
    from jepsen_tpu import telemetry

    return telemetry.registry()


def _count(state: str) -> None:
    try:
        _registry().counter("fleet-artifact-uploads", state=state).inc()
    except Exception:  # noqa: BLE001 — observability only
        pass


def pack_run_dir_file(d: str, fileobj: BinaryIO) -> Tuple[int, str]:
    """Tar a run dir (uncompressed — run artifacts are mostly jsonl
    that travels fine; keeps the chunk cursor simple) into a seekable
    ``fileobj`` and return ``(size, sha256 hex)``.  Both the tar and
    the digest stream, so an upload spooled through a temp file never
    holds the whole artifact in worker memory."""
    with tarfile.open(fileobj=fileobj, mode="w") as tf:
        for root, _dirs, files in os.walk(d):
            for fn in sorted(files):
                full = os.path.join(root, fn)
                tf.add(full, arcname=os.path.relpath(full, d))
    size = fileobj.tell()
    fileobj.seek(0)
    h = hashlib.sha256()
    for chunk in iter(lambda: fileobj.read(1 << 20), b""):
        h.update(chunk)
    return size, h.hexdigest()


def pack_run_dir(d: str) -> Tuple[bytes, str]:
    """In-memory `pack_run_dir_file`: ``(bytes, sha256 hex)``."""
    buf = io.BytesIO()
    _size, digest = pack_run_dir_file(d, buf)
    return buf.getvalue(), digest


def _safe_rel(rel: str) -> Optional[Tuple[str, str]]:
    """Validate a run-dir-relative path ``<name>/<timestamp>``: both
    components must survive `store.sanitize` unchanged and must not be
    dot-prefixed (dot-prefixed dirs are the atomic-landing staging
    convention the store scans skip)."""
    parts = [p for p in str(rel).replace("\\", "/").split("/") if p]
    if len(parts) != 2:
        return None
    name, ts = parts
    for p in (name, ts):
        if store.sanitize(p) != p or p.startswith(".") or p in (".", ".."):
            return None
    if name in ("campaigns", "verifier", "fleet"):
        return None
    return name, ts


class ArtifactStore:
    """Server half of the upload protocol; owned by the coordinator.
    Thread-safe: requests for the same run id serialize on a per-run
    lock (the threaded HTTP server would otherwise let a zombie
    worker's duplicate upload interleave bytes with the live one);
    landing is an atomic rename, so a racing duplicate of an already
    landed run just sees ``already``."""

    def __init__(self, base: str):
        self.base = base
        self.staging = os.path.join(base, STAGING_DIR)
        self._locks_guard = threading.Lock()
        self._run_locks: Dict[str, threading.Lock] = {}

    def _run_lock(self, run_id: str) -> threading.Lock:
        with self._locks_guard:
            return self._run_locks.setdefault(run_id, threading.Lock())

    def _paths(self, run_id: str) -> Tuple[str, str]:
        return (os.path.join(self.staging, run_id + ".tar"),
                os.path.join(self.staging, run_id + ".json"))

    def _meta(self, meta_path: str) -> Optional[Dict[str, Any]]:
        try:
            with open(meta_path) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            return None
        return doc if isinstance(doc, dict) else None

    def handle(self, run_id: str, params: Dict[str, Any],
               body: bytes) -> Tuple[int, Dict[str, Any]]:
        """One upload request.  Params (query string): ``offset``,
        ``total``, ``digest``, ``rel`` — all required on chunk
        requests; an empty body with no ``offset`` is a resume probe
        answering ``{"received": N, "landed": bool}``."""
        if store.sanitize(run_id) != run_id or not run_id:
            _count("rejected")
            return 400, {"error": f"bad run id {run_id!r}"}
        with self._run_lock(run_id):
            code, doc = self._handle(run_id, params, body)
        if doc.get("landed"):
            # the staged partial is gone — drop the per-run lock entry
            # so a long-lived coordinator's lock table stays bounded
            # (a late duplicate just mints a fresh lock; its paths are
            # read-only probes and atomic-rename already-acks)
            with self._locks_guard:
                self._run_locks.pop(run_id, None)
        return code, doc

    def _handle(self, run_id: str, params: Dict[str, Any],
                body: bytes) -> Tuple[int, Dict[str, Any]]:
        part, meta_path = self._paths(run_id)
        meta = self._meta(meta_path)
        landed = bool(meta and meta.get("landed"))
        received = 0
        try:
            received = os.path.getsize(part)
        except OSError:
            pass
        if params.get("offset") is None and not body:
            if received and not landed:
                _count("resumed")
            doc = {"received": received, "landed": landed}
            if meta and meta.get("rel"):
                doc["rel"] = meta["rel"]
            return 200, doc
        try:
            offset = int(params["offset"])
            total = int(params["total"])
            digest = str(params["digest"])
            rel = str(params["rel"])
        except (KeyError, TypeError, ValueError):
            _count("rejected")
            return 400, {"error": "chunk needs offset, total, digest, "
                                  "rel"}
        safe = _safe_rel(rel)
        if safe is None:
            _count("rejected")
            return 400, {"error": f"bad run dir rel {rel!r}"}
        if landed:
            if meta.get("rel") == rel:
                return 200, {"landed": True, "already": True,
                             "received": received}
            # same run id, DIFFERENT run dir: a lease-lapse
            # re-execution minted a new wall-clock timestamp.  The
            # landed marker covers the old dir only — this dir must
            # land too or the re-executor's verdict record points at
            # a path that never arrives
            self._discard(run_id)
            received = 0
            meta = None
        if total <= 0 or total > MAX_ARTIFACT_BYTES or offset < 0 \
                or offset + len(body) > total:
            _count("rejected")
            return 400, {"error": "bad chunk window",
                         "received": received}
        if meta is not None and not meta.get("landed") and (
                meta.get("total") != total
                or meta.get("digest") != digest
                or meta.get("rel") != rel):
            # a NEW upload of the same run id (e.g. after a digest
            # mismatch restart): drop the stale partial
            self._discard(run_id)
            received = 0
            meta = None
        if meta is None:
            os.makedirs(self.staging, exist_ok=True)
            doc = {"run": run_id, "total": total,
                   "digest": digest, "rel": rel,
                   "started": round(time.time(), 3)}
            try:
                # trace stitching (ISSUE 14): the upload rides the
                # run's trace — the web layer installed the incoming
                # Jepsen-Trace header on this handler thread
                from jepsen_tpu.telemetry import spans as spans_mod

                ctx = spans_mod.current_trace()
                if ctx is not None:
                    doc["trace"] = ctx.trace_id
            except Exception:  # noqa: BLE001 — observability only
                pass
            tmp = meta_path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(doc, f)
            os.replace(tmp, meta_path)
            _count("started")
        if offset > received:
            return 409, {"error": "chunk gap", "received": received}
        skip = received - offset
        if skip < len(body):
            with open(part, "ab") as f:
                f.write(body[skip:])
                f.flush()
                os.fsync(f.fileno())
            received += len(body) - skip
        _count("chunk")
        if received < total:
            return 200, {"received": received}
        return self._land(run_id, part, meta_path, digest, rel,
                          received)

    def _discard(self, run_id: str) -> None:
        part, meta_path = self._paths(run_id)
        for p in (part, meta_path):
            try:
                os.remove(p)
            except OSError:
                pass

    def _land(self, run_id: str, part: str, meta_path: str,
              digest: str, rel: str, received: int
              ) -> Tuple[int, Dict[str, Any]]:
        """Verify + unpack + atomically rename into the ordinary store.
        A digest mismatch discards the partial (the client restarts
        from 0); landing into an already-existing run dir is
        ``already`` (a duplicate upload raced us)."""
        h = hashlib.sha256()
        with open(part, "rb") as f:
            for chunk in iter(lambda: f.read(1 << 20), b""):
                h.update(chunk)
        if h.hexdigest() != digest:
            self._discard(run_id)
            _count("rejected")
            return 409, {"error": "digest mismatch; upload discarded",
                         "received": 0}
        name, ts = _safe_rel(rel)  # validated at chunk time
        final = os.path.join(self.base, name, ts)
        if os.path.isdir(final):
            self._mark_landed(meta_path)
            self._cleanup(part)
            return 200, {"landed": True, "already": True,
                         "received": received}
        # dot-prefixed sibling staging dir: same fs as the final
        # location, skipped by store.tests/warehouse until the rename
        incoming = os.path.join(self.base, name, f".incoming-{ts}")
        try:
            os.makedirs(incoming, exist_ok=True)
            with tarfile.open(part, mode="r") as tf:
                for m in tf.getmembers():
                    mn = m.name.replace("\\", "/")
                    if m.isdev() or m.issym() or m.islnk() \
                            or mn.startswith(("/", "..")) \
                            or "/../" in mn:
                        raise ValueError(
                            f"refusing tar member {m.name!r}")
                tf.extractall(incoming)
            os.replace(incoming, final)
        except Exception as e:  # noqa: BLE001 — a bad tar must not
            import shutil  # wedge the slot; client restarts

            shutil.rmtree(incoming, ignore_errors=True)
            self._discard(run_id)
            _count("rejected")
            return 409, {"error": f"unpack failed: {e}", "received": 0}
        self._mark_landed(meta_path)
        self._cleanup(part)
        _count("landed")
        logger.info("fleet: artifact %s landed at %s/%s (%d bytes)",
                    run_id, name, ts, received)
        return 200, {"landed": True, "received": received,
                     "dir": f"{name}/{ts}"}

    def _mark_landed(self, meta_path: str) -> None:
        meta = self._meta(meta_path) or {}
        meta["landed"] = True
        meta["landed-at"] = round(time.time(), 3)
        tmp = meta_path + ".tmp"
        try:
            with open(tmp, "w") as f:
                json.dump(meta, f)
            os.replace(tmp, meta_path)
        except OSError:
            pass

    def _cleanup(self, part: str) -> None:
        try:
            os.remove(part)
        except OSError:
            pass

    # -- staging retention (ISSUE 14 satellite) ------------------------------

    def staging_bytes(self) -> int:
        """Total bytes currently under ``<store>/fleet/staging/`` —
        the leak a GC-less coordinator accumulates forever."""
        total = 0
        try:
            for fn in os.listdir(self.staging):
                try:
                    total += os.path.getsize(
                        os.path.join(self.staging, fn))
                except OSError:
                    pass
        except OSError:
            pass
        return total

    def gc(self, retention_s: float,
           now: Optional[float] = None) -> Dict[str, int]:
        """Expire permanently abandoned staged uploads: partials (and
        landed markers) whose last activity — meta ``started`` /
        ``landed-at``, or the part file's mtime, whichever is newest —
        is older than `retention_s`.  A kill -9'd worker that never
        comes back otherwise leaks its partial forever.  Refreshes the
        ``fleet-artifact-staging-bytes`` gauge either way, so the leak
        is visible on /metrics before it is collected."""
        now = time.time() if now is None else now
        removed = 0
        try:
            names = os.listdir(self.staging)
        except OSError:
            names = []
        for fn in names:
            if not fn.endswith(".json") or fn.endswith(".tmp"):
                continue
            run_id = fn[:-len(".json")]
            part, meta_path = self._paths(run_id)
            meta = self._meta(meta_path) or {}
            latest = max(
                [t for t in (meta.get("started"), meta.get("landed-at"))
                 if isinstance(t, (int, float))] or [0.0])
            try:
                latest = max(latest, os.path.getmtime(part))
            except OSError:
                pass
            if latest and now - latest > float(retention_s):
                with self._run_lock(run_id):
                    self._discard(run_id)
                with self._locks_guard:
                    self._run_locks.pop(run_id, None)
                removed += 1
                _count("expired")
        # orphan part files whose sidecar meta never landed on disk
        # (a crash between the two writes) age out on mtime alone
        for fn in names:
            if not fn.endswith(".tar"):
                continue
            p = os.path.join(self.staging, fn)
            meta_p = p[:-len(".tar")] + ".json"
            try:
                if not os.path.exists(meta_p) and \
                        now - os.path.getmtime(p) > float(retention_s):
                    os.remove(p)
                    removed += 1
                    _count("expired")
            except OSError:
                pass
        remaining = self.staging_bytes()
        try:
            _registry().gauge("fleet-artifact-staging-bytes").set(
                remaining)
        except Exception:  # noqa: BLE001 — observability only
            pass
        return {"removed": removed, "staging-bytes": remaining}
