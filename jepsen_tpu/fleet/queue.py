"""The fleet's durable work queue: leased claims over a jsonl ledger.

One file per campaign (``<store>/fleet/<name>.jsonl``), one JSON event
per state transition, fsync'd on append — the same durability and
torn-line story as `campaign/index.py` and `verifier/journal.py`: a
``kill -9`` mid-append leaves at most one torn trailing line, which a
reload drops (and the next writer truncates away).

The queue's in-memory state is a **pure function of the event
sequence**: every live transition appends its event first, then applies
it through the same ``_apply`` the replay path uses, so a coordinator
killed and restarted over the ledger reaches the *identical* state —
pinned by :meth:`WorkQueue.digest` in the crash tests.

Events:

- ``enqueue`` — a cell (serialized RunSpec) enters, state ``queued``.
  Idempotent on the stable run id: re-enqueueing a known cell is a
  no-op, which is what makes a finished fleet re-serve resume with 0
  cells executed (parity with `campaign/index.py` resume semantics).
- ``claim`` — a worker takes the cell under a lease deadline.
- ``renew`` — the claim holder extends its lease while running.
- ``requeue`` — a lease lapsed (``lease-expired``) or the worker
  drained (``released``); the cell goes back to ``queued``.
- ``complete`` — the cell's one verdict record lands; state ``done``.
  **At-most-once**: a zombie worker completing an already-finished
  cell is detected and its duplicate discarded (a ``duplicate`` event
  is logged for attribution, the cell's record never changes, and the
  ``fleet-duplicate-completions`` counter ticks).  A *resend* of the
  identical record by the same worker (a lost ack retried) is
  recognized and acked as ``already`` — idempotent, not a duplicate.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

__all__ = ["WorkQueue", "fleet_path", "record_digest"]


def fleet_path(name: str, base: Optional[str] = None) -> str:
    """The fleet ledger path: ``<store>/fleet/<name>.jsonl``."""
    from jepsen_tpu import store

    return os.path.join(base or store.BASE, "fleet",
                        store.sanitize(name) + ".jsonl")


def record_digest(record: Dict[str, Any]) -> str:
    """Digest of a verdict record — the resend-vs-duplicate test: the
    same worker re-sending the same record (lost ack) matches; a
    re-executed cell's record (different wall_s at the very least)
    does not."""
    return hashlib.sha256(
        json.dumps(record, sort_keys=True, default=str).encode()
    ).hexdigest()[:16]


def _norm_mesh(m: Any) -> Optional[Tuple[int, ...]]:
    """Normalize a mesh-shape capability/requirement ("2x2", [2, 2],
    (2, 2)) to a comparable tuple; None when unspecified."""
    if m is None or m == "":
        return None
    if isinstance(m, str):
        m = [p for p in m.replace("x", ",").split(",") if p.strip()]
    try:
        return tuple(int(p) for p in m)
    except (TypeError, ValueError):
        return None


def _caps_match(spec: Dict[str, Any], caps: Optional[Dict[str, Any]]
                ) -> bool:
    """Worker-affine placement predicate: does this worker's advertised
    capability set satisfy a device cell's requirements?  A cell pins
    requirements via opts ``"backend"`` (e.g. ``"tpu"``) and/or
    ``"mesh"`` (device mesh shape); an unpinned cell matches everyone,
    an unadvertised capability fails a pinned one."""
    opts = spec.get("opts") or {}
    need_backend = opts.get("backend")
    if need_backend:
        have = str((caps or {}).get("backend") or "")
        if have.lower() != str(need_backend).lower():
            return False
    need_mesh = _norm_mesh(opts.get("mesh"))
    if need_mesh is not None:
        if _norm_mesh((caps or {}).get("mesh")) != need_mesh:
            return False
    return True


def _count(name: str, **labels: Any) -> None:
    """Bump a fleet counter on the live registry.  Applied during
    replay too, so a restarted coordinator's counters equal the ledger
    truth instead of restarting from zero."""
    try:
        from jepsen_tpu import telemetry

        telemetry.registry().counter(name, **labels).inc()
    except Exception:  # noqa: BLE001 — observability must not fail work
        pass


class WorkQueue:
    """One campaign's leased work queue, replayed from its ledger.

    Thread-safe (one lock around every transition).  The queue is
    owned by the coordinator — the single writer; like
    `campaign.index.Index`, a torn trailing line observed at load is
    only *healed* (truncated) right before the first append, never by
    a read-only replay (whose "torn line" may be a live writer's
    in-flight append).
    """

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.RLock()
        #: run id -> cell state dict (spec/state/worker/deadline/...)
        self.cells: Dict[str, Dict[str, Any]] = {}
        self._order: List[str] = []  # enqueue order = claim order
        self.requeues = 0
        self.duplicates = 0
        self._good_bytes: Optional[int] = None
        self._load()

    # -- replay --------------------------------------------------------------

    def _load(self) -> None:
        if not os.path.exists(self.path):
            return
        good = 0
        torn = False
        with open(self.path, "rb") as f:
            for line in f:
                if not line.strip():
                    good += len(line)
                    continue
                try:
                    ev = json.loads(line)
                except ValueError:
                    torn = True  # torn trailing event (crash debris)
                    break
                if not line.endswith(b"\n"):
                    torn = True  # unterminated: a later append would fuse
                    break
                if isinstance(ev, dict):
                    self._apply(ev)
                good += len(line)
        if torn:
            self._good_bytes = good

    def _apply(self, ev: Dict[str, Any]) -> None:
        """The one transition function — replay and live appends both
        go through here, so they cannot diverge."""
        k = ev.get("ev")
        run = ev.get("run")
        if k == "enqueue":
            self.cells[run] = {
                "run": run, "spec": ev.get("spec") or {},
                "state": "queued", "worker": None, "deadline": None,
                "claims": 0, "requeues": 0,
                "completed_by": None, "record": None,
                "record_digest": None,
                # timeline bookkeeping (ISSUE 14): the ledger event
                # timestamps, replay-stable (they come FROM the
                # ledger), excluded from the state digest (derived
                # observability, not queue state)
                "enqueued_ts": ev.get("ts"),
                "claimed_ts": None,
                # in-memory only (not digested, not replayed): when the
                # first affinity deferral parked this cell — the
                # starvation-fallback clock
                "_deferred_at": None,
            }
            self._order.append(run)
            return
        cell = self.cells.get(run)
        if cell is None:
            return  # event for an unknown cell: tolerate (old ledger)
        if k == "claim":
            cell.update(state="claimed", worker=ev.get("worker"),
                        deadline=ev.get("deadline"),
                        claimed_ts=ev.get("ts"))
            cell["claims"] += 1
        elif k == "renew":
            if cell["state"] == "claimed" and \
                    cell["worker"] == ev.get("worker"):
                cell["deadline"] = ev.get("deadline")
        elif k == "requeue":
            cell.update(state="queued", worker=None, deadline=None)
            cell["_deferred_at"] = None  # affinity clock restarts
            cell["requeues"] += 1
            self.requeues += 1
            _count("fleet-requeues", worker=ev.get("worker") or "?",
                   reason=ev.get("reason") or "?")
        elif k == "complete":
            rec = ev.get("record")
            cell.update(state="done", worker=None, deadline=None,
                        completed_by=ev.get("worker"), record=rec,
                        record_digest=record_digest(rec or {}))
        elif k == "duplicate":
            self.duplicates += 1
            _count("fleet-duplicate-completions",
                   worker=ev.get("worker") or "?")

    # -- the durable append --------------------------------------------------

    def _event(self, ev: Dict[str, Any]) -> None:
        """Append one event (fsync'd) and apply it.  Healing a torn
        tail observed at load happens here, right before the first
        append — writer-only, like the campaign index."""
        ev = dict(ev, ts=round(time.time(), 3))
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        if self._good_bytes is not None:
            with open(self.path, "r+b") as f:
                f.truncate(self._good_bytes)
            self._good_bytes = None
        with open(self.path, "a") as f:
            f.write(json.dumps(ev) + "\n")
            f.flush()
            os.fsync(f.fileno())
        self._apply(ev)

    # -- transitions ---------------------------------------------------------

    def enqueue(self, spec: Dict[str, Any]) -> bool:
        """Admit one cell (a ``RunSpec.to_dict()``); idempotent on the
        stable run id — a known cell (queued, claimed, or done) is a
        no-op."""
        run = spec["run_id"]
        with self._lock:
            if run in self.cells:
                return False
            self._event({"ev": "enqueue", "run": run, "spec": spec})
            return True

    def claim(self, worker: str, *, lease_s: float,
              device_ok: bool = True,
              caps: Optional[Dict[str, Any]] = None,
              now: Optional[float] = None
              ) -> Tuple[Optional[Dict[str, Any]], Optional[float]]:
        """Claim the first queued cell this worker can run; returns
        ``(spec, lease_deadline)`` or ``(None, None)``.  Expired leases
        are requeued first (opportunistic — the coordinator has no
        background reaper thread to crash).

        Placement is **worker-affine** (ISSUE 11): a device-classified
        cell whose opts pin a ``"backend"`` or ``"mesh"`` shape is
        skipped by workers whose registered `caps` don't match — each
        skip counts on ``fleet-affinity-deferrals{worker}`` — until the
        cell has been deferred for longer than one lease, after which
        ANY device-capable worker may take it (starvation-safe
        fallback: affinity is a preference, never a deadlock)."""
        now = time.time() if now is None else now
        with self._lock:
            self._expire_locked(now)
            for run in self._order:
                cell = self.cells[run]
                if cell["state"] != "queued":
                    continue
                if cell["spec"].get("device"):
                    if not device_ok:
                        continue
                    if not _caps_match(cell["spec"], caps):
                        first = cell["_deferred_at"]
                        if first is None:
                            cell["_deferred_at"] = first = now
                        if now - first <= float(lease_s):
                            _count("fleet-affinity-deferrals",
                                   worker=worker)
                            continue
                        # starved past a lease: any capable worker runs
                        # it — a fleet with no matching worker must
                        # still finish
                deadline = round(now + float(lease_s), 3)
                self._event({"ev": "claim", "run": run, "worker": worker,
                             "deadline": deadline})
                cell["_deferred_at"] = None
                return dict(cell["spec"]), deadline
            return None, None

    def renew(self, run: str, worker: str, lease_s: float,
              now: Optional[float] = None) -> bool:
        """Extend a held lease.  False means the lease was LOST (lapsed
        and requeued, or the cell finished elsewhere) — the worker may
        keep running, but its eventual completion can be discarded as
        a duplicate."""
        now = time.time() if now is None else now
        with self._lock:
            cell = self.cells.get(run)
            if cell is None or cell["state"] != "claimed" or \
                    cell["worker"] != worker:
                return False
            self._event({"ev": "renew", "run": run, "worker": worker,
                         "deadline": round(now + float(lease_s), 3)})
            return True

    def release(self, run: str, worker: str) -> bool:
        """Voluntarily give a claim back (the SIGTERM drain path)."""
        with self._lock:
            cell = self.cells.get(run)
            if cell is None or cell["state"] != "claimed" or \
                    cell["worker"] != worker:
                return False
            self._event({"ev": "requeue", "run": run, "worker": worker,
                         "reason": "released"})
            return True

    def expire(self, now: Optional[float] = None) -> List[str]:
        """Requeue every claimed cell whose lease deadline passed;
        returns the requeued run ids."""
        now = time.time() if now is None else now
        with self._lock:
            return self._expire_locked(now)

    def _expire_locked(self, now: float) -> List[str]:
        out = []
        for run in self._order:
            cell = self.cells[run]
            if cell["state"] == "claimed" and \
                    isinstance(cell["deadline"], (int, float)) and \
                    cell["deadline"] < now:
                self._event({"ev": "requeue", "run": run,
                             "worker": cell["worker"],
                             "reason": "lease-expired"})
                out.append(run)
        return out

    def complete(self, run: str, worker: str,
                 record: Dict[str, Any]) -> str:
        """Land a cell's verdict record.  Returns one of:

        - ``"accepted"`` — the one verdict record for this cell; the
          caller (coordinator) appends it to the campaign index.
        - ``"already"`` — the same worker resent the identical record
          (a lost ack): idempotent, ack again, append nothing.
        - ``"duplicate"`` — a zombie's record for a cell someone else
          already finished: discarded, counted, never indexed.
        - ``"unknown"`` — no such cell.

        A completion from a worker whose lease lapsed (the cell is
        requeued or re-claimed but NOT yet done) is accepted:
        first-verdict-wins preserves exactly-one-record-per-cell, and
        the slower executor's later completion becomes the duplicate.
        """
        with self._lock:
            cell = self.cells.get(run)
            if cell is None:
                return "unknown"
            if cell["state"] == "done":
                if cell["completed_by"] == worker and \
                        cell["record_digest"] == record_digest(record):
                    return "already"
                self._event({"ev": "duplicate", "run": run,
                             "worker": worker})
                return "duplicate"
            self._event({"ev": "complete", "run": run, "worker": worker,
                         "record": record})
            return "accepted"

    # -- views ---------------------------------------------------------------

    def counts(self) -> Dict[str, int]:
        with self._lock:
            out = {"queued": 0, "claimed": 0, "done": 0}
            for cell in self.cells.values():
                out[cell["state"]] += 1
            out["cells"] = len(self.cells)
            out["requeues"] = self.requeues
            out["duplicates"] = self.duplicates
            return out

    def done_cells(self) -> List[Dict[str, Any]]:
        """Completed cells in enqueue order (records included) — the
        coordinator's boot reconcile walks these."""
        with self._lock:
            return [dict(self.cells[r]) for r in self._order
                    if self.cells[r]["state"] == "done"]

    def cell_times(self, run: str) -> Dict[str, Any]:
        """One cell's control-plane timing facts (ledger timestamps):
        the material for the ``fleet:enqueue-wait`` segment the
        coordinator stamps into index records (ISSUE 14)."""
        with self._lock:
            c = self.cells.get(run)
            if c is None:
                return {}
            return {"enqueued": c.get("enqueued_ts"),
                    "claimed": c.get("claimed_ts"),
                    "claims": c["claims"], "requeues": c["requeues"]}

    def claim_latencies(self, last: int = 50) -> List[float]:
        """The most recent cells' enqueue->first-claim waits (ledger
        timestamps), in enqueue order — the raw material for the
        scaler's claim-latency signal (ISSUE 17)."""
        with self._lock:
            out = []
            for run in self._order:
                c = self.cells[run]
                enq, clm = c.get("enqueued_ts"), c.get("claimed_ts")
                if isinstance(enq, (int, float)) and \
                        isinstance(clm, (int, float)) and clm >= enq:
                    out.append(round(clm - enq, 6))
            return out[-max(1, int(last)):]

    def claim_latency_p95(self, last: int = 50) -> Optional[float]:
        """Nearest-rank p95 over `claim_latencies` — one of the two
        signals the autopilot scaler sizes the worker pool on (the
        other is queue depth).  None until a cell has been claimed."""
        xs = sorted(self.claim_latencies(last))
        if not xs:
            return None
        import math

        return xs[max(0, math.ceil(0.95 * len(xs)) - 1)]

    def leases(self) -> List[Dict[str, Any]]:
        """Active claims: run / worker / lease deadline."""
        with self._lock:
            return [{"run": r, "worker": c["worker"],
                     "deadline": c["deadline"]}
                    for r in self._order
                    if (c := self.cells[r])["state"] == "claimed"]

    def digest(self) -> str:
        """Digest of the queue state — replay-stable: a coordinator
        killed and restarted over the same ledger reports the same
        digest (the crash-test pin).  Covers cell states, holders,
        lease deadlines, claim counts, and completion identities; the
        observability counters are excluded (they are derived, not
        state)."""
        with self._lock:
            state = [(r, c["state"], c["worker"], c["deadline"],
                      c["claims"], c["completed_by"], c["record_digest"])
                     for r in self._order
                     for c in (self.cells[r],)]
        return hashlib.sha256(
            json.dumps(state, default=str).encode()).hexdigest()[:16]
