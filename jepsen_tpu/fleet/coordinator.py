"""The fleet coordinator: the campaign's HTTP control plane.

One coordinator per campaign (``cli fleet serve <spec.json>``), mounted
on `web.serve` (the handler routes ``POST /fleet/<verb>`` and ``GET
/fleet/status`` here).  It owns three durable artifacts:

- the **work queue ledger** (`queue.WorkQueue`,
  ``<store>/fleet/<name>.jsonl``) — who holds what under which lease;
- the **campaign index** (`campaign.index.Index`) — the same
  append-only verdict ledger a single-process `run_campaign` writes,
  so every downstream surface (web grid, warehouse, regression
  queries, resume) works unchanged on a distributed campaign;
- the **heartbeat file** (`telemetry.Heartbeat`,
  ``<store>/campaigns/<name>.live.json``) — ONE writer for the live
  dashboard, fed over HTTP by remote workers (the PR 5 open item:
  heartbeats pushed over HTTP merge into the exact shape the
  single-process scheduler writes, so ``/campaign/<name>/live`` renders
  both).

Crash discipline: the queue ledger is appended BEFORE the index (a
``complete`` event is the commit point), and boot **reconciles** —
any queue-done cell whose record is missing from the index (a crash
landed between the two appends) is re-appended from the ledger's own
copy of the record.  A ``kill -9``'d coordinator therefore replays to
the identical queue state (`boot digest pinned <queue.WorkQueue.digest>`)
and never loses or doubles a verdict.

Chaos: every endpoint fires the active `resilience.FaultPlan` at its
site (``fleet.register`` / ``fleet.claim`` / ``fleet.heartbeat`` /
``fleet.complete`` / ``fleet.release`` / ``fleet.status``) — an
injected fault becomes a 503 the workers' retry policy rides out, a
``stall`` kind becomes server-side latency.  The same site family
fires client-side in `worker.FleetWorker`, so a plan installed in
either process partitions that side of the seam.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Any, Dict, Optional, Tuple, Union

from jepsen_tpu import store
from jepsen_tpu.campaign import core as ccore
from jepsen_tpu.campaign import plan as plan_mod
from jepsen_tpu.campaign.index import Index
from jepsen_tpu.resilience import faults as faults_mod
from jepsen_tpu.resilience.faults import FaultInjected
from jepsen_tpu.telemetry import spans as spans_mod

from .artifacts import ArtifactStore
from .queue import WorkQueue, fleet_path

logger = logging.getLogger("jepsen.fleet")

__all__ = ["FleetCoordinator"]

#: a worker whose last heartbeat is older than this many leases is
#: counted dead by the workers-alive gauge (it can still come back)
ALIVE_LEASES = 3.0

#: a worker silent for this many leases is dropped from the registry
#: entirely — the cardinality bound (ISSUE 14): a fleet churning
#: through register/expire cycles (worker names embed pids) must not
#: grow the worker table, /fleet/status, or the federated /metrics
#: surface monotonically
PRUNE_LEASES = 40.0

#: per-worker cap on federated metric rows accepted over heartbeat —
#: the other half of the cardinality bound
MAX_FEDERATED_SERIES = 48

#: seconds between artifact-staging GC passes (ISSUE 14 satellite);
#: the passes ride the heartbeat/status paths, no dedicated thread
STAGING_GC_INTERVAL_S = 30.0

#: wall-clock t0 alignment (ISSUE 13 satellite): a generation's window
#: anchor is set this many seconds past its FIRST claim, so the other
#: hosts' cells claimed shortly after share the same absolute timeline
T0_LEAD_S = 0.5

#: a worker whose reported t0 differs from the authoritative anchor by
#: more than this is flagged clock-desynced on /fleet/status
T0_SKEW_S = 0.25


def _registry():
    from jepsen_tpu import telemetry

    return telemetry.registry()


class FleetCoordinator:
    """Serve one campaign spec to a fleet of HTTP workers.

    Thread-safe — the web server's handler threads call straight in
    (the queue has its own lock; the coordinator lock covers the index
    append + done-set bookkeeping).
    """

    def __init__(self, spec: Union[str, dict],
                 base: Optional[str] = None, *,
                 lease_s: float = 15.0,
                 run_deadline_s: Optional[float] = None,
                 staging_retention_s: float = 24 * 3600.0):
        self.spec = plan_mod.load_spec(spec)
        self.base = base or store.BASE
        self.name = self.spec["name"]
        self.lease_s = float(lease_s)
        self.specs = plan_mod.expand(self.spec)
        self.gen = time.strftime("%Y%m%dT%H%M%SZ", time.gmtime())
        self.spec_digest = plan_mod.spec_digest(self.spec)
        self._lock = threading.RLock()
        #: streamed-generation labels (ISSUE 17): run id -> the
        #: autopilot generation label its record is stamped with (the
        #: gate groups samples by this; admit() fills it)
        self._gen_by_run: Dict[str, str] = {}
        #: the owning `fleet.autopilot.Autopilot`, when one drives
        #: this coordinator — /fleet/status and the web panel join
        #: its status_doc through this
        self.autopilot: Optional[Any] = None
        for rs in self.specs:
            # same opt plumbing as run_campaign: a hard per-run wall
            # also bounds the checkers cooperatively
            if run_deadline_s and \
                    rs.opts.get("checker-time-limit") is None:
                rs.opts["checker-time-limit"] = run_deadline_s
        #: the campaign-level nemesis schedule (ISSUE 11): per
        #: generation g (= the seed axis), the synchronized window set
        #: every host's cell installs.  Expanded once here — the same
        #: pure function `expand` evaluates, so the sets broadcast over
        #: claim equal the ones already baked into the cell opts.
        self.sched = self.spec.get("nemesis-schedule")
        self._windows_by_gen: Dict[int, list] = {}
        self._windows_digests: Dict[int, str] = {}
        #: per-generation wall-clock window anchor (ISSUE 13): lazily
        #: set at a generation's first claim; broadcast with the
        #: window set so every host fires the schedule on the
        #: coordinator's absolute timeline
        self._gen_t0: Dict[int, float] = {}
        #: store federation (ISSUE 13): the artifact-upload endpoint's
        #: staging + atomic landing
        self.artifacts = ArtifactStore(self.base)
        try:
            # mint the fleet cache-transfer secret (compilecache.fleet
            # HMAC) up front so shared-base workers find it before
            # their first pull/push; env override wins inside
            from jepsen_tpu.compilecache import fleet as cc_fleet

            cc_fleet.shared_secret(self.base, create=True)
        except Exception:  # noqa: BLE001 — transfers then refuse,
            # workers just compile locally
            logger.warning("fleet secret mint failed", exc_info=True)
        #: staging retention (ISSUE 14 satellite): permanently
        #: abandoned upload partials expire past this; GC rides the
        #: heartbeat/status paths, throttled to one pass per interval
        self.staging_retention_s = float(staging_retention_s)
        self._staging_gc_at = 0.0
        if self.sched:
            for g in self.spec["seeds"]:
                # pass the normalized block, not the whole spec — the
                # spec path would re-run load_spec once per seed
                wins = plan_mod.schedule_windows(self.sched, g)
                self._windows_by_gen[int(g)] = wins
                self._windows_digests[int(g)] = \
                    plan_mod.windows_digest(wins)
        self.idx = Index(ccore.index_path(self.name, self.base))
        spec_ids = {rs.run_id for rs in self.specs}
        self._done_ids = self.idx.completed_ids() & spec_ids
        self.queue = WorkQueue(fleet_path(self.name, self.base))
        #: the replayed-state digest at construction — the kill -9
        #: crash tests compare this against an independent replay of
        #: the pre-kill ledger
        self.boot_digest = self.queue.digest()
        enqueued = 0
        for rs in self.specs:
            if rs.run_id in self._done_ids:
                continue  # resume parity: indexed cells never re-run
            if self.queue.enqueue(rs.to_dict()):
                enqueued += 1
        self._reconcile()
        #: worker registry: name -> capabilities + last_seen
        self.workers: Dict[str, Dict[str, Any]] = {}
        from jepsen_tpu.telemetry import Heartbeat

        #: the ONE live.json writer per campaign; remote workers feed
        #: it over /fleet/heartbeat
        self._hbs: Dict[str, Any] = {}
        self._hbs[self.name] = Heartbeat(
            ccore.live_path(self.name, self.base), campaign=self.name,
            total=len(self.specs), done=len(self._done_ids))
        logger.info(
            "fleet %s: %d cells (%d already indexed, %d enqueued), "
            "lease %.1fs, boot digest %s", self.name, len(self.specs),
            len(self._done_ids), enqueued, self.lease_s,
            self.boot_digest)
        self._update_gauges()

    def _reconcile(self) -> None:
        """Re-append index records for queue-done cells the index
        missed — the crash window between the queue's ``complete``
        event (the commit point) and the index append."""
        indexed = self.idx.completed_ids()
        for cell in self.queue.done_cells():
            run = cell["run"]
            if run in indexed or not isinstance(cell["record"], dict):
                continue
            self.idx.append(self._stamp(cell["record"],
                                        cell["completed_by"]))
            self._done_ids.add(run)
            logger.info("fleet %s: reconciled missing index record "
                        "for %s", self.name, run)

    def admit(self, run_specs, gen: Optional[str] = None
              ) -> Dict[str, int]:
        """Stream a new generation of cells into the LIVE queue (the
        autopilot's enqueue seam, ISSUE 17): extend the plan, map each
        run id to its generation label for record stamping, and
        enqueue idempotently — already-indexed cells count done
        immediately (restart-free resume), already-queued ids are
        refused by the ledger.  Safe to call any number of times with
        the same specs; that is the crash-window contract."""
        added = enq = already = 0
        with self._lock:
            known = {rs.run_id for rs in self.specs}
            indexed = self.idx.completed_ids()
            for rs in run_specs:
                rid = rs.run_id
                if gen:
                    self._gen_by_run[rid] = str(gen)
                if rid not in known:
                    self.specs.append(rs)
                    known.add(rid)
                    added += 1
                if rid in indexed:
                    if rid not in self._done_ids:
                        self._done_ids.add(rid)
                        already += 1
                elif self.queue.enqueue(rs.to_dict()):
                    enq += 1
            hb = self._hbs.get(self.name)
            if hb is not None:
                try:
                    hb.state["total"] = len(self.specs)
                    hb.state["done"] = len(self._done_ids)
                except Exception:  # noqa: BLE001 — display only
                    pass
        self._update_gauges()
        logger.info("fleet %s: admitted %s (+%d cells, %d enqueued, "
                    "%d already indexed)", self.name, gen or "-",
                    added, enq, already)
        return {"admitted": added, "enqueued": enq,
                "already-done": already}

    def _stamp(self, record: Dict[str, Any], worker: Any
               ) -> Dict[str, Any]:
        rec = dict(record)
        with self._lock:
            gen = self._gen_by_run.get(str(record.get("run") or ""))
        rec.setdefault("gen", gen or self.gen)
        rec.setdefault("spec", self.spec_digest)
        if worker:
            rec.setdefault("fleet-worker", str(worker))
        run = rec.get("run")
        if run:
            # trace stitching (ISSUE 14): the record always names its
            # trace (derived from the stable run id — identical across
            # retries), and the control-plane segments only the
            # coordinator's ledger knows land as gateable spans next
            # to the worker's checker spans (`obs gate --span
            # fleet:enqueue-wait` works like any checker span)
            rec.setdefault("trace", spans_mod.trace_id_for(str(run)))
            t = self.queue.cell_times(str(run))
            enq, clm = t.get("enqueued"), t.get("claimed")
            spans = rec.setdefault("spans", {})
            if isinstance(spans, dict) \
                    and isinstance(enq, (int, float)) \
                    and isinstance(clm, (int, float)) and clm >= enq:
                spans.setdefault("fleet:enqueue-wait",
                                 round(clm - enq, 6))
        return rec

    # -- shared endpoint plumbing -------------------------------------------

    def _guarded(self, site: str, fn, *args
                 ) -> Tuple[int, Dict[str, Any]]:
        """Fire the active fault plan at this control-plane seam, then
        run the handler.  Injected faults surface as 503 (the "drop"
        the workers' retry policy rides out); ``stall`` kinds sleep
        inside ``fire`` — server-side latency."""
        try:
            plan = faults_mod.active_plan()
            if plan is not None:
                plan.fire(site)
        except FaultInjected as e:
            return 503, {"error": str(e), "injected": True}
        try:
            return fn(*args)
        except Exception as e:  # noqa: BLE001 — a handler bug must
            logger.exception("fleet %s failed", site)  # not kill serve
            return 500, {"error": f"{type(e).__name__}: {e}"}

    @property
    def finished(self) -> bool:
        with self._lock:
            return len(self._done_ids) >= len(self.specs)

    # -- endpoints (code, doc) ----------------------------------------------

    def register(self, body: Dict[str, Any]
                 ) -> Tuple[int, Dict[str, Any]]:
        return self._guarded("fleet.register", self._register, body)

    def _register(self, body: Dict[str, Any]
                  ) -> Tuple[int, Dict[str, Any]]:
        worker = str(body.get("worker") or "")
        if not worker:
            return 400, {"error": "register needs a worker name"}
        with self._lock:
            self.workers[worker] = {
                "host": body.get("host"),
                "backend": body.get("backend"),
                "mesh": body.get("mesh"),
                "device-slots": int(body.get("device-slots", 1)),
                # rolling-upgrade visibility (ISSUE 17): the stamped
                # build version, refreshed on heartbeat, rendered as
                # jepsen_fleet_host_info{host,version}
                "version": str(body.get("version") or "") or None,
                "registered": round(time.time(), 3),
                "last-seen": round(time.time(), 3),
            }
        self._update_gauges()
        return 200, {"ok": True, "campaign": self.name,
                     "lease-s": self.lease_s,
                     "total": len(self.specs),
                     "nemesis-schedule": bool(self.sched)}

    def claim(self, body: Dict[str, Any]) -> Tuple[int, Dict[str, Any]]:
        return self._guarded("fleet.claim", self._claim, body)

    def _claim(self, body: Dict[str, Any]) -> Tuple[int, Dict[str, Any]]:
        worker = str(body.get("worker") or "")
        if not worker:
            return 400, {"error": "claim needs a worker name"}
        caps = self._touch(worker)
        spec, deadline = self.queue.claim(
            worker, lease_s=self.lease_s,
            device_ok=caps.get("device-slots", 1) > 0, caps=caps)
        self._update_gauges()
        if spec is None:
            c = self.queue.counts()
            # under an autopilot the fleet is never "finished" from a
            # worker's perspective — a drained generation is just the
            # gap before the next one streams in (ISSUE 17).  Workers
            # idle-poll; the autopilot drains them by SIGTERM when the
            # loop actually ends.
            fin = self.finished and self.autopilot is None
            return 200, {"spec": None, "finished": fin,
                         "queued": c["queued"], "claimed": c["claimed"]}
        out = {"spec": spec, "lease-s": self.lease_s,
               "deadline": deadline}
        # the trace broadcast (ISSUE 14): the claim carries the run's
        # trace context — minted at enqueue time semantics (a pure
        # function of the run id, so a re-claim after a lease lapse
        # hands out the SAME trace), parented on the coordinator's
        # claim segment
        ctx = spans_mod.mint_trace(str(spec.get("run_id") or ""))
        out["trace"] = dict(ctx.child("fleet:claim").to_dict(),
                            header=ctx.header())
        try:
            # compile-cache advert (docs/COMPILECACHE.md): the claim
            # names every distributable AOT entry so the worker can
            # pull what it lacks BEFORE executing — its first cell of
            # a known shape class pays dispatch, not compile.  Digests
            # are (size, mtime)-memoized; an empty store adverts
            # nothing and costs one listdir.
            from jepsen_tpu.compilecache import fleet as cc_fleet

            adv = cc_fleet.export_index(self.cache_dir())
            if adv:
                out["compilecache"] = adv
        except Exception:  # noqa: BLE001 — the advert is best-effort
            logger.debug("compilecache advert failed", exc_info=True)
        if self.sched:
            # the window broadcast: the claim response is the
            # AUTHORITATIVE carrier of the cell generation's
            # synchronized window set — a worker that missed every
            # heartbeat tick still installs the correct seeded windows
            # from here, before execute_run
            g = int(spec.get("seed", 0))
            with self._lock:
                # wall-clock t0 alignment: one absolute anchor per
                # generation, minted at its first claim.  The claim
                # also carries the coordinator's "now" so the worker
                # can estimate its clock offset and convert the anchor
                # into its own clock domain.
                t0 = self._gen_t0.setdefault(
                    g, round(time.time() + T0_LEAD_S, 3))
            out["windows"] = {
                "gen": g,
                "set": self._windows_by_gen.get(g, []),
                "digest": self._windows_digests.get(g, ""),
                "t0": t0,
                "now": round(time.time(), 3),
            }
        return 200, out

    def heartbeat(self, body: Dict[str, Any]
                  ) -> Tuple[int, Dict[str, Any]]:
        return self._guarded("fleet.heartbeat", self._heartbeat, body)

    def _heartbeat(self, body: Dict[str, Any]
                   ) -> Tuple[int, Dict[str, Any]]:
        """The merged heartbeat sink: fleet workers renew leases and
        publish in-flight state; remote `run_campaign`\\ s
        (`telemetry.stream.HttpHeartbeat`) push whole-campaign
        progress.  Everything lands in the campaign's ONE
        `telemetry.Heartbeat` writer, so ``/campaign/<n>/live``
        renders both sources in the same shape."""
        campaign = str(body.get("campaign") or self.name)
        worker = body.get("worker")
        hb = self._hb_for(campaign, total=body.get("total"),
                          done=body.get("init-done"))
        if worker is not None:
            # liveness only for KNOWN fleet workers (registered, or
            # implicitly via claim/complete): a remote run_campaign's
            # scheduler slot names (campaign-worker-0, ...) pushing
            # through this sink are that campaign's workers, not this
            # fleet's — registering them would pollute the worker
            # table and over-count the workers-alive gauge
            with self._lock:
                known = str(worker) in self.workers
            if known:
                self._touch(str(worker))
                if body.get("version"):
                    with self._lock:
                        if str(worker) in self.workers:
                            self.workers[str(worker)]["version"] = \
                                str(body["version"])
            if "state" in body:
                hb.worker(str(worker), body.get("state"))
        out: Dict[str, Any] = {"ok": True, "lease-s": self.lease_s}
        mx = body.get("metrics")
        if worker is not None and isinstance(mx, list):
            # metrics federation (ISSUE 14 tentpole b): the heartbeat
            # doubles as the metrics push channel.  Rows are capped
            # per worker (cardinality bound) and retire with worker
            # liveness — the exposition only renders alive workers'
            # snapshots, and the prune drops silent workers entirely
            rows = [r for r in mx[:MAX_FEDERATED_SERIES]
                    if isinstance(r, dict) and r.get("name")
                    and isinstance(r.get("value"), (int, float))]
            with self._lock:
                if str(worker) in self.workers:
                    self.workers[str(worker)]["metrics"] = {
                        "rows": rows, "ts": round(time.time(), 3)}
        wins = body.get("windows")
        if worker is not None and "windows" in body and wins is None:
            with self._lock:  # cell done: the worker's windows retire
                if str(worker) in self.workers:
                    self.workers[str(worker)].pop("windows", None)
        if worker is not None and isinstance(wins, dict):
            # window open/close ticks (ISSUE 11): lease renewal doubles
            # as chaos clock sync — the worker reports its installed
            # window digest + currently-open positions, the coordinator
            # records them (the /fleet dashboard's desync view) and
            # echoes the authoritative digest for that generation so a
            # desynced worker can see it immediately
            with self._lock:
                if str(worker) in self.workers:
                    self.workers[str(worker)]["windows"] = dict(
                        wins, ts=round(time.time(), 3))
            try:
                g = int(wins.get("gen"))
            except (TypeError, ValueError):
                g = None
            if g is not None and g in self._windows_digests:
                out["windows-digest"] = self._windows_digests[g]
        done = body.get("done")
        if isinstance(done, dict):
            hb.record_done(done.get("run"), done.get("valid?"))
        lost = []
        for run in body.get("renew") or []:
            if not self.queue.renew(str(run), str(worker or ""),
                                    self.lease_s):
                lost.append(str(run))
        self.queue.expire()
        if body.get("finished"):
            hb.close()
        self._update_gauges()
        out["lost"] = lost
        return 200, out

    def complete(self, body: Dict[str, Any]
                 ) -> Tuple[int, Dict[str, Any]]:
        return self._guarded("fleet.complete", self._complete, body)

    def _complete(self, body: Dict[str, Any]
                  ) -> Tuple[int, Dict[str, Any]]:
        worker = str(body.get("worker") or "")
        run = str(body.get("run") or "")
        record = body.get("record")
        if not worker or not run or not isinstance(record, dict):
            return 400, {"error": "complete needs worker, run, record"}
        self._touch(worker)
        status = self.queue.complete(run, worker, record)
        if status == "unknown":
            return 404, {"error": f"no such cell {run!r}"}
        if status == "accepted":
            with self._lock:
                self.idx.append(self._stamp(record, worker))
                self._done_ids.add(run)
                hb = self._hbs.get(self.name)
                if hb is not None:
                    hb.record_done(run, record.get("valid?"))
                    if self.finished:
                        hb.close()
        self._update_gauges()
        if status == "duplicate":
            logger.warning("fleet %s: duplicate completion of %s by "
                           "zombie %s discarded", self.name, run, worker)
            return 200, {"ok": False, "duplicate": True}
        return 200, {"ok": True, "status": status,
                     "finished": self.finished}

    def artifact(self, run_id: str, params: Dict[str, Any],
                 body: bytes) -> Tuple[int, Dict[str, Any]]:
        """``POST /fleet/artifact/<run-id>`` — the store-federation
        upload seam (chunked + digest-verified + idempotent; see
        `artifacts.ArtifactStore`).  Guarded like every other
        control-plane endpoint, so chaos plans drop/stall uploads."""
        return self._guarded("fleet.artifact", self._artifact,
                             run_id, params, body)

    def _artifact(self, run_id: str, params: Dict[str, Any],
                  body: bytes) -> Tuple[int, Dict[str, Any]]:
        code, doc = self.artifacts.handle(run_id, params, body)
        # compile-cache distribution (docs/COMPILECACHE.md): a landed
        # "compilecache/<batch>" artifact is a worker pushing AOT
        # entries, not a run dir — absorb them into the flat store so
        # the next claim's advert carries them fleet-wide
        landed_dir = doc.get("dir")
        if doc.get("landed") and not doc.get("already") \
                and isinstance(landed_dir, str) \
                and landed_dir.startswith("compilecache/"):
            try:
                from jepsen_tpu.compilecache import fleet as cc_fleet

                doc["absorbed"] = cc_fleet.absorb(self.base, landed_dir)
            except Exception:  # noqa: BLE001 — absorb is best-effort
                logger.warning("compilecache absorb of %s failed",
                               landed_dir, exc_info=True)
        return code, doc

    def cache_dir(self) -> str:
        """The coordinator's AOT entry store (pre-warmed by ``cli
        cache warm``, grown by worker pushes)."""
        return os.path.join(self.base, "compilecache")

    def cache_index(self) -> Tuple[int, Dict[str, Any]]:
        """``GET /fleet/cache`` — the distributable entry advert."""
        return self._guarded("fleet.cache", self._cache_index)

    def _cache_index(self) -> Tuple[int, Dict[str, Any]]:
        from jepsen_tpu.compilecache import fleet as cc_fleet

        entries = cc_fleet.export_index(self.cache_dir())
        return 200, {"entries": entries,
                     "bytes": sum(e["size"] for e in entries)}

    def cache_blob(self, name: str) -> Tuple[int, Dict[str, Any]]:
        """``GET /fleet/cache/<name>`` — one verified entry's bytes
        (the web layer streams ``doc["_blob"]`` as octet-stream with
        the ``doc["_mac"]`` HMAC in the response header, which the
        worker verifies before unpickling anything)."""
        return self._guarded("fleet.cache", self._cache_blob, name)

    def _cache_blob(self, name: str) -> Tuple[int, Dict[str, Any]]:
        from jepsen_tpu.compilecache import fleet as cc_fleet

        blob = cc_fleet.read_entry(self.cache_dir(), name)
        if blob is None:
            return 404, {"error": f"no cache entry {name!r}"}
        doc: Dict[str, Any] = {"_blob": blob, "name": name}
        secret = cc_fleet.shared_secret(self.base, create=True)
        if secret is not None:
            doc["_mac"] = cc_fleet.entry_mac(secret, blob)
        return 200, doc

    def release(self, body: Dict[str, Any]
                ) -> Tuple[int, Dict[str, Any]]:
        return self._guarded("fleet.release", self._release, body)

    def _release(self, body: Dict[str, Any]
                 ) -> Tuple[int, Dict[str, Any]]:
        worker = str(body.get("worker") or "")
        run = str(body.get("run") or "")
        ok = self.queue.release(run, worker)
        self._update_gauges()
        return 200, {"ok": ok}

    def status(self) -> Tuple[int, Dict[str, Any]]:
        return self._guarded("fleet.status", self._status)

    def _status(self) -> Tuple[int, Dict[str, Any]]:
        self.queue.expire()
        now = time.time()
        with self._lock:
            workers = {}
            for w, c in self.workers.items():
                row = {"host": c.get("host"),
                       "backend": c.get("backend"),
                       "mesh": c.get("mesh"),
                       "device-slots": c.get("device-slots"),
                       "version": c.get("version"),
                       "age-s": round(now - c["last-seen"], 3),
                       "alive": now - c["last-seen"] <=
                       ALIVE_LEASES * self.lease_s}
                wins = c.get("windows")
                if isinstance(wins, dict):
                    g = wins.get("gen")
                    auth = self._windows_digests.get(
                        int(g)) if isinstance(g, int) else None
                    row["windows"] = dict(
                        wins, synced=(auth is not None and
                                      wins.get("digest") == auth))
                    # clock-desync visibility (ISSUE 13): the worker's
                    # reported (offset-corrected) t0 vs the anchor
                    auth_t0 = (self._gen_t0.get(int(g))
                               if isinstance(g, int) else None)
                    wt0 = wins.get("t0")
                    if isinstance(wt0, (int, float)) \
                            and auth_t0 is not None:
                        skew = round(float(wt0) - auth_t0, 3)
                        row["windows"]["t0-skew"] = skew
                        row["windows"]["clock-synced"] = \
                            abs(skew) <= T0_SKEW_S
                workers[w] = row
            done = len(self._done_ids)
        self._update_gauges()
        counts = self.queue.counts()
        out = {
            "campaign": self.name,
            "gen": self.gen,
            "spec-digest": self.spec_digest,
            "total": len(self.specs),
            "done": done,
            "finished": done >= len(self.specs),
            "counts": counts,
            # the scaler's two inputs (ISSUE 17 satellite), first-class
            # instead of derivable-via-obs-sql
            "queue-depth": counts["queued"],
            "claim-latency-p95-s": self.queue.claim_latency_p95(),
            "leases": self.queue.leases(),
            "digest": self.queue.digest(),
            "boot-digest": self.boot_digest,
            "lease-s": self.lease_s,
            "workers": workers,
        }
        ap = self.autopilot
        if ap is not None:
            try:
                out["autopilot"] = ap.status_doc()
            except Exception:  # noqa: BLE001 — panel is best-effort
                logger.debug("autopilot status failed", exc_info=True)
        if self.sched:
            with self._lock:
                t0s = {str(g): t for g, t in
                       sorted(self._gen_t0.items())}
            out["nemesis-schedule"] = {
                "faults": self.sched["faults"],
                "windows": self.sched["windows"],
                "digest-by-gen": {str(g): d for g, d in
                                  sorted(self._windows_digests.items())},
                "t0-by-gen": t0s,
                "gens": {str(g): w for g, w in
                         sorted(self._windows_by_gen.items())},
            }
        return 200, out

    # -- internals -----------------------------------------------------------

    def _touch(self, worker: str) -> Dict[str, Any]:
        """Refresh a worker's liveness; unseen workers get implicit
        default capabilities (register is polite, not mandatory)."""
        with self._lock:
            caps = self.workers.setdefault(worker, {
                "host": None, "backend": None, "mesh": None,
                "device-slots": 1, "version": None,
                "registered": round(time.time(), 3),
                "last-seen": round(time.time(), 3)})
            caps["last-seen"] = round(time.time(), 3)
            return dict(caps)

    def _hb_for(self, campaign: str, total: Any = None,
                done: Any = None):
        from jepsen_tpu.telemetry import Heartbeat

        with self._lock:
            hb = self._hbs.get(campaign)
            if hb is None:
                hb = self._hbs[campaign] = Heartbeat(
                    ccore.live_path(campaign, self.base),
                    campaign=campaign,
                    total=int(total or 0), done=int(done or 0))
            return hb

    def federated_metrics(self) -> Dict[str, Dict[str, Any]]:
        """The fleet exposition's source (ISSUE 14 tentpole b): each
        ALIVE worker's last pushed metrics snapshot, keyed by worker
        name.  Dead workers' series retire here — the same
        liveness-gated discipline as PR 13's per-session gauge
        retirement, so a scrape's series set shrinks back as workers
        expire instead of growing monotonically."""
        now = time.time()
        out: Dict[str, Dict[str, Any]] = {}
        with self._lock:
            for w, c in self.workers.items():
                if now - c["last-seen"] > ALIVE_LEASES * self.lease_s:
                    continue
                mx = c.get("metrics")
                if isinstance(mx, dict) and mx.get("rows"):
                    out[w] = {"host": c.get("host"),
                              "version": c.get("version"),
                              "rows": list(mx["rows"]),
                              "age-s": round(now - mx["ts"], 3)}
        return out

    def _prune_workers(self, now: float) -> None:
        """Drop workers silent past PRUNE_LEASES from the registry —
        bounds the worker table (names embed pids, so a churning fleet
        mints new ones forever) and with it /fleet/status and the
        federated metrics surface.  Caller holds self._lock."""
        cutoff = PRUNE_LEASES * self.lease_s
        for w in [w for w, c in self.workers.items()
                  if now - c["last-seen"] > cutoff]:
            del self.workers[w]

    def gc_staging(self, now: Optional[float] = None) -> Dict[str, int]:
        """One artifact-staging retention pass (ISSUE 14 satellite):
        expire permanently abandoned upload partials, refresh the
        ``fleet-artifact-staging-bytes`` gauge."""
        return self.artifacts.gc(self.staging_retention_s, now=now)

    def _maybe_gc_staging(self, now: float) -> None:
        with self._lock:
            due = now >= self._staging_gc_at
            if due:
                self._staging_gc_at = now + STAGING_GC_INTERVAL_S
        if due:
            try:
                self.gc_staging(now)
            except Exception:  # noqa: BLE001 — retention is best-effort
                logger.debug("staging gc failed", exc_info=True)

    def _update_gauges(self) -> None:
        """The fleet's /metrics surface (live registry): workers alive
        by heartbeat freshness, active leases, cells by state."""
        try:
            reg = _registry()
            now = time.time()
            with self._lock:
                self._prune_workers(now)
                alive = sum(
                    1 for c in self.workers.values()
                    if now - c["last-seen"] <= ALIVE_LEASES * self.lease_s)
            c = self.queue.counts()
            reg.gauge("fleet-workers-alive").set(alive)
            reg.gauge("fleet-leases-active").set(c["claimed"])
            for state in ("queued", "claimed", "done"):
                reg.gauge("fleet-cells", state=state).set(c[state])
            # the scaler's two inputs (ISSUE 17): depth + claim p95
            reg.gauge("fleet-queue-depth").set(c["queued"])
            p95 = self.queue.claim_latency_p95()
            if p95 is not None:
                reg.gauge("fleet-claim-latency-p95-s").set(p95)
            if self.sched:
                # chaos visibility: currently-open windows across the
                # fleet, by fault family, from the workers' heartbeat
                # ticks (stale workers excluded by liveness)
                open_by_fault = {f: 0 for f in self.sched["faults"]}
                with self._lock:
                    for cw in self.workers.values():
                        if now - cw["last-seen"] > \
                                ALIVE_LEASES * self.lease_s:
                            continue
                        wins = cw.get("windows")
                        for o in (wins or {}).get("open") or ():
                            f = str((o or {}).get("fault"))
                            if f in open_by_fault:
                                open_by_fault[f] += 1
                for f, n in open_by_fault.items():
                    reg.gauge("fleet-nemesis-windows-active",
                              campaign=self.name, fault=f).set(n)
            self._maybe_gc_staging(now)
        except Exception:  # noqa: BLE001 — observability only
            logger.debug("fleet gauge update failed", exc_info=True)

    def summary(self) -> Dict[str, Any]:
        """The suite rollup once the fleet finished — the same shape
        `run_campaign` returns (`campaign.core.summarize`)."""
        return ccore.summarize(self.spec, self.base, idx=self.idx)

    def close(self) -> None:
        """Shut down the observability side.  Only THIS fleet's own
        heartbeat is closed, and only when its campaign actually
        finished — an interrupted fleet must leave its in-flight state
        in live.json for the /live post-mortem (same contract as
        `run_campaign`), and heartbeats created for OTHER campaigns
        pushing through /fleet/heartbeat belong to those campaigns:
        they close themselves via their own ``finished`` push."""
        with self._lock:
            hb = self._hbs.get(self.name)
        if hb is not None and self.finished:
            hb.close()
