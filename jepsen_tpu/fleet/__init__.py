"""jepsen_tpu.fleet — the fault-tolerant multi-host control plane.

A distributed, crash-tolerant execution layer over `campaign/`
(docs/FLEET.md): a coordinator serves a campaign spec as a **leased
work queue** over HTTP (`coordinator.FleetCoordinator` on `web.serve`),
remote workers (`worker.FleetWorker`, ``cli fleet work``) claim cells,
execute them through `campaign.core.execute_run`, renew their leases
while running, and upload the verdict record — and the whole plane
survives its own nemeses: worker ``kill -9`` (lease lapses, cell
requeues), coordinator ``kill -9`` (the fsync'd ledger replays to the
identical queue state), partitions (workers retry through them), and
zombie double-completions (discarded, at-most-once verdicts).

The contract is the campaign contract, distributed: every cell
terminates with exactly one attributable verdict record in the same
append-only index a single-process `run_campaign` writes.

On top sits the **autopilot** (`autopilot.Autopilot`, ``cli fleet
autopilot``, docs/AUTOPILOT.md): the continuous driver that streams
spec-template generations into the queue forever, gates each one,
quarantines + auto-shrinks regressions, and scales the worker pool —
including rolling version upgrades — from its own crash-replayable
journal.
"""

from .autopilot import (Autopilot, AutopilotJournal, autopilot_path,
                        scenario_rotation)
from .coordinator import FleetCoordinator
from .queue import WorkQueue, fleet_path, record_digest
from .worker import FleetWorker

__all__ = ["Autopilot", "AutopilotJournal", "FleetCoordinator",
           "FleetWorker", "WorkQueue", "autopilot_path",
           "fleet_path", "record_digest", "scenario_rotation"]
