"""Network manipulation: partitions and traffic shaping.

Equivalent of the reference's `jepsen/net.clj` + `net/proto.clj`
(SURVEY.md §2.1): the `Net` protocol — `drop_` (block src->dst), `heal`,
`slow`, `flaky`, `fast`, `shape` — with the default implementation
shelling out to **iptables** (partitions) and **tc qdisc netem**
(latency/loss/rate) on each node via the control plane, exactly the
binaries the reference drives.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

from jepsen_tpu import control
from jepsen_tpu.control import on_nodes


class Net:
    """Network manipulation protocol.  All methods take the full test map
    (for nodes/remote) and act cluster-wide unless given src/dst."""

    def drop_(self, test: dict, src: str, dst: str) -> None:
        """Block traffic from src to dst (on dst's side)."""
        raise NotImplementedError

    def heal(self, test: dict) -> None:
        """Remove all partitions."""
        raise NotImplementedError

    def slow(self, test: dict, *, mean_ms: float = 50.0,
             variance_ms: float = 10.0,
             distribution: str = "normal") -> None:
        """Add latency to all node links."""
        raise NotImplementedError

    def flaky(self, test: dict, *, loss_pct: float = 20.0,
              correlation_pct: float = 75.0) -> None:
        """Introduce packet loss."""
        raise NotImplementedError

    def fast(self, test: dict) -> None:
        """Remove traffic shaping (undo slow/flaky/shape)."""
        raise NotImplementedError

    def shape(self, test: dict, behaviors: Sequence[str]) -> None:
        """Apply raw netem behaviors, e.g. ["delay", "100ms", "loss", "5%"]."""
        raise NotImplementedError


class NoopNet(Net):
    def drop_(self, test, src, dst):
        pass

    def heal(self, test):
        pass

    def slow(self, test, **kw):
        pass

    def flaky(self, test, **kw):
        pass

    def fast(self, test):
        pass

    def shape(self, test, behaviors):
        pass


noop = NoopNet()


class IptablesNet(Net):
    """The reference's default `net/iptables` impl: DROP rules on the
    receiving node; netem on eth for shaping."""

    def __init__(self, interface: str = "eth0", chain: str = "INPUT"):
        self.interface = interface
        self.chain = chain

    def drop_(self, test, src, dst):
        def fn(t, node):
            control.exec_("iptables", "-A", self.chain, "-s", src,
                          "-j", "DROP", "-w")
        on_nodes(test, fn, nodes=[dst])

    def drop_all(self, test, grudge: Dict[str, Sequence[str]]) -> None:
        """Apply a whole grudge map {dst: [srcs-to-block]} in one parallel
        fan-out (reference: `net/drop-all!`)."""

        def fn(t, node):
            for src in grudge.get(node, ()):
                control.exec_("iptables", "-A", self.chain, "-s", src,
                              "-j", "DROP", "-w")
        on_nodes(test, fn, nodes=[n for n, srcs in grudge.items() if srcs])

    def heal(self, test):
        def fn(t, node):
            control.exec_("iptables", "-F", "-w")
            control.exec_("iptables", "-X", "-w")
        on_nodes(test, fn)

    def _netem(self, test, *behavior: str) -> None:
        def fn(t, node):
            control.exec_("tc", "qdisc", "replace", "dev", self.interface,
                          "root", "netem", *behavior)
        on_nodes(test, fn)

    def slow(self, test, *, mean_ms=50.0, variance_ms=10.0,
             distribution="normal"):
        self._netem(test, "delay", f"{mean_ms}ms", f"{variance_ms}ms",
                    "distribution", distribution)

    def flaky(self, test, *, loss_pct=20.0, correlation_pct=75.0):
        self._netem(test, "loss", f"{loss_pct}%", f"{correlation_pct}%")

    def shape(self, test, behaviors):
        self._netem(test, *behaviors)

    def fast(self, test):
        def fn(t, node):
            # deleting a qdisc that isn't there exits nonzero; that's fine
            control.exec_result("tc", "qdisc", "del", "dev", self.interface,
                                "root")
        on_nodes(test, fn)


class SimNet(Net):
    """In-memory net for tests: records the current partition state and
    shaping, and can drive a `MemStore`-style reachability predicate."""

    def __init__(self):
        self.blocked = set()  # (src, dst) pairs
        self.shaping: Optional[list] = None

    def drop_(self, test, src, dst):
        self.blocked.add((src, dst))

    def heal(self, test):
        self.blocked.clear()

    def slow(self, test, **kw):
        self.shaping = ["slow", kw]

    def flaky(self, test, **kw):
        self.shaping = ["flaky", kw]

    def shape(self, test, behaviors):
        self.shaping = list(behaviors)

    def fast(self, test):
        self.shaping = None

    def reachable(self, src: str, dst: str) -> bool:
        return (src, dst) not in self.blocked
