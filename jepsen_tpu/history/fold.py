"""Chunk-parallel folds over histories, with fold fusion.

Equivalent of the reference's `jepsen/history/fold.clj` + `task.clj`
(SURVEY.md §2.2): a fold is a spec of

- ``reducer_identity`` / ``reducer`` / ``post_reducer`` — applied within a
  chunk,
- ``combiner_identity`` / ``combiner`` / ``post_combiner`` — applied across
  chunk results **in order**,
- ``associative`` — when False the fold runs serially (exact reference
  semantics: only associative folds go chunk-parallel).

:class:`Folder` binds to a chunked op source (a History, a store
``LazyHistory``, or an explicit chunk list) and **fuses** concurrently
requested folds into one pass — each chunk is traversed once no matter how
many folds run (`fold_many`), the reference's signature optimization.

The numeric hot path lives on device: once a history is packed
(`history/soa.py`), sums/counts/extrema are jax segment reductions
(`ops/segments.py`).  This module is the general host path for arbitrary
Python reducers, parallelized across chunks with threads (numpy-heavy
reducers release the GIL; pure-Python ones still win via fusion).
"""

from __future__ import annotations

import concurrent.futures as _fut
import dataclasses
import os
from typing import Any, Callable, Dict, List, Optional, Sequence

from .ops import History, Op

CHUNK_SIZE = 16384


def _identity(x: Any) -> Any:
    return x


@dataclasses.dataclass
class Fold:
    """A fold spec (reference fold maps)."""

    reducer_identity: Callable[[], Any]
    reducer: Callable[[Any, Op], Any]
    post_reducer: Callable[[Any], Any] = _identity
    combiner_identity: Optional[Callable[[], Any]] = None
    combiner: Optional[Callable[[Any, Any], Any]] = None
    post_combiner: Callable[[Any], Any] = _identity
    associative: bool = True
    name: str = "fold"


def fold_spec(*, reducer_identity, reducer, post_reducer=_identity,
              combiner_identity=None, combiner=None,
              post_combiner=_identity, associative=True,
              name="fold") -> Fold:
    return Fold(reducer_identity, reducer, post_reducer, combiner_identity,
                combiner, post_combiner, associative, name)


class Folder:
    """Bound to one chunked source; runs (fused) folds over it."""

    def __init__(self, chunks_or_history, *,
                 max_workers: Optional[int] = None):
        self._chunks = self._chunkify(chunks_or_history)
        self.max_workers = max_workers or min(8, (os.cpu_count() or 2))

    @staticmethod
    def _chunkify(src) -> List[Sequence[Op]]:
        # store.format.LazyHistory: chunk-at-a-time access
        if hasattr(src, "iter_chunks"):
            return list(src.iter_chunks())
        if isinstance(src, History):
            ops = src.ops
        else:
            ops = list(src)
            if ops and not isinstance(ops[0], Op):
                # already a list of chunks
                return [list(c) for c in ops]
        return [ops[i:i + CHUNK_SIZE]
                for i in range(0, len(ops), CHUNK_SIZE)] or [[]]

    # -- execution ---------------------------------------------------------

    def _reduce_chunk(self, folds: Sequence[Fold], chunk: Sequence[Op]
                      ) -> List[Any]:
        accs = [f.reducer_identity() for f in folds]
        reducers = [f.reducer for f in folds]
        for op in chunk:
            for i, r in enumerate(reducers):
                accs[i] = r(accs[i], op)
        return [f.post_reducer(a) for f, a in zip(folds, accs)]

    def fold_many(self, folds: Sequence[Fold]) -> List[Any]:
        """Run several folds in ONE pass over the chunks (fold fusion).
        Associative folds share a chunk-parallel pass; non-associative
        ones run serially (still fused with each other)."""
        folds = list(folds)
        par = [f for f in folds if f.associative]
        ser = [f for f in folds if not f.associative]
        results: Dict[int, Any] = {}

        if par:
            for f in par:
                if f.combiner is None:
                    raise TypeError(f"associative fold {f.name!r} needs "
                                    f"a combiner")
            if len(self._chunks) > 1:
                with _fut.ThreadPoolExecutor(self.max_workers) as ex:
                    chunk_results = list(ex.map(
                        lambda c: self._reduce_chunk(par, c), self._chunks))
            else:
                chunk_results = [self._reduce_chunk(par, self._chunks[0])]
            for fi, f in enumerate(par):
                acc = (f.combiner_identity or f.reducer_identity)()
                for cr in chunk_results:  # ordered combine
                    acc = f.combiner(acc, cr[fi])
                results[id(f)] = f.post_combiner(acc)
        for f in ser:
            acc = f.reducer_identity()
            for chunk in self._chunks:
                for op in chunk:
                    acc = f.reducer(acc, op)
            results[id(f)] = f.post_combiner(f.post_reducer(acc))
        return [results[id(f)] for f in folds]

    def fold(self, f: Fold) -> Any:
        return self.fold_many([f])[0]


# ---------------------------------------------------------------------------
# Common folds (reference history's built-in folds / tesser shims)


def count_fold(pred: Optional[Callable[[Op], bool]] = None) -> Fold:
    return fold_spec(
        name="count",
        reducer_identity=lambda: 0,
        reducer=(lambda acc, op: acc + 1) if pred is None
        else (lambda acc, op: acc + (1 if pred(op) else 0)),
        combiner_identity=lambda: 0,
        combiner=lambda a, b: a + b)


def group_count_fold(key: Callable[[Op], Any]) -> Fold:
    def red(acc, op):
        k = key(op)
        acc[k] = acc.get(k, 0) + 1
        return acc

    def comb(a, b):
        for k, v in b.items():
            a[k] = a.get(k, 0) + v
        return a

    return fold_spec(name="group-count", reducer_identity=dict,
                     reducer=red, combiner_identity=dict, combiner=comb)


def collect_fold(pred: Callable[[Op], bool],
                 xform: Callable[[Op], Any] = _identity) -> Fold:
    return fold_spec(
        name="collect",
        reducer_identity=list,
        reducer=lambda acc, op: (acc.append(xform(op)) or acc)
        if pred(op) else acc,
        combiner_identity=list,
        combiner=lambda a, b: a + b)
