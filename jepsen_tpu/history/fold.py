"""Chunk-parallel folds over histories, with fold fusion.

Equivalent of the reference's `jepsen/history/fold.clj` + `task.clj`
(SURVEY.md §2.2): a fold is a spec of

- ``reducer_identity`` / ``reducer`` / ``post_reducer`` — applied within a
  chunk,
- ``combiner_identity`` / ``combiner`` / ``post_combiner`` — applied across
  chunk results **in order**,
- ``associative`` — when False the fold runs serially (exact reference
  semantics: only associative folds go chunk-parallel).

:class:`Folder` binds to a chunked op source (a History, a store
``LazyHistory``, or an explicit chunk list) and **fuses** folds into one
pass — each chunk is traversed once no matter how many folds run:

- `fold_many(folds)` fuses an explicit batch;
- `submit(fold)` fuses folds submitted *concurrently* (from any thread):
  submissions that arrive while a pass is in flight are batched into the
  next pass — the reference's concurrent-submission fusion, built on the
  dependency-DAG :class:`~jepsen_tpu.history.task.TaskExecutor`.

Chunks are held as lazy thunks: a LazyHistory source decodes chunks
inside the workers (bounded by its own LRU), never materializing a 10M-op
history on the host at once.

Columnar fast path: a fold may carry a ``columnar`` reducer operating on
a dict of numpy column arrays; sources that provide column chunks
(`columns_of`, or any PackedTxns-like object) then run folds at numpy
speed instead of per-op Python — the host-side mirror of the device
segment reductions in `ops/segments.py`.
"""

from __future__ import annotations

import concurrent.futures as _fut
import dataclasses
import os
import threading
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from .ops import History, Op
from .task import TaskExecutor

CHUNK_SIZE = 16384


def _identity(x: Any) -> Any:
    return x


@dataclasses.dataclass
class Fold:
    """A fold spec (reference fold maps).

    `columnar`, when given, maps a dict of numpy column arrays (keys
    "type", "process", "f", "time", "error?") to a chunk partial that
    feeds the combiner — used instead of the per-op reducer whenever the
    source provides column chunks.
    """

    reducer_identity: Callable[[], Any]
    reducer: Callable[[Any, Op], Any]
    post_reducer: Callable[[Any], Any] = _identity
    combiner_identity: Optional[Callable[[], Any]] = None
    combiner: Optional[Callable[[Any, Any], Any]] = None
    post_combiner: Callable[[Any], Any] = _identity
    associative: bool = True
    name: str = "fold"
    columnar: Optional[Callable[[Dict[str, np.ndarray]], Any]] = None


def fold_spec(*, reducer_identity, reducer, post_reducer=_identity,
              combiner_identity=None, combiner=None,
              post_combiner=_identity, associative=True,
              name="fold", columnar=None) -> Fold:
    return Fold(reducer_identity, reducer, post_reducer, combiner_identity,
                combiner, post_combiner, associative, name, columnar)


_GETTER = __import__("operator").attrgetter(
    "type", "process", "f", "time", "error")


def columns_of(ops: Sequence[Op]) -> Dict[str, np.ndarray]:
    """Build column arrays from an op chunk.  The per-op work is one
    C-level attrgetter call; everything downstream is numpy."""
    n = len(ops)
    if n == 0:
        return {"type": np.empty(0, "U6"), "process": np.empty(0, object),
                "f": np.empty(0, object), "time": np.empty(0, np.int64),
                "error?": np.zeros(0, bool), "client?": np.zeros(0, bool)}
    arr = np.array(list(map(_GETTER, ops)), dtype=object)
    process = arr[:, 1]
    client = np.fromiter(
        (isinstance(p, int) and p >= 0 for p in process),
        dtype=bool, count=n)
    return {
        "type": arr[:, 0].astype("U6"),
        "process": process,
        "f": arr[:, 2],
        "time": arr[:, 3].astype(np.int64),
        "error?": arr[:, 4] != None,  # noqa: E711 — elementwise object cmp
        "client?": client,
    }


def _memo_thunk(thunk: Callable[[], Any]) -> Callable[[], Any]:
    cell: list = []

    def get():
        if not cell:
            cell.append(thunk())
        return cell[0]

    return get


class Folder:
    """Bound to one chunked source; runs (fused) folds over it."""

    def __init__(self, chunks_or_history, *,
                 max_workers: Optional[int] = None,
                 executor: Optional[TaskExecutor] = None,
                 columnar: bool = False):
        self._thunks = self._chunkify(chunks_or_history, columnar)
        self.max_workers = max_workers or min(8, (os.cpu_count() or 2))
        self._executor = executor
        self._own_executor = executor is None
        self._lock = threading.Lock()
        self._pending: List[tuple] = []       # (Fold, Future)
        self._pass_scheduled = False

    # -- chunk sources ------------------------------------------------------

    @staticmethod
    def _chunkify(src, columnar: bool) -> List[Callable[[], Any]]:
        """Return lazy chunk thunks.  Never materializes a chunk-lazy
        source eagerly; workers decode chunks on demand."""
        # store.format.LazyHistory (or anything chunk-addressable)
        if hasattr(src, "_load_chunk") and hasattr(src, "_chunks"):
            n = len(src._chunks)
            thunks = [
                (lambda ci=ci: src._load_chunk(ci)) for ci in range(n)]
            thunks = thunks or [lambda: []]
            if columnar:
                return [(lambda t=t: columns_of(t())) for t in thunks]
            return thunks
        if hasattr(src, "iter_chunks"):  # generic chunked protocol
            chunks = list(src.iter_chunks())
            return Folder._wrap_lists(chunks, columnar)
        if isinstance(src, History):
            ops = src.ops
        else:
            ops = list(src)
            if ops and not isinstance(ops[0], Op):
                # a list of chunks — validate the shape: each chunk must
                # be a sequence of Ops (a history passed as raw dicts
                # would otherwise silently fold garbage)
                for c in ops:
                    if not isinstance(c, (list, tuple)) or \
                            (len(c) and not isinstance(c[0], Op)):
                        raise TypeError(
                            "Folder expects a History, a chunk-lazy "
                            "source, a list of Ops, or a list of Op "
                            f"chunks; got element {type(c).__name__}")
                return Folder._wrap_lists(ops, columnar)
        chunks = [ops[i:i + CHUNK_SIZE]
                  for i in range(0, len(ops), CHUNK_SIZE)] or [[]]
        return Folder._wrap_lists(chunks, columnar)

    @staticmethod
    def _wrap_lists(chunks, columnar):
        if columnar:
            # in-memory chunks are immutable: build columns once, reuse
            # across passes (LazyHistory chunks stay uncached above —
            # bounded memory beats repeat-pass speed there)
            return [_memo_thunk(lambda c=c: columns_of(c)) for c in chunks]
        return [(lambda c=c: c) for c in chunks]

    # -- execution ---------------------------------------------------------

    def _reduce_chunk(self, folds: Sequence[Fold], thunk) -> List[Any]:
        chunk = thunk()
        if isinstance(chunk, dict):  # column chunk
            out = []
            for f in folds:
                if f.columnar is None:
                    raise TypeError(
                        f"fold {f.name!r} has no columnar reducer but the "
                        "source provides column chunks")
                out.append(f.columnar(chunk))
            return out
        accs = [f.reducer_identity() for f in folds]
        reducers = [f.reducer for f in folds]
        for op in chunk:
            for i, r in enumerate(reducers):
                accs[i] = r(accs[i], op)
        return [f.post_reducer(a) for f, a in zip(folds, accs)]

    def fold_many(self, folds: Sequence[Fold]) -> List[Any]:
        """Run several folds in ONE pass over the chunks (fold fusion).
        Associative folds share a chunk-parallel pass; non-associative
        ones run serially (still fused with each other)."""
        folds = list(folds)
        par = [f for f in folds if f.associative]
        ser = [f for f in folds if not f.associative]
        results: Dict[int, Any] = {}

        if par:
            for f in par:
                if f.combiner is None:
                    raise TypeError(f"associative fold {f.name!r} needs "
                                    f"a combiner")
            if len(self._thunks) > 1:
                with _fut.ThreadPoolExecutor(self.max_workers) as ex:
                    chunk_results = list(ex.map(
                        lambda t: self._reduce_chunk(par, t), self._thunks))
            else:
                chunk_results = [self._reduce_chunk(par, self._thunks[0])]
            for fi, f in enumerate(par):
                acc = (f.combiner_identity or f.reducer_identity)()
                for cr in chunk_results:  # ordered combine
                    acc = f.combiner(acc, cr[fi])
                results[id(f)] = f.post_combiner(acc)
        for f in ser:
            acc = f.reducer_identity()
            for thunk in self._thunks:
                chunk = thunk()
                if isinstance(chunk, dict):
                    raise TypeError(f"non-associative fold {f.name!r} "
                                    "cannot run on column chunks")
                for op in chunk:
                    acc = f.reducer(acc, op)
            results[id(f)] = f.post_combiner(f.post_reducer(acc))
        return [results[id(f)] for f in folds]

    def fold(self, f: Fold) -> Any:
        return self.fold_many([f])[0]

    # -- concurrent submission fusion --------------------------------------

    def submit(self, f: Fold) -> "_fut.Future":
        """Submit a fold from any thread; returns a Future.  All folds
        pending when a pass starts are fused into that single pass; folds
        submitted while a pass is in flight batch into the next pass."""
        fut: _fut.Future = _fut.Future()
        with self._lock:
            self._pending.append((f, fut))
            if not self._pass_scheduled:
                self._pass_scheduled = True
                ex = self._ensure_executor()
                ex.submit(self._drain, name="fold-pass")
        return fut

    def _ensure_executor(self) -> TaskExecutor:
        if self._executor is None:
            self._executor = TaskExecutor(self.max_workers)
            self._own_executor = True
        return self._executor

    def _drain(self) -> None:
        while True:
            with self._lock:
                batch = self._pending
                self._pending = []
                if not batch:
                    self._pass_scheduled = False
                    return
            folds = [f for (f, _) in batch]
            try:
                outs = self.fold_many(folds)
                for (_, fut), out in zip(batch, outs):
                    fut.set_result(out)
            except BaseException as e:  # noqa: BLE001 — deliver to waiters
                for (_, fut) in batch:
                    if not fut.done():
                        fut.set_exception(e)

    def close(self) -> None:
        if self._own_executor and self._executor is not None:
            self._executor.shutdown()
            self._executor = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


# ---------------------------------------------------------------------------
# Common folds (reference history's built-in folds / tesser shims)


def count_fold(pred: Optional[Callable[[Op], bool]] = None) -> Fold:
    return fold_spec(
        name="count",
        reducer_identity=lambda: 0,
        reducer=(lambda acc, op: acc + 1) if pred is None
        else (lambda acc, op: acc + (1 if pred(op) else 0)),
        combiner_identity=lambda: 0,
        combiner=lambda a, b: a + b,
        columnar=None if pred is not None
        else (lambda cols: int(len(cols["type"]))))


def type_count_fold() -> Fold:
    """Counts by op type — columnar-capable (stats checker hot path)."""
    def red(acc, op):
        acc[op.type] = acc.get(op.type, 0) + 1
        return acc

    def comb(a, b):
        for k, v in b.items():
            a[k] = a.get(k, 0) + v
        return a

    def col(cols):
        vals, counts = np.unique(cols["type"], return_counts=True)
        return {str(v): int(c) for v, c in zip(vals, counts)}

    return fold_spec(name="type-count", reducer_identity=dict,
                     reducer=red, combiner_identity=dict, combiner=comb,
                     columnar=col)


def group_count_fold(key: Callable[[Op], Any] = None,
                     column: Optional[str] = None) -> Fold:
    """Counts grouped by key(op) — or by a column name, making the fold
    columnar-capable."""
    if key is None:
        if column is None:
            raise TypeError("need key or column")
        key = lambda op: getattr(op, column)  # noqa: E731

    def red(acc, op):
        k = key(op)
        acc[k] = acc.get(k, 0) + 1
        return acc

    def comb(a, b):
        for k, v in b.items():
            a[k] = a.get(k, 0) + v
        return a

    col = None
    if column is not None:
        def col(cols):  # noqa: F811
            vals, counts = np.unique(cols[column], return_counts=True)
            return {v: int(c) for v, c in zip(vals, counts)}

    return fold_spec(name="group-count", reducer_identity=dict,
                     reducer=red, combiner_identity=dict, combiner=comb,
                     columnar=col)


def collect_fold(pred: Callable[[Op], bool],
                 xform: Callable[[Op], Any] = _identity) -> Fold:
    return fold_spec(
        name="collect",
        reducer_identity=list,
        reducer=lambda acc, op: (acc.append(xform(op)) or acc)
        if pred(op) else acc,
        combiner_identity=list,
        combiner=lambda a, b: a + b)
