"""Dependency-DAG task scheduler on a fixed thread pool.

Equivalent of the reference's `jepsen/history/task.clj` (SURVEY.md §2.2):
tasks declare dependencies on other tasks; a task becomes runnable when
every dependency has finished, and receives their results as positional
arguments.  Cancellation cascades to dependents; a failed dependency
fails its dependents with the same exception.  This powers the Folder's
concurrent fold fusion (fold.py) the way task.clj powers fold.clj.

Host-side by design: scheduling is control flow, not compute — the
numeric work inside tasks is numpy/JAX which releases the GIL.
"""

from __future__ import annotations

import os
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Optional, Sequence

PENDING = "pending"      # waiting on deps
READY = "ready"          # queued on the pool
RUNNING = "running"
DONE = "done"
FAILED = "failed"
CANCELLED = "cancelled"


class Task:
    """Future-like handle with dependency metadata."""

    def __init__(self, fn: Callable, deps: Sequence["Task"], name: str):
        self.fn = fn
        self.deps = list(deps)
        self.name = name
        self.state = PENDING
        self.result_value: Any = None
        self.error: Optional[BaseException] = None
        self._dependents: list[Task] = []
        self._unmet = 0
        self._done = threading.Event()

    def result(self, timeout: Optional[float] = None) -> Any:
        if not self._done.wait(timeout):
            raise TimeoutError(f"task {self.name!r} not done")
        if self.state == CANCELLED:
            raise CancelledError(self.name)
        if self.state == FAILED:
            raise self.error
        return self.result_value

    def done(self) -> bool:
        return self._done.is_set()

    def __repr__(self) -> str:
        return f"<Task {self.name!r} {self.state}>"


class CancelledError(Exception):
    pass


class TaskExecutor:
    """Fixed pool + DAG bookkeeping.  Use as a context manager or call
    shutdown()."""

    def __init__(self, max_workers: Optional[int] = None):
        self.pool = ThreadPoolExecutor(
            max_workers or min(8, (os.cpu_count() or 2)))
        self.lock = threading.Lock()

    # -- public API --------------------------------------------------------

    def submit(self, fn: Callable, *, deps: Sequence[Task] = (),
               name: str = "task") -> Task:
        """Schedule fn(*dep_results) after every dep finishes."""
        t = Task(fn, deps, name)
        with self.lock:
            unmet = 0
            for d in deps:
                if d.state in (DONE,):
                    continue
                if d.state in (FAILED, CANCELLED):
                    # fail fast: dependency already failed
                    self._finish(t, FAILED if d.state == FAILED else
                                 CANCELLED, error=d.error or
                                 CancelledError(d.name))
                    return t
                d._dependents.append(t)
                unmet += 1
            t._unmet = unmet
            if unmet == 0:
                self._enqueue(t)
        return t

    def cancel(self, t: Task) -> bool:
        """Cancel a task that hasn't started; cascades to dependents.
        Returns True if the task was cancelled."""
        with self.lock:
            if t.state in (PENDING, READY):
                self._finish(t, CANCELLED, error=CancelledError(t.name))
                return True
            return False

    def shutdown(self, wait: bool = True) -> None:
        self.pool.shutdown(wait=wait)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown()

    # -- internals (lock held) ---------------------------------------------

    def _enqueue(self, t: Task) -> None:
        t.state = READY
        self.pool.submit(self._run, t)

    def _run(self, t: Task) -> None:
        with self.lock:
            if t.state != READY:
                return
            t.state = RUNNING
        try:
            args = [d.result_value for d in t.deps]
            out = t.fn(*args)
        except BaseException as e:  # noqa: BLE001 — must fail dependents
            with self.lock:
                self._finish(t, FAILED, error=e)
            return
        with self.lock:
            self._finish(t, DONE, value=out)

    def _finish(self, t: Task, state: str, *, value: Any = None,
                error: Optional[BaseException] = None) -> None:
        if t.state in (DONE, FAILED, CANCELLED):
            return
        t.state = state
        t.result_value = value
        t.error = error
        t._done.set()
        deps_ok = state == DONE
        for child in t._dependents:
            if deps_ok:
                child._unmet -= 1
                if child._unmet == 0 and child.state == PENDING:
                    self._enqueue(child)
            else:
                # cascade failure/cancellation
                self._finish(child,
                             FAILED if state == FAILED else CANCELLED,
                             error=error or CancelledError(t.name))
        t._dependents.clear()
