"""History substrate: the L2 layer (SURVEY.md §2.2).

Mirrors the capability surface of the `io.jepsen/history` library
(`jepsen/history.clj`): Op records, dense/sparse histories, O(1) pair
index / invocation / completion lookup, lazy filters — plus the TPU-native
part: flattening histories into structure-of-array device tensors
(`jepsen_tpu.history.soa`) and folds as device segment reductions
(`jepsen_tpu.history.fold`).
"""

from jepsen_tpu.history.ops import (
    Op,
    History,
    history,
    invoke,
    ok,
    fail,
    info,
    INVOKE,
    OK,
    FAIL,
    INFO,
)
from jepsen_tpu.history.soa import PackedTxns, pack_txns

__all__ = [
    "Op",
    "History",
    "history",
    "invoke",
    "ok",
    "fail",
    "info",
    "INVOKE",
    "OK",
    "FAIL",
    "INFO",
    "PackedTxns",
    "pack_txns",
]
