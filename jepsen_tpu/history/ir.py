"""One canonical packed-history IR for every checker family (ISSUE 12).

Before this module each checker family packed its own arrays — elle
list-append/rw packing (`history/soa.py`), the invariants matrices
(`checkers/invariants/packed.py`), knossos's entry table
(`checkers/knossos/prep.py`) — and a composed check over one history
re-derived each of them from the op list.  :class:`HistoryIR` is the
single carrier: built once per history, it memoizes

- the SoA transactional packing per workload kind (``PackedTxns``:
  txn/mop/read-element columns),
- the padded device layout (``PaddedLA``) including the static
  capacity/layout facts and the pad-time derived-order columns
  (run permutation, per-key longest-read table, process/realtime
  orders) that `device_infer.infer` consumes instead of re-sorting
  in-program — see docs/IR.md for the exact column set,
- the rw dependency inference (``RwInference``: writer maps, version
  edges, per-key chain ranks, ww/wr/rw + process/realtime orders)
  shared by the predicate and session invariants checkers,
- the bank balance matrix (``PackedBank``), and
- the knossos linearizability entry table (``LinOp`` rows).

``HistoryIR`` subclasses :class:`~jepsen_tpu.history.ops.History` and
*shares* the source history's op list and pair index, so every
non-IR-aware consumer (stats folds, timeline, perf, the host oracles)
keeps working unchanged — the IR is a History that also remembers its
packings.  ``checkers.api.Compose`` wraps each checked history once, so
a composed run derives each section exactly once.

Versioning: ``IR_VERSION`` stamps the layout contract (bump when a
column's meaning changes); the padded layout's static facts
(`PaddedLA.v_cap/o_cap/...`) are part of v2.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

import numpy as np

from jepsen_tpu.history.ops import History
from jepsen_tpu.history.soa import PackedTxns, pack_txns

__all__ = ["IR_VERSION", "HistoryIR"]


def _booked(build):
    """Run one cache-miss section build, booking its wall as
    ``host_pack_s`` phase self-time on the enclosing telemetry span
    (ISSUE 16 phase taxonomy) — memoized hits pay nothing."""
    from jepsen_tpu.telemetry import spans as _spans

    t0 = time.perf_counter()
    out = build()
    _spans.add_phase("host_pack_s", time.perf_counter() - t0)
    return out

#: layout contract version: v1 = the implicit per-family packings,
#: v2 = this module (capacity facts + pad-time derived-order columns)
IR_VERSION = 2


class HistoryIR(History):
    """A History that memoizes every checker family's packed view."""

    def __init__(self, source):
        self._packed: Dict[str, PackedTxns] = {}
        self._padded: Dict[str, Any] = {}
        self._rw_inf = None
        self._bank: Dict[Any, Any] = {}
        self._queue: Dict[str, Any] = {}
        self._lin_ops: Optional[List[Any]] = None
        self._packed_source: Optional[PackedTxns] = None
        if isinstance(source, PackedTxns):
            # packed-only IR: no op-level view (checkers that need ops
            # degrade exactly as they do for a bare PackedTxns today)
            self.ops = []
            self._pair = np.zeros(0, np.int64)
            self._packed_source = source
        elif isinstance(source, History):
            # share, don't rebuild: the op list and pair index are the
            # source's own objects
            self.ops = source.ops
            self._pair = source._pair
        else:
            ops = list(source)
            super().__init__(
                ops, reindex=any(op.index < 0 for op in ops))

    @property
    def packed_only(self) -> bool:
        """True when built from a bare PackedTxns — no op-level view;
        checkers needing ops must degrade exactly as for PackedTxns."""
        return self._packed_source is not None

    @classmethod
    def of(cls, history) -> "HistoryIR":
        """Idempotent constructor: an IR passes through unchanged."""
        if isinstance(history, HistoryIR):
            return history
        return cls(history)

    # -- memoized sections --------------------------------------------------

    def packed(self, workload: str = "list-append") -> PackedTxns:
        """The SoA transactional packing for `workload`
        ("list-append" / "rw-register")."""
        if self._packed_source is not None:
            return self._packed_source
        p = self._packed.get(workload)
        if p is None:
            p = self._packed[workload] = _booked(
                lambda: pack_txns(self, workload))
        return p

    def padded(self, workload: str = "list-append"):
        """The padded device layout (PaddedLA) with IR capacity facts
        and derived-order columns — pad cost paid once per history."""
        h = self._padded.get(workload)
        if h is None:
            from jepsen_tpu.checkers.elle.device_infer import pad_packed

            packed = self.packed(workload)
            h = self._padded[workload] = _booked(
                lambda: pad_packed(packed))
        return h

    def rw_inference(self):
        """The shared rw dependency inference (RwInference) the
        predicate and session invariants checkers both consume."""
        if self._rw_inf is None:
            from jepsen_tpu.checkers.invariants import packed as inv_packed

            packed = self.packed("rw-register")
            self._rw_inf = _booked(
                lambda: inv_packed.infer_rw(packed))
        return self._rw_inf

    def bank(self, accounts=None):
        """The bank balance-matrix packing (PackedBank)."""
        key = tuple(sorted(map(repr, accounts))) if accounts else None
        pb = self._bank.get(key)
        if pb is None:
            from jepsen_tpu.checkers.invariants.packed import pack_bank

            pb = self._bank[key] = _booked(
                lambda: pack_bank(self, accounts))
        return pb

    def queue(self, kind: str = "kafka"):
        """The queue-family packing: ``"kafka"`` -> PackedKafka
        (send/poll/epoch columns + derived orders), ``"fifo"`` ->
        PackedFifo (enqueue/dequeue counting columns + the
        per-consumer dequeue order)."""
        pq = self._queue.get(kind)
        if pq is None:
            from jepsen_tpu.checkers.queue import packed as q_packed

            build = (q_packed.pack_kafka if kind == "kafka"
                     else q_packed.pack_fifo)
            pq = self._queue[kind] = _booked(lambda: build(self))
        return pq

    def lin_ops(self) -> List[Any]:
        """The knossos linearizability entry table (LinOp rows)."""
        if self._lin_ops is None:
            from jepsen_tpu.checkers.knossos.prep import prepare

            self._lin_ops = _booked(lambda: prepare(self))
        return self._lin_ops

    def bucket_class(self, workload: str = "list-append",
                     site: str = "elle.infer") -> str:
        """The compile-cache shape-class label of this history's padded
        device view (``compilecache.bucket.class_label``): which AOT
        executable a check over it shares.  The padded layout already
        pads to pow2 capacities, so nearby history sizes report the
        SAME class — the property the bucket ladder pre-warms against."""
        from jepsen_tpu.compilecache import bucket

        h = self.padded(workload)
        return bucket.class_label(site, (h,), {"n_keys": h.n_keys})

    def layout(self) -> Dict[str, Any]:
        """The versioned layout summary of the padded list-append view
        (docs/IR.md): capacities + which facts/columns are active."""
        h = self.padded("list-append")
        return {
            "version": IR_VERSION,
            "T": int(h.txn_type.shape[0]),
            "M": int(h.mop_txn.shape[0]),
            "R": int(h.rd_elems.shape[0]),
            "v_cap": h.v_cap, "o_cap": h.o_cap,
            "txn_major": h.txn_major, "run_cap": h.run_cap,
            "complete_monotone": h.complete_monotone,
            "app_val_mono": h.app_val_mono,
            "rd_start_mono": h.rd_start_mono,
            "proc_seq": h.proc_seq,
            "derived_columns": h.run_sort is not None,
        }
