"""Structure-of-array packing of transactional histories.

This is the TPU-native half of the history substrate (SURVEY.md §7 stage 1):
a completed history is flattened into dense numpy/device arrays — the
direct analogue of the reference's dense `jepsen.history` vectors, laid out
so that Elle-style edge inference runs as vectorized segment ops on device.

Layout (all int32 unless noted):

  txn_*   — one row per completed client transaction (ok / fail / info):
            type (i8: 1 ok, 2 fail, 3 info), process, invoke_pos /
            complete_pos (event indices in the original history — these are
            the realtime & process orders), orig_index (completion op index).
  mop_*   — one row per micro-op, flattened across all txns in txn order:
            txn (owner), kind (i8: 0 append/write, 1 read), key (dense id),
            val (append/write value id; read value id for rw-register),
            rd_start / rd_len (list-append read lists into rd_elems;
            rd_len == -1 means the read's result is unknown — info/fail).
  rd_elems — concatenated list-append read lists (value ids).

Keys and values are remapped to dense ids; `key_names` / `val_names` map
back for reporting.  Value ids are globally unique *per (key, value) pair*
so that `(key, val_id)` identity is just `val_id` — list-append values are
unique per key by generator contract, and the checker verifies duplicates
anyway.
"""

from __future__ import annotations

import dataclasses
from typing import Any, List, Optional, Sequence

import numpy as np

from jepsen_tpu.history.ops import FAIL, INFO, INVOKE, OK, History, Op

MOP_APPEND = 0  # also rw-register write
MOP_READ = 1

TXN_OK = 1
TXN_FAIL = 2
TXN_INFO = 3


@dataclasses.dataclass
class PackedTxns:
    """A transactional history flattened to structure-of-arrays."""

    # per-txn
    txn_type: np.ndarray  # i8 [T]
    txn_process: np.ndarray  # i32 [T]
    txn_invoke_pos: np.ndarray  # i32 [T]
    txn_complete_pos: np.ndarray  # i32 [T]
    txn_orig_index: np.ndarray  # i32 [T]
    # per-mop
    mop_txn: np.ndarray  # i32 [M]
    mop_kind: np.ndarray  # i8 [M]
    mop_key: np.ndarray  # i32 [M]
    mop_val: np.ndarray  # i32 [M]
    mop_rd_start: np.ndarray  # i32 [M]
    mop_rd_len: np.ndarray  # i32 [M]
    rd_elems: np.ndarray  # i32 [R]
    # id maps
    key_names: List[Any]
    val_names: List[Any]  # val id -> (key id, value)
    n_events: int  # number of events in the original history

    @property
    def n_txns(self) -> int:
        return len(self.txn_type)

    @property
    def n_mops(self) -> int:
        return len(self.mop_txn)

    @property
    def n_keys(self) -> int:
        return len(self.key_names)

    @property
    def n_vals(self) -> int:
        return len(self.val_names)


_PACKED_COLS = (
    "txn_type", "txn_process", "txn_invoke_pos", "txn_complete_pos",
    "txn_orig_index", "mop_txn", "mop_kind", "mop_key", "mop_val",
    "mop_rd_start", "mop_rd_len", "rd_elems",
)


class _DenseValNames:
    """Lazy `val_names` for densely-id'd histories: val id v maps to
    (key_of_v, v).  Reconstructs key_of_v from the mop columns on first
    access; `len()` never materializes anything.  Lets a 10M-txn
    prestaged history load without building 30M Python tuples."""

    def __init__(self, n_vals: int, mop_key: np.ndarray, mop_val: np.ndarray):
        self._n = n_vals
        self._mop_key = mop_key
        self._mop_val = mop_val
        self._val_keys: Optional[np.ndarray] = None

    def __len__(self) -> int:
        return self._n

    def _keys(self) -> np.ndarray:
        if self._val_keys is None:
            vk = np.full(self._n, -1, dtype=np.int32)
            w = self._mop_val >= 0
            vk[self._mop_val[w]] = self._mop_key[w]
            self._val_keys = vk
        return self._val_keys

    def __getitem__(self, v):
        if isinstance(v, slice):
            return [self[i] for i in range(*v.indices(self._n))]
        if v < 0:
            v += self._n  # match list semantics (the eager form)
        if not 0 <= v < self._n:
            raise IndexError(v)
        return (int(self._keys()[v]), int(v))


def save_packed(path: str, p: "PackedTxns") -> None:
    """Persist a PackedTxns with *canonical dense names* to an .npz.

    Only histories whose key_names are `range(n_keys)` and whose
    val_names are the dense `(key, val_id)` map (what the synthetic
    `packed_la_history` / `packed_rw_history` generators emit) can be
    round-tripped — that covers the bench/campaign prestaging use case
    (VERDICT r04 item 1: pay zero gen time inside a tunnel window).
    General histories with rich names go through the store codecs
    (`store/format.py`) instead.
    """
    if list(p.key_names) != list(range(p.n_keys)):
        raise ValueError("save_packed requires dense range() key names")
    # sampled check of the val_names half of the precondition: the dense
    # map has val_names[v] == (key_of_v, v) — anything else would load
    # back with silently wrong value names
    if p.n_vals:
        probe = _DenseValNames(p.n_vals, p.mop_key, p.mop_val)
        for v in {0, p.n_vals // 2, p.n_vals - 1}:
            if tuple(p.val_names[v]) != probe[v]:
                raise ValueError(
                    f"save_packed requires dense (key, val_id) val names; "
                    f"val_names[{v}] == {p.val_names[v]!r} != {probe[v]!r}")
    np.savez(path, n_events=np.int64(p.n_events),
             n_keys=np.int64(p.n_keys), n_vals=np.int64(p.n_vals),
             **{c: getattr(p, c) for c in _PACKED_COLS})


def load_packed(path: str) -> "PackedTxns":
    """Load an .npz written by `save_packed`.  val_names come back as a
    lazy dense map (len + getitem only)."""
    with np.load(path) as z:
        cols = {c: z[c] for c in _PACKED_COLS}
        n_events = int(z["n_events"])
        n_keys = int(z["n_keys"])
        n_vals = int(z["n_vals"])
    return PackedTxns(
        key_names=list(range(n_keys)),
        val_names=_DenseValNames(n_vals, cols["mop_key"], cols["mop_val"]),
        n_events=n_events, **cols)


def _mops_of(op: Op) -> Sequence:
    v = op.value
    if v is None:
        return []
    if not isinstance(v, (list, tuple)):
        raise ValueError(f"txn op value must be a list of mops, got {v!r}")
    return v


_CHUNK_COLS = (
    ("txn_type", np.int8), ("txn_process", np.int32),
    ("txn_invoke_pos", np.int32), ("txn_complete_pos", np.int32),
    ("txn_orig_index", np.int32), ("mop_txn", np.int32),
    ("mop_kind", np.int8), ("mop_key", np.int32), ("mop_val", np.int32),
    ("mop_rd_start", np.int32), ("mop_rd_len", np.int32),
    ("rd_elems", np.int32),
)


class TxnPacker:
    """Chunk-feedable packer: flattens completed client txns to SoA
    column chunks without ever holding the whole op list.

    The streaming equivalent of the reference's big-vector blocks +
    soft-reference chunks (`store/format.clj`, `history/core.clj`,
    SURVEY.md §2.2 "Chunked storage"): `feed(ops)` consumes one history
    chunk in order and returns that chunk's column arrays with *global*
    txn ids and read-element offsets, so chunks can be shipped to the
    device as they are packed (see `checkers.elle.stream`).  Host state
    between chunks is O(concurrency + distinct keys/values): the
    pending-invocation table plus the interner maps.
    """

    def __init__(self, workload: str = "list-append"):
        self.la = workload == "list-append"
        self.key_ids: dict = {}
        self.key_names: List[Any] = []
        self.val_ids: dict = {}  # (key_id, value) -> val id
        self.val_names: List[Any] = []
        self.pending: dict = {}  # process -> invoke Op
        self.pos = 0             # global event position
        self.n_txns = 0
        self.n_mops = 0
        self.max_mops_txn = 0  # longest single txn seen (layout fact
        #                        consumed by streamed device staging)
        self.n_rd_elems = 0

    def _key_id(self, k) -> int:
        i = self.key_ids.get(k)
        if i is None:
            i = len(self.key_names)
            self.key_ids[k] = i
            self.key_names.append(k)
        return i

    def _val_id(self, ki: int, v) -> int:
        i = self.val_ids.get((ki, v))
        if i is None:
            i = len(self.val_names)
            self.val_ids[(ki, v)] = i
            self.val_names.append((ki, v))
        return i

    def feed(self, ops: Sequence[Op]) -> dict:
        """Pack one chunk of ops (must be fed in history order).  Returns
        {column: np.ndarray} for the txns COMPLETED in this chunk."""
        cols: dict = {name: [] for name, _ in _CHUNK_COLS}
        for op in ops:
            pos = self.pos
            self.pos += 1
            if not op.is_client_op():
                continue
            if op.type == INVOKE:
                self.pending[op.process] = op
                continue
            inv = self.pending.pop(op.process, None)
            if op.type == OK:
                ttype, mops, known_reads = TXN_OK, _mops_of(op), True
            else:
                src = inv if inv is not None else op
                ttype = TXN_FAIL if op.type == FAIL else TXN_INFO
                mops, known_reads = _mops_of(src), False
            t = self.n_txns
            self.n_txns += 1
            self.max_mops_txn = max(self.max_mops_txn, len(mops))
            cols["txn_type"].append(ttype)
            cols["txn_process"].append(int(op.process))
            cols["txn_invoke_pos"].append(inv.index if inv is not None
                                          else pos)
            cols["txn_complete_pos"].append(pos)
            cols["txn_orig_index"].append(op.index)
            for m in mops:
                fkind = m[0]
                k = self._key_id(m[1])
                self.n_mops += 1
                cols["mop_txn"].append(t)
                cols["mop_key"].append(k)
                if fkind in ("append", "w"):
                    cols["mop_kind"].append(MOP_APPEND)
                    cols["mop_val"].append(self._val_id(k, m[2]))
                    cols["mop_rd_start"].append(-1)
                    cols["mop_rd_len"].append(-1)
                elif fkind == "r":
                    cols["mop_kind"].append(MOP_READ)
                    rv = m[2] if len(m) > 2 else None
                    if self.la:
                        cols["mop_val"].append(-1)
                        if known_reads and rv is not None:
                            cols["mop_rd_start"].append(self.n_rd_elems)
                            cols["mop_rd_len"].append(len(rv))
                            cols["rd_elems"].extend(
                                self._val_id(k, v) for v in rv)
                            self.n_rd_elems += len(rv)
                        else:
                            cols["mop_rd_start"].append(-1)
                            cols["mop_rd_len"].append(-1)
                    else:  # rw-register: scalar read (None -> unborn/-1)
                        if known_reads:
                            cols["mop_val"].append(
                                -1 if rv is None else self._val_id(k, rv))
                            cols["mop_rd_len"].append(0)
                        else:
                            cols["mop_val"].append(-1)
                            cols["mop_rd_len"].append(-1)
                        cols["mop_rd_start"].append(-1)
                else:
                    raise ValueError(f"unknown mop kind {fkind!r}")
        return {name: np.asarray(cols[name], dtype=dt)
                for name, dt in _CHUNK_COLS}

    def to_packed(self, chunks: Sequence[dict]) -> PackedTxns:
        """Concatenate fed chunks into one PackedTxns."""
        def cat(name, dt):
            parts = [c[name] for c in chunks]
            return (np.concatenate(parts) if parts
                    else np.zeros(0, dt))

        return PackedTxns(
            **{name: cat(name, dt) for name, dt in _CHUNK_COLS},
            key_names=self.key_names,
            val_names=self.val_names,
            n_events=self.pos,
        )


def pack_txns(h: History | Sequence[Op], workload: str = "list-append") -> PackedTxns:
    """Flatten a history's completed client transactions to SoA arrays.

    Follows the reference's semantics for op visibility (elle/list_append.clj):
    - `ok` txns contribute their completion value (reads filled in);
    - `info` txns contribute the *invocation*'s mops — their writes may have
      committed, their reads are unknown;
    - `fail` txns' writes are known-uncommitted (used for G1a); reads unknown.
    """
    if not isinstance(h, History):
        ops = list(h)
        # raw op sequences may lack indices; (re)index unless already indexed
        h = History(ops, reindex=any(op.index < 0 for op in ops))
    pk = TxnPacker(workload)
    chunk = pk.feed(h.ops)
    return pk.to_packed([chunk])
