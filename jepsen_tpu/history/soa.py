"""Structure-of-array packing of transactional histories.

This is the TPU-native half of the history substrate (SURVEY.md §7 stage 1):
a completed history is flattened into dense numpy/device arrays — the
direct analogue of the reference's dense `jepsen.history` vectors, laid out
so that Elle-style edge inference runs as vectorized segment ops on device.

Layout (all int32 unless noted):

  txn_*   — one row per completed client transaction (ok / fail / info):
            type (i8: 1 ok, 2 fail, 3 info), process, invoke_pos /
            complete_pos (event indices in the original history — these are
            the realtime & process orders), orig_index (completion op index).
  mop_*   — one row per micro-op, flattened across all txns in txn order:
            txn (owner), kind (i8: 0 append/write, 1 read), key (dense id),
            val (append/write value id; read value id for rw-register),
            rd_start / rd_len (list-append read lists into rd_elems;
            rd_len == -1 means the read's result is unknown — info/fail).
  rd_elems — concatenated list-append read lists (value ids).

Keys and values are remapped to dense ids; `key_names` / `val_names` map
back for reporting.  Value ids are globally unique *per (key, value) pair*
so that `(key, val_id)` identity is just `val_id` — list-append values are
unique per key by generator contract, and the checker verifies duplicates
anyway.
"""

from __future__ import annotations

import dataclasses
from typing import Any, List, Optional, Sequence

import numpy as np

from jepsen_tpu.history.ops import FAIL, INFO, INVOKE, OK, History, Op

MOP_APPEND = 0  # also rw-register write
MOP_READ = 1

TXN_OK = 1
TXN_FAIL = 2
TXN_INFO = 3


@dataclasses.dataclass
class PackedTxns:
    """A transactional history flattened to structure-of-arrays."""

    # per-txn
    txn_type: np.ndarray  # i8 [T]
    txn_process: np.ndarray  # i32 [T]
    txn_invoke_pos: np.ndarray  # i32 [T]
    txn_complete_pos: np.ndarray  # i32 [T]
    txn_orig_index: np.ndarray  # i32 [T]
    # per-mop
    mop_txn: np.ndarray  # i32 [M]
    mop_kind: np.ndarray  # i8 [M]
    mop_key: np.ndarray  # i32 [M]
    mop_val: np.ndarray  # i32 [M]
    mop_rd_start: np.ndarray  # i32 [M]
    mop_rd_len: np.ndarray  # i32 [M]
    rd_elems: np.ndarray  # i32 [R]
    # id maps
    key_names: List[Any]
    val_names: List[Any]  # val id -> (key id, value)
    n_events: int  # number of events in the original history

    @property
    def n_txns(self) -> int:
        return len(self.txn_type)

    @property
    def n_mops(self) -> int:
        return len(self.mop_txn)

    @property
    def n_keys(self) -> int:
        return len(self.key_names)

    @property
    def n_vals(self) -> int:
        return len(self.val_names)


def _mops_of(op: Op) -> Sequence:
    v = op.value
    if v is None:
        return []
    if not isinstance(v, (list, tuple)):
        raise ValueError(f"txn op value must be a list of mops, got {v!r}")
    return v


def pack_txns(h: History | Sequence[Op], workload: str = "list-append") -> PackedTxns:
    """Flatten a history's completed client transactions to SoA arrays.

    Follows the reference's semantics for op visibility (elle/list_append.clj):
    - `ok` txns contribute their completion value (reads filled in);
    - `info` txns contribute the *invocation*'s mops — their writes may have
      committed, their reads are unknown;
    - `fail` txns' writes are known-uncommitted (used for G1a); reads unknown.
    """
    if not isinstance(h, History):
        ops = list(h)
        # raw op sequences may lack indices; (re)index unless already indexed
        h = History(ops, reindex=any(op.index < 0 for op in ops))

    key_ids: dict = {}
    key_names: List[Any] = []
    val_ids: dict = {}  # (key_id, value) -> val id
    val_names: List[Any] = []

    def key_id(k) -> int:
        i = key_ids.get(k)
        if i is None:
            i = len(key_names)
            key_ids[k] = i
            key_names.append(k)
        return i

    def val_id(ki: int, v) -> int:
        i = val_ids.get((ki, v))
        if i is None:
            i = len(val_names)
            val_ids[(ki, v)] = i
            val_names.append((ki, v))
        return i

    txn_type: List[int] = []
    txn_process: List[int] = []
    txn_invoke_pos: List[int] = []
    txn_complete_pos: List[int] = []
    txn_orig_index: List[int] = []
    mop_txn: List[int] = []
    mop_kind: List[int] = []
    mop_key: List[int] = []
    mop_val: List[int] = []
    mop_rd_start: List[int] = []
    mop_rd_len: List[int] = []
    rd_elems: List[int] = []

    la = workload == "list-append"

    for pos, op in enumerate(h.ops):
        if op.type == INVOKE or not op.is_client_op():
            continue
        if op.type == OK:
            ttype, mops, known_reads = TXN_OK, _mops_of(op), True
        else:
            inv = h.invocation(op)
            src = inv if inv is not None else op
            ttype = TXN_FAIL if op.type == FAIL else TXN_INFO
            mops, known_reads = _mops_of(src), False
        t = len(txn_type)
        txn_type.append(ttype)
        txn_process.append(int(op.process))
        inv = h.invocation(op)
        txn_invoke_pos.append(inv.index if inv is not None else pos)
        txn_complete_pos.append(pos)
        txn_orig_index.append(op.index)
        for m in mops:
            fkind = m[0]
            k = key_id(m[1])
            mop_txn.append(t)
            mop_key.append(k)
            if fkind in ("append", "w"):
                mop_kind.append(MOP_APPEND)
                mop_val.append(val_id(k, m[2]))
                mop_rd_start.append(-1)
                mop_rd_len.append(-1)
            elif fkind == "r":
                mop_kind.append(MOP_READ)
                rv = m[2] if len(m) > 2 else None
                if la:
                    mop_val.append(-1)
                    if known_reads and rv is not None:
                        mop_rd_start.append(len(rd_elems))
                        mop_rd_len.append(len(rv))
                        rd_elems.extend(val_id(k, v) for v in rv)
                    else:
                        mop_rd_start.append(-1)
                        mop_rd_len.append(-1)
                else:  # rw-register: scalar read value (None -> unborn/-1)
                    if known_reads:
                        mop_val.append(-1 if rv is None else val_id(k, rv))
                        mop_rd_len.append(0)
                    else:
                        mop_val.append(-1)
                        mop_rd_len.append(-1)
                    mop_rd_start.append(-1)
            else:
                raise ValueError(f"unknown mop kind {fkind!r}")

    def a(x, dt=np.int32):
        return np.asarray(x, dtype=dt)

    return PackedTxns(
        txn_type=a(txn_type, np.int8),
        txn_process=a(txn_process),
        txn_invoke_pos=a(txn_invoke_pos),
        txn_complete_pos=a(txn_complete_pos),
        txn_orig_index=a(txn_orig_index),
        mop_txn=a(mop_txn),
        mop_kind=a(mop_kind, np.int8),
        mop_key=a(mop_key),
        mop_val=a(mop_val),
        mop_rd_start=a(mop_rd_start),
        mop_rd_len=a(mop_rd_len),
        rd_elems=a(rd_elems),
        key_names=key_names,
        val_names=val_names,
        n_events=len(h.ops),
    )
