"""Op records and histories.

Host-side mirror of `jepsen/history.clj` (reference layout, SURVEY.md §2.2):
the `Op` record `{:index :time :type :process :f :value}`, the `history`
constructor that normalizes and indexes a sequence of ops, dense histories
(index == array position), the O(1) pair index, and `invocation`/`completion`
lookups.  Filters (`client_ops`, `oks`, `invokes`) preserve original indices,
like the reference's lazy index-preserving views.

This layer is pure Python/numpy; the device-resident representation lives in
`jepsen_tpu.history.soa`.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Iterable, Iterator, Optional, Sequence

import numpy as np

# Op types.  Encoded as small ints for device packing.
INVOKE = "invoke"
OK = "ok"
FAIL = "fail"
INFO = "info"

TYPE_CODE = {INVOKE: 0, OK: 1, FAIL: 2, INFO: 3}
CODE_TYPE = {v: k for k, v in TYPE_CODE.items()}

# Non-client processes get negative int codes (reference: keyword processes
# like :nemesis; we follow jepsen's convention that client processes are
# non-negative ints).
NEMESIS_PROCESS = -1


@dataclasses.dataclass
class Op:
    """A single operation event.

    Mirrors the reference Op defrecord: {:index :time :type :process :f
    :value} plus arbitrary extra keys (kept in `ext`).  `value` for Elle
    transactional workloads is a list of micro-ops (mops), e.g.
    ``[("append", k, v), ("r", k, [v1, v2])]``.
    """

    index: int = -1
    time: int = -1  # monotonic nanoseconds (relative test clock)
    type: str = INVOKE
    process: Any = None
    f: Any = None
    value: Any = None
    error: Any = None
    ext: Optional[dict] = None

    def is_invoke(self) -> bool:
        return self.type == INVOKE

    def is_ok(self) -> bool:
        return self.type == OK

    def is_fail(self) -> bool:
        return self.type == FAIL

    def is_info(self) -> bool:
        return self.type == INFO

    def is_client_op(self) -> bool:
        return isinstance(self.process, int) and self.process >= 0

    def with_(self, **kw) -> "Op":
        return dataclasses.replace(self, **kw)

    def to_dict(self) -> dict:
        d = {
            "index": self.index,
            "time": self.time,
            "type": self.type,
            "process": self.process,
            "f": self.f,
            "value": self.value,
        }
        if self.error is not None:
            d["error"] = self.error
        if self.ext:
            d.update(self.ext)
        return d

    @staticmethod
    def from_dict(d: dict) -> "Op":
        ext = {
            k: v
            for k, v in d.items()
            if k not in ("index", "time", "type", "process", "f", "value", "error")
        }
        return Op(
            index=d.get("index", -1),
            time=d.get("time", -1),
            type=d["type"],
            process=d.get("process"),
            f=d.get("f"),
            value=d.get("value"),
            error=d.get("error"),
            ext=ext or None,
        )


def invoke(process, f, value, **kw) -> Op:
    return Op(type=INVOKE, process=process, f=f, value=value, **kw)


def ok(process, f, value, **kw) -> Op:
    return Op(type=OK, process=process, f=f, value=value, **kw)


def fail(process, f, value, **kw) -> Op:
    return Op(type=FAIL, process=process, f=f, value=value, **kw)


def info(process, f, value, **kw) -> Op:
    return Op(type=INFO, process=process, f=f, value=value, **kw)


class History:
    """A dense, indexed history of ops.

    Construction normalizes ops: assigns `index` = position, assigns
    monotonically non-decreasing synthetic `time` where missing, and builds
    the invoke<->completion pair index (reference: `jepsen.history/pair-index`).

    An invocation is paired with the next op by the same process; `info` ops
    from a crashed process remain unpaired (pair == -1) and are treated as
    forever-concurrent by checkers, exactly as in the reference.
    """

    def __init__(self, ops: Sequence[Op], *, reindex: bool = True):
        ops = list(ops)
        if reindex:
            for i, op in enumerate(ops):
                op.index = i
        last_t = -1
        for op in ops:
            if op.time is None or op.time < 0:
                op.time = last_t + 1
            last_t = max(last_t, op.time)
        self.ops = ops
        self._pair = self._build_pair_index(ops)

    @staticmethod
    def _build_pair_index(ops: Sequence[Op]) -> np.ndarray:
        pair = np.full(len(ops), -1, dtype=np.int64)
        open_by_process: dict = {}
        for i, op in enumerate(ops):
            p = op.process
            if op.type == INVOKE:
                if p in open_by_process:
                    raise ValueError(
                        f"process {p!r} invoked op {i} while op "
                        f"{open_by_process[p]} was still open"
                    )
                open_by_process[p] = i
            else:
                j = open_by_process.pop(p, None)
                if j is not None:
                    pair[i] = j
                    pair[j] = i
                # A completion with no invocation (e.g. half a history) is
                # left unpaired, like the reference's sparse handling.
        return pair

    # -- core lookups (all O(1), mirroring jepsen.history) -----------------

    def __len__(self) -> int:
        return len(self.ops)

    def __iter__(self) -> Iterator[Op]:
        return iter(self.ops)

    def __getitem__(self, idx: int) -> Op:
        return self.ops[idx]

    def get_index(self, index: int) -> Op:
        return self.ops[index]

    def pair_index(self, index: int) -> int:
        return int(self._pair[index])

    def completion(self, op: Op) -> Optional[Op]:
        j = int(self._pair[op.index])
        return self.ops[j] if j >= 0 and self.ops[j].type != INVOKE else None

    def invocation(self, op: Op) -> Optional[Op]:
        j = int(self._pair[op.index])
        return self.ops[j] if j >= 0 and self.ops[j].type == INVOKE else None

    # -- filters (index-preserving views) ----------------------------------

    def filter(self, pred: Callable[[Op], bool]) -> list:
        return [op for op in self.ops if pred(op)]

    def client_ops(self) -> list:
        return self.filter(Op.is_client_op)

    def oks(self) -> list:
        return self.filter(Op.is_ok)

    def invokes(self) -> list:
        return self.filter(Op.is_invoke)

    def infos(self) -> list:
        return self.filter(Op.is_info)

    def fails(self) -> list:
        return self.filter(Op.is_fail)

    def to_dicts(self) -> list:
        return [op.to_dict() for op in self.ops]

    @staticmethod
    def from_dicts(ds: Iterable[dict]) -> "History":
        return History([Op.from_dict(d) for d in ds], reindex=False)


def history(ops: Iterable[Op | dict], *, reindex: bool = True) -> History:
    """Normalize a sequence of Ops (or op dicts) into a dense History."""
    out = []
    for op in ops:
        out.append(Op.from_dict(op) if isinstance(op, dict) else op)
    return History(out, reindex=reindex)
