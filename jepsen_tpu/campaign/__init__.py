"""Campaign layer (ISSUE 3): run, index, and regression-check whole
*fleets* of tests.

`core.run` (L6) orchestrates exactly one test; the ROADMAP north star is
a production-scale system, which means running many seeds × workloads ×
fault plans concurrently and keeping the verdicts queryable across
campaigns.  Four pieces:

- :mod:`~.plan` — declarative campaign spec (JSON/dict matrix) expanded
  into :class:`~.plan.RunSpec` rows with *stable* per-run ids (same
  spec → same ids, the resume/regression key);
- :mod:`~.scheduler` — a device-aware worker pool: host-only runs fill
  all workers freely, device-pipeline runs serialize through a bounded
  set of device slots; per-run isolation via thread or subprocess
  executors, with `resilience.RetryPolicy` retries on crashed runs and
  `Deadline` budgets threaded into each test map;
- :mod:`~.index` — the persistent results database: an append-only,
  fsync'd, torn-line-tolerant jsonl ledger keyed by run id, supporting
  resume (completed runs skipped on restart) and regression queries
  (verdict flips per (workload, fault, seed) key, checker span
  duration trends across campaign generations);
- :mod:`~.core` — the orchestrator: `run_campaign(spec)` → summary,
  plus `status`/`report` and the single-run executor the scheduler and
  the subprocess runner share.

Surfaces: `cli campaign run/status/report <spec.json>`, the web UI's
campaign dashboard (verdict grid, degraded/deadline runs highlighted),
`report.render_campaign` (suite rollup), and `bench.py`'s ladder
emitted as a campaign spec (``BENCH_EMIT_CAMPAIGN_SPEC=path``).

See ``docs/CAMPAIGN.md``.
"""

from jepsen_tpu.campaign.core import (
    execute_run,
    report_campaign,
    run_campaign,
    status_campaign,
)
from jepsen_tpu.campaign.index import Index
from jepsen_tpu.campaign.plan import RunSpec, expand, load_spec
from jepsen_tpu.campaign.scheduler import DeviceSlots, Scheduler

__all__ = [
    "RunSpec", "expand", "load_spec",
    "Scheduler", "DeviceSlots",
    "Index",
    "run_campaign", "status_campaign", "report_campaign", "execute_run",
]
