"""The campaign results database: an append-only jsonl ledger.

One file per campaign (``<store>/campaigns/<name>.jsonl``), one JSON
record per completed run, fsync'd on append — the same durability and
torn-line story as `parallel.batch.check_batch_checkpointed`'s
checkpoints and the original `scripts/tpu_campaign.py` stage ledger: a
crash mid-append leaves at most one torn trailing line, which a reload
drops (and truncates) before resuming.

Records are keyed two ways:

- ``run`` — the RunSpec's stable run id.  A run id with a verdict on
  file is *complete*; `run_campaign` skips it on restart (resume).
- ``key`` — ``workload|fault|seed``, stable across spec-opt tweaks and
  campaign generations; the regression-query key.

Each record carries the verdict (``valid?``), attribution (``error``,
``degraded``, ``deadline``), the run's store dir, wall time, and — for
telemetric runs — per-span checker durations pulled from the run's
``telemetry.json``, which powers the "checker p95 span duration trend"
query (:meth:`Index.span_trend`).
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, Iterable, List, Optional, Tuple

__all__ = ["Index", "witness_pair_diffs", "verdict_counts_over"]


def _percentile(xs: List[float], q: float) -> float:
    """Nearest-rank percentile over a non-empty list (stdlib-only)."""
    s = sorted(xs)
    i = max(0, min(len(s) - 1, int(round(q / 100.0 * (len(s) - 1)))))
    return s[i]


def verdict_counts_over(latest: Iterable[Dict[str, Any]]
                        ) -> Dict[str, int]:
    """The verdict histogram over latest-per-run records — ONE
    counting rule shared by the jsonl scan and the warehouse fast path
    (and its /metrics rollups), so the classification can't drift
    between backends."""
    counts = {"true": 0, "false": 0, "unknown": 0,
              "degraded": 0, "deadline": 0}
    for r in latest:
        v = r.get("valid?")
        counts["true" if v is True else
               "false" if v is False else "unknown"] += 1
        if r.get("degraded"):
            counts["degraded"] += 1
        if r.get("deadline"):
            counts["deadline"] += 1
    return counts


def witness_pair_diffs(by_key: Dict[str, List[Dict[str, Any]]]
                       ) -> List[Dict[str, Any]]:
    """The witness-drift diff over consecutive witness-bearing records
    per key.  Input: key → records (each holding ``gen`` + a
    ``witness`` dict), in append order.  ONE implementation shared by
    the jsonl scan and the warehouse fast path, so the two backends
    can't drift."""
    out: List[Dict[str, Any]] = []
    for key, recs in sorted(by_key.items()):
        for prev, cur in zip(recs[:-1], recs[1:]):
            pw, cw = prev["witness"], cur["witness"]
            pa = set(pw.get("anomaly-types") or ())
            ca = set(cw.get("anomaly-types") or ())
            p_ops, c_ops = pw.get("ops") or 0, cw.get("ops") or 0
            out.append({
                "key": key,
                "from-gen": prev.get("gen"), "to-gen": cur.get("gen"),
                "from-ops": p_ops, "to-ops": c_ops,
                "ops-delta": c_ops - p_ops,
                "from-digest": pw.get("digest"),
                "to-digest": cw.get("digest"),
                "digest-changed": pw.get("digest") != cw.get("digest"),
                "anomalies-added": sorted(ca - pa),
                "anomalies-removed": sorted(pa - ca),
                "changed": (pw.get("digest") != cw.get("digest")
                            or pa != ca or p_ops != c_ops),
            })
    return out


class Index:
    """In-memory view over one campaign's jsonl ledger.

    Loading tolerates a torn trailing record (crash mid-append): the
    first unparsable or unterminated line and everything after it are
    dropped from the in-memory view, like the batch checkpoint reader.
    The FILE is only healed (truncated back to the last durable record)
    lazily on the next :meth:`append` — read-only consumers (the web
    dashboard, `campaign status`) must never truncate, because their
    "torn line" may just be a live writer's append in flight.

    Loading is LAZY, because the regression/trend queries have a
    warehouse fast path (docs/TELEMETRY.md): when ``<store>/
    warehouse.sqlite`` exists and fully covers this ledger (ingest
    cursor == file size), ``flips``/``regressions``/``span_stats``/
    ``span_trend``/``witness_diffs``/``verdict_counts``/
    ``latest_by_run`` answer from indexed SQL without parsing the
    jsonl at all.  A stale or absent warehouse falls back to the scan
    — the ledger stays the source of truth either way.
    """

    def __init__(self, path: str, use_warehouse: bool = True):
        self.path = path
        self.use_warehouse = use_warehouse
        self._records: Optional[List[Dict[str, Any]]] = None
        self._load_lock = threading.Lock()
        self._wh: Optional[tuple] = None  # cached (warehouse, rel)
        self._wh_resolved = False
        self._wh_compacted = False
        #: byte offset of the last durable record seen at load; a
        #: resuming WRITER truncates to it before its first append
        self._good_bytes: Optional[int] = None

    @property
    def records(self) -> List[Dict[str, Any]]:
        if self._records is None:
            with self._load_lock:
                if self._records is None:
                    self._load()
        return self._records

    #: queries a COMPACTED ledger's warehouse still answers exactly:
    #: their rollup rows (flip_rollup / span_gen_rollup / the kept
    #: witness records) survive compaction untouched.  Everything else
    #: lost its raw rows and must fall back to the jsonl scan.
    _COMPACT_SAFE = frozenset({"flips", "span_trend", "witness_diffs"})

    def _warehouse(self, query: Optional[str] = None):
        """(warehouse, ledger-rel) when the SQL fast path may answer
        for this ledger, else None.  Resolved (freshness-checked) once
        per Index and cached — the same point-in-time semantics as the
        one-shot jsonl load — and invalidated by :meth:`append`, which
        makes the warehouse stale by definition.  ``query`` gates
        per-query on compaction (ISSUE 20): once a ledger's raw rows
        were folded past the generation horizon, only the
        ``_COMPACT_SAFE`` queries keep the SQL path."""
        if not self.use_warehouse:
            return None
        if not self._wh_resolved:
            try:
                from jepsen_tpu.telemetry import warehouse as wmod

                self._wh = wmod.for_ledger(self.path)
                self._wh_compacted = bool(
                    self._wh is not None and
                    self._wh[0].ledger_compacted(self._wh[1]))
            except Exception:  # noqa: BLE001 — fast path, never fail
                self._wh = None
                self._wh_compacted = False
            self._wh_resolved = True
        if self._wh_compacted and query not in self._COMPACT_SAFE:
            return None
        return self._wh

    # -- persistence --------------------------------------------------------

    def _load(self) -> None:
        recs: List[Dict[str, Any]] = []
        if not os.path.exists(self.path):
            self._records = recs
            return
        good_bytes = 0
        torn = False
        with open(self.path, "rb") as f:
            for line in f:
                if not line.strip():
                    good_bytes += len(line)
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    torn = True  # torn trailing record
                    break
                if not line.endswith(b"\n"):
                    torn = True  # parseable but unterminated: a later
                    break        # append would fuse with it
                recs.append(rec)
                good_bytes += len(line)
        # arm the heal only on an OBSERVED torn line — never because the
        # file merely grew between our read and now (that's a concurrent
        # writer's complete record, which truncation would destroy)
        if torn:
            self._good_bytes = good_bytes
        self._records = recs

    def append(self, rec: Dict[str, Any]) -> Dict[str, Any]:
        """Durably append one record (fsync'd) and index it.  If the
        load saw a torn tail, the writer truncates it away first so the
        new record can't fuse with crash debris."""
        rec = dict(rec)
        rec.setdefault("ts", time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                           time.gmtime()))
        recs = self.records  # force the load: the heal check below
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        if self._good_bytes is not None:
            with open(self.path, "r+b") as f:
                f.truncate(self._good_bytes)
            self._good_bytes = None
        with open(self.path, "a") as f:
            f.write(json.dumps(rec) + "\n")
            f.flush()
            os.fsync(f.fileno())
        recs.append(rec)
        # the append outdated any warehouse coverage of this ledger:
        # re-resolve (and re-check freshness) on the next query
        self._wh, self._wh_resolved = None, False
        return rec

    # -- resume -------------------------------------------------------------

    def completed_ids(self) -> set:
        """Run ids that already hold an attributable verdict — skipped
        on resume.  Any verdict counts (True / False / "unknown"): the
        contract is *attributable termination*, not success."""
        return {r["run"] for r in self.records if "valid?" in r}

    def latest(self, run_id: str) -> Optional[Dict[str, Any]]:
        for r in reversed(self.records):
            if r.get("run") == run_id:
                return r
        return None

    def by_key(self) -> Dict[str, List[Dict[str, Any]]]:
        """Records grouped by regression key, in append order."""
        out: Dict[str, List[Dict[str, Any]]] = {}
        for r in self.records:
            if "valid?" in r and r.get("key"):
                out.setdefault(r["key"], []).append(r)
        return out

    # -- regression queries -------------------------------------------------

    def flips(self) -> List[Dict[str, Any]]:
        """Verdict flips per key: every consecutive pair of records for
        the same (workload, fault, seed) whose ``valid?`` changed.
        ``regression`` marks the bad direction (away from True) — the
        "which (workload, seed) flipped valid? since the last campaign"
        query."""
        wh = self._warehouse("flips")
        if wh is not None:
            return wh[0].flips(wh[1])
        out: List[Dict[str, Any]] = []
        for key, recs in sorted(self.by_key().items()):
            for prev, cur in zip(recs[:-1], recs[1:]):
                if prev.get("valid?") != cur.get("valid?"):
                    out.append({
                        "key": key,
                        "run": cur.get("run"),
                        "from": prev.get("valid?"),
                        "to": cur.get("valid?"),
                        "regression": prev.get("valid?") is True,
                        "when": cur.get("ts"),
                        "gen": cur.get("gen"),
                    })
        return out

    def regressions(self) -> List[Dict[str, Any]]:
        return [f for f in self.flips() if f["regression"]]

    def witness_diffs(self) -> List[Dict[str, Any]]:
        """Per-key witness comparison across campaign generations
        (ROADMAP open item): for every consecutive pair of auto-shrunk
        records under the same ``workload|fault|seed`` key, the
        op-count / digest / anomaly-set deltas.  A digest change with
        an unchanged spec is the "the minimal repro MOVED" signal — a
        different failure than last generation, even when the verdict
        column still just says False."""
        wh = self._warehouse("witness_diffs")
        if wh is not None:
            return witness_pair_diffs(wh[0].witness_records(wh[1]))
        by_key: Dict[str, List[Dict[str, Any]]] = {}
        for r in self.records:
            w = r.get("witness")
            if isinstance(w, dict) and w.get("ops") and r.get("key"):
                by_key.setdefault(r["key"], []).append(r)
        return witness_pair_diffs(by_key)

    # -- telemetry aggregates ----------------------------------------------

    def _span_values(self) -> Dict[str, List[float]]:
        out: Dict[str, List[float]] = {}
        for r in self.records:
            for name, dur in (r.get("spans") or {}).items():
                if isinstance(dur, (int, float)):
                    out.setdefault(name, []).append(float(dur))
        return out

    def span_stats(self) -> Dict[str, Dict[str, float]]:
        """Per-span duration aggregates across every indexed run:
        count / min / p50 / p95 / max (seconds)."""
        wh = self._warehouse("span_stats")
        if wh is not None:
            return wh[0].span_stats(wh[1])
        return {
            name: {
                "count": len(vals),
                "min": round(min(vals), 6),
                "p50": round(_percentile(vals, 50), 6),
                "p95": round(_percentile(vals, 95), 6),
                "max": round(max(vals), 6),
            }
            for name, vals in sorted(self._span_values().items())
        }

    def span_samples(self, name: str
                     ) -> List[Tuple[Optional[str], float]]:
        """(gen, duration) samples for one span name, in append order —
        the material for :meth:`span_trend` and the ``cli obs gate``
        regression gate."""
        wh = self._warehouse("span_samples")
        if wh is not None:
            return wh[0].span_samples(wh[1], name)
        out: List[Tuple[Optional[str], float]] = []
        for r in self.records:
            dur = (r.get("spans") or {}).get(name)
            if isinstance(dur, (int, float)):
                out.append((r.get("gen"), float(dur)))
        return out

    def span_trend(self, name: str) -> List[Tuple[str, float]]:
        """p95 of one span per campaign generation, in first-seen gen
        order — the "checker p95 span duration trend" query.  The
        warehouse answers from its materialized per-generation rollup;
        the jsonl path recomputes from the raw samples."""
        wh = self._warehouse("span_trend")
        if wh is not None:
            return wh[0].span_trend(wh[1], name)
        by_gen: Dict[str, List[float]] = {}
        order: List[str] = []
        for gen, dur in self.span_samples(name):
            g = str(gen or "?")
            if g not in by_gen:
                order.append(g)
            by_gen.setdefault(g, []).append(dur)
        return [(g, round(_percentile(by_gen[g], 95), 6)) for g in order]

    def forensic_records(self) -> List[tuple]:
        """``(gen, spans, phases, counters)`` per record in append
        order — the backend-shared input of
        :mod:`jepsen_tpu.telemetry.forensics` (``obs diff`` / ``obs
        gate --explain``).  Warehouse and jsonl scan MUST return the
        identical shape so both paths reach the same verdict."""
        wh = self._warehouse("forensic_records")
        if wh is not None:
            return wh[0].forensic_records(wh[1])
        return [(r.get("gen"), r.get("spans") or {},
                 r.get("phases") or {}, r.get("counters") or {})
                for r in self.records]

    def profile(self) -> List[Dict[str, Any]]:
        """Per-(site, shape-class, host) device-call profile aggregated
        over the campaign's run dirs — ``cli obs profile``'s data.
        Warehouse-backed from the ``span_profile`` table when fresh;
        the fallback re-reads each run dir's telemetry.json through the
        same extraction (``forensics.profile_from_doc``)."""
        wh = self._warehouse("profile")
        if wh is not None:
            return wh[0].campaign_profile(wh[1])
        from jepsen_tpu.telemetry.forensics import profile_rows_from_dirs

        base = os.path.dirname(os.path.dirname(os.path.abspath(self.path)))
        dirs, seen = [], set()
        for r in self.records:
            d = r.get("dir")
            if d and d not in seen:
                seen.add(d)
                dirs.append(d)
        return profile_rows_from_dirs(base, dirs)

    # -- rollups ------------------------------------------------------------

    def latest_by_run(self) -> Dict[str, Dict[str, Any]]:
        """The LATEST verdict-bearing record per run id — what the web
        campaign grid renders.  Warehouse-backed when fresh; NOTE the
        warehouse path reconstructs the grid PROJECTION (run/key/
        workload/fault/seed/valid?/error/degraded/deadline/dir/ops/
        wall_s/gen/ts/witness) — per-span durations stay in
        :meth:`span_stats`/:meth:`span_samples`, not here."""
        wh = self._warehouse("latest_by_run")
        if wh is not None:
            return wh[0].latest_by_run(wh[1])
        latest: Dict[str, Dict[str, Any]] = {}
        for r in self.records:
            if "valid?" in r and r.get("run"):
                latest[r["run"]] = r
        return latest

    def verdict_counts(self, runs: Optional[Iterable[str]] = None
                       ) -> Dict[str, int]:
        """Verdict histogram over the LATEST record per run id.  Built
        on :meth:`latest_by_run` so both backends share ONE
        record-selection rule (verdict-bearing, truthy run id)."""
        latest = dict(self.latest_by_run())
        if runs is not None:
            wanted = set(runs)
            latest = {k: v for k, v in latest.items() if k in wanted}
        return verdict_counts_over(latest.values())
