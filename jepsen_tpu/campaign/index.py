"""The campaign results database: an append-only jsonl ledger.

One file per campaign (``<store>/campaigns/<name>.jsonl``), one JSON
record per completed run, fsync'd on append — the same durability and
torn-line story as `parallel.batch.check_batch_checkpointed`'s
checkpoints and the original `scripts/tpu_campaign.py` stage ledger: a
crash mid-append leaves at most one torn trailing line, which a reload
drops (and truncates) before resuming.

Records are keyed two ways:

- ``run`` — the RunSpec's stable run id.  A run id with a verdict on
  file is *complete*; `run_campaign` skips it on restart (resume).
- ``key`` — ``workload|fault|seed``, stable across spec-opt tweaks and
  campaign generations; the regression-query key.

Each record carries the verdict (``valid?``), attribution (``error``,
``degraded``, ``deadline``), the run's store dir, wall time, and — for
telemetric runs — per-span checker durations pulled from the run's
``telemetry.json``, which powers the "checker p95 span duration trend"
query (:meth:`Index.span_trend`).
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, Iterable, List, Optional, Tuple

__all__ = ["Index"]


def _percentile(xs: List[float], q: float) -> float:
    """Nearest-rank percentile over a non-empty list (stdlib-only)."""
    s = sorted(xs)
    i = max(0, min(len(s) - 1, int(round(q / 100.0 * (len(s) - 1)))))
    return s[i]


class Index:
    """In-memory view over one campaign's jsonl ledger.

    Loading tolerates a torn trailing record (crash mid-append): the
    first unparsable or unterminated line and everything after it are
    dropped from the in-memory view, like the batch checkpoint reader.
    The FILE is only healed (truncated back to the last durable record)
    lazily on the next :meth:`append` — read-only consumers (the web
    dashboard, `campaign status`) must never truncate, because their
    "torn line" may just be a live writer's append in flight.
    """

    def __init__(self, path: str):
        self.path = path
        self.records: List[Dict[str, Any]] = []
        #: byte offset of the last durable record seen at load; a
        #: resuming WRITER truncates to it before its first append
        self._good_bytes: Optional[int] = None
        self._load()

    # -- persistence --------------------------------------------------------

    def _load(self) -> None:
        if not os.path.exists(self.path):
            return
        good_bytes = 0
        torn = False
        recs: List[Dict[str, Any]] = []
        with open(self.path, "rb") as f:
            for line in f:
                if not line.strip():
                    good_bytes += len(line)
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    torn = True  # torn trailing record
                    break
                if not line.endswith(b"\n"):
                    torn = True  # parseable but unterminated: a later
                    break        # append would fuse with it
                recs.append(rec)
                good_bytes += len(line)
        # arm the heal only on an OBSERVED torn line — never because the
        # file merely grew between our read and now (that's a concurrent
        # writer's complete record, which truncation would destroy)
        if torn:
            self._good_bytes = good_bytes
        self.records = recs

    def append(self, rec: Dict[str, Any]) -> Dict[str, Any]:
        """Durably append one record (fsync'd) and index it.  If the
        load saw a torn tail, the writer truncates it away first so the
        new record can't fuse with crash debris."""
        rec = dict(rec)
        rec.setdefault("ts", time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                           time.gmtime()))
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        if self._good_bytes is not None:
            with open(self.path, "r+b") as f:
                f.truncate(self._good_bytes)
            self._good_bytes = None
        with open(self.path, "a") as f:
            f.write(json.dumps(rec) + "\n")
            f.flush()
            os.fsync(f.fileno())
        self.records.append(rec)
        return rec

    # -- resume -------------------------------------------------------------

    def completed_ids(self) -> set:
        """Run ids that already hold an attributable verdict — skipped
        on resume.  Any verdict counts (True / False / "unknown"): the
        contract is *attributable termination*, not success."""
        return {r["run"] for r in self.records if "valid?" in r}

    def latest(self, run_id: str) -> Optional[Dict[str, Any]]:
        for r in reversed(self.records):
            if r.get("run") == run_id:
                return r
        return None

    def by_key(self) -> Dict[str, List[Dict[str, Any]]]:
        """Records grouped by regression key, in append order."""
        out: Dict[str, List[Dict[str, Any]]] = {}
        for r in self.records:
            if "valid?" in r and r.get("key"):
                out.setdefault(r["key"], []).append(r)
        return out

    # -- regression queries -------------------------------------------------

    def flips(self) -> List[Dict[str, Any]]:
        """Verdict flips per key: every consecutive pair of records for
        the same (workload, fault, seed) whose ``valid?`` changed.
        ``regression`` marks the bad direction (away from True) — the
        "which (workload, seed) flipped valid? since the last campaign"
        query."""
        out: List[Dict[str, Any]] = []
        for key, recs in sorted(self.by_key().items()):
            for prev, cur in zip(recs[:-1], recs[1:]):
                if prev.get("valid?") != cur.get("valid?"):
                    out.append({
                        "key": key,
                        "run": cur.get("run"),
                        "from": prev.get("valid?"),
                        "to": cur.get("valid?"),
                        "regression": prev.get("valid?") is True,
                        "when": cur.get("ts"),
                        "gen": cur.get("gen"),
                    })
        return out

    def regressions(self) -> List[Dict[str, Any]]:
        return [f for f in self.flips() if f["regression"]]

    def witness_diffs(self) -> List[Dict[str, Any]]:
        """Per-key witness comparison across campaign generations
        (ROADMAP open item): for every consecutive pair of auto-shrunk
        records under the same ``workload|fault|seed`` key, the
        op-count / digest / anomaly-set deltas.  A digest change with
        an unchanged spec is the "the minimal repro MOVED" signal — a
        different failure than last generation, even when the verdict
        column still just says False."""
        out: List[Dict[str, Any]] = []
        by_key: Dict[str, List[Dict[str, Any]]] = {}
        for r in self.records:
            w = r.get("witness")
            if isinstance(w, dict) and w.get("ops") and r.get("key"):
                by_key.setdefault(r["key"], []).append(r)
        for key, recs in sorted(by_key.items()):
            for prev, cur in zip(recs[:-1], recs[1:]):
                pw, cw = prev["witness"], cur["witness"]
                pa = set(pw.get("anomaly-types") or ())
                ca = set(cw.get("anomaly-types") or ())
                p_ops, c_ops = pw.get("ops") or 0, cw.get("ops") or 0
                out.append({
                    "key": key,
                    "from-gen": prev.get("gen"), "to-gen": cur.get("gen"),
                    "from-ops": p_ops, "to-ops": c_ops,
                    "ops-delta": c_ops - p_ops,
                    "from-digest": pw.get("digest"),
                    "to-digest": cw.get("digest"),
                    "digest-changed": pw.get("digest") != cw.get("digest"),
                    "anomalies-added": sorted(ca - pa),
                    "anomalies-removed": sorted(pa - ca),
                    "changed": (pw.get("digest") != cw.get("digest")
                                or pa != ca or p_ops != c_ops),
                })
        return out

    # -- telemetry aggregates ----------------------------------------------

    def _span_values(self) -> Dict[str, List[float]]:
        out: Dict[str, List[float]] = {}
        for r in self.records:
            for name, dur in (r.get("spans") or {}).items():
                if isinstance(dur, (int, float)):
                    out.setdefault(name, []).append(float(dur))
        return out

    def span_stats(self) -> Dict[str, Dict[str, float]]:
        """Per-span duration aggregates across every indexed run:
        count / min / p50 / p95 / max (seconds)."""
        return {
            name: {
                "count": len(vals),
                "min": round(min(vals), 6),
                "p50": round(_percentile(vals, 50), 6),
                "p95": round(_percentile(vals, 95), 6),
                "max": round(max(vals), 6),
            }
            for name, vals in sorted(self._span_values().items())
        }

    def span_trend(self, name: str) -> List[Tuple[str, float]]:
        """p95 of one span per campaign generation, in first-seen gen
        order — the "checker p95 span duration trend" query."""
        by_gen: Dict[str, List[float]] = {}
        order: List[str] = []
        for r in self.records:
            dur = (r.get("spans") or {}).get(name)
            if not isinstance(dur, (int, float)):
                continue
            gen = str(r.get("gen") or "?")
            if gen not in by_gen:
                order.append(gen)
            by_gen.setdefault(gen, []).append(float(dur))
        return [(g, round(_percentile(by_gen[g], 95), 6)) for g in order]

    # -- rollups ------------------------------------------------------------

    def verdict_counts(self, runs: Optional[Iterable[str]] = None
                       ) -> Dict[str, int]:
        """Verdict histogram over the LATEST record per run id."""
        latest: Dict[str, Dict[str, Any]] = {}
        for r in self.records:
            if "valid?" in r:
                latest[r["run"]] = r
        if runs is not None:
            wanted = set(runs)
            latest = {k: v for k, v in latest.items() if k in wanted}
        counts = {"true": 0, "false": 0, "unknown": 0,
                  "degraded": 0, "deadline": 0}
        for r in latest.values():
            v = r.get("valid?")
            counts["true" if v is True else
                   "false" if v is False else "unknown"] += 1
            if r.get("degraded"):
                counts["degraded"] += 1
            if r.get("deadline"):
                counts["deadline"] += 1
        return counts
