"""Subprocess executor entry: one isolated campaign run.

``python -m jepsen_tpu.campaign.runner`` reads ``{"runspec": {...},
"base": "store"}`` JSON on stdin, executes the run, and prints the
index record as its LAST stdout line (the scheduler parses bottom-up,
so workload logging above it is harmless).  A crashing run exits
non-zero with NO record line — the scheduler treats that as a failed
attempt, retries per its policy, and only then indexes the crash
record; a clean exit always carries a record.

Honors ``JT_FORCE_CPU`` before the first jax init (same contract as
the CLI: on a box whose TPU tunnel is down, backend init hangs rather
than raising).
"""

from __future__ import annotations

import json
import os
import sys


def main() -> int:
    payload = json.loads(sys.stdin.read() or "{}")
    if os.environ.get("JT_FORCE_CPU", "").strip().lower() in (
            "1", "true", "yes", "on"):
        from jepsen_tpu.utils.backend import force_cpu_backend

        force_cpu_backend()
    import logging

    logging.basicConfig(
        level=logging.WARNING, stream=sys.stderr,
        format="%(asctime)s %(levelname)s %(name)s: %(message)s")
    from jepsen_tpu.campaign.core import execute_run
    from jepsen_tpu.campaign.plan import RunSpec

    rs = RunSpec.from_dict(payload["runspec"])
    rec = execute_run(rs, payload.get("base") or "store")
    slot = os.environ.get("JEPSEN_CAMPAIGN_DEVICE_SLOT")
    if slot is not None:
        rec["device-slot"] = int(slot)
    print(json.dumps(rec))
    sys.stdout.flush()
    return 0


if __name__ == "__main__":
    sys.exit(main())
