"""Campaign spec → expanded run list with stable per-run ids.

A campaign spec is a small declarative JSON document describing a
matrix of runs::

    {
      "name": "nightly",
      "workloads": ["append", {"name": "wr", "opts": {"concurrency": 8}}],
      "faults": [null, {"seed": 7, "p": 0.2, "kinds": "oom|xla"}],
      "seeds": [0, 1, 2],
      "opts": {"time-limit": 2.0, "telemetry": true,
               "checker-time-limit": 30}
    }

`expand` turns it into the cartesian product workload × fault × seed —
one :class:`RunSpec` per cell, in deterministic (workload-major) order.
Every RunSpec carries a *stable* ``run_id`` derived from a digest of
its canonicalized cell (campaign name, workload entry, fault entry,
seed, merged opts): re-expanding the same spec yields the same ids,
which is what makes the index resumable and regression queries
well-keyed across campaign generations.

Workloads resolve by name against the demo registry (`__main__._wl`,
the in-process sim cluster) plus ``"noop"`` (`core.noop_test` — runs no
ops, always valid; the campaign smoke workload).  A db suite extends
the table via :func:`register_workload`.
"""

from __future__ import annotations

import hashlib
import json
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Union

from jepsen_tpu.resilience import faults as faults_mod

__all__ = ["RunSpec", "expand", "load_spec", "spec_digest",
           "build_test", "register_workload", "DEVICE_WORKLOADS",
           "schedule_windows", "windows_digest"]

#: workload names whose checkers dispatch to the device pipelines (elle
#: list-append/rw-register, knossos device WGL, the invariants family)
#: — the scheduler serializes these through device slots; host-only
#: checkers run freely
DEVICE_WORKLOADS = frozenset({
    "append", "wr", "causal", "long-fork", "lin-register", "queue",
    "bank", "write-skew", "session", "kafka",
})

#: extension point: name -> builder(opts_dict) -> test map (db suites
#: add their own); names here shadow the demo registry
_EXTRA_WORKLOADS: Dict[str, Callable[[Dict[str, Any]], dict]] = {}


def register_workload(name: str, builder: Callable[[Dict[str, Any]], dict],
                      device: bool = False) -> None:
    """Register a campaign-runnable workload: `builder(opts) -> test
    map`.  `device=True` marks it for device-slot serialization."""
    _EXTRA_WORKLOADS[name] = builder
    if device:
        global DEVICE_WORKLOADS
        DEVICE_WORKLOADS = DEVICE_WORKLOADS | {name}


@dataclass
class RunSpec:
    """One cell of the campaign matrix — everything needed to build and
    run the test, declaratively (so a subprocess executor can rebuild
    it from JSON)."""

    run_id: str
    campaign: str
    workload: str
    seed: int
    fault: Optional[Union[dict, str]] = None
    fault_label: str = "nofault"
    workload_label: str = ""
    opts: Dict[str, Any] = field(default_factory=dict)
    device: bool = False

    @property
    def key(self) -> str:
        """The regression key: stable across campaign generations."""
        return f"{self.workload_label}|{self.fault_label}|s{self.seed}"

    def to_dict(self) -> Dict[str, Any]:
        return {
            "run_id": self.run_id, "campaign": self.campaign,
            "workload": self.workload, "seed": self.seed,
            "fault": self.fault, "fault_label": self.fault_label,
            "workload_label": self.workload_label, "opts": self.opts,
            "device": self.device,
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "RunSpec":
        return cls(**d)


def _digest(obj: Any, n: int = 8) -> str:
    return hashlib.sha256(
        json.dumps(obj, sort_keys=True, default=str).encode()
    ).hexdigest()[:n]


def spec_digest(spec: dict) -> str:
    """Digest of the whole (normalized) spec — stamped into index
    records so a ledger mixing two different specs is detectable."""
    return _digest(load_spec(spec), 12)


def load_spec(spec: Union[str, dict]) -> dict:
    """Load + normalize a campaign spec (path or dict).  Raises
    ValueError on malformed specs with a message naming the field."""
    if isinstance(spec, str):
        with open(spec) as f:
            spec = json.load(f)
    if not isinstance(spec, dict):
        raise ValueError(f"campaign spec must be a dict, got {type(spec).__name__}")
    out = dict(spec)
    out["name"] = str(out.get("name") or "campaign")
    wls = out.get("workloads")
    if not wls or not isinstance(wls, list):
        raise ValueError('campaign spec needs a non-empty "workloads" list')
    # dedupe each axis after normalization (order-preserving): entries
    # that alias to the same cell — e.g. faults [null, "", {}] all
    # normalize to None — would otherwise expand to runs with IDENTICAL
    # run_ids that race each other in the store
    out["workloads"] = _uniq([_norm_workload(w) for w in wls])
    out["faults"] = _uniq(
        [_norm_fault(fp) for fp in (out.get("faults") or [None])])
    seeds = out.get("seeds") or [0]
    out["seeds"] = _uniq([int(s) for s in seeds])
    out["opts"] = dict(out.get("opts") or {})
    out["nemesis-schedule"] = _norm_schedule(out.get("nemesis-schedule"))
    if out["nemesis-schedule"] is None:
        out.pop("nemesis-schedule")
    return out


def _norm_schedule(s: Union[None, dict]) -> Optional[dict]:
    """Normalize + validate the campaign-level ``"nemesis-schedule"``
    block (ISSUE 11 tentpole): generation-scoped fault windows every
    cell of generation *g* (= the seed axis) experiences identically,
    whether the campaign runs single-process or distributed over a
    fleet.  Keys:

        faults    list of window-able fault families (validated against
                  `nemesis.combined.WINDOW_FAULTS`)
        windows   int, windows per generation (round-robin over faults)
        interval  float s, nominal gap before/between windows
        duration  float s, how long each window stays open
        seed      int, the schedule seed — combined with the generation
                  so each generation draws its own (replayable) layout
        plan      optional resilience FaultPlan spec template; each
                  generation installs it with a generation-derived seed
                  (see `resilience.faults.seeded_for`)
    """
    if not s:
        return None
    if not isinstance(s, dict):
        raise ValueError('"nemesis-schedule" must be a dict, got '
                         f"{type(s).__name__}")
    from jepsen_tpu.nemesis.combined import WINDOW_FAULTS

    faults = s.get("faults")
    if isinstance(faults, str):
        faults = [faults]
    faults = [str(f) for f in (faults or ())]
    if not faults:
        raise ValueError('"nemesis-schedule" needs a non-empty '
                         '"faults" list')
    unknown = [f for f in faults if f not in WINDOW_FAULTS]
    if unknown:
        raise ValueError(
            f"unknown nemesis-schedule fault(s) {unknown}; window-able "
            f"families: {sorted(WINDOW_FAULTS)}")
    out = {
        "faults": faults,
        "windows": max(1, int(s.get("windows", 1))),
        "interval": float(s.get("interval", 0.25)),
        "duration": float(s.get("duration", s.get("interval", 0.25))),
        "seed": int(s.get("seed", 0)),
    }
    if out["interval"] < 0 or out["duration"] < 0:
        # a negative duration would sort a window's heal BEFORE its
        # start — fail at plan time like every other spec error
        raise ValueError(
            '"nemesis-schedule" interval/duration must be >= 0 (got '
            f"interval={out['interval']}, duration={out['duration']})")
    plan = faults_mod.parse_spec(s.get("plan"))
    if plan is not None:
        faults_mod.FaultPlan.from_spec(plan)  # raises on bad specs
        out["plan"] = plan
    return out


def schedule_windows(spec: Union[str, dict], generation: int
                     ) -> List[dict]:
    """Expand a campaign's nemesis schedule into generation *g*'s
    synchronized window assignments — the pure function both the
    single-process `run_campaign` (via `expand`) and the fleet
    coordinator's claim broadcast evaluate, so every host's cell for a
    generation installs the identical seeded window set.

    Each descriptor: ``{"pos", "fault", "at_s", "dur_s", "digest"}``
    — ``pos`` is the schedule position, ``at_s``/``dur_s`` the window's
    offset/length relative to workload start, and ``digest`` the
    window's schedule-shape identity (spec + generation + position;
    deliberately host-free, so distributed and single-process runs of
    the same spec agree on it)."""
    if isinstance(spec, dict) and "faults" in spec \
            and "workloads" not in spec:
        sched = _norm_schedule(spec)
    else:
        sched = load_spec(spec).get("nemesis-schedule")
    if not sched:
        return []
    import random as _random

    rng = _random.Random(f"nemesis-schedule|{sched['seed']}|{generation}")
    wins: List[dict] = []
    t = 0.0
    for pos in range(sched["windows"]):
        fault = sched["faults"][pos % len(sched["faults"])]
        t += sched["interval"] * rng.uniform(0.5, 1.5)
        w = {"pos": pos, "fault": fault, "at_s": round(t, 4),
             "dur_s": round(sched["duration"], 4)}
        w["digest"] = _digest({"schedule": {k: sched[k] for k in
                                            ("faults", "windows",
                                             "interval", "duration",
                                             "seed")},
                               "gen": int(generation), **w}, 12)
        wins.append(w)
        t += sched["duration"]
    return wins


def windows_digest(wins: Optional[List[dict]]) -> str:
    """One digest over a window set — what workers report as their
    installed-window identity and the dashboard compares for desync."""
    if not wins:
        return ""
    return _digest([w.get("digest") for w in wins], 12)


def _uniq(xs: list) -> list:
    out, seen = [], set()
    for x in xs:
        k = json.dumps(x, sort_keys=True, default=str)
        if k not in seen:
            seen.add(k)
            out.append(x)
    return out


def _norm_workload(w: Union[str, dict]) -> dict:
    if isinstance(w, str):
        w = {"name": w}
    if not isinstance(w, dict) or not w.get("name"):
        raise ValueError(f'bad workload entry {w!r} (want "name" or '
                         '{"name": ..., "opts": {...}})')
    out = {"name": str(w["name"]), "opts": dict(w.get("opts") or {})}
    if w.get("label"):
        out["label"] = str(w["label"])
    return out


def _norm_fault(fp: Union[None, str, dict]) -> Optional[dict]:
    """Normalize a fault-plan entry; validates via the FaultPlan parser
    so a bad spec fails at plan time, not mid-campaign."""
    if fp is None:
        return None
    if isinstance(fp, dict) and "spec" in fp:  # labeled form
        d = faults_mod.parse_spec(fp["spec"])
        if d is None:
            return None
        faults_mod.FaultPlan.from_spec(d)  # raises on unknown keys/kinds
        return {"label": str(fp.get("label") or "f-" + _digest(d, 6)),
                "spec": d}
    d = faults_mod.parse_spec(fp)
    if d is None:
        return None
    faults_mod.FaultPlan.from_spec(d)  # raises on unknown keys/kinds
    return {"label": "f-" + _digest(d, 6), "spec": d}


def _wl_label(w: dict) -> str:
    if w.get("label"):
        return w["label"]
    return w["name"] + (f"-{_digest(w['opts'], 4)}" if w["opts"] else "")


def known_workloads() -> List[str]:
    """Every workload name a spec entry may resolve to: registered
    builders, ``"noop"``, and the demo registry."""
    from jepsen_tpu.__main__ import DEMOS

    return sorted(set(_EXTRA_WORKLOADS) | {"noop"} | set(DEMOS))


def expand(spec: Union[str, dict]) -> List[RunSpec]:
    """Expand a campaign spec into its RunSpec list (workload-major,
    then fault, then seed — deterministic).

    Workload names are validated here, at plan time: an unknown entry
    raises a ValueError naming the bad workload and listing every
    registered one, instead of surfacing as a bare resolution error
    mid-campaign."""
    spec = load_spec(spec)
    known = known_workloads()
    for w in spec["workloads"]:
        if w["name"] not in known:
            raise ValueError(
                f"unknown workload {w['name']!r} in campaign spec "
                f"{spec['name']!r}; registered workloads: "
                f"{', '.join(known)}")
    name = spec["name"]
    base_opts = spec["opts"]
    sched = spec.get("nemesis-schedule")
    # one window set per generation, shared by every cell of that seed
    sched_wins = {s: schedule_windows(sched, s)
                  for s in spec["seeds"]} if sched else {}
    out: List[RunSpec] = []
    for w in spec["workloads"]:
        wl_label = _wl_label(w)
        merged = {**base_opts, **w["opts"]}
        for fp in spec["faults"]:
            f_label = fp["label"] if fp else "nofault"
            f_spec = fp["spec"] if fp else None
            for seed in spec["seeds"]:
                cell_opts = dict(merged)
                if sched:
                    # the campaign-level nemesis schedule: every cell
                    # of generation g (= the seed axis) carries the
                    # same seeded window set, so the single-process
                    # and fleet-distributed expansions of one spec are
                    # chaos-equivalent cell for cell
                    cell_opts.setdefault(
                        "nemesis-windows", sched_wins[seed])
                    if sched.get("plan") is not None:
                        cell_opts.setdefault(
                            "nemesis-plan",
                            faults_mod.seeded_for(sched["plan"], seed))
                cell = {"campaign": name, "workload": w, "fault": f_spec,
                        "seed": seed, "opts": cell_opts}
                rid = f"{wl_label}-{f_label}-s{seed}-{_digest(cell)}"
                out.append(RunSpec(
                    run_id=rid, campaign=name, workload=w["name"],
                    seed=seed, fault=f_spec, fault_label=f_label,
                    workload_label=wl_label, opts=dict(cell_opts),
                    device=bool(cell_opts.get(
                        "device", w["name"] in DEVICE_WORKLOADS)),
                ))
    return out


# ---------------------------------------------------------------------------
# RunSpec -> runnable test map
# ---------------------------------------------------------------------------

def _nemesis_for(opts: Dict[str, Any], seed: int, nodes, client):
    """Build the combined nemesis package a cell's opts request.

    ``opts["nemesis"]`` is a dict (``{"faults": ["skew"], "interval":
    0.2, ...}``) or a bare fault-name string/list; seeded from the
    cell's seed so schedules replay deterministically."""
    spec = opts.get("nemesis")
    if not spec:
        return None
    import random as _random

    from jepsen_tpu.nemesis import combined

    if isinstance(spec, str):
        spec = {"faults": [spec]}
    elif isinstance(spec, (list, tuple)):
        spec = {"faults": list(spec)}
    pkg_opts = dict(spec)
    pkg_opts.setdefault("faults", [])
    pkg_opts.setdefault("interval", 0.25)
    pkg_opts.setdefault("nodes", list(nodes))
    pkg_opts["rng"] = _random.Random(seed)
    pkg_opts.setdefault("client", client)
    return combined.nemesis_package(pkg_opts)


def _schedule_pkg_for(opts: Dict[str, Any], nodes, client):
    """Build the campaign-schedule nemesis package for a cell carrying
    ``opts["nemesis-windows"]`` (injected by `expand`, or installed by
    a fleet worker from its claim response).  Seeded from the window
    set's own digest, so two hosts handed the same window set run the
    identical fault schedule; the executing host's identity
    (``opts["_fleet-host"]``, the fleet worker name, else the
    hostname) is stamped onto every window op for the cross-host
    ddmin's host attribution."""
    wins = opts.get("nemesis-windows")
    if not wins:
        return None
    import random as _random
    import socket as _socket

    from jepsen_tpu.nemesis import combined

    host = str(opts.get("_fleet-host") or _socket.gethostname())
    return combined.schedule_package({
        "windows": wins,
        "nodes": list(nodes),
        "rng": _random.Random(f"sched|{windows_digest(wins)}"),
        "host": host,
        "client": client,
        # wall-clock anchor (ISSUE 13): fleet workers install the
        # claim's clock-offset-corrected t0 so every host's windows
        # fire at the same absolute time; absent (single-process) the
        # offsets stay relative to workload start
        "t0": opts.get("nemesis-t0"),
    })


def build_test(rs: RunSpec, base: str) -> dict:
    """Build the `core.run`-able test map for one campaign cell.

    Workloads resolve by name: registered builders first, then
    ``"noop"``, then the demo registry over the in-process sim cluster.
    Opts honored: ``time-limit`` (seconds of workload; None = ops-bound
    only), ``ops`` (op-count cap), ``concurrency``, ``nodes``,
    ``telemetry``, ``checker-time-limit``.  The run's fault spec (if
    any) lands in ``test["faults"]`` — the resilience FaultPlan key."""
    from jepsen_tpu import core as jcore
    from jepsen_tpu.generator import core as g

    opts = dict(rs.opts)
    name = f"{rs.campaign}--{rs.run_id}"
    if rs.workload in _EXTRA_WORKLOADS:
        t = _EXTRA_WORKLOADS[rs.workload]({**opts, "seed": rs.seed})
        t["name"] = name
    elif rs.workload == "noop":
        t = jcore.noop_test(name=name)
    else:
        from jepsen_tpu.__main__ import _wl

        wl, client = _wl(rs.workload, {**opts, "seed": rs.seed})
        nodes = list(opts.get("nodes") or ["n1", "n2", "n3"])
        gen = g.clients(wl["generator"])
        if opts.get("ops"):
            gen = g.limit(int(opts["ops"]), gen)
        # nemesis schedules (opts "nemesis": {"faults": [...], ...})
        # compose BEFORE the time limit: the package generators are
        # unbounded cycles, and the wall clock must bound the whole
        # interleaving, not just the client half.  A campaign-level
        # window schedule (opts "nemesis-windows") composes alongside
        # any per-cell nemesis.
        pkgs = [p for p in (_nemesis_for(opts, rs.seed, nodes, client),
                            _schedule_pkg_for(opts, nodes, client)) if p]
        pkg = None
        if pkgs:
            from jepsen_tpu.nemesis import combined

            pkg = pkgs[0] if len(pkgs) == 1 \
                else combined.compose_packages(pkgs)
        if pkg is not None and pkg.get("generator") is not None:
            gen = g.any_gen(gen, g.nemesis(pkg["generator"]))
        tl = opts.get("time-limit", 1.0)
        if tl:
            gen = g.time_limit(float(tl), gen)
        t = jcore.noop_test(
            name=name,
            nodes=nodes,
            concurrency=int(opts.get("concurrency", 4)),
            client=client, generator=gen, checker=wl["checker"])
        for k, v in wl.items():
            if k not in ("generator", "checker", "final-generator"):
                t.setdefault(k, v)
        finals = []
        if "final-generator" in wl:
            finals.append(wl["final-generator"])
        if pkg is not None:
            t["nemesis"] = pkg["nemesis"]
            if pkg.get("final_generator"):
                finals.append(g.nemesis(pkg["final_generator"]))
        if finals:
            t["final-generator"] = finals[0] if len(finals) == 1 \
                else finals
    t["store-dir"] = base
    t["seed"] = rs.seed
    t["campaign"] = rs.campaign
    t["campaign-run-id"] = rs.run_id
    # the distributed trace id (ISSUE 14): claim-carried for fleet
    # cells, derived from the stable run id otherwise — either way the
    # SAME id, so distributed and single-process cells stitch alike
    from jepsen_tpu.telemetry import spans as _spans

    t["trace-id"] = str(opts.get("trace-id")
                        or _spans.trace_id_for(rs.run_id))
    if opts.get("_fleet-host"):
        # which fleet worker executes this cell — the live-check
        # session's host attribution (verdict-freshness per host on
        # the /fleet page) and the timeline's host column
        t["fleet-host"] = str(opts["_fleet-host"])
    if opts.get("telemetry"):
        t["telemetry"] = True
    if opts.get("live-check"):
        # live verification (ISSUE 13): the cell's interpreter streams
        # completed ops into a verifier session while it runs — a URL
        # (remote service / fleet coordinator with --ingest) or
        # {"inproc": true}; see docs/VERIFIER.md
        t["live-check"] = opts["live-check"]
    if opts.get("checker-time-limit") is not None:
        t["checker-time-limit"] = float(opts["checker-time-limit"])
    if rs.fault is not None:
        t["faults"] = rs.fault
    elif opts.get("nemesis-plan") is not None:
        # the schedule's generation-seeded resilience plan: installed
        # only when the cell's own fault axis is empty (an explicit
        # fault entry always wins)
        t["faults"] = dict(opts["nemesis-plan"])
    return t
