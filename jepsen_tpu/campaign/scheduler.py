"""Device-aware worker pool for campaign runs.

The placement rule mirrors the hardware reality the parallel/ layer
models: host-only checking (stats, set, bank, the elle host oracle)
parallelizes freely across worker threads, but device-pipeline runs
(elle list-append/rw-register, knossos device WGL) contend for the one
jax runtime — so RunSpecs marked ``device=True`` serialize through a
bounded set of :class:`DeviceSlots` (default 1 slot: one device
pipeline at a time; a multi-mesh host raises ``device_slots`` and each
run learns its slot id, the seam a future per-slot
`parallel.batch.make_mesh` placement hangs off).

Isolation + resilience per run:

- ``executor="thread"`` (default) runs in-process — cheap, shares the
  warm jit cache across runs.  Two process-global resources constrain
  it: the telemetry collector (`telemetry.activate` is process-wide,
  so TELEMETRIC runs additionally serialize through one token — a
  concurrent pair would cross-attribute each other's spans), and the
  shared "jepsen" logger (concurrent runs' ``jepsen.log`` files can
  interleave lines; use the subprocess executor when per-run logs
  must be pristine);
- ``executor="subprocess"`` re-invokes ``python -m
  jepsen_tpu.campaign.runner`` per run — a crashing checker (or a
  wedged backend) cannot take the campaign down, and the hard
  ``run_deadline_s`` is enforced with a real kill;
- crashed runs retry per a seeded `resilience.RetryPolicy` (every
  exception is retryable at this level — the run may have died to an
  environment flake), and whatever survives the retries is recorded as
  an attributable ``valid? unknown`` record, never an exception: the
  campaign always completes with a full index.
"""

from __future__ import annotations

import json
import logging
import os
import queue
import subprocess
import sys
import threading
import time
import traceback
from typing import Any, Callable, Dict, List, Optional

from jepsen_tpu.campaign.plan import RunSpec
from jepsen_tpu.resilience import RetryPolicy

logger = logging.getLogger("jepsen.campaign")

__all__ = ["DeviceSlots", "Scheduler", "crash_record"]


class DeviceSlots:
    """A bounded pool of device slots.  `acquire()` blocks until a slot
    frees and returns its index (stable ids, lowest-free-first) so a
    run can pin work to "its" mesh slice; `try_acquire()` is the
    non-blocking form the scheduler uses so a slotless device run parks
    back in the queue instead of wedging a worker."""

    def __init__(self, n: int = 1):
        self.n = max(1, int(n))
        self._free = list(range(self.n))
        self._cv = threading.Condition()

    def acquire(self) -> int:
        with self._cv:
            while not self._free:
                self._cv.wait()
            return self._free.pop(0)

    def try_acquire(self) -> Optional[int]:
        with self._cv:
            return self._free.pop(0) if self._free else None

    def release(self, slot: int) -> None:
        with self._cv:
            self._free.append(slot)
            self._free.sort()
            self._cv.notify()


def crash_record(rs: RunSpec, err: str, attempt: int,
                 wall_s: float = 0.0) -> Dict[str, Any]:
    """The attributable record for a run that died outside `core.run`'s
    own error handling — still a verdict, never a crash."""
    from jepsen_tpu.telemetry import spans as _spans

    return {
        "run": rs.run_id, "key": rs.key, "campaign": rs.campaign,
        "workload": rs.workload_label, "fault": rs.fault_label,
        "seed": rs.seed, "valid?": "unknown", "error": err,
        "trace": _spans.trace_id_for(rs.run_id),
        "degraded": None, "deadline": False, "dir": None,
        "ops": 0, "wall_s": round(wall_s, 3), "attempt": attempt,
        "spans": {},
    }


class Scheduler:
    """Run a list of RunSpecs across `n_workers` threads."""

    def __init__(self, n_workers: int = 2, *, device_slots: int = 1,
                 executor: str = "thread",
                 retry: Optional[RetryPolicy] = None,
                 run_deadline_s: Optional[float] = None,
                 heartbeat: Optional[Any] = None):
        if executor not in ("thread", "subprocess"):
            raise ValueError(f"unknown executor {executor!r}")
        self.n_workers = max(1, int(n_workers))
        self.slots = DeviceSlots(device_slots)
        self.executor = executor
        #: optional telemetry.Heartbeat: per-worker in-flight state
        #: published to the campaign ledger dir as runs start/finish —
        #: the live fleet dashboard's data (docs/TELEMETRY.md)
        self.heartbeat = heartbeat
        # campaign-level retries: ANY exception is retryable here (the
        # run may have died to an env flake, not a code bug); seeded
        # backoff keeps faulted campaigns replayable
        self.retry = retry or RetryPolicy(max_attempts=2, base_delay_s=0.1,
                                          classify=lambda e: True)
        self.run_deadline_s = run_deadline_s
        # one telemetric thread-run at a time: the collector activated
        # by core.run is process-global, so a concurrent pair would
        # record each other's spans (subprocess runs are immune)
        self._tel_lock = threading.Lock()

    def run(self, specs: List[RunSpec],
            execute: Callable[[RunSpec], Dict[str, Any]],
            on_result: Optional[Callable[[Dict[str, Any]], None]] = None
            ) -> List[Dict[str, Any]]:
        """Execute every spec; returns records in spec order.  `execute`
        maps a RunSpec to its index record (the thread-executor path);
        the subprocess executor ignores it and shells out to the runner
        module.  `on_result` fires on the scheduler threads as records
        land (the campaign appends to the index there, so a kill
        mid-campaign loses at most the in-flight runs)."""
        q: "queue.Queue[tuple]" = queue.Queue()
        for i, rs in enumerate(specs):
            q.put((i, rs))
        results: List[Optional[Dict[str, Any]]] = [None] * len(specs)
        lock = threading.Lock()
        # queue-wait accounting (ISSUE 16 phase taxonomy): a parked run
        # stamps its park time; the dequeue that finally proceeds books
        # the gap.  Entries are only ever touched by the thread holding
        # that queue item, so plain dicts suffice.
        parked: Dict[int, float] = {}
        waited: Dict[int, float] = {}
        nparks: Dict[int, int] = {}

        def park(i: int, rs: RunSpec) -> None:
            parked[i] = time.monotonic()
            nparks[i] = nparks.get(i, 0) + 1
            q.put((i, rs))
            time.sleep(0.02)

        def work() -> None:
            while True:
                try:
                    i, rs = q.get_nowait()
                except queue.Empty:
                    return
                t_park = parked.pop(i, None)
                if t_park is not None:
                    waited[i] = (waited.get(i, 0.0)
                                 + (time.monotonic() - t_park))
                slot = None
                if rs.device:
                    # never BLOCK a worker on a slot: a slotless device
                    # run goes back in the queue so host-only runs
                    # behind it keep flowing ("host-only runs fill all
                    # workers freely"); the brief sleep bounds the spin
                    # when only device work remains
                    slot = self.slots.try_acquire()
                    if slot is None:
                        park(i, rs)
                        continue
                # wanted_for, not a bare opts check: the process-wide
                # telemetry.enable()/JEPSEN_TELEMETRY opt-ins make
                # core.run activate a collector too
                from jepsen_tpu import telemetry

                tel = (self.executor == "thread" and telemetry.wanted_for(
                    {"telemetry": rs.opts.get("telemetry")}))
                if tel and not self._tel_lock.acquire(blocking=False):
                    # same park-don't-block rule for the telemetry token
                    if slot is not None:
                        self.slots.release(slot)
                    park(i, rs)
                    continue
                # Heartbeat methods never raise (see its no-raise
                # guarantee) — no defensive wrapping here
                hb = self.heartbeat
                wname = threading.current_thread().name
                if hb is not None:
                    st = {
                        "run": rs.run_id, "workload": rs.workload_label,
                        "fault": rs.fault_label, "seed": rs.seed,
                        "slot": slot}
                    if rs.opts.get("nemesis-windows"):
                        # parity with fleet workers: the live dashboard
                        # shows which window set a local worker runs
                        from jepsen_tpu.campaign.plan import \
                            windows_digest

                        st["windows-digest"] = windows_digest(
                            rs.opts["nemesis-windows"])
                    hb.worker(wname, st)
                try:
                    rec = self._run_one(rs, execute, slot)
                finally:
                    if tel:
                        self._tel_lock.release()
                    if slot is not None:
                        self.slots.release(slot)
                    if hb is not None:
                        hb.worker(wname, None)
                if hb is not None:
                    hb.record_done(rs.run_id, rec.get("valid?"))
                qw = waited.pop(i, None)
                if qw:
                    try:
                        ph = rec.setdefault("phases", {}).setdefault(
                            "run", {})
                        ph["queue_wait_s"] = round(
                            float(ph.get("queue_wait_s") or 0.0) + qw, 6)
                        n = nparks.pop(i, 1)
                        cn = rec.setdefault("counters", {})
                        cn["scheduler-requeues"] = (
                            float(cn.get("scheduler-requeues") or 0) + n)
                        telemetry.registry().counter(
                            "scheduler-requeues").inc(n)
                    except Exception:  # noqa: BLE001 — accounting only
                        pass
                with lock:
                    results[i] = rec
                    if on_result is not None:
                        try:
                            on_result(rec)
                        except Exception:  # noqa: BLE001
                            logger.exception("on_result failed for %s",
                                             rs.run_id)

        threads = [threading.Thread(target=work, daemon=True,
                                    name=f"campaign-worker-{w}")
                   for w in range(self.n_workers)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return [r for r in results if r is not None]

    # -- one run, with slots + retries --------------------------------------

    def _run_one(self, rs: RunSpec,
                 execute: Callable[[RunSpec], Dict[str, Any]],
                 slot: Optional[int] = None) -> Dict[str, Any]:
        t0 = time.monotonic()
        delays = self.retry.delays()
        attempt = 0
        while True:
            attempt += 1
            try:
                if self.executor == "subprocess":
                    rec = self._run_subprocess(rs, slot)
                else:
                    # pin this thread's device slice to the acquired
                    # slot: the run's device checks then build their
                    # default mesh over slot_devices(slot, n_slots) —
                    # one host drives N sub-meshes concurrently
                    # (parallel/slots.py, ISSUE 12 satellite)
                    from jepsen_tpu.parallel import slots as slots_mod

                    slots_mod.set_active_slot(slot, self.slots.n)
                    try:
                        rec = execute(rs)
                    finally:
                        slots_mod.set_active_slot(None)
                rec["attempt"] = attempt
                if slot is not None:
                    rec["device-slot"] = slot
                return rec
            except Exception as e:  # noqa: BLE001 — retried below
                delay = next(delays, None)
                err = f"{type(e).__name__}: {e}"
                if delay is None:
                    logger.warning("run %s failed after %d attempt(s): "
                                   "%s", rs.run_id, attempt, err)
                    rec = crash_record(
                        rs, err + "\n" + traceback.format_exc(limit=3),
                        attempt, time.monotonic() - t0)
                    if slot is not None:
                        rec["device-slot"] = slot
                    return rec
                logger.warning("run %s attempt %d failed (%s); "
                               "retrying in %.2fs", rs.run_id, attempt,
                               err, delay)
                time.sleep(delay)

    # -- subprocess isolation ------------------------------------------------

    def _run_subprocess(self, rs: RunSpec, slot: Optional[int]
                        ) -> Dict[str, Any]:
        """One run in its own interpreter: `python -m
        jepsen_tpu.campaign.runner` reads the RunSpec JSON on argv,
        prints the index record as its last stdout line.  A deadline
        overrun is a hard kill -> attributable unknown."""
        base = rs.opts.get("_base") or "store"
        payload = json.dumps({"runspec": rs.to_dict(), "base": base})
        env = dict(os.environ)
        if slot is not None:
            env["JEPSEN_CAMPAIGN_DEVICE_SLOT"] = str(slot)
            env["JEPSEN_CAMPAIGN_DEVICE_SLOTS"] = str(self.slots.n)
        try:
            r = subprocess.run(
                [sys.executable, "-m", "jepsen_tpu.campaign.runner"],
                input=payload, capture_output=True, text=True,
                timeout=self.run_deadline_s, env=env,
                cwd=os.getcwd())
        except subprocess.TimeoutExpired:
            rec = crash_record(rs, "run-deadline-exceeded "
                               f"({self.run_deadline_s}s, killed)", 1)
            rec["deadline"] = True
            return rec
        for line in reversed((r.stdout or "").strip().splitlines()):
            if line.startswith("{"):
                try:
                    return json.loads(line)
                except ValueError:
                    break
        raise RuntimeError(
            f"runner rc={r.returncode}, no record on stdout; stderr tail: "
            f"{(r.stderr or '')[-500:]}")
