"""Campaign orchestration: spec → scheduled fleet → indexed verdicts.

`run_campaign` is the L6-of-L6: where `core.run` turns one test map
into one verdict, this turns a campaign spec into a fully-indexed
fleet of `core.run` invocations — expanded by `plan.expand`, placed by
`scheduler.Scheduler` (device-aware slots, retries, isolation),
recorded durably by `index.Index` as each run lands (a killed campaign
resumes where it stopped), and rolled up into a summary the CLI, the
web dashboard, and `report.render_campaign` all share.

The per-run contract matches the resilience layer's: every scheduled
run terminates with an attributable verdict (True / False / "unknown"
with an error) — a crashing workload, checker, or executor becomes an
``unknown`` record, never a campaign abort.
"""

from __future__ import annotations

import json
import logging
import os
import time
from typing import Any, Dict, List, Optional, Union

from jepsen_tpu import store
from jepsen_tpu.campaign import plan as plan_mod
from jepsen_tpu.campaign.index import Index
from jepsen_tpu.campaign.plan import RunSpec
from jepsen_tpu.campaign.scheduler import Scheduler
from jepsen_tpu.resilience import DEADLINE_ERROR

logger = logging.getLogger("jepsen.campaign")

__all__ = ["run_campaign", "status_campaign", "report_campaign",
           "execute_run", "index_path", "live_path", "result_flags",
           "summarize"]


def index_path(name: str, base: Optional[str] = None) -> str:
    """The campaign's ledger path: ``<store>/campaigns/<name>.jsonl``."""
    return os.path.join(base or store.BASE, "campaigns",
                        store.sanitize(name) + ".jsonl")


def live_path(name: str, base: Optional[str] = None) -> str:
    """The campaign's heartbeat state file (atomically replaced by the
    scheduler as workers pick up / finish runs): ``<store>/campaigns/
    <name>.live.json`` — the data behind the ``/campaign/<name>/live``
    dashboard."""
    return os.path.join(base or store.BASE, "campaigns",
                        store.sanitize(name) + ".live.json")


def result_flags(results: Any) -> Dict[str, Any]:
    """Scan a (possibly nested, composed-checker) results map for the
    attribution flags the index and the web badges surface: the first
    ``error`` string, any ``degraded`` stamp, and whether any level
    reported ``deadline-exceeded``."""
    out: Dict[str, Any] = {"error": None, "degraded": None,
                           "deadline": False}

    def walk(r: Any) -> None:
        if not isinstance(r, dict):
            return
        err = r.get("error")
        if isinstance(err, str) and err:
            if out["error"] is None:
                out["error"] = err
            if DEADLINE_ERROR in err:
                out["deadline"] = True
        deg = r.get("degraded")
        if deg and out["degraded"] is None:
            out["degraded"] = str(deg)
        for v in r.values():
            walk(v)

    walk(results)
    return out


def _read_telemetry(d: Optional[str]) -> Optional[Dict[str, Any]]:
    """The run dir's parsed telemetry.json, or None — read ONCE per
    record build (spans + phases + counters all come from it)."""
    if not d:
        return None
    path = os.path.join(d, "telemetry.json")
    if not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    return doc if isinstance(doc, dict) else None


def _spans_from_doc(doc: Optional[Dict[str, Any]],
                    cap: int = 48) -> Dict[str, float]:
    """Per-span total durations (seconds) from a run's telemetry doc —
    the material for the index's span-duration trend queries.  Missing
    or unreadable telemetry is just an empty dict."""
    if not doc:
        return {}
    out: Dict[str, float] = {}

    def walk(sp: Dict[str, Any]) -> None:
        dur = sp.get("dur_ns")
        if dur is not None:
            out[sp["name"]] = out.get(sp["name"], 0.0) + dur / 1e9
        for c in sp.get("children") or []:
            walk(c)

    for r in doc.get("spans", []):
        walk(r)
    if len(out) > cap:  # biggest spans win: the trend queries want the
        out = dict(sorted(out.items(),  # expensive stages, not leaf noise
                          key=lambda kv: -kv[1])[:cap])
    return {k: round(v, 6) for k, v in out.items()}


def _spans_from_dir(d: Optional[str], cap: int = 48) -> Dict[str, float]:
    return _spans_from_doc(_read_telemetry(d), cap)


def _phases_from_doc(doc: Optional[Dict[str, Any]],
                     cap: int = 48) -> Dict[str, Dict[str, float]]:
    """Per-span phase self-time buckets (ISSUE 16): ``{span-name:
    {bucket: seconds}}`` summed over the forest — the ledger-side half
    of the forensics parity contract (the warehouse explodes the same
    attrs into ``span_profile``; `obs diff` must reach one verdict from
    either)."""
    if not doc:
        return {}
    from jepsen_tpu.telemetry import PHASE_BUCKETS

    out: Dict[str, Dict[str, float]] = {}

    def walk(sp: Dict[str, Any]) -> None:
        attrs = sp.get("attrs") or {}
        for b in PHASE_BUCKETS:
            v = attrs.get(b)
            if isinstance(v, (int, float)) and v:
                cell = out.setdefault(sp["name"], {})
                cell[b] = cell.get(b, 0.0) + float(v)
        for c in sp.get("children") or []:
            walk(c)

    for r in doc.get("spans", []):
        walk(r)
    if len(out) > cap:
        out = dict(sorted(
            out.items(),
            key=lambda kv: -sum(kv[1].values()))[:cap])
    return {name: {b: round(v, 6) for b, v in cell.items()}
            for name, cell in out.items()}


#: counters whose per-run deltas the forensics report attributes a
#: regression to (compile misses, retries, fallbacks, anomalies) —
#: allowlisted so index records stay small
_FORENSIC_COUNTERS = ("compile-cache-miss", "resilience-retries",
                      "resilience-fallbacks", "resilience-env-anomalies",
                      "scheduler-requeues")


def _counters_from_doc(doc: Optional[Dict[str, Any]]
                       ) -> Dict[str, float]:
    """Allowlisted counter totals (plus sweep-dispatch counts) from the
    run's metric snapshot, keyed ``name{k=v,...}`` so label-level deltas
    ("compile-cache-miss{site=elle.infer} 0→14") survive the ledger."""
    if not doc:
        return {}
    m = doc.get("metrics") or {}
    out: Dict[str, float] = {}
    for c in m.get("counters") or []:
        name = c.get("name")
        if name not in _FORENSIC_COUNTERS or not c.get("value"):
            continue
        lbl = ",".join(f"{k}={v}" for k, v in
                       sorted((c.get("labels") or {}).items()))
        out[f"{name}{{{lbl}}}" if lbl else name] = float(c["value"])
    for h in m.get("histograms") or []:
        if h.get("name") == "verifier-sweep-s" and h.get("count"):
            out["verifier-sweeps"] = (
                out.get("verifier-sweeps", 0.0) + float(h["count"]))
    return out


def execute_run(rs: RunSpec, base: str) -> Dict[str, Any]:
    """Run one campaign cell end to end and build its index record.
    Exceptions out of `core.run` (setup/workload crashes — checker
    crashes are already absorbed by `check_safe`) PROPAGATE: the
    scheduler owns the retry policy and converts whatever survives its
    retries into the attributable ``unknown`` crash record — absorbing
    them here would silently disable those retries."""
    from jepsen_tpu import core as jcore

    t0 = time.monotonic()
    test = plan_mod.build_test(rs, base)
    done = jcore.run(test)
    results = done.get("results") or {}
    flags = result_flags(results)
    d = store.test_dir(done)
    rel = os.path.relpath(d, base)
    try:
        ops = len(done.get("history") or ())
    except TypeError:
        ops = 0
    rec = {
        "run": rs.run_id, "key": rs.key, "campaign": rs.campaign,
        "workload": rs.workload_label, "fault": rs.fault_label,
        "seed": rs.seed,
        # the distributed trace id (ISSUE 14): derived from the stable
        # run id, so a lease-lapse re-execution's record carries the
        # same trace as the attempt it replaced
        "trace": test.get("trace-id"),
        "valid?": results.get("valid?", "unknown"),
        "error": flags["error"],
        "degraded": flags["degraded"],
        "deadline": flags["deadline"],
        "dir": rel,
        "ops": ops,
        "wall_s": round(time.monotonic() - t0, 3),
    }
    doc = _read_telemetry(d)
    rec["spans"] = _spans_from_doc(doc)
    phases = _phases_from_doc(doc)
    if phases:
        rec["phases"] = phases
    counters = _counters_from_doc(doc)
    if counters:
        rec["counters"] = counters
    if rs.opts.get("nemesis-windows"):
        # the installed window set's identity: what the soak compares
        # between a fleet-distributed cell and its single-process twin,
        # straight off the index record
        rec["windows-digest"] = plan_mod.windows_digest(
            rs.opts["nemesis-windows"])
    if rec["valid?"] is False and rs.opts.get("shrink"):
        rec["witness"] = _auto_shrink(rs, done, d)
    return rec


def _auto_shrink(rs: RunSpec, done: dict, d: str) -> Optional[dict]:
    """The campaign auto-shrink hook (spec opts ``"shrink": true`` or a
    knob dict): delta-debug an invalid cell's history right after the
    run, while its checker object is still live, and index the witness
    summary alongside the verdict.  A failed shrink never fails the
    cell — the verdict already stands."""
    from jepsen_tpu import minimize

    knobs = rs.opts.get("shrink")
    knobs = dict(knobs) if isinstance(knobs, dict) else {}
    try:
        s = minimize.shrink(
            done, checker=done.get("checker"),
            rounds=knobs.get("rounds"),
            # bounded by default: ddmin generates exactly the
            # adversarial sub-histories per-probe deadlines exist for,
            # and a thread-executor campaign has no hard kill
            probe_deadline_s=knobs.get("probe-deadline", 30.0),
            workers=int(knobs.get("workers", 2)),
            device_slots=int(knobs.get("device-slots", 1)),
            host_oracle=bool(knobs.get("host-oracle", True)))
    except Exception as e:  # noqa: BLE001 — triage must not fail the run
        logger.warning("auto-shrink of %s failed: %s", rs.run_id, e)
        return {"error": f"{type(e).__name__}: {e}"}
    if s.get("error"):
        return {"error": s["error"]}
    out = {"ops": s.get("ops"), "source-ops": s.get("source-ops"),
           "digest": s.get("digest"),
           "anomaly-types": s.get("anomaly-types"),
           "probes": s.get("probes"), "cached": bool(s.get("cached"))}
    fw = s.get("fault-windows")
    if fw:
        # the surviving window identities ride the index record too, so
        # cross-host witness comparisons (distributed vs single-process
        # of the same spec + seed) need only the campaign ledger — the
        # full descriptors stay in witness.json
        out["fault-windows"] = [
            {k: w.get(k) for k in ("f", "pos", "digest", "fault",
                                   "host", "kept") if w.get(k)
             is not None} for w in fw]
    return out


def summarize(spec: Union[str, dict], base: Optional[str] = None,
              *, executed: int = 0, skipped: int = 0,
              wall_s: float = 0.0, idx: Optional[Index] = None
              ) -> Dict[str, Any]:
    """Build the suite rollup for a spec from its index: the one
    summary shape `report.render_campaign`, the CLI, and the web
    dashboard consume.  Pass `idx` to reuse an already-loaded Index
    (run_campaign does) instead of re-parsing the ledger."""
    spec = plan_mod.load_spec(spec)
    base = base or store.BASE
    specs = plan_mod.expand(spec)
    if idx is None:
        idx = Index(index_path(spec["name"], base))
    rows: List[Dict[str, Any]] = []
    for rs in specs:
        rec = idx.latest(rs.run_id)
        row = {"run": rs.run_id, "key": rs.key,
               "workload": rs.workload_label, "fault": rs.fault_label,
               "seed": rs.seed, "device": rs.device}
        if rec is not None:
            row.update({k: rec.get(k) for k in
                        ("valid?", "error", "degraded", "deadline",
                         "dir", "ops", "wall_s", "gen", "witness")})
        else:
            row["valid?"] = None  # not yet run
        rows.append(row)
    flips = idx.flips()
    return {
        "campaign": spec["name"],
        "spec-digest": plan_mod.spec_digest(spec),
        "index": idx.path,
        "total": len(specs),
        "executed": executed,
        "skipped": skipped,
        "pending": sum(1 for r in rows if r["valid?"] is None),
        "wall_s": round(wall_s, 3),
        "counts": idx.verdict_counts(runs=[rs.run_id for rs in specs]),
        "seeds": sorted({rs.seed for rs in specs}),
        "rows": rows,
        "regressions": [f for f in flips if f["regression"]],
        "flips": flips,
        "span-stats": idx.span_stats(),
    }


def run_campaign(spec: Union[str, dict], base: Optional[str] = None, *,
                 workers: int = 2, device_slots: int = 1,
                 executor: str = "thread", rerun: bool = False,
                 run_deadline_s: Optional[float] = None,
                 retry=None) -> Dict[str, Any]:
    """Run a campaign: expand, skip already-indexed runs (unless
    `rerun`), schedule the rest over `workers`, index every verdict as
    it lands, and return the suite summary."""
    spec = plan_mod.load_spec(spec)
    base = base or store.BASE
    specs = plan_mod.expand(spec)
    idx = Index(index_path(spec["name"], base))
    done = set() if rerun else idx.completed_ids()
    todo = [rs for rs in specs if rs.run_id not in done]
    gen = time.strftime("%Y%m%dT%H%M%SZ", time.gmtime())
    digest = plan_mod.spec_digest(spec)
    logger.info("campaign %s: %d runs (%d already indexed), %d workers, "
                "%s executor", spec["name"], len(specs),
                len(specs) - len(todo), workers, executor)
    for rs in todo:
        # a hard per-run wall also bounds the checkers cooperatively
        if run_deadline_s and rs.opts.get("checker-time-limit") is None:
            rs.opts["checker-time-limit"] = run_deadline_s
        rs.opts["_base"] = base  # the subprocess runner needs the store

    def on_result(rec: Dict[str, Any]) -> None:
        rec["gen"] = gen
        rec["spec"] = digest
        idx.append(rec)
        logger.info("campaign %s: %s -> valid? = %s", spec["name"],
                    rec.get("run"), rec.get("valid?"))

    t0 = time.monotonic()
    # heartbeat routing (ISSUE 9 satellite): when a fleet coordinator
    # URL is configured (spec opts "coordinator", or the
    # JEPSEN_COORDINATOR env for whole-process routing), progress is
    # PUSHED over HTTP and the coordinator's single Heartbeat writer
    # renders the live.json; the file-only path stays the fallback —
    # both produce the same /campaign/<name>/live shape.
    coord_url = spec["opts"].get("coordinator") or \
        os.environ.get("JEPSEN_COORDINATOR", "").strip()
    if coord_url:
        from jepsen_tpu.telemetry import HttpHeartbeat

        hb = HttpHeartbeat(coord_url, campaign=spec["name"],
                           total=len(specs),
                           done=len(specs) - len(todo))
    else:
        from jepsen_tpu.telemetry import Heartbeat

        hb = Heartbeat(live_path(spec["name"], base),
                       campaign=spec["name"],
                       total=len(specs), done=len(specs) - len(todo))
    sched = Scheduler(workers, device_slots=device_slots,
                      executor=executor, retry=retry,
                      run_deadline_s=run_deadline_s, heartbeat=hb)
    sched.run(todo, lambda rs: execute_run(rs, base),
              on_result=on_result)
    # normal completion only: an interrupted fleet must leave its
    # in-flight worker state in live.json for the /live post-mortem
    hb.close()
    # keep an existing warehouse warm: ingest the records this fleet
    # just appended (cursor-incremental, cheap), so summarize() and the
    # next dashboard render take the SQL fast path.  No warehouse on
    # this store -> nothing to do (cli obs ingest builds one).
    try:
        from jepsen_tpu.telemetry import warehouse as wmod

        wh = wmod.open_if_exists(base)
        if wh is not None:
            wh.ingest_ledger(idx.path, base)
    except Exception as e:  # noqa: BLE001 — derived index only
        logger.warning("warehouse ingest after campaign failed: %s", e)
    return summarize(spec, base, executed=len(todo),
                     skipped=len(specs) - len(todo),
                     wall_s=time.monotonic() - t0, idx=idx)


def status_campaign(spec: Union[str, dict], base: Optional[str] = None
                    ) -> Dict[str, Any]:
    """Cheap index-only view: how much of the spec has verdicts."""
    s = summarize(spec, base)
    return {k: s[k] for k in ("campaign", "index", "total", "pending",
                              "counts")}


def report_campaign(spec: Union[str, dict], base: Optional[str] = None
                    ) -> str:
    """The suite-level text rollup (grid + aggregates + regressions)."""
    from jepsen_tpu import report

    return report.render_campaign(summarize(spec, base))
