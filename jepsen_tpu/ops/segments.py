"""Segment primitives for device-side history analysis.

These are the building blocks the reference gets from JVM fork-join folds
(`jepsen/history/fold.clj`) and bifurcan collections — re-expressed as
XLA-friendly vectorized ops: segmented prefix-OR scans (chains), masked
scatter-combine (relaxation steps), and run-boundary detection over sorted
keys.  Everything here is shape-static and jit-safe.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def segment_starts_from_sorted(keys: jnp.ndarray) -> jnp.ndarray:
    """Boolean 'segment starts here' flags for a sorted key array."""
    if keys.shape[0] == 0:
        return jnp.zeros((0,), dtype=bool)
    first = jnp.ones((1,), dtype=bool)
    rest = keys[1:] != keys[:-1]
    return jnp.concatenate([first, rest])


def segmented_prefix_or(values: jnp.ndarray, starts: jnp.ndarray,
                        exclusive: bool = False) -> jnp.ndarray:
    """Segmented prefix-OR along axis 0.

    values: (n, ...) integer/bool lanes; starts: (n,) bool, True at the first
    element of each segment.  Returns, for each position, the OR of all
    values from its segment start through itself (or strictly before, if
    exclusive).  Implemented with `jax.lax.associative_scan` over the
    standard segmented-combine monoid, so it runs in O(log n) depth — this
    is what lets chain-structured dependency edges (realtime barrier chain,
    process order, per-key version order) propagate in one pass instead of
    O(chain length) rounds.
    """
    n = values.shape[0]
    if n == 0:
        return values
    if exclusive:
        # exclusive = inclusive scan over values shifted down one slot, with
        # segment-start positions zeroed (they must not see the previous
        # segment's last value)
        shifted = jnp.concatenate(
            [jnp.zeros_like(values[:1]), values[:-1]], axis=0)
        vals = jnp.where(_bcast(starts, shifted), jnp.zeros_like(shifted),
                         shifted)
        return _seg_or_impl(vals, starts)
    return _seg_or_impl(values, starts)


#: above this row count the loop-based scan is used: `associative_scan`
#: unrolls ~2*log2(n) full-width combine steps into the HLO at trace
#: time, and XLA:TPU compile time scales with that inflated graph (the
#: round-3 compile wall, PROFILE.md §2) — the Hillis-Steele fori_loop
#: body compiles ONCE and iterates log2(n) times at runtime.  O(n log n)
#: work instead of O(n), but these are int8 OR lanes: compile time, not
#: FLOPs, is the wall at 1M+-op shapes.
LOOP_SCAN_MIN_ROWS = 1 << 17


def _seg_or_impl(values: jnp.ndarray, starts: jnp.ndarray) -> jnp.ndarray:
    if values.shape[0] >= LOOP_SCAN_MIN_ROWS:
        from jepsen_tpu.ops import pallas_scan

        if pallas_scan.pallas_scan_enabled(values):
            # one HBM pass (Pallas kernel, TPU backend) instead of
            # log2(n) full-width passes; seg_or_auto carries the
            # vmap-safe batching rule — see ops/pallas_scan.py
            return pallas_scan.seg_or_auto(values, starts)
        return _seg_scan_loop(values, starts)
    return _seg_scan(values, starts)


def _seg_scan_loop(values: jnp.ndarray, starts: jnp.ndarray) -> jnp.ndarray:
    """Hillis-Steele segmented inclusive prefix-OR with doubling
    strides: state (v, blocked); blocked[i] = a segment start lies in
    (i - dist, i], so row i may not absorb the row `dist` back.  One
    compiled body, ceil(log2(n)) runtime iterations — differential-
    tested against the associative_scan path."""
    import numpy as np

    n = values.shape[0]
    n_steps = max(1, int(np.ceil(np.log2(n))))
    rows = jnp.arange(n)

    def body(_, state):
        v, blocked, dist = state
        idx = rows - dist
        ok = idx >= 0
        src = jnp.clip(idx, 0, n - 1)
        prev_v = jnp.where(_bcast(ok & ~blocked, v), v[src],
                           jnp.zeros_like(v))
        prev_blocked = jnp.where(ok, blocked[src], True)
        return v | prev_v, blocked | prev_blocked, dist * 2

    v, _, _ = jax.lax.fori_loop(
        0, n_steps, body, (values, starts, jnp.int32(1)))
    return v


def _bcast(flags: jnp.ndarray, like: jnp.ndarray) -> jnp.ndarray:
    return flags.reshape(flags.shape + (1,) * (like.ndim - 1))


def _seg_scan(values: jnp.ndarray, starts: jnp.ndarray) -> jnp.ndarray:
    def combine(a, b):
        fa, va = a
        fb, vb = b
        v = jnp.where(_bcast(fb, vb), vb, va | vb)
        return fa | fb, v

    _, out = jax.lax.associative_scan(combine, (starts, values), axis=0)
    return out


def scatter_or(target: jnp.ndarray, idx: jnp.ndarray, values: jnp.ndarray,
               mask: jnp.ndarray) -> jnp.ndarray:
    """target[idx] |= values where mask, for 0/1 int8 label planes.

    For boolean-per-bit labels, OR == max, so this lowers to scatter-max,
    which XLA supports natively on TPU.  Masked rows are redirected to a
    sink row that is dropped afterwards.
    """
    n = target.shape[0]
    sink = jnp.int32(n)
    safe_idx = jnp.where(mask, idx.astype(jnp.int32), sink)
    padded = jnp.concatenate(
        [target, jnp.zeros((1,) + target.shape[1:], dtype=target.dtype)], axis=0)
    out = padded.at[safe_idx].max(values)
    return out[:n]


def gather_rows(src: jnp.ndarray, idx: jnp.ndarray,
                mask: jnp.ndarray) -> jnp.ndarray:
    """src[idx] with masked rows zeroed (out-of-range-safe)."""
    safe = jnp.where(mask, idx, 0).astype(jnp.int32)
    rows = src[safe]
    return jnp.where(_bcast(mask, rows), rows, jnp.zeros_like(rows))


def segment_ids_from_starts(starts: jnp.ndarray) -> jnp.ndarray:
    """0-based segment id per position from start flags (parallel cumsum)."""
    return jnp.cumsum(starts.astype(jnp.int32)) - 1


def segmented_cumsum(values: jnp.ndarray, starts: jnp.ndarray,
                     exclusive: bool = False) -> jnp.ndarray:
    """Per-segment running sum via global-cumsum minus segment base.

    O(n) work, O(log n) depth — no sequential scan.
    """
    g = jnp.cumsum(values)
    seg = segment_ids_from_starts(starts)
    start_pos = jnp.nonzero(starts, size=starts.shape[0], fill_value=0)[0]
    base_incl = g[start_pos]          # inclusive cumsum AT each segment start
    start_vals = values[start_pos]
    base = (base_incl - start_vals)[seg]   # cumsum strictly before segment
    incl = g - base
    return incl - values if exclusive else incl


def segmented_cummax(values: jnp.ndarray, starts: jnp.ndarray,
                     exclusive: bool = False,
                     neutral: int = -(2 ** 31) + 1) -> jnp.ndarray:
    """Per-segment running max (values int32).  Uses lax.cummax on values
    with segment starts reset to a neutral floor by offsetting: implemented
    via the associative scan monoid (flag, value)."""
    import jax

    def combine(a, b):
        fa, va = a
        fb, vb = b
        v = jnp.where(fb, vb, jnp.maximum(va, vb))
        return fa | fb, v

    vals = values
    if exclusive:
        vals = jnp.concatenate(
            [jnp.full((1,), neutral, values.dtype), values[:-1]])
        vals = jnp.where(starts, jnp.full_like(vals, neutral), vals)
    _, out = jax.lax.associative_scan(combine, (starts, vals))
    return out
