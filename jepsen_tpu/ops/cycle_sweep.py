"""Device cycle detection over dependency graphs: the parallel-SCC engine.

This replaces the reference's sequential Java Tarjan
(`io.lacuna.bifurcan.Graphs/stronglyConnectedComponents`, SURVEY.md §2.5 #1)
with a TPU-shaped decomposition.  Tarjan is inherently sequential; instead:

1. **Rank decomposition.**  Nodes carry a static rank (completion order of
   txns, with realtime-barrier nodes interleaved).  Edges split into
   *forward* (rank(src) < rank(dst)) and *backward* (the rest).  Forward
   edges alone form a DAG, so **every cycle contains >= 1 backward edge**.
   In valid histories backward edges are rare (version order mostly agrees
   with commit order), giving a device-only fast path: K == 0 -> acyclic.

2. **Forward reachability from backward-edge heads.**  label[v] = the set
   of backward edges e with dst(e) ->* v through forward edges, as (N, K)
   0/1 int8 planes (OR == max, so relaxation is scatter-max — native on
   TPU).  Long chains (realtime barrier chain, per-process order, per-key
   ww version order) would make naive relaxation O(diameter); they are
   instead resolved each round by **segmented prefix-OR scans**
   (associative_scan, O(log N) depth), so rounds are bounded by the number
   of *non-chain* hops (wr/rw/barrier-entry/exit edges) on the longest
   shortest-path — small in practice.  Fixpoint via `lax.while_loop`.

3. **Meta-closure.**  Cycle exists iff the K-node meta-graph — meta-edge
   e -> e' iff dst(e) ->*_forward src(e') — has a cycle (self-loops
   included).  K x K boolean closure by repeated squaring (MXU-friendly).

Backward edges on meta-cycles are returned as *witnesses*; exact anomaly
classification/explanation happens host-side on the (small) offending
subgraph, mirroring the reference's SCC -> in-SCC search split.

If the fixpoint loop hits `max_rounds` without converging the result is
flagged `converged=False`; callers MUST fall back to the host checker
(checkers are oracles — a truncated propagation could miss cycles, and we
never trade exactness for speed).
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from jepsen_tpu.ops.segments import (
    gather_rows,
    scatter_or,
    segmented_prefix_or,
)


@dataclasses.dataclass
class SweepGraph:
    """Static, padded graph layout for the sweep kernel (device arrays).

    Non-chain edges are COO (src, dst, mask).  Chain edges are given as
    concatenated node sequences: chain_nodes with chain_starts flags; the
    implied edges are chain_nodes[i] -> chain_nodes[i+1] within a segment.
    chain_mask disables whole entries (padding / rel not in projection).
    All ranks must be unique per node; forward = rank increases.
    """

    n_nodes: int
    rank: jnp.ndarray          # (N,) int32, unique
    nc_src: jnp.ndarray        # (E,) int32 non-chain edges
    nc_dst: jnp.ndarray        # (E,) int32
    nc_mask: jnp.ndarray       # (E,) bool
    chain_nodes: jnp.ndarray   # (C,) int32
    chain_starts: jnp.ndarray  # (C,) bool
    chain_mask: jnp.ndarray    # (C,) bool


def backward_test(rank, nc_src, nc_dst, n_nodes: int):
    """The projection-independent backward-edge test (edge goes backward
    iff rank does not increase).  Single source of truth for callers that
    hoist it out of a projection scan AND for `_sweep_window`'s internal
    fallback — the two must stay bit-identical."""
    return rank[jnp.clip(nc_src, 0, n_nodes - 1)] >= \
        rank[jnp.clip(nc_dst, 0, n_nodes - 1)]


def _sweep_window(n_nodes: int, k_total: int, k_local: int, max_rounds: int,
                  rank, nc_src, nc_dst, nc_mask,
                  chain_nodes, chain_starts, chain_mask,
                  k_offset, axis_name=None, back_raw=None, back_pre=None,
                  back_tables=None):
    """Sweep kernel over a window of the backward-edge axis.

    Each caller owns backward edges with global ids in
    [k_offset, k_offset + k_local) and propagates only their (N, k_local)
    label planes — backward-edge columns are fully independent until the
    tiny meta-graph closure, which is the ONLY cross-window coupling.  With
    `axis_name` set (inside shard_map over a mesh axis of
    k_total // k_local devices) the local meta rows are combined with an
    ICI all_gather and convergence with a psum; every device then holds the
    full (k_total, k_total) meta graph and computes the closure redundantly
    (it is k_total^2 bytes — trivial next to the label planes).

    Returns (has_cycle, witness_bits (k_total,), n_backward, converged) —
    replicated across the axis when axis_name is set.
    """
    # ---- split edges: backward iff rank[src] >= rank[dst] -----------------
    # (chain edges are forward by construction: caller guarantees ranks
    # increase along chains).  `back_raw` lets a caller scanning over
    # several projections hoist the two E-sized rank gathers out of the
    # scan — the comparison is projection-independent, only the mask
    # varies (1 byte/edge hoisted vs 8 bytes/edge re-gathered 5x).
    if back_pre is not None:
        # caller hoisted the whole backward enumeration (is_back,
        # position-stable back_id, n_back) — e.g. device_core's
        # projection scan, which derives them from ONE shared cumsum
        # plus per-family offsets instead of an E-sized cumsum per
        # projection.  Must be bit-identical to the block below.
        is_back, back_id, n_back = back_pre
    else:
        if back_raw is None:
            back_raw = backward_test(rank, nc_src, nc_dst, n_nodes)
        is_back = nc_mask & back_raw
        n_back = jnp.sum(is_back.astype(jnp.int32))

        # stable enumeration of backward edges: order by edge position
        back_order = jnp.cumsum(is_back.astype(jnp.int32)) - 1
        back_id = jnp.where(is_back, back_order, -1)

    if back_tables is not None:
        # caller supplied the (k_total,) backward-edge endpoint tables
        # (projection_scan builds them with ~k binary searches over its
        # ONE shared cumsum) — skip the two E-sized scatter-max
        # reductions below entirely.  On TPU those scatters measured
        # 2.4 s/run at 1M shapes (0.24 s x 2 x 5 projections, ~24% of
        # the whole check); the searchsorted tables are microseconds.
        bsrc_full, bdst_full = back_tables
        bdst_local = jax.lax.dynamic_slice(
            bdst_full, (k_offset,), (k_local,))
    else:
        # full-width source table (identical on every window — needed
        # for the meta-graph columns)
        in_full = is_back & (back_id < k_total)
        scat_full = jnp.where(in_full, back_id, k_total).astype(jnp.int32)
        bsrc_full = jnp.zeros((k_total + 1,), jnp.int32).at[scat_full].max(
            jnp.where(in_full, nc_src, 0))[:k_total]

        # local window endpoints
        in_local = is_back & (back_id >= k_offset) \
            & (back_id < k_offset + k_local)
        scat_local = jnp.where(in_local, back_id - k_offset,
                               k_local).astype(jnp.int32)
        bdst_local = jnp.zeros((k_local + 1,), jnp.int32).at[scat_local].max(
            jnp.where(in_local, nc_dst, 0))[:k_local]

    bvalid_full = (jnp.arange(k_total) < n_back)
    bvalid_local = (jnp.arange(k_local) + k_offset) < n_back
    fwd_mask = nc_mask & ~is_back  # forward non-chain edges only

    def propagate(_):
        # labels: (N, k_local) int8; seed label[bdst[e], e] = 1
        labels0 = jnp.zeros((n_nodes, k_local), jnp.int8)
        labels0 = labels0.at[jnp.where(bvalid_local, bdst_local, 0),
                             jnp.arange(k_local)].max(
            bvalid_local.astype(jnp.int8))

        def chain_pass(labels):
            vals = gather_rows(labels, chain_nodes, chain_mask)
            pref = segmented_prefix_or(vals, chain_starts, exclusive=True)
            return scatter_or(labels, chain_nodes, pref, chain_mask)

        def relax_pass(labels):
            vals = gather_rows(labels, nc_src, fwd_mask)
            return scatter_or(labels, nc_dst, vals, fwd_mask)

        def body(state):
            labels, _, i = state
            new = chain_pass(labels)
            new = relax_pass(new)
            new = chain_pass(new)
            changed = jnp.any(new != labels)
            return new, changed, i + 1

        def cond(state):
            _, changed, i = state
            return changed & (i < max_rounds)

        # carry components derive from sharded inputs so their varying-axis
        # type matches the body's outputs under shard_map
        changed0 = n_back >= 0                 # always True, varying-typed
        rounds0 = jnp.where(n_back < 0, 1, 0)  # always 0, varying-typed
        if axis_name is not None:
            # the label plane is varying over the mesh axis (its window
            # depends on axis_index), so the whole carry must be too
            # (no-op on jax versions without the varying-type system)
            from jepsen_tpu.utils.backend import pcast_varying

            changed0 = pcast_varying(changed0, axis_name)
            rounds0 = pcast_varying(rounds0, axis_name)
        labels, changed, rounds = jax.lax.while_loop(
            cond, body, (chain_pass(labels0), changed0, rounds0))
        converged = ~(changed & (rounds >= max_rounds))

        # meta-graph rows for the local window: meta[e, e2] = dst(e) ->*
        # src(e2), read from labels[src(e2), e]
        meta_local = gather_rows(labels, bsrc_full, bvalid_full).T
        if axis_name is not None:
            meta = jax.lax.all_gather(meta_local, axis_name, axis=0,
                                      tiled=True)
            # psum/pmax outputs are replicated over the axis — required for
            # the P() out_specs of the enclosing shard_map
            n_bad = jax.lax.psum((~converged).astype(jnp.int32), axis_name)
            converged = n_bad == 0
            meta = jax.lax.pmax(meta, axis_name)
        else:
            meta = meta_local
        meta = meta & bvalid_full[:, None].astype(jnp.int8) \
                    & bvalid_full[None, :].astype(jnp.int8)

        def close_body(_, r):
            ri = r.astype(jnp.int32)
            r2 = ((ri @ ri) > 0).astype(jnp.int8)
            return r | r2

        n_sq = max(1, int(np.ceil(np.log2(max(2, k_total)))))
        closure = jax.lax.fori_loop(0, n_sq, close_body, meta)
        # backward edge e is on a cycle iff closure[e][e] (dst ->* src,
        # then the edge src -> dst itself closes it)
        witness = jnp.diagonal(closure) & bvalid_full.astype(jnp.int8)
        return jnp.any(witness == 1), witness, converged

    def acyclic(_):
        # no backward edges: forward edges strictly increase rank, so the
        # projection is a DAG — nothing to propagate (the common case for
        # valid histories; this skip is the fast path)
        # zeros derived from n_back so the varying-axis type matches the
        # propagate branch under shard_map
        zeros = jnp.zeros((k_total,), jnp.int8) + (n_back * 0).astype(jnp.int8)
        return (n_back < 0, zeros, n_back >= 0)

    has_cycle, witness, converged = jax.lax.cond(
        n_back > 0, propagate, acyclic, operand=None)
    return has_cycle, witness, n_back, converged


def _sweep_arrays(n_nodes: int, max_k: int, max_rounds: int,
                  rank, nc_src, nc_dst, nc_mask,
                  chain_nodes, chain_starts, chain_mask, back_raw=None,
                  back_pre=None, back_tables=None):
    """Core kernel (single window).  Returns (has_cycle, witness_bits,
    n_backward, converged).

    witness_bits: (max_k,) int8 — 1 for backward edges on some cycle.
    n_backward: actual number of backward edges found (may exceed max_k —
    caller must re-batch; we still compute exactly for the first max_k and
    report overflow via n_backward).
    """
    return _sweep_window(n_nodes, max_k, max_k, max_rounds,
                         rank, nc_src, nc_dst, nc_mask,
                         chain_nodes, chain_starts, chain_mask,
                         k_offset=jnp.int32(0), axis_name=None,
                         back_raw=back_raw, back_pre=back_pre,
                         back_tables=back_tables)


_sweep = jax.jit(_sweep_arrays,
                 static_argnames=("n_nodes", "max_k", "max_rounds"))


# arrays-first twins of _sweep/_sweep_sharded for the AOT compile-cache
# seam (compilecache.call dispatches a cached Compiled with the dynamic
# args alone, so statics must bind by keyword behind the arrays)
@partial(jax.jit, static_argnames=("n_nodes", "max_k", "max_rounds"))
def _sweep_kw(rank, nc_src, nc_dst, nc_mask, chain_nodes, chain_starts,
              chain_mask, *, n_nodes, max_k, max_rounds):
    return _sweep_arrays(n_nodes, max_k, max_rounds, rank, nc_src,
                         nc_dst, nc_mask, chain_nodes, chain_starts,
                         chain_mask)


@partial(jax.jit, static_argnames=("n_nodes", "max_k", "max_rounds",
                                   "mesh", "axis"))
def _sweep_sharded(n_nodes: int, max_k: int, max_rounds: int, mesh, axis,
                   rank, nc_src, nc_dst, nc_mask,
                   chain_nodes, chain_starts, chain_mask):
    """`_sweep_arrays` with the backward-edge axis sharded over `mesh`
    (the per-projection form of `parallel/op_shard.py`'s K-window
    pattern): each device owns max_k / n_shards backward-edge columns
    and propagates only its label-plane window; the (K, K) meta graph
    merges with one all_gather.  Same result contract as `_sweep`."""
    from jax.sharding import PartitionSpec as P

    from jepsen_tpu.utils.backend import get_shard_map

    n_shards = mesh.shape[axis]
    assert max_k % n_shards == 0, (max_k, n_shards)
    k_local = max_k // n_shards
    shard_map = get_shard_map()
    rep = P()

    @partial(shard_map, mesh=mesh, in_specs=(rep,) * 7,
             out_specs=(rep, rep, rep, rep))
    def run(rank_, s_, d_, m_, cn_, cs_, cm_):
        off = jax.lax.axis_index(axis) * k_local
        return _sweep_window(n_nodes, max_k, k_local, max_rounds,
                             rank_, s_, d_, m_, cn_, cs_, cm_,
                             k_offset=off, axis_name=axis)

    return run(rank, nc_src, nc_dst, nc_mask, chain_nodes, chain_starts,
               chain_mask)


@partial(jax.jit, static_argnames=("n_nodes", "max_k", "max_rounds",
                                   "mesh", "axis"))
def _sweep_sharded_kw(rank, nc_src, nc_dst, nc_mask, chain_nodes,
                      chain_starts, chain_mask, *, n_nodes, max_k,
                      max_rounds, mesh, axis):
    return _sweep_sharded(n_nodes, max_k, max_rounds, mesh, axis, rank,
                          nc_src, nc_dst, nc_mask, chain_nodes,
                          chain_starts, chain_mask)


def projection_scan(n_nodes: int, max_k: int, max_rounds: int,
                    rank, e_src, e_dst, fam_masks, inc_stack,
                    chain_nodes, chain_starts, chain_masks, cinc_stack,
                    sweep=None):
    """Scan `_sweep_arrays` over projections given per-family masks and
    per-projection family-include flags — the single-sourced hoisted
    form shared by device_core.core_check and device_rw.rw_core_check.

    `sweep` (optional) replaces the single-window `_sweep_arrays` call
    with a caller-supplied kernel of signature (rank, e_src, e_dst,
    mask, chain_nodes, chain_starts, chain_mask, back_pre,
    back_tables) -> (has, witness, n_back, converged), where
    back_tables is the (max_k,) (bsrc, bdst) endpoint pair built here
    by binary search — how the K-windowed sharded paths
    (`parallel/op_shard.py`, `parallel/hybrid.py`) reuse this scan with
    `_sweep_window` inside shard_map while keeping the hoisted
    enumeration (VERDICT r04 item 2: the sharded sweep previously
    re-materialized (5, E) mask stacks and ran 5 E-sized cumsums).

    Instead of materialized (P, E)/(P, C) mask stacks and an E-sized
    cumsum per projection, the scan consumes tiny include matrices:
    per-projection masks are `family_mask & include`, and backward-edge
    enumeration hoists to ONE shared cumsum + per-family count offsets.
    Families are concatenated blocks, so a projection's position-stable
    enumeration equals its within-family ids shifted by the counts of
    its included predecessor families — bit-identical to cumsum over
    the projection's own mask (the `back_pre` path in `_sweep_window`).
    Measured effect at 1M txns on CPU: fused check 7.98 s -> 5.18 s and
    compile 28.8 s -> 7.9 s (PROFILE.md §0b).

    fam_masks: per-family (E_f,) bool masks, concat order == e_src.
    inc_stack: (P, F) int32 — family f included in projection p.
    chain_masks: per-chain-group (C_g,) bool, concat order ==
    chain_nodes.  cinc_stack: (P, G) int32.
    Returns (conv_all, overflow, cyc_bits (P,) int32).
    """
    fam_lens = [int(m.shape[0]) for m in fam_masks]
    bounds = np.cumsum([0] + fam_lens)
    union_mask = jnp.concatenate(list(fam_masks))

    back_raw = backward_test(rank, e_src, e_dst, n_nodes)
    back_all = union_mask & back_raw
    cum = jnp.cumsum(back_all.astype(jnp.int32))             # ONE E-cumsum
    cum_start = [cum[int(b) - 1] if b > 0 else jnp.int32(0)
                 for b in bounds[:-1]]
    count_f = jnp.stack([
        (cum[int(e) - 1] if e > 0 else jnp.int32(0)) - s
        for s, e in zip(cum_start, bounds[1:])])
    within = (cum - 1) - jnp.concatenate(
        [jnp.broadcast_to(s, (L,)) for s, L in zip(cum_start, fam_lens)])

    def rep(valsF):
        return jnp.concatenate(
            [jnp.broadcast_to(valsF[i], (L,))
             for i, L in enumerate(fam_lens)])

    def proj_body(carry, mc):
        conv_all, overflow = carry
        inc, cinc = mc
        inc_b = inc.astype(bool)
        m = union_mask & rep(inc_b)
        cm = jnp.concatenate([cmask & (cinc[g] > 0)
                              for g, cmask in enumerate(chain_masks)])
        offs = jnp.concatenate(
            [jnp.zeros(1, jnp.int32), jnp.cumsum(count_f * inc)[:-1]])
        is_back = back_all & rep(inc_b)
        back_id = jnp.where(is_back, within + rep(offs), -1)
        n_back = jnp.sum(count_f * inc)

        # (max_k,) backward-edge endpoint tables via binary search over
        # the shared cumsum instead of E-sized scatter-max in the sweep
        # (the scatters measured 0.24 s each per projection at 1M-txn
        # TPU shapes — ~24% of the whole check).  The edge with
        # projection id i of family f is the first position in f's
        # block where `cum` reaches cum_start[f] + (i - offs[f]) + 1:
        # cum steps by exactly 1 at each union-masked backward edge,
        # and a projection's family-f backward set IS the union's
        # (family masks don't vary per projection, only inclusion).
        # Bit-identical to the scatter form: unique ids -> the single
        # contributing edge's endpoint; ids >= n_back stay 0.
        tgt = jnp.arange(max_k, dtype=jnp.int32)
        bsrc_k = jnp.zeros((max_k,), jnp.int32)
        bdst_k = jnp.zeros((max_k,), jnp.int32)
        for f, L in enumerate(fam_lens):
            if L == 0:
                continue
            lo, hi = int(bounds[f]), int(bounds[f + 1])
            j = tgt - offs[f]
            pos = lo + jnp.searchsorted(
                cum[lo:hi], cum_start[f] + j + 1,
                side="left").astype(jnp.int32)
            pos = jnp.clip(pos, 0, cum.shape[0] - 1)
            sel = inc_b[f] & (j >= 0) & (j < count_f[f])
            bsrc_k = jnp.where(sel, e_src[pos], bsrc_k)
            bdst_k = jnp.where(sel, e_dst[pos], bdst_k)

        if sweep is None:
            has, _, n_back_out, conv = _sweep_arrays(
                n_nodes, max_k, max_rounds, rank, e_src, e_dst, m,
                chain_nodes, chain_starts, cm,
                back_pre=(is_back, back_id, n_back),
                back_tables=(bsrc_k, bdst_k))
        else:
            has, _, n_back_out, conv = sweep(
                rank, e_src, e_dst, m, chain_nodes, chain_starts, cm,
                (is_back, back_id, n_back), (bsrc_k, bdst_k))
        carry = (conv_all & conv,
                 jnp.maximum(overflow,
                             jnp.maximum(n_back_out - max_k, 0)))
        return carry, has.astype(jnp.int32)

    # carry init derives from traced inputs so its varying-axis type
    # matches the body outputs under shard_map/vmap
    zero0 = e_src[0] * 0
    n_proj = int(inc_stack.shape[0])

    def run_scan(_):
        (conv_all, overflow), cyc_bits = jax.lax.scan(
            proj_body, (zero0 == 0, zero0), (inc_stack, cinc_stack))
        return conv_all, overflow, cyc_bits

    def no_backward(_):
        # zero backward edges across the FULL family union: every
        # projection's backward set is a subset, so all P projections
        # are DAGs — converged, no overflow, no cycles.  The common
        # case for valid histories; skipping the scan saves P rounds of
        # E-sized masking/enumeration.  (Under vmap this cond lowers to
        # select and both branches still run — batched paths keep their
        # old cost, never a new one.)
        return zero0 == 0, zero0, jnp.zeros((n_proj,), jnp.int32) + zero0

    total_back = cum[-1] if cum.shape[0] else jnp.int32(0)
    return jax.lax.cond(total_back > 0, run_scan, no_backward,
                        operand=None)

#: budget ceilings shared by every sweep driver (detect_cycles here,
#: grow_until_exact in device_core): past these, callers fall back to
#: the host oracle rather than approximate
MAX_K_CAP = 8192
MAX_ROUNDS_CAP = 1024


@dataclasses.dataclass
class SweepResult:
    has_cycle: bool
    witness_edge_ids: np.ndarray  # indices into the non-chain edge arrays
    n_backward: int
    converged: bool


def detect_cycles(g: SweepGraph, max_k: int = 128,
                  max_rounds: int = 64, deadline=None, mesh=None,
                  axis: str = "batch") -> SweepResult:
    """Run the sweep; rebatch automatically if backward edges exceed max_k.

    Exact: cycle reported iff one exists in the (masked) graph, provided
    converged=True.  Witnesses identify backward edges on cycles (for the
    first max_k; enough to hand the host a subgraph to classify).

    `deadline` (a `resilience.Deadline`) is polled before each grow-
    retry — the budget-doubling fixpoint is this driver's unbounded
    loop, and a pathological graph must not hold the checker past its
    time budget (expiry raises `DeadlineExceeded`).

    `mesh` (a 1-D jax Mesh, ISSUE 12 sharded-by-default) shards the
    backward-edge axis over its devices — verdict-identical to the
    single-device sweep, differential-pinned in tests/test_parallel.py.
    """
    if deadline is not None:
        deadline.check("cycle-sweep")
    # both branches ride the AOT compile cache: verifier sweep chunks
    # and checker projections pad to pow2 (N, E) classes, so
    # maintenance rounds and probes share persisted executables
    from jepsen_tpu import compilecache

    if mesh is not None and mesh.devices.size > 1:
        n_shards = mesh.shape[axis]
        if max_k % n_shards:
            max_k = ((max_k // n_shards) + 1) * n_shards
        has, wit, n_back, conv = compilecache.call(
            "cycle-sweep.sharded", _sweep_sharded_kw, g.rank, g.nc_src,
            g.nc_dst, g.nc_mask, g.chain_nodes, g.chain_starts,
            g.chain_mask, n_nodes=g.n_nodes, max_k=max_k,
            max_rounds=max_rounds, mesh=mesh, axis=axis)
    else:
        mesh = None
        has, wit, n_back, conv = compilecache.call(
            "cycle-sweep", _sweep_kw, g.rank, g.nc_src, g.nc_dst,
            g.nc_mask, g.chain_nodes, g.chain_starts, g.chain_mask,
            n_nodes=g.n_nodes, max_k=max_k, max_rounds=max_rounds)
    n_back = int(n_back)
    if n_back > max_k:
        if n_back > MAX_K_CAP or max_k >= MAX_K_CAP:
            # bit budget unreachable or exhausted (an (n_nodes, max_k)
            # label plane past the cap would chew through memory; and
            # n_back is a property of the graph, so a capped retry that
            # still cannot fit it would be a guaranteed-wasted sweep):
            # report inexact — the caller falls back to the host oracle,
            # same contract as grow_until_exact
            return SweepResult(has_cycle=bool(has),
                               witness_edge_ids=np.zeros(0, np.int64),
                               n_backward=n_back, converged=False)
        # too many backward edges for the bit budget: double and retry
        return detect_cycles(g,
                             max_k=min(max(max_k * 2, _pow2(n_back)),
                                       MAX_K_CAP),
                             max_rounds=max_rounds, deadline=deadline,
                             mesh=mesh, axis=axis)
    if not bool(conv) and max_rounds < MAX_ROUNDS_CAP:
        # fixpoint truncated: grow rounds like grow_until_exact does for
        # the fused path (histories dense with injected cycles can need
        # hundreds of rounds) before surrendering to the host fallback
        return detect_cycles(g, max_k=max_k,
                             max_rounds=min(max_rounds * 2,
                                            MAX_ROUNDS_CAP),
                             deadline=deadline, mesh=mesh, axis=axis)
    wit = np.asarray(wit)
    conv = bool(conv)
    has = bool(has)
    # map witness backward-edge ids back to edge-array positions
    mask = np.asarray(g.nc_mask)
    rank = np.asarray(g.rank)
    src = np.clip(np.asarray(g.nc_src), 0, g.n_nodes - 1)
    dst = np.clip(np.asarray(g.nc_dst), 0, g.n_nodes - 1)
    is_back = mask & (rank[src] >= rank[dst])
    back_pos = np.nonzero(is_back)[0]
    wit_ids = back_pos[np.nonzero(wit[:len(back_pos)])[0]] \
        if len(back_pos) else np.zeros(0, np.int64)
    return SweepResult(has_cycle=has, witness_edge_ids=wit_ids,
                       n_backward=n_back, converged=conv)


def _pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p
