"""Device primitives: segment ops, bitset label propagation, cycle kernels."""
