"""Pallas TPU kernel: single-pass flat forward-fill (LOCF) over int32.

Edge inference (`checkers/elle/device_infer.py`) expands per-mop tables
to the R-sized read-element axis: seed a value at each segment start,
then fill holes forward ("last observed carried forward").  The lax
path does this with `lax.cummax` for monotone channels plus R-sized
gathers `table[er]` for the rest — and on TPU those gathers execute at
~0.4 GB/s (scalar loads; measured 0.45 s EACH at R = 2^24, PROFILE.md
round-5 trace), totalling ~2.3 s of the 1M-txn check.

This kernel replaces cummax + the monotone/table gathers with one pass
per channel over HBM: values are viewed as a (rows, 128) plane in flat
row-major order; each grid step loads a block into VMEM, runs a
cross-lane then cross-row doubling fill at VPU speed, absorbs the
scalar carry from previous blocks (TPU Pallas grids execute
sequentially, so the carry lives in VMEM scratch), and writes back.

Hole representation is a sentinel (-1): every filled channel here is
nonnegative (mop positions, rd_start offsets, lengths, key ids, txn
ids), so no separate mask plane is needed, and on monotone seed
channels LOCF is bitwise `lax.cummax` (the last seed IS the max).

Exactness protocol (same as `ops/pallas_scan.py`): the block math is
shared verbatim with a pure-JAX grid emulator (`locf_blocked_reference`)
differential-tested against the lax scan on any backend; the compiled
kernel is differential-tested against the emulator on the TPU backend.

vmap: a batched call must not leak the carry across batch rows; the
custom_vmap rule falls back to the O(log n)-pass lax scan per row
(exact, slower — the batched checking paths pay this, as they already
do for the dup-sort branch).
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp

_BLOCK_ROWS = 1024   # (B, 128) int32 = 512 KB/buffer in VMEM
_LANES = 128
HOLE = -1


def locf_lax(x: jnp.ndarray, hole: int = HOLE) -> jnp.ndarray:
    """Reference semantics: out[i] = x[j] for the largest j <= i with
    x[j] != hole, else hole.  O(log n) full passes."""
    return jax.lax.associative_scan(
        lambda a, b: jnp.where(b == hole, a, b), x)


def _block_fill(v, block: int, roll):
    """In-block flat LOCF of a (B, 128) int32 plane in row-major order,
    shared by the kernel (roll = pltpu.roll) and the emulator
    (roll = jnp.roll).  Returns the filled block (holes before the
    block's first non-hole stay HOLE — the caller absorbs the carry).

    Two-level doubling: cross-lane fill within each row, then the
    row-level fill propagates each row's last value (lane 127 after the
    lane fill) downward, and rows still starting with holes prepend it.
    """
    # 1. cross-lane LOCF per row (lanes are the minor/flat-order axis)
    dist = 1
    lanes = jax.lax.broadcasted_iota(jnp.int32, v.shape, 1)
    while dist < _LANES:
        v_p = roll(v, dist, 1)
        take = (lanes >= dist) & (v == HOLE)
        v = jnp.where(take, v_p, v)
        dist *= 2
    # 2. per-row last value (lane 127), filled across rows
    last = v[:, _LANES - 1:_LANES]                      # (B, 1)
    rows = jax.lax.broadcasted_iota(jnp.int32, last.shape, 0)
    dist = 1
    while dist < block:
        l_p = roll(last, dist, 0)
        take = (rows >= dist) & (last == HOLE)
        last = jnp.where(take, l_p, last)
        dist *= 2
    # 3. rows adopt the previous row's filled last value for their
    # leading holes (the lane fill left them HOLE)
    prev = roll(last, 1, 0)
    prev = jnp.where(rows >= 1, prev, HOLE)             # row 0: no prev
    return jnp.where(v == HOLE, prev, v)


def _replicate_last_lane(row, roll):
    """(1, 128) -> (1, 128) with every lane = input lane 127, via
    cyclic-roll doubling (Mosaic has no (1,1)->(1,128) broadcast; a
    full replicated row sidesteps it — the same reason the OR kernel
    carries a (1, K) row).  Shared by kernel and emulator."""
    lanes = jax.lax.broadcasted_iota(jnp.int32, row.shape, 1)
    v = jnp.where(lanes == _LANES - 1, row, HOLE)
    dist = 1
    while dist < _LANES:
        # cyclic roll by -dist: lane l reads lane l+dist (mod 128);
        # only lane 127 is non-hole initially, so this backward-fills
        v_p = roll(v, _LANES - dist, 1)
        v = jnp.where(v == HOLE, v_p, v)
        dist *= 2
    return v


def _fill_kernel(block: int, v_ref, o_ref, carry_ref):
    """One grid step: in-block fill + carry absorb/update.  carry_ref is
    (8, 128) int32 VMEM scratch; row 0 holds the last non-hole value of
    all previous blocks (or HOLE), replicated across lanes."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b = pl.program_id(0)

    @pl.when(b == 0)
    def _():
        carry_ref[...] = jnp.full_like(carry_ref, HOLE)

    roll = lambda x, d, ax: pltpu.roll(x, shift=d, axis=ax)  # noqa: E731
    v = v_ref[...]
    out = _block_fill(v, block, roll)
    carry = carry_ref[0:1, :]                            # (1, 128)
    out = jnp.where(out == HOLE, carry, out)
    # new carry = last flat element (already carry-absorbed, so a fully
    # empty block propagates the old carry), replicated across lanes
    carry_ref[0:1, :] = _replicate_last_lane(
        out[block - 1:block, :], roll)
    o_ref[...] = out


@functools.partial(jax.jit, static_argnames=("block",))
def _locf_pallas_padded(v2d: jnp.ndarray, block: int) -> jnp.ndarray:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    rows, lanes = v2d.shape
    return pl.pallas_call(
        functools.partial(_fill_kernel, block),
        grid=(rows // block,),
        in_specs=[pl.BlockSpec((block, lanes), lambda i: (i, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((block, lanes), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((rows, lanes), jnp.int32),
        scratch_shapes=[pltpu.VMEM((8, lanes), jnp.int32)],
    )(v2d)


def _pad_2d(x: jnp.ndarray, block: int):
    n = x.shape[0]
    rows = -(-n // _LANES)
    rows_pad = -rows % block
    total = (rows + rows_pad) * _LANES
    v = jnp.pad(x, (0, total - n), constant_values=HOLE)
    return v.reshape(rows + rows_pad, _LANES), n


def locf_pallas(x: jnp.ndarray, block: int = _BLOCK_ROWS) -> jnp.ndarray:
    """Flat forward-fill of a 1-D int32 array on TPU (holes = -1).
    Padding rows are appended as holes and sliced off; the carry flows
    only forward, so they cannot affect real elements."""
    v2d, n = _pad_2d(x, block)
    block = min(block, v2d.shape[0])
    return _locf_pallas_padded(v2d, block).reshape(-1)[:n]


def locf_blocked_reference(x: jnp.ndarray,
                           block: int = _BLOCK_ROWS) -> jnp.ndarray:
    """Pure-JAX emulation of the kernel schedule (same `_block_fill`
    body, explicit sequential carry) — the any-backend differential
    anchor for the kernel."""
    v2d, n = _pad_2d(x, block)
    block = min(block, v2d.shape[0])
    outs = []
    roll = lambda a, d, ax: jnp.roll(a, d, ax)  # noqa: E731
    carry = jnp.full((1, _LANES), HOLE, jnp.int32)
    for b in range(v2d.shape[0] // block):
        vb = v2d[b * block:(b + 1) * block]
        out = _block_fill(vb, block, roll)
        out = jnp.where(out == HOLE, carry, out)
        carry = _replicate_last_lane(out[block - 1:block, :], roll)
        outs.append(out)
    return jnp.concatenate(outs).reshape(-1)[:n]


#: default-on for the TPU backend once scripts/tpu_fill_bench.py has
#: validated the compiled kernel bitwise against the lax scan on chip
_TPU_VALIDATED = True


def fill_enabled() -> bool:
    """True when the kernel path should be used (TPU backend, or
    JT_PALLAS=1 forcing it; JT_PALLAS=0 forces the lax paths).  Callers
    branch their whole expansion strategy on this — the lax strategy
    (cummax + gathers) beats the lax LOCF scan on CPU, so the fallback
    is the legacy code, not `locf_lax`."""
    knob = os.environ.get("JT_PALLAS", "").strip()
    if knob == "0":
        return False
    if knob == "1":
        return True
    return _TPU_VALIDATED and jax.default_backend() == "tpu"


@jax.custom_batching.custom_vmap
def locf_flat(x: jnp.ndarray) -> jnp.ndarray:
    """Forward-fill holes (== -1) from the left; leading holes stay -1.

    TPU backend: single-pass Pallas kernel.  Elsewhere (or with
    JT_PALLAS=0): the O(log n) lax associative scan.  On seed arrays
    whose non-hole values are non-decreasing this is bitwise
    `lax.cummax` of the same array.
    """
    use = x.ndim == 1 and x.dtype == jnp.int32 and fill_enabled()
    if not use:
        return locf_lax(x)
    if os.environ.get("JT_PALLAS_EMULATE", "").strip() == "1":
        # tests: drive the whole kernel-branch integration (seeds,
        # hole-compat wheres, block math) on any backend through the
        # grid emulator; only kernel-vs-emulator equivalence remains
        # chip-gated
        return locf_blocked_reference(x)
    return locf_pallas(x)


@locf_flat.def_vmap
def _locf_flat_vmap(axis_size, in_batched, x):
    # per-row lax scan: exact, no cross-row carry to corrupt (the
    # sequential-carry kernel schedule is wrong under batching — same
    # hazard as pallas_scan.seg_or_auto, solved here by falling back)
    del axis_size, in_batched
    return jax.vmap(locf_lax)(x), True
