"""Pallas TPU kernel: single-pass segmented prefix-OR scan.

The chain-propagation op of the cycle sweep (`ops/cycle_sweep.py`
chain_pass) is a segmented prefix-OR over an (n, K) int8 label plane.
The lax fallbacks cost either ~2*log2(n) full-width HLO steps traced at
compile time (`associative_scan`) or log2(n) full HBM passes at runtime
(the Hillis-Steele `fori_loop`, `ops/segments.py`).  At 1M-txn shapes
(n = 2^21 chain rows, K = 128) that loop moves ~2 * n*K * log2(n) ≈
11 GB of HBM per chain pass, three passes per propagation round.

This kernel does the whole scan in ONE pass over HBM (read n*K + write
n*K ≈ 0.5 GB at the same shapes): TPU Pallas grids execute sequentially
on a core, so the running carry lives in VMEM scratch across grid steps —
each block loads (B, K) into VMEM, runs the in-block segmented
Hillis-Steele scan at VMEM bandwidth (log2(B) VPU steps), ORs in the
carry from the previous blocks, and writes the block back.

This is the Pallas equivalent of the reference's sequential-Java SCC
machinery hot op (SURVEY.md §2.5 #1: bifurcan `Graphs`), per the
BASELINE "Pallas parallel-SCC kernel" target: the sweep's other ops
(scatter-max relax, K×K closure matmuls) already lower well from lax
(PROFILE.md §3); the segmented chain scan is the one op where a custom
schedule beats XLA, so it is the one that gets a kernel.

Exactness: the block-scan math is shared verbatim between the kernel and
a pure-JAX grid emulator (`seg_or_blocked_reference`) that replicates the
sequential-grid + scratch-carry execution; the emulator is differential-
tested against the lax scans on adversarial layouts (`tests/
test_pallas.py`) on any backend, and the compiled kernel is differential-
tested against the emulator on the TPU backend itself (same file, gated).
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp

_BLOCK_ROWS = 2048  # (B, 128) int8 = 256 KB/buffer in VMEM


def _block_scan(v, starts, block: int, roll):
    """In-block segmented inclusive prefix-OR (Hillis-Steele), shared by
    the Pallas kernel (roll = pltpu.roll) and the grid emulator
    (roll = jnp.roll).

    v: (B, K) int32 values; starts: (B, 1) bool.  Returns (scan, seen):
      scan[i] = OR of v over [last start <= i (or block begin) .. i]
      seen[i] = a start lies in [0, i]        (decides carry absorption)
    """
    rows = jax.lax.broadcasted_iota(jnp.int32, (block, 1), 0)
    #   blocked[i] = a start lies in (i - dist, i] (rows before the block
    #   count as blocked, so scans never absorb across the block boundary)
    # flags are int32 0/1 lanes, not bool: Mosaic's dynamic_rotate has no
    # i1 support ("Rotate with non-32-bit data" on the real chip)
    blocked = starts.astype(jnp.int32)
    seen = starts.astype(jnp.int32)
    one = jnp.ones_like(blocked)
    zero = jnp.zeros_like(seen)
    dist = 1
    while dist < block:
        ok = rows >= dist
        v_p = roll(v, dist, 0)
        blk_p = roll(blocked, dist, 0)
        seen_p = roll(seen, dist, 0)
        take = ok & (blocked == 0)
        v = jnp.where(take, v | v_p, v)
        blocked = blocked | jnp.where(ok, blk_p, one)
        seen = seen | jnp.where(ok, seen_p, zero)
        dist *= 2
    return v, seen != 0


def _scan_kernel(block: int, v_ref, s_ref, o_ref, carry_ref):
    """One grid step: in-block segmented scan + carry absorb/update.

    v_ref: (B, K) int8 values; s_ref: (B, 1) int8 segment-start flags;
    o_ref: (B, K) int8 out; carry_ref: (8, K) int32 VMEM scratch, row 0 =
    running OR of the segment open at the end of the previous block
    (persists across sequential grid steps on TPU).
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b = pl.program_id(0)

    @pl.when(b == 0)
    def _():
        carry_ref[...] = jnp.zeros_like(carry_ref)

    v = v_ref[...].astype(jnp.int32)             # (B, K)
    starts = (s_ref[...] != 0)                   # (B, 1) bool
    scan, seen = _block_scan(
        v, starts, block, lambda x, d, ax: pltpu.roll(x, shift=d, axis=ax))
    carry = carry_ref[0:1, :]                    # (1, K) int32
    out = jnp.where(seen, scan, scan | carry)    # pre-first-start rows absorb
    carry_ref[0:1, :] = out[block - 1:block, :]
    o_ref[...] = out.astype(jnp.int8)


@functools.partial(jax.jit, static_argnames=("block",))
def _seg_or_pallas_padded(values: jnp.ndarray, starts_i8: jnp.ndarray,
                          block: int) -> jnp.ndarray:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n, k = values.shape
    return pl.pallas_call(
        functools.partial(_scan_kernel, block),
        grid=(n // block,),
        in_specs=[
            pl.BlockSpec((block, k), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block, 1), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((block, k), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((n, k), jnp.int8),
        scratch_shapes=[pltpu.VMEM((8, k), jnp.int32)],
    )(values, starts_i8)


def _pad_blocks(values, starts, block):
    from jepsen_tpu.checkers.elle.device_infer import pow2_at_least

    n, _ = values.shape
    block = min(block, pow2_at_least(max(n, 8)))
    n_pad = -n % block
    v = jnp.pad(values, ((0, n_pad), (0, 0))) if n_pad else values
    s = starts.astype(jnp.int8).reshape(-1, 1)
    s = jnp.pad(s, ((0, n_pad), (0, 0)), constant_values=1) if n_pad else s
    return v, s, block, n


def seg_or_pallas(values: jnp.ndarray, starts: jnp.ndarray,
                  block: int = _BLOCK_ROWS) -> jnp.ndarray:
    """Inclusive segmented prefix-OR of an (n, K) int8 plane on TPU.

    Pads rows to a block multiple (padding is sliced back off; carry only
    flows forward, so trailing pad rows cannot affect real rows).
    """
    v, s, block, n = _pad_blocks(values, starts, block)
    out = _seg_or_pallas_padded(v, s, block)
    return out[:n]


def seg_or_blocked_reference(values: jnp.ndarray, starts: jnp.ndarray,
                             block: int = _BLOCK_ROWS) -> jnp.ndarray:
    """Pure-JAX emulation of the kernel's execution: the same
    `_block_scan` body, driven block-by-block in Python with an explicit
    carry — exactly the sequential-grid + VMEM-scratch schedule.  The
    any-backend differential anchor for the kernel."""
    v, s, block, n = _pad_blocks(values, starts, block)
    outs = []
    carry = jnp.zeros((1, v.shape[1]), jnp.int32)
    for b in range(v.shape[0] // block):
        vb = v[b * block:(b + 1) * block].astype(jnp.int32)
        sb = s[b * block:(b + 1) * block] != 0
        scan, seen = _block_scan(vb, sb, block,
                                 lambda x, d, ax: jnp.roll(x, d, axis=ax))
        out = jnp.where(seen, scan, scan | carry)
        carry = out[block - 1:block, :]
        outs.append(out.astype(jnp.int8))
    return jnp.concatenate(outs)[:n]


#: default-on for the TPU backend: scripts/tpu_scan_bench.py validated
#: the compiled kernel bitwise against the lax scans on the real chip
#: (4 adversarial layouts + the 2^21-row bench shapes) and measured it
#: 28x faster than the loop scan (51 ms vs 1428 ms per chain pass at
#: (2^21, 128), 2026-07-30; PROFILE.md §2c)
_TPU_VALIDATED = True


def flatten_batch(values: jnp.ndarray, starts: jnp.ndarray):
    """Collapse a (B, n, K)/(B, n) batched scan input to one (B*n, K)
    scan with a forced segment start at each batch boundary.

    Exact: within the unbatched semantics row 0 of each history scans
    from nothing (there is no carry before it), which is precisely what
    a segment start at row g*n reproduces — so one flat scan equals B
    independent scans, and the sequential carry cannot leak across
    histories.
    """
    b, n, k = values.shape
    flat_v = values.reshape(b * n, k)
    flat_s = starts.reshape(b * n)
    boundary = (jnp.arange(b * n) % n) == 0
    return flat_v, flat_s | boundary


@jax.custom_batching.custom_vmap
def seg_or_auto(values: jnp.ndarray, starts: jnp.ndarray) -> jnp.ndarray:
    """`seg_or_pallas` with a batching rule.

    The default pallas_call batching rule prepends the vmap axis to the
    grid, which would turn `pl.program_id(0)` into the batch index and
    corrupt the sequential VMEM carry (re-zeroing it per block of batch
    element 0, leaking it across later elements) — and because the
    dispatch decision is traced into the jaxpr before an outer vmap
    applies (vmap-of-jit re-traces nothing at the Python level), no
    call-site guard can catch it.  This wrapper owns the batching
    instead: batched calls flatten to ONE long scan with forced segment
    boundaries (`flatten_batch`), which is exact and keeps the
    single-pass kernel schedule.
    """
    return seg_or_pallas(values, starts)


@seg_or_auto.def_vmap
def _seg_or_auto_vmap(axis_size, in_batched, values, starts):
    v_b, s_b = in_batched
    if not v_b:
        values = jnp.broadcast_to(values[None], (axis_size,) + values.shape)
    if not s_b:
        starts = jnp.broadcast_to(starts[None], (axis_size,) + starts.shape)
    b, n, k = values.shape
    flat_v, flat_s = flatten_batch(values, starts)
    out = seg_or_auto(flat_v, flat_s)  # recursive: nested vmap re-applies
    return out.reshape(b, n, k), True


def pallas_scan_enabled(values: jnp.ndarray) -> bool:
    """Use the kernel for 2D int8 planes on the TPU backend (JT_PALLAS=0
    forces the lax paths; JT_PALLAS=1 forces the kernel on, still
    TPU-compiled — there is no interpret fallback, see tests/
    test_pallas.py)."""
    knob = os.environ.get("JT_PALLAS", "").strip()
    if knob == "0":
        return False
    ok_shape = values.ndim == 2 and values.dtype == jnp.int8
    if knob == "1":
        return ok_shape
    return ok_shape and _TPU_VALIDATED and jax.default_backend() == "tpu"
