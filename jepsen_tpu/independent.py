"""Independent keys: lift a single-key workload over many keys.

Equivalent of the reference's `jepsen/src/jepsen/independent.clj`
(SURVEY.md §2.1): op values become ``(k, v)`` tuples;
:func:`sequential_generator` runs a fresh sub-generator per key in order;
:func:`concurrent_generator` splits the client threads into fixed groups of
`n`, each group working through its own queue of keys; and :func:`checker`
splits the history per key and checks each sub-history independently —
CPU Jepsen's main data-parallel axis, and on the TPU side the natural
`vmap`/batch axis (`jepsen_tpu.parallel.batch` consumes the same per-key
split).
"""

from __future__ import annotations

import math
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from .checkers import api as checker_api
from .generator import core as g
from .history.ops import History, Op


def tuple_(k, v) -> Tuple[Any, Any]:
    """An independent (key, value) pair (reference `independent/tuple`)."""
    return (k, v)


def is_tuple(v: Any) -> bool:
    return isinstance(v, tuple) and len(v) == 2


def _wrap_key(k, gen_spec) -> g.Generator:
    """Wrap every op of a sub-generator so value -> (k, value)."""
    return g.f_map(lambda op: dict(op, value=(k, op.get("value"))),
                   g.lift(gen_spec))


def sequential_generator(keys: Sequence[Any],
                         gen_fn: Callable[[Any], Any]) -> g.Generator:
    """One key at a time: exhaust gen_fn(k) before moving on (reference
    `independent/sequential-generator`)."""
    return g.lift([_wrap_key(k, gen_fn(k)) for k in keys])


class _GroupWorker(g.Generator):
    """One thread group's generator: works through keys from a shared
    queue, running gen_fn(k) to exhaustion for each."""

    def __init__(self, keys: List[Any], gen_fn: Callable[[Any], Any],
                 current: Optional[g.Generator] = None):
        self.keys = keys
        self.gen_fn = gen_fn
        self.current = current

    def _advance(self) -> Optional["_GroupWorker"]:
        if not self.keys:
            return None
        k = self.keys[0]
        return _GroupWorker(self.keys[1:], self.gen_fn,
                            _wrap_key(k, self.gen_fn(k)))

    def op(self, test, ctx):
        cur = self
        while True:
            if cur.current is None:
                cur = cur._advance()
                if cur is None:
                    return None
            res = g.next_op(cur.current, test, ctx)
            if res is None:
                cur = _GroupWorker(cur.keys, cur.gen_fn, None)
                continue
            op_, gen2 = res
            return (op_, _GroupWorker(cur.keys, cur.gen_fn, gen2))

    def update(self, test, ctx, event):
        if self.current is None:
            return self
        return _GroupWorker(self.keys, self.gen_fn,
                            g.gen_update(self.current, test, ctx, event))


def concurrent_generator(n: int, keys: Sequence[Any],
                         gen_fn: Callable[[Any], Any]) -> g.Generator:
    """Divide client threads into groups of `n`; groups run concurrently,
    each working its own share of `keys` sequentially (reference
    `independent/concurrent-generator`; requires concurrency % n == 0,
    checked at runtime by thread restriction)."""
    keys = list(keys)

    class _Concurrent(g.Generator):
        def __init__(self, inner: Optional[g.Generator] = None):
            self.inner = inner

        def _build(self, ctx) -> g.Generator:
            threads = sorted(t for t, _ in ctx.workers
                             if isinstance(t, int))
            if not threads:
                return g.lift([])
            n_groups = max(1, len(threads) // n)
            if len(threads) % n != 0:
                raise ValueError(
                    f"concurrent_generator: concurrency {len(threads)} "
                    f"not divisible by group size {n}")
            shard = math.ceil(len(keys) / n_groups)
            subs = []
            for gi in range(n_groups):
                lo, hi = gi * n, (gi + 1) * n
                group_keys = keys[gi * shard:(gi + 1) * shard]
                subs.append(g.on_threads(
                    (lambda lo=lo, hi=hi: lambda t: isinstance(t, int)
                     and threads[lo] <= t <= threads[hi - 1])(),
                    _GroupWorker(group_keys, gen_fn)))
            return g.any_gen(*subs)

        def op(self, test, ctx):
            inner = self.inner or self._build(ctx)
            res = g.next_op(inner, test, ctx)
            if res is None:
                return None
            op_, gen2 = res
            return (op_, _Concurrent(gen2))

        def update(self, test, ctx, event):
            if self.inner is None:
                return self
            return _Concurrent(g.gen_update(self.inner, test, ctx, event))

    return _Concurrent()


def subhistories(history) -> Dict[Any, History]:
    """Split a history on tuple values into per-key dense histories
    (reference `independent/history-keys` + per-key projection)."""
    by_key: Dict[Any, List[Op]] = {}
    for op in history:
        v = op.value
        if is_tuple(v):
            k, inner = v
            by_key.setdefault(k, []).append(op.with_(value=inner))
    return {k: History(ops, reindex=True) for k, ops in by_key.items()}


class IndependentChecker(checker_api.Checker):
    """Check each key's sub-history with its own checker instance; valid
    iff every key is valid (reference `independent/checker`)."""

    def __init__(self, checker_or_factory):
        import copy

        if callable(checker_or_factory) and not isinstance(
                checker_or_factory, checker_api.Checker):
            self.factory = checker_or_factory
        else:
            # fresh copy per key so stateful checkers can't leak state
            # across keys
            self.factory = lambda: copy.deepcopy(checker_or_factory)

    def check(self, test, history, opts=None):
        subs = subhistories(history)
        if not subs:
            return {"valid?": "unknown", "key-count": 0}
        results: Dict[Any, dict] = {}
        for k, h in sorted(subs.items(), key=lambda kv: repr(kv[0])):
            results[k] = checker_api.check_safe(self.factory(), test, h, opts)
        valids = [r.get("valid?") for r in results.values()]
        if all(v is True for v in valids):
            valid = True
        elif any(v is False for v in valids):
            valid = False
        else:
            valid = "unknown"
        failures = [k for k, r in results.items()
                    if r.get("valid?") is False]
        return {"valid?": valid, "key-count": len(subs),
                "failures": failures[:32],
                "results": {repr(k): r for k, r in results.items()}}


def checker(checker_or_factory) -> IndependentChecker:
    return IndependentChecker(checker_or_factory)
