"""Generative tests for the session-guarantee checker.

Reference pattern: elle's test.check generative suites (SURVEY.md §4) —
random histories from a model that satisfies the property by
construction, plus targeted injections that violate exactly one
guarantee, asserting the checker flags precisely that.

The simulator is a single-copy per-key register store: every write txn
reads the current version then writes a fresh one, so the inferred
version DAG is a chain per key and session reads of the live store are
trivially monotone.  Injections rewrite READS in read-only txns only, so
the inferred version DAG (built from read->write chains inside write
txns) is untouched and the violation is unambiguous.
"""

import random

from jepsen_tpu.checkers.elle import sessions
from jepsen_tpu.history import history, invoke, ok


def _simulate(seed, n_procs=4, n_keys=3, n_txns=60, causal_frac=0.0):
    """Returns a mutable txn list [(proc, mops)] where every session's
    reads are monotone by construction.  `causal_frac` of the write txns
    read a DIFFERENT key before writing — registering cross-key causal
    dependencies (round-5 WFR/MW cross-key rules) that a single-copy
    store satisfies by construction."""
    rng = random.Random(seed)
    cur = {k: None for k in range(n_keys)}  # live version per key
    next_v = [0]
    txns = []
    for _ in range(n_txns):
        proc = rng.randrange(n_procs)
        r = rng.random()
        if r < 0.5:
            # write txn: read current, install successor (chains the DAG)
            k = rng.randrange(n_keys)
            v = next_v[0]
            next_v[0] += 1
            if rng.random() < causal_frac and n_keys > 1:
                ka = rng.choice([x for x in range(n_keys) if x != k])
                txns.append((proc, [["r", ka, cur[ka]],
                                    ["r", k, cur[k]], ["w", k, v]]))
            else:
                txns.append((proc, [["r", k, cur[k]], ["w", k, v]]))
            cur[k] = v
        else:
            # read-only txn over 1-2 keys at the live versions
            ks = rng.sample(range(n_keys), rng.choice([1, 2]))
            txns.append((proc, [["r", k, cur[k]] for k in ks]))
    return txns


def _to_history(txns):
    ops = []
    for proc, mops in txns:
        ops.append(invoke(proc, "txn", [list(m) for m in mops]))
        ops.append(ok(proc, "txn", [list(m) for m in mops]))
    return history(ops)


def _read_only_reads(txns, proc):
    """(txn_pos, mop_pos, key, version) for reads in read-only txns."""
    out = []
    for i, (p, mops) in enumerate(txns):
        if p != proc or any(m[0] == "w" for m in mops):
            continue
        for j, m in enumerate(mops):
            if m[0] == "r":
                out.append((i, j, m[1], m[2]))
    return out


def test_valid_sessions_fuzz():
    for seed in range(25):
        res = sessions.check(_to_history(_simulate(seed)))
        assert res["valid?"] is True, (seed, res)


def test_valid_sessions_fuzz_with_causal_writes():
    """Cross-key dependency registration must not manufacture
    violations on a single-copy store."""
    for seed in range(25):
        res = sessions.check(
            _to_history(_simulate(seed, causal_frac=0.5)))
        assert res["valid?"] is True, (seed, res)


def test_monotonic_reads_injection_fuzz():
    injected = 0
    for seed in range(60):
        txns = _simulate(seed)
        # find a session with two read-only reads of the same key at
        # different written versions and swap them -> the later read
        # goes backwards in the (chain) version order
        done = False
        for proc in range(4):
            reads = _read_only_reads(txns, proc)
            for a in range(len(reads)):
                for b in range(a + 1, len(reads)):
                    ia, ja, ka, va = reads[a]
                    ib, jb, kb, vb = reads[b]
                    if ka == kb and va != vb and va is not None:
                        txns[ia][1][ja][2] = vb
                        txns[ib][1][jb][2] = va
                        done = True
                        break
                if done:
                    break
            if done:
                break
        if not done:
            continue
        injected += 1
        res = sessions.check(_to_history(txns))
        assert res["valid?"] is False, (seed, res)
        assert "monotonic-reads-violation" in res["anomaly-types"], \
            (seed, res)
        assert "monotonic-reads" in res["not"] + res["also-not"], res
    assert injected >= 30, f"only {injected} injectable cases"


def test_read_your_writes_injection_fuzz():
    injected = 0
    for seed in range(60):
        txns = _simulate(seed)
        # find a session write txn [r k prior, w k v] followed by a
        # read-only read of k in the same session; rewrite that read to
        # `prior` (a strict ancestor of v)
        done = False
        for proc in range(4):
            writes = []  # (txn_pos, key, prior_version)
            for i, (p, mops) in enumerate(txns):
                if p != proc:
                    continue
                for j in range(len(mops) - 1):
                    if mops[j][0] == "r" and mops[j + 1][0] == "w" and \
                            mops[j][1] == mops[j + 1][1] and \
                            mops[j][2] is not None:
                        writes.append((i, mops[j][1], mops[j][2]))
            for i, (p, mops) in enumerate(txns):
                if done or p != proc or any(m[0] == "w" for m in mops):
                    continue
                for wpos, wk, prior in writes:
                    if wpos < i:
                        for j, m in enumerate(mops):
                            if m[0] == "r" and m[1] == wk:
                                txns[i][1][j][2] = prior
                                done = True
                                break
                    if done:
                        break
            if done:
                break
        if not done:
            continue
        injected += 1
        res = sessions.check(_to_history(txns))
        assert res["valid?"] is False, (seed, res)
        assert "read-your-writes-violation" in res["anomaly-types"], \
            (seed, res)
    assert injected >= 30, f"only {injected} injectable cases"


def test_cross_key_wfr_injection_fuzz():
    """S1 read u(ka) then wrote v(kb); rewrite a later observer to read
    v(kb) and afterwards an ancestor of u on ka — cross-key WFR."""
    injected = 0
    for seed in range(80):
        txns = _simulate(seed, causal_frac=0.6)
        done = False
        # causal writes: (txn_pos, ka, u, kb, v) with a known u
        cws = []
        for i, (p, mops) in enumerate(txns):
            if len(mops) == 3 and mops[0][0] == "r" and \
                    mops[2][0] == "w" and mops[0][1] != mops[2][1] and \
                    mops[0][2] is not None:
                cws.append((i, p, mops[0][1], mops[0][2],
                            mops[2][1], mops[2][2]))
        for i1, p1, ka, u, kb, v in cws:
            if done:
                break
            for p2 in range(4):
                if p2 == p1 or done:
                    continue
                ro = [(i, j, m[1]) for i, j, m in (
                    (i, j, m) for i, (p, mops) in enumerate(txns)
                    if p == p2 and not any(x[0] == "w" for x in mops)
                    for j, m in enumerate(mops)) if i > i1]
                for a in range(len(ro)):
                    for b in range(a + 1, len(ro)):
                        i2, j2, k2 = ro[a]
                        i3, j3, k3 = ro[b]
                        if k2 == kb and k3 == ka and i3 > i2:
                            txns[i2][1][j2][2] = v
                            txns[i3][1][j3][2] = None  # INIT < u
                            done = True
                            break
                    if done:
                        break
        if not done:
            continue
        injected += 1
        res = sessions.check(_to_history(txns))
        assert res["valid?"] is False, (seed, res)
        assert "writes-follow-reads-violation" in res["anomaly-types"], \
            (seed, res)
    assert injected >= 20, f"only {injected} injectable cases"


def test_cross_key_mw_injection_fuzz():
    """S1 wrote v1(ka) then v2(kb); rewrite an observer to read v2(kb)
    then an ancestor of v1 on ka — cross-key MW."""
    injected = 0
    for seed in range(80):
        txns = _simulate(seed, causal_frac=0.3)
        done = False
        for p1 in range(4):
            if done:
                break
            # this session's writes in order: (txn_pos, key, val)
            ws = [(i, mops[-1][1], mops[-1][2])
                  for i, (p, mops) in enumerate(txns)
                  if p == p1 and mops[-1][0] == "w"]
            for a in range(len(ws)):
                for b in range(a + 1, len(ws)):
                    ia, ka, v1 = ws[a]
                    ib, kb, v2 = ws[b]
                    if ka == kb:
                        continue
                    for p2 in range(4):
                        if p2 == p1 or done:
                            continue
                        ro = [(i, j, m[1]) for i, (p, mops) in
                              enumerate(txns) if p == p2 and
                              not any(x[0] == "w" for x in mops)
                              for j, m in enumerate(mops) if i > ib]
                        for x in range(len(ro)):
                            for y in range(x + 1, len(ro)):
                                i2, j2, k2 = ro[x]
                                i3, j3, k3 = ro[y]
                                if k2 == kb and k3 == ka and i3 > i2:
                                    txns[i2][1][j2][2] = v2
                                    txns[i3][1][j3][2] = None
                                    done = True
                                    break
                            if done:
                                break
                    if done:
                        break
                if done:
                    break
        if not done:
            continue
        injected += 1
        res = sessions.check(_to_history(txns))
        assert res["valid?"] is False, (seed, res)
        assert "monotonic-writes-violation" in res["anomaly-types"], \
            (seed, res)
    assert injected >= 20, f"only {injected} injectable cases"
