"""Generative tests for the session-guarantee checker.

Reference pattern: elle's test.check generative suites (SURVEY.md §4) —
random histories from a model that satisfies the property by
construction, plus targeted injections that violate exactly one
guarantee, asserting the checker flags precisely that.

The simulator is a single-copy per-key register store: every write txn
reads the current version then writes a fresh one, so the inferred
version DAG is a chain per key and session reads of the live store are
trivially monotone.  Injections rewrite READS in read-only txns only, so
the inferred version DAG (built from read->write chains inside write
txns) is untouched and the violation is unambiguous.
"""

import random

from jepsen_tpu.checkers.elle import sessions
from jepsen_tpu.history import history, invoke, ok


def _simulate(seed, n_procs=4, n_keys=3, n_txns=60):
    """Returns a mutable txn list [(proc, mops)] where every session's
    reads are monotone by construction."""
    rng = random.Random(seed)
    cur = {k: None for k in range(n_keys)}  # live version per key
    next_v = [0]
    txns = []
    for _ in range(n_txns):
        proc = rng.randrange(n_procs)
        if rng.random() < 0.5:
            # write txn: read current, install successor (chains the DAG)
            k = rng.randrange(n_keys)
            v = next_v[0]
            next_v[0] += 1
            txns.append((proc, [["r", k, cur[k]], ["w", k, v]]))
            cur[k] = v
        else:
            # read-only txn over 1-2 keys at the live versions
            ks = rng.sample(range(n_keys), rng.choice([1, 2]))
            txns.append((proc, [["r", k, cur[k]] for k in ks]))
    return txns


def _to_history(txns):
    ops = []
    for proc, mops in txns:
        ops.append(invoke(proc, "txn", [list(m) for m in mops]))
        ops.append(ok(proc, "txn", [list(m) for m in mops]))
    return history(ops)


def _read_only_reads(txns, proc):
    """(txn_pos, mop_pos, key, version) for reads in read-only txns."""
    out = []
    for i, (p, mops) in enumerate(txns):
        if p != proc or any(m[0] == "w" for m in mops):
            continue
        for j, m in enumerate(mops):
            if m[0] == "r":
                out.append((i, j, m[1], m[2]))
    return out


def test_valid_sessions_fuzz():
    for seed in range(25):
        res = sessions.check(_to_history(_simulate(seed)))
        assert res["valid?"] is True, (seed, res)


def test_monotonic_reads_injection_fuzz():
    injected = 0
    for seed in range(60):
        txns = _simulate(seed)
        # find a session with two read-only reads of the same key at
        # different written versions and swap them -> the later read
        # goes backwards in the (chain) version order
        done = False
        for proc in range(4):
            reads = _read_only_reads(txns, proc)
            for a in range(len(reads)):
                for b in range(a + 1, len(reads)):
                    ia, ja, ka, va = reads[a]
                    ib, jb, kb, vb = reads[b]
                    if ka == kb and va != vb and va is not None:
                        txns[ia][1][ja][2] = vb
                        txns[ib][1][jb][2] = va
                        done = True
                        break
                if done:
                    break
            if done:
                break
        if not done:
            continue
        injected += 1
        res = sessions.check(_to_history(txns))
        assert res["valid?"] is False, (seed, res)
        assert "monotonic-reads-violation" in res["anomaly-types"], \
            (seed, res)
        assert "monotonic-reads" in res["not"] + res["also-not"], res
    assert injected >= 30, f"only {injected} injectable cases"


def test_read_your_writes_injection_fuzz():
    injected = 0
    for seed in range(60):
        txns = _simulate(seed)
        # find a session write txn [r k prior, w k v] followed by a
        # read-only read of k in the same session; rewrite that read to
        # `prior` (a strict ancestor of v)
        done = False
        for proc in range(4):
            writes = []  # (txn_pos, key, prior_version)
            for i, (p, mops) in enumerate(txns):
                if p != proc:
                    continue
                for j in range(len(mops) - 1):
                    if mops[j][0] == "r" and mops[j + 1][0] == "w" and \
                            mops[j][1] == mops[j + 1][1] and \
                            mops[j][2] is not None:
                        writes.append((i, mops[j][1], mops[j][2]))
            for i, (p, mops) in enumerate(txns):
                if done or p != proc or any(m[0] == "w" for m in mops):
                    continue
                for wpos, wk, prior in writes:
                    if wpos < i:
                        for j, m in enumerate(mops):
                            if m[0] == "r" and m[1] == wk:
                                txns[i][1][j][2] = prior
                                done = True
                                break
                    if done:
                        break
            if done:
                break
        if not done:
            continue
        injected += 1
        res = sessions.check(_to_history(txns))
        assert res["valid?"] is False, (seed, res)
        assert "read-your-writes-violation" in res["anomaly-types"], \
            (seed, res)
    assert injected >= 30, f"only {injected} injectable cases"
