"""Campaign subsystem tests (ISSUE 3): spec expansion determinism,
fleet scheduling, index resume, regression detection, CLI + web
surfaces, and the degraded/deadline verdict badges."""

import json
import os
import urllib.request

import pytest

from jepsen_tpu import campaign, cli, report, store, web
from jepsen_tpu.campaign import core as ccore
from jepsen_tpu.campaign.index import Index
from jepsen_tpu.campaign.plan import RunSpec, build_test, expand, load_spec
from jepsen_tpu.campaign.scheduler import DeviceSlots, Scheduler

SPEC = {
    "name": "t",
    "workloads": ["noop", "set"],
    "faults": [None, {"seed": 3, "p": 0.5, "kinds": "oom|xla"}],
    "seeds": [0, 1, 2],
    "opts": {"time-limit": 0.2, "concurrency": 2},
}


# ----------------------------------------------------------------- plan

def test_expand_deterministic_and_stable():
    a = expand(SPEC)
    b = expand(SPEC)
    assert [r.run_id for r in a] == [r.run_id for r in b]
    assert len(a) == 2 * 2 * 3
    assert len({r.run_id for r in a}) == 12  # all distinct
    # ids are stable across orthogonal spec edits (opts change -> new
    # ids; seed list extension keeps existing ids)
    wider = dict(SPEC, seeds=[0, 1, 2, 3])
    ids_wider = {r.run_id for r in expand(wider)}
    assert {r.run_id for r in a} < ids_wider


def test_expand_key_is_opts_independent():
    # the regression KEY survives opts tweaks (ids don't — they pin the
    # exact cell config)
    a = expand(SPEC)
    tweaked = dict(SPEC, opts={"time-limit": 9.9, "concurrency": 2})
    b = expand(tweaked)
    assert [r.key for r in a] == [r.key for r in b]
    assert [r.run_id for r in a] != [r.run_id for r in b]


def test_expand_device_classification():
    rs = expand({"name": "d", "workloads": ["append", "set"],
                 "seeds": [0]})
    by_wl = {r.workload: r for r in rs}
    assert by_wl["append"].device is True
    assert by_wl["set"].device is False


def test_expand_dedupes_aliasing_entries():
    # faults that all normalize to None (null/""/{}), duplicate seeds,
    # and duplicate workloads must collapse to ONE cell each — two
    # RunSpecs with identical run_ids would race in the store
    rs = expand({"name": "d", "workloads": ["noop", "noop"],
                 "faults": [None, "", {}], "seeds": [0, 0, 1]})
    assert len(rs) == 2  # 1 workload x 1 fault x 2 seeds
    assert len({r.run_id for r in rs}) == 2


def test_telemetric_thread_runs_serialized(tmp_path):
    """Two concurrent telemetric thread-executor runs would record
    each other's spans (the collector is process-global): the
    scheduler must never run two at once."""
    import threading
    import time as _t

    def mk(i):
        return RunSpec(run_id=f"r{i}", campaign="c", workload="w",
                       seed=i, workload_label="w",
                       opts={"telemetry": True})

    active = []
    worst = []
    lk = threading.Lock()

    def execute(rs):
        with lk:
            active.append(rs.run_id)
            worst.append(len(active))
        _t.sleep(0.03)
        with lk:
            active.remove(rs.run_id)
        return {"run": rs.run_id, "key": rs.key, "valid?": True}

    recs = Scheduler(3).run([mk(i) for i in range(4)], execute)
    assert len(recs) == 4
    assert max(worst) == 1


def test_telemetric_serialization_honors_env_optin(monkeypatch):
    """JEPSEN_TELEMETRY=1 makes EVERY core.run telemetric, so the token
    must engage even when the spec opts don't mention telemetry."""
    import threading
    import time as _t

    monkeypatch.setenv("JEPSEN_TELEMETRY", "1")
    active, worst, lk = [], [], threading.Lock()

    def mk(i):
        return RunSpec(run_id=f"r{i}", campaign="c", workload="w",
                       seed=i, workload_label="w")

    def execute(rs):
        with lk:
            active.append(1)
            worst.append(len(active))
        _t.sleep(0.03)
        with lk:
            active.pop()
        return {"run": rs.run_id, "key": rs.key, "valid?": True}

    Scheduler(3).run([mk(i) for i in range(4)], execute)
    assert max(worst) == 1


def test_op_shard_guard_not_nested():
    """The sharded sweep's fault site must fire ONCE per dispatch
    (site parallel.op-shard), not once per nesting level — nested
    guards would multiply retries and shift the deterministic fault
    schedule."""
    from jepsen_tpu.parallel.batch import make_mesh
    from jepsen_tpu.parallel.op_shard import check_sharded
    from jepsen_tpu.resilience import FaultPlan, RetryPolicy
    from jepsen_tpu.workloads import synth

    p = synth.packed_la_history(n_txns=48, n_keys=4, seed=2)
    plan = FaultPlan(at={0: "oom"})  # first dispatch faults, once
    r = check_sharded(p, mesh=make_mesh(2), plan=plan,
                      policy=RetryPolicy(max_attempts=2,
                                         base_delay_s=0.0))
    assert r["valid?"] is True
    assert plan.injected == [(0, "parallel.op-shard", "oom")]
    # exactly one guarded site saw the calls: the retry (call 1) plus
    # the grow loop's later dispatches all carry the op-shard label
    assert plan._n_calls >= 2


def test_load_spec_rejects_garbage(tmp_path):
    with pytest.raises(ValueError, match="workloads"):
        load_spec({"name": "x"})
    with pytest.raises(ValueError):
        load_spec({"workloads": [{"opts": {}}]})
    with pytest.raises(ValueError):  # unknown fault kind caught at plan time
        load_spec({"workloads": ["noop"],
                   "faults": [{"kinds": "frobnicate"}]})


def test_build_test_carries_fault_and_seed(tmp_path):
    rs = expand(dict(SPEC, workloads=["set"]))[3]  # faulted cell
    assert rs.fault is not None
    t = build_test(rs, str(tmp_path))
    assert t["faults"] == rs.fault
    assert t["seed"] == rs.seed
    assert t["campaign-run-id"] == rs.run_id
    assert t["store-dir"] == str(tmp_path)


# ------------------------------------------------------------ scheduler

def test_device_slots_serialize():
    import threading
    import time as _t

    slots = DeviceSlots(1)
    active = []
    worst = []

    def job():
        s = slots.acquire()
        active.append(s)
        worst.append(len(active))
        _t.sleep(0.02)
        active.remove(s)
        slots.release(s)

    ts = [threading.Thread(target=job) for _ in range(4)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert max(worst) == 1  # never two device runs at once


def test_scheduler_crash_becomes_attributable_record():
    rs = expand({"name": "c", "workloads": ["noop"], "seeds": [0]})[0]

    def boom(_):
        raise RuntimeError("kaboom")

    recs = Scheduler(1).run([rs], boom)
    assert len(recs) == 1
    assert recs[0]["valid?"] == "unknown"
    assert "kaboom" in recs[0]["error"]
    assert recs[0]["attempt"] == 2  # default policy retried once


def test_scheduler_retry_then_succeed():
    rs = expand({"name": "c", "workloads": ["noop"], "seeds": [0]})[0]
    calls = []

    def flaky(r):
        calls.append(1)
        if len(calls) < 2:
            raise RuntimeError("transient")
        return {"run": r.run_id, "key": r.key, "valid?": True}

    recs = Scheduler(1).run([rs], flaky)
    assert recs[0]["valid?"] is True and recs[0]["attempt"] == 2


def test_scheduler_host_runs_not_starved_by_device_queue():
    """A device run waiting for the (busy) slot must not wedge a
    worker: host-only runs queued behind it keep flowing."""
    import threading
    import time as _t

    def mk(i, device):
        return RunSpec(run_id=f"r{i}", campaign="c", workload="w",
                       seed=i, workload_label="w", device=device)

    release = threading.Event()
    done_at = {}

    def execute(rs):
        if rs.device:
            release.wait(5)
        done_at[rs.run_id] = _t.monotonic()
        return {"run": rs.run_id, "key": rs.key, "valid?": True}

    specs = [mk(0, True), mk(1, True), mk(2, False), mk(3, False)]
    t0 = _t.monotonic()
    sched = Scheduler(2, device_slots=1)
    t = threading.Thread(target=lambda: sched.run(specs, execute))
    t.start()
    # both host runs must finish while the device runs still hold/await
    # the single slot
    deadline = _t.monotonic() + 3
    while _t.monotonic() < deadline and \
            not {"r2", "r3"} <= set(done_at):
        _t.sleep(0.01)
    assert {"r2", "r3"} <= set(done_at), done_at
    assert "r0" not in done_at and "r1" not in done_at
    release.set()
    t.join(timeout=5)
    assert not t.is_alive()
    assert set(done_at) == {"r0", "r1", "r2", "r3"}


def test_campaign_thread_executor_retries_crashed_run(tmp_path):
    """execute_run crashes must reach the scheduler's retry loop (they
    are NOT absorbed into a record early): a run that fails once and
    then succeeds is indexed with its real verdict, attempt 2."""
    from jepsen_tpu.campaign.plan import register_workload

    calls = []

    def flaky_builder(opts):
        calls.append(1)
        if len(calls) < 2:
            raise RuntimeError("env flake")
        from jepsen_tpu import core as jcore

        return jcore.noop_test(name="flaky")

    register_workload("flaky", flaky_builder)
    try:
        summary = campaign.run_campaign(
            {"name": "fl", "workloads": ["flaky"], "seeds": [0]},
            str(tmp_path), workers=1)
    finally:
        from jepsen_tpu.campaign import plan as plan_mod

        plan_mod._EXTRA_WORKLOADS.pop("flaky", None)
    assert summary["counts"]["true"] == 1
    rec = Index(summary["index"]).records[0]
    assert rec["valid?"] is True and rec["attempt"] == 2


# ---------------------------------------------------------------- index

def test_index_torn_line_heals(tmp_path):
    p = str(tmp_path / "c.jsonl")
    idx = Index(p)
    idx.append({"run": "a", "key": "k", "valid?": True})
    idx.append({"run": "b", "key": "k2", "valid?": False})
    # crash mid-append: torn trailing bytes
    with open(p, "ab") as f:
        f.write(b'{"run": "c", "valid?"')
    size_torn = os.path.getsize(p)
    idx2 = Index(p)
    assert idx2.completed_ids() == {"a", "b"}
    # a read-only load must NOT touch the file — its "torn line" could
    # be a live writer's append in flight
    assert os.path.getsize(p) == size_torn
    # the WRITER heals on its next append: parseable ledger, no fusing
    idx2.append({"run": "c", "key": "k3", "valid?": True})
    assert Index(p).completed_ids() == {"a", "b", "c"}


def test_index_clean_load_never_arms_truncation(tmp_path):
    # a CLEAN ledger load must not arm the heal — a file that grows
    # after our read (concurrent writer) is not crash debris
    p = str(tmp_path / "c.jsonl")
    idx = Index(p)
    idx.append({"run": "a", "key": "k", "valid?": True})
    idx2 = Index(p)
    assert idx2._good_bytes is None
    # another writer lands a record between idx2's load and append
    idx.append({"run": "b", "key": "k2", "valid?": True})
    idx2.append({"run": "c", "key": "k3", "valid?": True})
    assert Index(p).completed_ids() == {"a", "b", "c"}  # nothing lost


def test_index_flip_reported_as_regression(tmp_path):
    idx = Index(str(tmp_path / "c.jsonl"))
    idx.append({"run": "r1", "key": "append|nofault|s2", "valid?": True,
                "gen": "g1"})
    idx.append({"run": "r1", "key": "append|nofault|s2", "valid?": False,
                "gen": "g2"})
    idx.append({"run": "r2", "key": "append|nofault|s3",
                "valid?": "unknown", "gen": "g1"})
    idx.append({"run": "r2", "key": "append|nofault|s3", "valid?": True,
                "gen": "g2"})
    flips = idx.flips()
    assert len(flips) == 2
    regs = idx.regressions()
    assert len(regs) == 1
    assert regs[0]["key"] == "append|nofault|s2"
    assert regs[0]["from"] is True and regs[0]["to"] is False
    # the rollup surfaces it
    txt = report.render_campaign({"campaign": "c", "total": 2,
                                  "counts": idx.verdict_counts(),
                                  "regressions": regs, "rows": [],
                                  "seeds": []})
    assert "REGRESSIONS" in txt and "append|nofault|s2" in txt


def test_index_span_stats_and_trend(tmp_path):
    idx = Index(str(tmp_path / "c.jsonl"))
    for gen, dur in (("g1", 1.0), ("g1", 2.0), ("g2", 4.0)):
        idx.append({"run": f"r-{gen}-{dur}", "key": "k", "valid?": True,
                    "gen": gen, "spans": {"check:append": dur}})
    st = idx.span_stats()["check:append"]
    assert st["count"] == 3 and st["min"] == 1.0 and st["max"] == 4.0
    trend = idx.span_trend("check:append")
    assert [g for g, _ in trend] == ["g1", "g2"]
    assert trend[1][1] == 4.0


# ----------------------------------------------- the fleet, end to end

@pytest.fixture(scope="module")
def campaign_store(tmp_path_factory):
    """One 12-run campaign (2 workloads x 2 fault plans x 3 seeds) run
    via the CLI on 2 workers — the ISSUE 3 acceptance fleet."""
    base = str(tmp_path_factory.mktemp("cstore"))
    spec_path = os.path.join(base, "spec.json")
    with open(spec_path, "w") as f:
        json.dump(SPEC, f)
    rc = cli.run(cli.single_test_cmd(lambda o: o),
                 ["--store-dir", base, "campaign", "run", spec_path,
                  "--workers", "2"])
    return base, spec_path, rc


def test_cli_campaign_completes_fully_indexed(campaign_store, capsys):
    base, spec_path, rc = campaign_store
    assert rc == 0
    idx = Index(ccore.index_path("t", base))
    specs = expand(SPEC)
    assert idx.completed_ids() == {r.run_id for r in specs}
    for rec in idx.records:  # every run attributable, never a crash
        assert rec["valid?"] in (True, False, "unknown")
        assert rec["dir"] is None or \
            os.path.isdir(os.path.join(base, rec["dir"]))


def test_cli_campaign_report_rollup(campaign_store, capsys):
    base, spec_path, _ = campaign_store
    rc = cli.run(cli.single_test_cmd(lambda o: o),
                 ["--store-dir", base, "campaign", "report", spec_path])
    out = capsys.readouterr().out
    assert rc == 0
    assert "campaign t — 12 runs" in out
    assert "no regressions" in out


def test_cli_campaign_resumes_instantly(campaign_store, capsys):
    base, spec_path, _ = campaign_store
    n_before = len(Index(ccore.index_path("t", base)).records)
    summary = campaign.run_campaign(SPEC, base, workers=2)
    assert summary["executed"] == 0
    assert summary["skipped"] == 12
    # 0 runs re-executed -> 0 new records
    assert len(Index(ccore.index_path("t", base)).records) == n_before


def test_campaign_kill_and_resume(tmp_path):
    """A campaign killed mid-flight (simulated: an index holding only a
    prefix of the records) resumes by executing ONLY the missing runs."""
    base = str(tmp_path)
    spec = dict(SPEC, name="kr", seeds=[0, 1])
    full = campaign.run_campaign(spec, base, workers=2)
    assert full["executed"] == 8
    path = ccore.index_path("kr", base)
    kept = Index(path).records[:3]  # "kill" after 3 runs landed
    with open(path, "w") as f:
        for r in kept:
            f.write(json.dumps(r) + "\n")
    resumed = campaign.run_campaign(spec, base, workers=2)
    assert resumed["skipped"] == 3
    assert resumed["executed"] == 5
    assert Index(path).completed_ids() == \
        {r.run_id for r in expand(spec)}


def test_campaign_interrupt_preserves_live_state(tmp_path, monkeypatch):
    """An interrupted run_campaign (Ctrl-C mid-fleet) must NOT mark the
    heartbeat finished — a killed campaign's live.json is the
    post-mortem naming exactly the cells that were in flight."""
    base = str(tmp_path)

    def interrupted(self, *a, **kw):
        self.heartbeat.worker("campaign-worker-0",
                              {"run": "r-inflight", "slot": 0})
        raise KeyboardInterrupt

    monkeypatch.setattr(Scheduler, "run", interrupted)
    with pytest.raises(KeyboardInterrupt):
        campaign.run_campaign(dict(SPEC, name="intr", seeds=[0]), base)
    doc = json.load(open(ccore.live_path("intr", base)))
    assert doc["finished"] is False
    assert "campaign-worker-0" in doc["workers"]
    assert doc["workers"]["campaign-worker-0"]["run"] == "r-inflight"


def test_campaign_status(campaign_store, capsys):
    base, spec_path, _ = campaign_store
    rc = cli.run(cli.single_test_cmd(lambda o: o),
                 ["--store-dir", base, "campaign", "status", spec_path])
    out = capsys.readouterr().out
    assert rc == 0
    assert "12 runs, 0 pending" in out


def test_campaign_bad_spec_clean_error(tmp_path, capsys):
    p = str(tmp_path / "bad.json")
    with open(p, "w") as f:
        f.write("{}")
    rc = cli.run(cli.single_test_cmd(lambda o: o),
                 ["campaign", "run", p])
    assert rc == 2
    assert "bad spec" in capsys.readouterr().err


def test_campaign_crashing_workload_indexed_unknown(tmp_path):
    from jepsen_tpu.campaign.plan import register_workload

    def bad_builder(opts):
        raise RuntimeError("builder exploded")

    register_workload("exploder", bad_builder)
    try:
        summary = campaign.run_campaign(
            {"name": "x", "workloads": ["exploder"], "seeds": [0]},
            str(tmp_path), workers=1)
    finally:
        from jepsen_tpu.campaign import plan as plan_mod

        plan_mod._EXTRA_WORKLOADS.pop("exploder", None)
    assert summary["counts"]["unknown"] == 1
    rec = Index(summary["index"]).records[0]
    assert "builder exploded" in rec["error"]


def test_result_flags_nested():
    flags = ccore.result_flags({
        "valid?": "unknown",
        "sub": {"valid?": "unknown", "error": "deadline-exceeded"},
        "other": {"valid?": True, "degraded": "host-fallback"},
    })
    assert flags["deadline"] is True
    assert flags["degraded"] == "host-fallback"
    assert flags["error"] == "deadline-exceeded"


def test_bench_emits_campaign_spec(tmp_path):
    import bench

    p = str(tmp_path / "ladder.json")
    spec = bench.emit_campaign_spec(p, sizes=[100, 200])
    # the emitted file is a valid, expandable campaign spec
    rs = expand(p)
    assert len(rs) == 2
    assert {r.workload_label for r in rs} == {"la-100", "la-200"}
    assert all(r.device for r in rs)
    assert all(r.opts["telemetry"] for r in rs)


def test_campaign_append_device_runs_with_degradation(tmp_path):
    """Seeded noop_test/append campaign on 2 workers (the satellite
    fleet): the append cells run the device elle pipeline; the faulted
    plan is PERSISTENT at the infer seam, so those runs must degrade to
    the host oracle — and the index must say so (degraded attribution,
    same verdicts)."""
    spec = {
        "name": "dev",
        "workloads": ["noop", "append"],
        "faults": [None, {"label": "kill-infer",
                          "spec": {"persistent": ["elle.infer"]}}],
        "seeds": [0, 1],
        "opts": {"time-limit": 0.2, "concurrency": 2},
    }
    summary = campaign.run_campaign(spec, str(tmp_path), workers=2)
    assert summary["executed"] == 8
    c = summary["counts"]
    assert c["true"] == 8  # tiny mem-cluster histories are all valid
    assert c["degraded"] == 2  # both faulted append cells fell back
    idx = Index(summary["index"])
    degraded = [r for r in idx.records if r.get("degraded")]
    assert {r["fault"] for r in degraded} == {"kill-infer"}
    assert all(r["workload"] == "append" for r in degraded)
    assert all(r["degraded"] == "host-fallback" for r in degraded)
    # the rollup marks them with the ·h flag
    assert "ok·h" in report.render_campaign(summary)


# ------------------------------------------------------------------ web

@pytest.fixture(scope="module")
def served_campaign(campaign_store):
    base, _, _ = campaign_store
    srv = web.serve(port=0, base=base, background=True)
    yield base, srv.server_address[1]
    srv.shutdown()
    srv.server_close()


def _get(port, path):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}") as r:
        return r.status, r.read().decode()


def test_web_campaign_dashboard(served_campaign):
    base, port = served_campaign
    status, body = _get(port, "/campaigns")
    assert status == 200 and ">t<" in body
    status, body = _get(port, "/campaign/t")
    assert status == 200
    # the grid: both workloads, both fault labels, a seed column per seed
    assert "noop" in body and "set" in body and "nofault" in body
    assert "<th>s0</th>" in body and "<th>s2</th>" in body
    assert body.count("b-true") >= 12
    # index page links to campaigns
    status, body = _get(port, "/")
    assert status == 200 and 'href="/campaigns"' in body


def test_web_deadline_and_degraded_badges(tmp_path):
    """The satellite contract: unknown+deadline-exceeded and
    host-fallback degraded runs render as DISTINCT badges on the index
    and the run page."""
    base = str(tmp_path)
    d1 = os.path.join(base, "dl-run", "20260101T000000.000Z")
    os.makedirs(d1)
    with open(os.path.join(d1, "results.json"), "w") as f:
        json.dump({"valid?": "unknown", "error": "deadline-exceeded"}, f)
    d2 = os.path.join(base, "deg-run", "20260101T000001.000Z")
    os.makedirs(d2)
    with open(os.path.join(d2, "results.json"), "w") as f:
        json.dump({"valid?": True,
                   "append": {"valid?": True,
                              "degraded": "host-fallback"}}, f)
    srv = web.serve(port=0, base=base, background=True)
    try:
        port = srv.server_address[1]
        status, body = _get(port, "/")
        assert status == 200
        assert "b-deadline" in body and "deadline" in body
        assert "b-degraded" in body and "host-fallback" in body
        # run pages carry the same badges
        _, run1 = _get(port, "/run/dl-run/20260101T000000.000Z")
        assert "b-deadline" in run1
        _, run2 = _get(port, "/run/deg-run/20260101T000001.000Z")
        assert "b-degraded" in run2 and "host-fallback" in run2
    finally:
        srv.shutdown()
        srv.server_close()


def test_web_campaign_regression_highlighted(tmp_path):
    base = str(tmp_path)
    idx = Index(os.path.join(base, "campaigns", "r.jsonl"))
    idx.append({"run": "r1", "key": "append|nofault|s0",
                "workload": "append", "fault": "nofault", "seed": 0,
                "valid?": True, "gen": "g1"})
    idx.append({"run": "r1", "key": "append|nofault|s0",
                "workload": "append", "fault": "nofault", "seed": 0,
                "valid?": False, "gen": "g2"})
    srv = web.serve(port=0, base=base, background=True)
    try:
        port = srv.server_address[1]
        _, body = _get(port, "/campaign/r")
        assert "regressions" in body
        assert "append|nofault|s0" in body
        assert "b-false" in body  # latest verdict shown in the grid
    finally:
        srv.shutdown()
        srv.server_close()


# ------------------------------------------- subprocess executor (slow)

@pytest.mark.slow
def test_campaign_subprocess_executor(tmp_path):
    """One noop run through the real `python -m
    jepsen_tpu.campaign.runner` isolation path."""
    os.environ.setdefault("JT_FORCE_CPU", "1")
    spec = {"name": "sub", "workloads": ["noop"], "seeds": [0]}
    summary = campaign.run_campaign(spec, str(tmp_path), workers=1,
                                    executor="subprocess",
                                    run_deadline_s=120)
    assert summary["counts"]["true"] == 1


# ------------------------------------- checker-span perf gate (ISSUE 12)

def test_perf_gate_over_checker_spans_two_generations(tmp_path):
    """The CI sharding-regression gate: a small list-append + bank
    campaign run for TWO generations, `cli obs gate` evaluated over the
    real ``check:list-append`` / ``check:bank`` spans — then a
    synthesized +60% generation must trip rc 1, so a genuine slowdown
    of the (sharded-by-default) checking path fails the suite
    deterministically instead of depending on ambient timing."""
    import time as _time

    base = str(tmp_path)
    spec = {
        "name": "perfgate",
        "workloads": [
            {"name": "append", "label": "la",
             "opts": {"ops": 120, "time-limit": None}},
            {"name": "bank", "label": "bank",
             "opts": {"ops": 120, "time-limit": None}},
        ],
        "faults": [None],
        "seeds": [0, 1, 2, 3, 4, 5],
        "opts": {"telemetry": True, "concurrency": 2,
                 "checker-time-limit": 60},
    }
    s1 = campaign.run_campaign(spec, base, workers=2)
    assert s1["counts"].get("true") == 12
    _time.sleep(1.1)  # generations are second-resolution timestamps
    s2 = campaign.run_campaign(spec, base, workers=2, rerun=True)
    assert s2["counts"].get("true") == 12

    disp = cli.single_test_cmd(lambda o: {})
    argv = ["--store-dir", base]
    assert cli.run(disp, argv + ["obs", "ingest"]) == 0
    for span in ("check:list-append", "check:bank"):
        rc = cli.run(disp, argv + ["obs", "gate", "--campaign",
                                   "perfgate", "--span", span,
                                   "--min-runs", "3"])
        # two identical back-to-back generations: a real verdict (0
        # expected; 1 tolerated under ambient load), never rc 2
        assert rc in (0, 1), (span, rc)

    # synthesize a +60% generation from the REAL gen-2 records: the
    # gate must flag it for both checker spans (rc 1, deterministic).
    # Durations come from the generation MAX per span, not each
    # record's own values — real cross-run spread on ms-scale spans
    # can exceed the 1.6x factor, and a slow record built from a fast
    # run's values would not stochastically dominate the old
    # generation (Mann-Whitney would not trip).
    idx = Index(ccore.index_path("perfgate", base))
    last_gen = idx.records[-1]["gen"]
    slow = [dict(r) for r in idx.records if r.get("gen") == last_gen]
    peak = {}
    base_mean = {}
    phase_mean = {}
    for r in slow:
        for k, v in (r.get("spans") or {}).items():
            peak[k] = max(peak.get(k, 0.0), v)
            base_mean.setdefault(k, []).append(v)
        for k, ph in (r.get("phases") or {}).items():
            for b, v in ph.items():
                phase_mean.setdefault(k, {}).setdefault(b, []).append(v)
    base_mean = {k: sum(v) / len(v) for k, v in base_mean.items()}
    phase_mean = {k: {b: sum(v) / len(v) for b, v in ph.items()}
                  for k, ph in phase_mean.items()}
    for i, r in enumerate(slow):
        r["run"] = f"slow-{i}"
        r["gen"] = "zslow"
        spans = {k: round(v * 1.6 + i * 1e-6, 6)
                 for k, v in peak.items()}
        r["spans"] = spans
        # compile-heavy composition (ISSUE 16): 90% of each span's
        # delta vs the old generation's mean lands in compile_s, so
        # the forensics diff must attribute the regression there
        r["phases"] = {
            k: {"compile_s": round(
                    phase_mean.get(k, {}).get("compile_s", 0.0)
                    + 0.9 * (spans[k] - base_mean[k]), 6),
                "execute_s": round(
                    phase_mean.get(k, {}).get("execute_s", 0.0)
                    + 0.1 * (spans[k] - base_mean[k]), 6)}
            for k in spans}
        r["counters"] = {"compile-cache-miss{site=checker}": 40.0 + i}
        idx.append(r)
    assert cli.run(disp, argv + ["obs", "ingest"]) == 0
    for span in ("check:list-append", "check:bank"):
        rc = cli.run(disp, argv + ["obs", "gate", "--campaign",
                                   "perfgate", "--span", span,
                                   "--min-runs", "3"])
        assert rc == 1, (span, rc)
    # satellite 1: one gate invocation over repeated --span flags and
    # globs — rc is the worst single-span verdict (regression here)
    rc = cli.run(disp, argv + ["obs", "gate", "--campaign", "perfgate",
                               "--span", "check:*",
                               "--span", "check:bank",
                               "--min-runs", "3"])
    assert rc == 1, rc

    # ISSUE 16 forensics: `obs diff` must attribute the synthesized
    # compile-heavy regression to compile_s (>= half the delta), name
    # the compile-cache-miss counter delta, and exit deterministically
    # (rc 1 — never 2 on real data)
    out_path = os.path.join(base, "diff.json")
    rc = cli.run(disp, argv + ["obs", "diff", "perfgate",
                               "--min-runs", "3", "--json", out_path])
    assert rc == 1, rc
    with open(out_path) as f:
        rep = json.load(f)
    assert rep["status"] == "regression"
    assert rep["to-gen"] == "zslow"
    by_span = {e["span"]: e for e in rep["spans"]}
    for span in ("check:list-append", "check:bank"):
        e = by_span[span]
        assert e["status"] == "regression", e
        assert e["dominant"] == "compile_s", e
        comp = next(p for p in e["phases"]
                    if p["bucket"] == "compile_s")
        assert comp["share"] >= 0.5, comp
        assert any(c["name"].startswith("compile-cache-miss")
                   and c["delta"] > 0
                   for c in e["counters"]), e["counters"]

    # backend parity: the warehouse fast path and the raw jsonl scan
    # must feed forensics the identical record shape (same verdict)
    p = ccore.index_path("perfgate", base)
    assert Index(p).forensic_records() == \
        Index(p, use_warehouse=False).forensic_records()


def test_perf_gate_applies_to_live_verifier_sweep_span(tmp_path):
    """ISSUE 13 satellite: live-checked cells (in-proc verifier) land
    their ``verifier.sweep`` spans in the run records, so `cli obs
    gate` regression-gates the batched sweep path exactly like a
    checker span — rc 0/1 on real data (never 2/inapplicable), rc 1
    deterministically on a synthesized +60% generation."""
    import time as _time

    base = str(tmp_path)
    spec = {
        "name": "sweepgate", "workloads": ["append"],
        "seeds": [0, 1, 2],
        "opts": {"telemetry": True, "ops": 100, "time-limit": None,
                 "concurrency": 2, "live-check": {"inproc": True}},
    }
    s1 = campaign.run_campaign(spec, base, workers=2)
    assert s1["counts"].get("true") == 3
    _time.sleep(1.1)  # generations are second-resolution timestamps
    s2 = campaign.run_campaign(spec, base, workers=2, rerun=True)
    assert s2["counts"].get("true") == 3

    disp = cli.single_test_cmd(lambda o: {})
    argv = ["--store-dir", base]
    assert cli.run(disp, argv + ["obs", "ingest"]) == 0
    rc = cli.run(disp, argv + ["obs", "gate", "--campaign",
                               "sweepgate", "--span", "verifier.sweep",
                               "--min-runs", "3"])
    assert rc in (0, 1), rc
    idx = Index(ccore.index_path("sweepgate", base))
    assert all("verifier.sweep" in (r.get("spans") or {})
               for r in idx.records)
    last_gen = idx.records[-1]["gen"]
    slow = [dict(r) for r in idx.records if r.get("gen") == last_gen]
    # generation MAX per span (same reasoning as the perfgate test):
    # ms-scale sweep spans spread more than 1.6x across runs, and the
    # synthesized generation must stochastically dominate for rc 1 to
    # be deterministic
    peak = {}
    for r in slow:
        for k, v in (r.get("spans") or {}).items():
            peak[k] = max(peak.get(k, 0.0), v)
    for i, r in enumerate(slow):
        r["run"] = f"slow-{i}"
        r["gen"] = "zslow"
        r["spans"] = {k: round(v * 1.6 + i * 1e-6, 6)
                      for k, v in peak.items()}
        idx.append(r)
    assert cli.run(disp, argv + ["obs", "ingest"]) == 0
    rc = cli.run(disp, argv + ["obs", "gate", "--campaign",
                               "sweepgate", "--span", "verifier.sweep",
                               "--min-runs", "3"])
    assert rc == 1, rc
