"""Toy raft suite: replication, elections, partitions, membership, and
the end-to-end leave/rejoin-under-partition test the membership nemesis
exists for (VERDICT r03 item 7).  The stale-read mode proves the checker
catches a real distributed consistency bug end-to-end.
"""

import pytest

from jepsen_tpu import core
from jepsen_tpu.dbs import toyraft as tr
from jepsen_tpu.generator import core as g
from jepsen_tpu.nemesis import core as nem
from jepsen_tpu.nemesis import membership as mem

NODES = ["n1", "n2", "n3", "n4", "n5"]


def mk_cluster(**kw):
    return tr.ToyRaftCluster(NODES, **kw)


# ------------------------------------------------------------ cluster unit

def test_replication_and_read():
    c = mk_cluster()
    st, out = c.submit_txn([["append", "x", 1]])
    assert st == "ok"
    st, out = c.submit_txn([["append", "x", 2], ["r", "x", None]])
    assert st == "ok"
    assert out[1] == ["r", "x", [1, 2]]


def test_no_quorum_fails_clean():
    c = mk_cluster()
    # 2/2/1 split: nobody has a majority
    for a in ("n1", "n2"):
        for b in ("n3", "n4", "n5"):
            c.block(a, b)
    for a in ("n3", "n4"):
        for b in ("n5",):
            c.block(a, b)
    st, why = c.submit_txn([["append", "x", 1]])
    assert st == "fail" and why == "no-quorum"


def test_partial_replication_is_info_then_commits_after_heal():
    c = mk_cluster()
    st, _ = c.submit_txn([["append", "x", 1]])
    assert st == "ok"
    leader = c.leader
    # cut the leader off from everyone: entry lands only in its own log
    for b in NODES:
        if b != leader:
            c.block(leader, b)
    st, why = c.submit_txn([["append", "x", 2]])
    # the old leader can't commit; a new quorum elects a leader without
    # the entry, or routing finds no quorum path through the old leader
    assert st in ("info", "ok", "fail")
    c.heal()
    st2, out = c.submit_txn([["r", "x", None]])
    assert st2 == "ok"
    lst = out[0][2]
    # committed history must be a consistent prefix: 1 always present
    assert lst[0] == 1


def test_leader_kill_failover_and_restart_catchup():
    c = mk_cluster()
    c.submit_txn([["append", "x", 1]])
    dead = c.leader
    c.kill(dead)
    st, out = c.submit_txn([["append", "x", 2], ["r", "x", None]])
    assert st == "ok"
    assert out[1][2] == [1, 2]
    assert c.leader != dead
    c.start(dead)
    c.submit_txn([["append", "x", 3]])
    # the restarted node catches up through replication
    st, out = c.submit_txn([["r", "x", None]])
    assert out[0][2] == [1, 2, 3]
    assert c.nodes[dead].state.get("x") == [1, 2, 3]


def test_membership_change_and_quorum_shift():
    c = mk_cluster()
    st, _ = c.submit_config(["n1", "n2", "n3"])
    assert st == "ok"
    # with a 3-node config, n4/n5 don't count: partition them away and
    # the cluster still commits
    for a in ("n4", "n5"):
        for b in ("n1", "n2", "n3"):
            c.block(a, b)
    st, out = c.submit_txn([["append", "x", 9], ["r", "x", None]])
    assert st == "ok"
    assert out[1][2] == [9]


# ------------------------------------------------- membership nemesis unit

def sim_test(db):
    from jepsen_tpu.control.sim import SimRemote

    return {"nodes": NODES, "remote": SimRemote(), "db": db}


def test_membership_nemesis_ok_completion_and_view_log():
    db = tr.ToyRaftDB()
    t = sim_test(db)
    db.setup(t, "n1")
    state = tr.ToyRaftMembers(db)
    n = mem.MembershipNemesis(state, converge_timeout_s=5,
                              poll_interval_s=0.01).setup(t)
    comp = n.invoke(t, {"type": "invoke", "f": "leave-node", "value": "n5"})
    assert comp["type"] == "ok"          # resolved against the view: ok
    assert comp["value"]["converged"] is True
    assert comp["value"]["view-index"] >= 1
    assert n.view == ["n1", "n2", "n3", "n4"]
    comp = n.invoke(t, {"type": "invoke", "f": "join-node", "value": "n5"})
    assert comp["type"] == "ok"
    assert n.view == NODES
    # the view log recorded each distinct view in order
    views = [e["view"] for e in n.view_log]
    assert views == [NODES, ["n1", "n2", "n3", "n4"], NODES]


def test_membership_nemesis_no_quorum_is_clean_fail():
    db = tr.ToyRaftDB()
    t = sim_test(db)
    db.setup(t, "n1")
    state = tr.ToyRaftMembers(db)
    n = mem.MembershipNemesis(state, converge_timeout_s=0.05,
                              poll_interval_s=0.01).setup(t)
    # total partition: no quorum -> the change definitely never started,
    # so the completion is fail and nothing joins the pending set
    cluster = db.cluster
    for a in NODES:
        for b in NODES:
            if a != b:
                cluster.block(a, b)
    comp = n.invoke(t, {"type": "invoke", "f": "leave-node", "value": "n5"})
    assert comp["type"] == "fail"
    assert n.pending == []
    cluster.heal()
    comp2 = n.invoke(t, {"type": "invoke", "f": "join-node",
                         "value": "n5"})
    # n5 never left, so the join resolves against the unchanged view
    assert comp2["type"] == "ok"


def test_membership_nemesis_pending_resolves_later():
    """An applied-but-unresolved change times out as info, stays
    pending, and is reported in also-resolved by a later invocation."""

    class SlowState(mem.MembershipState):
        def __init__(self):
            self.resolved = False

        def node_view(self, test, node):
            return ["n1", "n2"] if self.resolved else ["n1"]

        def possible_ops(self, test, view):
            return []

        def apply_op(self, test, op):
            return {"status": "applied"}

        def resolve_op(self, test, op, result, view):
            return view == ["n1", "n2"]

    st = SlowState()
    t = {"nodes": ["n1"]}
    n = mem.MembershipNemesis(st, converge_timeout_s=0.05,
                              poll_interval_s=0.01).setup(t)
    comp = n.invoke(t, {"type": "invoke", "f": "join-node", "value": "n2"})
    assert comp["type"] == "info"
    assert comp["value"]["pending"] is True
    assert len(n.pending) == 1
    st.resolved = True
    comp2 = n.invoke(t, {"type": "invoke", "f": "join-node", "value": "n2"})
    assert comp2["type"] == "ok"
    # the earlier, timed-out op resolved during this invocation
    assert comp2["value"]["also-resolved"], comp2
    assert n.pending == []


# ----------------------------------------------------------- e2e spine

def _opts(tmp_path):
    return {"store-dir": str(tmp_path / "store"), "concurrency": 5,
            "nodes": NODES}


def test_toyraft_append_valid(tmp_path):
    t = tr.append_test(_opts(tmp_path))
    t["generator"] = g.limit(150, t["generator"])
    done = core.run(t)
    res = done["results"]
    assert res["valid?"] is True, res
    oks = [op for op in done["history"] if op.type == "ok" and
           op.f == "txn"]
    assert len(oks) >= 100


def test_toyraft_leave_rejoin_under_partition_exact(tmp_path):
    """The VERDICT r03 item-7 integration: a node leaves and rejoins
    while a partition is up; the checker verdict stays exact and valid."""
    t = tr.append_test(_opts(tmp_path))
    db = t["db"]
    members = tr.ToyRaftMembers(db)
    t["nemesis"] = nem.compose({
        frozenset({"start-partition", "stop-partition"}): nem.partitioner(),
        frozenset({"leave-node", "join-node"}):
            mem.MembershipNemesis(members, converge_timeout_s=5,
                                  poll_interval_s=0.01),
    })
    grudge = nem.complete_grudge([["n1", "n2", "n3"], ["n4", "n5"]])
    nem_seq = [
        g.sleep(0.05),
        {"type": "invoke", "f": "start-partition", "value": grudge},
        g.sleep(0.1),
        {"type": "invoke", "f": "leave-node", "value": "n5"},
        g.sleep(0.1),
        {"type": "invoke", "f": "stop-partition"},
        g.sleep(0.05),
        {"type": "invoke", "f": "join-node", "value": "n5"},
        g.sleep(0.05),
    ]
    t["generator"] = g.any_gen(g.limit(250, t["generator"]),
                               g.nemesis(nem_seq))
    done = core.run(t)
    res = done["results"]
    assert res["valid?"] is True, res
    # the membership ops really ran and resolved
    mem_ops = [op for op in done["history"]
               if op.f in ("leave-node", "join-node")]
    assert any(op.type == "ok" for op in mem_ops), \
        [(op.f, op.type) for op in mem_ops]
    # real client commits happened on both sides of the churn
    oks = [op for op in done["history"] if op.type == "ok" and
           op.f == "txn"]
    assert len(oks) >= 100


def test_toyraft_stale_reads_caught(tmp_path):
    """stale_reads mode: reads served from a partitioned replica without
    quorum — the checker must find realtime anomalies."""
    from jepsen_tpu.workloads import append as append_wl

    opts = _opts(tmp_path)
    opts["consistency-models"] = ("strict-serializable",)
    t = tr.append_test(opts, stale_reads=True)
    db = t["db"]

    class IsolateN5(nem.Nemesis):
        def invoke(self, test, op):
            c = db.cluster
            for b in NODES:
                if b != "n5":
                    c.block("n5", b)
                    c.block(b, "n5")
            return dict(op, type="info", value="n5 isolated")

    t["nemesis"] = IsolateN5()
    # ONE stateful txn generator across phases keeps append values unique
    # max_writes_per_key high enough that keys 0-2 never rotate out —
    # the stale reads target exactly those keys.  read_frac > 0 matters:
    # fresh (linearizable, through-the-log) reads on the majority side
    # pin the version order PAST the stale prefix, which is what gives
    # the stale read its rw successor edge (no observed successor = no
    # inferable anti-dependency, and the anomaly would be invisible)
    writes = append_wl.gen(read_frac=0.3, key_count=3,
                           max_writes_per_key=100_000)
    # stagger the stale reads so they overlap committed majority writes
    # in realtime (a read strictly after a missed write's completion is
    # what makes the anomaly realtime-visible)
    reads = g.stagger(0.02, g.limit(10, lambda test, ctx: {
        "f": "txn", "value": [("r", k, None) for k in range(3)]}))
    t["generator"] = g.phases(
        # replicate some state everywhere
        g.limit(40, g.clients(writes)),
        g.nemesis([{"type": "invoke", "f": "isolate"}]),
        # new writes commit on the majority; thread 4 (bound to n5)
        # reads the frozen replica without quorum
        g.any_gen(g.limit(60, g.clients(writes)),
                  g.on_threads(lambda th: th == 4, reads)),
    )
    done = core.run(t)
    res = done["results"]
    # reads from the isolated replica violate realtime: must NOT be valid
    assert res["valid?"] is False, res
