"""Oracle list-append checker tests: one micro-history per anomaly,
mirroring the reference's elle/list_append_test.clj strategy (SURVEY.md §4).
"""

import pytest

from jepsen_tpu.checkers.elle import oracle
from jepsen_tpu.history import history, invoke, ok, fail, info
from jepsen_tpu.workloads import synth


def txn_pair(process, mops_inv, mops_ok, t0=0):
    return [
        invoke(process, "txn", mops_inv),
        ok(process, "txn", mops_ok),
    ]


def seq_history(*txns):
    """Sequential (non-overlapping) history: txn i fully before txn i+1."""
    ops = []
    for i, (mops_inv, mops_ok) in enumerate(txns):
        ops.append(invoke(i % 5, "txn", mops_inv))
        if mops_ok == "fail":
            ops.append(fail(i % 5, "txn", mops_inv))
        elif mops_ok == "info":
            ops.append(info(i % 5, "txn", None))
        else:
            ops.append(ok(i % 5, "txn", mops_ok))
    return history(ops)


def concurrent_history(*txns):
    """All txns invoke first, then all complete (no realtime edges)."""
    inv, comp = [], []
    for i, (mops_inv, mops_ok) in enumerate(txns):
        inv.append(invoke(i, "txn", mops_inv))
        if mops_ok == "fail":
            comp.append(fail(i, "txn", mops_inv))
        else:
            comp.append(ok(i, "txn", mops_ok))
    return history(inv + comp)


def test_valid_sequential():
    h = seq_history(
        ([["append", "x", 1]], [["append", "x", 1]]),
        ([["r", "x", None]], [["r", "x", [1]]]),
        ([["append", "x", 2]], [["append", "x", 2]]),
        ([["r", "x", None]], [["r", "x", [1, 2]]]),
    )
    res = oracle.check(h, ["strict-serializable"])
    assert res["valid?"] is True
    assert res["anomaly-types"] == []


def test_g1a_aborted_read():
    h = seq_history(
        ([["append", "x", 1]], "fail"),
        ([["r", "x", None]], [["r", "x", [1]]]),
    )
    res = oracle.check(h, ["serializable"])
    assert res["valid?"] is False
    assert "G1a" in res["anomaly-types"]
    assert "read-committed" in res["not"] + res["also-not"]


def test_g1b_intermediate_read():
    h = concurrent_history(
        ([["append", "x", 1], ["append", "x", 2]],
         [["append", "x", 1], ["append", "x", 2]]),
        ([["r", "x", None]], [["r", "x", [1]]]),
    )
    res = oracle.check(h, ["serializable"])
    assert res["valid?"] is False
    assert "G1b" in res["anomaly-types"]


def test_internal_inconsistency():
    h = seq_history(
        ([["append", "x", 5], ["r", "x", None]],
         [["append", "x", 5], ["r", "x", [5, 7]]]),
    )
    res = oracle.check(h, ["serializable"])
    assert "internal" in res["anomaly-types"]


def test_duplicate_elements():
    h = seq_history(
        ([["append", "x", 1]], [["append", "x", 1]]),
        ([["r", "x", None]], [["r", "x", [1, 1]]]),
    )
    res = oracle.check(h, ["serializable"])
    assert "duplicate-elements" in res["anomaly-types"]


def test_incompatible_order():
    h = concurrent_history(
        ([["r", "x", None]], [["r", "x", [1, 2]]]),
        ([["r", "x", None]], [["r", "x", [2, 1]]]),
    )
    res = oracle.check(h, ["serializable"])
    assert "incompatible-order" in res["anomaly-types"]


def test_dirty_update():
    h = concurrent_history(
        ([["append", "x", 1]], "fail"),
        ([["append", "x", 2]], [["append", "x", 2]]),
        ([["r", "x", None]], [["r", "x", [1, 2]]]),
    )
    res = oracle.check(h, ["serializable"])
    assert "dirty-update" in res["anomaly-types"]
    assert "G1a" in res["anomaly-types"]  # reading 1 is also an aborted read


def test_g0_write_cycle():
    # ww cycle via interleaved version orders on two keys
    h = concurrent_history(
        ([["append", "k", 1], ["append", "j", 20]],
         [["append", "k", 1], ["append", "j", 20]]),
        ([["append", "k", 2], ["append", "j", 10]],
         [["append", "k", 2], ["append", "j", 10]]),
        ([["r", "k", None], ["r", "j", None]],
         [["r", "k", [1, 2]], ["r", "j", [10, 20]]]),
    )
    res = oracle.check(h, ["read-uncommitted"])
    assert res["valid?"] is False
    assert "G0" in res["anomaly-types"]


def test_g1c_wr_cycle():
    h = concurrent_history(
        ([["append", "x", 1], ["r", "y", None]],
         [["append", "x", 1], ["r", "y", [9]]]),
        ([["append", "y", 9], ["r", "x", None]],
         [["append", "y", 9], ["r", "x", [1]]]),
    )
    res = oracle.check(h, ["read-committed"])
    assert res["valid?"] is False
    assert "G1c" in res["anomaly-types"]
    nodes = set()
    for step in res["anomalies"]["G1c"][0]["cycle"]:
        nodes.add(step["src"])
        nodes.add(step["dst"])
    assert len(nodes) == 2


def test_g_single():
    # T0 -ww-> T1 (k versions), T1 -rw-> T0 (T1 read j=[] missing T0's append)
    h = concurrent_history(
        ([["append", "k", 1], ["append", "j", 10]],
         [["append", "k", 1], ["append", "j", 10]]),
        ([["append", "k", 2], ["r", "j", None]],
         [["append", "k", 2], ["r", "j", []]]),
        ([["r", "k", None], ["r", "j", None]],
         [["r", "k", [1, 2]], ["r", "j", [10]]]),
    )
    res = oracle.check(h, ["snapshot-isolation"])
    assert res["valid?"] is False
    assert "G-single" in res["anomaly-types"]
    assert "G2-item" not in res["anomaly-types"]  # not searched for SI
    # under serializable, the same cycle also matches G2-item
    res2 = oracle.check(h, ["serializable"])
    assert "G-single" in res2["anomaly-types"]
    assert "G2-item" in res2["anomaly-types"]


def test_g2_item_write_skew():
    # classic write skew: two rw edges, adjacent -> G2-item but not G-single
    h = concurrent_history(
        ([["r", "x", None], ["append", "y", 10]],
         [["r", "x", []], ["append", "y", 10]]),
        ([["r", "y", None], ["append", "x", 1]],
         [["r", "y", []], ["append", "x", 1]]),
        ([["r", "x", None], ["r", "y", None]],
         [["r", "x", [1]], ["r", "y", [10]]]),
    )
    res = oracle.check(h, ["serializable"])
    assert res["valid?"] is False
    assert "G2-item" in res["anomaly-types"]
    assert "G-single" not in res["anomaly-types"]
    # snapshot isolation permits write skew: SI check stays valid
    res_si = oracle.check(h, ["snapshot-isolation"])
    assert res_si["valid?"] is True


def test_realtime_cycle_strict_only():
    # T0 reads T1's append but completed before T1 invoked:
    # wr T1->T0 + realtime T0->T1 cycle. Strict-serializable invalid,
    # plain serializable valid.
    h = history([
        invoke(0, "txn", [["r", "x", None]]),
        ok(0, "txn", [["r", "x", [1]]]),
        invoke(1, "txn", [["append", "x", 1]]),
        ok(1, "txn", [["append", "x", 1]]),
    ])
    res = oracle.check(h, ["strict-serializable"])
    assert res["valid?"] is False
    assert "G1c-realtime" in res["anomaly-types"]
    res2 = oracle.check(h, ["serializable"])
    assert res2["valid?"] is True


def test_info_txn_writes_count():
    # an info (indeterminate) txn's append observed by a read is fine,
    # and participates in the graph without G1a
    h = seq_history(
        ([["append", "x", 1]], "info"),
        ([["r", "x", None]], [["r", "x", [1]]]),
    )
    res = oracle.check(h, ["serializable"])
    assert res["valid?"] is True
    assert "G1a" not in res["anomaly-types"]


def test_empty_history_unknown():
    res = oracle.check(history([]), ["serializable"])
    assert res["valid?"] == "unknown"


# -- synthetic generator round-trips ---------------------------------------


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_synth_valid(seed):
    h = synth.la_history(n_txns=150, n_keys=6, concurrency=5,
                         fail_prob=0.05, info_prob=0.05, seed=seed)
    res = oracle.check(h, ["strict-serializable"])
    assert res["valid?"] is True, res


def test_synth_inject_g1a():
    h = synth.la_history(n_txns=150, n_keys=6, concurrency=5, seed=3)
    assert synth.inject_g1a(h)
    res = oracle.check(h, ["serializable"])
    assert res["valid?"] is False
    assert "G1a" in res["anomaly-types"]


def test_synth_inject_wr_cycle():
    h = synth.la_history(n_txns=150, n_keys=6, concurrency=5, seed=4)
    assert synth.inject_wr_cycle(h)
    res = oracle.check(h, ["read-committed"])
    assert res["valid?"] is False
    assert "G1c" in res["anomaly-types"]


def test_synth_inject_rw_cycle():
    h = synth.la_history(n_txns=150, n_keys=6, concurrency=5, seed=5)
    assert synth.inject_rw_cycle(h)
    res = oracle.check(h, ["serializable"])
    assert res["valid?"] is False
    assert ("G2-item" in res["anomaly-types"]
            or "G-single" in res["anomaly-types"])


def test_packed_generator_valid():
    p = synth.packed_la_history(n_txns=2000, n_keys=20, seed=7)
    res = oracle.check(p, ["serializable"])
    assert res["valid?"] is True, res["anomaly-types"]


# -- regressions from code review ------------------------------------------


def test_no_false_g_nonadjacent_on_single_rw_cycle():
    # a single-rw (G-single) cycle must NOT be reported as G-nonadjacent:
    # non-simple closed walks don't count (Adya cycles are simple)
    h = concurrent_history(
        ([["append", "k", 1], ["append", "j", 10]],
         [["append", "k", 1], ["append", "j", 10]]),
        ([["append", "k", 2], ["r", "j", None]],
         [["append", "k", 2], ["r", "j", []]]),
        ([["r", "k", None], ["r", "j", None]],
         [["r", "k", [1, 2]], ["r", "j", [10]]]),
    )
    res = oracle.check(h, ["serializable"])
    assert "G-single" in res["anomaly-types"]
    assert "G-nonadjacent" not in res["anomaly-types"]


def test_raw_op_list_gets_indexed():
    # passing a raw op list (indices unset) must behave like history():
    # realtime edges depend on positions
    ops = [
        invoke(0, "txn", [["r", "x", None]]),
        ok(0, "txn", [["r", "x", [1]]]),
        invoke(1, "txn", [["append", "x", 1]]),
        ok(1, "txn", [["append", "x", 1]]),
    ]
    res = oracle.check(ops, ["strict-serializable"])
    assert res["valid?"] is False
    assert "G1c-realtime" in res["anomaly-types"]
