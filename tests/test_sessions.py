"""Session-guarantee checker tests (monotonic reads/writes, RYW, WFR)."""

from jepsen_tpu.checkers.elle import sessions
from jepsen_tpu.history import history, invoke, ok


def seq(*txns):
    """Sequential history: each (process, invoked-mops, ok-mops) txn
    completes before the next invokes (session order = list order)."""
    ops = []
    for p, mi, mo in txns:
        ops.append(invoke(p, "txn", mi))
        ops.append(ok(p, "txn", mo))
    return history(ops)


# version chain for key x: INIT -> 1 -> 2, built by process 0's txns
CHAIN = [
    (0, [["r", "x", None], ["w", "x", 1]],
        [["r", "x", None], ["w", "x", 1]]),
    (0, [["r", "x", None], ["w", "x", 2]],
        [["r", "x", 1], ["w", "x", 2]]),
]


def test_valid_session_history():
    h = seq(*CHAIN,
            (1, [["r", "x", None]], [["r", "x", 1]]),
            (1, [["r", "x", None]], [["r", "x", 2]]))
    res = sessions.check(h)
    assert res["valid?"] is True, res


def test_monotonic_reads_violation():
    h = seq(*CHAIN,
            (1, [["r", "x", None]], [["r", "x", 2]]),
            (1, [["r", "x", None]], [["r", "x", 1]]))  # went backwards
    res = sessions.check(h)
    assert res["valid?"] is False
    assert res["anomaly-types"] == ["monotonic-reads-violation"]
    assert res["not"] == ["monotonic-reads"]
    assert "PRAM" in res["also-not"]


def test_read_backwards_to_nil_is_monotonic_reads():
    h = seq(*CHAIN,
            (1, [["r", "x", None]], [["r", "x", 1]]),
            (1, [["r", "x", None]], [["r", "x", None]]))  # back to init
    res = sessions.check(h)
    assert "monotonic-reads-violation" in res["anomaly-types"]


def test_read_your_writes_violation():
    h = seq(CHAIN[0],
            (1, [["r", "x", None], ["w", "x", 2]],
                [["r", "x", 1], ["w", "x", 2]]),   # proc 1 installs 2
            (1, [["r", "x", None]], [["r", "x", 1]]))  # then reads 1
    res = sessions.check(h)
    assert "read-your-writes-violation" in res["anomaly-types"]
    assert "read-your-writes" in res["not"]


def test_monotonic_writes_violation():
    h = seq(*CHAIN,
            (1, [["w", "x", 2]], [["w", "x", 2]]),   # blind write 2
            (1, [["w", "x", 1]], [["w", "x", 1]]))   # then 1 (1 < 2)
    res = sessions.check(h)
    assert "monotonic-writes-violation" in res["anomaly-types"]


def test_writes_follow_reads_violation():
    h = seq(*CHAIN,
            (1, [["r", "x", None]], [["r", "x", 2]]),  # read 2
            (1, [["w", "x", 1]], [["w", "x", 1]]))     # then write 1 < 2
    res = sessions.check(h)
    assert "writes-follow-reads-violation" in res["anomaly-types"]


def test_incomparable_versions_no_false_positive():
    # two blind writes: versions 5 and 6 are incomparable — reading one
    # then the other is NOT a definite violation
    h = seq((0, [["w", "x", 5]], [["w", "x", 5]]),
            (0, [["w", "y", 6]], [["w", "y", 6]]),
            (1, [["r", "x", None]], [["r", "x", 5]]),
            (1, [["r", "x", None]], [["r", "x", 5]]))
    res = sessions.check(h)
    assert res["valid?"] is True, res


def test_indeterminate_txns_excluded():
    from jepsen_tpu.history import info as info_op

    ops = [invoke(0, "txn", [["w", "x", 1]]),
           info_op(0, "txn", [["w", "x", 1]]),
           invoke(1, "txn", [["r", "x", None]]),
           ok(1, "txn", [["r", "x", None]])]
    res = sessions.check(history(ops))
    assert res["valid?"] is True


def test_guarantee_selection():
    h = seq(*CHAIN,
            (1, [["r", "x", None]], [["r", "x", 2]]),
            (1, [["r", "x", None]], [["r", "x", 1]]))
    res = sessions.check(h, guarantees=("monotonic-writes",))
    assert res["valid?"] is True  # MR not requested


# ---- cross-key WFR / MW (round 5, VERDICT r04 item 8) ----------------


def test_cross_key_wfr_rw_register():
    """S1 read u(k1) then wrote v(k2); S2 observed v then read k1 older
    than u — v applied before the write it depends on."""
    from jepsen_tpu.checkers.elle import sessions
    from jepsen_tpu.history import history, invoke, ok

    h = history([
        invoke(0, "txn", [["w", "a", 1]]),
        ok(0, "txn", [["w", "a", 1]]),
        invoke(1, "txn", [["r", "a", None], ["w", "b", 9]]),
        ok(1, "txn", [["r", "a", 1], ["w", "b", 9]]),
        invoke(2, "txn", [["r", "b", None]]),
        ok(2, "txn", [["r", "b", 9]]),
        invoke(2, "txn", [["r", "a", None]]),
        ok(2, "txn", [["r", "a", None]]),  # INIT: older than u=1
    ])
    res = sessions.check(h, guarantees=("writes-follow-reads",))
    assert "writes-follow-reads-violation" in res["anomaly-types"], res
    item = res["anomalies"]["writes-follow-reads-violation"][0]
    assert "cross-key-dependency" in item, item


def test_cross_key_mw_rw_register():
    """S1 wrote w1(k1) then v(k2); S2 observed v then read k1 older
    than w1 — S1's writes applied out of session order."""
    from jepsen_tpu.checkers.elle import sessions
    from jepsen_tpu.history import history, invoke, ok

    h = history([
        invoke(1, "txn", [["w", "a", 7]]),
        ok(1, "txn", [["w", "a", 7]]),
        invoke(1, "txn", [["w", "b", 9]]),
        ok(1, "txn", [["w", "b", 9]]),
        invoke(2, "txn", [["r", "b", None]]),
        ok(2, "txn", [["r", "b", 9]]),
        invoke(2, "txn", [["r", "a", None]]),
        ok(2, "txn", [["r", "a", None]]),  # INIT: older than 7
    ])
    res = sessions.check(h, guarantees=("monotonic-writes",))
    assert "monotonic-writes-violation" in res["anomaly-types"], res


def test_cross_key_wfr_list_append():
    from jepsen_tpu.checkers.elle import sessions
    from jepsen_tpu.history import history, invoke, ok

    h = history([
        invoke(0, "txn", [["append", "a", 1]]),
        ok(0, "txn", [["append", "a", 1]]),
        invoke(1, "txn", [["r", "a", None], ["append", "b", 9]]),
        ok(1, "txn", [["r", "a", [1]], ["append", "b", 9]]),
        invoke(2, "txn", [["r", "b", None]]),
        ok(2, "txn", [["r", "b", [9]]]),
        invoke(2, "txn", [["r", "a", None]]),
        ok(2, "txn", [["r", "a", []]]),  # shorter than S1's read
    ])
    res = sessions.check_la(h, guarantees=("writes-follow-reads",))
    assert "writes-follow-reads-violation" in res["anomaly-types"], res


def test_cross_key_mw_list_append():
    from jepsen_tpu.checkers.elle import sessions
    from jepsen_tpu.history import history, invoke, ok

    h = history([
        invoke(1, "txn", [["append", "a", 7]]),
        ok(1, "txn", [["append", "a", 7]]),
        invoke(1, "txn", [["append", "b", 9]]),
        ok(1, "txn", [["append", "b", 9]]),
        invoke(2, "txn", [["r", "b", None]]),
        ok(2, "txn", [["r", "b", [9]]]),
        invoke(2, "txn", [["r", "a", None]]),
        ok(2, "txn", [["r", "a", []]]),  # missing S1's append 7
    ])
    res = sessions.check_la(h, guarantees=("monotonic-writes",))
    assert "monotonic-writes-violation" in res["anomaly-types"], res


def test_cross_key_no_false_positive_on_causal_history():
    """A session that reads the dependency key at or past the required
    version stays clean."""
    from jepsen_tpu.checkers.elle import sessions
    from jepsen_tpu.history import history, invoke, ok

    h = history([
        invoke(0, "txn", [["w", "a", 1]]),
        ok(0, "txn", [["w", "a", 1]]),
        invoke(1, "txn", [["r", "a", None], ["w", "b", 9]]),
        ok(1, "txn", [["r", "a", 1], ["w", "b", 9]]),
        invoke(2, "txn", [["r", "b", None]]),
        ok(2, "txn", [["r", "b", 9]]),
        invoke(2, "txn", [["r", "a", None]]),
        ok(2, "txn", [["r", "a", 1]]),  # exactly u: fine
    ])
    res = sessions.check(h)
    assert res["valid?"] is True, res
