"""Session-guarantee checker tests (monotonic reads/writes, RYW, WFR)."""

from jepsen_tpu.checkers.elle import sessions
from jepsen_tpu.history import history, invoke, ok


def seq(*txns):
    """Sequential history: each (process, invoked-mops, ok-mops) txn
    completes before the next invokes (session order = list order)."""
    ops = []
    for p, mi, mo in txns:
        ops.append(invoke(p, "txn", mi))
        ops.append(ok(p, "txn", mo))
    return history(ops)


# version chain for key x: INIT -> 1 -> 2, built by process 0's txns
CHAIN = [
    (0, [["r", "x", None], ["w", "x", 1]],
        [["r", "x", None], ["w", "x", 1]]),
    (0, [["r", "x", None], ["w", "x", 2]],
        [["r", "x", 1], ["w", "x", 2]]),
]


def test_valid_session_history():
    h = seq(*CHAIN,
            (1, [["r", "x", None]], [["r", "x", 1]]),
            (1, [["r", "x", None]], [["r", "x", 2]]))
    res = sessions.check(h)
    assert res["valid?"] is True, res


def test_monotonic_reads_violation():
    h = seq(*CHAIN,
            (1, [["r", "x", None]], [["r", "x", 2]]),
            (1, [["r", "x", None]], [["r", "x", 1]]))  # went backwards
    res = sessions.check(h)
    assert res["valid?"] is False
    assert res["anomaly-types"] == ["monotonic-reads-violation"]
    assert res["not"] == ["monotonic-reads"]
    assert "PRAM" in res["also-not"]


def test_read_backwards_to_nil_is_monotonic_reads():
    h = seq(*CHAIN,
            (1, [["r", "x", None]], [["r", "x", 1]]),
            (1, [["r", "x", None]], [["r", "x", None]]))  # back to init
    res = sessions.check(h)
    assert "monotonic-reads-violation" in res["anomaly-types"]


def test_read_your_writes_violation():
    h = seq(CHAIN[0],
            (1, [["r", "x", None], ["w", "x", 2]],
                [["r", "x", 1], ["w", "x", 2]]),   # proc 1 installs 2
            (1, [["r", "x", None]], [["r", "x", 1]]))  # then reads 1
    res = sessions.check(h)
    assert "read-your-writes-violation" in res["anomaly-types"]
    assert "read-your-writes" in res["not"]


def test_monotonic_writes_violation():
    h = seq(*CHAIN,
            (1, [["w", "x", 2]], [["w", "x", 2]]),   # blind write 2
            (1, [["w", "x", 1]], [["w", "x", 1]]))   # then 1 (1 < 2)
    res = sessions.check(h)
    assert "monotonic-writes-violation" in res["anomaly-types"]


def test_writes_follow_reads_violation():
    h = seq(*CHAIN,
            (1, [["r", "x", None]], [["r", "x", 2]]),  # read 2
            (1, [["w", "x", 1]], [["w", "x", 1]]))     # then write 1 < 2
    res = sessions.check(h)
    assert "writes-follow-reads-violation" in res["anomaly-types"]


def test_incomparable_versions_no_false_positive():
    # two blind writes: versions 5 and 6 are incomparable — reading one
    # then the other is NOT a definite violation
    h = seq((0, [["w", "x", 5]], [["w", "x", 5]]),
            (0, [["w", "y", 6]], [["w", "y", 6]]),
            (1, [["r", "x", None]], [["r", "x", 5]]),
            (1, [["r", "x", None]], [["r", "x", 5]]))
    res = sessions.check(h)
    assert res["valid?"] is True, res


def test_indeterminate_txns_excluded():
    from jepsen_tpu.history import info as info_op

    ops = [invoke(0, "txn", [["w", "x", 1]]),
           info_op(0, "txn", [["w", "x", 1]]),
           invoke(1, "txn", [["r", "x", None]]),
           ok(1, "txn", [["r", "x", None]])]
    res = sessions.check(history(ops))
    assert res["valid?"] is True


def test_guarantee_selection():
    h = seq(*CHAIN,
            (1, [["r", "x", None]], [["r", "x", 2]]),
            (1, [["r", "x", None]], [["r", "x", 1]]))
    res = sessions.check(h, guarantees=("monotonic-writes",))
    assert res["valid?"] is True  # MR not requested
