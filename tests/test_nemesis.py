"""Nemesis core, net, and db protocol tests over sim/loopback remotes."""

import random

import pytest

from jepsen_tpu import control, db as db_, net as net_
from jepsen_tpu.control.local import LoopbackRemote
from jepsen_tpu.control.sim import SimRemote
from jepsen_tpu.nemesis import (Noop, bridge, complete_grudge, compose,
                                majorities_ring, partition_halves,
                                partition_random_halves,
                                partition_random_node, partitioner)

NODES = ["n1", "n2", "n3", "n4", "n5"]


def sim_test(**extra):
    t = {"nodes": list(NODES), "remote": SimRemote(),
         "net": net_.SimNet()}
    t.update(extra)
    return t


# ---------------------------------------------------------------- grudges

def test_complete_grudge():
    g = complete_grudge([["n1", "n2"], ["n3", "n4", "n5"]])
    assert g["n1"] == {"n3", "n4", "n5"}
    assert g["n3"] == {"n1", "n2"}


def test_bridge():
    g = bridge(NODES)
    # n3 is the bridge: blocks nothing, nobody blocks it
    assert g["n3"] == set()
    assert g["n1"] == {"n4", "n5"}
    assert g["n4"] == {"n1", "n2"}
    for n in ("n1", "n2", "n4", "n5"):
        assert "n3" not in g[n]


def test_majorities_ring():
    rng = random.Random(5)
    g = majorities_ring(NODES, rng=rng)
    # every node sees a majority (itself + 2 neighbors of 5)
    for n in NODES:
        visible = set(NODES) - g[n]
        assert n in visible
        assert len(visible) >= 3


def test_partition_halves():
    g = partition_halves(["a", "b", "c", "d"])
    assert g["a"] == {"c", "d"} and g["c"] == {"a", "b"}


def test_partition_random_node_isolates_one():
    g = partition_random_node(NODES, rng=random.Random(1))
    isolated = [n for n in NODES if len(g[n]) == len(NODES) - 1]
    assert len(isolated) == 1


# ---------------------------------------------------------------- partitioner

def test_partitioner_applies_and_heals():
    t = sim_test()
    nem = partitioner(partition_random_halves).setup(t)
    comp = nem.invoke(t, {"f": "start-partition", "value": None,
                          "type": "invoke"})
    assert comp["type"] == "info"
    net = t["net"]
    assert net.blocked, "partition applied"
    comp2 = nem.invoke(t, {"f": "stop-partition", "value": None,
                           "type": "invoke"})
    assert comp2["value"] == "network healed"
    assert not net.blocked


def test_partitioner_iptables_cmds():
    t = {"nodes": ["n1", "n2"], "remote": SimRemote(),
         "net": net_.IptablesNet()}
    nem = partitioner(lambda nodes: {"n1": {"n2"}, "n2": {"n1"}}).setup(t)
    nem.invoke(t, {"f": "start-partition", "value": None, "type": "invoke"})
    cmds = t["remote"].all_cmds()
    assert any("iptables -A INPUT -s n2 -j DROP" in c for c in cmds["n1"])
    assert any("iptables -A INPUT -s n1 -j DROP" in c for c in cmds["n2"])
    nem.invoke(t, {"f": "stop-partition", "value": None, "type": "invoke"})
    assert any("iptables -F" in c for c in cmds["n1"] +
               t["remote"].node("n1").cmds())


def test_netem_shaping_cmds():
    t = {"nodes": ["n1"], "remote": SimRemote(), "net": net_.IptablesNet()}
    t["net"].slow(t, mean_ms=100.0, variance_ms=5.0)
    cmds = t["remote"].node("n1").cmds()
    assert any("tc qdisc replace dev eth0 root netem delay 100.0ms" in c
               for c in cmds)
    t["net"].fast(t)
    assert any("tc qdisc del" in c for c in t["remote"].node("n1").cmds())


# ---------------------------------------------------------------- compose

def test_compose_routes_and_raises():
    t = sim_test()
    seen = []

    class Rec(Noop):
        def __init__(self, name):
            self.nm = name

        def invoke(self, test, op):
            seen.append((self.nm, op["f"]))
            return dict(op, type="info")

    nem = compose({("start-partition", "stop-partition"): Rec("part"),
                   ("kill",): Rec("kill")}).setup(t)
    nem.invoke(t, {"f": "kill", "type": "invoke", "value": None})
    nem.invoke(t, {"f": "start-partition", "type": "invoke", "value": None})
    assert seen == [("kill", "kill"), ("part", "start-partition")]
    with pytest.raises(ValueError):
        nem.invoke(t, {"f": "mystery", "type": "invoke", "value": None})


# ---------------------------------------------------------------- db facets

class FakeDB(db_.DB, db_.LogFiles, db_.Primary):
    def __init__(self):
        self.events = []

    def setup(self, test, node):
        self.events.append(("setup", node))

    def teardown(self, test, node):
        self.events.append(("teardown", node))

    def log_files(self, test, node):
        return ["db.log"]

    def primaries(self, test):
        return [test["nodes"][0]]


def test_db_facets():
    d = FakeDB()
    assert db_.supports(d, db_.LogFiles)
    assert db_.supports(d, db_.Primary)
    assert not db_.supports(d, db_.Pause)
    assert db_.supports(db_.noop, db_.DB)


def test_process_db_lifecycle(tmp_path):
    t = {"nodes": ["n1"], "remote": LoopbackRemote(base_dir=str(tmp_path))}
    d = db_.ProcessDB("sleep", ["60"], logfile="s.log", pidfile="s.pid")

    def up(test, node):
        d.setup(test, node)
        from jepsen_tpu.control import util as cu
        assert cu.daemon_running("s.pid")
        d.kill(test, node)
        assert not cu.daemon_running("s.pid")
        d.teardown(test, node)

    control.on_nodes(t, up)


def test_hammer_time_cmds():
    from jepsen_tpu.nemesis import hammer_time
    t = sim_test()
    nem = hammer_time("mydb", targeter=lambda test, nodes: ["n2"]).setup(t)
    nem.invoke(t, {"f": "start-pause", "type": "invoke", "value": None})
    cmds = t["remote"].node("n2").cmds()
    assert any("pgrep -f -- mydb" in c and "STOP" in c for c in cmds)
    nem.invoke(t, {"f": "stop-pause", "type": "invoke", "value": None})
    assert any("CONT" in c for c in t["remote"].node("n2").cmds())
