"""Consistency-lattice tests — mirrors the reference's
`elle/test/elle/consistency_model_test.clj` surface: canonicalization,
implication closure, anomaly→impossible-models, friendly_boundary.
"""

import pytest

from jepsen_tpu.checkers.elle import consistency as cm


def test_all_models_well_formed():
    # every model has proscriptions defined and canonicalizes to itself
    for m in cm.ALL_MODELS:
        assert cm.canonical(m) == m
        cm.proscribed_anomalies(m)  # no KeyError
    # the reference's lattice is ~40 models; ours must match that scale
    assert len(cm.ALL_MODELS) >= 35
    assert len(cm.ALL_MODELS) + len(cm.ALIASES) >= 40


def test_aliases_resolve():
    assert cm.canonical("PL-3") == "serializable"
    assert cm.canonical("PL-3U") == "update-serializable"
    assert cm.canonical("PL-FCV") == "forward-consistent-view"
    assert cm.canonical("PL-MSR") == "monotonic-snapshot-read"
    assert cm.canonical("PL-2L") == "monotonic-view"
    assert cm.canonical("strong-serializable") == "strict-serializable"
    assert cm.canonical("prefix-consistent-SI") == \
        "prefix-consistent-snapshot-isolation"
    assert cm.canonical("PSI") == "parallel-snapshot-isolation"
    assert cm.canonical("sequential-consistency") == "sequential"
    with pytest.raises(ValueError):
        cm.canonical("nope")


def test_dag_is_antisymmetric():
    # no two distinct models imply each other (the lattice is a DAG)
    for m in cm.ALL_MODELS:
        for n in cm._DESC[m]:
            if n != m:
                assert m not in cm._DESC[n], (m, n)


def test_implication_closure_spot_checks():
    # strict-serializable sits on top: implies the serializable column,
    # the SI family, and (via linearizable) every session guarantee
    top = cm._DESC["strict-serializable"]
    for weaker in ("serializable", "snapshot-isolation", "read-committed",
                   "read-atomic", "sequential", "causal", "PRAM",
                   "monotonic-reads", "read-your-writes",
                   "update-serializable", "forward-consistent-view",
                   "strong-read-committed", "view-serializable"):
        assert weaker in top, weaker
    # Adya column ordering: PL-3 > PL-3U > PL-FCV > PL-2+ > PL-2L > PL-2
    assert "update-serializable" in cm._DESC["serializable"]
    assert "forward-consistent-view" in cm._DESC["update-serializable"]
    assert "consistent-view" in cm._DESC["forward-consistent-view"]
    assert "monotonic-view" in cm._DESC["consistent-view"]
    assert "read-committed" in cm._DESC["monotonic-view"]
    # session column: sequential > causal > PRAM > {MR, MW, RYW}
    assert "causal" in cm._DESC["sequential"]
    assert {"monotonic-reads", "monotonic-writes",
            "read-your-writes"} <= cm._DESC["PRAM"]
    assert "writes-follow-reads" in cm._DESC["causal"]
    # SI family: strong > strong-session > prefix-consistent > SI
    assert "prefix-consistent-snapshot-isolation" in \
        cm._DESC["strong-session-snapshot-isolation"]
    assert "snapshot-isolation" in \
        cm._DESC["prefix-consistent-snapshot-isolation"]
    # serializability does NOT imply snapshot isolation (incomparable)
    assert "snapshot-isolation" not in cm._DESC["serializable"]
    # nor does SI imply serializability
    assert "serializable" not in cm._DESC["snapshot-isolation"]


def test_proscribed_anomalies_select_right_sets():
    # the VERDICT r03 acceptance probe: these must answer, not KeyError
    mr = cm.anomalies_for_models(["monotonic-reads"])
    assert mr == {"monotonic-reads-violation"}
    us = cm.anomalies_for_models(["update-serializable"])
    assert "G-update" in us
    assert "G-SIb" in us          # via forward-consistent-view
    assert "G-single" in us       # via consistent-view
    assert "G1a" in us and "G0" in us
    assert "G2-item" not in us    # full PL-3 territory, not PL-3U
    # serializable searches its whole downward closure
    ser = cm.anomalies_for_models(["serializable"])
    assert {"G2-item", "G1c", "G0", "G-update", "internal"} <= ser
    assert "G-single-realtime" not in ser
    # strict adds the realtime variants
    strict = cm.anomalies_for_models(["strict-serializable"])
    assert {"G2-item-realtime", "G0-realtime",
            "G-nonadjacent-realtime"} <= strict


def test_anomaly_impossible_models():
    out = cm.anomaly_impossible_models(["G1a"])
    assert "read-committed" in out
    assert "serializable" in out
    assert "strict-serializable" in out
    assert "read-uncommitted" not in out
    assert "monotonic-reads" not in out
    # a session violation knocks out the session column and everything
    # above it, but not transactional isolation
    out = cm.anomaly_impossible_models(["monotonic-reads-violation"])
    assert {"monotonic-reads", "PRAM", "causal", "sequential",
            "linearizable", "strict-serializable"} <= out
    assert "serializable" not in out
    assert "snapshot-isolation" not in out


def test_friendly_boundary():
    b = cm.friendly_boundary(["G1a"])
    assert b["not"] == ["read-committed"]
    assert "serializable" in b["also-not"]
    b = cm.friendly_boundary(["G-single"])
    assert b["not"] == ["consistent-view"]
    assert "snapshot-isolation" in b["also-not"]
    b = cm.friendly_boundary(["internal"])
    assert b["not"] == ["read-atomic"]
    b = cm.friendly_boundary(["G-update"])
    assert b["not"] == ["update-serializable"]
    b = cm.friendly_boundary(["monotonic-reads-violation"])
    assert b["not"] == ["monotonic-reads"]
    assert "PRAM" in b["also-not"] and "linearizable" in b["also-not"]
    # two independent anomalies -> two boundary models
    b = cm.friendly_boundary(["G-cursor", "G-MSR"])
    assert b["not"] == ["cursor-stability", "monotonic-snapshot-read"]
    # nothing observed -> nothing violated
    b = cm.friendly_boundary([])
    assert b == {"not": [], "also-not": []}


def test_g2_vs_g2_item():
    # G2 (predicate) rules out serializable but not repeatable-read
    out = cm.anomaly_impossible_models(["G2"])
    assert "serializable" in out
    assert "repeatable-read" not in out
    out = cm.anomaly_impossible_models(["G2-item"])
    assert "repeatable-read" in out and "view-serializable" in out
