"""Control plane tests: escaping, exec DSL, loopback/sim remotes,
on_nodes fan-out, retry remote, daemon utils."""

import os

import pytest

from jepsen_tpu import control
from jepsen_tpu.control import util as cu
from jepsen_tpu.control.core import (Action, CmdResult, ConnectionError_,
                                     Remote, RetryRemote, Session, escape,
                                     join_cmd, lit)
from jepsen_tpu.control.local import LoopbackRemote
from jepsen_tpu.control.sim import SimRemote


# ---------------------------------------------------------------- escaping

def test_escape_plain():
    assert escape("ls") == "ls"
    assert escape("/var/log/x.log") == "/var/log/x.log"


def test_escape_quoting():
    assert escape("hello world") == "'hello world'"
    assert escape("a'b") == "'a'\\''b'"
    assert escape("") == "''"
    assert escape(lit("a | b")) == "a | b"


def test_join_cmd():
    assert join_cmd(["echo", "hi there", lit(">"), "f"]) == \
        "echo 'hi there' > f"


def test_action_wrapping():
    a = Action(cmd="ls", dir="/tmp", sudo="root", env={"A": "1"})
    w = a.wrapped_cmd()
    assert "cd /tmp" in w and "sudo -n -u root" in w and "env A=1" in w


# ---------------------------------------------------------------- loopback

def test_loopback_exec_and_exit(tmp_path):
    r = LoopbackRemote(base_dir=str(tmp_path))
    s = r.connect("n1")
    with control.with_session("n1", s):
        assert control.exec_("echo", "hello world") == "hello world"
        res = control.exec_result("bash", "-c", "exit 3")
        assert res.exit_status == 3
        with pytest.raises(control.RemoteError):
            control.exec_("false")


def test_loopback_sandbox_isolation(tmp_path):
    r = LoopbackRemote(base_dir=str(tmp_path))
    for node in ("n1", "n2"):
        with control.with_session(node, r.connect(node)):
            control.exec_("bash", "-c", f"echo {node} > who.txt")
    with control.with_session("n1", r.connect("n1")):
        assert control.exec_("cat", "who.txt") == "n1"
    with control.with_session("n2", r.connect("n2")):
        assert control.exec_("cat", "who.txt") == "n2"


def test_loopback_upload_download(tmp_path):
    src = tmp_path / "src.txt"
    src.write_text("payload")
    r = LoopbackRemote(base_dir=str(tmp_path / "nodes"))
    s = r.connect("n1")
    with control.with_session("n1", s):
        control.upload(str(src), "data/up.txt")
        assert control.exec_("cat", "data/up.txt") == "payload"
        dl = tmp_path / "dl"
        control.download("data/up.txt", str(dl))
        assert (dl / "up.txt").read_text() == "payload"


def test_cd_and_env_scoping(tmp_path):
    r = LoopbackRemote(base_dir=str(tmp_path))
    with control.with_session("n1", r.connect("n1")):
        control.exec_("mkdir", "-p", "sub")
        with control.cd("sub"):
            control.exec_("touch", "inner.txt")
        assert control.exec_("ls", "sub") == "inner.txt"
        with control.with_env(MYVAR="42"):
            assert control.exec_("bash", "-c", "echo $MYVAR") == "42"


# ---------------------------------------------------------------- on_nodes

def test_on_nodes_parallel(tmp_path):
    test = {"nodes": ["n1", "n2", "n3"],
            "remote": LoopbackRemote(base_dir=str(tmp_path))}

    def fn(t, node):
        return control.exec_("bash", "-c", "echo $JEPSEN_NODE")

    res = control.on_nodes(test, fn)
    assert res == {"n1": "n1", "n2": "n2", "n3": "n3"}


def test_on_nodes_subset(tmp_path):
    test = {"nodes": ["n1", "n2", "n3"],
            "remote": LoopbackRemote(base_dir=str(tmp_path))}
    res = control.on_nodes(test, lambda t, n: control.host(), nodes=["n2"])
    assert res == {"n2": "n2"}


def test_exec_without_session_raises():
    with pytest.raises(control.RemoteError):
        control.exec_("ls")


# ---------------------------------------------------------------- sim

def test_sim_remote_records_and_responds():
    r = SimRemote()
    r.node("n1").respond("uname*", "Linux")
    s = r.connect("n1")
    with control.with_session("n1", s):
        assert control.exec_("uname", "-a") == "Linux"
        control.exec_("iptables", "-A", "INPUT", "-j", "DROP")
    cmds = r.node("n1").cmds()
    assert cmds[0].startswith("uname")
    assert "iptables -A INPUT -j DROP" in cmds[1]


# ---------------------------------------------------------------- retry

class FlakySession(Session):
    def __init__(self, fail_times):
        self.fails_left = fail_times

    def execute(self, action):
        if self.fails_left > 0:
            self.fails_left -= 1
            raise ConnectionError_("transient")
        return CmdResult(cmd=action.cmd, out="ok", err="", exit_status=0)

    def disconnect(self):
        pass


class FlakyRemote(Remote):
    def __init__(self):
        self.connects = 0

    def connect(self, host, opts=None):
        self.connects += 1
        # first session fails twice, reconnected sessions succeed
        return FlakySession(2 if self.connects == 1 else 0)


def test_retry_remote_reconnects():
    rr = RetryRemote(FlakyRemote(), retries=3, backoff_s=0.01)
    s = rr.connect("n1")
    res = s.execute(Action(cmd="x"))
    assert res.out == "ok"


# ---------------------------------------------------------------- util

def test_daemon_lifecycle(tmp_path):
    r = LoopbackRemote(base_dir=str(tmp_path))
    with control.with_session("n1", r.connect("n1")):
        cu.start_daemon("sleep", "30", logfile="d.log", pidfile="d.pid")
        assert cu.daemon_running("d.pid")
        cu.stop_daemon("d.pid", wait_s=1.0)
        assert not cu.daemon_running("d.pid")
        assert not cu.exists("d.pid")


def test_util_exists_ls_tmpdir(tmp_path):
    r = LoopbackRemote(base_dir=str(tmp_path))
    with control.with_session("n1", r.connect("n1")):
        assert not cu.exists("nope")
        control.exec_("touch", "yes.txt")
        assert cu.exists("yes.txt")
        assert "yes.txt" in cu.ls(".")


def test_write_and_read_file(tmp_path):
    r = LoopbackRemote(base_dir=str(tmp_path))
    with control.with_session("n1", r.connect("n1")):
        control.write_file("conf/app.cfg", "key=value\n")
        assert control.file_contents("conf/app.cfg") == "key=value"


def test_install_archive_zip_strips_top_dir(tmp_path):
    # install_archive shells out to the unzip binary for .zip archives;
    # minimal containers don't ship it — skip rather than fail the env
    import shutil
    if not shutil.which("unzip"):
        pytest.skip("no unzip binary on PATH")
    # build app-1.0.zip containing app-1.0/bin/run
    import zipfile
    src = tmp_path / "app-1.0"
    (src / "bin").mkdir(parents=True)
    (src / "bin" / "run").write_text("#!/bin/sh\n")
    zpath = tmp_path / "app-1.0.zip"
    with zipfile.ZipFile(zpath, "w") as z:
        z.write(src / "bin" / "run", "app-1.0/bin/run")
    r = LoopbackRemote(base_dir=str(tmp_path / "nodes"))
    with control.with_session("n1", r.connect("n1")):
        # pre-seed the wget cache so no network is needed
        control.exec_("mkdir", "-p", "/tmp/jepsen/cache")
        control.upload(str(zpath), "/tmp/jepsen/cache/app-1.0.zip")
        cu.install_archive("http://example.com/app-1.0.zip", "opt/app")
        assert cu.exists("opt/app/bin/run"), \
            "zip should match tar layout (top dir stripped)"


# ---------------------------------------------------------- os setup

def test_os_variants_issue_expected_commands():
    from jepsen_tpu import os_setup

    r = SimRemote()
    for os_obj, host, expect in (
            (os_setup.Debian(packages=["jq"]), "n1", "apt-get"),
            (os_setup.Ubuntu(packages=["jq"]), "n2", "unattended-upgrades"),
            (os_setup.Centos(packages=["jq"]), "n3", "yum"),
    ):
        s = r.connect(host)
        with control.with_session(host, s):
            os_obj.setup({}, host)
        joined = "\n".join(r.node(host).cmds())
        assert expect in joined, (host, joined)
    # Ubuntu inherits the Debian apt path too
    assert "apt-get" in "\n".join(r.node("n2").cmds())
