"""minimize/ — the ddmin shrinker + minimal-witness store (ISSUE 4).

Covers: closure invariants (no orphan invoke/ok in any rebuilt
candidate), ddmin determinism (same seed + history → identical
witness), verdict preservation (the witness still fails with the same
anomaly class — including under forced host-fallback degradation),
instant no-op re-shrink via the source digest, the campaign auto-shrink
hook, and the golden minimal witness for a seeded G1c history
(tests/data/witness-g1c-golden.json).
"""

import json
import os

import pytest

from jepsen_tpu import core as jcore
from jepsen_tpu import minimize, store
from jepsen_tpu.checkers.elle import oracle
from jepsen_tpu.history.ops import History, INVOKE
from jepsen_tpu.minimize import reduce as reduce_mod
from jepsen_tpu.workloads import synth
from jepsen_tpu.workloads.append import AppendChecker

GOLDEN = os.path.join(os.path.dirname(__file__), "data",
                      "witness-g1c-golden.json")


def g1c_history(n_txns=250, seed=11):
    """The seeded 500+-op invalid list-append history of the ISSUE's
    acceptance criterion: strict-serializable sim + injected wr cycle."""
    h = synth.la_history(n_txns=n_txns, n_keys=6, concurrency=5,
                         seed=seed)
    assert synth.inject_wr_cycle(h)
    return h


def save_run(tmp_path, h, name="inv"):
    """Persist a history as a stored run with its (invalid) results."""
    base = str(tmp_path / "s")
    test = jcore.noop_test(name=name)
    test["store-dir"] = base
    test["history"] = h
    store.save_0(test)
    test["results"] = oracle.check(h, ["serializable"])
    store.save_1(test)
    return base, store.test_dir(test)


# ---------------------------------------------------------------- units

def test_units_pair_invoke_with_completion():
    h = g1c_history(n_txns=30, seed=3)
    units = reduce_mod.units_of(h)
    # every 2-op unit is (invoke, completion) of one process
    for u in units:
        if len(u) == 2:
            assert u.ops[0].type == INVOKE
            assert u.ops[1].type != INVOKE
            assert u.ops[0].process == u.ops[1].process
    assert sum(len(u) for u in units) == len(h)


def test_closure_no_orphans_on_any_subset():
    h = g1c_history(n_txns=30, seed=4)
    units = reduce_mod.units_of(h)
    # arbitrary subsets re-close: every completion's invocation is
    # present (History._build_pair_index would also raise on a double
    # invoke, so constructing it is itself part of the assertion)
    for lo, hi in ((0, 7), (3, 11), (5, len(units))):
        sub = reduce_mod.build_history(units[lo:hi])
        for op in sub:
            if op.is_client_op() and not op.is_invoke():
                if op.is_info():
                    continue  # infos may be legitimately unpaired
                inv = sub.invocation(op)
                assert inv is not None, f"orphan completion {op}"
                assert inv.process == op.process
        # dense reindex
        assert [op.index for op in sub] == list(range(len(sub)))


def test_drop_key_projects_mops_and_drops_empty():
    h = g1c_history(n_txns=30, seed=5)
    units = reduce_mod.units_of(h)
    keys = {k for u in units for k in reduce_mod.unit_keys(u)}
    k = sorted(keys)[0]
    out = reduce_mod.drop_key(units, k)
    for u in out:
        assert k not in reduce_mod.unit_keys(u)


# ---------------------------------------------------------------- shrink

def test_shrink_verdict_preserved_and_minimal(tmp_path):
    h = g1c_history(n_txns=60, seed=7)
    base, d = save_run(tmp_path, h)
    s = minimize.shrink(d, host_oracle=True, anomalies="G1c")
    assert s["valid?"] is False
    assert "G1c" in s["anomaly-types"]
    assert s["ops"] <= 12
    assert s["source-ops"] == len(h)
    # the confirm pass ran the device pipeline: the persisted cycle
    # carries the Explainer's evidence on every dependency edge
    w = json.load(open(s["paths"]["meta"]))
    assert w["checker"] == "list-append"
    cyc = w["anomalies"]["G1c"][0]["cycle"]
    assert all(e.get("why") for e in cyc)
    # witness.jsonl reloads as a closed history
    loaded = minimize.load_witness(d)
    assert len(loaded["history"]) == s["ops"]
    assert loaded["digest"] == s["digest"]


def test_shrink_deterministic(tmp_path):
    h1 = g1c_history(n_txns=60, seed=9)
    h2 = g1c_history(n_txns=60, seed=9)
    base1, d1 = save_run(tmp_path, h1, name="a")
    s1 = minimize.shrink(d1, host_oracle=True, workers=3)
    # same seed + history in a fresh store dir, parallel probes on —
    # the canonical-order selection must yield the identical witness
    base2, d2 = save_run(tmp_path, h2, name="b")
    s2 = minimize.shrink(d2, host_oracle=True, workers=1)
    assert s1["digest"] == s2["digest"]
    assert s1["ops"] == s2["ops"]
    assert s1["anomaly-types"] == s2["anomaly-types"]


def test_shrink_noop_reshrink_is_instant(tmp_path):
    h = g1c_history(n_txns=40, seed=13)
    base, d = save_run(tmp_path, h)
    s1 = minimize.shrink(d, host_oracle=True)
    assert not s1["cached"] and s1["probes"] > 0
    s2 = minimize.shrink(d, host_oracle=True)
    assert s2["cached"] is True
    assert s2["probes"] == 0
    assert s2["digest"] == s1["digest"]


def test_shrink_valid_run_refuses(tmp_path):
    h = synth.la_history(n_txns=20, n_keys=3, concurrency=3, seed=1)
    base, d = save_run(tmp_path, h)
    s = minimize.shrink(d, host_oracle=True)
    assert s["error"] == "not-invalid"
    assert minimize.load_witness(d) is None


def test_shrink_target_absent(tmp_path):
    h = g1c_history(n_txns=40, seed=15)
    base, d = save_run(tmp_path, h)
    s = minimize.shrink(d, host_oracle=True, anomalies=["G0-nonsense"])
    assert s["error"] == "target-absent"


def test_shrink_under_forced_host_fallback(tmp_path):
    """Verdict preservation under degradation: with a persistent
    device fault installed, every probe's device dispatch degrades to
    the host oracle — the witness must still be invalid with the same
    anomaly class (the resilience contract carried through triage)."""
    from jepsen_tpu.resilience import FaultPlan, use

    h = g1c_history(n_txns=40, seed=17)
    base, d = save_run(tmp_path, h)
    plan = FaultPlan(persistent=True, kinds=("device-lost",))
    with use(plan):
        s = minimize.shrink(d, host_oracle=False)  # device checker path
    assert s["valid?"] is False
    assert "G1c" in s["anomaly-types"]
    assert s["ops"] <= 12
    assert len(plan.injected) > 0  # the faults really fired
    # the confirm result records the degradation it survived
    w = json.load(open(s["paths"]["meta"]))
    assert w["anomalies"], w


def test_shrink_telemetry_round_spans(tmp_path):
    from jepsen_tpu import telemetry

    h = g1c_history(n_txns=40, seed=19)
    base, d = save_run(tmp_path, h)
    coll = telemetry.activate()
    try:
        minimize.shrink(d, host_oracle=True)
    finally:
        telemetry.deactivate(coll)
    names = []

    def walk(sp):
        names.append(sp.name)
        for c in sp.children:
            walk(c)

    for r in coll.roots:
        walk(r)
    assert "shrink" in names
    assert "shrink.baseline" in names
    assert "shrink.confirm" in names
    rounds = [n for n in names if n == "shrink.round"]
    assert len(rounds) >= 3
    # round spans carry phase + probe latency attrs
    shrink_root = next(r for r in coll.roots if r.name == "shrink")

    def find_rounds(sp, out):
        if sp.name == "shrink.round":
            out.append(sp)
        for c in sp.children:
            find_rounds(c, out)

    rs = []
    find_rounds(shrink_root, rs)
    assert any(sp.attrs.get("phase") == "ops" for sp in rs)
    assert any("probe_p50_s" in sp.attrs for sp in rs)
    assert any("ops_remaining" in sp.attrs for sp in rs)
    # probe durations also landed in the fixed-bucket histogram the
    # web percentile table reads
    snap = coll.registry.snapshot()
    hists = [x for x in snap["histograms"]
             if x["name"] == "shrink-probe-duration-s"]
    assert hists and hists[0]["count"] > 0


def _rw_txn(p, inv, ok):
    from jepsen_tpu.history.ops import Op

    return [Op(type="invoke", process=p, f="txn", value=inv),
            Op(type="ok", process=p, f="txn", value=ok)]


def rw_g1c_history():
    """A tiny invalid rw-register history: a pure wr-edge cycle (G1c)
    between two txns, plus droppable filler."""
    from jepsen_tpu.history.ops import history

    ops = []
    for i, p in enumerate((2, 3, 4)):
        v = 500 + i
        ops += _rw_txn(p, [["w", 2 + (i % 2), v]],
                       [["w", 2 + (i % 2), v]])
    ops += _rw_txn(0, [["w", 0, 100], ["r", 1, None]],
                   [["w", 0, 100], ["r", 1, 200]])
    ops += _rw_txn(1, [["w", 1, 200], ["r", 0, None]],
                   [["w", 1, 200], ["r", 0, 100]])
    return history(ops)


def test_rw_host_equivalent_twin_matches_device():
    """ISSUE 5 satellite (ROADMAP open item): rw-register now has a
    host probe twin — `use_device=False` through the same exact host
    inference, so many-small shrink probes skip the per-shape jit."""
    from jepsen_tpu.minimize import probe as probe_mod
    from jepsen_tpu.workloads.wr import WrChecker

    chk = WrChecker()
    twin = probe_mod.host_equivalent(chk)
    assert twin is not None
    assert twin.name() == "rw-register-host"
    for h in (synth.rw_history(n_txns=30, seed=2), rw_g1c_history()):
        dev = chk.check({}, h, {})
        host = twin.check({}, h, {})
        assert host["valid?"] == dev["valid?"]
        assert sorted(host.get("anomaly-types") or []) == \
            sorted(dev.get("anomaly-types") or [])


def test_shrink_rw_with_host_oracle_uses_twin(tmp_path):
    from jepsen_tpu.checkers.elle import rw_register
    from jepsen_tpu.workloads.wr import WrChecker

    h = rw_g1c_history()
    base = str(tmp_path / "s")
    test = jcore.noop_test(name="rw-inv")
    test["store-dir"] = base
    test["history"] = h
    store.save_0(test)
    test["results"] = rw_register.check(h)
    store.save_1(test)
    d = store.test_dir(test)

    s = minimize.shrink(d, checker=WrChecker(), host_oracle=True)
    assert s["valid?"] is False
    assert s["probe-checker"] == "rw-register-host"
    assert s["checker"] == "rw-register"  # confirm ran the original
    assert "G1c" in s["anomaly-types"]
    assert s["ops"] == 4  # exactly the two wr-cycle txns survive


def test_rw_register_probes_classified_device():
    """Review regression: WrChecker must carry the canonical
    "rw-register" name so shrink probes of rw runs serialize through
    DeviceSlots like every other device pipeline."""
    from jepsen_tpu.minimize.probe import is_device_checker
    from jepsen_tpu.workloads.wr import WrChecker

    assert WrChecker().name() == "rw-register"
    assert is_device_checker(WrChecker())


def test_probe_does_not_replay_run_fault_plan():
    """Review regression: a chaos cell's own recorded fault plan must
    not replay into its triage probes — the plan's shared call counter
    advanced by parallel probes would make witnesses
    scheduling-dependent (and a persistent plan would degrade every
    probe).  Process-installed plans (the degradation drill,
    test_shrink_under_forced_host_fallback) still apply."""
    from jepsen_tpu.minimize.probe import ProbePool
    from jepsen_tpu.resilience import FaultPlan

    h = g1c_history(n_txns=20, seed=23)
    plan = FaultPlan(persistent=True, kinds=("device-lost",))
    pool = ProbePool({"faults": plan, "store-dir": "/nope"},
                     AppendChecker(("serializable",)))
    res = pool.check_history(h)
    assert res["valid?"] is False
    assert plan.injected == [], "the run's own plan fired in a probe"
    assert not res.get("degraded")


def test_cached_witness_honors_anomaly_pin(tmp_path):
    """Review regression: the source-digest cache must not satisfy an
    --anomaly pin the cached witness doesn't exhibit."""
    h = g1c_history(n_txns=40, seed=26)  # baseline: G-single/G1c/G2-item
    base, d = save_run(tmp_path, h)
    baseline = set(oracle.check(h, ["serializable"])["anomaly-types"])
    others = sorted(baseline - {"G1c"})
    assert others, "seed 26 regressed to a single-class baseline"
    s1 = minimize.shrink(d, host_oracle=True, anomalies="G1c")
    assert "G1c" in s1["anomaly-types"]
    s2 = minimize.shrink(d, host_oracle=True, anomalies=[others[0]])
    assert others[0] in set(s2["anomaly-types"]), \
        (others[0], s2["anomaly-types"], s2.get("cached"))
    # and a pin the fresh witness DOES exhibit is a cache hit
    s3 = minimize.shrink(d, host_oracle=True, anomalies=[others[0]])
    assert s3["cached"] is True and s3["probes"] == 0


def test_baseline_and_confirm_unbounded_by_probe_deadline(tmp_path):
    """Review regression: the per-candidate probe deadline must not
    bound the FULL-history baseline or the confirm pass — with an
    instantly-expiring probe budget every candidate is refused, but
    the shrink still terminates with a reproducing (unreduced)
    witness instead of a bogus 'not-invalid'."""
    h = g1c_history(n_txns=30, seed=27)
    base, d = save_run(tmp_path, h)
    s = minimize.shrink(d, host_oracle=True, probe_deadline_s=0.0)
    assert s.get("error") is None
    assert s["valid?"] is False
    assert s["ops"] == len(h)  # no candidate survived its 0 s budget


def test_broken_cached_witness_is_not_a_cache_hit(tmp_path):
    """Review regression: a persisted witness whose confirm pass came
    back non-false (expired deadline, flake) must not be served from
    cache forever — the digest match alone is not enough."""
    h = g1c_history(n_txns=40, seed=29)
    base, d = save_run(tmp_path, h)
    s1 = minimize.shrink(d, host_oracle=True)
    meta_path = s1["paths"]["meta"]
    w = json.load(open(meta_path))
    w["valid?"] = "unknown"  # simulate a flaked confirm
    with open(meta_path, "w") as f:
        json.dump(w, f)
    s2 = minimize.shrink(d, host_oracle=True)
    assert s2["cached"] is False and s2["probes"] > 0
    assert s2["valid?"] is False  # the re-shrink healed the witness
    s3 = minimize.shrink(d, host_oracle=True)
    assert s3["cached"] is True


# ---------------------------------------------------------------- golden

def test_golden_g1c_witness(tmp_path):
    """The checked-in minimal witness for the canonical seeded G1c
    history: shrinking it must reproduce the golden ops exactly
    (regenerate with scripts/make_golden.py-style: see the file's
    "generator" field)."""
    with open(GOLDEN) as f:
        golden = json.load(f)
    h = g1c_history(n_txns=golden["generator"]["n_txns"],
                    seed=golden["generator"]["seed"])
    base, d = save_run(tmp_path, h)
    s = minimize.shrink(d, host_oracle=True, anomalies="G1c")
    assert s["digest"] == golden["digest"]
    got = [[op.type, op.process, op.f, op.value]
           for op in s["witness-history"]]
    assert got == golden["ops"]
    assert "G1c" in s["anomaly-types"]


# ---------------------------------------------------------------- campaign

class _StaleReadClient:
    """A deliberately broken list-append client: reads return the
    key's list REVERSED — incompatible-order from the second append
    on, so every run is deterministically invalid."""

    def open(self, test, node):
        return self

    def close(self, test):
        pass

    def setup(self, test):
        pass

    def teardown(self, test):
        pass

    def __init__(self):
        import threading

        self.lock = threading.Lock()
        self.lists = {}

    def invoke(self, test, op):
        out = []
        with self.lock:
            for m in op["value"]:
                kind, k = m[0], m[1]
                if kind == "append":
                    self.lists.setdefault(k, []).append(m[2])
                    out.append(["append", k, m[2]])
                else:
                    out.append(["r", k, list(reversed(
                        self.lists.get(k, [])))])
        return dict(op, type="ok", value=out)


def test_campaign_auto_shrink_cell(tmp_path):
    """The opt-in `"shrink": true` spec key: an invalid cell's index
    record gains a witness summary, and the witness artifacts land in
    the run dir (the web grid renders them as the witness column)."""
    from jepsen_tpu import campaign
    from jepsen_tpu.campaign import plan as plan_mod
    from jepsen_tpu.generator import core as g

    def bad_append(opts):
        import random

        rng = random.Random(opts.get("seed", 0))
        return {
            "name": "bad-append",
            "nodes": ["n1"],
            "concurrency": 2,
            "client": _StaleReadClient(),
            "generator": g.clients(g.limit(
                40, synth.la_generator(n_keys=2, read_frac=0.4,
                                       rng=rng))),
            "checker": AppendChecker(("serializable",)),
        }

    plan_mod.register_workload("bad-append-shrink", bad_append,
                               device=True)
    base = str(tmp_path / "s")
    spec = {"name": "shrinky", "workloads": ["bad-append-shrink"],
            "seeds": [0], "opts": {"shrink": True}}
    summary = campaign.run_campaign(spec, base, workers=1)
    row = summary["rows"][0]
    assert row["valid?"] is False
    w = row["witness"]
    assert w and w.get("ops") and w["ops"] <= 12, row
    assert w["anomaly-types"]
    run_dir = os.path.join(base, row["dir"])
    assert os.path.exists(os.path.join(run_dir, "witness.json"))
    assert os.path.exists(os.path.join(run_dir, "witness.jsonl"))
    # the witness summary is in the index ledger (what the web grid
    # and regression queries read)
    from jepsen_tpu.campaign.core import index_path

    idx = campaign.Index(index_path("shrinky", base))
    rec = idx.latest(row["run"])
    assert rec["witness"]["digest"] == w["digest"]


# ---------------------------------------------------------------- slow

@pytest.mark.slow  # device-pipeline probes recompile per shape bucket
def test_acceptance_device_probes_500_ops(tmp_path):
    """ISSUE 4 acceptance: a seeded 500+-op invalid list-append
    history shrinks to a ≤12-op witness that re-checks invalid with
    the same anomaly class, deterministically, with probe rounds as
    telemetry spans and DEVICE probes serialized through DeviceSlots
    (the probe checker is the device pipeline here — no host twin)."""
    from jepsen_tpu import telemetry
    from jepsen_tpu.minimize.probe import is_device_checker

    h = g1c_history()  # 500 ops
    assert len(h) >= 500
    base, d = save_run(tmp_path, h)
    assert is_device_checker(AppendChecker())
    coll = telemetry.activate()
    try:
        s = minimize.shrink(d, anomalies="G1c", workers=2,
                            device_slots=1)
    finally:
        telemetry.deactivate(coll)
    assert s["valid?"] is False
    assert s["ops"] <= 12
    assert "G1c" in s["anomaly-types"]
    assert s["probe-checker"] == "list-append"  # the device pipeline
    names = []

    def walk(sp):
        names.append(sp.name)
        for c in sp.children:
            walk(c)

    for r in coll.roots:
        walk(r)
    assert names.count("shrink.round") >= 3
