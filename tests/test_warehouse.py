"""Warehouse + /metrics + regression-gate tests (ISSUE 6).

The observatory contract under test:

- ingest is INCREMENTAL (byte cursors / stat digests: an unchanged
  store is a no-op) and REBUILDABLE (the jsonl ledgers stay the source
  of truth, even for torn/partial/mid-crash stores);
- the SQL fast paths return exactly what the jsonl scans return, and
  the hot pair (``flips`` + ``span_trend``) is >= 10x faster on a
  1k-run campaign (the acceptance criterion);
- the Prometheus exposition is pinned byte-for-byte by a golden file;
- ``cli obs gate`` passes an unchanged generation pair and fails an
  injected +50% p95 regression, with distinct exit codes.
"""

import json
import os
import random
import time

import pytest

from jepsen_tpu import cli
from jepsen_tpu.campaign.index import Index
from jepsen_tpu.telemetry import gate, metrics, prometheus
from jepsen_tpu.telemetry import warehouse as wmod


# ------------------------------------------------------------ helpers

def _write_ledger(base, name="soak", gens=("g1", "g2"), n=20,
                  scale=None, seed=0, witness_every=0, flip_every=0):
    """A synthetic campaign ledger: ``n`` runs per generation, span
    durations ~U(0.9, 1.1) * scale[gen]."""
    scale = scale or {}
    cdir = os.path.join(str(base), "campaigns")
    os.makedirs(cdir, exist_ok=True)
    path = os.path.join(cdir, name + ".jsonl")
    rng = random.Random(seed)
    with open(path, "a") as f:
        for gen in gens:
            m = scale.get(gen, 1.0)
            for i in range(n):
                rec = {
                    "campaign": name, "run": f"r-{gen}-{i}",
                    "key": f"la|none|{i}", "workload": "la",
                    "fault": None, "seed": i,
                    "valid?": (False if flip_every and gen != gens[0]
                               and i % flip_every == 0 else True),
                    "dir": f"d/{gen}/{i}", "ops": 100,
                    "wall_s": round(rng.uniform(5, 20), 2), "gen": gen,
                    "ts": "2026-08-03T00:00:00Z",
                    "spans": {
                        "check:la": round(rng.uniform(0.9, 1.1) * m, 6),
                        "workload": round(rng.uniform(1, 3), 6),
                    },
                }
                if witness_every and i % witness_every == 0:
                    rec["witness"] = {
                        "ops": 2 + (i + (0 if gen == gens[0] else 1)) % 5,
                        "digest": f"w{(i + len(gen)) % 3}",
                        "anomaly-types": ["G1c"]}
                f.write(json.dumps(rec) + "\n")
    return path


def _fresh(base, path):
    wh = wmod.open_or_create(str(base))
    wh.ingest_ledger(path, str(base))
    return wh


# ------------------------------------------------- incremental ingest

def test_ingest_is_cursor_incremental(tmp_path):
    path = _write_ledger(tmp_path, n=10)
    wh = wmod.open_or_create(str(tmp_path))
    assert wh.ingest_ledger(path, str(tmp_path)) == 20
    # unchanged ledger: a no-op (cursor == size)
    assert wh.ingest_ledger(path, str(tmp_path)) == 0
    assert wh.ledger_fresh(path, str(tmp_path))
    # appended records: only the new lines are parsed
    _write_ledger(tmp_path, gens=("g3",), n=5)
    assert wh.ingest_ledger(path, str(tmp_path)) == 5
    assert wh.counts()["campaign_records"] == 25


def test_torn_tail_left_unconsumed_until_healed(tmp_path):
    path = _write_ledger(tmp_path, n=3)
    with open(path, "a") as f:
        f.write('{"run": "torn", "valid?": tru')  # no newline
    wh = wmod.open_or_create(str(tmp_path))
    assert wh.ingest_ledger(path, str(tmp_path)) == 6
    assert not wh.ledger_fresh(path, str(tmp_path))  # fast path gated
    assert Index(path)._warehouse() is None
    # the writer heals (truncates) the torn line -> file shrinks below
    # the durable content... here it completes the line instead
    with open(path, "a") as f:
        f.write('e, "key": "k", "gen": "g9"}\n')
    assert wh.ingest_ledger(path, str(tmp_path)) == 1
    assert wh.ledger_fresh(path, str(tmp_path))


def test_shrunken_ledger_wiped_and_reingested(tmp_path):
    path = _write_ledger(tmp_path, n=10)
    wh = wmod.open_or_create(str(tmp_path))
    wh.ingest_ledger(path, str(tmp_path))
    # a heal/rewrite shrank the file: derived rows are rebuilt from 0
    lines = open(path).readlines()
    with open(path, "w") as f:
        f.writelines(lines[:7])
    wh.ingest_ledger(path, str(tmp_path))
    assert wh.counts()["campaign_records"] == 7
    assert Index(path).flips() == Index(path, use_warehouse=False).flips()


def test_mid_ingest_crash_rolls_back_whole_unit(tmp_path, monkeypatch):
    path = _write_ledger(tmp_path, n=10)
    wh = wmod.open_or_create(str(tmp_path))
    real = wmod.Warehouse._insert_record
    calls = []

    def dying(self, ledger, rec):
        if len(calls) == 12:
            raise RuntimeError("simulated crash mid-ingest")
        calls.append(rec)
        return real(self, ledger, rec)

    monkeypatch.setattr(wmod.Warehouse, "_insert_record", dying)
    with pytest.raises(RuntimeError):
        wh.ingest_ledger(path, str(tmp_path))
    monkeypatch.setattr(wmod.Warehouse, "_insert_record", real)
    # the transaction rolled back: no partial rows, no cursor movement
    assert wh.counts()["campaign_records"] == 0
    assert not wh.ledger_fresh(path, str(tmp_path))
    # ... and the next ingest simply redoes the unit, to the same state
    assert wh.ingest_ledger(path, str(tmp_path)) == 20
    assert wh.ledger_fresh(path, str(tmp_path))


# -------------------------------------------- fast path == jsonl scan

def test_sql_queries_equal_jsonl_scan(tmp_path):
    path = _write_ledger(tmp_path, n=30, scale={"g2": 1.4},
                         witness_every=4, flip_every=5)
    _fresh(tmp_path, path)
    slow = Index(path, use_warehouse=False)
    fast = Index(path)
    assert fast._warehouse() is not None, "fast path not engaged"
    assert fast.flips() == slow.flips()
    assert fast.regressions() == slow.regressions()
    assert fast.span_stats() == slow.span_stats()
    assert fast.span_trend("check:la") == slow.span_trend("check:la")
    assert fast.span_samples("workload") == slow.span_samples("workload")
    assert fast.witness_diffs() == slow.witness_diffs()
    assert fast.verdict_counts() == slow.verdict_counts()
    # latest_by_run: the warehouse returns the grid PROJECTION
    la, lb = slow.latest_by_run(), fast.latest_by_run()
    assert set(la) == set(lb)
    for run in la:
        for fld in ("run", "key", "workload", "fault", "seed", "valid?",
                    "dir", "ops", "wall_s", "gen", "ts"):
            assert la[run].get(fld) == lb[run].get(fld), (run, fld)
        assert la[run].get("witness") == lb[run].get("witness")


def test_runless_and_empty_run_records_agree_across_backends(tmp_path):
    """Records with a missing or empty run id: both backends apply the
    SAME selection rule (verdict-bearing AND truthy run), so the
    campaign grid and verdict counts can't change with warehouse
    freshness."""
    path = _write_ledger(tmp_path, n=4)
    with open(path, "a") as f:
        f.write(json.dumps({"campaign": "soak", "key": "k-norun",
                            "valid?": False, "gen": "g2"}) + "\n")
        f.write(json.dumps({"campaign": "soak", "run": "", "key": "k-e",
                            "valid?": False, "gen": "g2"}) + "\n")
    _fresh(tmp_path, path)
    slow = Index(path, use_warehouse=False)
    fast = Index(path)
    assert fast._warehouse() is not None
    assert set(fast.latest_by_run()) == set(slow.latest_by_run())
    assert fast.verdict_counts() == slow.verdict_counts()
    assert slow.verdict_counts()["false"] == 0  # run-less rows excluded


def test_corrupt_midfile_event_line_same_prefix_both_backends(tmp_path):
    """A complete-but-corrupt mid-file event line: the warehouse ingest
    stops where the read_events scan stops (same indexed prefix) and
    pins cursor < size, so events_fresh gates the fast path off and
    `tail --since` answers identically from either backend."""
    from jepsen_tpu.telemetry import stream as ts
    d = _mk_run(tmp_path, "a-test", "t9", events=5)
    p = os.path.join(d, "events.jsonl")
    with open(p, "a") as f:
        f.write('{"t": 200.0, "ev": "corrupt"\n')  # complete, bad JSON
        f.write(json.dumps({"t": 201.0, "ev": "tick", "i": 99}) + "\n")
    wh = wmod.open_or_create(str(tmp_path))
    n = wh.ingest_events(d, str(tmp_path))
    scan = ts.read_events(p)
    assert [e.get("i") for e in wh.events_since(d, str(tmp_path))] == \
        [e.get("i") for e in scan]
    assert n == 5 and not wh.events_fresh(d, str(tmp_path))
    # re-ingest: cursor parked before the bad line, no double-indexing
    assert wh.ingest_events(d, str(tmp_path)) == 0


def test_stale_warehouse_falls_back_to_scan(tmp_path):
    path = _write_ledger(tmp_path, n=5, flip_every=2)
    _fresh(tmp_path, path)
    assert Index(path)._warehouse() is not None
    # a writer appends a fresh flip (g2 left this key False): coverage
    # is stale, the scan answers
    idx = Index(path)
    idx.append({"run": "r-new", "key": "la|none|0", "valid?": True,
                "gen": "g3"})
    assert idx._warehouse() is None
    flips = idx.flips()
    assert any(f["run"] == "r-new" for f in flips)
    # a fresh reader also refuses the stale warehouse
    assert Index(path)._warehouse() is None
    assert Index(path).flips() == flips


def test_1k_campaign_speedup_10x(tmp_path):
    """THE acceptance criterion: warehouse-backed flips() + span_trend()
    >= 10x faster than the jsonl scan on a synthetic >=1k-run campaign,
    with both paths returning identical results.  (2k records: the
    scan cost scales with the ledger while SQL stays ~flat, so the
    bigger campaign doubles the timing margin this load-sensitive
    gate runs with.)"""
    path = _write_ledger(tmp_path, gens=("g1", "g2"), n=1000,
                         scale={"g2": 1.2}, flip_every=9)
    _fresh(tmp_path, path)

    def scan():
        idx = Index(path, use_warehouse=False)
        return idx.flips(), idx.span_trend("check:la")

    def sql():
        idx = Index(path)
        return idx.flips(), idx.span_trend("check:la")

    assert scan() == sql()

    # INTERLEAVED best-of reps: timing the two phases back-to-back let
    # an ambient load burst land entirely on one side (observed: all 7
    # sql reps slow while scan ran unloaded — a false <10x under the
    # full suite); alternating them each rep exposes both paths to the
    # same noise, and best-of still measures the unloaded cost
    def timed(fn):
        t0 = time.perf_counter()
        fn()
        return time.perf_counter() - t0

    t_scan = min(timed(scan) for _ in range(9))
    t_sql = float("inf")
    for _ in range(9):
        timed(scan)  # interleave: noise hits both paths alike
        t_sql = min(t_sql, timed(sql))
    assert t_scan >= 10 * t_sql, \
        f"scan {t_scan * 1e3:.2f}ms vs sql {t_sql * 1e3:.2f}ms " \
        f"({t_scan / t_sql:.1f}x, need >= 10x)"


# ---------------------------------- batching + compaction (ISSUE 20)

def test_batched_ingest_equivalent_and_fewer_commits(tmp_path):
    """ROADMAP 5a: batch_units groups N ingest units into ONE sqlite
    transaction.  Equivalence (tables + query results identical to the
    per-unit path) and economy (commit count shrinks with the batch)
    are both pinned."""
    for sub in ("a", "b"):
        b = tmp_path / sub
        os.makedirs(b)
        _write_ledger(b, name="s1", n=8, flip_every=3, seed=1)
        _write_ledger(b, name="s2", n=8, flip_every=3, seed=2)
        _write_ledger(b, name="s3", n=8, flip_every=3, seed=3)

    def commits(wh, fn):
        seen = []
        wh.db.set_trace_callback(
            lambda s: seen.append(s) if "COMMIT" in s.upper() else None)
        try:
            fn()
        finally:
            wh.db.set_trace_callback(None)
        return len(seen)

    wa = wmod.open_or_create(str(tmp_path / "a"))
    wb = wmod.open_or_create(str(tmp_path / "b"))
    na = commits(wa, lambda: wa.ingest_store(str(tmp_path / "a"),
                                             events=False,
                                             batch_units=1))
    nb = commits(wb, lambda: wb.ingest_store(str(tmp_path / "b"),
                                             events=False,
                                             batch_units=64))
    assert wa.counts() == wb.counts()
    for name in ("s1", "s2", "s3"):
        pa = os.path.join(str(tmp_path / "a"), "campaigns",
                          name + ".jsonl")
        pb = os.path.join(str(tmp_path / "b"), "campaigns",
                          name + ".jsonl")
        assert Index(pa).flips() == Index(pb).flips()
    assert nb < na, (na, nb)
    # both paths leave the cursors flush: re-ingest is a no-op
    again = wb.ingest_store(str(tmp_path / "b"), events=False)
    assert again["records"] == 0


def test_compaction_parity_for_safe_queries(tmp_path):
    """Folding old generations into gen_compact/key_compact must not
    change what flips/span_trend/witness_diffs answer (rollups are
    never touched), while raw rows below the horizon are dropped and
    witness-bearing records survive."""
    path = _write_ledger(tmp_path, gens=("g1", "g2", "g3", "g4"),
                         n=30, scale={"g4": 1.3}, flip_every=7,
                         witness_every=10)
    wh = _fresh(tmp_path, path)
    idx = Index(path)
    before = (idx.flips(), idx.span_trend("check:la"),
              idx.witness_diffs())
    n_before = wh.counts()["campaign_records"]

    stats = wh.compact_ledger(path, str(tmp_path), keep_gens=2)
    assert stats["gens-compacted"] == 2
    assert stats["dropped-records"] > 0
    assert stats["kept-witnesses"] > 0
    rel = os.path.relpath(path, str(tmp_path))
    assert wh.ledger_compacted(rel)
    assert wh.counts()["campaign_records"] < n_before

    idx2 = Index(path)
    after = (idx2.flips(), idx2.span_trend("check:la"),
             idx2.witness_diffs())
    assert after == before
    # ...and all three still match the raw jsonl scan
    scan = Index(path, use_warehouse=False)
    assert after == (scan.flips(), scan.span_trend("check:la"),
                     scan.witness_diffs())
    # the safe set answers from SQL; everything else falls back to
    # the scan (still identical — the jsonl is untouched)
    assert idx2._warehouse("flips") is not None
    assert idx2._warehouse("span_stats") is None
    assert idx2.span_stats() == scan.span_stats()
    # compaction never moves the byte cursor: re-ingest is a no-op
    again = wh.ingest_store(str(tmp_path), events=False)
    assert again["records"] == 0


def test_flip_detection_across_compaction_horizon(tmp_path):
    """A key's last verdict below the horizon lives only in
    key_compact; a NEW record flipping against it must still roll up
    as a flip, identically to the jsonl scan (which sees every raw
    line)."""
    path = _write_ledger(tmp_path, gens=("g1", "g2"), n=12,
                         flip_every=5)
    wh = _fresh(tmp_path, path)
    wh.compact_ledger(path, str(tmp_path), keep_gens=0)
    assert wh.counts()["campaign_records"] == 0
    # append g3 flipping every 4th key against its g2 verdict
    _write_ledger(tmp_path, gens=("g3",), n=12, flip_every=4)
    wh.ingest_store(str(tmp_path), events=False)
    assert Index(path).flips() ==         Index(path, use_warehouse=False).flips()


def test_alert_signals_touch_rollup_tables_only(tmp_path):
    """THE O(rollup rows) pin: the alert tick's warehouse leg may not
    read campaign_records or record_spans — trace-asserted, so a
    future 'quick join' cannot quietly make the tick O(runs)."""
    path = _write_ledger(tmp_path, gens=("g1", "g2"), n=25,
                         flip_every=6)
    wh = _fresh(tmp_path, path)
    stmts = []
    wh.db.set_trace_callback(stmts.append)
    try:
        sig = wh.alert_signals()
    finally:
        wh.db.set_trace_callback(None)
    for s in stmts:
        low = s.lower()
        assert "campaign_records" not in low, s
        assert "record_spans" not in low, s
    assert sig["flips"] > 0
    assert sig["span-p95-s:check:la"] > 0
    # compaction only shifts rows between tables the signals already
    # aggregate — the answers survive it
    wh.compact_ledger(path, str(tmp_path), keep_gens=1)
    sig2 = wh.alert_signals()
    assert sig2["flips"] == sig["flips"]
    assert sig2["compacted-gens"] == 1.0


def test_100k_store_speedup_compacted(tmp_path):
    """THE ISSUE 20 acceptance criterion: a synthetic 100k-run store —
    batched ingest, compacted rollups — answers flips + span_trend +
    the alert-signal query >= 10x faster than the jsonl scan with
    identical results; re-ingest is a digest no-op; the alert tick
    stays O(rollup rows).  (Timing is interleaved best-of like the 1k
    pin, so ambient suite load hits both paths alike.)"""
    cdir = tmp_path / "campaigns"
    os.makedirs(cdir)
    path = str(cdir / "big.jsonl")
    rng = random.Random(0)
    with open(path, "w") as f:
        for gen in ("g1", "g2"):
            for i in range(50000):
                f.write(json.dumps({
                    "campaign": "big", "run": f"r-{gen}-{i}",
                    "key": f"la|none|{i % 500}", "workload": "la",
                    "fault": None, "seed": i,
                    "valid?": not (gen == "g2" and i % 97 == 0),
                    "dir": f"d/{gen}/{i}", "ops": 100, "wall_s": 9.0,
                    "gen": gen, "ts": "2026-08-03T00:00:00Z",
                    "spans": {
                        "check:la": round(rng.uniform(0.9, 1.1), 6),
                        "workload": round(rng.uniform(1, 3), 6),
                    }}) + "\n")
    wh = wmod.open_or_create(str(tmp_path))
    stats = wh.ingest_store(str(tmp_path), events=False)
    assert stats["records"] == 100000
    wh.compact_ledger(path, str(tmp_path), keep_gens=1)

    def scan():
        idx = Index(path, use_warehouse=False)
        return idx.flips(), idx.span_trend("check:la")

    def sql():
        idx = Index(path)
        return idx.flips(), idx.span_trend("check:la")

    assert scan() == sql()

    def timed(fn):
        t0 = time.perf_counter()
        fn()
        return time.perf_counter() - t0

    t_scan = min(timed(scan) for _ in range(3))
    t_sql = float("inf")
    for _ in range(3):
        timed(scan)  # interleave: noise hits both paths alike
        t_sql = min(t_sql, timed(sql))
    assert t_scan >= 10 * t_sql, \
        f"scan {t_scan * 1e3:.2f}ms vs sql {t_sql * 1e3:.2f}ms " \
        f"({t_scan / t_sql:.1f}x, need >= 10x)"
    # the alert tick is rollup-bounded: orders of magnitude under the
    # scan even on the 100k store
    t_alert = min(timed(wh.alert_signals) for _ in range(3))
    assert t_alert * 10 <= t_sql + t_scan, \
        f"alert tick {t_alert * 1e3:.2f}ms is not O(rollup rows)"
    sig = wh.alert_signals()
    assert sig["flips"] > 0 and sig["compacted-gens"] == 1.0
    # batched ingest left every cursor flush: the re-ingest is a no-op
    again = wh.ingest_store(str(tmp_path), events=False)
    assert again["records"] == 0 and again["ledgers"] == 1


# ------------------------------------------------- run dirs + rebuild

def _mk_run(base, name, ts, valid=True, telemetry=True, witness=False,
            events=None, torn_events=False):
    d = os.path.join(str(base), name, ts)
    os.makedirs(d, exist_ok=True)
    with open(os.path.join(d, "results.json"), "w") as f:
        json.dump({"valid?": valid}, f)
    if telemetry:
        with open(os.path.join(d, "telemetry.json"), "w") as f:
            json.dump({
                "spans": [{"name": "run", "dur_ns": 2_000_000_000,
                           "children": [{"name": "check:la",
                                         "dur_ns": 500_000_000}]}],
                "metrics": {"counters": [{"name": "ops-ok", "labels": {},
                                          "value": 42}],
                            "gauges": [], "histograms": []},
            }, f)
    if witness:
        with open(os.path.join(d, "witness.json"), "w") as f:
            json.dump({"ops": 4, "source-ops": 100, "digest": "wd",
                       "anomaly-types": ["G1c"], "probes": 9}, f)
    if events is not None:
        with open(os.path.join(d, "events.jsonl"), "w") as f:
            for i in range(events):
                f.write(json.dumps({"t": 100.0 + i, "ev": "tick",
                                    "i": i}) + "\n")
            if torn_events:
                f.write('{"t": 999.0, "ev": "to')  # crash mid-append
    return d


def test_run_dir_ingest_digest_noop_and_missing_artifacts(tmp_path):
    d = _mk_run(tmp_path, "a-test", "t1", witness=True)
    _mk_run(tmp_path, "a-test", "t2", valid=False, telemetry=False)
    wh = wmod.open_or_create(str(tmp_path))
    stats = wh.ingest_store(str(tmp_path))
    assert stats["runs"] == 2
    # unchanged store: full no-op
    assert wh.ingest_store(str(tmp_path)) == \
        {"ledgers": 0, "records": 0, "runs": 0, "events": 0,
         "sessions": 0, "fleet-events": 0, "archived": 0}
    c = wh.counts()
    assert c["runs"] == 2 and c["witnesses"] == 1
    assert c["run_spans"] == 2   # run + check:la (telemetric run only)
    rel = os.path.relpath(d, str(tmp_path))
    spans = dict((n, (t, c)) for n, t, c in wh.run_spans(rel))
    assert spans["run"] == (2.0, 1) and spans["check:la"] == (0.5, 1)
    # touching an artifact re-ingests just that run
    time.sleep(0.01)
    with open(os.path.join(d, "results.json"), "w") as f:
        json.dump({"valid?": "unknown", "error": "x"}, f)
    assert wh.ingest_store(str(tmp_path))["runs"] == 1
    assert wh.rollups()["runs_by_verdict"] == {"false": 1, "unknown": 1}


def test_in_progress_run_recorded_as_running(tmp_path):
    """ISSUE 7 satellite: a run dir with no results.json yet (still
    executing, or crashed before analysis) lands as status='running'
    instead of an indistinguishable NULL-verdict row; when results
    appear the digest changes and the row flips to done."""
    d = os.path.join(str(tmp_path), "a-test", "t-live")
    os.makedirs(d)
    wh = wmod.open_or_create(str(tmp_path))
    assert wh.ingest_store(str(tmp_path))["runs"] == 1
    assert wh.rollups()["runs_by_verdict"] == {"running": 1}
    row = wh.query("SELECT status, valid FROM runs")[1][0]
    assert row == ("running", None)
    # unchanged: no-op; results appearing re-ingests to done
    assert wh.ingest_store(str(tmp_path))["runs"] == 0
    time.sleep(0.01)
    with open(os.path.join(d, "results.json"), "w") as f:
        json.dump({"valid?": True}, f)
    assert wh.ingest_store(str(tmp_path))["runs"] == 1
    assert wh.rollups()["runs_by_verdict"] == {"true": 1}
    assert wh.query("SELECT status FROM runs")[1][0] == ("done",)


def test_verifier_session_ingest_and_rollup(tmp_path):
    """ISSUE 7 satellite: verifier session.json snapshots land in the
    warehouse (one upserted row per session) and roll up by state on
    /metrics."""
    from jepsen_tpu.verifier import VerifierService
    from jepsen_tpu.workloads import synth

    svc = VerifierService(str(tmp_path))
    h = synth.la_history(n_txns=60, n_keys=3, seed=0)
    body = b"".join(json.dumps(op.to_dict()).encode() + b"\n"
                    for op in h)
    svc.ingest("wh-a", body, cursor=0)
    svc.verdict("wh-a")
    svc.ingest("wh-b", body, cursor=0)
    svc.verdict("wh-b")
    svc.seal("wh-b")
    svc.close()
    wh = wmod.open_or_create(str(tmp_path))
    stats = wh.ingest_store(str(tmp_path))
    assert stats["sessions"] == 2
    rows = {r["name"]: r for r in wh.verifier_sessions()}
    assert rows["wh-a"]["state"] == "open" and \
        rows["wh-a"]["valid"] is True
    assert rows["wh-b"]["state"] == "sealed" and \
        rows["wh-b"]["seal_equal"] == 1
    assert rows["wh-a"]["txns"] == rows["wh-b"]["txns"] > 0
    assert wh.rollups()["verifier_by_state"] == {"open": 1, "sealed": 1}
    # sessions are NOT runs: the run table stays empty
    assert wh.rollups()["runs_by_verdict"] == {}
    ex = prometheus.exposition(base=str(tmp_path),
                               registry=metrics.Registry())
    assert 'jepsen_warehouse_verifier_sessions{state="open"} 1' in ex
    assert 'jepsen_warehouse_verifier_sessions{state="sealed"} 1' in ex


def test_rebuild_from_torn_partial_store(tmp_path):
    """Satellite: a store with a truncated events.jsonl tail, a run
    missing telemetry.json, and a corrupt results.json still rebuilds
    into a consistent, re-ingestable warehouse."""
    _mk_run(tmp_path, "a-test", "t1", events=5, torn_events=True)
    _mk_run(tmp_path, "a-test", "t2", telemetry=False)
    d3 = _mk_run(tmp_path, "a-test", "t3", telemetry=False)
    with open(os.path.join(d3, "results.json"), "w") as f:
        f.write("{not json")
    _write_ledger(tmp_path, n=4)
    wh = wmod.open_or_create(str(tmp_path))
    stats = wh.rebuild(str(tmp_path))
    assert stats["runs"] == 3 and stats["records"] == 8
    assert stats["events"] == 5  # torn tail dropped, not ingested
    c1 = wh.counts()
    assert c1["runs"] == 3 and c1["events"] == 5
    # rebuild is idempotent: same state from scratch again
    assert wh.rebuild(str(tmp_path))["runs"] == 3
    assert wh.counts() == c1
    # ... and a plain re-ingest on top is a no-op
    assert wh.ingest_store(str(tmp_path)) == \
        {"ledgers": 1, "records": 0, "runs": 0, "events": 0,
         "sessions": 0, "fleet-events": 0, "archived": 0}


def test_v4_to_v5_migration_on_populated_store(tmp_path):
    """Satellite: opening a v4-era (PR 14) warehouse migrates it in
    place — rollups and timelines survive untouched, the new
    span_profile table and phase/counter columns stay empty until a
    re-ingest, and ``rebuild`` over span_profile is idempotent."""
    import sqlite3

    d = _mk_run(tmp_path, "a-test", "t1")
    tp = os.path.join(d, "telemetry.json")
    with open(tp) as f:
        doc = json.load(f)
    doc["spans"][0]["children"][0]["attrs"] = {"profile": {
        "elle.infer|i32[1024]": {"calls": 3, "compile_s": 0.21,
                                 "execute_s": 0.05,
                                 "device_dispatch_s": 0.012}}}
    doc["meta"] = {"host": "host-a"}
    with open(tp, "w") as f:
        json.dump(doc, f)
    path = _write_ledger(tmp_path, n=6)
    # graft phase buckets + forensic counters onto the ledger so
    # campaign_records exercises the v5 columns
    recs = [json.loads(ln) for ln in open(path)]
    for r in recs:
        r["phases"] = {"check:la": {"compile_s": 0.1,
                                    "execute_s": 0.2}}
        r["counters"] = {"compile-cache-miss{site=checker}": 2.0}
    with open(path, "w") as f:
        for r in recs:
            f.write(json.dumps(r) + "\n")
    rel = os.path.relpath(path, str(tmp_path))
    whp = wmod.warehouse_path(str(tmp_path))
    wh = wmod.Warehouse(whp)
    wh.ingest_store(str(tmp_path))
    PROF_SQL = ("SELECT dir, host, site, shape, calls, compile_s, "
                "execute_s, device_dispatch_s FROM span_profile "
                "ORDER BY dir, site, shape")
    prof0 = wh.query(PROF_SQL)[1]
    assert prof0 and prof0[0][1] == "host-a" and \
        prof0[0][2] == "elle.infer"
    roll0 = wh.query("SELECT * FROM span_rollup ORDER BY 1, 2")[1]
    gen0 = wh.query("SELECT * FROM span_gen_rollup ORDER BY 1, 2, 3")[1]
    nrec = wh.query("SELECT COUNT(*) FROM campaign_records")[1][0][0]
    assert roll0 and nrec == 12
    fr0 = wh.forensic_records(rel)
    assert fr0 and all(p and c for _, _, p, c in fr0)
    wh.close()

    # demote the file to v4: drop the ISSUE-16 surface wholesale.
    # DROP COLUMN needs sqlite >= 3.35, so the columns go via the
    # portable rename-copy-drop dance (which is also exactly what a
    # real PR-14-era file looks like: no phases/counters at all).
    V4_COLS = ("id, ledger, campaign, run, key, workload, fault, "
               "seed, valid, error, degraded, deadline, dir, ops, "
               "wall_s, gen, spec, ts, witness, trace")
    db = sqlite3.connect(whp)
    with db:
        db.execute("DROP TABLE span_profile")
        db.execute("ALTER TABLE campaign_records "
                   "RENAME TO campaign_records_v5")
        db.execute("""CREATE TABLE campaign_records(
            id INTEGER PRIMARY KEY, ledger TEXT NOT NULL,
            campaign TEXT, run TEXT, key TEXT, workload TEXT,
            fault TEXT, seed TEXT, valid TEXT, error TEXT,
            degraded TEXT, deadline INTEGER, dir TEXT, ops INTEGER,
            wall_s REAL, gen TEXT, spec TEXT, ts TEXT, witness TEXT,
            trace TEXT)""")
        db.execute(f"INSERT INTO campaign_records({V4_COLS}) "
                   f"SELECT {V4_COLS} FROM campaign_records_v5")
        db.execute("DROP TABLE campaign_records_v5")
        db.execute("CREATE INDEX IF NOT EXISTS cr_ledger_key ON "
                   "campaign_records(ledger, key, id)")
        db.execute("CREATE INDEX IF NOT EXISTS cr_ledger_run ON "
                   "campaign_records(ledger, run, id)")
        db.execute("INSERT OR REPLACE INTO meta(key, value) "
                   "VALUES ('schema_version', '4')")
    db.close()

    wh = wmod.Warehouse(whp)
    assert wh.query("SELECT value FROM meta WHERE key = "
                    "'schema_version'")[1][0][0] == str(
                        wmod.SCHEMA_VERSION)
    # rollups and timelines are untouched by the migration...
    assert wh.query("SELECT * FROM span_rollup "
                    "ORDER BY 1, 2")[1] == roll0
    assert wh.query("SELECT * FROM span_gen_rollup "
                    "ORDER BY 1, 2, 3")[1] == gen0
    assert wh.query("SELECT COUNT(*) FROM "
                    "campaign_records")[1][0][0] == nrec
    # ...but the new surface stays empty until a re-ingest; the
    # incremental path is a digest no-op, so rebuild is the
    # documented recovery route
    assert wh.query("SELECT COUNT(*) FROM span_profile")[1][0][0] == 0
    assert all(p == {} and c == {}
               for _, _, p, c in wh.forensic_records(rel))
    assert wh.ingest_store(str(tmp_path))["records"] == 0
    assert wh.query("SELECT COUNT(*) FROM span_profile")[1][0][0] == 0
    wh.rebuild(str(tmp_path))
    assert wh.query(PROF_SQL)[1] == prof0
    assert wh.forensic_records(rel) == fr0
    # rebuild twice: span_profile lands identical (idempotent)
    wh.rebuild(str(tmp_path))
    assert wh.query(PROF_SQL)[1] == prof0
    wh.close()


def test_event_ingest_rotation_resets_and_since_filter(tmp_path):
    from jepsen_tpu.telemetry.stream import EventStream

    d = os.path.join(str(tmp_path), "a-test", "t1")
    os.makedirs(d)
    with open(os.path.join(d, "results.json"), "w") as f:
        json.dump({"valid?": True}, f)
    p = os.path.join(d, "events.jsonl")
    s = EventStream(p, max_bytes=256, keep=9)
    for i in range(12):
        s.emit("tick", i=i)
    wh = wmod.open_or_create(str(tmp_path))
    n1 = wh.ingest_store(str(tmp_path))["events"]
    assert n1 > 12  # ticks + rotate/rotate-cont markers, all segments
    assert wh.events_fresh(d, str(tmp_path))
    # more events (and maybe another rotation): re-ingest catches up
    for i in range(12, 18):
        s.emit("tick", i=i)
    wh.ingest_store(str(tmp_path))
    evs = wh.events_since(d, str(tmp_path))
    ticks = [e["i"] for e in evs if e.get("ev") == "tick"]
    assert ticks == list(range(18))
    tick_evs = [e for e in evs if e.get("ev") == "tick"]
    cut = tick_evs[9]["t"]
    since = [e.get("i") for e in
             wh.events_since(d, str(tmp_path), since=cut)
             if e.get("ev") == "tick"]
    # compare against the same filter applied in python: two ticks
    # emitted within one timestamp-rounding quantum share a t, so the
    # cut may legitimately include a neighbor — the pin is that the
    # warehouse filter matches the scan semantics, not the clock
    assert since == [e["i"] for e in tick_evs if e["t"] >= cut]
    assert 9 in since and 17 in since and 0 not in since


def test_event_ingest_new_session_regrow_not_spliced(tmp_path):
    """A truncate-and-regrow NEW session that outgrows the old byte
    cursor must trigger a full re-ingest (the live file's first line
    is the session id), never an incremental append of new-session
    bytes after the old session's rows."""
    import time as _time

    from jepsen_tpu.telemetry.stream import EventStream

    d = os.path.join(str(tmp_path), "a-test", "t1")
    os.makedirs(d)
    with open(os.path.join(d, "results.json"), "w") as f:
        json.dump({"valid?": True}, f)
    p = os.path.join(d, "events.jsonl")
    s = EventStream(p)
    for i in range(3):
        s.emit("tick", i=i, session=1)
    wh = wmod.open_or_create(str(tmp_path))
    wh.ingest_store(str(tmp_path))
    assert wh.events_fresh(d, str(tmp_path))
    _time.sleep(0.01)  # a distinct first-event timestamp
    s2 = EventStream(p)  # new session: truncates the file
    for i in range(20):  # ...and regrows PAST the old cursor
        s2.emit("tick", i=i, session=2)
    assert not wh.events_fresh(d, str(tmp_path))
    wh.ingest_store(str(tmp_path))
    evs = [e for e in wh.events_since(d, str(tmp_path))
           if e.get("ev") == "tick"]
    assert {e.get("session") for e in evs} == {2}, \
        "old-session rows spliced in front of the new session"
    assert [e["i"] for e in evs] == list(range(20))


def test_cached_handle_detects_deleted_and_replaced_file(tmp_path):
    """A long-lived process (the web server) must not keep serving a
    warehouse that was rm'd or rebuilt on disk: the handle cache
    validates the path still names the inode it opened."""
    path = _write_ledger(tmp_path, n=3)
    wh = wmod.open_or_create(str(tmp_path))
    wh.ingest_ledger(path, str(tmp_path))
    assert wmod.open_if_exists(str(tmp_path)) is wh
    # deleted: open_if_exists returns None again (not the dead handle)
    os.remove(wmod.warehouse_path(str(tmp_path)))
    assert wmod.open_if_exists(str(tmp_path)) is None
    # rebuilt by "another process" (fresh inode): a NEW handle with
    # the new file's contents, not the unlinked one
    wh2 = wmod.Warehouse(wmod.warehouse_path(str(tmp_path)))
    wh2.ingest_ledger(path, str(tmp_path))
    wh2.close()
    wh3 = wmod.open_if_exists(str(tmp_path))
    assert wh3 is not None and wh3 is not wh
    assert wh3.counts()["campaign_records"] == 6


def test_bench_self_ingest_never_creates_a_store(tmp_path, monkeypatch):
    """bench.py's contract is one JSON line on stdout: the warehouse
    self-ingest only fires into an EXISTING store/ (or an explicit
    BENCH_WAREHOUSE) — it never grows a new filesystem footprint."""
    sys_path = os.path.dirname(os.path.dirname(
        os.path.abspath(wmod.__file__)))
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "bench_mod", os.path.join(os.path.dirname(sys_path), "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    payload = {"metric": "m", "value": 1.0, "unit": "ops/s"}
    monkeypatch.chdir(tmp_path)
    monkeypatch.delenv("BENCH_WAREHOUSE", raising=False)
    bench._ingest_warehouse(payload)
    assert not os.path.exists(tmp_path / "store"), "store/ created"
    # an existing store/ opts in
    os.makedirs(tmp_path / "store")
    bench._ingest_warehouse(payload)
    assert os.path.exists(tmp_path / "store" / "warehouse.sqlite")
    # explicit BENCH_WAREHOUSE always opts in
    monkeypatch.setenv("BENCH_WAREHOUSE", str(tmp_path / "w.sqlite"))
    bench._ingest_warehouse(payload)
    assert os.path.exists(tmp_path / "w.sqlite")


def test_obs_sql_cte_write_refused_at_engine_level(tmp_path):
    """`WITH x AS (SELECT 1) DELETE ...` passes a keyword prefix check
    — the read-only guard must hold at the sqlite level."""
    import sqlite3

    wh = wmod.open_or_create(str(tmp_path))
    wh.ingest_bench({"metric": "m", "value": 1.0}, "BENCH_r09.json")
    with pytest.raises(sqlite3.OperationalError):
        wh.query("WITH x AS (SELECT 1) DELETE FROM bench")
    assert len(wh.bench_series()) == 1  # nothing was deleted
    cols, rows = wh.query("SELECT COUNT(*) FROM bench")  # reads fine
    assert rows == [(1,)]


# ----------------------------------------------------- bench series

def test_bench_ingest_series_and_bad_file(tmp_path):
    wh = wmod.open_or_create(str(tmp_path))
    for i, v in ((3, 133000.0), (4, 186000.0), (5, 277000.0)):
        wh.ingest_bench({"metric": "check-throughput", "value": v,
                         "unit": "ops/s", "n_txns": 1000000,
                         "backend": "cpu"}, f"BENCH_r0{i}.json")
    series = wh.bench_series()
    assert [r["source"] for r in series] == \
        ["BENCH_r03.json", "BENCH_r04.json", "BENCH_r05.json"]
    assert [r["value"] for r in series] == [133000.0, 186000.0, 277000.0]
    # re-ingest overwrites by source key (no duplicate rows)
    wh.ingest_bench({"metric": "check-throughput", "value": 140000.0},
                    "BENCH_r03.json")
    assert len(wh.bench_series()) == 3
    bad = tmp_path / "bad.json"
    bad.write_text("{nope")
    assert wh.ingest_bench_file(str(bad)) is False
    # the committed BENCH_r0*.json are driver wrappers: the payload
    # rides under "parsed" and must be unwrapped, not ingested as 0s
    wrapped = tmp_path / "BENCH_r06.json"
    wrapped.write_text(json.dumps({
        "n": 6, "cmd": "python bench.py", "rc": 0, "tail": "...",
        "parsed": {"metric": "check-throughput", "value": 300000.0,
                   "unit": "ops/s", "backend": "cpu"}}))
    assert wh.ingest_bench_file(str(wrapped)) is True
    r06 = [r for r in wh.bench_series()
           if r["source"] == "BENCH_r06.json"][0]
    assert r06["value"] == 300000.0 and r06["unit"] == "ops/s"
    # a dict with no metric anywhere is refused, not ingested empty
    empty = tmp_path / "empty.json"
    empty.write_text(json.dumps({"rc": 0, "tail": "no json line"}))
    assert wh.ingest_bench_file(str(empty)) is False


# ------------------------------------------------------ the gate

def test_mann_whitney_u_detects_shift_and_ignores_ties():
    rng = random.Random(7)
    a = [rng.uniform(1.0, 1.2) for _ in range(20)]
    b = [x * 1.5 for x in a]
    assert gate.mann_whitney_u(a, b)["p"] < 0.001
    assert gate.mann_whitney_u(b, a)["p"] > 0.99  # one-sided: b larger
    assert gate.mann_whitney_u([1.0] * 10, [1.0] * 10)["p"] == 1.0
    assert gate.mann_whitney_u([], [1.0])["p"] == 1.0


def test_gate_samples_statuses():
    rng = random.Random(3)
    old = [rng.uniform(1.0, 1.2) for _ in range(15)]
    same = [rng.uniform(1.0, 1.2) for _ in range(15)]
    assert gate.gate_samples(old, same)["status"] == "pass"
    worse = [x * 1.5 for x in old]
    res = gate.gate_samples(old, worse)
    assert res["status"] == "regression" and res["rel_delta"] > 0.25
    # statistically detectable but operationally tiny: pass
    tiny = [x * 1.05 for x in old]
    assert gate.gate_samples(old, tiny)["status"] == "pass"
    # a huge delta on 2 runs: insufficient data, not a silent verdict
    assert gate.gate_samples([1.0, 1.0], [9.9, 9.9])["status"] == \
        "insufficient-data"


def test_cli_obs_gate_pass_and_regression_exit_codes(tmp_path, capsys):
    """Acceptance: gate passes an unchanged generation pair (rc 0) and
    fails an injected +50% p95 regression (rc 1); unknown span rc 2."""
    _write_ledger(tmp_path, gens=("g1", "g2", "g3"),
                  scale={"g3": 1.5}, n=20)
    argv = ["--store-dir", str(tmp_path)]
    disp = cli.single_test_cmd(lambda o: {})
    assert cli.run(disp, argv + ["obs", "ingest"]) == 0
    rc = cli.run(disp, argv + ["obs", "gate", "--campaign", "soak",
                               "--span", "check:la",
                               "--from-gen", "g1", "--to-gen", "g2"])
    assert rc == 0
    assert "PASS" in capsys.readouterr().out
    rc = cli.run(disp, argv + ["obs", "gate", "--campaign", "soak",
                               "--span", "check:la",
                               "--from-gen", "g2", "--to-gen", "g3"])
    assert rc == 1
    assert "REGRESSION" in capsys.readouterr().out
    rc = cli.run(disp, argv + ["obs", "gate", "--campaign", "soak",
                               "--span", "no-such-span"])
    assert rc == 2
    # default generation pair = the two most recent
    rc = cli.run(disp, argv + ["obs", "gate", "--campaign", "soak",
                               "--span", "check:la"])
    assert rc == 1
    # a half-specified pair resolving to self-comparison is refused
    # (would otherwise pass forever), not silently passed
    capsys.readouterr()
    rc = cli.run(disp, argv + ["obs", "gate", "--campaign", "soak",
                               "--span", "check:la",
                               "--from-gen", "g3"])
    assert rc == 2
    assert "from-gen == to-gen" in capsys.readouterr().out


def test_gate_works_without_warehouse_via_scan(tmp_path, capsys):
    _write_ledger(tmp_path, gens=("g1", "g2"), n=10)
    disp = cli.single_test_cmd(lambda o: {})
    rc = cli.run(disp, ["--store-dir", str(tmp_path), "obs", "gate",
                        "--campaign", "soak", "--span", "check:la"])
    assert rc == 0  # jsonl fallback: no warehouse was ever built


# ------------------------------------------------- obs CLI + sql

def test_cli_obs_ingest_rebuild_sql_bench(tmp_path, capsys):
    _write_ledger(tmp_path, n=5)
    _mk_run(tmp_path, "a-test", "t1")
    bench = tmp_path / "BENCH_r05.json"
    bench.write_text(json.dumps({"metric": "m", "value": 277000.0,
                                 "unit": "ops/s", "n_txns": 1000000,
                                 "backend": "cpu"}))
    argv = ["--store-dir", str(tmp_path)]
    disp = cli.single_test_cmd(lambda o: {})
    rc = cli.run(disp, argv + ["obs", "ingest", "--bench", str(bench)])
    assert rc == 0
    out = capsys.readouterr().out
    assert "10 records" in out and "1 runs" in out and "1 bench" in out
    assert cli.run(disp, argv + ["obs", "rebuild"]) == 0
    capsys.readouterr()
    rc = cli.run(disp, argv + [
        "obs", "sql", "SELECT COUNT(*) FROM campaign_records"])
    assert rc == 0
    assert capsys.readouterr().out.splitlines()[1] == "10"
    # writes refused
    assert cli.run(disp, argv + ["obs", "sql",
                                 "DELETE FROM campaign_records"]) == 2
    capsys.readouterr()
    assert cli.run(disp, argv + ["obs", "bench"]) == 0
    assert "BENCH_r05.json" in capsys.readouterr().out
    # a --bench that matches/ingests nothing (typo'd glob) fails loudly
    # instead of leaving CI green with a silently stale bench series
    assert cli.run(disp, argv + ["obs", "ingest", "--bench",
                                 str(tmp_path / "BENCH_r0*.jsn")]) == 2


def test_cli_obs_query_surfaces_need_warehouse(tmp_path, capsys):
    disp = cli.single_test_cmd(lambda o: {})
    rc = cli.run(disp, ["--store-dir", str(tmp_path), "obs", "sql",
                        "SELECT 1"])
    assert rc == 2
    assert "no warehouse" in capsys.readouterr().err


# ------------------------------------------------ prometheus golden

GOLDEN = os.path.join(os.path.dirname(__file__), "data",
                      "prometheus-golden.txt")


class _GoldenFleet:
    """A deterministic stand-in for the coordinator's federated-
    metrics surface (ISSUE 14): two alive workers' pushed snapshots."""

    def federated_metrics(self):
        return {
            "w1": {"host": "h1", "age-s": 1.0, "version": "v1",
                   "rows": [
                {"name": "worker-cells-done", "kind": "counter",
                 "labels": {}, "value": 3},
                {"name": "jit-cache-entries", "kind": "gauge",
                 "labels": {}, "value": 7},
                {"name": "worker-rss-peak-bytes", "kind": "gauge",
                 "labels": {}, "value": 120_000_000},
                {"name": "compile-cache-hits", "kind": "counter",
                 "labels": {}, "value": 9},
            ]},
            "w2": {"host": "h2", "age-s": 2.0, "version": "v2",
                   "rows": [
                {"name": "worker-cells-done", "kind": "counter",
                 "labels": {}, "value": 5},
                {"name": "jit-cache-entries", "kind": "gauge",
                 "labels": {}, "value": 4},
                {"name": "worker-rss-peak-bytes", "kind": "gauge",
                 "labels": {}, "value": 95_000_000},
            ]},
        }


def _golden_exposition(base):
    """A deterministic exposition: fixed registry (including the ISSUE 7
    verifier instruments), the ISSUE 14 federated fleet series, one
    heartbeat at a pinned age, and a warehouse with one ledger + one
    running run + one verifier session + one bench row."""
    reg = metrics.Registry()
    reg.counter("ops-invoked", worker=0).inc(42)
    reg.counter("resilience-faults-injected", site="elle.infer").inc(3)
    reg.gauge("checker-ops-per-s", checker="list-append").set(277000.5)
    reg.gauge("unset-gauge")  # never set: skipped from the exposition
    h = reg.histogram("probe-s", (0.1, 1.0), path='a"b\\c\nd')
    for v in (0.05, 0.5, 5.0):
        h.observe(v)
    # verifier gauges (ISSUE 7 satellite): sessions active, ops
    # ingested, per-session verdict freshness, sweep duration buckets
    reg.gauge("verifier-sessions-active").set(2)
    reg.counter("verifier-ops-ingested").inc(1234)
    reg.gauge("verifier-verdict-freshness-s", session="s1").set(0.25)
    sw = reg.histogram("verifier-sweep-s", (0.001, 0.01, 0.1, 1.0, 10.0))
    for v in (0.005, 0.02, 0.02, 0.3):
        sw.observe(v)
    # session lifecycle + live checking + store federation (ISSUE 13):
    # journal bytes bounded by compaction, compaction count, degraded
    # live streams, artifact uploads by protocol state
    reg.gauge("verifier-journal-bytes").set(5120)
    reg.counter("verifier-compactions").inc(3)
    reg.counter("verifier-live-degraded").inc(1)
    for state, n in (("started", 2), ("chunk", 9), ("resumed", 1),
                     ("landed", 2), ("rejected", 1)):
        reg.counter("fleet-artifact-uploads", state=state).inc(n)
    # fleet gauges (ISSUE 9 satellite): the coordinator's control-plane
    # view — workers alive by heartbeat freshness, active leases, cells
    # by state, requeue/duplicate counters attributed per worker
    reg.gauge("fleet-workers-alive").set(3)
    reg.gauge("fleet-leases-active").set(2)
    for state, n in (("queued", 4), ("claimed", 2), ("done", 6)):
        reg.gauge("fleet-cells", state=state).set(n)
    reg.counter("fleet-requeues", worker="w1",
                reason="lease-expired").inc(2)
    reg.counter("fleet-duplicate-completions", worker="w1").inc(1)
    # coordinated chaos (ISSUE 11 satellite): currently-open
    # synchronized nemesis windows by fault family, and worker-affine
    # placement deferrals
    reg.gauge("fleet-nemesis-windows-active", campaign="soak",
              fault="skew").set(1)
    reg.gauge("fleet-nemesis-windows-active", campaign="soak",
              fault="partition").set(0)
    reg.counter("fleet-affinity-deferrals", worker="w1").inc(3)
    # fleet observability (ISSUE 14): staging retention + compile-cost
    # groundwork gauges on the coordinator/worker registries
    reg.gauge("fleet-artifact-staging-bytes").set(4096)
    reg.gauge("jit-cache-entries").set(11)
    reg.counter("compile-cache-miss", site="elle.infer").inc(2)
    # AOT compile cache (ISSUE 18): hit/miss/byte counters + the entry
    # gauge on the live registry (federated over the fleet heartbeat
    # like every registry series), fall-through by seam site, and
    # fleet entry-transfer states
    reg.counter("compile-cache-hits").inc(9)
    reg.counter("compile-cache-misses").inc(4)
    reg.counter("compile-cache-bytes").inc(3131146)
    reg.counter("compile-cache-fallthrough",
                site="elle.core-check").inc(1)
    reg.gauge("compile-cache-entries").set(3)
    reg.counter("compile-cache-transfers", state="pushed").inc(2)
    reg.counter("compile-cache-transfers", state="absorbed").inc(2)
    # memory watermarks (ISSUE 16): peak-RSS / per-device / jit-cache
    # high-watermark gauges published by the resource sampler
    reg.gauge("process-rss-peak-bytes").set(104857600)
    reg.gauge("device-memory-peak-bytes", device="cpu:0").set(8388608)
    reg.gauge("jit-cache-entries-peak").set(13)
    # autopilot (ISSUE 17): the scaler's two inputs (queue depth +
    # claim-latency p95) and the continuous loop's own state gauges
    reg.gauge("fleet-queue-depth").set(4)
    reg.gauge("fleet-claim-latency-p95-s").set(0.42)
    reg.gauge("fleet-quarantined-cells").set(1)
    reg.gauge("fleet-paroled-cells").set(1)
    reg.gauge("fleet-autopilot-generations").set(5)
    # queue family (ISSUE 19): anomalies the packed checkers attribute
    # and adversarial-client injections by shape
    reg.counter("queue-anomalies-found", anomaly="lost-write").inc(2)
    reg.counter("queue-anomalies-found", anomaly="duplicate").inc(3)
    reg.counter("queue-adversarial-injections",
                shape="torn-send").inc(2)
    reg.counter("queue-adversarial-injections",
                shape="zombie-resend").inc(1)
    cdir = os.path.join(str(base), "campaigns")
    os.makedirs(cdir, exist_ok=True)
    with open(os.path.join(cdir, "soak.live.json"), "w") as f:
        json.dump({"campaign": "soak", "updated": 990.0, "total": 12,
                   "done": 7, "workers": {"0": {"run": "x"}},
                   "finished": False}, f)
    path = _write_ledger(base, n=2, flip_every=1)
    # one in-progress run (no results.json yet) -> status=running row
    os.makedirs(os.path.join(str(base), "live-test", "t0"),
                exist_ok=True)
    # one verifier session snapshot -> warehouse verifier gauge
    vdir = os.path.join(str(base), "verifier", "s1")
    os.makedirs(vdir, exist_ok=True)
    with open(os.path.join(vdir, "session.json"), "w") as f:
        json.dump({"session": "s1", "state": "open", "txns": 10,
                   "ops": 40, "segments": 2, "updated": 995.0,
                   "verdict": {"valid?": True, "anomaly-types": []}}, f)
    wh = wmod.open_or_create(str(base))
    wh.ingest_store(str(base), events=False)
    wh.ingest_bench({"metric": "check-throughput", "value": 277000.0,
                     "unit": "ops/s", "n_txns": 1000000,
                     "backend": "cpu"}, "BENCH_r05.json")
    # the watchtower (ISSUE 20): one firing + one pending alert in the
    # durable journal -> literal ALERTS{...} series on the exposition
    # (deterministic: state comes from the injected evaluation `now`)
    from jepsen_tpu.telemetry import alerts as alerts_mod

    eng = alerts_mod.AlertEngine(str(base), rules=alerts_mod.load_rules([
        {"name": "claim-latency-blowout", "kind": "threshold",
         "severity": "page", "signal": "gauge:x", "op": ">",
         "value": 0.0, "for": 0.0},
        {"name": "journal-growth", "kind": "threshold",
         "severity": "warn", "signal": "gauge:x", "op": ">",
         "value": 0.0, "for": 3600.0}]), sinks=[])
    eng.evaluate(signals={"gauge:x": 1.0}, now=990.0)
    return prometheus.exposition(base=str(base), registry=reg,
                                 now=1000.0, fleet=_GoldenFleet())


def test_prometheus_exposition_matches_golden(tmp_path):
    """Satellite: the exposition format is pinned byte-for-byte —
    # HELP/# TYPE blocks, cumulative histogram _bucket/_sum/_count,
    label escaping — so the endpoint stays scrape-compatible.  If this
    fails because of an INTENTIONAL format change, regenerate with:
    python -m tests.test_warehouse"""
    got = _golden_exposition(tmp_path)
    with open(GOLDEN) as f:
        want = f.read()
    assert got == want


def test_exposition_names_and_escaping():
    assert prometheus.metric_name("checker-ops-per-s") == \
        "jepsen_checker_ops_per_s"
    assert prometheus.metric_name("9bad") == "jepsen__9bad"
    assert prometheus.escape_label_value('a"b\\c\nd') == \
        'a\\"b\\\\c\\nd'


def test_exposition_histogram_buckets_cumulative_and_ordered():
    reg = metrics.Registry()
    h = reg.histogram("lat-s", (0.1, 1.0))
    for v in (0.05, 0.06, 0.5, 5.0):
        h.observe(v)
    lines = prometheus.render_registry(reg)
    buckets = [ln for ln in lines if "_bucket" in ln]
    assert buckets == [
        'jepsen_lat_s_bucket{le="0.1"} 2',
        'jepsen_lat_s_bucket{le="1"} 3',
        'jepsen_lat_s_bucket{le="+Inf"} 4',
    ]
    assert "jepsen_lat_s_sum 5.61" in lines
    assert "jepsen_lat_s_count 4" in lines


# ------------------------------------------------- the gate smoke

def test_gate_bench_script_smoke():
    """scripts/gate_bench.py end-to-end (ISSUE 6 satellite): a real
    mini-campaign + synthesized unchanged/regressed generations, gated
    through the obs CLI on both backends — regression gating runs in
    tier-1."""
    import subprocess
    import sys

    script = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "scripts", "gate_bench.py")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run([sys.executable, script, "--runs", "4"],
                          capture_output=True, text=True, timeout=300,
                          env=env)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "gate smoke OK" in proc.stdout
    assert "REGRESSION" in proc.stdout and "PASS" in proc.stdout


if __name__ == "__main__":  # regenerate the golden file
    import shutil
    import tempfile

    tmp = tempfile.mkdtemp(prefix="prom-golden-")
    try:
        doc = _golden_exposition(tmp)
        with open(GOLDEN, "w") as f:
            f.write(doc)
        print(f"wrote {GOLDEN}:\n{doc}")
    finally:
        shutil.rmtree(tmp)
