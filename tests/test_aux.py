"""Aux subsystem tests: role, lazyfs, faketime, fs_cache, report, repl
(SURVEY.md §2.1 aux rows), driven through the sim control plane."""

import os

import pytest

from jepsen_tpu import (control, core, db, faketime, fs_cache, lazyfs, repl,
                        report, role, store)
from jepsen_tpu.checkers.api import Stats
from jepsen_tpu.control.sim import SimRemote
from jepsen_tpu.generator import core as g
from jepsen_tpu.workloads.mem import MemClient


# ---------------------------------------------------------------- role

class TrackDB(db.DB):
    def __init__(self, name):
        self.name = name
        self.calls = []

    def setup(self, test, node):
        self.calls.append(("setup", node, tuple(test["nodes"])))

    def teardown(self, test, node):
        self.calls.append(("teardown", node, tuple(test["nodes"])))


def test_role_of_and_nodes():
    t = {"roles": {"shard-a": ["n1", "n2"], "coord": ["n3"]}}
    assert role.role_of(t, "n1") == "shard-a"
    assert role.role_of(t, "n3") == "coord"
    assert role.role_of(t, "nx") is None
    assert role.nodes_of(t, "shard-a") == ["n1", "n2"]


def test_role_db_dispatch(tmp_path):
    shard_db = TrackDB("shard")
    coord_db = TrackDB("coord")
    rdb = role.RoleDB({"shard-a": shard_db, "coord": coord_db})
    remote = SimRemote()
    for n in ("n1", "n2", "n3"):
        remote.node(n).respond("*", "")
    t = {
        "name": "role-test", "nodes": ["n1", "n2", "n3"],
        "roles": {"shard-a": ["n1", "n2"], "coord": ["n3"]},
        "remote": remote, "db": rdb, "client": MemClient(),
        "concurrency": 2, "store-dir": str(tmp_path / "s"),
        "generator": g.clients(g.limit(
            4, lambda t, c: {"f": "read", "value": None})),
        "checker": Stats(),
    }
    done = core.run(t)
    assert done["results"]["valid?"] is True
    # each role db saw only its own nodes, with a restricted node view
    assert {c[1] for c in shard_db.calls} == {"n1", "n2"}
    assert all(c[2] == ("n1", "n2") for c in shard_db.calls)
    assert {c[1] for c in coord_db.calls} == {"n3"}
    assert all(c[2] == ("n3",) for c in coord_db.calls)


def test_role_nemesis_scoped():
    from jepsen_tpu.nemesis.core import Nemesis

    seen = {}

    class Grab(Nemesis):
        def invoke(self, test, op):
            seen["nodes"] = list(test["nodes"])
            return dict(op, type="info")

    rn = role.RoleNemesis("coord", Grab())
    t = {"nodes": ["n1", "n2", "n3"],
         "roles": {"shard-a": ["n1", "n2"], "coord": ["n3"]}}
    rn = rn.setup(t)
    rn.invoke(t, {"f": "kill", "type": "invoke"})
    assert seen["nodes"] == ["n3"]


# ---------------------------------------------------------------- lazyfs

def test_lazyfs_mount_commands():
    remote = SimRemote()
    node = remote.node("n1")
    node.respond("*", "")
    fs = lazyfs.LazyFS(dir="/var/lib/db")
    assert fs.data_dir == "/var/lib/db.data"
    assert fs.fifo == "/var/lib/db.fifo"
    with control.with_session("n1", remote.connect("n1")):
        lazyfs.mount(fs)
        lazyfs.lose_unfsynced_writes(fs)
        lazyfs.checkpoint(fs)
        lazyfs.umount(fs)
    cmds = node.cmds()
    assert any("lazyfs" in c and "/var/lib/db" in c for c in cmds)
    assert any("clear-cache" in c for c in cmds)
    assert any("cache-checkpoint" in c for c in cmds)
    assert any("fusermount" in c for c in cmds)


def test_lazyfs_db_wrapper_forwards_facets():
    inner = TrackDB("inner")
    wrapped = lazyfs.DB(inner, lazyfs.LazyFS(dir="/d"))
    assert wrapped.name == "inner"  # __getattr__ forwarding


# ---------------------------------------------------------------- faketime

def test_faketime_spec_and_wrap():
    assert faketime.faketime_spec(5, 2.0) == "+5s x2"
    assert faketime.faketime_spec(-3.5, 0.5) == "-3.5s x0.5"
    remote = SimRemote()
    node = remote.node("n1")
    node.respond("test -e /usr/lib/x86_64-linux-gnu/faketime/*", "")
    node.respond("*", "")
    with control.with_session("n1", remote.connect("n1")):
        cmd = faketime.wrap_cmd(["etcd", "--flag"], offset_s=10, rate=5)
    joined = control.core.join_cmd(cmd)
    assert "LD_PRELOAD=" in joined and "FAKETIME=" in joined
    assert joined.endswith("etcd --flag")


def test_faketime_rand_factor_bounds():
    import random
    for _ in range(50):
        f = faketime.rand_factor(random.Random(), max_skew=5.0)
        assert 1 / 5.0 <= f <= 5.0


# ---------------------------------------------------------------- fs_cache

def test_fs_cache_save_and_deploy(tmp_path, monkeypatch):
    monkeypatch.setattr(fs_cache, "CACHE_DIR", str(tmp_path / "cache"))
    src = tmp_path / "artifact.tar"
    src.write_bytes(b"dbdata")
    p = fs_cache.save("etcd-v3.5", str(src))
    assert fs_cache.cached("etcd-v3.5") == p
    assert fs_cache.cached("nope") is None

    remote = SimRemote()
    node = remote.node("n1")
    node.respond("*", "")
    with control.with_session("n1", remote.connect("n1")):
        fs_cache.deploy_remote("etcd-v3.5", "/opt/db/etcd.tar", mode="755")
    cmds = node.cmds()
    assert any("mkdir" in c for c in cmds)
    assert any("chmod 755" in c for c in cmds)
    assert ("/opt/db/etcd.tar", p) in [(d, s) for (s, d) in node.uploads] \
        or node.uploads  # upload recorded


def test_fs_cache_deploy_uncached_raises():
    with pytest.raises(FileNotFoundError):
        fs_cache.deploy_remote("never-cached", "/tmp/x")


# ---------------------------------------------------------------- report/repl

def test_report_render():
    t = {"name": "demo", "history": [1, 2, 3],
         "results": {"valid?": False, "anomaly-types": ["G1c"],
                     "count": 3}}
    out = report.render(t)
    assert "✗ demo" in out and "G1c" in out and "count: 3" in out
    t["results"]["valid?"] = True
    assert "✓" in report.render(t)


def test_repl_roundtrip(tmp_path):
    base = str(tmp_path / "s")
    t = core.run({
        "name": "repl-test", "client": MemClient(), "concurrency": 2,
        "nodes": ["n1"], "store-dir": base,
        "generator": g.clients(g.limit(
            4, lambda t, c: {"f": "read", "value": None})),
        "checker": Stats(),
    })
    loaded = repl.latest("repl-test", base=base)
    assert loaded["name"] == "repl-test"
    h = repl.history(loaded)
    assert len(h) == 8
    re = repl.recheck(loaded, Stats())
    assert re["results"]["valid?"] is True
    assert len(repl.runs("repl-test", base=base)) == 1
