"""Closed-predicate checker tests (elle/closed_predicate.clj style):
micro-histories pinning phantoms and predicate anomalies."""

import pytest

from jepsen_tpu.checkers.elle import closed_predicate
from jepsen_tpu.history import history, invoke, ok, fail


def concurrent_history(*txns):
    inv, comp = [], []
    for i, (mops_inv, mops_ok) in enumerate(txns):
        inv.append(invoke(i, "txn", mops_inv))
        if mops_ok == "fail":
            comp.append(fail(i, "txn", mops_inv))
        else:
            comp.append(ok(i, "txn", mops_ok))
    return history(inv + comp)


def test_valid_serial_inserts_and_read_all():
    h = history([
        invoke(0, "txn", [("insert", "a", 1)]),
        ok(0, "txn", [("insert", "a", 1)]),
        invoke(0, "txn", [("insert", "b", 2)]),
        ok(0, "txn", [("insert", "b", 2)]),
        invoke(1, "txn", [("rp", "all", None)]),
        ok(1, "txn", [("rp", "all", {"a": 1, "b": 2})]),
    ])
    res = closed_predicate.check(h, ["serializable"])
    assert res["valid?"] is True, res


def test_phantom_write_skew_detected():
    # classic predicate write skew: each txn reads all (sees only its
    # own absence) then inserts — both predicate reads miss the other's
    # insert, forming a phantom rw cycle
    h = concurrent_history(
        ([("rp", "all", None), ("insert", "a", 1)],
         [("rp", "all", {}), ("insert", "a", 1)]),
        ([("rp", "all", None), ("insert", "b", 2)],
         [("rp", "all", {}), ("insert", "b", 2)]),
    )
    res = closed_predicate.check(h, ["serializable"])
    assert res["valid?"] is False, res
    assert any(a.endswith("-predicate") for a in res["anomaly-types"]), res


def test_read_all_missing_committed_insert_is_phantom_edge():
    # serial: T0 inserts a; T1 later reads all and MISSES a -> the
    # forced unborn binding anti-depends on T0, and realtime order makes
    # it a cycle (strict-serializable violation)
    h = history([
        invoke(0, "txn", [("insert", "a", 1)]),
        ok(0, "txn", [("insert", "a", 1)]),
        invoke(1, "txn", [("rp", "all", None)]),
        ok(1, "txn", [("rp", "all", {})]),
    ])
    res = closed_predicate.check(h, ["strict-serializable"])
    assert res["valid?"] is False, res


def test_equality_predicate_matched_and_phantom():
    # T2 reads (= 1): sees a=1; key b (written once, value 2, never
    # matching) is a forced unborn->2 chain with one non-matching
    # written version... ambiguous bindings emit nothing, so this stays
    # valid under serializable
    h = history([
        invoke(0, "txn", [("insert", "a", 1)]),
        ok(0, "txn", [("insert", "a", 1)]),
        invoke(0, "txn", [("insert", "b", 2)]),
        ok(0, "txn", [("insert", "b", 2)]),
        invoke(1, "txn", [("rp", ("=", 1), None)]),
        ok(1, "txn", [("rp", ("=", 1), {"a": 1})]),
    ])
    res = closed_predicate.check(h, ["serializable"])
    assert res["valid?"] is True, res


def test_delete_then_read_all_sees_nothing():
    h = history([
        invoke(0, "txn", [("insert", "a", 1)]),
        ok(0, "txn", [("insert", "a", 1)]),
        invoke(0, "txn", [("delete", "a")]),
        ok(0, "txn", [("delete", "a")]),
        invoke(1, "txn", [("rp", "all", None)]),
        ok(1, "txn", [("rp", "all", {})]),
    ])
    res = closed_predicate.check(h, ["strict-serializable"])
    assert res["valid?"] is True, res


def test_structural_anomalies_reported():
    # reading a value never written, and inserting over a live key
    h = history([
        invoke(0, "txn", [("insert", "a", 1)]),
        ok(0, "txn", [("insert", "a", 1)]),
        invoke(0, "txn", [("insert", "a", 9)]),
        ok(0, "txn", [("insert", "a", 9)]),
        invoke(1, "txn", [("rp", "all", None)]),
        ok(1, "txn", [("rp", "all", {"a": 7})]),
    ])
    res = closed_predicate.check(h, ["serializable"])
    assert res["valid?"] is False
    assert "insert-of-live-key" in res["anomaly-types"]
    assert "predicate-read-of-unwritten" in res["anomaly-types"]


def test_g1c_predicate_wr_cycle():
    # each txn's predicate read observes the other's insert: wr cycle
    h = concurrent_history(
        ([("insert", "a", 1), ("rp", "all", None)],
         [("insert", "a", 1), ("rp", "all", {"a": 1, "b": 2})]),
        ([("insert", "b", 2), ("rp", "all", None)],
         [("insert", "b", 2), ("rp", "all", {"a": 1, "b": 2})]),
    )
    res = closed_predicate.check(h, ["read-committed"])
    assert res["valid?"] is False, res
