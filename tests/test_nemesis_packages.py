"""Clock / file / membership nemeses and combined packages."""

import os
import random
import subprocess

import pytest

from jepsen_tpu import control, db as db_, net as net_
from jepsen_tpu.control.local import LoopbackRemote
from jepsen_tpu.control.sim import SimRemote
from jepsen_tpu.generator.sim import simulate
from jepsen_tpu.nemesis import combined, membership
from jepsen_tpu.nemesis.file import FileCorruptionNemesis
from jepsen_tpu.nemesis.time import HELPER_SRC, ClockNemesis

NODES = ["n1", "n2", "n3"]


def sim_test(**extra):
    t = {"nodes": list(NODES), "remote": SimRemote(), "net": net_.SimNet()}
    t.update(extra)
    return t


# ---------------------------------------------------------------- clock

def test_bump_time_c_compiles(tmp_path):
    out = tmp_path / "bump_time"
    subprocess.run(["cc", "-O2", "-o", str(out), HELPER_SRC], check=True)
    r = subprocess.run([str(out)], capture_output=True)
    assert r.returncode == 2  # usage
    assert b"usage" in r.stderr


def test_clock_nemesis_cmds():
    t = sim_test()
    nem = ClockNemesis().setup(t)
    # setup uploaded + compiled on every node
    for n in NODES:
        node = t["remote"].node(n)
        assert node.uploads, f"no upload on {n}"
        assert any("cc -O2" in c for c in node.cmds())
    comp = nem.invoke(t, {"f": "bump-clock", "value": {"n2": 5000},
                          "type": "invoke"})
    assert comp["type"] == "info"
    assert any("bump_time bump 5000" in c
               for c in t["remote"].node("n2").cmds())
    nem.invoke(t, {"f": "strobe-clock",
                   "value": {"delta_ms": 100, "period_ms": 5,
                             "duration_ms": 50, "nodes": ["n1"]},
                   "type": "invoke"})
    assert any("strobe 100 5 50" in c for c in t["remote"].node("n1").cmds())
    t["remote"].node("n3").respond("date*", "0")
    nem.invoke(t, {"f": "reset-clock", "value": None, "type": "invoke"})
    assert any(c.startswith("date -u -s")
               for c in t["remote"].node("n3").cmds())


# ---------------------------------------------------------------- file

def test_file_corruption_loopback(tmp_path):
    t = {"nodes": ["n1"], "remote": LoopbackRemote(base_dir=str(tmp_path))}
    target = "data/db.bin"
    with control.with_session("n1", t["remote"].connect("n1")):
        control.exec_("mkdir", "-p", "data")
        control.exec_("bash", "-c",
                      f"head -c 4096 /dev/zero > {target}")
    nem = FileCorruptionNemesis(target)
    original = (tmp_path / "n1" / target).read_bytes()

    nem.invoke(t, {"f": "snapshot-file", "value": None, "type": "invoke"})
    comp = nem.invoke(t, {"f": "bitflip-file", "value": None,
                          "type": "invoke"})
    assert comp["type"] == "info"
    corrupted = (tmp_path / "n1" / target).read_bytes()
    assert corrupted != original, "bitflip changed nothing"
    assert len(corrupted) == len(original)

    nem.invoke(t, {"f": "truncate-file", "value": {"bytes": 100},
                   "type": "invoke"})
    assert (tmp_path / "n1" / target).stat().st_size == 4096 - 100


# ------------------------------------------------------------ membership

class FakeMembers(membership.MembershipState):
    def __init__(self, nodes):
        self.members = set(nodes)
        self._pending = None

    def view(self, test):
        # converge one poll after apply
        if self._pending:
            op, steps = self._pending
            if steps <= 0:
                if op["f"] == "leave-node":
                    self.members.discard(op["value"])
                else:
                    self.members.add(op["value"])
                self._pending = None
            else:
                self._pending = (op, steps - 1)
        return set(self.members)

    def possible_ops(self, test, view):
        if len(view) > 1:
            return [{"f": "leave-node", "value": sorted(view)[-1],
                     "type": "invoke"}]
        return []

    def apply_op(self, test, op):
        self._pending = (op, 1)
        return "requested"

    def converged(self, test, view, op):
        if op["f"] == "leave-node":
            return op["value"] not in view
        return op["value"] in view


def test_membership_nemesis_converges():
    t = sim_test()
    st = FakeMembers(NODES)
    nem = membership.MembershipNemesis(st, converge_timeout_s=5,
                                       poll_interval_s=0.01).setup(t)
    ops = membership.possible_op(st, t)
    comp = nem.invoke(t, ops)
    assert comp["value"]["converged"] is True
    assert st.members == {"n1", "n2"}


# ---------------------------------------------------------------- combined

class FakeProcDB(db_.DB, db_.Process, db_.Pause):
    def __init__(self):
        self.state = {}

    def start(self, test, node):
        self.state[node] = "up"

    def kill(self, test, node):
        self.state[node] = "down"

    def pause(self, test, node):
        self.state[node] = "paused"

    def resume(self, test, node):
        self.state[node] = "up"


def test_nemesis_package_composition():
    rng = random.Random(0)
    pkg = combined.nemesis_package({
        "faults": {"partition", "kill", "pause"},
        "db": FakeProcDB(), "interval": 1.0, "rng": rng})
    assert pkg["nemesis"] is not None
    assert pkg["generator"] is not None
    assert len(pkg["perf"]) == 3
    assert pkg["final_generator"]


def test_nemesis_package_generator_schedule():
    rng = random.Random(0)
    pkg = combined.nemesis_package({
        "faults": {"kill"}, "db": FakeProcDB(), "interval": 1.0,
        "rng": rng})
    import jepsen_tpu.generator as g
    evs = simulate(g.time_limit(5.0, pkg["generator"]),
                   {"concurrency": 1})
    fs = [e["f"] for e in evs if e["type"] == "invoke"]
    # 5s at interval 1 -> kill@1, start@2, kill@3, start@4
    assert fs[:4] == ["kill", "start", "kill", "start"]


def test_kill_package_invokes_db():
    rng = random.Random(0)
    d = FakeProcDB()
    t = sim_test()
    pkg = combined.nemesis_package({"faults": {"kill"}, "db": d,
                                    "rng": rng})
    nem = pkg["nemesis"].setup(t)
    comp = nem.invoke(t, {"f": "kill", "value": None, "type": "invoke"})
    killed = comp["value"]
    assert len(killed) == 1 and d.state[killed[0]] == "down"
    comp2 = nem.invoke(t, {"f": "start", "value": None, "type": "invoke"})
    assert d.state[killed[0]] == "up"


def test_partition_package_full_cycle():
    rng = random.Random(3)
    t = sim_test()
    pkg = combined.nemesis_package({"faults": {"partition"},
                                    "interval": 1.0, "rng": rng})
    nem = pkg["nemesis"].setup(t)
    # drive the generator for one start op (fn-valued, needs test map)
    import jepsen_tpu.generator as g
    evs = simulate(g.time_limit(2.5, pkg["generator"]),
                   {"concurrency": 1, "nodes": list(NODES)})
    starts = [e for e in evs
              if e["type"] == "invoke" and e["f"] == "start-partition"]
    assert starts and starts[0]["value"], "grudge chosen by generator"
    comp = nem.invoke(t, starts[0])
    assert t["net"].blocked
    nem.invoke(t, {"f": "stop-partition", "value": None, "type": "invoke"})
    assert not t["net"].blocked
