"""JIT-linearization tests: agreement with WGL on random histories,
violation localization, competition racing, SVG failure report
(SURVEY.md §2.4)."""

import os
import random

import pytest

from jepsen_tpu.checkers.knossos import (competition, linear, report, wgl)
from jepsen_tpu.checkers.knossos.search import Search
from jepsen_tpu.history.ops import history, info, invoke, ok
from jepsen_tpu.models import cas_register, register


def test_linear_valid_sequential():
    h = history([
        invoke(0, "write", 1), ok(0, "write", 1),
        invoke(0, "read", None), ok(0, "read", 1),
    ])
    res = linear.check(h, register())
    assert res["valid?"] is True
    assert res["algorithm"] == "linear"


def test_linear_invalid_localizes_op():
    h = history([
        invoke(0, "write", 1), ok(0, "write", 1),
        invoke(0, "read", None), ok(0, "read", 9),
    ])
    res = linear.check(h, register())
    assert res["valid?"] is False
    # the violation is localized to the bad read's invocation index
    assert res["final-info"]["op"]["index"] == 2
    assert res["final-info"]["op"]["f"] == "read"


def test_linear_concurrent_reordering_ok():
    # two concurrent writes, read can see either — but this read's value
    # requires w2 to linearize first even though w1 invoked first
    h = history([
        invoke(0, "write", 1),
        invoke(1, "write", 2),
        ok(1, "write", 2),
        ok(0, "write", 1),
        invoke(2, "read", None), ok(2, "read", 1),
    ])
    assert linear.check(h, register())["valid?"] is True


def test_linear_info_may_never_linearize():
    h = history([
        invoke(0, "write", 5), info(0, "write", 5),
        invoke(1, "read", None), ok(1, "read", None),
        invoke(1, "read", None), ok(1, "read", 5),  # later it lands
    ])
    assert linear.check(h, register())["valid?"] is True


def test_linear_wgl_agree_random():
    rng = random.Random(11)
    for trial in range(30):
        ops = []
        events = []
        for p in range(3):
            for _ in range(rng.randint(1, 3)):
                kind = rng.choice(["read", "write", "cas"])
                if kind == "read":
                    v = rng.choice([None, 0, 1])
                elif kind == "write":
                    v = rng.choice([0, 1, 2])
                else:
                    v = [rng.choice([0, 1]), rng.choice([0, 1])]
                events.append((p, kind, v))
        rng.shuffle(events)
        for p, kind, v in events:
            ops.append(invoke(p, kind, v))
            ops.append(rng.choice([ok, ok, ok, info])(p, kind, v))
        h = history(ops)
        rl = linear.check(h, cas_register())
        os.environ["JT_NO_NATIVE"] = "1"
        try:
            rw = wgl.check(h, cas_register())
        finally:
            del os.environ["JT_NO_NATIVE"]
        assert rl["valid?"] == rw["valid?"], f"trial {trial}"


def test_linear_abort():
    ctl = Search()
    ctl.abort()
    h = history([invoke(0, "write", 1), ok(0, "write", 1)])
    res = linear.check(h, register(), ctl=ctl)
    assert res["valid?"] == "unknown"


def test_competition_race_and_fallbacks():
    h = history([
        invoke(0, "write", 1), ok(0, "write", 1),
        invoke(1, "cas", [1, 2]), ok(1, "cas", [1, 2]),
        invoke(0, "read", None), ok(0, "read", 2),
    ])
    for algo in ("auto", "wgl", "linear", "device"):
        assert competition.analysis(h, cas_register(),
                                    algorithm=algo)["valid?"] is True, algo
    bad = history([
        invoke(0, "write", 1), ok(0, "write", 1),
        invoke(0, "read", None), ok(0, "read", 3),
    ])
    for algo in ("auto", "wgl", "linear"):
        assert competition.analysis(bad, cas_register(),
                                    algorithm=algo)["valid?"] is False, algo


def test_failure_report_svg(tmp_path):
    h = history([
        invoke(0, "write", 1), ok(0, "write", 1),
        invoke(1, "write", 2), ok(1, "write", 2),
        invoke(0, "read", None), ok(0, "read", 7),
    ])
    res = linear.check(h, register())
    assert res["valid?"] is False
    path = str(tmp_path / "linear.svg")
    out = report.render_analysis(h, res, path)
    assert out == path
    svg = open(path).read()
    assert svg.startswith("<svg") and "non-linearizable" in svg
    assert "read" in svg


def test_failure_report_handles_wgl_shape(tmp_path):
    h = history([
        invoke(0, "write", 1), ok(0, "write", 1),
        invoke(0, "read", None), ok(0, "read", 9),
    ])
    os.environ["JT_NO_NATIVE"] = "1"
    try:
        res = wgl.check(h, register())
    finally:
        del os.environ["JT_NO_NATIVE"]
    assert res["valid?"] is False
    path = str(tmp_path / "wgl.svg")
    out = report.render_analysis(h, res, path)
    # WGL failures carry configs; report may or may not localize, but must
    # not crash, and when it renders the file must be valid SVG
    if out is not None:
        assert open(path).read().startswith("<svg")


# ---- wide-mask packed search (round 5: the P > 57 regime) ------------


def test_wide_matches_sets_differential():
    """Wide-mask rows and the sets path agree on verdicts (the wide
    path is forced, so P <= 57 histories exercise it too)."""
    from jepsen_tpu.checkers.knossos.linear import _search
    from jepsen_tpu.checkers.knossos.memo import memoize
    from jepsen_tpu.checkers.knossos.prep import prepare
    from jepsen_tpu.models import cas_register
    from jepsen_tpu.workloads import synth

    for seed in range(12):
        h = synth.lin_register_history(n_ops=100, concurrency=5,
                                       info_prob=0.08, cas_prob=0.3,
                                       seed=seed)
        ops = prepare(h)
        memo = memoize(cas_register(), ops)
        a, _ = _search(ops, memo, 200_000, _force_wide=True)
        b, _ = _search(ops, memo, 200_000, _force_sets=True)
        assert a == b, (seed, a, b)


def test_wide_selected_past_57_slots():
    """P > 57 histories take the wide path (previously the slow sets
    cliff), and it reaches any budget far faster than sets."""
    import time

    from jepsen_tpu.checkers.knossos.linear import (
        _events,
        _peak_concurrency,
        _search,
    )
    from jepsen_tpu.checkers.knossos.memo import memoize
    from jepsen_tpu.checkers.knossos.prep import prepare
    from jepsen_tpu.models import cas_register
    from jepsen_tpu.workloads import synth

    h = synth.lin_register_history(n_ops=600, concurrency=120,
                                   info_prob=0.0, cas_prob=0.3, seed=7)
    ops = prepare(h)
    assert _peak_concurrency(_events(ops)) > 57
    memo = memoize(cas_register(), ops)
    t0 = time.time()
    ok, info = _search(ops, memo, 100_000)
    wall = time.time() - t0
    # high-concurrency JIT-linear blows up by nature (the config
    # lattice, not the representation — measured: a 45x-faster explorer
    # hits the same budget); what the wide path guarantees is bounded,
    # fast budget exhaustion instead of the sets path's crawl
    assert ok in (True, False, None)
    assert wall < 60, wall


def test_wide_aborts_mid_event():
    """A deadline ctl stops the wide search INSIDE one event's closure
    (crash-heavy events can run minutes; the race must abort losers)."""
    import time

    from jepsen_tpu.checkers.knossos import linear
    from jepsen_tpu.checkers.knossos.search import Search
    from jepsen_tpu.models import cas_register
    from jepsen_tpu.workloads import synth

    h = synth.lin_register_history(n_ops=1300, concurrency=6,
                                   info_prob=0.15, cas_prob=0.2, seed=5)
    ctl = Search(deadline_s=5)
    t0 = time.time()
    r = linear.check(h, cas_register(), ctl=ctl)
    assert r["valid?"] == "unknown", r
    assert time.time() - t0 < 60
