"""Device list-append checker vs host oracle — differential tests.

The reference's pattern of checking parallel folds against serial folds
(SURVEY.md §4), upgraded to device-vs-host: every verdict and anomaly set
must match the exact host oracle (one exception: the budget-limited
G-nonadjacent family, where the device can be MORE complete — see
test_device_finds_nonadjacent_oracle_budget_misses).  `_force_no_fallback=True` ensures we are
actually testing the device path, not the oracle fallback.
"""

import numpy as np
import pytest

from jepsen_tpu.checkers.elle import list_append, oracle
from jepsen_tpu.history import history, invoke, ok, fail, info
from jepsen_tpu.workloads import synth

MODELS = ["strict-serializable"]


def both(h, models=MODELS):
    r_o = oracle.check(h, models)
    r_d = list_append.check(h, models, _force_no_fallback=True)
    assert r_o["valid?"] == r_d["valid?"], (r_o, r_d)
    assert set(r_o["anomaly-types"]) == set(r_d["anomaly-types"]), (r_o, r_d)
    return r_d


def concurrent_history(*txns):
    inv, comp = [], []
    for i, (mops_inv, mops_ok) in enumerate(txns):
        inv.append(invoke(i, "txn", mops_inv))
        if mops_ok == "fail":
            comp.append(fail(i, "txn", mops_inv))
        elif mops_ok == "info":
            comp.append(info(i, "txn", None))
        else:
            comp.append(ok(i, "txn", mops_ok))
    return history(inv + comp)


def test_device_valid_and_g1c():
    h = concurrent_history(
        ([["append", "x", 1], ["r", "y", None]],
         [["append", "x", 1], ["r", "y", [9]]]),
        ([["append", "y", 9], ["r", "x", None]],
         [["append", "y", 9], ["r", "x", [1]]]),
    )
    r = both(h)
    assert r["valid?"] is False
    assert "G1c" in r["anomaly-types"]


def test_device_g_single():
    h = concurrent_history(
        ([["append", "k", 1], ["append", "j", 10]],
         [["append", "k", 1], ["append", "j", 10]]),
        ([["append", "k", 2], ["r", "j", None]],
         [["append", "k", 2], ["r", "j", []]]),
        ([["r", "k", None], ["r", "j", None]],
         [["r", "k", [1, 2]], ["r", "j", [10]]]),
    )
    r = both(h)
    assert "G-single" in r["anomaly-types"]
    assert "G-nonadjacent" not in r["anomaly-types"]


def test_device_write_skew():
    h = concurrent_history(
        ([["r", "x", None], ["append", "y", 10]],
         [["r", "x", []], ["append", "y", 10]]),
        ([["r", "y", None], ["append", "x", 1]],
         [["r", "y", []], ["append", "x", 1]]),
        ([["r", "x", None], ["r", "y", None]],
         [["r", "x", [1]], ["r", "y", [10]]]),
    )
    r = both(h)
    assert "G2-item" in r["anomaly-types"]
    assert "G-single" not in r["anomaly-types"]


def test_device_realtime_cycle():
    h = history([
        invoke(0, "txn", [["r", "x", None]]),
        ok(0, "txn", [["r", "x", [1]]]),
        invoke(1, "txn", [["append", "x", 1]]),
        ok(1, "txn", [["append", "x", 1]]),
    ])
    r = both(h)
    assert r["valid?"] is False
    assert "G1c-realtime" in r["anomaly-types"]


def test_device_noncycle_anomalies():
    h = concurrent_history(
        ([["append", "x", 1], ["append", "x", 2]],
         [["append", "x", 1], ["append", "x", 2]]),
        ([["r", "x", None]], [["r", "x", [1]]]),          # G1b
        ([["append", "y", 7]], "fail"),
        ([["r", "y", None]], [["r", "y", [7]]]),          # G1a
        ([["append", "z", 5], ["r", "z", None]],
         [["append", "z", 5], ["r", "z", [5, 9]]]),       # internal
    )
    r = both(h)
    for a in ("G1a", "G1b", "internal"):
        assert a in r["anomaly-types"]


@pytest.mark.parametrize("seed", range(8))
def test_device_differential_synth(seed):
    h = synth.la_history(n_txns=120, n_keys=5, concurrency=4,
                         fail_prob=0.05, info_prob=0.05,
                         multi_append_prob=0.2, seed=seed)
    if seed % 4 == 1:
        synth.inject_g1a(h)
    elif seed % 4 == 2:
        synth.inject_wr_cycle(h)
    elif seed % 4 == 3:
        synth.inject_rw_cycle(h)
    both(h)


def test_device_packed_generator_valid():
    p = synth.packed_la_history(n_txns=3000, n_keys=24, seed=11)
    r = list_append.check(p, MODELS, _force_no_fallback=True)
    assert r["valid?"] is True, r["anomaly-types"]


def test_explainer_g_single_names_key_and_values():
    # VERDICT done-bar: a G-single report names the key and read/append
    # values on EVERY edge (elle/core.clj Explainer equivalence)
    h = concurrent_history(
        ([["append", "k", 1], ["append", "j", 10]],
         [["append", "k", 1], ["append", "j", 10]]),
        ([["append", "k", 2], ["r", "j", None]],
         [["append", "k", 2], ["r", "j", []]]),
        ([["r", "k", None], ["r", "j", None]],
         [["r", "k", [1, 2]], ["r", "j", [10]]]),
    )
    r = list_append.check(h, MODELS, _force_no_fallback=True)
    assert "G-single" in r["anomalies"]
    cyc = r["anomalies"]["G-single"][0]["cycle"]
    assert len(cyc) >= 2
    for e in cyc:
        assert e.get("why"), e
        if e["rel"] in ("ww", "wr", "rw"):
            assert e.get("key") is not None, e
            assert ("value" in e) or ("value'" in e), e
    # the rw (anti-dependency) edge must name the unobserved successor
    rw = [e for e in cyc if e["rel"] == "rw"]
    assert rw and rw[0]["value'"] is not None


def test_explainer_realtime_edge_positions():
    h = history([
        invoke(0, "txn", [["r", "x", None]]),
        ok(0, "txn", [["r", "x", [1]]]),
        invoke(1, "txn", [["append", "x", 1]]),
        ok(1, "txn", [["append", "x", 1]]),
    ])
    r = list_append.check(h, MODELS, _force_no_fallback=True)
    cyc = r["anomalies"]["G1c-realtime"][0]["cycle"]
    rt = [e for e in cyc if e["rel"] == "realtime"]
    assert rt and "completed-at" in rt[0] and "invoked-at" in rt[0]
    wr = [e for e in cyc if e["rel"] == "wr"]
    assert wr and wr[0]["key"] == "x" and wr[0]["value"] == 1


def test_loop_scan_path_matches_assoc_scan(monkeypatch):
    # the Hillis-Steele fori_loop scan (used at 1M+ shapes to kill the
    # associative_scan compile blowup, PROFILE.md §2) must give bitwise
    # the same verdicts as the associative_scan path
    from jepsen_tpu.checkers.elle.device_core import core_check
    from jepsen_tpu.checkers.elle.device_infer import pad_packed
    from jepsen_tpu.history.soa import pack_txns
    from jepsen_tpu.ops import segments

    cases = []
    h1 = synth.la_history(n_txns=120, n_keys=5, concurrency=6,
                          multi_append_prob=0.2, seed=21)
    cases.append(pack_txns(h1, "list-append"))
    h2 = synth.la_history(n_txns=120, n_keys=5, concurrency=6, seed=22)
    synth.inject_rw_cycle(h2)
    synth.inject_wr_cycle(h2)
    cases.append(pack_txns(h2, "list-append"))

    orig_threshold = segments.LOOP_SCAN_MIN_ROWS
    for p in cases:
        hp = pad_packed(p)
        ref = np.asarray(core_check(hp, p.n_keys)[0])
        monkeypatch.setattr(segments, "LOOP_SCAN_MIN_ROWS", 1)
        core_check.clear_cache()
        got = np.asarray(core_check(hp, p.n_keys)[0])
        monkeypatch.setattr(segments, "LOOP_SCAN_MIN_ROWS",
                            orig_threshold)
        core_check.clear_cache()
        assert np.array_equal(ref, got), (ref, got)


def test_device_converges_on_round_hungry_history():
    """Fuzz regression (2026-07-30): dense injected cycles can need
    hundreds of propagation rounds; detect_cycles must grow max_rounds
    (like the fused path's grow_until_exact) instead of surrendering to
    the host fallback at 64."""
    h = synth.la_history(n_txns=400, n_keys=2, concurrency=8,
                         info_prob=0.2, multi_append_prob=0.2,
                         seed=569558050)
    synth.inject_wr_cycle(h)
    synth.inject_rw_cycle(h)
    r = list_append.check(h, ["serializable"], _force_no_fallback=True)
    assert r["valid?"] is False
    assert "G1c" in r["anomaly-types"]


def test_device_duplicate_elements_fast_path():
    # dup visible in the version order (reads agree with the order):
    # the cond-gated fast path must flag it without the R-sort
    h = concurrent_history(
        ([["append", "x", 1]], [["append", "x", 1]]),
        ([["r", "x", None]], [["r", "x", [1, 1]]]),
    )
    r = both(h, ["serializable"])
    assert "duplicate-elements" in r["anomaly-types"]


def test_device_duplicate_elements_slow_path():
    # dup hidden from the orders: the longest read [1, 2] defines the
    # order, a second read [1, 1] disagrees (incompatible-order) AND
    # holds the dup — only the exact per-read sort path can see it
    h = concurrent_history(
        ([["append", "x", 1]], [["append", "x", 1]]),
        ([["append", "x", 2]], [["append", "x", 2]]),
        ([["r", "x", None]], [["r", "x", [1, 2]]]),
        ([["r", "x", None]], [["r", "x", [1, 1]]]),
    )
    r = both(h, ["serializable"])
    assert "duplicate-elements" in r["anomaly-types"]
    assert "incompatible-order" in r["anomaly-types"]


@pytest.mark.slow  # ~90 s (dense 900-txn graph) — tier-1 budget hog (ISSUE 3)
def test_device_finds_nonadjacent_oracle_budget_misses():
    """Fuzz find (2026-07-30, seed 999 case 33): on a dense 900-txn
    graph the device's witness-region search finds a genuine
    G-nonadjacent cycle that the oracle's whole-SCC budgeted DFS gives
    up on.  Pins (a) the device's stronger completeness, (b) the
    structural validity of its reported cycle, and (c) that the
    verdicts still agree (a nonadjacent cycle is also a G2-item cycle).
    """
    h = synth.la_history(n_txns=900, n_keys=5, concurrency=8,
                         fail_prob=0.05, info_prob=0.05,
                         multi_append_prob=0.2, seed=737240089)
    for _ in range(4):
        synth.inject_wr_cycle(h)
        synth.inject_rw_cycle(h)
    r_d = list_append.check(h, ["strict-serializable"],
                            _force_no_fallback=True)
    r_o = oracle.check(h, ["strict-serializable"])
    assert r_d["valid?"] is False and r_o["valid?"] is False
    na = r_d["anomalies"]["G-nonadjacent"]
    rels = [e["rel"] for e in na[0]["cycle"]]
    # structural spec check: >= 2 rw, none cyclically adjacent
    assert rels.count("rw") >= 2
    for i, rel in enumerate(rels):
        assert not (rel == "rw" and rels[(i + 1) % len(rels)] == "rw"), rels
    # every edge carries concrete evidence (the Explainer filled it in)
    assert all(e.get("why") for e in na[0]["cycle"])
    # apart from the budget-limited nonadjacent family, the sets agree
    from jepsen_tpu.checkers.elle.specs import NONADJACENT_FAMILY

    assert set(r_o["anomaly-types"]) - NONADJACENT_FAMILY == \
        set(r_d["anomaly-types"]) - NONADJACENT_FAMILY


@pytest.mark.parametrize("seed", range(6))
def test_sort_free_run_order_matches_lax_sort(seed):
    """The layout-aware inference paths (sort-free run order via
    within-txn shifted-compare ranking; barrier order via stable
    partition) must be bit-identical to the lax.sort paths they replace.
    Seeds cover valid, fail/info-bearing, and anomaly-injected histories.
    """
    import dataclasses

    import jax

    from jepsen_tpu.checkers.elle.device_infer import infer, pad_packed
    from jepsen_tpu.history.soa import pack_txns

    h = synth.la_history(n_txns=160, n_keys=5, concurrency=6,
                         fail_prob=0.08, info_prob=0.08,
                         multi_append_prob=0.25, max_mops=6, seed=seed)
    if seed % 3 == 1:
        synth.inject_g1a(h)
    elif seed % 3 == 2:
        synth.inject_wr_cycle(h)
    p = pack_txns(h)
    hp = pad_packed(p)
    assert hp.txn_major and hp.run_cap and hp.complete_monotone
    off = dataclasses.replace(hp, txn_major=False, run_cap=0,
                              complete_monotone=False)
    fast = infer(hp, p.n_keys)
    slow = infer(off, p.n_keys)
    for a, b in zip(jax.tree_util.tree_leaves(fast),
                    jax.tree_util.tree_leaves(slow)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_layout_facts_reject_non_txn_major():
    """Hand-built packings that violate the txn-major layout must fall
    back to the sort path (flags off) and still check correctly."""
    from jepsen_tpu.checkers.elle.device_infer import infer, pad_packed
    from jepsen_tpu.history.soa import pack_txns

    h = synth.la_history(n_txns=60, n_keys=4, concurrency=4, seed=3)
    synth.inject_g1a(h)  # a nonzero count the fallback must reproduce
    p = pack_txns(h)
    ref = infer(pad_packed(p), p.n_keys)

    # Equivalent packing with txn mop-blocks in REVERSE txn order:
    # within-txn mop order is preserved (stable argsort) and the
    # read-element extents are rebuilt to match the new mop order, so
    # the packing means the same history but violates txn-major layout.
    order = np.argsort(-p.mop_txn, kind="stable")
    for f in ("mop_txn", "mop_kind", "mop_key", "mop_val",
              "mop_rd_start", "mop_rd_len"):
        setattr(p, f, getattr(p, f)[order])
    elems, new_starts, cur = [], np.full(p.n_mops, -1, np.int32), 0
    for i in range(p.n_mops):
        s, ln = p.mop_rd_start[i], p.mop_rd_len[i]
        if s >= 0:
            new_starts[i] = cur
            elems.extend(p.rd_elems[s:s + max(ln, 0)])
            cur += max(ln, 0)
    p.mop_rd_start, p.rd_elems = new_starts, np.asarray(elems, np.int32)
    hp = pad_packed(p)
    assert not hp.txn_major
    # the device-sort fallback still checks the reordered packing, and
    # the anomaly counts match the txn-major packing's exactly
    scr = infer(hp, p.n_keys)
    for name, v in ref["counts"].items():
        np.testing.assert_array_equal(np.asarray(v),
                                      np.asarray(scr["counts"][name]),
                                      err_msg=name)
    assert int(np.asarray(ref["counts"]["G1a"])) > 0

    # negative sentinel rows must disable the fast path, not crash
    p.mop_txn = np.sort(p.mop_txn)
    p.mop_txn[0] = -1
    hp2 = pad_packed(p)
    assert not hp2.txn_major


@pytest.mark.parametrize("seed", range(4))
def test_staged_core_check_matches_fused(seed):
    """core_check_staged (two XLA programs, the 10M remote-compile
    workaround) is bitwise-equal to the fused core_check — valid and
    injected-invalid histories both."""
    from jepsen_tpu.checkers.elle.device_core import (core_check,
                                                      core_check_staged)
    from jepsen_tpu.checkers.elle.device_infer import pad_packed
    from jepsen_tpu.history.soa import pack_txns

    if seed == 0:
        p = synth.packed_la_history(n_txns=2000, n_keys=16, seed=3)
    else:
        h = synth.la_history(n_txns=150, n_keys=5, concurrency=4,
                             fail_prob=0.05, info_prob=0.05,
                             multi_append_prob=0.2, seed=seed)
        [synth.inject_g1a, synth.inject_wr_cycle,
         synth.inject_rw_cycle][seed - 1](h)
        p = pack_txns(h)
    hp = pad_packed(p)
    bits_f, over_f = core_check(hp, p.n_keys, max_k=32)
    bits_s, over_s = core_check_staged(hp, p.n_keys, max_k=32)
    np.testing.assert_array_equal(np.asarray(bits_f), np.asarray(bits_s))
    assert int(over_f) == int(over_s)
