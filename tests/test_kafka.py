"""Kafka workload tests: healthy runs pass; each injected fault family is
detected (reference kafka_test strategy, SURVEY.md §2.6/§4)."""

import random

from jepsen_tpu import core
from jepsen_tpu.generator import core as g
from jepsen_tpu.history.ops import history, invoke, ok
from jepsen_tpu.workloads import kafka


def _run(tmp_path, client, *, n_ops=60, crash_frac=0.0,
         subscribe_frac=0.0, txn_frac=0.0, seed=1):
    wl = kafka.workload(rng=random.Random(seed), crash_frac=crash_frac,
                        subscribe_frac=subscribe_frac, txn_frac=txn_frac)
    t = {
        "name": "kafka-test", "nodes": ["n1", "n2"], "client": client,
        "concurrency": 4, "store-dir": str(tmp_path / "s"),
        "kafka-key-count": wl["kafka-key-count"],
        "generator": g.clients(g.limit(n_ops, wl["generator"])),
        "final-generator": wl["final-generator"],
        "checker": wl["checker"],
    }
    return core.run(t)


def test_kafka_healthy_run_valid(tmp_path):
    done = _run(tmp_path, kafka.KafkaClient())
    assert done["results"]["valid?"] is True
    assert done["results"]["send-count"] > 0
    assert done["results"]["poll-count"] > 0


def test_kafka_with_crashes_still_valid(tmp_path):
    done = _run(tmp_path, kafka.KafkaClient(), crash_frac=0.1, seed=3)
    assert done["results"]["valid?"] is True


def test_kafka_lost_writes_detected(tmp_path):
    done = _run(tmp_path,
                kafka.KafkaClient(lose_tail_p=0.3,
                                  rng=random.Random(5)), seed=5)
    res = done["results"]
    assert res["valid?"] is False
    assert "lost-write" in res["anomaly-types"] \
        or "inconsistent-offsets" in res["anomaly-types"]


def test_kafka_duplicates_detected(tmp_path):
    done = _run(tmp_path,
                kafka.KafkaClient(dup_p=0.5, rng=random.Random(6)),
                seed=6)
    res = done["results"]
    assert res["valid?"] is False
    assert "duplicate" in res["anomaly-types"]


# ---- checker unit cases on literal histories ----


def test_checker_inconsistent_offsets():
    h = history([
        invoke(0, "send", [("send", 0, 1)]),
        ok(0, "send", [("send", 0, (0, 1))]),
        invoke(1, "send", [("send", 0, 2)]),
        ok(1, "send", [("send", 0, (0, 2))]),  # same offset, different value
    ])
    res = kafka.KafkaChecker().check({}, h)
    assert res["valid?"] is False
    assert "inconsistent-offsets" in res["anomaly-types"]


def test_checker_lost_write():
    h = history([
        invoke(0, "send", [("send", 0, 10)]),
        ok(0, "send", [("send", 0, (0, 10))]),
        invoke(0, "send", [("send", 0, 11)]),
        ok(0, "send", [("send", 0, (1, 11))]),
        invoke(1, "poll", [("poll", None)]),
        ok(1, "poll", [("poll", {0: [(1, 11)]})]),  # saw offset 1, not 0
    ])
    res = kafka.KafkaChecker().check({}, h)
    assert res["valid?"] is False
    assert res["anomalies"]["lost-write"] == [(0, 0, 10)]


def test_checker_nonmonotonic_poll():
    h = history([
        invoke(0, "poll", [("poll", None)]),
        ok(0, "poll", [("poll", {0: [(3, "c"), (4, "d")]})]),
        invoke(0, "poll", [("poll", None)]),
        ok(0, "poll", [("poll", {0: [(2, "b")]})]),  # went backwards
    ])
    res = kafka.KafkaChecker().check({}, h)
    assert res["valid?"] is False
    assert "nonmonotonic-poll" in res["anomaly-types"]


def test_checker_int_poll_skip():
    h = history([
        invoke(0, "poll", [("poll", None)]),
        ok(0, "poll", [("poll", {0: [(0, "a"), (2, "c")]})]),  # skipped 1
        invoke(1, "poll", [("poll", None)]),
        ok(1, "poll", [("poll", {0: [(1, "b")]})]),  # 1 does exist
    ])
    res = kafka.KafkaChecker().check({}, h)
    assert res["valid?"] is False
    assert "int-poll-skip" in res["anomaly-types"]


def test_checker_poll_skip_cross_batch():
    h = history([
        invoke(0, "poll", [("poll", None)]),
        ok(0, "poll", [("poll", {0: [(0, "a")]})]),
        invoke(0, "poll", [("poll", None)]),
        ok(0, "poll", [("poll", {0: [(2, "c")]})]),  # skipped 1 across polls
        invoke(1, "poll", [("poll", None)]),
        ok(1, "poll", [("poll", {0: [(1, "b")]})]),  # 1 does exist
    ])
    res = kafka.KafkaChecker().check({}, h)
    assert res["valid?"] is False
    assert "poll-skip" in res["anomaly-types"]


def test_checker_redelivery_after_assign_is_legal():
    # ADVICE round 1: consumers seek back to the committed offset on
    # (re)assign, so the same poll repeating after an assign must NOT be
    # a nonmonotonic-poll
    h = history([
        invoke(0, "poll", [("poll", None)]),
        ok(0, "poll", [("poll", {0: [(0, "a"), (1, "b")]})]),
        invoke(0, "assign", [0]),
        ok(0, "assign", [0]),
        invoke(0, "poll", [("poll", None)]),
        ok(0, "poll", [("poll", {0: [(0, "a"), (1, "b")]})]),  # re-delivery
    ])
    res = kafka.KafkaChecker().check({}, h)
    assert res["valid?"] is True, res["anomalies"]


def test_checker_nonmonotonic_send():
    h = history([
        invoke(0, "send", [("send", 0, 1)]),
        ok(0, "send", [("send", 0, (5, 1))]),
        invoke(0, "send", [("send", 0, 2)]),
        ok(0, "send", [("send", 0, (3, 2))]),  # offset went backwards
    ])
    res = kafka.KafkaChecker().check({}, h)
    assert res["valid?"] is False
    assert "nonmonotonic-send" in res["anomaly-types"]


def test_checker_int_send_skip():
    h = history([
        invoke(0, "txn", [("send", 0, 1), ("send", 0, 2)]),
        ok(0, "txn", [("send", 0, (0, 1)), ("send", 0, (4, 2))]),
    ])
    res = kafka.KafkaChecker().check({}, h)
    assert res["valid?"] is False
    assert "int-send-skip" in res["anomaly-types"]


def test_checker_precommitted_read():
    h = history([
        invoke(1, "poll", [("poll", None)]),
        ok(1, "poll", [("poll", {0: [(0, "x")]})]),   # sees x ...
        invoke(0, "send", [("send", 0, "x")]),
        ok(0, "send", [("send", 0, (0, "x"))]),        # ... before commit
    ])
    res = kafka.KafkaChecker().check({}, h)
    assert res["valid?"] is False
    assert "precommitted-read" in res["anomaly-types"]


def test_checker_unseen_reported_not_invalid():
    h = history([
        invoke(0, "send", [("send", 0, 1)]),
        ok(0, "send", [("send", 0, (0, 1))]),
        invoke(0, "send", [("send", 0, 2)]),
        ok(0, "send", [("send", 0, (1, 2))]),
        invoke(1, "poll", [("poll", None)]),
        ok(1, "poll", [("poll", {0: [(0, 1)]})]),  # offset 1 not yet seen
    ])
    res = kafka.KafkaChecker().check({}, h)
    assert res["valid?"] is True
    assert res["unseen"] == {0: 1}


def test_kafka_subscribe_rebalance_run(tmp_path):
    # group-managed consumption with rebalances stays valid
    done = _run(tmp_path, kafka.KafkaClient(), subscribe_frac=0.25,
                n_ops=120, seed=11)
    res = done["results"]
    assert res["valid?"] is True, res["anomalies"]
    assert res["poll-count"] > 0


def test_kafka_txn_ops_run(tmp_path):
    done = _run(tmp_path, kafka.KafkaClient(), txn_frac=0.4, n_ops=100,
                seed=12)
    res = done["results"]
    assert res["valid?"] is True, res["anomalies"]


def test_checker_group_rebalance_seek_is_legal():
    # a rebalance triggered by ANOTHER member moves a partition away and
    # back; the returning consumer resumes from the group's committed
    # offset.  Its own op stream has no assign/subscribe, so only the
    # attached rebalance generation can mark the epoch change.
    h = history([
        invoke(0, "poll", [("poll", None)]),
        ok(0, "poll", [("poll", {0: [(0, "a"), (1, "b")]})],
           ext={"rebalance": 1}),
        invoke(1, "poll", [("poll", None)]),
        ok(1, "poll", [("poll", {0: [(2, "c"), (3, "d")]})],
           ext={"rebalance": 2}),
        invoke(0, "poll", [("poll", None)]),
        ok(0, "poll", [("poll", {0: [(4, "e")]})],
           ext={"rebalance": 3}),  # jumped 1 -> 4: legal, epoch changed
    ])
    res = kafka.KafkaChecker().check({}, h)
    assert res["valid?"] is True, res["anomalies"]


def test_checker_empty_unknown():
    assert kafka.KafkaChecker().check({}, history([]))["valid?"] == "unknown"
