"""Kafka workload tests: healthy runs pass; each injected fault family is
detected (reference kafka_test strategy, SURVEY.md §2.6/§4)."""

import random

from jepsen_tpu import core
from jepsen_tpu.generator import core as g
from jepsen_tpu.history.ops import history, invoke, ok
from jepsen_tpu.workloads import kafka


def _run(tmp_path, client, *, n_ops=60, crash_frac=0.0, seed=1):
    wl = kafka.workload(rng=random.Random(seed), crash_frac=crash_frac)
    t = {
        "name": "kafka-test", "nodes": ["n1", "n2"], "client": client,
        "concurrency": 4, "store-dir": str(tmp_path / "s"),
        "kafka-key-count": wl["kafka-key-count"],
        "generator": g.clients(g.limit(n_ops, wl["generator"])),
        "final-generator": wl["final-generator"],
        "checker": wl["checker"],
    }
    return core.run(t)


def test_kafka_healthy_run_valid(tmp_path):
    done = _run(tmp_path, kafka.KafkaClient())
    assert done["results"]["valid?"] is True
    assert done["results"]["send-count"] > 0
    assert done["results"]["poll-count"] > 0


def test_kafka_with_crashes_still_valid(tmp_path):
    done = _run(tmp_path, kafka.KafkaClient(), crash_frac=0.1, seed=3)
    assert done["results"]["valid?"] is True


def test_kafka_lost_writes_detected(tmp_path):
    done = _run(tmp_path,
                kafka.KafkaClient(lose_tail_p=0.3,
                                  rng=random.Random(5)), seed=5)
    res = done["results"]
    assert res["valid?"] is False
    assert "lost-write" in res["anomaly-types"] \
        or "inconsistent-offsets" in res["anomaly-types"]


def test_kafka_duplicates_detected(tmp_path):
    done = _run(tmp_path,
                kafka.KafkaClient(dup_p=0.5, rng=random.Random(6)),
                seed=6)
    res = done["results"]
    assert res["valid?"] is False
    assert "duplicate" in res["anomaly-types"]


# ---- checker unit cases on literal histories ----


def test_checker_inconsistent_offsets():
    h = history([
        invoke(0, "send", [("send", 0, 1)]),
        ok(0, "send", [("send", 0, (0, 1))]),
        invoke(1, "send", [("send", 0, 2)]),
        ok(1, "send", [("send", 0, (0, 2))]),  # same offset, different value
    ])
    res = kafka.KafkaChecker().check({}, h)
    assert res["valid?"] is False
    assert "inconsistent-offsets" in res["anomaly-types"]


def test_checker_lost_write():
    h = history([
        invoke(0, "send", [("send", 0, 10)]),
        ok(0, "send", [("send", 0, (0, 10))]),
        invoke(0, "send", [("send", 0, 11)]),
        ok(0, "send", [("send", 0, (1, 11))]),
        invoke(1, "poll", [("poll", None)]),
        ok(1, "poll", [("poll", {0: [(1, 11)]})]),  # saw offset 1, not 0
    ])
    res = kafka.KafkaChecker().check({}, h)
    assert res["valid?"] is False
    assert res["anomalies"]["lost-write"] == [(0, 0, 10)]


def test_checker_nonmonotonic_poll():
    h = history([
        invoke(0, "poll", [("poll", None)]),
        ok(0, "poll", [("poll", {0: [(3, "c"), (4, "d")]})]),
        invoke(0, "poll", [("poll", None)]),
        ok(0, "poll", [("poll", {0: [(2, "b")]})]),  # went backwards
    ])
    res = kafka.KafkaChecker().check({}, h)
    assert res["valid?"] is False
    assert "nonmonotonic-poll" in res["anomaly-types"]


def test_checker_skipped_poll():
    h = history([
        invoke(0, "poll", [("poll", None)]),
        ok(0, "poll", [("poll", {0: [(0, "a"), (2, "c")]})]),  # skipped 1
        invoke(1, "poll", [("poll", None)]),
        ok(1, "poll", [("poll", {0: [(1, "b")]})]),  # 1 does exist
    ])
    res = kafka.KafkaChecker().check({}, h)
    assert res["valid?"] is False
    assert "skipped-poll" in res["anomaly-types"]


def test_checker_empty_unknown():
    assert kafka.KafkaChecker().check({}, history([]))["valid?"] == "unknown"
