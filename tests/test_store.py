"""Store layer tests: codec round-trips, binary format, two-phase save."""

import os

import pytest

from jepsen_tpu import store
from jepsen_tpu.store import codec
from jepsen_tpu.store.format import CHUNK_SIZE, FormatError, JepsenFile
from jepsen_tpu.history.ops import History, Op, invoke, ok


# -- codec ----------------------------------------------------------------


@pytest.mark.parametrize(
    "v",
    [
        None,
        42,
        3.5,
        "hi",
        [1, 2, 3],
        ("append", 3, 7),
        [("append", 1, 2), ("r", 1, [1, 2])],
        {"a": 1, "b": [True, False]},
        {1: "x", (2, 3): "y"},
        {"§t": "literal-key"},
        {1, 2, 3},
        b"\x00\xffbytes",
        {"nested": {"deep": [({"k": (1,)},)]}},
    ],
)
def test_codec_roundtrip(v):
    assert codec.loads(codec.dumps(v)) == v


def test_codec_unserializable_placeholder():
    class Weird:
        pass

    out = codec.loads(codec.dumps({"db": Weird()}))
    assert "Weird" in out["db"]["§obj"]


# -- binary format --------------------------------------------------------


def _mk_history(n):
    ops = []
    for i in range(n // 2):
        ops.append(invoke(i % 5, "txn", [("append", 1, i)]))
        ops.append(ok(i % 5, "txn", [("append", 1, i)]))
    return History(ops)


def test_format_roundtrip(tmp_path):
    p = str(tmp_path / "t.jepsen")
    h = _mk_history(100)
    test = {"name": "fmt", "nodes": ["n1"], "concurrency": 5}
    jf = JepsenFile(p)
    jf.write_test(test, h)

    t2 = jf.read_test()
    assert t2["name"] == "fmt"
    assert "history" not in t2

    h2 = jf.read_history()
    assert len(h2) == 100
    assert h2[0].type == "invoke"
    assert h2[0].value == [("append", 1, 0)]  # tuples survive
    assert h2[99].index == 99
    assert jf.read_results() is None


def test_format_append_results_preserves_history(tmp_path):
    p = str(tmp_path / "t.jepsen")
    jf = JepsenFile(p)
    jf.write_test({"name": "x"}, _mk_history(10))
    size0 = os.path.getsize(p)
    jf.append_results({"valid?": True, "count": 10})
    # results appended, not rewritten in place
    assert os.path.getsize(p) > size0
    assert jf.read_results() == {"valid?": True, "count": 10}
    assert len(jf.read_history()) == 10
    # append again (re-analysis) overrides
    jf.append_results({"valid?": False})
    assert jf.read_results() == {"valid?": False}


def test_format_multi_chunk_lazy(tmp_path):
    p = str(tmp_path / "big.jepsen")
    n = CHUNK_SIZE * 2 + 10
    h = _mk_history(n)
    JepsenFile(p).write_test({"name": "big"}, h)
    lh = JepsenFile(p).read_history()
    assert len(lh) == n
    assert len(lh._chunks) == 3
    # random access hits the right chunk
    assert lh[CHUNK_SIZE].index == CHUNK_SIZE
    assert lh[-1].index == n - 1
    # chunk streaming yields everything in order
    seen = 0
    for chunk in lh.iter_chunks():
        for op in chunk:
            assert op.index == seen
            seen += 1
    assert seen == n
    assert len(lh.materialize()) == n


def test_format_corruption_detected(tmp_path):
    p = str(tmp_path / "c.jepsen")
    JepsenFile(p).write_test({"name": "c"}, _mk_history(4))
    with open(p, "r+b") as f:
        f.seek(40)
        f.write(b"\xde\xad\xbe\xef")
    with pytest.raises(FormatError):
        JepsenFile(p).read()


# -- store dirs + two-phase ------------------------------------------------


def test_store_two_phase(tmp_path):
    base = str(tmp_path / "store")
    test = {"name": "demo", "store-dir": base, "history": _mk_history(20)}
    store.save_0(test)
    d = store.test_dir(test)
    assert os.path.exists(os.path.join(d, "test.jepsen"))
    assert os.path.exists(os.path.join(d, "history.json"))

    test["results"] = {"valid?": True}
    store.save_1(test)
    assert os.path.exists(os.path.join(d, "results.json"))

    loaded = store.load(d)
    assert loaded["name"] == "demo"
    assert loaded["results"]["valid?"] is True
    assert len(loaded["history"]) == 20
    assert loaded["history"][3].value == [("append", 1, 1)]


def test_store_listing_and_latest(tmp_path):
    base = str(tmp_path / "store")
    for i in range(2):
        t = {"name": "lst", "store-dir": base, "start-time": 1000.0 + i * 61,
             "history": _mk_history(2), "results": {"valid?": True, "i": i}}
        store.save_0(t)
        store.save_1(t)
    runs = store.tests("lst", base=base)
    assert len(runs) == 2
    assert runs[0] > runs[1]
    assert store.latest("lst", base=base) == runs[0]
    # latest symlink resolves via load(name, "latest")
    loaded = store.load("lst", "latest", base=base)
    assert loaded["results"]["i"] == 1
    store.delete("lst", base=base)
    assert store.tests("lst", base=base) == []


# -- review regressions ----------------------------------------------------


def test_save0_with_exception_error_and_numpy(tmp_path):
    import numpy as np

    ops = [invoke(0, "r", None),
           Op(type="info", process=0, f="r", value=None,
              error=RuntimeError("boom"))]
    t = {"name": "err", "store-dir": str(tmp_path / "s"),
         "history": History(ops), "results": {"valid?": np.True_}}
    store.save_0(t)  # must not raise on unserializable error values
    store.save_1(t)
    loaded = store.load(store.test_dir(t))
    assert loaded["results"]["valid?"] is True  # np.bool_ round-trips


def test_save1_without_save0_dict_ops(tmp_path):
    t = {"name": "dicts", "store-dir": str(tmp_path / "s"),
         "history": [{"type": "invoke", "process": 0, "f": "r", "value": None},
                     {"type": "ok", "process": 0, "f": "r", "value": 1}],
         "results": {"valid?": True}}
    store.save_1(t)
    assert store.load(store.test_dir(t))["results"]["valid?"] is True


def test_listing_with_unsanitized_name(tmp_path):
    base = str(tmp_path / "s")
    t = {"name": "my test!", "store-dir": base, "history": _mk_history(2)}
    store.save_0(t)
    assert len(store.tests("my test!", base=base)) == 1
    assert store.latest("my test!", base=base) is not None
    assert store.load("my test!", "latest", base=base)["name"] == "my test!"


def test_codec_frozenset_roundtrip():
    v = {frozenset({1, 2}): "x"}
    assert codec.loads(codec.dumps(v)) == v
    fs = codec.loads(codec.dumps(frozenset({1})))
    assert isinstance(fs, frozenset)


def test_sanitize_dotdot():
    assert store.sanitize("..") == "test"
    assert store.sanitize(".") == "test"
    assert store.sanitize("a..b") == "a..b"


def test_listing_skips_current_symlink(tmp_path):
    base = str(tmp_path / "s")
    t = {"name": "demo", "store-dir": base, "history": _mk_history(2)}
    store.save_0(t)
    os.makedirs(os.path.join(store.test_dir(t), "n1"))  # node-log dir
    runs = store.tests(base=base)
    assert len(runs) == 1
    assert "current" not in os.path.relpath(runs[0], base)


def test_password_not_persisted(tmp_path):
    t = {"name": "sec", "store-dir": str(tmp_path / "s"),
         "password": "s3cret", "private_key_path": "/root/.ssh/id",
         "username": "admin", "history": _mk_history(2)}
    store.save_0(t)
    loaded = store.load(store.test_dir(t))
    assert "password" not in loaded and "private_key_path" not in loaded
    assert loaded["username"] == "admin"
    raw = open(os.path.join(store.test_dir(t), "test.jepsen"), "rb").read()
    assert b"s3cret" not in raw


def test_latest_across_names_orders_by_timestamp(tmp_path):
    # regression: sorting full paths ranked runs by lexicographically
    # greatest *name*; latest(None) must return the newest run overall
    base = str(tmp_path / "store")
    for name, start in (("zzz-old", 1000.0), ("aaa-new", 5000.0)):
        t = {"name": name, "store-dir": base, "start-time": start,
             "history": _mk_history(2), "results": {"valid?": True}}
        store.save_0(t)
        store.save_1(t)
    newest = store.latest(None, base=base)
    assert newest is not None and "aaa-new" in newest


@pytest.mark.slow  # ~60 s on this box — tier-1 budget hog (the >60 s
# convention from ISSUE 3)
def test_check_stored_streams_chunks(tmp_path):
    # store a multi-chunk run, check it end-to-end via the streaming
    # path, and pin the verdict against the materialized checker
    from jepsen_tpu.checkers.elle import list_append, stream
    from jepsen_tpu.workloads import synth

    base = str(tmp_path / "store")
    h = synth.la_history(n_txns=9000, n_keys=40, concurrency=8, seed=4)
    t = {"name": "streamed", "store-dir": base, "start-time": 1000.0,
         "history": h}
    store.save_0(t)
    loaded = store.load("streamed", base=base)
    lazy = loaded["history"]
    assert len(lazy._chunks) >= 2, "need a multi-chunk history"

    got = stream.check_stored(loaded)
    assert got["valid?"] is True, got
    assert got["exact"] is True
    assert got["n-txns"] == 9000

    ref = list_append.check(h, ["strict-serializable"])
    assert ref["valid?"] is True


def test_check_stored_catches_anomaly(tmp_path):
    from jepsen_tpu.checkers.elle import stream
    from jepsen_tpu.workloads import synth

    base = str(tmp_path / "store")
    h = synth.la_history(n_txns=200, n_keys=5, concurrency=5, seed=9)
    assert synth.inject_wr_cycle(h)
    t = {"name": "streamed-bad", "store-dir": base, "start-time": 1000.0,
         "history": h}
    store.save_0(t)
    got = stream.check_stored(store.load("streamed-bad", base=base))
    assert got["valid?"] is False, got
    assert got["cycles"]["G1c"] is True


def test_check_stored_rw_register_routed(tmp_path):
    # workload="rw-register" must run the rw checker, not list-append
    # inference over rw-packed columns
    from jepsen_tpu.checkers.elle import stream
    from jepsen_tpu.workloads import synth

    base = str(tmp_path / "store")
    h = synth.rw_history(n_txns=150, n_keys=6, concurrency=5, seed=2)
    t = {"name": "rw-streamed", "store-dir": base, "start-time": 1.0,
         "history": h}
    store.save_0(t)
    got = stream.check_stored(store.load("rw-streamed", base=base),
                              workload="rw-register")
    assert got["valid?"] is True, got
    assert "lost-update" in got["counts"]  # rw-checker bit layout
