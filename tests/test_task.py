"""Task scheduler tests (reference `jepsen/history/task.clj` strategy:
DAG ordering, cancellation cascade, failure propagation, stress)."""

import random
import threading
import time

import pytest

from jepsen_tpu.history.task import (
    CancelledError,
    TaskExecutor,
)


def test_simple_chain():
    with TaskExecutor(4) as ex:
        a = ex.submit(lambda: 2, name="a")
        b = ex.submit(lambda x: x * 3, deps=[a], name="b")
        c = ex.submit(lambda x: x + 1, deps=[b], name="c")
        assert c.result(5) == 7


def test_fanin_receives_dep_results_in_order():
    with TaskExecutor(4) as ex:
        parts = [ex.submit(lambda i=i: i, name=f"p{i}") for i in range(5)]
        total = ex.submit(lambda *xs: list(xs), deps=parts, name="sum")
        assert total.result(5) == [0, 1, 2, 3, 4]


def test_failure_cascades():
    with TaskExecutor(2) as ex:
        a = ex.submit(lambda: 1 / 0, name="boom")
        b = ex.submit(lambda x: x, deps=[a], name="child")
        with pytest.raises(ZeroDivisionError):
            a.result(5)
        with pytest.raises(ZeroDivisionError):
            b.result(5)


def test_submit_on_failed_dep_fails_fast():
    with TaskExecutor(2) as ex:
        a = ex.submit(lambda: 1 / 0, name="boom")
        with pytest.raises(ZeroDivisionError):
            a.result(5)
        b = ex.submit(lambda x: x, deps=[a], name="late-child")
        assert b.done()
        with pytest.raises(ZeroDivisionError):
            b.result(5)


def test_cancel_cascades_to_dependents():
    gate = threading.Event()
    with TaskExecutor(1) as ex:
        blocker = ex.submit(gate.wait, name="blocker")
        a = ex.submit(lambda: 1, deps=[blocker], name="a")
        b = ex.submit(lambda x: x, deps=[a], name="b")
        assert ex.cancel(a)
        gate.set()
        with pytest.raises(CancelledError):
            a.result(5)
        with pytest.raises(CancelledError):
            b.result(5)
        assert blocker.result(5) is True


def test_cancel_running_task_returns_false():
    gate = threading.Event()
    started = threading.Event()

    def run():
        started.set()
        gate.wait()
        return "done"

    with TaskExecutor(2) as ex:
        t = ex.submit(run, name="running")
        started.wait(5)
        assert not ex.cancel(t)
        gate.set()
        assert t.result(5) == "done"


def test_diamond_dag():
    with TaskExecutor(4) as ex:
        a = ex.submit(lambda: 1, name="a")
        b = ex.submit(lambda x: x + 1, deps=[a], name="b")
        c = ex.submit(lambda x: x + 2, deps=[a], name="c")
        d = ex.submit(lambda x, y: x * y, deps=[b, c], name="d")
        assert d.result(5) == 6


def test_stress_random_dag():
    rng = random.Random(42)
    with TaskExecutor(8) as ex:
        tasks = []
        expect = []
        for i in range(300):
            k = rng.randint(0, min(3, len(tasks)))
            dep_idx = rng.sample(range(len(tasks)), k) if tasks else []
            deps = [tasks[j] for j in dep_idx]
            t = ex.submit(lambda *xs: sum(xs) + 1, deps=deps, name=f"t{i}")
            tasks.append(t)
            expect.append(sum(expect[j] for j in dep_idx) + 1)
        for t, e in zip(tasks, expect):
            assert t.result(30) == e


def test_dep_ordering_under_contention():
    # each task appends after its dep: final list must respect DAG order
    out = []
    lock = threading.Lock()

    def emit(i):
        def go(*_):
            time.sleep(random.random() * 0.002)
            with lock:
                out.append(i)
        return go

    with TaskExecutor(8) as ex:
        prev = None
        chain = []
        for i in range(50):
            prev = ex.submit(emit(i), deps=[prev] if prev else [],
                             name=f"c{i}")
            chain.append(prev)
        chain[-1].result(30)
    assert out == list(range(50))
