"""Differential tests for the Pallas LOCF forward-fill kernel
(`ops/pallas_fill.py`) and its integration into edge inference.

Protocol mirrors tests/test_pallas.py: the pure-JAX grid emulator
(`locf_blocked_reference`) — same block math, explicit sequential
carry — anchors the kernel on any backend; the emulator is checked
against the O(log n) lax scan here, and the whole device_infer
kernel-branch restructuring is driven through the emulator
(JT_PALLAS=1 + JT_PALLAS_EMULATE=1) and compared bitwise against the
default lax path on full checker verdicts.
"""

import os

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from jepsen_tpu.ops.pallas_fill import (  # noqa: E402
    HOLE,
    locf_blocked_reference,
    locf_lax,
)


def _random_seed_array(rng, n, density, monotone=False):
    x = np.full(n, HOLE, np.int32)
    pos = rng.random(n) < density
    vals = rng.integers(0, 1_000_000, size=int(pos.sum()))
    if monotone:
        vals = np.sort(vals)
    x[np.nonzero(pos)[0]] = vals
    return jnp.asarray(x)


@pytest.mark.parametrize("n", [1, 7, 128, 129, 1000, 4096, 200_000])
@pytest.mark.parametrize("density", [0.0, 0.01, 0.5, 1.0])
def test_emulator_matches_lax(n, density):
    rng = np.random.default_rng(n * 1000 + int(density * 100))
    x = _random_seed_array(rng, n, density)
    got = np.asarray(locf_blocked_reference(x, block=8))
    want = np.asarray(locf_lax(x))
    np.testing.assert_array_equal(got, want)


def test_emulator_matches_cummax_on_monotone_seeds():
    rng = np.random.default_rng(7)
    x = _random_seed_array(rng, 50_000, 0.05, monotone=True)
    got = np.asarray(locf_blocked_reference(x))
    want = np.asarray(jax.lax.cummax(x))
    np.testing.assert_array_equal(got, want)


def test_emulator_adversarial_layouts():
    cases = [
        jnp.full(300, HOLE, jnp.int32),                       # all holes
        jnp.arange(300, dtype=jnp.int32),                     # no holes
        jnp.asarray([HOLE] * 299 + [5], jnp.int32),           # one at end
        jnp.asarray([5] + [HOLE] * 299, jnp.int32),           # one at start
        # value at every block boundary only
        jnp.asarray([v if i % 128 == 0 else HOLE
                     for i, v in enumerate(range(300))], jnp.int32),
    ]
    for x in cases:
        np.testing.assert_array_equal(
            np.asarray(locf_blocked_reference(x, block=8)),
            np.asarray(locf_lax(x)))


def test_locf_flat_vmap_exact():
    from jepsen_tpu.ops.pallas_fill import locf_flat

    rng = np.random.default_rng(11)
    xs = jnp.stack([_random_seed_array(rng, 500, 0.1) for _ in range(4)])
    got = np.asarray(jax.vmap(locf_flat)(xs))
    want = np.asarray(jax.vmap(locf_lax)(xs))
    np.testing.assert_array_equal(got, want)


def test_infer_kernel_branch_matches_legacy():
    """The full device_infer kernel-branch restructure, driven through
    the emulator on this backend, must reproduce the legacy core_check
    bits exactly — including on histories with seeded anomalies."""
    import dataclasses

    from jepsen_tpu.checkers.elle.device_core import core_check
    from jepsen_tpu.checkers.elle.device_infer import pad_packed
    from jepsen_tpu.history.soa import TXN_FAIL
    from jepsen_tpu.workloads import synth

    # odd sizes -> unique padded shapes -> fresh jit traces under each
    # env setting (the branch is chosen at trace time from the env)
    padded = []
    for n, nk, seed in [(531, 7, 3), (1043, 1, 4), (775, 19, 5),
                        (777, 5, 6)]:
        p = synth.packed_la_history(n_txns=n, n_keys=nk, seed=seed)
        h = pad_packed(p)
        if seed == 5:
            # aborted writer whose appends stay visible -> G1a et al.
            h = dataclasses.replace(
                h, txn_type=h.txn_type.at[0].set(TXN_FAIL))
        if seed == 6:
            # corrupt one read element -> incompatible-order / internal
            h = dataclasses.replace(
                h, rd_elems=h.rd_elems.at[3].set(h.rd_elems[9]))
        padded.append((h, p.n_keys))
    results = {}
    for mode, env in [("legacy", {"JT_PALLAS": "0"}),
                      ("kernel", {"JT_PALLAS": "1",
                                  "JT_PALLAS_EMULATE": "1"})]:
        old = {k: os.environ.get(k) for k in
               ("JT_PALLAS", "JT_PALLAS_EMULATE")}
        os.environ.update(env)
        # the env branch is chosen at trace time: drop cached traces so
        # the second mode doesn't silently reuse the first mode's program
        core_check.clear_cache()
        try:
            outs = []
            for h, nk in padded:
                bits, over = core_check(h, nk)
                outs.append((np.asarray(bits), int(over)))
            results[mode] = outs
        finally:
            for k, v in old.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
    for (gb, go), (wb, wo) in zip(results["kernel"], results["legacy"]):
        np.testing.assert_array_equal(gb, wb)
        assert go == wo
