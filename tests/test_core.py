"""Orchestration tests: core.run against the in-process sim cluster
(reference core_test.clj strategy, SURVEY.md §4)."""

import os

import pytest

from jepsen_tpu import core, db, store
from jepsen_tpu import control as control_api
from jepsen_tpu.checkers import api as checker_api
from jepsen_tpu.control.sim import SimRemote
from jepsen_tpu.generator import core as g
from jepsen_tpu.history.ops import INVOKE, OK
from jepsen_tpu.nemesis.core import Noop as NoopNemesis
from jepsen_tpu.workloads.mem import MemClient, MemStore


class RecordingDB(db.DB, db.LogFiles):
    """A db that records lifecycle calls and runs a setup command."""

    def __init__(self):
        self.calls = []

    def setup(self, test, node):
        self.calls.append(("setup", node))
        control_api.exec_("install-db", "--version", "1")

    def teardown(self, test, node):
        self.calls.append(("teardown", node))

    def log_files(self, test, node):
        return []


def _base_test(tmp_path, **kw):
    remote = SimRemote()
    for n in ("n1", "n2", "n3"):
        remote.node(n).respond("*", "")
    t = dict(
        name="core-test",
        nodes=["n1", "n2", "n3"],
        remote=remote,
        db=RecordingDB(),
        client=MemClient(),
        concurrency=3,
        generator=g.clients(g.limit(
            12, lambda t, c: {"f": "read", "value": None})),
        checker=checker_api.Stats(),
        **{"store-dir": str(tmp_path / "store")},
    )
    t.update(kw)
    return t


def test_run_full_lifecycle(tmp_path):
    t = _base_test(tmp_path)
    rdb = t["db"]
    done = core.run(t)

    # history produced and complete
    h = done["history"]
    assert len([o for o in h if o.type == INVOKE]) == 12
    assert len([o for o in h if o.type == OK]) == 12
    # results from the checker
    assert done["results"]["valid?"] is True
    assert done["results"]["count"] == 12
    # db setup and teardown ran on every node
    assert {("setup", n) for n in t["nodes"]} <= set(rdb.calls)
    assert {("teardown", n) for n in t["nodes"]} <= set(rdb.calls)
    # setup command actually went through the control plane
    assert any("install-db" in c
               for c in t["remote"].all_cmds()["n1"])
    # store artifacts written
    d = store.test_dir(done)
    for f in ("test.jepsen", "history.json", "results.json", "jepsen.log"):
        assert os.path.exists(os.path.join(d, f)), f
    # sessions were closed and scrubbed from the map
    assert "sessions" not in done


def test_run_noop_no_nodes(tmp_path):
    done = core.run({"name": "noop", "store-dir": str(tmp_path / "s")})
    assert done["results"]["valid?"] is True
    assert len(done["history"]) == 0


def test_run_with_nemesis_lifecycle(tmp_path):
    events = []

    class TrackingNemesis(NoopNemesis):
        def setup(self, test):
            events.append("setup")
            return self

        def invoke(self, test, op):
            events.append(op["f"])
            return dict(op, type="info")

        def teardown(self, test):
            events.append("teardown")

    gen = g.any_gen(
        g.clients(g.limit(4, lambda t, c: {"f": "read", "value": None})),
        g.nemesis(g.limit(1, {"f": "start-partition", "value": None})),
    )
    t = _base_test(tmp_path, nemesis=TrackingNemesis(), generator=gen)
    done = core.run(t)
    assert events[0] == "setup" and events[-1] == "teardown"
    assert "start-partition" in events
    nem_ops = [o for o in done["history"] if o.process == "nemesis"]
    assert nem_ops


def test_checker_crash_is_captured_not_raised(tmp_path):
    class Exploder(checker_api.Checker):
        def check(self, test, history, opts=None):
            raise RuntimeError("kaboom")

    t = _base_test(tmp_path, checker=Exploder())
    done = core.run(t)
    assert done["results"]["valid?"] == "unknown"
    assert "kaboom" in str(done["results"].get("error", ""))
    # phase-0 artifacts survived the checker crash
    assert os.path.exists(os.path.join(store.test_dir(done), "history.json"))


def test_analyze_recheck_from_store(tmp_path):
    t = _base_test(tmp_path)
    done = core.run(t)
    d = store.test_dir(done)
    re = core.analyze(d, checker=checker_api.Stats())
    assert re["results"]["valid?"] is True
    assert re["results"]["count"] == 12
    # results were re-saved
    assert store.load(d)["results"]["count"] == 12


def test_analyze_requires_checker(tmp_path):
    t = _base_test(tmp_path)
    done = core.run(t)
    with pytest.raises(ValueError):
        core.analyze(store.test_dir(done))


def test_leave_db_running(tmp_path):
    t = _base_test(tmp_path, **{"leave-db-running": True})
    rdb = t["db"]
    core.run(t)
    assert not any(c[0] == "teardown" for c in rdb.calls)


def test_teardown_runs_when_workload_crashes(tmp_path):
    t = _base_test(tmp_path, client=MemClient())
    rdb = t["db"]

    # crash during db setup on one node
    orig_setup = rdb.setup
    def bad_setup(test, node):
        orig_setup(test, node)
        if node == "n2":
            raise RuntimeError("node 2 is on fire")
    rdb.setup = bad_setup
    with pytest.raises(Exception):
        core.run(t)
    # teardown still ran on all nodes despite the setup crash
    assert {("teardown", n) for n in t["nodes"]} <= set(rdb.calls)
