"""Interpreter tests: real worker threads against the in-process simulated
cluster (reference core_test.clj / interpreter strategy, SURVEY.md §4)."""

import random

from jepsen_tpu.generator import core as g
from jepsen_tpu.generator import interpreter
from jepsen_tpu.history.ops import INFO, INVOKE, OK
from jepsen_tpu.workloads.mem import MemClient, MemStore


def run_test(gen, *, concurrency=3, client=None, nodes=None, **kw):
    test = {"concurrency": concurrency,
            "client": client or MemClient(),
            "nodes": nodes or ["n1", "n2", "n3"],
            "generator": gen, **kw}
    return interpreter.run(test)


def test_basic_run_builds_history():
    h = run_test(g.clients(g.limit(10, lambda t, c: {"f": "read", "value": None})))
    invokes = [op for op in h if op.type == INVOKE]
    oks = [op for op in h if op.type == OK]
    assert len(invokes) == 10
    assert len(oks) == 10
    # histories are dense: index == position, invoke/completion paired
    for op in invokes:
        comp = h.completion(op)
        assert comp is not None and comp.f == op.f


def test_concurrency_respected():
    h = run_test(g.clients(g.limit(30, lambda t, c: {"f": "read", "value": None})),
                 concurrency=2)
    open_count, worst = 0, 0
    for op in h:
        if op.type == INVOKE:
            open_count += 1
            worst = max(worst, open_count)
        else:
            open_count -= 1
    assert worst <= 2


def test_writes_visible_to_reads():
    store = MemStore()
    gen = g.clients([
        {"f": "write", "value": 7},
        {"f": "read", "value": None},
    ])
    h = run_test(gen, client=MemClient(store), concurrency=1)
    reads = [op for op in h if op.type == OK and op.f == "read"]
    assert reads and reads[-1].value == 7


def test_info_crashes_bump_process():
    client = MemClient(crash_p=0.5, rng=random.Random(3))
    h = run_test(g.clients(g.limit(20, lambda t, c: {"f": "read", "value": None})),
                 client=client, concurrency=2)
    infos = [op for op in h if op.type == INFO and op.is_client_op()]
    assert infos, "crash_p=0.5 over 20 ops should produce infos"
    procs = {op.process for op in h if op.is_client_op()}
    assert any(p >= 2 for p in procs), procs


def test_time_limit_stops_run():
    h = run_test(g.clients(g.time_limit(
        0.3, g.stagger(0.01, g.cycle({"f": "read", "value": None})))))
    assert len(h) > 0
    assert max(op.time for op in h) < 2_000_000_000


def test_nemesis_ops_complete_info():
    class Nem:
        def invoke(self, test, op):
            return dict(op, type="info", value="partitioned")

    gen = g.any_gen(
        g.clients(g.limit(5, lambda t, c: {"f": "read", "value": None})),
        g.nemesis(g.limit(1, {"f": "start", "value": None})))
    h = run_test(gen, nemesis=Nem())
    nem_ops = [op for op in h if op.process == "nemesis"]
    assert len(nem_ops) == 2  # invoke + info completion
    assert nem_ops[-1].type == INFO
    assert nem_ops[-1].value == "partitioned"


def test_end_to_end_list_append_valid():
    """Full slice: generator -> interpreter -> mem cluster -> Elle checker."""
    from jepsen_tpu.checkers.elle import oracle
    from jepsen_tpu.workloads.synth import la_generator

    rng = random.Random(11)
    store = MemStore()
    gen = g.clients(g.limit(120, la_generator(n_keys=4, rng=rng)))
    h = run_test(gen, client=MemClient(store), concurrency=4)
    res = oracle.check(h, ["strict-serializable"])
    assert res["valid?"] is True, res


def test_exception_becomes_info():
    class Boom(MemClient):
        def invoke(self, test, op):
            raise RuntimeError("kaput")

    h = run_test(g.clients(g.limit(3, lambda t, c: {"f": "read", "value": None})),
                 client=Boom(), concurrency=1)
    infos = [op for op in h if op.type == INFO and op.is_client_op()]
    assert len(infos) == 3
    assert "kaput" in str(infos[0].error)
