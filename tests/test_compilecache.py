"""compilecache/ — the shape-bucketed AOT executable cache (ISSUE 18).

Pins the three layers and their contracts:

- **bucket policy**: the pow2 rounding rule equals
  ``device_infer.pow2_at_least`` (drift pin), padding is monotone, and
  a shrink probe + campaign cell at nearby sizes land in the SAME
  shape class (the whole point of bucketing);
- **store**: entries are self-verifying — roundtrip, truncation, and
  bit-flips are detected, and a corrupt entry is deleted on sight;
- **seam**: miss -> disk entry -> (cleared memory) -> disk hit with
  identical values; corrupt entries fall through and re-serialize;
  chaos plans fire ONLY when they name a compilecache site; disabled
  env means plain jit untouched;
- **cold vs warm**: a real core check loaded from the AOT store
  returns bitwise the verdict of the cold compile, with zero misses;
- **warm ladder**: ``warm_ladder`` populates exactly the classes the
  live dispatcher routes, so the next live check is all hits;
- **fleet**: advert/pull/push/absorb over a real coordinator + HTTP
  server — a pre-warmed first claim dispatches with ZERO compile-cache
  misses, wrong-digest pulls are rejected, and pushed entries land in
  the coordinator's flat store.
"""

import os

import numpy as np
import pytest

from jepsen_tpu import compilecache
from jepsen_tpu.compilecache import bucket, fleet as cc_fleet, store
from jepsen_tpu.compilecache import warm as cc_warm


@pytest.fixture(autouse=True)
def _cc_isolated():
    """Save/restore the process-global cache-dir override and drop the
    in-memory table + stats around every test — no test leaks its pin
    or its executables into the next."""
    prev = compilecache._dir_override
    compilecache.clear()
    compilecache.reset_stats()
    yield
    compilecache._dir_override = prev
    compilecache.clear()
    compilecache.reset_stats()


def _jit_double():
    import jax

    return jax.jit(lambda x: x * 2 + 1)


def _arange(n):
    import jax.numpy as jnp

    return jnp.arange(n, dtype=jnp.float32)


# -- bucket policy -----------------------------------------------------------


def test_pow2_rule_pinned_to_device_infer():
    """The drift pin: bucket's rounding rule IS device_infer's — two
    copies of the rule may never disagree on any size."""
    from jepsen_tpu.checkers.elle import device_infer

    for n in [*range(1, 300), 1000, 4097, 65536, 100001]:
        assert bucket.pow2_at_least(n) == device_infer.pow2_at_least(n)


def test_pow2_monotone_floor():
    prev = 0
    for n in range(1, 2050):
        b = bucket.pow2_at_least(n)
        assert b >= n and b >= 8
        assert b & (b - 1) == 0, f"{b} not a power of two"
        assert b >= prev
        prev = b
    assert bucket.pow2_at_least(3, floor=16) == 16


def test_probe_and_cell_share_class():
    """A shrink probe at 300 txns and a campaign cell at 400 pad into
    the SAME shape class — one executable serves both."""
    from jepsen_tpu.checkers.elle.device_infer import pad_packed
    from jepsen_tpu.workloads import synth

    kw = dict(concurrency=10, mops_per_txn=4, read_frac=0.25, seed=7)
    sigs = []
    for n in (300, 400):
        p = synth.packed_la_history(n_txns=n, n_keys=64, **kw)
        sigs.append(bucket.signature((pad_packed(p),)))
    assert sigs[0] == sigs[1]
    st = {"n_keys": 64, "max_k": 128}
    assert bucket.class_digest("elle.core-check", (), st) == \
        bucket.class_digest("elle.core-check", (), st)
    # a different static is a different specialization
    assert bucket.class_digest("elle.core-check", (), st) != \
        bucket.class_digest("elle.core-check", (), {**st, "max_k": 256})
    # and a different site is a different class
    assert bucket.class_digest("elle.infer", (), st) != \
        bucket.class_digest("elle.core-check", (), st)


def test_abstract_and_concrete_sign_identically():
    import jax

    x = _arange(64)
    sds = jax.ShapeDtypeStruct(x.shape, x.dtype)
    assert bucket.signature((x,)) == bucket.signature((sds,))


def test_ladder():
    assert bucket.ladder() == sorted(bucket.LADDER)
    assert bucket.ladder(max_txns=5000) == \
        sorted(set(bucket.LADDER) | {2048, 4096, 8192})
    assert bucket.ladder(sizes=[100, 100, 3]) == [8, 128]
    # --max-txns CAPS the ladder (the CLI help's contract): rungs
    # above the bucket are dropped, never warmed
    assert bucket.ladder(max_txns=128) == [64, 128]
    assert bucket.ladder(max_txns=200) == [64, 128, 256]
    assert bucket.ladder(max_txns=5) == [8]


def test_ir_bucket_class_shared_across_sizes():
    """history.ir exposes the class label; nearby sizes report the
    same one (what the pre-warm ladder covers)."""
    from jepsen_tpu.history.ir import HistoryIR
    from jepsen_tpu.workloads import synth

    kw = dict(concurrency=10, mops_per_txn=4, read_frac=0.25, seed=7)
    labels = set()
    for n in (300, 400):
        p = synth.packed_la_history(n_txns=n, n_keys=64, **kw)
        labels.add(HistoryIR(p).bucket_class())
    assert len(labels) == 1


# -- store -------------------------------------------------------------------


def test_store_roundtrip_and_corruption(tmp_path):
    d = str(tmp_path)
    meta = {"site": "t", "class": "c"}
    payload = (b"executable-bytes", {"tree": 1})
    blob = store.pack_entry(meta, payload)
    assert blob.startswith(store.MAGIC)
    doc = store.unpack_entry(blob)
    assert doc["meta"] == meta and doc["payload"] == payload
    # truncation and bit-flips are both detected
    assert store.unpack_entry(blob[:-3]) is None
    flipped = bytearray(blob)
    flipped[len(blob) // 2] ^= 0xFF
    assert store.unpack_entry(bytes(flipped)) is None
    assert store.unpack_entry(b"not an entry") is None

    n = store.put(d, "f" * 40, meta, payload)
    assert n > 0
    got = store.get(d, "f" * 40)
    assert got is not None and got[0]["meta"] == meta
    assert [e["name"] for e in store.entries(d)] == \
        ["f" * 40 + store.SUFFIX]
    assert store.total_bytes(d) == n
    store.delete(d, "f" * 40)
    assert store.entries(d) == []


def test_store_get_deletes_corrupt_on_sight(tmp_path):
    d = str(tmp_path)
    store.put(d, "a" * 40, {"site": "t"}, b"p")
    path = os.path.join(d, "a" * 40 + store.SUFFIX)
    with open(path, "r+b") as f:
        f.seek(os.path.getsize(path) // 2)
        b = f.read(1)
        f.seek(-1, os.SEEK_CUR)
        f.write(bytes([b[0] ^ 0xFF]))
    assert store.get(d, "a" * 40) is None
    assert not os.path.exists(path), "corrupt entry must be deleted"


# -- the call seam -----------------------------------------------------------


def test_call_miss_then_disk_hit(tmp_path):
    """miss -> persisted entry; cleared memory -> disk load counts a
    hit and returns the identical value."""
    compilecache.set_cache_dir(str(tmp_path))
    f = _jit_double()
    x = _arange(64)
    want = np.asarray(x) * 2 + 1
    out = compilecache.call("t.seam", f, x)
    assert np.array_equal(np.asarray(out), want)
    st = compilecache.stats()
    assert st["misses"] == 1 and st["hits"] == 0
    assert st["entries"] == 1 and st["fallthroughs"] == 0
    # in-memory fast path: second call is a hit without touching disk
    compilecache.call("t.seam", f, x)
    assert compilecache.stats()["hits"] == 1
    # drop the executable table: the disk entry alone must serve
    compilecache.clear()
    compilecache.reset_stats()
    out2 = compilecache.call("t.seam", f, x)
    st = compilecache.stats()
    assert np.array_equal(np.asarray(out2), want)
    assert st["hits"] == 1 and st["misses"] == 0 \
        and st["fallthroughs"] == 0


def test_corrupt_entry_falls_through_and_reserializes(tmp_path):
    compilecache.set_cache_dir(str(tmp_path))
    f = _jit_double()
    x = _arange(64)
    compilecache.call("t.corrupt", f, x)
    [e] = store.entries(str(tmp_path))
    path = os.path.join(str(tmp_path), e["name"])
    with open(path, "r+b") as fh:
        fh.seek(e["size"] // 2)
        b = fh.read(1)
        fh.seek(-1, os.SEEK_CUR)
        fh.write(bytes([b[0] ^ 0xFF]))
    compilecache.clear()
    compilecache.reset_stats()
    out = compilecache.call("t.corrupt", f, x)
    st = compilecache.stats()
    assert np.array_equal(np.asarray(out), np.asarray(x) * 2 + 1)
    assert st["misses"] == 1 and st["hits"] == 0
    # the recompile re-serialized a good entry in place
    [e2] = store.entries(str(tmp_path))
    with open(os.path.join(str(tmp_path), e2["name"]), "rb") as fh:
        assert store.unpack_entry(fh.read()) is not None


def test_loaded_entry_raising_at_dispatch_self_heals(tmp_path,
                                                     monkeypatch):
    """An entry that deserializes fine but whose executable raises at
    dispatch (execute-time skew) is DELETED, the call falls through to
    plain jit, and the next call recompiles + re-persists — the cache
    never pays deserialize + fall-through forever."""
    from jax.experimental import serialize_executable as se

    compilecache.set_cache_dir(str(tmp_path))
    f = _jit_double()
    x = _arange(64)
    want = np.asarray(x) * 2 + 1
    compilecache.call("t.skew", f, x)
    assert len(store.entries(str(tmp_path))) == 1

    class _Broken:
        def __call__(self, *a):
            raise RuntimeError("Symbols not found (execute-time skew)")

    compilecache.clear()
    compilecache.reset_stats()
    monkeypatch.setattr(se, "deserialize_and_load",
                        lambda *a, **kw: _Broken())
    out = compilecache.call("t.skew", f, x)
    assert np.array_equal(np.asarray(out), want)  # fell through, right
    st = compilecache.stats()
    assert st["fallthroughs"] == 1
    assert store.entries(str(tmp_path)) == [], \
        "the skewed entry must be deleted, not retried forever"
    monkeypatch.undo()
    compilecache.clear()
    compilecache.reset_stats()
    out2 = compilecache.call("t.skew", f, x)
    assert np.array_equal(np.asarray(out2), want)
    st = compilecache.stats()
    assert st["misses"] == 1 and st["fallthroughs"] == 0
    assert len(store.entries(str(tmp_path))) == 1, "re-persisted"


def test_chaos_plan_fires_only_when_named(tmp_path):
    """The opt-in contract: a plan naming compilecache.compile forces
    the fall-through tail (correct value, counted); a bare p=1 plan
    does NOT fire at cache seams."""
    from jepsen_tpu.resilience import FaultPlan, use

    compilecache.set_cache_dir(str(tmp_path))
    f = _jit_double()
    x = _arange(64)
    want = np.asarray(x) * 2 + 1
    plan = FaultPlan(seed=3, p=1.0, kinds=("xla",),
                     sites="compilecache.compile")
    with use(plan):
        out = compilecache.call("t.chaos", f, x)
    st = compilecache.stats()
    assert np.array_equal(np.asarray(out), want)
    assert st["fallthroughs"] == 1 and st["misses"] == 0
    assert store.entries(str(tmp_path)) == [], \
        "a faulted compile must not persist an entry"

    compilecache.reset_stats()
    bare = FaultPlan(seed=3, p=1.0, kinds=("xla",))
    with use(bare):
        out = compilecache.call("t.chaos", f, x)
    st = compilecache.stats()
    assert np.array_equal(np.asarray(out), want)
    assert st["fallthroughs"] == 0 and st["misses"] == 1
    assert bare.injected == [], \
        "an unnamed plan must not advance at cache seams"


def test_disabled_env_means_plain_jit(tmp_path, monkeypatch):
    monkeypatch.setenv("JT_COMPILECACHE", "0")
    compilecache.set_cache_dir(str(tmp_path))
    f = _jit_double()
    x = _arange(64)
    out = compilecache.call("t.off", f, x)
    assert np.array_equal(np.asarray(out), np.asarray(x) * 2 + 1)
    st = compilecache.stats()
    assert st["hits"] == 0 and st["misses"] == 0 \
        and st["fallthroughs"] == 0
    assert store.entries(str(tmp_path)) == []


def test_ensure_abstract_then_concrete_hit(tmp_path):
    """ensure() at ShapeDtypeStruct shapes populates the class a later
    concrete call hits — the pre-warm mechanism itself."""
    import jax

    compilecache.set_cache_dir(str(tmp_path))
    f = _jit_double()
    x = _arange(128)
    how = compilecache.ensure(
        "t.warm", f, jax.ShapeDtypeStruct(x.shape, x.dtype))
    assert how == "compiled"
    assert compilecache.ensure(
        "t.warm", f, jax.ShapeDtypeStruct(x.shape, x.dtype)) == "cached"
    compilecache.reset_stats()
    out = compilecache.call("t.warm", f, x)
    st = compilecache.stats()
    assert np.array_equal(np.asarray(out), np.asarray(x) * 2 + 1)
    assert st["hits"] == 1 and st["misses"] == 0


# -- cold vs warm on the real checker ----------------------------------------


def _leaves_equal(a, b):
    import jax

    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    return len(la) == len(lb) and all(
        np.array_equal(np.asarray(x), np.asarray(y))
        for x, y in zip(la, lb))


def test_cold_vs_warm_core_check_equal(tmp_path):
    """The acceptance bar: a core check served from the AOT store is
    bitwise the cold-compile verdict, with zero misses.

    The suite's persistent jax compilation cache is disabled for the
    cold compile: an XLA:CPU executable the jit cache LOADED (rather
    than compiled) re-serializes incompletely ("Symbols not found" at
    deserialize) — the seam detects that, drops the entry, and
    recompiles (graceful), but this test pins the genuine
    serialize→deserialize round trip, so it needs a fresh compile.
    Flipping the config alone is not enough once the cache singleton
    has initialized; reset_cache() makes the flip take effect."""
    import jax

    from jepsen_tpu.checkers.elle.device_core import core_check_auto
    from jepsen_tpu.checkers.elle.device_infer import pad_packed
    from jepsen_tpu.workloads import synth

    def _reset_jit_cache():
        try:
            from jax._src import compilation_cache as cc_mod
            cc_mod.reset_cache()
        except Exception:
            pass

    compilecache.set_cache_dir(str(tmp_path))
    prev_jit_cache = jax.config.jax_compilation_cache_dir
    jax.config.update("jax_compilation_cache_dir", None)
    _reset_jit_cache()
    try:
        p = synth.packed_la_history(n_txns=100, n_keys=64,
                                    concurrency=10, mops_per_txn=4,
                                    read_frac=0.25, seed=7)
        h = pad_packed(p)
        cold = core_check_auto(h, p.n_keys, max_k=64)
        st = compilecache.stats()
        assert st["misses"] >= 1
        assert st["entries"] >= 1, "the cold compile must persist"

        compilecache.clear()
        jax.clear_caches()
        compilecache.reset_stats()
        warm = core_check_auto(h, p.n_keys, max_k=64)
        st = compilecache.stats()
        assert _leaves_equal(cold, warm)
        assert st["hits"] >= 1 and st["misses"] == 0 \
            and st["fallthroughs"] == 0
    finally:
        jax.config.update("jax_compilation_cache_dir", prev_jit_cache)
        _reset_jit_cache()


def test_warm_ladder_covers_live_dispatch(tmp_path):
    """The warmed class IS the live class: after warm_ladder at one
    rung, a live check over a default-generator history of that rung
    dispatches with zero misses."""
    from jepsen_tpu.checkers.elle.device_core import core_check_auto
    from jepsen_tpu.checkers.elle.device_infer import pad_packed
    from jepsen_tpu.workloads import synth

    compilecache.set_cache_dir(str(tmp_path))
    recs = cc_warm.warm_ladder(sizes=(64,), families=("la",), max_k=64)
    assert len(recs) == 1 and recs[0]["ok"], recs
    assert all(p["how"] in ("compiled", "loaded", "cached")
               for p in recs[0]["programs"])
    assert len(store.entries(str(tmp_path))) >= 1

    p = synth.packed_la_history(n_txns=64, n_keys=64,
                                **cc_warm._LA_KW)
    h = pad_packed(p)
    compilecache.reset_stats()
    core_check_auto(h, p.n_keys, max_k=64)
    st = compilecache.stats()
    assert st["misses"] == 0 and st["fallthroughs"] == 0, st
    assert st["hits"] >= 1


# -- fleet distribution ------------------------------------------------------


def test_safe_name():
    fp = "a" * 40
    assert cc_fleet._safe_name(fp + store.SUFFIX)
    assert not cc_fleet._safe_name("x/../y" + store.SUFFIX)
    assert not cc_fleet._safe_name("." + store.SUFFIX)
    assert not cc_fleet._safe_name("a\\b" + store.SUFFIX)
    assert not cc_fleet._safe_name(fp)  # wrong suffix


def test_export_index_memo_and_read(tmp_path):
    d = str(tmp_path)
    store.put(d, "b" * 40, {"site": "t"}, b"p")
    [row] = cc_fleet.export_index(d)
    assert row["name"] == "b" * 40 + store.SUFFIX
    path = os.path.join(d, row["name"])
    assert row["digest"] == store.file_digest(path)
    # memoized by path + (size, mtime_ns): a second export returns the
    # same row
    assert cc_fleet.export_index(d) == [row]
    assert path in cc_fleet._digests
    blob = cc_fleet.read_entry(d, row["name"])
    assert blob is not None and store.unpack_entry(blob) is not None
    assert cc_fleet.read_entry(d, "../" + row["name"]) is None
    assert cc_fleet.read_entry(d, "nope" + store.SUFFIX) is None
    # the memo never outlives its file: a deleted entry's digest is
    # pruned on the next export, and compilecache.clear() empties it
    store.delete(d, "b" * 40)
    assert cc_fleet.export_index(d) == []
    assert path not in cc_fleet._digests
    store.put(d, "b" * 40, {"site": "t"}, b"p")
    cc_fleet.export_index(d)
    compilecache.clear()
    assert cc_fleet._digests == {}


def _mint_batch(base, entries):
    """Stage a pushed-batch dir: (name, blob, mac|None) triples."""
    batch = os.path.join(base, "compilecache", "cc-test")
    os.makedirs(batch, exist_ok=True)
    for name, blob, mac in entries:
        with open(os.path.join(batch, name), "wb") as f:
            f.write(blob)
        if mac is not None:
            with open(os.path.join(batch,
                                   name + cc_fleet.MAC_SUFFIX),
                      "wb") as f:
                f.write(mac.encode())
    return batch


def test_absorb_verifies_and_flattens(tmp_path, monkeypatch):
    monkeypatch.setenv(cc_fleet.SECRET_ENV, "test-secret")
    secret = cc_fleet.shared_secret(None)
    base = str(tmp_path)
    good = store.pack_entry({"site": "t"}, b"p")
    other = store.pack_entry({"site": "t"}, b"q")
    batch = _mint_batch(base, [
        ("c" * 40 + store.SUFFIX, good, cc_fleet.entry_mac(secret,
                                                           good)),
        ("d" * 40 + store.SUFFIX, b"corrupt",
         cc_fleet.entry_mac(secret, b"corrupt")),
        ("e" * 40 + store.SUFFIX, other, "0" * 64),  # forged MAC
        ("f" * 40 + store.SUFFIX, other, None),      # no sidecar
        ("notes.txt", b"skip me", None),
    ])
    n = cc_fleet.absorb(base, "compilecache/cc-test")
    assert n == 1
    assert not os.path.exists(batch), "batch dir must be removed"
    flat = os.path.join(base, "compilecache")
    assert [e["name"] for e in store.entries(flat)] == \
        ["c" * 40 + store.SUFFIX]


def test_transfers_refuse_without_secret(tmp_path, monkeypatch):
    """The RCE guard: no shared secret means NO network bytes are ever
    unpickled — absorb drops the whole batch, pull and push refuse
    outright.  The local cache is untouched either way."""
    monkeypatch.delenv(cc_fleet.SECRET_ENV, raising=False)
    base = str(tmp_path)
    good = store.pack_entry({"site": "t"}, b"p")
    batch = _mint_batch(base, [
        ("c" * 40 + store.SUFFIX, good, None)])
    assert cc_fleet.shared_secret(base) is None
    # a FILE at <base>/fleet makes the coordinator's auto-mint fail,
    # pinning the secretless-absorb branch: the whole batch drops
    with open(os.path.join(base, "fleet"), "wb") as f:
        f.write(b"not a dir")
    assert cc_fleet.absorb(base, "compilecache/cc-test") == 0
    assert not os.path.exists(batch)
    assert store.entries(os.path.join(base, "compilecache")) == []
    # with a mintable secret, an entry missing its MAC sidecar is
    # still dropped — unauthenticated bytes are never unpickled
    os.remove(os.path.join(base, "fleet"))
    batch = _mint_batch(base, [
        ("c" * 40 + store.SUFFIX, good, None)])
    assert cc_fleet.absorb(base, "compilecache/cc-test") == 0
    assert store.entries(os.path.join(base, "compilecache")) == []
    # worker side: no secret -> pull refuses before any HTTP
    adv = [{"name": "c" * 40 + store.SUFFIX, "digest": "0" * 64,
            "size": 1}]
    d = os.path.join(base, "wdir")
    assert cc_fleet.pull_missing("http://127.0.0.1:9", adv, d,
                                 secret=None) == 0
    assert cc_fleet.push_new(object(), {"x" + store.SUFFIX}, d,
                             secret=None) is False


def test_shared_secret_mint_and_reuse(tmp_path, monkeypatch):
    monkeypatch.delenv(cc_fleet.SECRET_ENV, raising=False)
    base = str(tmp_path)
    assert cc_fleet.shared_secret(base) is None, "no mint on read"
    s = cc_fleet.shared_secret(base, create=True)
    assert s and len(s) == 64  # token_hex(32)
    assert cc_fleet.shared_secret(base) == s, "stable across reads"
    assert os.stat(os.path.join(base, "fleet", "secret")).st_mode \
        & 0o777 == 0o600
    monkeypatch.setenv(cc_fleet.SECRET_ENV, "env-wins")
    assert cc_fleet.shared_secret(base) == b"env-wins"


def test_fleet_prewarmed_first_claim_zero_miss(tmp_path, monkeypatch):
    """End to end over a real coordinator + HTTP server: the claim
    adverts the coordinator's entries, the worker pulls what it lacks
    (HMAC-verified under the shared secret), and its FIRST dispatch of
    those classes counts ZERO misses.  Wrong digests are rejected; a
    worker-minted entry pushed over the artifact channel (with MAC
    sidecars) lands in the coordinator's flat store."""
    from jepsen_tpu import web
    from jepsen_tpu.fleet import FleetCoordinator, FleetWorker

    # the coordinator and (different-base) worker share the fleet
    # secret the multi-host way: the env var
    monkeypatch.setenv(cc_fleet.SECRET_ENV, "fleet-test-secret")
    secret = cc_fleet.shared_secret(None)
    base1 = str(tmp_path / "coord")
    cdir = os.path.join(base1, "compilecache")
    compilecache.set_cache_dir(cdir)
    f = _jit_double()
    xs = [_arange(64), _arange(128)]
    for x in xs:
        compilecache.call("t.fleet", f, x)
    names = cc_fleet.entry_names(cdir)
    assert len(names) == 2

    spec = {"name": "cc", "workloads": ["set"], "seeds": [1],
            "opts": {"time-limit": 0.1}}
    coord = FleetCoordinator(spec, base1, lease_s=5.0)
    srv = web.serve(port=0, base=base1, background=True, fleet=coord)
    url = f"http://127.0.0.1:{srv.server_address[1]}"
    try:
        code, resp = coord.claim({"worker": "w1"})
        assert code == 200 and resp.get("spec") is not None
        adv = resp.get("compilecache")
        assert adv and {r["name"] for r in adv} == names

        # worker side: a fresh store pulls everything at claim time
        base2 = str(tmp_path / "worker")
        wdir = os.path.join(base2, "compilecache")
        compilecache.set_cache_dir(wdir)
        assert cc_fleet.pull_missing(url, adv, wdir, secret) == 2
        assert cc_fleet.pull_missing(url, adv, wdir,
                                     secret) == 0  # idempotent
        compilecache.clear()
        compilecache.reset_stats()
        for x in xs:
            out = compilecache.call("t.fleet", f, x)
            assert np.array_equal(np.asarray(out),
                                  np.asarray(x) * 2 + 1)
        st = compilecache.stats()
        assert st["misses"] == 0 and st["fallthroughs"] == 0, st
        assert st["hits"] == 2

        # a wrong-digest advert is rejected, never installed
        victim = sorted(names)[0]
        os.remove(os.path.join(wdir, victim))
        bad = [{"name": victim, "digest": "0" * 64, "size": 1}]
        assert cc_fleet.pull_missing(url, bad, wdir, secret) == 0
        assert victim not in cc_fleet.entry_names(wdir)

        # a wrong SECRET fails the MAC check before anything else
        good_adv = [r for r in adv if r["name"] == victim]
        assert cc_fleet.pull_missing(url, good_adv, wdir,
                                     b"wrong-secret") == 0
        assert victim not in cc_fleet.entry_names(wdir)

        # push: a worker-minted class travels back and is absorbed
        x256 = _arange(256)
        compilecache.call("t.fleet", f, x256)
        new = cc_fleet.entry_names(wdir) - names
        assert len(new) == 1
        w = FleetWorker(url, base2, name="w1", poll_s=0.05)
        assert cc_fleet.push_new(w, new, wdir, secret)
        assert new <= cc_fleet.entry_names(cdir)
    finally:
        srv.server_close()
        coord.close()
