"""The anomaly-coverage contract (VERDICT r04 item 4): a checker asked
to validate a model whose proscribed anomalies it will not search must
return "unknown" with the unchecked list — never silently valid — and
session-guarantee tokens on list-append run the dedicated checker."""

import numpy as np
import pytest

from jepsen_tpu.checkers.elle import list_append, oracle, sessions
from jepsen_tpu.history import history, invoke, ok
from jepsen_tpu.history.soa import pack_txns
from jepsen_tpu.workloads import synth


def _valid_la_history(n=120):
    return synth.la_history(n_txns=n, n_keys=6, concurrency=4, seed=3)


def test_bare_causal_on_oplevel_history_runs_sessions():
    """Op-level input: session tokens are checked, verdict stays
    definitive (the round-4 hole: they were silently skipped)."""
    h = _valid_la_history()
    for check in (list_append.check, oracle.check):
        res = check(h, consistency_models=("causal",))
        assert res["valid?"] is True, res
        assert "unchecked-anomalies" not in res, res


def test_bare_causal_on_packed_input_degrades_to_unknown():
    """PackedTxns input drops the op-level view the session walker
    needs: a bare session-class request must degrade, not pass."""
    p = pack_txns(_valid_la_history(), "list-append")
    for check in (list_append.check, oracle.check):
        res = check(p, consistency_models=("causal",))
        assert res["valid?"] == "unknown", res
        assert "monotonic-reads-violation" in res["unchecked-anomalies"]


def test_strict_serializable_on_packed_stays_definitive():
    """Strict/strong-session-class requests keep their verdict on packed
    input: per-session ordering violations surface as process-edge
    cycles, which ARE searched."""
    p = pack_txns(_valid_la_history(), "list-append")
    for check in (list_append.check, oracle.check):
        res = check(p, consistency_models=("strict-serializable",))
        assert res["valid?"] is True, res


def test_monotonic_reads_violation_on_list_append():
    # P0 appends 1 then 2; P1 reads [1,2] then [1] — its view went
    # backwards.  Prefix-compatible, acyclic: only the session checker
    # can catch this.
    h = history([
        invoke(0, "txn", [["append", "x", 1]]),
        ok(0, "txn", [["append", "x", 1]]),
        invoke(0, "txn", [["append", "x", 2]]),
        ok(0, "txn", [["append", "x", 2]]),
        invoke(1, "txn", [["r", "x", None]]),
        ok(1, "txn", [["r", "x", [1, 2]]]),
        invoke(1, "txn", [["r", "x", None]]),
        ok(1, "txn", [["r", "x", [1]]]),
    ])
    sres = sessions.check_la(h)
    assert "monotonic-reads-violation" in sres["anomaly-types"], sres
    for check in (list_append.check, oracle.check):
        res = check(h, consistency_models=("monotonic-reads",))
        assert res["valid?"] is False, res
        assert "monotonic-reads-violation" in res["anomaly-types"]
        # a serializability-only request must not report (or be failed
        # by) an unrequested session token
        res2 = check(h, consistency_models=("serializable",))
        assert res2["valid?"] is True, res2


def test_read_your_writes_violation_on_list_append():
    # P0 appends 5 to y, later reads y=[] — own committed append absent.
    h = history([
        invoke(0, "txn", [["append", "y", 5]]),
        ok(0, "txn", [["append", "y", 5]]),
        invoke(0, "txn", [["r", "y", None]]),
        ok(0, "txn", [["r", "y", []]]),
    ])
    sres = sessions.check_la(h)
    assert "read-your-writes-violation" in sres["anomaly-types"], sres
    res = list_append.check(h, consistency_models=("read-your-writes",))
    assert res["valid?"] is False, res


def test_monotonic_writes_violation_on_list_append():
    # P0 appends 1 then 2 (separate txns); the longest read shows [2, 1]
    # — installed against session order.
    h = history([
        invoke(0, "txn", [["append", "x", 1]]),
        ok(0, "txn", [["append", "x", 1]]),
        invoke(0, "txn", [["append", "x", 2]]),
        ok(0, "txn", [["append", "x", 2]]),
        invoke(1, "txn", [["r", "x", None]]),
        ok(1, "txn", [["r", "x", [2, 1]]]),
    ])
    sres = sessions.check_la(h)
    assert "monotonic-writes-violation" in sres["anomaly-types"], sres


def test_snapshot_isolation_request_stays_definitive_on_la():
    """The SI-family tokens (G-SI/G-SIa/G-SIb/lost-update) are covered
    by equivalence on list-append (see coverage.py) — no degradation."""
    h = _valid_la_history()
    res = list_append.check(h, consistency_models=("snapshot-isolation",))
    assert res["valid?"] is True, res
    assert "unchecked-anomalies" not in res


def test_device_host_parity_with_sessions():
    """Device pipeline and host oracle agree on session-aware verdicts
    (the differential-fuzz contract extends to the new tokens)."""
    h = synth.la_history(n_txns=200, n_keys=5, concurrency=5, seed=11)
    for models in (("causal",), ("strict-serializable",), ("PRAM",)):
        a = list_append.check(h, consistency_models=models)
        b = oracle.check(h, consistency_models=models)
        assert a["valid?"] == b["valid?"], (models, a, b)
        assert a["anomaly-types"] == b["anomaly-types"], (models, a, b)


def test_g0_process_request_does_not_cover_session_tokens():
    """G0-process/G1c-process projections lack rw edges, so they cannot
    stand in for read-centric session checks on packed input (review
    r05 finding)."""
    p = pack_txns(_valid_la_history(), "list-append")
    res = list_append.check(p, consistency_models=("causal",),
                            anomalies=("G0-process",))
    assert res["valid?"] == "unknown", res
    assert "monotonic-reads-violation" in res["unchecked-anomalies"]


def test_rw_packed_bare_session_request_degrades():
    """The rw checker's inline degradation follows the same contract
    and key shape as the la checkers (review r05 finding: this path
    had no coverage)."""
    from jepsen_tpu.checkers.elle import rw_register

    p = synth.packed_rw_history(n_txns=150, n_keys=8, seed=2)
    res = rw_register.check(p, consistency_models=("causal",))
    assert res["valid?"] == "unknown", res
    assert "monotonic-reads-violation" in res["unchecked-anomalies"]
