"""Built-in checker tests (reference checker_test.clj style)."""

from jepsen_tpu.checkers.api import (
    CounterChecker, TotalQueueChecker, SetChecker, Stats, UniqueIds,
    check_safe, compose,
)
from jepsen_tpu.history import history, invoke, ok, fail, info


def test_queue_info_enqueue_not_lost():
    # an indeterminate enqueue that never appears is NOT lost
    h = history([
        invoke(0, "enqueue", 1),
        info(0, "enqueue", 1),
    ])
    res = TotalQueueChecker().check({}, h)
    assert res["valid?"] is True
    assert res["lost-count"] == 0


def test_queue_lost_and_unexpected():
    h = history([
        invoke(0, "enqueue", 1), ok(0, "enqueue", 1),
        invoke(1, "dequeue", None), ok(1, "dequeue", 7),
    ])
    res = TotalQueueChecker().check({}, h)
    assert res["valid?"] is False
    assert res["lost"] == {1: 1}
    assert res["unexpected"] == {7: 1}


def test_set_checker():
    h = history([
        invoke(0, "add", 1), ok(0, "add", 1),
        invoke(1, "add", 2), ok(1, "add", 2),
        invoke(2, "add", 3), fail(2, "add", 3),
        invoke(0, "read", None), ok(0, "read", [1]),
    ])
    res = SetChecker().check({}, h)
    assert res["valid?"] is False
    assert res["lost"] == [2]


def test_counter_checker():
    h = history([
        invoke(0, "add", 1), ok(0, "add", 1),
        invoke(1, "read", None), ok(1, "read", 1),
        invoke(0, "add", 2), info(0, "add", 2),   # maybe applied
        invoke(1, "read", None), ok(1, "read", 3),
        invoke(2, "read", None), ok(2, "read", 1),
        invoke(3, "read", None), ok(3, "read", 9),  # impossible
    ])
    res = CounterChecker().check({}, h)
    assert res["valid?"] is False
    assert len(res["errors"]) == 1 and res["errors"][0]["value"] == 9


def test_stats_and_compose():
    h = history([
        invoke(0, "txn", None), ok(0, "txn", None),
        invoke(1, "cas", None), fail(1, "cas", None),
    ])
    res = Stats().check({}, h)
    assert res["valid?"] is False  # cas never succeeded
    assert res["by-f"]["txn"]["ok-count"] == 1
    combined = compose({"stats": Stats(), "uids": UniqueIds()})
    out = check_safe(combined, {}, h)
    assert out["valid?"] is False
