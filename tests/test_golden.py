"""Golden-corpus replay: stored histories must keep their verdicts.

Reference parity: knossos's `data/` dirs of known good/bad histories
checked for expected verdicts (SURVEY.md §4).  Every file in tests/data
replays through the host oracle AND the device pipeline; both must
reproduce the frozen verdict.  Regenerate/extend with
scripts/make_golden.py.
"""

import glob
import json
import os

import pytest

from jepsen_tpu.history import history
from jepsen_tpu.history.ops import Op

DATA = os.path.join(os.path.dirname(os.path.abspath(__file__)), "data")
FILES = sorted(glob.glob(os.path.join(DATA, "*.json")))


def _load(path):
    with open(path) as f:
        d = json.load(f)
    h = history([Op(type=o["type"], process=o["process"], f=o["f"],
                    value=o["value"]) for o in d["history"]])
    return d, h


def test_corpus_present():
    assert len(FILES) >= 12, FILES


@pytest.mark.parametrize(
    "path", [p for p in FILES if os.path.basename(p).startswith("la-")],
    ids=os.path.basename)
def test_golden_list_append(path):
    from jepsen_tpu.checkers.elle import list_append, oracle

    d, h = _load(path)
    want = d["expected"]
    r_o = oracle.check(h, d["models"])
    r_d = list_append.check(h, d["models"], _force_no_fallback=True)
    for r in (r_o, r_d):
        assert r["valid?"] == want["valid?"], (path, r)
        assert sorted(r["anomaly-types"]) == want["anomaly-types"], (path, r)


@pytest.mark.parametrize(
    "path", [p for p in FILES if os.path.basename(p).startswith("rw-")],
    ids=os.path.basename)
def test_golden_rw_register(path):
    from jepsen_tpu.checkers.elle import rw_register

    d, h = _load(path)
    want = d["expected"]
    for use_device in (False, True):
        r = rw_register.check(h, d["models"], use_device=use_device)
        assert r["valid?"] == want["valid?"], (path, use_device, r)
        assert sorted(r["anomaly-types"]) == want["anomaly-types"], \
            (path, use_device, r)


@pytest.mark.parametrize(
    "path", [p for p in FILES if os.path.basename(p).startswith("lin-")],
    ids=os.path.basename)
def test_golden_linearizable(path):
    # same algorithm the corpus was generated with (wgl): competition
    # can legitimately return "unknown" on budget exhaustion, which
    # would flake a frozen True/False verdict
    from jepsen_tpu.checkers.knossos import wgl
    from jepsen_tpu.models import cas_register

    d, h = _load(path)
    want = d["expected"]
    r = wgl.check(h, cas_register())
    assert r["valid?"] == want["valid?"], (path, r)
