"""Generator DSL tests.

Mirrors the reference's generator test strategy (SURVEY.md §4): drive
generators with a fake context / simulated perfect clock and assert on the
exact op sequences."""

import random

from jepsen_tpu.generator import core as g
from jepsen_tpu.generator.context import Context, context
from jepsen_tpu.generator.sim import completions, invokes, simulate

TEST = {"concurrency": 2}


def ops_of(events):
    return [(e["f"], e["value"]) for e in invokes(events)]


# -- lifting ----------------------------------------------------------------

def test_map_is_one_shot():
    evs = simulate({"f": "read", "value": None}, TEST)
    assert ops_of(evs) == [("read", None)]
    # invoke then ok
    assert [e["type"] for e in evs] == ["invoke", "ok"]


def test_map_gets_process_and_time():
    evs = simulate({"f": "read", "value": None}, TEST)
    inv = invokes(evs)[0]
    assert inv["process"] == 0
    assert inv["time"] >= 0


def test_fn_is_infinite_with_limit():
    counter = {"n": 0}

    def w(test, ctx):
        counter["n"] += 1
        return {"f": "write", "value": counter["n"]}

    evs = simulate(g.limit(3, w), TEST)
    assert ops_of(evs) == [("write", 1), ("write", 2), ("write", 3)]


def test_seq_runs_in_order():
    evs = simulate([{"f": "a", "value": None}, {"f": "b", "value": None}], TEST)
    assert [f for f, _ in ops_of(evs)] == ["a", "b"]


def test_nested_seqs():
    evs = simulate([[{"f": "a", "value": 1}], [{"f": "b", "value": 2},
                                               {"f": "c", "value": 3}]], TEST)
    assert [f for f, _ in ops_of(evs)] == ["a", "b", "c"]


# -- cardinality ------------------------------------------------------------

def test_repeat_n():
    evs = simulate(g.repeat({"f": "r", "value": None}, 4), TEST)
    assert len(invokes(evs)) == 4


def test_once():
    evs = simulate(g.once(lambda t, c: {"f": "r", "value": None}), TEST)
    assert len(invokes(evs)) == 1


# -- scheduling -------------------------------------------------------------

def test_delay_spaces_ops():
    evs = simulate(g.delay(1.0, g.repeat({"f": "r", "value": None}, 3)), TEST)
    times = [e["time"] for e in invokes(evs)]
    assert times[1] - times[0] >= 1_000_000_000
    assert times[2] - times[1] >= 1_000_000_000


def test_stagger_spaces_ops_on_average():
    rng = random.Random(0)
    evs = simulate(
        g.stagger(0.1, g.repeat({"f": "r", "value": None}, 50), rng=rng), TEST)
    times = [e["time"] for e in invokes(evs)]
    span = times[-1] - times[0]
    # 50 ops averaging 0.1s apart -> ~4.9s; allow wide tolerance
    assert 2e9 < span < 10e9


def test_sleep_then_op():
    evs = simulate([g.sleep(5.0), {"f": "r", "value": None}], TEST)
    inv = invokes(evs)[0]
    assert inv["time"] >= 5_000_000_000


def test_time_limit():
    evs = simulate(
        g.time_limit(1.0, g.delay(0.3, g.cycle({"f": "r", "value": None}))),
        TEST)
    n = len(invokes(evs))
    assert 2 <= n <= 4  # ops at t=0, .3, .6, .9


# -- composition ------------------------------------------------------------

def test_then():
    evs = simulate(g.then({"f": "a", "value": None}, {"f": "b", "value": None}),
                   TEST)
    assert [f for f, _ in ops_of(evs)] == ["a", "b"]


def test_mix_draws_from_all():
    rng = random.Random(42)
    evs = simulate(
        g.limit(60, g.mix([lambda t, c: {"f": "a", "value": None},
                           lambda t, c: {"f": "b", "value": None}], rng=rng)),
        TEST)
    fs = [f for f, _ in ops_of(evs)]
    assert 10 < fs.count("a") < 50
    assert 10 < fs.count("b") < 50


def test_mix_finishes_exhausted_members():
    rng = random.Random(7)
    evs = simulate(g.mix([{"f": "a", "value": None},
                          {"f": "b", "value": None}], rng=rng), TEST)
    assert sorted(f for f, _ in ops_of(evs)) == ["a", "b"]


def test_any_picks_soonest():
    evs = simulate(g.any_gen([g.sleep(5.0), {"f": "slow", "value": None}],
                             {"f": "fast", "value": None}), TEST)
    fs = [f for f, _ in ops_of(evs)]
    assert fs[0] == "fast"


def test_flip_flop():
    evs = simulate(
        g.limit(4, g.flip_flop(g.cycle({"f": "a", "value": None}),
                               g.cycle({"f": "b", "value": None}))), TEST)
    assert [f for f, _ in ops_of(evs)] == ["a", "b", "a", "b"]


def test_filter():
    ctr = {"n": 0}

    def go(test, ctx):
        ctr["n"] += 1
        return {"f": "w", "value": ctr["n"]}

    evs = simulate(g.limit(3, g.filter_gen(lambda op: op["value"] % 2 == 0, go)),
                   TEST)
    assert [v for _, v in ops_of(evs)] == [2, 4, 6]


def test_f_map():
    evs = simulate(g.f_map(lambda op: dict(op, value=99),
                           {"f": "w", "value": 1}), TEST)
    assert ops_of(evs) == [("w", 99)]


def test_until_ok():
    evs = simulate(g.until_ok(g.cycle({"f": "r", "value": None})), TEST)
    # first op's ok completion ends the stream; in-flight ops may add a few
    assert len(invokes(evs)) <= 4
    assert completions(evs)[0]["type"] == "ok"


# -- thread restriction -----------------------------------------------------

def test_clients_excludes_nemesis():
    evs = simulate(g.clients(g.limit(6, lambda t, c: {"f": "r", "value": None})),
                   TEST)
    assert all(isinstance(e["process"], int) for e in invokes(evs))


def test_nemesis_only():
    evs = simulate(g.nemesis(g.limit(2, lambda t, c: {"f": "start", "value": None})),
                   TEST)
    assert all(e["process"] == "nemesis" for e in invokes(evs))


def test_reserve_partitions_threads():
    test = {"concurrency": 4}
    evs = simulate(
        g.limit(40, g.reserve(2, g.cycle({"f": "a", "value": None}),
                              g.cycle({"f": "b", "value": None}))), test)
    for e in invokes(evs):
        if e["f"] == "a":
            assert e["process"] in (0, 1)
        elif e["f"] == "b":
            assert e["process"] in (2, 3)
    fs = {f for f, _ in ops_of(evs)}
    assert fs == {"a", "b"}


def test_phases_barrier():
    test = {"concurrency": 3}
    evs = simulate(
        g.phases(g.clients(g.each_thread({"f": "a", "value": None})),
                 g.clients(g.each_thread({"f": "b", "value": None}))), test)
    a_completions = [e for e in completions(evs) if e["f"] == "a"]
    b_invokes = [e for e in invokes(evs) if e["f"] == "b"]
    assert len(a_completions) == 3 and len(b_invokes) == 3
    latest_a = max(e["time"] for e in a_completions)
    earliest_b = min(e["time"] for e in b_invokes)
    assert latest_a <= earliest_b


def test_each_thread():
    test = {"concurrency": 3}
    evs = simulate(g.clients(g.each_thread({"f": "w", "value": 1})), test)
    procs = sorted(e["process"] for e in invokes(evs))
    assert procs == [0, 1, 2]


# -- updates & crashed processes -------------------------------------------

def test_info_crash_bumps_process():
    test = {"concurrency": 2}

    def complete(op):
        # process 0's first op crashes
        if op["process"] == 0:
            return dict(op, type="info")
        return dict(op, type="ok")

    evs = simulate(g.limit(4, lambda t, c: {"f": "r", "value": None}),
                   test, complete=complete)
    procs = {e["process"] for e in invokes(evs)}
    # thread 0 reincarnates as process 2 (0 + concurrency), then 4 ...
    assert 2 in procs or 4 in procs


def test_context_basics():
    ctx = Context.make(3)
    assert ctx.all_threads() == [0, 1, 2, "nemesis"]
    assert ctx.some_free_process() == 0
    ctx2 = ctx.busy_thread(0)
    assert ctx2.some_free_process() == 1
    ctx3 = ctx2.with_next_process(0, 3)
    assert ctx3.process_for_thread(0) == 3
    sub = ctx.restrict(lambda t: t == "nemesis")
    assert sub.all_threads() == ["nemesis"]
    assert sub.free_processes() == ["nemesis"]


# -- regression: pending successors must survive polls ----------------------

def test_any_sleep_deadline_does_not_drift():
    # any(sleep ; op-after-sleep, fast ops): the sleep side's end time must
    # be fixed at the first poll, even while the other side keeps emitting.
    evs = simulate(
        g.any_gen([g.sleep(1.0), {"f": "late", "value": None}],
                  g.limit(30, g.stagger(0.1, g.cycle({"f": "fast",
                                                      "value": None})))),
        TEST)
    late = [e for e in invokes(evs) if e["f"] == "late"]
    assert late, "sleep side never fired — its deadline drifted"
    assert late[0]["time"] <= 2_000_000_000


def test_each_thread_sleep_deadline_does_not_drift():
    test = {"concurrency": 3}
    evs = simulate(
        g.any_gen(g.clients(g.each_thread([g.sleep(1.0),
                                           {"f": "late", "value": None}])),
                  g.limit(30, g.stagger(0.1, g.cycle({"f": "fast",
                                                      "value": None})))),
        test)
    late = [e for e in invokes(evs) if e["f"] == "late"]
    assert len(late) == 3, "per-thread sleeps never fired"
    assert all(e["time"] <= 2_000_000_000 for e in late)
