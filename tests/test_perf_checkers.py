"""Perf/timeline/clock checker tests: synthetic histories -> artifacts
written, point extraction correct (reference checker_test.clj style)."""

import os

import numpy as np

from jepsen_tpu.checkers import clock, perf, timeline
from jepsen_tpu.history.ops import History, Op, history, info, invoke, ok

S = 1_000_000_000  # ns


def _mk_history():
    ops = []
    # two processes, reads at 1s intervals, one nemesis window 2s..4s
    ops.append(Op(type="invoke", process="nemesis", f="start-partition",
                  time=2 * S))
    ops.append(Op(type="info", process="nemesis", f="start-partition",
                  time=2 * S + S // 10))
    for i in range(8):
        t0 = i * S
        p = i % 2
        ops.append(Op(type="invoke", process=p, f="read", value=None,
                      time=t0))
        typ = "ok" if i % 3 != 2 else "fail"
        ops.append(Op(type=typ, process=p, f="read", value=i,
                      time=t0 + 50_000_000))  # 50ms latency
    ops.append(Op(type="invoke", process="nemesis", f="stop-partition",
                  time=4 * S))
    ops.append(Op(type="info", process="nemesis", f="stop-partition",
                  time=4 * S + S // 10))
    ops.sort(key=lambda o: o.time)
    return history(ops)


def test_latency_points():
    pts = perf.latency_points(_mk_history())
    assert len(pts["time"]) == 8
    assert np.allclose(pts["latency_ms"], 50.0)
    assert (pts["type"] == "ok").sum() == 5 + 1  # i=0,1,3,4,6,7 -> 6 oks
    assert (pts["type"] == "fail").sum() == 2


def test_rate_points():
    series = perf.rate_points(_mk_history(), dt=1.0)
    t, rate = series[("read", "ok")]
    assert rate.max() <= 1.0 + 1e-9  # one op per second max
    assert ("read", "fail") in series


def test_nemesis_intervals():
    iv = perf.nemesis_intervals(_mk_history())
    assert len(iv) == 1
    t0, t1, f = iv[0]
    assert abs(t0 - 2.1) < 0.2 and abs(t1 - 4.1) < 0.2


def test_latency_and_rate_graphs_write_files(tmp_path):
    test = {"name": "perfy", "store-dir": str(tmp_path / "s")}
    h = _mk_history()
    r1 = perf.LatencyGraph().check(test, h)
    r2 = perf.RateGraph().check(test, h)
    assert r1["valid?"] is True and os.path.exists(r1["file"])
    assert r2["valid?"] is True and os.path.exists(r2["file"])
    assert os.path.getsize(r1["file"]) > 1000


def test_perf_compose(tmp_path):
    test = {"name": "perfy2", "store-dir": str(tmp_path / "s")}
    res = perf.perf().check(test, _mk_history())
    assert res["valid?"] is True


def test_empty_history_graphs():
    assert perf.LatencyGraph().check({"name": "e"}, history([]))["valid?"] \
        is True
    assert perf.RateGraph().check({"name": "e"}, history([]))["valid?"] \
        is True


def test_timeline_html(tmp_path):
    test = {"name": "tl", "store-dir": str(tmp_path / "s")}
    res = timeline.Timeline().check(test, _mk_history())
    assert res["valid?"] is True
    content = open(res["file"]).read()
    assert "timeline" in content and "read" in content
    assert res["op-count"] == 10  # 8 client + 2 nemesis invokes


def test_timeline_unpaired_invoke(tmp_path):
    test = {"name": "tl2", "store-dir": str(tmp_path / "s")}
    h = history([invoke(0, "read", None)])  # never completes
    res = timeline.Timeline().check(test, h)
    assert res["valid?"] is True and res["op-count"] == 1


def test_clock_plot(tmp_path):
    test = {"name": "ck", "store-dir": str(tmp_path / "s")}
    ops = []
    for i in range(4):
        ops.append(Op(type="invoke", process="nemesis",
                      f="check-clock-offsets", time=i * S))
        ops.append(Op(type="info", process="nemesis",
                      f="check-clock-offsets",
                      value={"n1": float(i * 10), "n2": -5.0},
                      time=i * S + 1000))
    res = clock.ClockPlot().check(test, history(ops))
    assert res["valid?"] is True and res["nodes"] == 2
    assert os.path.exists(res["file"])


def test_clock_series_extraction():
    ops = [Op(type="info", process="nemesis", f="check-clock-offsets",
              value={"n1": 5.0, "n2": None}, time=S)]
    series = clock.offset_series(history(ops))
    assert series == {"n1": [(1.0, 5.0)]}


def test_nemesis_intervals_kill_package_metadata():
    # the kill package's recovery op is f="start" — metadata must close
    # the window that the name heuristic would keep open
    ops = []
    for (t, f) in [(1, "kill"), (2, "start"), (3, "kill"), (4, "start")]:
        ops.append(Op(type="invoke", process="nemesis", f=f, time=t * S))
        ops.append(Op(type="info", process="nemesis", f=f,
                      time=t * S + 1000))
    test = {"plot": {"nemeses": [{"name": "kill", "start": {"kill"},
                                  "stop": {"start"}}]}}
    iv = perf.nemesis_intervals(history(ops), test)
    assert len(iv) == 2
    assert abs(iv[0][0] - 1.0) < 0.1 and abs(iv[0][1] - 2.0) < 0.1
    assert abs(iv[1][0] - 3.0) < 0.1 and abs(iv[1][1] - 4.0) < 0.1


def test_nemesis_intervals_conventional_start_stop():
    # the plain start/stop nemesis with no metadata still shades
    ops = []
    for (t, f) in [(1, "start"), (2, "stop"), (3, "start"), (4, "stop")]:
        ops.append(Op(type="invoke", process="nemesis", f=f, time=t * S))
        ops.append(Op(type="info", process="nemesis", f=f,
                      time=t * S + 1000))
    iv = perf.nemesis_intervals(history(ops))
    assert len(iv) == 2


def test_nemesis_intervals_kill_start_heuristic_no_metadata():
    # metadata-less kill nemesis: bare "start" closes an open kill window
    ops = []
    for (t, f) in [(1, "kill"), (2, "start"), (3, "kill"), (4, "start")]:
        ops.append(Op(type="invoke", process="nemesis", f=f, time=t * S))
        ops.append(Op(type="info", process="nemesis", f=f,
                      time=t * S + 1000))
    iv = perf.nemesis_intervals(history(ops))
    assert len(iv) == 2
    assert abs(iv[0][1] - 2.0) < 0.1 and abs(iv[1][1] - 4.0) < 0.1
    # windows are keyed to the OPENING f (the fault), not the closer
    assert iv[0][2] == "kill" and iv[1][2] == "kill"


def _nem_ops(spec):
    ops = []
    for (t, f) in spec:
        ops.append(Op(type="invoke", process="nemesis", f=f, time=t * S))
        ops.append(Op(type="info", process="nemesis", f=f,
                      time=t * S + 1000))
    return ops


def test_nemesis_intervals_bare_start_opens_when_no_window_open():
    # heuristic mode: with NO window open, a bare "start" is the
    # conventional start/stop nemesis's opener, not a kill recovery
    iv = perf.nemesis_intervals(history(_nem_ops([(1, "start"),
                                                  (3, "stop")])))
    assert len(iv) == 1
    assert abs(iv[0][0] - 1.0) < 0.1 and abs(iv[0][1] - 3.0) < 0.1
    assert iv[0][2] == "start"


def test_nemesis_intervals_still_open_window_closes_at_history_end():
    # a kill with no recovery: the window must extend to the last op's
    # time instead of being dropped
    ops = _nem_ops([(1, "kill")])
    ops.append(Op(type="invoke", process=0, f="read", value=None,
                  time=6 * S))
    ops.append(Op(type="ok", process=0, f="read", value=1,
                  time=6 * S + 1000))
    iv = perf.nemesis_intervals(history(ops))
    assert len(iv) == 1
    t0, t1, f = iv[0]
    assert abs(t0 - 1.0) < 0.1 and abs(t1 - 6.0) < 0.1 and f == "kill"


def test_nemesis_intervals_open_window_sole_op_history():
    # degenerate: the opening completion is the LAST op — the window
    # closes at that same time, not negative or dropped
    iv = perf.nemesis_intervals(history(_nem_ops([(1, "kill")])))
    assert len(iv) == 1
    t0, t1, _ = iv[0]
    assert t1 >= t0 and abs(t0 - 1.0) < 0.1


def test_nemesis_intervals_kill_start_kill_reopen_then_end():
    # recovery closes window 1; the second kill's window runs to the end
    iv = perf.nemesis_intervals(history(_nem_ops(
        [(1, "kill"), (2, "start"), (4, "kill")])))
    assert len(iv) == 2
    assert abs(iv[0][0] - 1.0) < 0.1 and abs(iv[0][1] - 2.0) < 0.1
    assert abs(iv[1][0] - 4.0) < 0.1 and abs(iv[1][1] - 4.0) < 0.11


def test_graphs_degrade_without_matplotlib(tmp_path, monkeypatch):
    """Satellite: a missing matplotlib returns computed counts instead
    of raising into check_safe."""
    import sys
    # None in sys.modules makes `import matplotlib` raise ImportError
    monkeypatch.setitem(sys.modules, "matplotlib", None)
    monkeypatch.setitem(sys.modules, "matplotlib.pyplot", None)
    test = {"name": "nomp", "store-dir": str(tmp_path / "s")}
    h = _mk_history()
    r1 = perf.LatencyGraph().check(test, h)
    assert r1["valid?"] is True
    assert r1["points"] == 8
    assert r1["plot"] == "skipped (no matplotlib)"
    r2 = perf.RateGraph().check(test, h)
    assert r2["valid?"] is True
    assert r2["plot"] == "skipped (no matplotlib)"
    assert r2["points"] > 0 and r2["series"] > 0
    # through check_safe + compose: still a clean valid result
    res = perf.perf().check(test, h)
    assert res["valid?"] is True
