"""Flight-recorder tests (ISSUE 5): streaming events.jsonl, torn-line
tolerance, partial traces after mid-check crashes, the resource
sampler, device-time attribution, the profiler bridge, and the
heartbeat state file."""

import glob
import json
import os
import signal
import subprocess
import sys
import textwrap
import time

import pytest

from jepsen_tpu import core, store, telemetry
from jepsen_tpu.checkers import api as checker_api
from jepsen_tpu.generator import core as g
from jepsen_tpu.telemetry import stream as tel_stream
from jepsen_tpu.workloads.mem import MemClient


# ------------------------------------------------------------ the stream

def test_event_stream_roundtrip(tmp_path):
    p = str(tmp_path / "events.jsonl")
    s = tel_stream.EventStream(p, meta={"name": "t"})
    s.emit("fault", site="elle.infer", kind="oom")
    s.close(valid=True)
    evs = tel_stream.read_events(p)
    assert [e["ev"] for e in evs] == ["start", "fault", "end"]
    assert evs[0]["name"] == "t"
    assert evs[1]["site"] == "elle.infer"
    assert evs[2]["valid"] is True
    assert all(isinstance(e["t"], float) for e in evs)
    # emits after close are silently dropped, never raised
    s.emit("late")
    assert len(tel_stream.read_events(p)) == 3


def test_read_events_drops_torn_tail(tmp_path):
    p = str(tmp_path / "events.jsonl")
    s = tel_stream.EventStream(p, meta={})
    s.emit("span", name="a", dur_ns=1)
    s.emit("span", name="b", dur_ns=2)
    # simulate a kill mid-append: a torn, unterminated trailing record
    with open(p, "ab") as f:
        f.write(b'{"t": 1.0, "ev": "span", "na')
    evs = tel_stream.read_events(p)
    assert [e.get("name") for e in evs] == [None, "a", "b"]
    # a parseable but unterminated line is also treated as torn
    with open(p, "ab") as f:
        f.write(b'\n{"t": 1.0, "ev": "x"}')  # heal + unterminated
    evs2 = tel_stream.read_events(p)
    assert len(evs2) == 3


def test_event_stream_truncates_previous_session(tmp_path):
    """One session per file: a re-shrink (--force) of the same run dir
    must not concatenate after the old session's `end` — replay() would
    render the killed re-run as ended, with mixed counters."""
    p = str(tmp_path / "events-shrink.jsonl")
    s1 = tel_stream.EventStream(p, meta={"name": "first"})
    s1.emit("span", name="old", dur_ns=1)
    s1.close(valid=False)
    s2 = tel_stream.EventStream(p, meta={"name": "second"})
    s2.emit("span-open", name="shrink-round", tid=1)
    # killed here: no close()
    evs = tel_stream.read_events(p)
    assert evs[0]["name"] == "second"
    assert [e["ev"] for e in evs] == ["start", "span-open"]
    st = tel_stream.replay(evs)
    assert not st["ended"]
    assert [sp["name"] for sp in st["open"]] == ["shrink-round"]


def test_read_events_incremental_cursor(tmp_path):
    """`tail -f`'s byte cursor: each poll parses only appended bytes,
    a torn tail is left unconsumed and picked up once healed."""
    p = str(tmp_path / "events.jsonl")
    s = tel_stream.EventStream(p, meta={})
    s.emit("span", name="a", dur_ns=1)
    evs, off = tel_stream.read_events_incremental(p, 0)
    assert [e["ev"] for e in evs] == ["start", "span"]
    assert off == os.path.getsize(p)
    # nothing new → empty batch, cursor unchanged
    evs2, off2 = tel_stream.read_events_incremental(p, off)
    assert evs2 == [] and off2 == off
    # torn append: not consumed, cursor stays before it
    with open(p, "ab") as f:
        f.write(b'{"t": 1.0, "ev": "span", "na')
    evs3, off3 = tel_stream.read_events_incremental(p, off)
    assert evs3 == [] and off3 == off
    # writer finishes the line → the healed record is consumed
    with open(p, "ab") as f:
        f.write(b'me": "b"}\n')
    evs4, off4 = tel_stream.read_events_incremental(p, off3)
    assert [e.get("name") for e in evs4] == ["b"]
    assert off4 == os.path.getsize(p)
    # cursor batches concatenate to the full-file read
    assert evs + evs4 == tel_stream.read_events(p)
    # a complete-but-corrupt line is skipped, not retried forever —
    # the follower must stay live past unrecoverable garbage
    with open(p, "ab") as f:
        f.write(b'not json at all\n{"t": 2.0, "ev": "span", "name": "c"}\n')
    evs5, off5 = tel_stream.read_events_incremental(p, off4)
    assert [e.get("name") for e in evs5] == ["c"]
    assert off5 == os.path.getsize(p)
    # a SHRUNKEN file means a new session truncated the stream: the
    # cursor resets to 0 instead of seeking past EOF forever (the
    # `tail -f` across `shrink --force` case)
    s2 = tel_stream.EventStream(p, meta={"name": "session-2"})
    s2.emit("span-open", name="fresh", tid=1)
    evs6, off6 = tel_stream.read_events_incremental(p, off5)
    assert [e["ev"] for e in evs6] == ["start", "span-open"]
    assert evs6[0]["name"] == "session-2"
    assert off6 == os.path.getsize(p)


def test_heartbeat_concurrent_writers_never_tear(tmp_path):
    """Concurrent scheduler workers force heartbeat writes; the
    published live.json must parse on every read (the tmp+replace
    pair runs under the lock — a shared tmp path written unlocked
    could publish a half-written inode)."""
    import threading

    p = str(tmp_path / "c.live.json")
    hb = tel_stream.Heartbeat(p, campaign="c", total=64)
    errs = []

    def reader():
        for _ in range(200):
            if os.path.exists(p) and tel_stream.Heartbeat.load(p) is None:
                errs.append("torn read")

    def writer(wid):
        for i in range(50):
            hb.worker(str(wid), {"run": f"r{i}", "padding": "x" * 512})
            hb.record_done(f"r{i}")

    threads = [threading.Thread(target=writer, args=(w,))
               for w in range(4)] + [threading.Thread(target=reader)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs
    hb.close()  # record_done writes are throttled; close forces one
    doc = tel_stream.Heartbeat.load(p)
    assert doc and doc["done"] == 200 and doc["finished"]


def test_event_stream_unwritable_dir_is_broken_not_fatal(tmp_path):
    s = tel_stream.EventStream(str(tmp_path / "no" / "such" / "e.jsonl"))
    assert s.broken
    s.emit("x")  # no-op, no raise
    s.close()


def test_replay_and_render_tail_name_open_span_and_counters():
    evs = [
        {"t": 1.0, "ev": "start", "name": "demo"},
        {"t": 1.1, "ev": "span-open", "name": "run", "tid": 1},
        {"t": 1.2, "ev": "span-open", "name": "workload", "tid": 1},
        {"t": 1.5, "ev": "span", "name": "workload", "tid": 1,
         "dur_ns": int(3e8)},
        {"t": 1.5, "ev": "metrics",
         "counters": {"interpreter-ops{type=ok,worker=0}": 4}},
        {"t": 1.6, "ev": "span-open", "name": "check:wedged", "tid": 1},
        {"t": 1.7, "ev": "metrics",
         "counters": {"interpreter-ops{type=ok,worker=0}": 6}},
        {"t": 1.8, "ev": "retry", "site": "elle.infer", "attempt": 1},
    ]
    st = tel_stream.replay(evs)
    assert not st["ended"]
    assert st["retries"] == 1  # regression: "retry" pluralizes irregularly
    assert [s["name"] for s in st["open"]] == ["run", "check:wedged"]
    assert st["counters"]["interpreter-ops{type=ok,worker=0}"] == 6
    out = tel_stream.render_tail(evs)
    assert "last open span: check:wedged" in out
    assert "open spans: run > check:wedged" in out
    assert "interpreter-ops{type=ok,worker=0} = 6" in out
    # the limit prefixes an elision marker
    out2 = tel_stream.render_tail(evs, limit=2)
    assert "earlier events" in out2


def test_collector_streams_spans_and_metric_deltas(tmp_path):
    c = telemetry.Collector()
    rec = tel_stream.attach(c, str(tmp_path), meta={"name": "x"},
                            sampler=False)
    with c.span("run"):
        c.registry.counter("ops").inc(3)
        with c.span("inner") as sp:
            sp.set_attr(n=1)
    rec.close()
    evs = tel_stream.read_events(str(tmp_path / "events.jsonl"))
    kinds = [(e["ev"], e.get("name")) for e in evs]
    assert ("span-open", "run") in kinds
    assert ("span-open", "inner") in kinds
    assert ("span", "inner") in kinds and ("span", "run") in kinds
    inner = next(e for e in evs if e["ev"] == "span"
                 and e["name"] == "inner")
    assert inner["attrs"] == {"n": 1} and inner["dur_ns"] >= 0
    # the counter flushed at a span boundary, before close
    m = [e for e in evs if e["ev"] == "metrics"]
    assert any(e.get("counters", {}).get("ops") == 3 for e in m)
    # same-value re-flush is suppressed (deltas, not dumps)
    assert sum("ops" in (e.get("counters") or {}) for e in m) == 1


def test_crashed_workload_still_ends_stream(tmp_path):
    def boom(t, c):
        raise RuntimeError("generator exploded")

    base = str(tmp_path / "s")
    t = dict(core.noop_test(), name="crashed", client=MemClient(),
             generator=g.clients(boom), telemetry=True,
             **{"store-dir": base})
    with pytest.raises(RuntimeError):
        core.run(t)
    # core.run works on a merged copy of the test map, so find the run
    # dir by scanning rather than via the caller's (timestampless) map
    (path,) = glob.glob(os.path.join(base, "crashed", "*",
                                     "events.jsonl"))
    evs = tel_stream.read_events(path)
    st = tel_stream.replay(evs)
    assert st["ended"]  # recorder.close ran in core.run's finally
    # the run span closed during exception unwind and streamed
    assert any(e["ev"] == "span" and e["name"] == "run" for e in evs)
    assert telemetry.active() is telemetry.NOOP


# ----------------------------------------------------- resource sampler

def test_noop_run_has_sampler_gauges_and_sample_events(tmp_path):
    done = core.run(core.noop_test(
        telemetry=True, **{"store-dir": str(tmp_path / "s")}))
    d = store.test_dir(done)
    evs = tel_stream.read_events(os.path.join(d, "events.jsonl"))
    samples = [e for e in evs if e["ev"] == "sample"]
    assert samples, "no resource sample in a noop run"
    assert samples[0].get("threads", 0) >= 1
    # detach always lands one last synchronous sample (ISSUE 16
    # satellite): peaks can't be lost to tick-interval truncation
    assert samples[-1].get("final") is True
    doc = json.load(open(os.path.join(d, "telemetry.json")))
    gauges = {gg["name"] for gg in doc["metrics"]["gauges"]}
    assert "process-threads" in gauges
    if samples[0].get("rss_bytes"):  # /proc present on this platform
        assert "process-rss-bytes" in gauges
        assert "process-rss-peak-bytes" in gauges
        # watermark monotonicity across the sample series
        peaks = [s["rss_peak_bytes"] for s in samples
                 if "rss_peak_bytes" in s]
        assert peaks == sorted(peaks) and \
            peaks[-1] >= samples[-1]["rss_bytes"]
        # ... and the enclosing run span carries the watermark as of
        # export time (the final sample at detach can only grow it)
        run_span = next(s for s in doc["spans"] if s["name"] == "run")
        assert 0 < run_span["attrs"]["rss_peak_bytes"] <= peaks[-1]


# --------------------------------- partial trace after mid-check SIGKILL

KILLER_SCRIPT = """
import os, signal, sys
from jepsen_tpu import core
from jepsen_tpu.checkers import api as checker_api
from jepsen_tpu.generator import core as g
from jepsen_tpu.workloads.mem import MemClient

class Killer(checker_api.Checker):
    def check(self, test, history, opts=None):
        os.kill(os.getpid(), signal.SIGKILL)

core.run({
    "name": "killed",
    "client": MemClient(),
    "concurrency": 2,
    "generator": g.clients(g.limit(
        8, lambda t, c: {"f": "write", "value": 1})),
    "checker": Killer(),
    "telemetry": True,
    "store-dir": sys.argv[1],
})
"""


def test_sigkill_mid_check_leaves_partial_trace(tmp_path):
    """ISSUE 5 acceptance: a run SIGKILLed mid-check leaves an
    events.jsonl whose rendered `cli tail` output names the last open
    span and the final counter values."""
    script = tmp_path / "killer.py"
    script.write_text(textwrap.dedent(KILLER_SCRIPT))
    base = str(tmp_path / "s")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=repo + os.pathsep
               + os.environ.get("PYTHONPATH", ""))
    r = subprocess.run([sys.executable, str(script), base], env=env,
                       capture_output=True, text=True, timeout=300)
    assert r.returncode == -signal.SIGKILL, (r.returncode, r.stderr)
    paths = glob.glob(os.path.join(base, "killed", "*", "events.jsonl"))
    assert paths, "killed run left no events.jsonl"
    evs = tel_stream.read_events(paths[0])
    st = tel_stream.replay(evs)
    assert not st["ended"]
    assert [s["name"] for s in st["open"]][-1] == "check:Killer"
    # the workload span boundary flushed the op counters before the
    # check began, so the partial trace carries the final tallies
    inv = sum(v for k, v in st["counters"].items()
              if k.startswith("interpreter-ops") and "invoke" in k)
    assert inv == 8
    # ... and the `cli tail` rendering makes both quotable
    out = tel_stream.render_tail(evs)
    assert "last open span: check:Killer" in out
    assert "interpreter-ops" in out

    # the same file renders through the real cli command
    from jepsen_tpu import cli

    rc = cli.run(cli.single_test_cmd(lambda o: {}),
                 ["tail", os.path.dirname(paths[0])])
    assert rc == 0


# ------------------------------------------------ resilience event feed

def test_fault_fallback_and_deadline_events_streamed(tmp_path):
    from jepsen_tpu.checkers.elle import list_append
    from jepsen_tpu.resilience import Deadline, DeadlineExceeded, FaultPlan
    from jepsen_tpu.workloads import synth

    c = telemetry.activate()
    rec = tel_stream.attach(c, str(tmp_path), sampler=False)
    try:
        h = synth.la_history(n_txns=20, seed=3)
        plan = FaultPlan(persistent=True, kinds=("device-lost",))
        res = list_append.check(h, plan=plan)
        assert res.get("degraded") == "host-fallback"
        with pytest.raises(DeadlineExceeded):
            Deadline(0).check("unit-test")
    finally:
        rec.close()
        telemetry.deactivate(c)
    evs = tel_stream.read_events(str(tmp_path / "events.jsonl"))
    kinds = [e["ev"] for e in evs]
    assert "fault" in kinds and "fallback" in kinds
    dl = next(e for e in evs if e["ev"] == "deadline")
    assert dl["site"] == "unit-test"
    fb = next(e for e in evs if e["ev"] == "fallback")
    assert fb["site"].startswith("elle.")


# -------------------------------------------- device-time attribution

def test_device_call_stamps_device_time_on_span():
    import jax.numpy as jnp

    from jepsen_tpu.resilience import guard

    c = telemetry.activate()
    try:
        with telemetry.span("check:unit") as sp:
            out = guard.device_call(
                "unit.seam", lambda: jnp.arange(8).sum(),
                plan=guard.NO_PLAN)
            out2 = guard.device_call(
                "unit.seam", lambda: jnp.arange(8).sum(),
                plan=guard.NO_PLAN)
        assert int(out) == int(out2) == 28
        assert sp.attrs.get("device_time_ns", 0) > 0
        snap = c.registry.snapshot()
        dt = [x for x in snap["counters"] if x["name"] == "device-time-ns"]
        assert dt and dt[0]["labels"]["site"] == "unit.seam"
        assert dt[0]["value"] == sp.attrs["device_time_ns"]
    finally:
        telemetry.deactivate(c)


def test_device_call_unchanged_when_telemetry_off():
    from jepsen_tpu.resilience import guard

    assert telemetry.active() is telemetry.NOOP
    assert guard.device_call("unit.seam", lambda: 41 + 1,
                             plan=guard.NO_PLAN) == 42


class _PoisonedResult:
    """An async-dispatched device value whose failure only surfaces at
    the block-until-ready sync point."""

    def block_until_ready(self):
        err = RuntimeError("RESOURCE_EXHAUSTED: async dispatch failed")
        err.transient = True
        raise err


def test_device_call_surfaces_async_failure_at_sync_point():
    """A device failure first observable when the stamper syncs must
    reach device_call's retry/fallback classifier — not be swallowed
    and the poisoned value returned as success (regression: the
    device-time stamper's bare except around block_until_ready)."""
    from jepsen_tpu.resilience import guard
    from jepsen_tpu.resilience.policy import RetryPolicy

    pol = RetryPolicy(max_attempts=2, base_delay_s=0.0, jitter=0.0)
    calls = {"n": 0}

    def flaky_seam():
        calls["n"] += 1
        return _PoisonedResult() if calls["n"] == 1 else 42

    c = telemetry.activate()
    try:
        with telemetry.span("check:unit"):
            out = guard.device_call("unit.seam", flaky_seam,
                                    policy=pol, plan=guard.NO_PLAN)
        assert out == 42 and calls["n"] == 2  # retried, not poisoned

        calls["n"] = 0
        with telemetry.span("check:unit"), \
                pytest.raises(RuntimeError, match="RESOURCE_EXHAUSTED"):
            guard.device_call("unit.seam", lambda: _PoisonedResult(),
                              policy=pol, plan=guard.NO_PLAN)
    finally:
        telemetry.deactivate(c)


# ------------------------------------------------------ profiler bridge

def test_profile_dir_bridges_spans_to_profiler_trace(tmp_path):
    """ISSUE 5 acceptance: with --profile-dir set, the exported
    profiler trace contains TraceAnnotation slices matching telemetry
    span names (skipped when the profiler produces no trace)."""
    prof = str(tmp_path / "prof")
    t = dict(core.noop_test(), name="prof-run", client=MemClient(),
             concurrency=1,
             generator=g.clients(g.limit(
                 4, lambda t, c: {"f": "write", "value": 1})),
             checker=checker_api.Stats(),
             **{"store-dir": str(tmp_path / "s"), "profile-dir": prof})
    done = core.run(t)
    # profile-dir implies telemetry: the run streamed + exported
    d = store.test_dir(done)
    assert os.path.exists(os.path.join(d, "telemetry.json"))
    files = glob.glob(os.path.join(prof, "**", "*.xplane.pb"),
                      recursive=True)
    if not files:
        pytest.skip("jax profiler unavailable on this box")
    data = b"".join(open(f, "rb").read() for f in files)
    # span names land as TraceAnnotation slice names; these strings
    # exist nowhere else (no function/symbol is named store.save_0)
    assert b"store.save_0" in data
    assert b"check:Stats" in data
    assert telemetry.active() is telemetry.NOOP


# ----------------------------------------------------------- top spans

def test_top_spans_self_time_table():
    from jepsen_tpu.telemetry import export

    doc = {"spans": [{
        "name": "run", "dur_ns": int(10e9),
        "children": [
            {"name": "check", "dur_ns": int(9e9), "children": []},
            {"name": "save", "dur_ns": int(0.5e9), "children": []},
        ]}]}
    rows = export.top_spans(doc, 10)
    by = {r["name"]: r for r in rows}
    assert rows[0]["name"] == "check"  # biggest SELF time wins
    assert by["run"]["total_self_s"] == pytest.approx(0.5)
    assert by["check"]["count"] == 1
    out = export.render_top_spans(rows)
    assert "check" in out and "p95" in out
    # n caps the table
    assert len(export.top_spans(doc, 1)) == 1


def test_cli_trace_top_flag(tmp_path, capsys):
    from jepsen_tpu import cli

    t = dict(core.noop_test(), name="top-run", client=MemClient(),
             concurrency=1,
             generator=g.clients(g.limit(
                 4, lambda t, c: {"f": "write", "value": 1})),
             checker=checker_api.Stats(), telemetry=True,
             **{"store-dir": str(tmp_path / "s")})
    d = store.test_dir(core.run(t))
    rc = cli.run(cli.single_test_cmd(lambda o: {}),
                 ["trace", d, "--top", "3"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "top 3 spans by self time" in out
    assert "workload" in out


# ----------------------------------------------------------- heartbeat

def test_heartbeat_state_file(tmp_path):
    p = str(tmp_path / "c.live.json")
    hb = telemetry.Heartbeat(p, campaign="c", total=4, done=1,
                             min_interval_s=0.0)
    hb.worker("w0", {"run": "r1", "seed": 0})
    doc = telemetry.Heartbeat.load(p)
    assert doc["total"] == 4 and doc["done"] == 1
    assert doc["workers"]["w0"]["run"] == "r1"
    assert isinstance(doc["workers"]["w0"]["since"], float)
    hb.record_done("r1", False)
    hb.worker("w0", None)
    hb.close()
    doc = telemetry.Heartbeat.load(p)
    assert doc["done"] == 2 and doc["finished"] is True
    assert doc["workers"] == {}
    assert doc["last"] == {"run": "r1", "valid?": False}
    assert telemetry.Heartbeat.load(str(tmp_path / "nope.json")) is None


def test_run_campaign_publishes_heartbeat(tmp_path):
    from jepsen_tpu import campaign
    from jepsen_tpu.campaign.core import live_path

    base = str(tmp_path / "s")
    spec = {"name": "hb", "workloads": ["noop"], "seeds": [0, 1],
            "opts": {"time-limit": 0.2}}
    campaign.run_campaign(spec, base, workers=2)
    doc = telemetry.Heartbeat.load(live_path("hb", base))
    assert doc is not None
    assert doc["finished"] is True
    assert doc["done"] == doc["total"] == 2
    assert doc["workers"] == {}


# -------------------------------------------------------- witness diff

def test_index_witness_diffs(tmp_path):
    from jepsen_tpu.campaign.index import Index

    idx = Index(str(tmp_path / "c.jsonl"))
    idx.append({"run": "r1", "key": "append|f|0", "valid?": False,
                "gen": "g1", "witness": {"ops": 6, "digest": "aaa",
                                         "anomaly-types": ["G1c"]}})
    idx.append({"run": "r1", "key": "append|f|0", "valid?": False,
                "gen": "g2", "witness": {"ops": 4, "digest": "bbb",
                                         "anomaly-types": ["G1b",
                                                           "G1c"]}})
    idx.append({"run": "r2", "key": "wr|f|1", "valid?": False,
                "gen": "g2", "witness": {"ops": 5, "digest": "ccc",
                                         "anomaly-types": ["G0"]}})
    # records without a witness never pair up
    idx.append({"run": "r3", "key": "wr|f|2", "valid?": True})
    (d,) = idx.witness_diffs()  # r2/r3 have no consecutive pair
    assert d["key"] == "append|f|0"
    assert d["ops-delta"] == -2
    assert d["digest-changed"] is True
    assert d["anomalies-added"] == ["G1b"]
    assert d["anomalies-removed"] == []
    assert d["changed"] is True


# ------------------------------------------------------ shrink streaming

def test_shrink_streams_round_events(tmp_path):
    from jepsen_tpu import minimize
    from jepsen_tpu.checkers.elle import oracle
    from jepsen_tpu.workloads import synth

    base = str(tmp_path / "s")
    h = synth.la_history(n_txns=40, n_keys=4, concurrency=3, seed=11)
    assert synth.inject_wr_cycle(h)
    t = core.noop_test(name="shrink-stream", telemetry=True)
    t["store-dir"] = base
    t["history"] = h
    store.save_0(t)
    t["results"] = oracle.check(h, ["serializable"])
    store.save_1(t)
    d = store.test_dir(t)
    s = minimize.shrink(d, host_oracle=True)
    assert s["valid?"] is False
    evs = tel_stream.read_events(os.path.join(d, "events-shrink.jsonl"))
    assert evs and evs[-1]["ev"] == "end"
    rounds = [e for e in evs if e["ev"] == "shrink-round"]
    assert rounds and all("ops_remaining" in e for e in rounds)
    assert any(e["ev"] == "span" and e["name"] == "shrink.baseline"
               for e in evs)
    # the run's own events file (none here) was never touched
    assert not os.path.exists(os.path.join(d, "events.jsonl"))


def test_events_path_follows_the_freshest_stream(tmp_path):
    """When a shrink session streams next to an already-ENDED run
    stream, tail/live must follow the live shrink — not replay the
    finished run and exit (regression: events_path always preferred
    events.jsonl)."""
    d = str(tmp_path)
    run_p = os.path.join(d, "events.jsonl")
    shrink_p = os.path.join(d, "events-shrink.jsonl")
    assert tel_stream.events_path(d) is None
    tel_stream.EventStream(run_p, meta={}).close(valid=True)
    assert tel_stream.events_path(d) == run_p
    s = tel_stream.EventStream(shrink_p, meta={})
    s.emit("shrink-round", round=1)
    os.utime(run_p, (1, 1))  # the run ended first: older mtime
    assert tel_stream.events_path(d) == shrink_p
    s.close(valid=False)
    # a LATER re-run of the test flips the preference back
    os.utime(run_p, None)
    os.utime(shrink_p, (1, 1))
    assert tel_stream.events_path(d) == run_p


# ----------------------------------------------- size-based rotation

def test_rotation_keeps_n_segments_with_in_stream_markers(tmp_path):
    """Satellite (ISSUE 6): a bounded stream rotates events.jsonl ->
    events.jsonl.1 ... keep-N with the rotation recorded IN-STREAM
    (`rotate` closes the old segment, `rotate-cont` opens the new one),
    and read_events transparently spans the surviving segments."""
    p = str(tmp_path / "events.jsonl")
    s = tel_stream.EventStream(p, meta={"name": "soak"},
                               max_bytes=400, keep=2)
    for i in range(60):
        s.emit("tick", i=i)
    s.close(valid=True)
    names = sorted(os.path.basename(x)
                   for x in tel_stream.segment_files(p))
    assert names == ["events.jsonl", "events.jsonl.1", "events.jsonl.2"]
    assert all(os.path.getsize(x) <= 400 + 120
               for x in tel_stream.segment_files(p))
    evs = tel_stream.read_events(p)
    kinds = [e["ev"] for e in evs]
    assert "rotate" in kinds and "rotate-cont" in kinds
    st = tel_stream.replay(evs)
    assert st["rotations"] >= 1 and st["ended"]
    # keep=2 dropped the oldest segments; the surviving tail is
    # contiguous and ends at the last tick
    ticks = [e["i"] for e in evs if e["ev"] == "tick"]
    assert ticks == list(range(ticks[0], 60))


def test_rotation_markers_pair_segment_boundaries(tmp_path):
    p = str(tmp_path / "events.jsonl")
    s = tel_stream.EventStream(p, max_bytes=300, keep=9)
    for i in range(30):
        s.emit("tick", i=i)
    s.close()
    # every rotated segment's LAST event is the rotate marker, and
    # every continuation file's FIRST is rotate-cont (same segment no.)
    segs = tel_stream.segment_files(p)
    assert len(segs) >= 3
    for seg, nxt in zip(segs[:-1], segs[1:]):
        last = tel_stream.read_events(seg, spanning=False)[-1]
        first = tel_stream.read_events(nxt, spanning=False)[0]
        assert last["ev"] == "rotate"
        assert first["ev"] == "rotate-cont"
        assert first["segment"] == last["segment"]
    # nothing lost across the whole chain (keep was large enough)
    ticks = [e["i"] for e in tel_stream.read_events(p)
             if e["ev"] == "tick"]
    assert ticks == list(range(30))


def test_incremental_follower_survives_rotation(tmp_path):
    """`tail -f`'s byte cursor spans a rotation: when the live file
    shrinks, the follower first drains the just-rotated segment's tail
    past its old cursor, then restarts at byte 0 of the new file."""
    p = str(tmp_path / "events.jsonl")
    s = tel_stream.EventStream(p, max_bytes=300, keep=5)
    off, got = 0, []
    for i in range(40):
        s.emit("tick", i=i)
        evs, off = tel_stream.read_events_incremental(p, off)
        got.extend(evs)
    ticks = [e["i"] for e in got if e["ev"] == "tick"]
    assert ticks == list(range(40))


def test_follow_events_spans_multiple_rotations_between_polls(tmp_path):
    """A plain byte cursor points at the wrong segment when the stream
    rotates twice between polls; follow_events' identity-carrying
    cursor spans any number of rotations losslessly."""
    p = str(tmp_path / "events.jsonl")
    s = tel_stream.EventStream(p, max_bytes=200, keep=10)
    cur, got = None, []
    for burst in range(6):
        # ~10 lines per burst at ~40 B each vs a 200 B bound: >= 2
        # rotations happen between consecutive polls
        for i in range(burst * 10, burst * 10 + 10):
            s.emit("tick", i=i)
        evs, cur = tel_stream.follow_events(p, cur)
        got.extend(evs)
    ticks = [e["i"] for e in got if e["ev"] == "tick"]
    assert ticks == list(range(60))
    assert cur["head"].strip()  # cursor carries the live identity


def test_first_line_identity_survives_oversized_first_line(tmp_path):
    """A first line longer than the cap yields a stable capped-prefix
    identity once the file has grown past it — never a permanent ""
    that would blind follow_events/tail -f for the whole run."""
    p = str(tmp_path / "events.jsonl")
    big = '{"ev": "meta", "pad": "' + "x" * (2 << 20) + '"}\n'
    with open(p, "w") as f:
        f.write(big)
        f.write('{"ev": "tick", "i": 0}\n')
    h1 = tel_stream._first_line(p)
    assert h1 and h1 == tel_stream._first_line(p)
    # a normal small first line still returns the whole line
    q = str(tmp_path / "small.jsonl")
    with open(q, "w") as f:
        f.write('{"ev": "meta"}\n')
    assert tel_stream._first_line(q) == '{"ev": "meta"}\n'
    # torn (no newline yet, under the cap): no identity yet
    r = str(tmp_path / "torn.jsonl")
    with open(r, "w") as f:
        f.write('{"ev": "met')
    assert tel_stream._first_line(r) == ""


def test_follow_events_keepn_overrun_no_duplicates(tmp_path):
    """When the follower's former segment aged out of keep-N, every
    surviving segment is delivered whole: events are lost (they're
    gone from disk), but what's delivered is ordered and duplicate
    free, ending at the stream's last event."""
    p = str(tmp_path / "events.jsonl")
    s = tel_stream.EventStream(p, max_bytes=200, keep=1)
    s.emit("tick", i=0)
    evs, cur = tel_stream.follow_events(p, None)
    for i in range(1, 80):  # many rotations; keep=1 drops history
        s.emit("tick", i=i)
    evs2, cur = tel_stream.follow_events(p, cur)
    ticks = [e["i"] for e in evs + evs2 if e["ev"] == "tick"]
    assert ticks == sorted(set(ticks)), "duplicated or reordered"
    assert ticks[-1] == 79
    # a third poll with nothing new delivers nothing
    evs3, cur = tel_stream.follow_events(p, cur)
    assert evs3 == []


def test_follow_events_segment_walk_race_loses_nothing(tmp_path,
                                                       monkeypatch):
    """A rotation firing in the middle of the segment catch-up walk
    renames other content onto the paths being walked; the post-read
    fingerprint check must stop the walk at the last good anchor so
    the next poll re-delivers — nothing lost, nothing duplicated."""
    p = str(tmp_path / "events.jsonl")
    s = tel_stream.EventStream(p, max_bytes=200, keep=50)
    for i in range(20):  # several segments on disk
        s.emit("tick", i=i)
    real = tel_stream.read_events
    fired = []

    def racing(path, spanning=True):
        evs = real(path, spanning=spanning)
        if not fired and path != p:  # first rotated segment read
            fired.append(path)
            for i in range(100, 130):  # rotations rename mid-walk
                s.emit("tick", i=i)
        return evs

    monkeypatch.setattr(tel_stream, "read_events", racing)
    got, cur = [], None
    evs, cur = tel_stream.follow_events(p, cur)  # the raced poll
    got.extend(evs)
    monkeypatch.setattr(tel_stream, "read_events", real)
    for _ in range(4):  # drain: each poll may stop at a boundary
        evs, cur = tel_stream.follow_events(p, cur)
        got.extend(evs)
    assert fired, "race injection never fired"
    ticks = [e["i"] for e in got if e["ev"] == "tick"]
    assert ticks == list(range(20)) + list(range(100, 130)), ticks


def test_new_session_truncation_not_mistaken_for_rotation(tmp_path):
    p = str(tmp_path / "events.jsonl")
    s1 = tel_stream.EventStream(p, meta={})
    for i in range(20):
        s1.emit("tick", i=i)
    evs, off = tel_stream.read_events_incremental(p, 0)
    assert len([e for e in evs if e["ev"] == "tick"]) == 20
    # a NEW session truncates (no .1 segment exists): cursor resets,
    # no phantom catch-up events are delivered
    s2 = tel_stream.EventStream(p, meta={})
    s2.emit("tick", i=99)
    evs, off = tel_stream.read_events_incremental(p, off)
    ticks = [e["i"] for e in evs if e["ev"] == "tick"]
    assert ticks == [99]


def test_attach_env_defaults_enable_rotation(tmp_path, monkeypatch):
    monkeypatch.setenv("JEPSEN_EVENTS_MAX_BYTES", "300")
    monkeypatch.setenv("JEPSEN_EVENTS_KEEP", "2")

    class _Col:
        registry = None
    rec = tel_stream.attach(_Col(), str(tmp_path), sampler=False)
    assert rec.stream.max_bytes == 300 and rec.stream.keep == 2
    rec.close()


def test_env_anomaly_counter_and_stream_event(tmp_path):
    """Satellite (ISSUE 6): environment anomalies (bench r05's 544s
    backend-init hang) are a structured resilience signal — a labeled
    counter plus a streamed `env-anomaly` event that replay() tallies
    — not a free-text field."""
    from jepsen_tpu.resilience import env_anomaly

    c = telemetry.activate()
    rec = tel_stream.attach(c, str(tmp_path), sampler=False)
    try:
        env_anomaly("backend-init", kind="retried",
                    probes=17, wait_s=544.0)
    finally:
        rec.close()
        telemetry.deactivate(c)
    snap = c.registry.snapshot()
    ctr = [x for x in snap["counters"]
           if x["name"] == "resilience-env-anomalies"]
    assert ctr and ctr[0]["value"] == 1
    assert ctr[0]["labels"] == {"site": "backend-init",
                                "kind": "retried"}
    evs = tel_stream.read_events(str(tmp_path / "events.jsonl"))
    anoms = [e for e in evs if e["ev"] == "env-anomaly"]
    assert anoms and anoms[0]["wait_s"] == 544.0
    st = tel_stream.replay(evs)
    assert st["env_anomalies"] == 1
    assert "1 env anomalies" in tel_stream.render_tail(evs)
