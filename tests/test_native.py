"""Native C++ oracle tests: differential against the pure-Python anchors
(SURVEY.md §4's parallel-vs-serial fold pattern, here C++-vs-Python)."""

import os
import random

import numpy as np
import pytest

from jepsen_tpu import native
from jepsen_tpu.checkers.elle import graph
from jepsen_tpu.checkers.knossos import wgl
from jepsen_tpu.checkers.knossos.memo import memoize
from jepsen_tpu.checkers.knossos.prep import prepare
from jepsen_tpu.history.ops import history, invoke, ok, info
from jepsen_tpu.models import cas_register, register

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="no C++ toolchain")


def _relabel(comp):
    """Canonical relabeling: component id by first occurrence."""
    out = np.empty_like(comp)
    seen = {}
    for i, c in enumerate(comp):
        out[i] = seen.setdefault(int(c), len(seen))
    return out


def _py_scc(n, src, dst):
    os.environ["JT_NO_NATIVE"] = "1"
    try:
        return graph.tarjan_scc(n, np.asarray(src), np.asarray(dst))
    finally:
        del os.environ["JT_NO_NATIVE"]


def test_scc_simple_cycle():
    src = np.array([0, 1, 2, 3])
    dst = np.array([1, 2, 0, 3])
    comp = native.scc(4, src, dst)
    assert comp[0] == comp[1] == comp[2]
    assert comp[3] != comp[0]


def test_scc_differential_random():
    rng = random.Random(42)
    for trial in range(25):
        n = rng.randint(1, 60)
        m = rng.randint(0, 3 * n)
        src = np.array([rng.randrange(n) for _ in range(m)], dtype=np.int64)
        dst = np.array([rng.randrange(n) for _ in range(m)], dtype=np.int64)
        c_native = native.scc(n, src, dst)
        c_py = _py_scc(n, src, dst)
        assert np.array_equal(_relabel(c_native), _relabel(c_py)), \
            f"trial {trial}: SCC mismatch"


def test_scc_big_path_no_recursion_limit():
    # a 100k-node path + back edge = one giant SCC; must not blow stacks
    n = 100_000
    src = np.arange(n, dtype=np.int64)
    dst = np.roll(src, -1)
    comp = native.scc(n, src, dst)
    assert (comp == comp[0]).all()


def test_bfs_cycle():
    # 0->1->2->0 plus a dead-end 2->3
    src = np.array([0, 1, 2, 2])
    dst = np.array([1, 2, 0, 3])
    cyc = native.bfs_cycle(4, src, dst, 0)
    assert cyc is not None
    assert cyc[0] == cyc[-1] == 0
    assert len(cyc) == 4  # 0 1 2 0


def test_bfs_cycle_none():
    src = np.array([0, 1])
    dst = np.array([1, 2])
    assert native.bfs_cycle(3, src, dst, 0) is None


def test_bfs_cycle_mask_restricts():
    # two cycles through 0: short via 1, long via 2,3; mask out node 1
    src = np.array([0, 1, 0, 2, 3])
    dst = np.array([1, 0, 2, 3, 0])
    mask = np.array([1, 0, 1, 1], dtype=np.uint8)
    cyc = native.bfs_cycle(4, src, dst, 0, mask=mask)
    assert cyc is not None and 1 not in cyc[1:-1]
    assert len(cyc) == 4  # 0 2 3 0


# --------------------------------------------------------------- WGL

def _wgl_both(h, model):
    """Run native and pure-Python WGL on the same history."""
    res_native = wgl.check(h, model)
    os.environ["JT_NO_NATIVE"] = "1"
    try:
        res_py = wgl.check(h, model)
    finally:
        del os.environ["JT_NO_NATIVE"]
    return res_native, res_py


def test_wgl_valid_register():
    h = history([
        invoke(0, "write", 1), ok(0, "write", 1),
        invoke(1, "read", None), ok(1, "read", 1),
    ])
    rn, rp = _wgl_both(h, register())
    assert rn["valid?"] is True and rp["valid?"] is True


def test_wgl_invalid_register():
    h = history([
        invoke(0, "write", 1), ok(0, "write", 1),
        invoke(0, "read", None), ok(0, "read", 2),  # never written
    ])
    rn, rp = _wgl_both(h, register())
    assert rn["valid?"] is False and rp["valid?"] is False


def test_wgl_info_op_may_not_linearize():
    h = history([
        invoke(0, "write", 1), info(0, "write", 1),  # crashed write
        invoke(1, "read", None), ok(1, "read", None),  # reads initial
    ])
    rn, rp = _wgl_both(h, register())
    assert rn["valid?"] is True and rp["valid?"] is True


def test_wgl_differential_random_histories():
    rng = random.Random(7)
    agree = 0
    for trial in range(30):
        # random concurrent cas-register history (2-3 procs, 6-10 ops)
        ops = []
        vals = [None, 0, 1, 2]
        state = {p: None for p in range(3)}
        events = []
        for p in range(3):
            for _ in range(rng.randint(1, 3)):
                kind = rng.choice(["read", "write", "cas"])
                if kind == "read":
                    v = rng.choice(vals)
                elif kind == "write":
                    v = rng.choice([0, 1, 2])
                else:
                    v = [rng.choice([0, 1, 2]), rng.choice([0, 1, 2])]
                events.append((p, kind, v))
        rng.shuffle(events)
        for p, kind, v in events:
            ops.append(invoke(p, kind, v))
            typ = rng.choice([ok, ok, ok, info])
            ops.append(typ(p, kind, v))
        # interleave completions realistically: keep as alternating pairs
        h = history(ops)
        rn, rp = _wgl_both(h, cas_register())
        assert rn["valid?"] == rp["valid?"], f"trial {trial} diverged"
        agree += 1
    assert agree == 30


def test_bfs_cycle_grows_buffer():
    # a cycle longer than the initial buffer must still be found
    n = 50
    src = np.arange(n, dtype=np.int64)
    dst = np.roll(src, -1)
    cyc = native.bfs_cycle(n, src, dst, 0, max_len=4)
    assert cyc is not None and len(cyc) == n + 1
    assert cyc[0] == cyc[-1] == 0


def test_wgl_native_abort_flag_stops_search():
    # a hard (wide-window) invalid history would explore many configs;
    # with the abort flag pre-set the C++ must stop almost immediately
    # and report aborted (knossos/search.clj ctl semantics)
    if not native.available():
        pytest.skip("native unavailable")
    from jepsen_tpu.checkers.knossos.memo import memoize
    from jepsen_tpu.checkers.knossos.prep import prepare
    from jepsen_tpu.checkers.knossos.search import Search
    from jepsen_tpu.models import cas_register

    n = 18
    events = []
    for i in range(n):  # n fully-concurrent writes, then a bad read
        events.append(invoke(i, "write", i))
    for i in range(n):
        events.append(ok(i, "write", i))
    events.append(invoke(n, "read", None))
    events.append(ok(n, "read", 777))  # never written -> must explore all
    h = history(events)
    ops = prepare(h)
    memo = memoize(cas_register(), ops)

    ctl = Search()
    ctl.abort()
    res = native.wgl(memo.op_sym,
                     [op.invoke_pos for op in ops],
                     [op.return_pos for op in ops],
                     2 * len(events) + 1, memo.table, memo.init_state,
                     50_000_000, abort_flag=ctl.flag)
    assert res is not None
    verdict, explored, aborted = res
    assert aborted is True and verdict is None
    assert explored < 10_000  # stopped within ~1k-config poll window
