"""fleet/ — the fault-tolerant multi-host control plane (ISSUE 9).

Covers the tentpole contracts:

- the **leased work queue**: enqueue/claim/renew/complete/requeue over
  an fsync'd jsonl ledger; lease expiry requeues; at-most-once verdict
  records (zombie double-completions discarded, idempotent resends
  acked); a replayed ledger reaches the identical state digest; torn
  trailing lines tolerated and healed writer-side only;
- the **HTTP control plane** end to end: real coordinator + real
  workers over a real socket, every cell exactly one attributable
  record, the distributed index equal to a single-process
  `run_campaign` on verdict keys, finished fleets resuming with 0
  cells executed;
- the **shared heartbeat writer**: the scheduler's file path and the
  coordinator's HTTP-push path render the same ``/campaign/<n>/live``
  shape, and `run_campaign` with a coordinator URL pushes instead of
  writing locally;
- the **chaos acceptance** (`scripts/soak_fleet.py --fast`): 12 cells
  x 3 worker subprocesses under seeded control-plane drops/stalls, a
  worker kill -9, and a coordinator kill -9 + digest-pinned restart.
"""

import json
import os
import subprocess
import sys
import threading
import time
import urllib.request

import pytest

from jepsen_tpu import store, web
from jepsen_tpu.campaign import core as ccore
from jepsen_tpu.campaign.index import Index
from jepsen_tpu.campaign.plan import expand
from jepsen_tpu.fleet import (
    FleetCoordinator,
    FleetWorker,
    WorkQueue,
    fleet_path,
    record_digest,
)

SPEC = {"name": "fl", "workloads": ["set"], "seeds": [0, 1, 2, 3, 4, 5],
        "opts": {"time-limit": 0.15}}


def _post(url, path, doc, timeout=10):
    req = urllib.request.Request(
        url + path, data=json.dumps(doc).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read().decode())


def _get(url, path, timeout=10):
    with urllib.request.urlopen(url + path, timeout=timeout) as r:
        return r.read().decode()


# ---------------------------------------------------------------- queue

def _spec(run, device=False):
    return {"run_id": run, "campaign": "q", "workload": "set",
            "seed": 0, "opts": {}, "fault": None,
            "fault_label": "nofault", "workload_label": "set",
            "device": device}


def test_queue_lifecycle(tmp_path):
    q = WorkQueue(str(tmp_path / "q.jsonl"))
    assert q.enqueue(_spec("r1"))
    assert q.enqueue(_spec("r2"))
    assert not q.enqueue(_spec("r1"))  # idempotent on run id
    spec, deadline = q.claim("w1", lease_s=5.0, now=100.0)
    assert spec["run_id"] == "r1" and deadline == 105.0  # FIFO
    # only the holder renews
    assert q.renew("r1", "w1", 5.0, now=102.0)
    assert not q.renew("r1", "w2", 5.0, now=102.0)
    assert not q.renew("r2", "w1", 5.0)  # unclaimed
    # a fresh lease survives expiry sweeps until its deadline
    assert q.expire(now=106.0) == []
    assert q.expire(now=108.0) == ["r1"]
    assert q.cells["r1"]["state"] == "queued"
    # release = voluntary requeue (the SIGTERM drain)
    q.claim("w2", lease_s=5.0, now=110.0)
    assert q.release("r1", "w2")
    assert not q.release("r1", "w2")  # no longer held
    assert q.counts()["requeues"] == 2


def test_queue_device_capability_filter(tmp_path):
    q = WorkQueue(str(tmp_path / "q.jsonl"))
    q.enqueue(_spec("dev", device=True))
    q.enqueue(_spec("host"))
    spec, _ = q.claim("w0", lease_s=5.0, device_ok=False)
    assert spec["run_id"] == "host"  # device cell skipped
    spec, _ = q.claim("w1", lease_s=5.0, device_ok=True)
    assert spec["run_id"] == "dev"


def test_queue_at_most_once_completion(tmp_path):
    q = WorkQueue(str(tmp_path / "q.jsonl"))
    q.enqueue(_spec("r1"))
    q.claim("w1", lease_s=0.1, now=0.0)
    q.expire(now=1.0)  # w1's lease lapses
    q.claim("w2", lease_s=5.0, now=1.0)
    rec2 = {"run": "r1", "valid?": True, "wall_s": 0.2}
    assert q.complete("r1", "w2", rec2) == "accepted"
    # w2 resending the identical record (lost ack) is idempotent
    assert q.complete("r1", "w2", dict(rec2)) == "already"
    # the zombie's different record is discarded + counted
    assert q.complete("r1", "w1",
                      {"run": "r1", "valid?": True,
                       "wall_s": 0.9}) == "duplicate"
    assert q.complete("nope", "w1", rec2) == "unknown"
    c = q.counts()
    assert c["done"] == 1 and c["duplicates"] == 1
    assert q.cells["r1"]["record"]["wall_s"] == 0.2  # first wins
    assert record_digest(rec2) != record_digest({"run": "r1",
                                                 "valid?": True,
                                                 "wall_s": 0.9})


def test_queue_replay_reaches_identical_state(tmp_path):
    path = str(tmp_path / "q.jsonl")
    q = WorkQueue(path)
    for i in range(5):
        q.enqueue(_spec(f"r{i}"))
    q.claim("w1", lease_s=0.1, now=0.0)
    q.claim("w2", lease_s=9.0, now=0.0)
    q.expire(now=5.0)  # w1 requeued, w2 still holds
    q.complete("r1", "w2", {"valid?": False})
    q.complete("r1", "w9", {"valid?": True})  # duplicate
    q.claim("w3", lease_s=9.0, now=6.0)
    replayed = WorkQueue(path)
    assert replayed.digest() == q.digest()
    assert replayed.counts() == q.counts()
    # replay preserves claim order too: next claim picks the same cell
    a = q.claim("wx", lease_s=1.0, now=7.0)[0]["run_id"]
    b = replayed.claim("wx", lease_s=1.0, now=7.0)[0]["run_id"]
    assert a == b


def test_queue_torn_tail_tolerated_and_healed(tmp_path):
    path = str(tmp_path / "q.jsonl")
    q = WorkQueue(path)
    q.enqueue(_spec("r1"))
    q.claim("w1", lease_s=5.0)
    digest = q.digest()
    with open(path, "a") as f:
        f.write('{"ev": "complete", "run": "r1", "wor')  # kill -9 debris
    size_with_debris = os.path.getsize(path)
    # read-only replay drops the torn line, does NOT truncate the file
    seen = WorkQueue(path)
    assert seen.digest() == digest
    assert os.path.getsize(path) == size_with_debris
    # the next WRITER heals before appending: no fused line, state sane
    seen.complete("r1", "w1", {"valid?": True})
    again = WorkQueue(path)
    assert again.cells["r1"]["state"] == "done"
    assert again.digest() == seen.digest()


# ------------------------------------------------- transient classifier

def test_is_transient_http():
    import urllib.error

    from jepsen_tpu.resilience import DeadlineExceeded, is_transient_http
    from jepsen_tpu.resilience.faults import FaultInjected

    assert is_transient_http(ConnectionRefusedError(111, "refused"))
    assert is_transient_http(TimeoutError())
    assert is_transient_http(
        urllib.error.URLError(OSError("unreachable")))
    e503 = urllib.error.HTTPError("u", 503, "busy", {}, None)
    e404 = urllib.error.HTTPError("u", 404, "nope", {}, None)
    assert is_transient_http(e503)
    assert not is_transient_http(e404)
    assert is_transient_http(FaultInjected("oom", "fleet.claim", 0))
    assert not is_transient_http(DeadlineExceeded("x"))
    assert not is_transient_http(ValueError("bug"))


# --------------------------------------------- HTTP end to end (real IO)

@pytest.fixture(scope="module")
def fleet_run(tmp_path_factory):
    """One 6-cell campaign run by 2 in-process FleetWorkers against a
    real coordinator over a real socket."""
    base = str(tmp_path_factory.mktemp("fleet"))
    coord = FleetCoordinator(SPEC, base, lease_s=5.0)
    srv = web.serve(port=0, base=base, background=True, fleet=coord)
    url = f"http://127.0.0.1:{srv.server_address[1]}"
    ws = [FleetWorker(url, base, name=f"w{i}", poll_s=0.05)
          for i in range(2)]
    ts = [threading.Thread(target=w.run, daemon=True) for w in ws]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=120)
    assert not any(t.is_alive() for t in ts), "workers wedged"
    yield base, url, coord, ws
    srv.server_close()
    coord.close()


def test_fleet_every_cell_exactly_one_record(fleet_run):
    base, url, coord, ws = fleet_run
    idx = Index(ccore.index_path("fl", base))
    per_run = {}
    for rec in idx.records:
        assert rec["valid?"] in (True, False, "unknown")
        per_run[rec["run"]] = per_run.get(rec["run"], 0) + 1
    assert per_run == {rs.run_id: 1 for rs in expand(SPEC)}
    assert sum(w.cells_done for w in ws) == 6
    # every record names its executor
    assert all(rec.get("fleet-worker") in ("w0", "w1")
               for rec in idx.records)


def test_fleet_matches_single_process_campaign(fleet_run, tmp_path):
    base, *_ = fleet_run
    from jepsen_tpu import campaign

    ref = campaign.run_campaign(SPEC, str(tmp_path), workers=2)
    ref_verdicts = {r["key"]: r["valid?"] for r in ref["rows"]}
    idx = Index(ccore.index_path("fl", base))
    got = {rec["key"]: rec["valid?"]
           for rec in idx.latest_by_run().values()}
    assert got == ref_verdicts


def test_fleet_status_and_page(fleet_run):
    base, url, coord, _ws = fleet_run
    s = json.loads(_get(url, "/fleet/status"))
    assert s["finished"] is True and s["done"] == 6
    assert s["counts"]["done"] == 6 and s["counts"]["queued"] == 0
    assert s["digest"] and s["boot-digest"]
    assert set(s["workers"]) == {"w0", "w1"}
    page = _get(url, "/fleet")
    assert "fleet — fl" in page and "w0" in page
    # the index page links the fleet dashboard
    assert 'href="/fleet"' in _get(url, "/")


def test_fleet_metrics_gauges(fleet_run):
    base, url, *_ = fleet_run
    body = _get(url, "/metrics")
    assert "jepsen_fleet_workers_alive" in body
    assert 'jepsen_fleet_cells{state="done"} 6' in body
    assert "jepsen_fleet_leases_active 0" in body


def test_fleet_live_page_renders_coordinator_heartbeat(fleet_run):
    """Satellite: the coordinator's Heartbeat writer produces the same
    live.json shape the single-process scheduler writes — the
    /campaign/<n>/live dashboard renders it unchanged."""
    base, url, *_ = fleet_run
    doc = json.load(open(ccore.live_path("fl", base)))
    assert doc["finished"] is True and doc["done"] == 6
    page = _get(url, "/campaign/fl/live")
    assert "finished" in page and "6/6 runs done" in page


def test_fleet_finished_campaign_resumes_zero(fleet_run):
    base, url, *_ = fleet_run
    # a fresh coordinator over the finished store replays to done
    c2 = FleetCoordinator(SPEC, base, lease_s=5.0)
    assert c2.finished
    assert c2.queue.counts()["queued"] == 0
    code, r = c2.claim({"worker": "late"})
    assert code == 200 and r["spec"] is None and r["finished"]
    # and single-process resume parity: run_campaign executes 0 cells
    from jepsen_tpu import campaign

    summary = campaign.run_campaign(SPEC, base, workers=2)
    assert summary["executed"] == 0 and summary["skipped"] == 6


def test_fleet_lease_expiry_requeue_and_zombie_discard(tmp_path):
    """Worker death mid-run, end to end: a ghost claims a cell and
    stops renewing; the lease lapses, the cell requeues and completes
    on a live worker; the ghost's eventual completion is discarded as
    a duplicate (at-most-once verdicts)."""
    base = str(tmp_path)
    spec = dict(SPEC, name="fl-ghost", seeds=[0, 1, 2])
    coord = FleetCoordinator(spec, base, lease_s=0.6)
    srv = web.serve(port=0, base=base, background=True, fleet=coord)
    url = f"http://127.0.0.1:{srv.server_address[1]}"
    try:
        ghost = _post(url, "/fleet/claim", {"worker": "ghost"})
        run = ghost["spec"]["run_id"]
        time.sleep(0.7)  # the ghost never renews: lease lapses
        w = FleetWorker(url, base, name="alive", poll_s=0.05)
        t = threading.Thread(target=w.run, daemon=True)
        t.start()
        t.join(timeout=60)
        assert not t.is_alive()
        s = json.loads(_get(url, "/fleet/status"))
        assert s["finished"] and s["counts"]["requeues"] >= 1
        idx = Index(ccore.index_path("fl-ghost", base))
        assert {r.run_id for r in expand(spec)} == \
            {rec["run"] for rec in idx.records}
        # the zombie wakes up and uploads its stale verdict: discarded
        r = _post(url, "/fleet/complete",
                  {"worker": "ghost", "run": run,
                   "record": {"run": run, "valid?": True,
                              "wall_s": 99.0}})
        assert r == {"ok": False, "duplicate": True}
        assert json.loads(_get(url, "/fleet/status"))[
            "counts"]["duplicates"] == 1
        assert len([rec for rec in Index(
            ccore.index_path("fl-ghost", base)).records
            if rec["run"] == run]) == 1  # still exactly one record
    finally:
        srv.server_close()
        coord.close()


def test_coordinator_reconciles_index_from_ledger(tmp_path):
    """Crash between the queue's complete event (the commit point) and
    the index append: boot re-derives the missing index record from
    the ledger's own copy — no cell lost, none doubled."""
    base = str(tmp_path)
    spec = dict(SPEC, name="fl-rec", seeds=[0, 1])
    ids = [rs.run_id for rs in expand(spec)]
    q = WorkQueue(fleet_path("fl-rec", base))
    for rs in expand(spec):
        q.enqueue(rs.to_dict())
    q.claim("w1", lease_s=9.0)
    rec = {"run": ids[0], "key": "set|nofault|s0", "valid?": True,
           "wall_s": 0.1}
    assert q.complete(ids[0], "w1", rec) == "accepted"
    # ...and the process dies HERE, before the index append
    pre = WorkQueue(fleet_path("fl-rec", base)).digest()
    coord = FleetCoordinator(spec, base, lease_s=5.0)
    assert coord.boot_digest == pre  # replay is digest-pinned
    idx = Index(ccore.index_path("fl-rec", base))
    recs = [r for r in idx.records if r["run"] == ids[0]]
    assert len(recs) == 1
    assert recs[0]["valid?"] is True
    assert recs[0]["fleet-worker"] == "w1"
    # a second boot does not double the reconciled record
    FleetCoordinator(spec, base, lease_s=5.0)
    assert len([r for r in Index(ccore.index_path("fl-rec", base))
                .records if r["run"] == ids[0]]) == 1


# --------------------------------------- heartbeat sharing (satellite)

def test_scheduler_and_fleet_heartbeats_share_one_shape(tmp_path):
    """Both writers — the scheduler's file-only Heartbeat and the
    coordinator's HTTP-fed one — must render on /campaign/<n>/live."""
    from jepsen_tpu.telemetry import Heartbeat

    base = str(tmp_path)
    # scheduler shape: written straight to the file (the fallback path)
    hb = Heartbeat(ccore.live_path("filecamp", base),
                   campaign="filecamp", total=4)
    hb.worker("campaign-worker-0", {"run": "r-file", "workload": "set",
                                    "fault": "nofault", "seed": 0,
                                    "slot": None})
    coord = FleetCoordinator(dict(SPEC, name="fl-hb", seeds=[0]), base,
                             lease_s=5.0)
    srv = web.serve(port=0, base=base, background=True, fleet=coord)
    url = f"http://127.0.0.1:{srv.server_address[1]}"
    try:
        page = _get(url, "/campaign/filecamp/live")
        assert "r-file" in page and "campaign-worker-0" in page
        # coordinator shape: the same state pushed over HTTP
        _post(url, "/fleet/heartbeat",
              {"worker": "remote-w", "state": {
                  "run": "r-http", "workload": "set",
                  "fault": "nofault", "seed": 1, "slot": None}})
        page = _get(url, "/campaign/fl-hb/live")
        assert "r-http" in page and "remote-w" in page
    finally:
        srv.server_close()
        coord.close()


def test_run_campaign_pushes_heartbeat_to_coordinator(tmp_path):
    """`run_campaign` with a coordinator URL (spec opts) pushes its
    heartbeat over HTTP: the live.json lands in the COORDINATOR's
    store via its single writer, not in the campaign's own store."""
    from jepsen_tpu import campaign

    coord_base = str(tmp_path / "coord")
    camp_base = str(tmp_path / "camp")
    coord = FleetCoordinator(dict(SPEC, name="fl-push", seeds=[0]),
                             coord_base, lease_s=5.0)
    srv = web.serve(port=0, base=coord_base, background=True,
                    fleet=coord)
    url = f"http://127.0.0.1:{srv.server_address[1]}"
    try:
        spec = {"name": "pushed", "workloads": ["noop"], "seeds": [0],
                "opts": {"coordinator": url}}
        summary = campaign.run_campaign(spec, camp_base, workers=1)
        assert summary["executed"] == 1
        # pushed, not written locally
        assert not os.path.exists(ccore.live_path("pushed", camp_base))
        doc = json.load(open(ccore.live_path("pushed", coord_base)))
        assert doc["campaign"] == "pushed"
        assert doc["finished"] is True
        assert doc["total"] == 1 and doc["done"] == 1
        page = _get(url, "/campaign/pushed/live")
        assert "finished" in page and "1/1 runs done" in page
    finally:
        srv.server_close()
        coord.close()


def test_http_heartbeat_never_raises_without_a_coordinator():
    from jepsen_tpu.telemetry import HttpHeartbeat

    hb = HttpHeartbeat("http://127.0.0.1:1", campaign="x", total=2,
                       timeout_s=0.2)  # nothing listens on port 1
    hb.worker("w", {"run": "r"})
    hb.record_done("r", True)
    hb.close()  # all best-effort no-ops


def test_http_heartbeat_backs_off_after_failure(monkeypatch):
    """Review regression: heartbeats are posted synchronously from the
    scheduler's worker threads, so an unreachable coordinator must
    cost ONE timeout per cooldown window, not one per cell
    transition."""
    import urllib.request

    from jepsen_tpu.telemetry import HttpHeartbeat

    calls = []

    def dying(*a, **kw):
        calls.append(1)
        raise OSError("unreachable")

    monkeypatch.setattr(urllib.request, "urlopen", dying)
    hb = HttpHeartbeat("http://coord:1", campaign="x", backoff_s=60.0)
    assert len(calls) == 1  # the init push tried and armed the backoff
    for i in range(10):
        hb.worker("w", {"run": f"r{i}"})
        hb.record_done(f"r{i}", True)
    assert len(calls) == 1  # every update inside the cooldown skipped
    hb._down_until = 0.0  # window over: the next push tries again
    hb.worker("w", None)
    assert len(calls) == 2


def test_coordinator_close_and_touch_scoped_to_own_fleet(tmp_path):
    """Review regressions: (a) a pushed campaign's scheduler slot
    names must not register as fleet workers (the workers-alive view
    would over-count); (b) coordinator close() must not mark OTHER
    campaigns' pushed heartbeats finished while they still run."""
    base = str(tmp_path)
    spec = dict(SPEC, name="fl-scope", seeds=[0])
    run_id = expand(spec)[0].run_id
    coord = FleetCoordinator(spec, base, lease_s=5.0)
    # a remote run_campaign pushes through the heartbeat sink
    coord.heartbeat({"campaign": "other", "total": 3,
                     "worker": "campaign-worker-0",
                     "state": {"run": "r-other", "slot": 0}})
    code, s = coord.status()
    assert "campaign-worker-0" not in s["workers"]  # not a fleet worker
    # ...but its state still reaches the other campaign's live.json
    doc = json.load(open(ccore.live_path("other", base)))
    assert doc["workers"]["campaign-worker-0"]["run"] == "r-other"
    # a real fleet worker registers via claim and finishes the fleet
    code, r = coord.claim({"worker": "real-w"})
    assert code == 200 and r["spec"]["run_id"] == run_id
    code, _ = coord.complete({"worker": "real-w", "run": run_id,
                              "record": {"run": run_id, "key": "k",
                                         "valid?": True}})
    assert code == 200 and coord.finished
    assert "real-w" in coord.status()[1]["workers"]
    coord.close()
    own = json.load(open(ccore.live_path("fl-scope", base)))
    assert own["finished"] is True
    other = json.load(open(ccore.live_path("other", base)))
    assert other["finished"] is False  # still that campaign's to close


# ------------------------------------------------- warehouse satellite

def test_warehouse_ingests_fleet_ledger(tmp_path):
    from jepsen_tpu.telemetry import warehouse as wmod

    base = str(tmp_path)
    q = WorkQueue(fleet_path("wf", base))
    for i in range(3):
        q.enqueue(_spec(f"r{i}"))
    q.claim("hostA", lease_s=0.1, now=0.0)
    q.expire(now=1.0)  # hostA requeues
    q.claim("hostB", lease_s=9.0, now=1.0)
    q.complete("r0", "hostB", {"valid?": True})
    q.complete("r0", "hostA", {"valid?": True})  # zombie duplicate
    wh = wmod.open_or_create(base)
    stats = wh.ingest_store(base)
    assert stats["fleet-events"] == 8
    roll = wh.fleet_worker_rollup("fleet/wf.jsonl")
    # "which host's cells requeue most": hostA leads
    assert roll[0]["worker"] == "hostA" and roll[0]["requeues"] == 1
    assert roll[0]["duplicates"] == 1
    by = {r["worker"]: r for r in roll}
    assert by["hostB"]["completes"] == 1 and by["hostB"]["claims"] == 1
    # incremental: unchanged ledger is a no-op; appends ingest alone
    assert wh.ingest_store(base)["fleet-events"] == 0
    q.complete("r1", "hostB", {"valid?": False})
    assert wh.ingest_store(base)["fleet-events"] == 1
    # cli obs sql can answer it
    cols, rows = wh.query(
        "SELECT worker FROM fleet_worker_rollup "
        "ORDER BY requeues DESC LIMIT 1")
    assert rows == [("hostA",)]
    # a healed/rewritten (shrunken) ledger wipes + re-ingests
    path = fleet_path("wf", base)
    lines = open(path).readlines()
    with open(path, "w") as f:
        f.writelines(lines[:4])
    wh.ingest_fleet_ledger(path, base)
    assert wh.counts()["fleet_events"] == 4
    wh.close()


def test_store_tests_skips_fleet_subtree(tmp_path):
    base = str(tmp_path)
    os.makedirs(os.path.join(base, "fleet"))
    with open(os.path.join(base, "fleet", "x.jsonl"), "w") as f:
        f.write("{}\n")
    os.makedirs(os.path.join(base, "a-test", "t1"))
    assert [os.path.basename(os.path.dirname(d))
            for d in store.tests(base=base)] == ["a-test"]


# ------------------------------- nemesis schedule (ISSUE 11 tentpole)

SCHED_SPEC = {
    "name": "fl-sched", "workloads": ["bank"], "seeds": [0, 1],
    "nemesis-schedule": {"faults": ["skew", "partition"], "windows": 2,
                         "interval": 0.02, "duration": 0.2, "seed": 5},
    "opts": {"time-limit": 0.3, "ops": 60, "concurrency": 2,
             "client-latency": 0.002},
}


def test_schedule_windows_deterministic_and_generation_scoped():
    from jepsen_tpu.campaign.plan import (expand, schedule_windows,
                                          windows_digest)

    w0 = schedule_windows(SCHED_SPEC, 0)
    assert w0 == schedule_windows(SCHED_SPEC, 0)  # pure function
    assert [w["fault"] for w in w0] == ["skew", "partition"]
    assert all(w["digest"] for w in w0)
    w1 = schedule_windows(SCHED_SPEC, 1)
    # generation-scoped: each generation draws its own seeded layout
    assert [w["digest"] for w in w0] != [w["digest"] for w in w1]
    assert windows_digest(w0) != windows_digest(w1)
    # expand injects the window set into every cell's opts — the
    # single-process and distributed expansions of one spec are
    # chaos-equivalent cell for cell
    specs = expand(SCHED_SPEC)
    assert all(rs.opts.get("nemesis-windows") ==
               schedule_windows(SCHED_SPEC, rs.seed) for rs in specs)
    # and run ids stay stable across re-expansion
    assert [rs.run_id for rs in specs] == \
        [rs.run_id for rs in expand(SCHED_SPEC)]


def test_schedule_composes_with_per_cell_nemesis():
    """Review regression: a cell carrying BOTH its own nemesis opts and
    the campaign window schedule must compose (compose_packages is
    closed under composition) — and both fault sources' ops must be
    routed and answered in the run's history."""
    import tempfile

    from jepsen_tpu import core as jcore
    from jepsen_tpu.campaign.plan import build_test, expand

    spec = dict(SCHED_SPEC, name="fl-both", seeds=[0])
    spec["workloads"] = [{"name": "bank", "opts": {
        "nemesis": {"faults": ["membership"], "interval": 0.05}}}]
    spec["nemesis-schedule"] = {"faults": ["skew"], "windows": 1,
                                "interval": 0.02, "duration": 0.2,
                                "seed": 3}
    rs = expand(spec)[0]
    assert rs.opts["nemesis"] and rs.opts["nemesis-windows"]
    t = build_test(rs, tempfile.mkdtemp(prefix="both-"))
    done = jcore.run(t)
    assert "valid?" in (done.get("results") or {})
    nem_fs = {op.f for op in done["history"]
              if op.process == "nemesis" and op.type != "invoke"}
    assert "start-skew" in nem_fs  # the scheduled window ran...
    assert nem_fs & {"leave-node", "join-node", "membership-view"}, \
        nem_fs  # ...and so did the cell's own nemesis


def test_schedule_validates_fault_families():
    from jepsen_tpu.campaign.plan import load_spec

    bad = dict(SCHED_SPEC,
               **{"nemesis-schedule": {"faults": ["wat"]}})
    with pytest.raises(ValueError, match="wat"):
        load_spec(bad)
    neg = dict(SCHED_SPEC, **{"nemesis-schedule": {
        "faults": ["skew"], "duration": -0.5}})
    with pytest.raises(ValueError, match="duration"):
        load_spec(neg)  # heal-before-start schedules refused at plan time


def test_schedule_plan_template_seeds_per_generation():
    """A schedule "plan" template derives a distinct-but-replayable
    FaultPlan spec per generation, installed only when the cell's own
    fault axis is empty."""
    from jepsen_tpu.campaign.plan import build_test, expand
    from jepsen_tpu.resilience.faults import seeded_for

    spec = dict(SCHED_SPEC, name="fl-plan")
    spec["nemesis-schedule"] = dict(
        SCHED_SPEC["nemesis-schedule"],
        plan={"seed": 9, "p": 0.1, "kinds": "oom"})
    specs = expand(spec)
    by_seed = {rs.seed: rs for rs in specs}
    assert by_seed[0].opts["nemesis-plan"]["seed"] == 9 ^ 0
    assert by_seed[1].opts["nemesis-plan"]["seed"] == 9 ^ 1
    assert seeded_for({"seed": 9}, 1)["seed"] == 8
    t = build_test(by_seed[1], "store")
    assert t["faults"]["seed"] == 9 ^ 1
    # an explicit fault axis entry wins over the schedule plan
    spec2 = dict(spec, faults=[{"seed": 77, "p": 0.2}])
    rs2 = expand(spec2)[0]
    assert build_test(rs2, "store")["faults"]["seed"] == 77


def test_queue_affinity_and_starvation_fallback(tmp_path):
    """Worker-affine placement: a device cell pinning a backend defers
    on non-matching workers (counted), lands on the matching one, and
    falls back to any device-capable worker once starved past a
    lease."""
    from jepsen_tpu import telemetry

    q = WorkQueue(str(tmp_path / "q.jsonl"))
    cell = _spec("dev", device=True)
    cell["opts"] = {"backend": "tpu"}
    q.enqueue(cell)
    reg = telemetry.registry()
    before = reg.counter("fleet-affinity-deferrals", worker="cpu-w").value
    # a cpu worker defers; the tpu worker claims
    spec, _ = q.claim("cpu-w", lease_s=5.0,
                      caps={"backend": "cpu"}, now=100.0)
    assert spec is None
    assert reg.counter("fleet-affinity-deferrals",
                       worker="cpu-w").value == before + 1
    spec, _ = q.claim("tpu-w", lease_s=5.0,
                      caps={"backend": "tpu"}, now=100.5)
    assert spec and spec["run_id"] == "dev"
    # starvation-safe fallback: past one lease of deferral, any
    # device-capable worker may take it
    q2 = WorkQueue(str(tmp_path / "q2.jsonl"))
    q2.enqueue(dict(_spec("dev2", device=True), opts={"backend": "tpu"}))
    assert q2.claim("cpu-w", lease_s=5.0, caps={"backend": "cpu"},
                    now=100.0)[0] is None  # arms the clock
    assert q2.claim("cpu-w", lease_s=5.0, caps={"backend": "cpu"},
                    now=103.0)[0] is None  # still inside the lease
    spec, _ = q2.claim("cpu-w", lease_s=5.0, caps={"backend": "cpu"},
                       now=106.0)
    assert spec and spec["run_id"] == "dev2"  # starved: affinity yields
    # mesh-shape pins behave the same way
    q3 = WorkQueue(str(tmp_path / "q3.jsonl"))
    q3.enqueue(dict(_spec("dev3", device=True), opts={"mesh": "2x2"}))
    assert q3.claim("w", lease_s=5.0, caps={"mesh": [4]},
                    now=0.0)[0] is None
    assert q3.claim("w", lease_s=5.0, caps={"mesh": [2, 2]},
                    now=0.1)[0] is not None


def test_claim_broadcasts_windows_and_worker_installs(tmp_path):
    """The claim response carries the cell generation's synchronized
    window set; the worker installs it (authoritative over the
    ledger's serialized spec) before execute_run."""
    from jepsen_tpu.campaign.plan import schedule_windows, windows_digest
    from jepsen_tpu.campaign.plan import RunSpec

    base = str(tmp_path)
    coord = FleetCoordinator(SCHED_SPEC, base, lease_s=5.0)
    try:
        code, r = coord.claim({"worker": "w"})
        assert code == 200 and r["spec"]
        g = r["spec"]["seed"]
        want = schedule_windows(SCHED_SPEC, g)
        assert r["windows"]["set"] == want
        assert r["windows"]["digest"] == windows_digest(want)
        assert r["windows"]["gen"] == g
        # the worker-side install: claim wins even over a stale spec
        w = FleetWorker("http://127.0.0.1:1", base, name="w")
        stale = dict(r["spec"], opts=dict(r["spec"]["opts"]))
        stale["opts"].pop("nemesis-windows", None)  # pre-schedule ledger
        rs = RunSpec.from_dict(stale)
        w._install_windows(rs, r["windows"])
        assert rs.opts["nemesis-windows"] == want
        assert rs.opts["_fleet-host"] == "w"
        assert w.installed_windows["digest"] == r["windows"]["digest"]
        # tick derivation: before any window opens, none are open
        ticks = w._window_ticks(__import__("time").monotonic())
        assert ticks["digest"] == r["windows"]["digest"]
        assert ticks["n"] == 2 and ticks["open"] == []
    finally:
        coord.close()


def test_heartbeat_ticks_sync_and_desync_visible(tmp_path):
    """Lease renewal doubles as chaos clock sync: worker window ticks
    land in the coordinator's worker table (synced flag, /fleet page,
    gauges); a desynced digest is visible at a glance."""
    from jepsen_tpu import telemetry
    from jepsen_tpu.telemetry import prometheus

    base = str(tmp_path)
    coord = FleetCoordinator(SCHED_SPEC, base, lease_s=5.0)
    try:
        code, r = coord.claim({"worker": "w"})
        auth = r["windows"]["digest"]
        code, hb = coord.heartbeat({
            "worker": "w",
            "windows": {"gen": r["windows"]["gen"], "digest": auth,
                        "open": [{"pos": 0, "fault": "skew"}]},
            "renew": [r["spec"]["run_id"]]})
        assert code == 200 and hb["windows-digest"] == auth
        code, s = coord.status()
        ws = s["workers"]["w"]["windows"]
        assert ws["synced"] is True and ws["digest"] == auth
        assert s["nemesis-schedule"]["digest-by-gen"][
            str(r["windows"]["gen"])] == auth
        lines = prometheus.render_registry(telemetry.registry())
        assert any("jepsen_fleet_nemesis_windows_active" in ln
                   and 'fault="skew"} 1' in ln for ln in lines), lines
        # a desynced worker is flagged
        coord.heartbeat({"worker": "w",
                         "windows": {"gen": r["windows"]["gen"],
                                     "digest": "bogus", "open": []}})
        code, s = coord.status()
        assert s["workers"]["w"]["windows"]["synced"] is False
        # windows retire with the cell
        coord.heartbeat({"worker": "w", "state": None, "windows": None})
        code, s = coord.status()
        assert "windows" not in s["workers"]["w"]
    finally:
        coord.close()


def test_nemesis_broadcast_survives_heartbeat_chaos(tmp_path):
    """ISSUE 11 satellite: with the fleet.heartbeat seam fully dead
    (the existing fleet.* fault sites), a worker misses every window
    tick — and still installs the correct seeded window set from its
    next claim: the records' installed-window digests equal the
    coordinator's authoritative ones."""
    from jepsen_tpu.campaign.plan import schedule_windows, windows_digest
    from jepsen_tpu.resilience import RetryPolicy
    from jepsen_tpu.resilience.faults import FaultPlan, use
    from jepsen_tpu.resilience.policy import is_transient_http

    base = str(tmp_path)
    spec = dict(SCHED_SPEC, name="fl-chaos-hb")
    coord = FleetCoordinator(spec, base, lease_s=30.0)
    srv = web.serve(port=0, base=base, background=True, fleet=coord)
    url = f"http://127.0.0.1:{srv.server_address[1]}"
    plan = FaultPlan(persistent=("fleet.heartbeat",), kinds=("oom",))
    try:
        w = FleetWorker(url, base, name="deaf", poll_s=0.05,
                        retry=RetryPolicy(max_attempts=2,
                                          base_delay_s=0.02,
                                          classify=is_transient_http))
        with use(plan):
            t = threading.Thread(target=w.run, daemon=True)
            t.start()
            t.join(timeout=120)
            assert not t.is_alive(), "worker wedged"
        assert len(plan.injected) > 0  # heartbeats really dropped
        idx = Index(ccore.index_path("fl-chaos-hb", base))
        recs = list(idx.latest_by_run().values())
        assert len(recs) == 2
        for rec in recs:
            want = windows_digest(schedule_windows(spec, rec["seed"]))
            assert rec["windows-digest"] == want
        # the ticks never arrived: the worker table records no windows
        code, s = coord.status()
        assert "windows" not in s["workers"]["deaf"]
    finally:
        srv.server_close()
        coord.close()


def test_worker_claim_backoff_seeded_and_budgeted():
    """Claim give-up is a seeded-jittered backoff under a configurable
    budget — two workers never share a delay stream (no synchronized
    re-poll storms), and the budget bounds the total wait."""
    wa = FleetWorker("http://127.0.0.1:1", "store", name="wa",
                     poll_s=0.1)
    wb = FleetWorker("http://127.0.0.1:1", "store", name="wb",
                     poll_s=0.1)
    da = [wa._claim_backoff(i) for i in range(1, 9)]
    db = [wb._claim_backoff(i) for i in range(1, 9)]
    assert da != db  # per-name seeding desynchronizes the fleet
    wa2 = FleetWorker("http://127.0.0.1:1", "store", name="wa",
                      poll_s=0.1)
    assert da == [wa2._claim_backoff(i) for i in range(1, 9)]
    # ...but each worker's stream replays
    for i, d in enumerate(da, start=1):
        base = min(0.1 * 2 ** (i - 1), 5.0)
        assert 0.5 * base <= d <= 1.5 * base
    # budget give-up: a claim outage outlasting claim_budget_s raises
    w = FleetWorker("http://127.0.0.1:1", "store", name="wc",
                    poll_s=0.01, claim_budget_s=0.05)
    w.register = lambda: None
    calls = []

    def dead_post(site, path, doc):
        calls.append(site)
        raise ConnectionRefusedError("down")

    w._post = dead_post
    with pytest.raises(ConnectionRefusedError):
        w.run()
    assert len(calls) > 1  # re-polled under backoff before giving up


# ------------------------------------------- chaos acceptance (tier 1)

def test_fleet_soak_fast_chaos_acceptance():
    """The ISSUE 9 + ISSUE 11 acceptance pin, end to end in
    subprocesses: a 12-cell campaign run by 3 workers under seeded
    control-plane chaos (drops + stalls on claim/heartbeat/complete,
    both sides), one worker kill -9 (lease-expiry requeue), one
    coordinator kill -9 + restart (ledger replay digest-pinned against
    an independent replay) — exactly one attributable verdict per
    cell, the distributed result set equal to a single-process
    run_campaign on verdict keys — followed by the coordinated-chaos
    round: a synchronized skew+partition window schedule across 3
    workers whose per-generation minimal witness sets (fault-window
    digests, host-attributed) equal the single-process equivalent of
    the same spec + seed."""
    script = os.path.join(os.path.dirname(__file__), os.pardir,
                          "scripts", "soak_fleet.py")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run([sys.executable, script, "--fast"],
                          capture_output=True, text=True, timeout=420,
                          env=env)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "fleet soak OK" in proc.stdout
    assert "replayed to identical state" in proc.stdout
    assert "killed -9 worker" in proc.stdout
    assert "killed -9 coordinator" in proc.stdout
    assert "coordinated chaos OK" in proc.stdout
    assert "witness windows match single-process" in proc.stdout
    # the ISSUE 13 federation + live-check round rode along
    assert "federation round OK" in proc.stdout
    assert "no shared " in proc.stdout
    # ISSUE 14: trace ids survive chaos — the relanded/replayed runs'
    # stitched timelines carry ONE trace id with zero orphan spans
    assert "stitched timelines single-trace" in proc.stdout
    assert "zero orphan spans" in proc.stdout


# ------------------------- store federation: artifact uploads (ISSUE 13)

def _make_run_dir(root, name="a-test", ts="t1", extra=0):
    d = os.path.join(root, name, ts)
    os.makedirs(d, exist_ok=True)
    with open(os.path.join(d, "results.json"), "w") as f:
        json.dump({"valid?": True, "n": extra}, f)
    with open(os.path.join(d, "history.jsonl"), "w") as f:
        for i in range(50 + extra):
            f.write(json.dumps({"type": "ok", "i": i}) + "\n")
    sub = os.path.join(d, "telemetry")
    os.makedirs(sub, exist_ok=True)
    with open(os.path.join(sub, "spans.json"), "w") as f:
        f.write("{}")
    return d, f"{name}/{ts}"


def _tree(d):
    out = {}
    for root, _dirs, files in os.walk(d):
        for fn in files:
            p = os.path.join(root, fn)
            with open(p, "rb") as f:
                out[os.path.relpath(p, d)] = f.read()
    return out


def test_artifact_store_chunked_resumable_idempotent(tmp_path):
    """The upload protocol: probe -> cursor, gap -> 409 carrying the
    cursor, resend overlap skipped, digest-verified atomic landing,
    re-upload of a landed run acked ``already``."""
    from jepsen_tpu.fleet.artifacts import ArtifactStore, pack_run_dir

    wbase, cbase = str(tmp_path / "worker"), str(tmp_path / "coord")
    src, rel = _make_run_dir(wbase)
    data, digest = pack_run_dir(src)
    st = ArtifactStore(cbase)
    # probe on an unknown run: nothing received, nothing landed
    code, r = st.handle("r1", {}, b"")
    assert (code, r) == (200, {"received": 0, "landed": False})
    p = {"total": len(data), "digest": digest, "rel": rel}
    # a gap is a 409 carrying the resume cursor
    code, r = st.handle("r1", dict(p, offset=100), data[100:200])
    assert code == 409 and r["received"] == 0
    # chunks land in order; a resend below the cursor overlap-skips
    code, r = st.handle("r1", dict(p, offset=0), data[:200])
    assert code == 200 and r["received"] == 200
    code, r = st.handle("r1", dict(p, offset=100), data[100:300])
    assert code == 200 and r["received"] == 300
    # kill -9 the "coordinator": a fresh ArtifactStore resumes from
    # the fsync'd partial
    st2 = ArtifactStore(cbase)
    code, r = st2.handle("r1", {}, b"")
    assert code == 200 and r == {"received": 300, "landed": False,
                                 "rel": rel}
    code, r = st2.handle("r1", dict(p, offset=300), data[300:])
    assert code == 200 and r["landed"] is True
    final = os.path.join(cbase, rel)
    assert _tree(final) == _tree(src)  # digest-equal landing
    # landing is idempotent: the staging partial is gone, a re-upload
    # (a zombie worker's late attempt) is acked without rewriting
    assert not os.path.exists(os.path.join(
        cbase, "fleet", "staging", "r1.tar"))
    code, r = st2.handle("r1", dict(p, offset=0), data[:200])
    assert code == 200 and r.get("already") is True
    # the landed dir is an ordinary store run dir
    assert os.path.join(cbase, rel) in store.tests(base=cbase)


def test_artifact_digest_mismatch_and_new_upload_discard(tmp_path):
    """A digest mismatch at landing discards the partial (client
    restarts from 0); a NEW upload of the same run id with a different
    digest (the re-executed cell after a worker kill -9 mid-upload)
    drops the stale partial instead of corrupting the tar."""
    from jepsen_tpu.fleet.artifacts import ArtifactStore, pack_run_dir

    wbase, cbase = str(tmp_path / "w"), str(tmp_path / "c")
    src, rel = _make_run_dir(wbase)
    data, digest = pack_run_dir(src)
    st = ArtifactStore(cbase)
    # whole body declared under a WRONG digest: discarded at landing
    p_bad = {"total": len(data), "digest": "0" * 64, "rel": rel}
    code, r = st.handle("r2", dict(p_bad, offset=0), data)
    assert code == 409 and "digest" in r["error"] and r["received"] == 0
    # stale partial from a dead worker's attempt (different content):
    src2, _ = _make_run_dir(str(tmp_path / "w2"), extra=7)
    data2, digest2 = pack_run_dir(src2)
    p_old = {"total": len(data2), "digest": digest2, "rel": rel}
    code, r = st.handle("r2", dict(p_old, offset=0), data2[:100])
    assert code == 200 and r["received"] == 100
    # ... the re-executed cell uploads the REAL artifact: the store
    # notices total/digest changed and restarts clean
    p_new = {"total": len(data), "digest": digest, "rel": rel}
    code, r = st.handle("r2", dict(p_new, offset=0), data)
    assert code == 200 and r["landed"] is True
    assert _tree(os.path.join(cbase, rel)) == _tree(src)


def test_artifact_reexecution_new_rel_lands_too(tmp_path):
    """Landing is at-most-once per run DIR, not per run id: a
    lease-lapse re-execution mints a new wall-clock timestamp, so its
    upload of the same run id under a different ``rel`` must drop the
    stale landed marker and land the new dir too — otherwise the
    re-executor's verdict record points at a path that never arrives.
    The resume probe answers the staged ``rel`` so a client can tell
    whose partial/marker it is resuming."""
    from jepsen_tpu.fleet.artifacts import ArtifactStore, pack_run_dir

    wbase, cbase = str(tmp_path / "w"), str(tmp_path / "c")
    src, rel = _make_run_dir(wbase, ts="t1")
    data, digest = pack_run_dir(src)
    st = ArtifactStore(cbase)
    p = {"total": len(data), "digest": digest, "rel": rel}
    code, r = st.handle("r1", dict(p, offset=0), data)
    assert code == 200 and r["landed"] is True
    # probe for the SAME dir: landed, carrying the rel
    code, r = st.handle("r1", {}, b"")
    assert code == 200 and r["landed"] is True and r["rel"] == rel
    # re-execution: same run id, new timestamp dir
    src2, rel2 = _make_run_dir(wbase, ts="t2", extra=3)
    data2, digest2 = pack_run_dir(src2)
    p2 = {"total": len(data2), "digest": digest2, "rel": rel2}
    code, r = st.handle("r1", dict(p2, offset=0), data2)
    assert code == 200 and r["landed"] is True and "already" not in r
    assert _tree(os.path.join(cbase, rel)) == _tree(src)
    assert _tree(os.path.join(cbase, rel2)) == _tree(src2)
    # a LATE duplicate of the first dir still acks already (its run
    # dir exists — _land's at-most-once path)
    code, r = st.handle("r1", dict(p, offset=0), data)
    assert code == 200 and r.get("already") is True


def test_artifact_rejects_traversal_and_reserved_subtrees(tmp_path):
    from jepsen_tpu.fleet.artifacts import ArtifactStore

    st = ArtifactStore(str(tmp_path))
    base_p = {"offset": 0, "total": 10, "digest": "d" * 64}
    for rel in ("../evil/t", "a/../../b", "a", "a/b/c", ".hide/t",
                "a/.incoming-t", "campaigns/t", "verifier/t",
                "fleet/t"):
        code, r = st.handle("r3", dict(base_p, rel=rel), b"x" * 10)
        assert code == 400, rel
    code, _r = st.handle("../run", {}, b"")
    assert code == 400


def test_artifact_refuses_hostile_tar_members(tmp_path):
    """A digest-valid tar whose members escape the run dir (absolute
    or ``..`` paths, links) must be refused at landing, leaving no
    partial and no stray files."""
    import hashlib
    import io
    import tarfile

    from jepsen_tpu.fleet.artifacts import ArtifactStore

    buf = io.BytesIO()
    with tarfile.open(fileobj=buf, mode="w") as tf:
        info = tarfile.TarInfo("../escape.txt")
        payload = b"pwned"
        info.size = len(payload)
        tf.addfile(info, io.BytesIO(payload))
    evil = buf.getvalue()
    st = ArtifactStore(str(tmp_path))
    p = {"offset": 0, "total": len(evil),
         "digest": hashlib.sha256(evil).hexdigest(), "rel": "a-test/t9"}
    code, r = st.handle("r4", p, evil)
    assert code == 409 and "unpack" in r["error"]
    assert not os.path.exists(os.path.join(str(tmp_path), "escape.txt"))
    assert not os.path.exists(
        os.path.join(str(tmp_path), "a-test", "t9"))


def test_store_tests_skips_upload_staging_dirs(tmp_path):
    """ISSUE 13 satellite: dot-prefixed dirs are in-flight atomic-
    landing staging — `store.tests` (and the warehouse ingest riding
    on it) must not read them as run dirs."""
    base = str(tmp_path)
    _make_run_dir(base, "a-test", "t1")
    staged, _ = _make_run_dir(base, "a-test", ".incoming-t2")
    assert [os.path.basename(d) for d in store.tests(base=base)] == \
        ["t1"]
    # ... and the warehouse ingest sees exactly the landed run
    from jepsen_tpu.telemetry import warehouse

    wh = warehouse.Warehouse(os.path.join(base, "w.sqlite"))
    try:
        wh.ingest_store(base)
        _cols, rows = wh.query("SELECT dir FROM runs")
    finally:
        wh.close()
    assert len(rows) == 1 and ".incoming" not in rows[0][0]


_ARTIFACT_SERVER = """\
import json, sys
from jepsen_tpu import web
from jepsen_tpu.fleet import FleetCoordinator
base, port = sys.argv[1], int(sys.argv[2])
spec = {"name": "fed", "workloads": ["noop"], "seeds": [0],
        "opts": {"time-limit": 0.05}}
coord = FleetCoordinator(spec, base, lease_s=5.0)
web.serve(port=port, base=base, fleet=coord)
"""


def test_kill9_coordinator_mid_upload_resumable_then_lands(tmp_path):
    """THE federation crash pin: kill -9 the coordinator mid-upload;
    the staged partial survives, the restarted coordinator's probe
    answers the durable cursor, the worker's client resumes from it,
    and the landed dir is byte-equal to the source — never torn."""
    import signal

    from jepsen_tpu.fleet.artifacts import pack_run_dir

    cbase = str(tmp_path / "coord")
    wbase = str(tmp_path / "worker")
    os.makedirs(cbase)
    src, rel = _make_run_dir(wbase, extra=400)  # a few chunks' worth
    data, digest = pack_run_dir(src)

    def spawn(port):
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        proc = subprocess.Popen(
            [sys.executable, "-c", _ARTIFACT_SERVER, cbase, str(port)],
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
            env=env, cwd=os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))))
        deadline = time.time() + 60
        while time.time() < deadline:
            try:
                _get(f"http://127.0.0.1:{port}", "/fleet/status",
                     timeout=2)
                return proc
            except Exception:  # noqa: BLE001
                time.sleep(0.2)
        raise AssertionError("artifact server did not come up")

    import socket as _socket
    s = _socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    url = f"http://127.0.0.1:{port}"
    proc = spawn(port)
    chunk = 1024
    sent = 0
    try:
        # stream a strict prefix, then SIGKILL the server mid-upload
        while sent < min(3 * chunk, len(data) // 2):
            body = data[sent:sent + chunk]
            r = _post_raw(url, f"/fleet/artifact/up1?offset={sent}"
                          f"&total={len(data)}&digest={digest}"
                          f"&rel={rel}", body)
            assert r["received"] == sent + len(body)
            sent = r["received"]
    finally:
        proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=10)
    part = os.path.join(cbase, "fleet", "staging", "up1.tar")
    assert os.path.getsize(part) == sent  # the resumable partial
    proc = spawn(port)  # the restarted coordinator, same store
    try:
        w = FleetWorker(url, wbase, name="up-w")
        assert w.upload_artifact("up1", rel) is True
        assert w.uploads_done == 1
        assert _tree(os.path.join(cbase, rel)) == _tree(src)
        # idempotent re-upload after the fact (zombie attempt)
        assert w.upload_artifact("up1", rel) is True
    finally:
        proc.kill()
        proc.wait(timeout=10)


def _post_raw(url, path, body, timeout=10):
    req = urllib.request.Request(
        url + path, data=body,
        headers={"Content-Type": "application/octet-stream"},
        method="POST")
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read().decode() or "{}")


# -------------------- wall-clock t0 alignment (ISSUE 13 satellite)

def test_claim_carries_t0_anchor_and_skew_visible(tmp_path):
    """The first claim of a generation mints ONE absolute window
    anchor (t0), broadcast with the coordinator's `now` for
    clock-offset correction; the worker installs the corrected local
    anchor into opts["nemesis-t0"], its heartbeat ticks report it, and
    /fleet/status shows per-worker t0 skew vs the authoritative
    anchor."""
    from jepsen_tpu.campaign.plan import RunSpec

    base = str(tmp_path)
    coord = FleetCoordinator(SCHED_SPEC, base, lease_s=5.0)
    try:
        t_before = time.time()
        code, r1 = coord.claim({"worker": "wa"})
        assert code == 200
        w1 = r1["windows"]
        assert w1["t0"] >= t_before  # minted ahead: claim + lead
        assert abs(w1["now"] - time.time()) < 2.0
        # a second claim of the SAME generation shares the anchor;
        # a different generation mints its own (same value is fine —
        # anchors are per-generation, not globally unique)
        code, r2 = coord.claim({"worker": "wb"})
        g1, g2 = r1["spec"]["seed"], r2["spec"]["seed"]
        if g1 == g2:
            assert r2["windows"]["t0"] == w1["t0"]
        # worker install: corrected anchor lands in the cell opts and
        # in the tick payload
        w = FleetWorker("http://127.0.0.1:1", base, name="wa")
        rs = RunSpec.from_dict(r1["spec"])
        w._install_windows(rs, w1)
        assert abs(rs.opts["nemesis-t0"] - w1["t0"]) < 2.0  # same clock
        ticks = w._window_ticks(time.monotonic())
        assert ticks["t0"] == w.installed_windows["t0"]
        # the tick lands skew on status
        code, _hb = coord.heartbeat({
            "worker": "wa", "renew": [r1["spec"]["run_id"]],
            "windows": ticks})
        code, s = coord.status()
        ws = s["workers"]["wa"]["windows"]
        assert isinstance(ws["t0-skew"], float)
        assert ws["clock-synced"] is True  # same host, same clock
        assert s["nemesis-schedule"]["t0-by-gen"][str(g1)] == w1["t0"]
        assert str(g2) in s["nemesis-schedule"]["t0-by-gen"]
    finally:
        coord.close()

