"""Fleet-wide observability (ISSUE 14).

The tentpole contracts under test:

- **distributed trace context**: trace ids are a pure function of the
  run id (stable across retries/resends), travel the control plane in
  the ``Jepsen-Trace`` header, and land in span attrs, telemetry.json,
  index records, verifier session metadata, and the warehouse;
- **metrics federation**: workers push metric snapshots on the
  heartbeat channel; the coordinator's /metrics re-exposes them with
  ``host=`` labels plus fleet rollups, and the series RETIRE with
  worker liveness (cardinality stays flat under register/expire
  churn);
- **timeline stitching**: the warehouse's ``trace_spans`` view stitches
  fleet ledgers, run telemetry, and verifier sessions into one
  host-attributed waterfall per run (`cli obs timeline`, web
  ``/timeline/<run-id>``), with orphan detection;
- satellites: compile-cost attribution on device_call spans,
  artifact-staging GC, per-host verdict freshness on /fleet.
"""

import json
import os
import time
import urllib.request

import pytest

from jepsen_tpu import telemetry
from jepsen_tpu.telemetry import spans as spans_mod
from jepsen_tpu.telemetry import warehouse as wmod


# ------------------------------------------------- trace context core

def test_trace_id_is_pure_function_of_run_id():
    a = spans_mod.trace_id_for("append-s0-abc")
    assert a == spans_mod.trace_id_for("append-s0-abc")
    assert a != spans_mod.trace_id_for("append-s1-abc")
    assert len(a) == 32 and int(a, 16) >= 0


def test_mint_parse_header_round_trip():
    ctx = spans_mod.mint_trace("run-1")
    hdr = ctx.header()
    assert hdr == f"00-{ctx.trace_id}-{ctx.span_id}-01"
    back = spans_mod.parse_trace_header(hdr)
    assert back is not None
    assert back.trace_id == ctx.trace_id
    assert back.parent_id == ctx.span_id
    # malformed headers parse to None, never raise
    for bad in (None, "", "zz", "00-short-x-01", "00-" + "g" * 32
                + "-" + "0" * 16 + "-01"):
        assert spans_mod.parse_trace_header(bad) is None


def test_child_and_segment_contexts_deterministic():
    ctx = spans_mod.mint_trace("run-1")
    c1, c2 = ctx.child("claim"), ctx.child("claim")
    assert c1.span_id == c2.span_id and c1.parent_id == ctx.span_id
    seg = spans_mod.trace_context(ctx.trace_id, "run")
    assert seg.trace_id == ctx.trace_id
    assert seg.span_id != ctx.span_id


def test_trace_scope_is_thread_local_and_restores():
    assert spans_mod.current_trace() is None
    ctx = spans_mod.mint_trace("run-1")
    with spans_mod.trace_scope(ctx):
        assert spans_mod.current_trace() is ctx
        with spans_mod.trace_scope(None):
            assert spans_mod.current_trace() is None
        assert spans_mod.current_trace() is ctx
    assert spans_mod.current_trace() is None


def test_collector_stamps_trace_on_roots_and_snapshot():
    from jepsen_tpu.telemetry import export as tel_export

    coll = telemetry.Collector()
    coll.trace = spans_mod.trace_context(
        spans_mod.trace_id_for("run-1"), "run")
    with coll.span("run"):
        with coll.span("inner"):
            pass
    doc = tel_export.snapshot(coll)
    assert doc["trace"]["trace-id"] == spans_mod.trace_id_for("run-1")
    root = doc["spans"][0]
    assert root["attrs"]["trace_id"] == coll.trace.trace_id
    assert "trace_id" not in root["children"][0]["attrs"]


def test_core_run_derives_trace_from_campaign_run_id(tmp_path):
    from jepsen_tpu import core

    t = core.noop_test(name="tr")
    t["store-dir"] = str(tmp_path)
    t["telemetry"] = True
    t["campaign-run-id"] = "cell-7"
    done = core.run(t)
    d = __import__("jepsen_tpu.store", fromlist=["store"]).test_dir(done)
    with open(os.path.join(d, "telemetry.json")) as f:
        doc = json.load(f)
    assert doc["trace"]["trace-id"] == spans_mod.trace_id_for("cell-7")
    assert doc["meta"]["host"]
    assert doc["meta"]["run-id"] == "cell-7"


# --------------------------------------- compile-cost groundwork

def test_compile_vs_execute_attribution_on_device_call_spans():
    import numpy as np

    from jepsen_tpu import resilience
    from jepsen_tpu.resilience import guard

    guard.reset_compile_cache_stats()
    coll = telemetry.activate()
    try:
        x = np.zeros((4, 4))
        with coll.span("device-site") as sp:
            resilience.device_call("obs.test", lambda v: v, x)
            assert "compile_s" in sp.attrs and "execute_s" not in sp.attrs
            resilience.device_call("obs.test", lambda v: v, x)
            assert "execute_s" in sp.attrs
            # a NEW shape is a fresh miss
            resilience.device_call("obs.test", lambda v: v,
                                   np.zeros((8, 4)))
        st = guard.compile_cache_stats()
        assert st == {"entries": 2, "misses": 2}
        reg = telemetry.registry()
        assert reg.gauge("jit-cache-entries").value == 2
        assert reg.counter("compile-cache-miss", site="obs.test").value \
            == 2
    finally:
        telemetry.deactivate(coll)
        guard.reset_compile_cache_stats()


# --------------------------------------------- metrics federation

class _FakeQueue:
    def counts(self):
        return {"queued": 0, "claimed": 0, "done": 0}


def _mk_coordinator(tmp_path, lease_s=5.0):
    from jepsen_tpu.fleet import FleetCoordinator

    spec = {"name": "fed", "workloads": ["noop"], "seeds": [0],
            "opts": {}}
    return FleetCoordinator(spec, str(tmp_path), lease_s=lease_s)


def _hb(coord, worker, rows):
    code, out = coord.heartbeat({"worker": worker, "metrics": rows})
    assert code == 200, out


def test_federated_metrics_host_labels_rollups_and_retirement(tmp_path):
    from jepsen_tpu.telemetry import prometheus as prom

    coord = _mk_coordinator(tmp_path, lease_s=0.2)
    coord.register({"worker": "w1", "host": "h1"})
    coord.register({"worker": "w2", "host": "h2"})
    rows = [{"name": "worker-cells-done", "kind": "counter",
             "labels": {}, "value": 3},
            {"name": "worker-rss-bytes", "kind": "gauge",
             "labels": {}, "value": 1000.0},
            {"name": "worker-rss-peak-bytes", "kind": "gauge",
             "labels": {}, "value": 1500.0}]
    _hb(coord, "w1", rows)
    _hb(coord, "w2", [dict(rows[0], value=5)])
    expo = prom.exposition(base=str(tmp_path), fleet=coord)
    assert ('jepsen_fleet_host_worker_cells_done_total{host="w1"} 3'
            in expo)
    assert ('jepsen_fleet_host_worker_cells_done_total{host="w2"} 5'
            in expo)
    assert "jepsen_fleet_rollup_worker_cells_done_total 8" in expo
    assert 'jepsen_fleet_host_worker_rss_bytes{host="w1"} 1000' in expo
    assert ('jepsen_fleet_host_worker_rss_peak_bytes{host="w1"} 1500'
            in expo)
    assert "jepsen_fleet_fed_workers_reporting 2" in expo
    # liveness retirement: silence both workers past ALIVE_LEASES —
    # their series stop rendering without any explicit removal call
    with coord._lock:
        for c in coord.workers.values():
            c["last-seen"] -= 10.0
    expo = prom.exposition(base=str(tmp_path), fleet=coord)
    assert "jepsen_fleet_host_" not in expo
    assert "jepsen_fleet_fed_workers_reporting 0" in expo


def test_federation_cardinality_flat_under_worker_churn(tmp_path):
    """Satellite (CI): series count stays FLAT as workers churn
    through register/expire cycles — the exposition never grows with
    the number of workers that EVER existed, and the worker table
    itself is pruned past PRUNE_LEASES."""
    from jepsen_tpu.fleet import coordinator as coord_mod
    from jepsen_tpu.telemetry import prometheus as prom

    from jepsen_tpu.telemetry import alerts as alerts_mod

    coord = _mk_coordinator(tmp_path, lease_s=0.05)
    # an alert engine churning fire→resolve alongside the workers
    # (ISSUE 20 satellite): ALERTS series exist only while
    # pending/firing and retire on resolve — the exposition never
    # grows with the number of alerts that EVER fired
    eng = alerts_mod.AlertEngine(str(tmp_path), rules=alerts_mod.load_rules([
        {"name": "churn-alert", "kind": "threshold", "severity": "warn",
         "signal": "gauge:churn-x", "op": ">", "value": 0.5,
         "for": 0.0}]), sinks=[])

    def _n_alert_series(expo):
        return sum(1 for ln in expo.splitlines()
                   if ln.startswith("ALERTS{"))

    counts = []
    for gen in range(6):
        name = f"churn-{gen}"
        coord.register({"worker": name, "host": name})
        _hb(coord, name, [{"name": "worker-cells-done",
                           "kind": "counter", "labels": {},
                           "value": gen},
                          {"name": "worker-rss-peak-bytes",
                           "kind": "gauge", "labels": {},
                           "value": 1000 + gen}])
        now = 100.0 + 10.0 * gen
        eng.evaluate(signals={"gauge:churn-x": 1.0}, now=now)
        expo = prom.exposition(base=str(tmp_path), fleet=coord,
                               now=now)
        assert _n_alert_series(expo) == 1, expo
        assert ('ALERTS{alertname="churn-alert",severity="warn",'
                'state="firing"} 1') in expo
        eng.evaluate(signals={"gauge:churn-x": 0.0}, now=now + 1.0)
        expo = prom.exposition(base=str(tmp_path), fleet=coord,
                               now=now + 1.0)
        assert _n_alert_series(expo) == 0, expo
        counts.append(sum(1 for ln in expo.splitlines()
                          if ln.startswith("jepsen_fleet_host_")
                          and not ln.startswith("#")))
        # expire this generation before the next registers
        with coord._lock:
            for c in coord.workers.values():
                c["last-seen"] -= 100 * coord_mod.PRUNE_LEASES
    assert counts == [counts[0]] * len(counts), counts
    coord._update_gauges()  # prune pass
    with coord._lock:
        assert not coord.workers  # every churned worker pruned


def test_worker_metrics_snapshot_shape_and_cap(tmp_path):
    from jepsen_tpu.fleet import FleetWorker
    from jepsen_tpu.fleet.worker import MAX_PUSHED_SERIES

    w = FleetWorker("http://127.0.0.1:1", str(tmp_path), name="w")
    rows = w.metrics_snapshot()
    assert 0 < len(rows) <= MAX_PUSHED_SERIES
    names = {r["name"] for r in rows}
    assert {"worker-cells-done", "worker-uploads-done",
            "jit-cache-entries", "compile-cache-miss",
            "worker-rss-peak-bytes"} <= names
    for r in rows:
        assert r["kind"] in ("counter", "gauge")
        assert isinstance(r["value"], (int, float))
        assert isinstance(r["labels"], dict)


# ------------------------------------------------ staging GC

def test_artifact_staging_gc_expires_abandoned_partials(tmp_path):
    from jepsen_tpu.fleet.artifacts import ArtifactStore

    st = ArtifactStore(str(tmp_path))
    os.makedirs(st.staging, exist_ok=True)
    now = time.time()

    def stage(run_id, started, landed=False):
        with open(os.path.join(st.staging, run_id + ".tar"), "wb") as f:
            f.write(b"x" * 64)
        os.utime(os.path.join(st.staging, run_id + ".tar"),
                 (started, started))
        doc = {"run": run_id, "total": 128, "digest": "d",
               "rel": "a/t", "started": started}
        if landed:
            doc["landed"] = True
            doc["landed-at"] = started
        with open(os.path.join(st.staging, run_id + ".json"), "w") as f:
            json.dump(doc, f)

    stage("old-abandoned", now - 1000)
    stage("old-landed-marker", now - 1000, landed=True)
    stage("fresh", now - 10)
    out = st.gc(retention_s=100, now=now)
    assert out["removed"] == 2
    left = sorted(os.listdir(st.staging))
    assert left == ["fresh.json", "fresh.tar"]
    assert out["staging-bytes"] > 0
    assert telemetry.registry().gauge(
        "fleet-artifact-staging-bytes").value == out["staging-bytes"]
    # everything fresh: nothing removed, gauge still refreshed
    assert st.gc(retention_s=100, now=now)["removed"] == 0


# ----------------------------------------- warehouse timeline stitching

def _write_fleet_ledger(base, name="fl", run="r-0", worker="w0",
                        t0=1000.0, spans=None, requeue=False):
    d = os.path.join(str(base), "fleet")
    os.makedirs(d, exist_ok=True)
    path = os.path.join(d, name + ".jsonl")
    evs = [{"ev": "enqueue", "run": run, "ts": t0}]
    t = t0 + 0.5
    if requeue:
        evs.append({"ev": "claim", "run": run, "worker": "dead",
                    "ts": t})
        evs.append({"ev": "requeue", "run": run, "worker": "dead",
                    "reason": "lease-expired", "ts": t + 1.0})
        t += 1.5
    evs.append({"ev": "claim", "run": run, "worker": worker, "ts": t})
    rec = {"run": run, "valid?": True}
    if spans:
        rec["spans"] = spans
    evs.append({"ev": "complete", "run": run, "worker": worker,
                "record": rec, "ts": t + 2.0})
    with open(path, "a") as f:
        for ev in evs:
            f.write(json.dumps(ev) + "\n")
    return path


def test_fleet_ledger_stitches_trace_segments(tmp_path):
    path = _write_fleet_ledger(
        tmp_path, run="cell-1", worker="w0", requeue=True,
        spans={"fleet:claim-to-start": 0.25, "fleet:upload": 0.5,
               "check:la": 1.0})
    wh = wmod.open_or_create(str(tmp_path))
    wh.ingest_fleet_ledger(path, str(tmp_path))
    tl = wh.trace_timeline("cell-1")
    assert tl["trace-id"] == spans_mod.trace_id_for("cell-1")
    assert not tl["orphans"]
    by_name = {s["name"]: s for s in tl["spans"]}
    assert by_name["fleet:enqueue-wait"]["dur_s"] == 0.5
    assert by_name["fleet:attempt"]["host"] == "dead"
    assert by_name["fleet:execute"]["host"] == "w0"
    assert by_name["fleet:execute"]["dur_s"] == 2.0
    assert by_name["fleet:claim-to-start"]["dur_s"] == 0.25
    assert by_name["fleet:upload"]["dur_s"] == 0.5
    # non-fleet spans from the record do NOT leak into the timeline
    assert "check:la" not in by_name
    # re-ingest is idempotent (recompute, not accumulate)
    wh.ingest_fleet_ledger(path, str(tmp_path))
    _write_fleet_ledger(tmp_path, run="cell-2", t0=2000.0)
    wh.ingest_fleet_ledger(path, str(tmp_path))
    tl = wh.trace_timeline("cell-1")
    assert len(tl["spans"]) == len(by_name)


def test_run_dir_trace_rows_on_absolute_time(tmp_path):
    d = os.path.join(str(tmp_path), "a-test", "t1")
    os.makedirs(d)
    tid = spans_mod.trace_id_for("cell-9")
    doc = {
        "version": 1, "epoch_ns": 1_000_000_000_000,
        "perf0_ns": 500_000,
        "meta": {"name": "a-test", "host": "hostA",
                 "run-id": "cell-9"},
        "trace": {"trace-id": tid, "span-id": "s" * 16},
        "spans": [{"name": "run", "t0_ns": 500_000,
                   "dur_ns": 2_000_000_000, "attrs": {},
                   "children": [
                       {"name": "workload", "t0_ns": 600_000,
                        "dur_ns": 1_000_000_000, "attrs": {},
                        "children": [
                            {"name": "leaf", "t0_ns": 700_000,
                             "dur_ns": 1, "attrs": {},
                             "children": []}]}]}],
        "metrics": {"counters": [], "gauges": [], "histograms": []},
    }
    with open(os.path.join(d, "telemetry.json"), "w") as f:
        json.dump(doc, f)
    with open(os.path.join(d, "results.json"), "w") as f:
        json.dump({"valid?": True}, f)
    wh = wmod.open_or_create(str(tmp_path))
    wh.ingest_run_dir(d, str(tmp_path))
    tl = wh.trace_timeline("cell-9")
    by_name = {s["name"]: s for s in tl["spans"]}
    assert set(by_name) == {"run", "run:workload"}  # leaves excluded
    assert by_name["run"]["t0"] == 1000.0
    assert by_name["run"]["dur_s"] == 2.0
    assert by_name["run"]["host"] == "hostA"
    assert by_name["run:workload"]["run"] == "cell-9"


def test_orphan_spans_detected(tmp_path):
    # two ledgers complete the SAME run id... impossible via one
    # queue, but a mis-stitched artifact (wrong trace id) must show
    path = _write_fleet_ledger(tmp_path, run="cell-1")
    wh = wmod.open_or_create(str(tmp_path))
    wh.ingest_fleet_ledger(path, str(tmp_path))
    with wh._lock, wh.db:
        wh.db.execute(
            "INSERT INTO trace_spans(trace_id, origin, source, run, "
            "host, name, t0, t1, dur_s) VALUES (?, ?, ?, ?, ?, ?, ?, "
            "?, ?)",
            ("f" * 32, "bogus", "run", "cell-1", "hX", "run", 1000.0,
             1001.0, 1.0))
    tl = wh.trace_timeline("cell-1")
    assert len(tl["orphans"]) == 1
    assert tl["orphans"][0]["trace_id"] == "f" * 32
    assert all(s["trace_id"] == tl["trace-id"] for s in tl["spans"])


def test_verifier_session_snapshot_stitches(tmp_path):
    tid = spans_mod.trace_id_for("cell-3")
    vdir = os.path.join(str(tmp_path), "verifier", "s3")
    os.makedirs(vdir)
    with open(os.path.join(vdir, "session.json"), "w") as f:
        json.dump({"session": "s3", "state": "sealed", "opened": 100.0,
                   "updated": 105.5, "txns": 4, "ops": 10,
                   "segments": 1,
                   "config": {"trace-id": tid, "host": "w7"}}, f)
    wh = wmod.open_or_create(str(tmp_path))
    assert wh.ingest_verifier_sessions(str(tmp_path)) == 1
    tl = wh.trace_timeline("cell-3")
    assert [s["name"] for s in tl["spans"]] == ["verifier:live-session"]
    s = tl["spans"][0]
    assert s["host"] == "w7" and s["dur_s"] == 5.5
    # re-ingest upserts (no duplicate segments)
    wh.ingest_verifier_sessions(str(tmp_path))
    assert len(wh.trace_timeline("cell-3")["spans"]) == 1


def test_cli_obs_timeline_renders_and_flags_orphans(tmp_path, capsys):
    from jepsen_tpu import cli

    path = _write_fleet_ledger(tmp_path, run="cell-1", worker="w0")
    wh = wmod.open_or_create(str(tmp_path))
    wh.ingest_fleet_ledger(path, str(tmp_path))
    disp = cli.single_test_cmd(lambda o: {})
    argv = ["--store-dir", str(tmp_path), "obs", "timeline"]
    assert cli.run(disp, argv + ["cell-1"]) == 0
    out = capsys.readouterr().out
    assert "fleet:enqueue-wait" in out and "fleet:execute" in out
    assert spans_mod.trace_id_for("cell-1") in out
    # a trace id works as the key too
    assert cli.run(disp, argv
                   + [spans_mod.trace_id_for("cell-1")]) == 0
    capsys.readouterr()
    assert cli.run(disp, argv + ["no-such-run"]) == 2
    # orphans flip the exit code red
    with wh._lock, wh.db:
        wh.db.execute(
            "INSERT INTO trace_spans(trace_id, origin, source, run, "
            "host, name, t0, t1, dur_s) VALUES (?, ?, ?, ?, ?, ?, ?, "
            "?, ?)", ("e" * 32, "bogus", "run", "cell-1", None, "x",
                      1000.0, 1001.0, 1.0))
    capsys.readouterr()
    assert cli.run(disp, argv + ["cell-1"]) == 1
    assert "ORPHAN" in capsys.readouterr().out


def test_timeline_with_only_orphan_spans_reports_not_crashes(
        tmp_path, capsys):
    """A run whose every artifact disagrees with the derived trace id
    lays out ZERO stitched spans — the renderers must show the orphan
    diagnostic (exit 1 / the red section), not die on min() of an
    empty sequence."""
    from jepsen_tpu import cli

    wh = wmod.open_or_create(str(tmp_path))
    with wh._lock, wh.db:
        wh.db.execute(
            "INSERT INTO trace_spans(trace_id, origin, source, run, "
            "host, name, t0, t1, dur_s) VALUES (?, ?, ?, ?, ?, ?, ?, "
            "?, ?)", ("d" * 32, "bogus", "run", "lonely", "h", "run",
                      1000.0, 1001.0, 1.0))
    tl = wh.trace_timeline("lonely")
    assert not tl["spans"] and len(tl["orphans"]) == 1
    lay = wmod.Warehouse.timeline_layout(tl)
    assert lay["spans"] == [] and lay["hosts"] == []
    disp = cli.single_test_cmd(lambda o: {})
    rc = cli.run(disp, ["--store-dir", str(tmp_path), "obs",
                        "timeline", "lonely"])
    assert rc == 1
    assert "ORPHAN" in capsys.readouterr().out


def test_compile_attribution_lands_on_the_attempt_that_succeeds():
    """A transient failure on a shape's first attempt must NOT consume
    the first-sighting: the retry that actually pays the compile is
    the one booked as compile_s / compile-cache-miss."""
    import numpy as np

    from jepsen_tpu import resilience
    from jepsen_tpu.resilience import RetryPolicy, guard

    guard.reset_compile_cache_stats()
    coll = telemetry.activate()
    try:
        x = np.zeros((3, 3))
        calls = {"n": 0}

        def flaky(v):
            calls["n"] += 1
            if calls["n"] == 1:
                e = RuntimeError("RESOURCE_EXHAUSTED: transient")
                e.transient = True  # the classifier's explicit verdict
                raise e
            return v

        pol = RetryPolicy(max_attempts=3, base_delay_s=0.0,
                          max_delay_s=0.0)
        with coll.span("site") as sp:
            resilience.device_call("obs.flaky", flaky, x, policy=pol)
        assert calls["n"] == 2
        assert "compile_s" in sp.attrs  # the SUCCESSFUL attempt's wall
        assert guard.compile_cache_stats()["misses"] == 1
    finally:
        telemetry.deactivate(coll)
        guard.reset_compile_cache_stats()


# --------------------------------- the trace across a live fleet seam

@pytest.fixture()
def fleet_server(tmp_path):
    from jepsen_tpu import web
    from jepsen_tpu.fleet import FleetCoordinator
    from jepsen_tpu.verifier import VerifierService

    spec = {"name": "obsfl", "workloads": ["set"], "seeds": [0],
            "opts": {"time-limit": 0.1, "telemetry": True}}
    coord = FleetCoordinator(spec, str(tmp_path), lease_s=10.0)
    ver = VerifierService(str(tmp_path))
    srv = web.serve(port=0, base=str(tmp_path), background=True,
                    fleet=coord, verifier=ver)
    try:
        yield coord, ver, srv, f"http://127.0.0.1:{srv.server_address[1]}"
    finally:
        srv.server_close()
        ver.close()
        coord.close()


def test_fleet_worker_end_to_end_single_trace(fleet_server, tmp_path):
    """One cell through a real coordinator + worker over HTTP: the
    claim carries the trace, the record carries it, the run dir's
    telemetry carries it, gateable ``fleet:*`` spans land on the
    record, and the stitched timeline is single-trace with zero
    orphans (the acceptance, in-process edition)."""
    coord, _ver, _srv, url = fleet_server
    from jepsen_tpu.fleet import FleetWorker

    w = FleetWorker(url, str(tmp_path), name="obs-w0", poll_s=0.05)
    assert w.run() == 1
    run_id = next(iter(coord._done_ids))
    want = spans_mod.trace_id_for(run_id)
    rec = coord.idx.latest_by_run()[run_id]
    assert rec["trace"] == want
    assert "fleet:claim-to-start" in rec["spans"]
    assert "fleet:enqueue-wait" in rec["spans"]
    wh = wmod.open_or_create(str(tmp_path))
    wh.ingest_store(str(tmp_path))
    tl = wh.trace_timeline(run_id)
    assert tl["trace-id"] == want and not tl["orphans"]
    names = {s["name"] for s in tl["spans"]}
    assert {"fleet:enqueue-wait", "fleet:claim-to-start",
            "fleet:execute", "run:workload"} <= names
    assert {s["trace_id"] for s in tl["spans"]} == {want}
    # one worker = ONE timeline lane: the run dir's telemetry carries
    # the fleet worker name as its host, same as the ledger segments
    assert {s["host"] for s in tl["spans"]
            if s["source"] == "run"} == {"obs-w0"}
    # the web waterfall renders it
    with urllib.request.urlopen(f"{url}/timeline/{run_id}") as r:
        page = r.read().decode()
    assert want in page and "fleet:execute" in page


def test_verifier_adopts_trace_from_header(fleet_server, tmp_path):
    _coord, ver, _srv, url = fleet_server
    ctx = spans_mod.mint_trace("cell-x")
    req = urllib.request.Request(
        f"{url}/ingest/hsess?cursor=0",
        data=b'{"type": "invoke", "process": 0, "f": "txn", '
             b'"value": []}\n',
        headers={spans_mod.TRACE_HEADER: ctx.header()}, method="POST")
    with urllib.request.urlopen(req) as r:
        assert json.loads(r.read().decode())["ops"] == 1
    sessions = {s["session"]: s for s in ver.sessions()}
    assert sessions["hsess"]["config"]["trace-id"] == ctx.trace_id
    # persisted into the on-disk session.json (journal session meta)
    with open(os.path.join(str(tmp_path), "verifier", "hsess",
                           "session.json")) as f:
        assert json.load(f)["config"]["trace-id"] == ctx.trace_id


def test_fleet_status_surfaces_verdict_freshness_per_host(
        fleet_server, tmp_path):
    coord, ver, _srv, url = fleet_server
    coord.register({"worker": "fw1", "host": "fw1"})
    code, _ = ver.open("livesess", {"host": "fw1"})
    assert code == 200
    code, _ = ver.ingest(
        "livesess",
        b'{"type": "invoke", "process": 0, "f": "txn", "value": []}\n')
    assert code == 200
    with urllib.request.urlopen(url + "/fleet/status") as r:
        doc = json.loads(r.read().decode())
    assert "fw1" in doc["verifier-freshness"]
    row = doc["workers"]["fw1"]
    assert isinstance(row["verdict-freshness-s"], (int, float))
    assert row["live-sessions"] == 1
    # the HTML dashboard shows the column
    with urllib.request.urlopen(url + "/fleet") as r:
        page = r.read().decode()
    assert "verdict freshness" in page
