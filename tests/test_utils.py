"""Tests for jepsen_tpu.utils.core (reference util.clj semantics)."""

import random
import time

import pytest

from jepsen_tpu.history.ops import Op
from jepsen_tpu.utils import core as u


def test_majority_minority():
    assert u.majority(1) == 1
    assert u.majority(2) == 2
    assert u.majority(3) == 2
    assert u.majority(5) == 3
    assert u.minority(5) == 2
    assert u.minority(4) == 1


def test_relative_time_monotonic():
    u.init_time_origin()
    a = u.relative_time_nanos()
    b = u.relative_time_nanos()
    assert 0 <= a <= b


def test_timeout_completes():
    assert u.timeout(5.0, lambda: 42) == 42


def test_timeout_fires():
    with pytest.raises(u.TimeoutError_):
        u.timeout(0.05, lambda: time.sleep(5))


def test_timeout_value_on_timeout():
    assert u.timeout(0.05, lambda: time.sleep(5), on_timeout="late") == "late"


def test_fcatch():
    def boom():
        raise ValueError("x")

    res = u.fcatch(boom)()
    assert isinstance(res, ValueError)
    assert u.fcatch(lambda: 7)() == 7


def test_rand_distribution():
    rng = random.Random(0)
    assert u.rand_distribution({"distribution": "constant", "value": 3}) == 3
    for _ in range(100):
        x = u.rand_distribution(
            {"distribution": "uniform", "min": 1, "max": 2}, rng)
        assert 1 <= x <= 2
        z = u.rand_distribution({"distribution": "zipf", "n": 10}, rng)
        assert 0 <= z < 10
        e = u.rand_distribution({"distribution": "exponential", "mean": 5}, rng)
        assert e >= 0


def test_zipf_is_skewed():
    rng = random.Random(1)
    draws = [u.rand_distribution({"distribution": "zipf", "n": 100, "skew": 1.5},
                                 rng) for _ in range(2000)]
    assert draws.count(0) > draws.count(50)


def test_with_retry():
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise OSError("nope")
        return "ok"

    assert u.with_retry(flaky, retries=5, backoff=0.001) == "ok"
    assert len(calls) == 3

    with pytest.raises(OSError):
        u.with_retry(lambda: (_ for _ in ()).throw(OSError("always")),
                     retries=2, backoff=0.001)


def test_nemesis_intervals():
    ops = [
        Op(type="info", process=-1, f="start", value=None, time=1),
        Op(type="info", process=-1, f="stop", value=None, time=2),
        Op(type="info", process=-1, f="start", value=None, time=3),
    ]
    ivs = u.nemesis_intervals(ops)
    assert len(ivs) == 2
    assert ivs[0][0].time == 1 and ivs[0][1].time == 2
    assert ivs[1][0].time == 3 and ivs[1][1] is None


def test_coll():
    assert u.coll(None) == []
    assert u.coll(3) == [3]
    assert u.coll([1, 2]) == [1, 2]


def test_profiler_trace_writes_and_is_safe(tmp_path):
    import jax.numpy as jnp

    from jepsen_tpu.utils import profiling

    out = str(tmp_path / "tr")
    with profiling.trace(out):
        with profiling.annotate("span"):
            jnp.arange(8).sum().block_until_ready()
    import os as _os
    assert _os.path.isdir(out) and _os.listdir(out)  # trace files exist
    with profiling.trace(None):  # no-op path
        pass
