"""Linearizability checker tests: micro-histories with known verdicts plus
host-vs-device differential testing (reference knossos test style,
SURVEY.md §4)."""

import pytest

from jepsen_tpu.checkers.knossos import analysis, device_wgl, wgl
from jepsen_tpu.checkers.knossos.prep import prepare
from jepsen_tpu.history import history, invoke, ok, fail, info
from jepsen_tpu.models import (
    CASRegister,
    FIFOQueue,
    Mutex,
    Register,
    cas_register,
    register,
)
from jepsen_tpu.workloads import synth


def h_seq(*events):
    return history(list(events))


def test_trivially_linearizable():
    h = h_seq(
        invoke(0, "write", 1), ok(0, "write", 1),
        invoke(1, "read", None), ok(1, "read", 1),
    )
    assert wgl.check(h, register())["valid?"] is True


def test_stale_read_not_linearizable():
    h = h_seq(
        invoke(0, "write", 1), ok(0, "write", 1),
        invoke(0, "write", 2), ok(0, "write", 2),
        invoke(1, "read", None), ok(1, "read", 1),
    )
    assert wgl.check(h, register())["valid?"] is False


def test_concurrent_read_either_value():
    # read concurrent with a write may see old or new value
    h1 = h_seq(
        invoke(0, "write", 1), ok(0, "write", 1),
        invoke(1, "read", None),
        invoke(0, "write", 2),
        ok(1, "read", 1),
        ok(0, "write", 2),
    )
    h2 = h_seq(
        invoke(0, "write", 1), ok(0, "write", 1),
        invoke(1, "read", None),
        invoke(0, "write", 2),
        ok(1, "read", 2),
        ok(0, "write", 2),
    )
    assert wgl.check(h1, register())["valid?"] is True
    assert wgl.check(h2, register())["valid?"] is True


def test_cas_semantics():
    h = h_seq(
        invoke(0, "write", 1), ok(0, "write", 1),
        invoke(1, "cas", [1, 3]), ok(1, "cas", [1, 3]),
        invoke(2, "read", None), ok(2, "read", 3),
    )
    assert wgl.check(h, cas_register())["valid?"] is True
    h_bad = h_seq(
        invoke(0, "write", 1), ok(0, "write", 1),
        invoke(1, "cas", [2, 3]), ok(1, "cas", [2, 3]),  # cas of wrong old
    )
    assert wgl.check(h_bad, cas_register())["valid?"] is False


def test_failed_op_never_happened():
    h = h_seq(
        invoke(0, "write", 1), ok(0, "write", 1),
        invoke(1, "write", 9), fail(1, "write", 9),
        invoke(2, "read", None), ok(2, "read", 1),
    )
    assert wgl.check(h, register())["valid?"] is True


def test_info_write_may_or_may_not_apply():
    base = [
        invoke(0, "write", 1), ok(0, "write", 1),
        invoke(1, "write", 2), info(1, "write", 2),
    ]
    h_applied = h_seq(*base, invoke(2, "read", None), ok(2, "read", 2))
    h_not = h_seq(*base, invoke(2, "read", None), ok(2, "read", 1))
    assert wgl.check(h_applied, register())["valid?"] is True
    assert wgl.check(h_not, register())["valid?"] is True
    # but reading a value never written is invalid
    h_bad = h_seq(*base, invoke(2, "read", None), ok(2, "read", 7))
    assert wgl.check(h_bad, register())["valid?"] is False


def test_mutex():
    h = h_seq(
        invoke(0, "acquire", None), ok(0, "acquire", None),
        invoke(1, "acquire", None),
        invoke(0, "release", None), ok(0, "release", None),
        ok(1, "acquire", None),
    )
    assert wgl.check(h, Mutex())["valid?"] is True
    h_bad = h_seq(
        invoke(0, "acquire", None), ok(0, "acquire", None),
        invoke(1, "acquire", None), ok(1, "acquire", None),
    )
    assert wgl.check(h_bad, Mutex())["valid?"] is False


def test_fifo_queue():
    h = h_seq(
        invoke(0, "enqueue", 1), ok(0, "enqueue", 1),
        invoke(0, "enqueue", 2), ok(0, "enqueue", 2),
        invoke(1, "dequeue", None), ok(1, "dequeue", 1),
        invoke(1, "dequeue", None), ok(1, "dequeue", 2),
    )
    assert wgl.check(h, FIFOQueue())["valid?"] is True
    h_bad = h_seq(
        invoke(0, "enqueue", 1), ok(0, "enqueue", 1),
        invoke(0, "enqueue", 2), ok(0, "enqueue", 2),
        invoke(1, "dequeue", None), ok(1, "dequeue", 2),  # out of order
    )
    assert wgl.check(h_bad, FIFOQueue())["valid?"] is False


@pytest.mark.parametrize("seed", range(6))
def test_synth_register_linearizable(seed):
    h = synth.lin_register_history(n_ops=40, concurrency=3, seed=seed)
    assert wgl.check(h, cas_register())["valid?"] is True


def test_synth_register_stale_reads_detected():
    hits = 0
    for seed in range(8):
        h = synth.lin_register_history(n_ops=40, concurrency=3,
                                       stale_read_prob=0.4, seed=seed)
        if wgl.check(h, cas_register())["valid?"] is False:
            hits += 1
    assert hits >= 4  # stale reads usually break linearizability


@pytest.mark.parametrize("seed", range(8))
def test_device_vs_host_differential(seed):
    h = synth.lin_register_history(
        n_ops=30, concurrency=3,
        stale_read_prob=0.3 if seed % 2 else 0.0,
        info_prob=0.1, seed=seed)
    ops = prepare(h)
    r_host = wgl.check(ops, cas_register())
    r_dev = device_wgl.check(ops, cas_register(), max_frontier=4096)
    assert r_host["valid?"] == r_dev["valid?"], (seed, r_host, r_dev)


def test_analysis_competition():
    h = synth.lin_register_history(n_ops=30, concurrency=3, seed=1)
    res = analysis(h, cas_register())
    assert res["valid?"] is True
