"""Linearizability checker tests: micro-histories with known verdicts plus
host-vs-device differential testing (reference knossos test style,
SURVEY.md §4)."""
import os

import pytest

from jepsen_tpu.checkers.knossos import analysis, device_wgl, wgl
from jepsen_tpu.checkers.knossos.prep import prepare
from jepsen_tpu.history import history, invoke, ok, fail, info
from jepsen_tpu.models import (
    CASRegister,
    FIFOQueue,
    Mutex,
    Register,
    cas_register,
    register,
)
from jepsen_tpu.workloads import synth


def h_seq(*events):
    return history(list(events))


def test_trivially_linearizable():
    h = h_seq(
        invoke(0, "write", 1), ok(0, "write", 1),
        invoke(1, "read", None), ok(1, "read", 1),
    )
    assert wgl.check(h, register())["valid?"] is True


def test_stale_read_not_linearizable():
    h = h_seq(
        invoke(0, "write", 1), ok(0, "write", 1),
        invoke(0, "write", 2), ok(0, "write", 2),
        invoke(1, "read", None), ok(1, "read", 1),
    )
    assert wgl.check(h, register())["valid?"] is False


def test_concurrent_read_either_value():
    # read concurrent with a write may see old or new value
    h1 = h_seq(
        invoke(0, "write", 1), ok(0, "write", 1),
        invoke(1, "read", None),
        invoke(0, "write", 2),
        ok(1, "read", 1),
        ok(0, "write", 2),
    )
    h2 = h_seq(
        invoke(0, "write", 1), ok(0, "write", 1),
        invoke(1, "read", None),
        invoke(0, "write", 2),
        ok(1, "read", 2),
        ok(0, "write", 2),
    )
    assert wgl.check(h1, register())["valid?"] is True
    assert wgl.check(h2, register())["valid?"] is True


def test_cas_semantics():
    h = h_seq(
        invoke(0, "write", 1), ok(0, "write", 1),
        invoke(1, "cas", [1, 3]), ok(1, "cas", [1, 3]),
        invoke(2, "read", None), ok(2, "read", 3),
    )
    assert wgl.check(h, cas_register())["valid?"] is True
    h_bad = h_seq(
        invoke(0, "write", 1), ok(0, "write", 1),
        invoke(1, "cas", [2, 3]), ok(1, "cas", [2, 3]),  # cas of wrong old
    )
    assert wgl.check(h_bad, cas_register())["valid?"] is False


def test_failed_op_never_happened():
    h = h_seq(
        invoke(0, "write", 1), ok(0, "write", 1),
        invoke(1, "write", 9), fail(1, "write", 9),
        invoke(2, "read", None), ok(2, "read", 1),
    )
    assert wgl.check(h, register())["valid?"] is True


def test_info_write_may_or_may_not_apply():
    base = [
        invoke(0, "write", 1), ok(0, "write", 1),
        invoke(1, "write", 2), info(1, "write", 2),
    ]
    h_applied = h_seq(*base, invoke(2, "read", None), ok(2, "read", 2))
    h_not = h_seq(*base, invoke(2, "read", None), ok(2, "read", 1))
    assert wgl.check(h_applied, register())["valid?"] is True
    assert wgl.check(h_not, register())["valid?"] is True
    # but reading a value never written is invalid
    h_bad = h_seq(*base, invoke(2, "read", None), ok(2, "read", 7))
    assert wgl.check(h_bad, register())["valid?"] is False


def test_mutex():
    h = h_seq(
        invoke(0, "acquire", None), ok(0, "acquire", None),
        invoke(1, "acquire", None),
        invoke(0, "release", None), ok(0, "release", None),
        ok(1, "acquire", None),
    )
    assert wgl.check(h, Mutex())["valid?"] is True
    h_bad = h_seq(
        invoke(0, "acquire", None), ok(0, "acquire", None),
        invoke(1, "acquire", None), ok(1, "acquire", None),
    )
    assert wgl.check(h_bad, Mutex())["valid?"] is False


def test_fifo_queue():
    h = h_seq(
        invoke(0, "enqueue", 1), ok(0, "enqueue", 1),
        invoke(0, "enqueue", 2), ok(0, "enqueue", 2),
        invoke(1, "dequeue", None), ok(1, "dequeue", 1),
        invoke(1, "dequeue", None), ok(1, "dequeue", 2),
    )
    assert wgl.check(h, FIFOQueue())["valid?"] is True
    h_bad = h_seq(
        invoke(0, "enqueue", 1), ok(0, "enqueue", 1),
        invoke(0, "enqueue", 2), ok(0, "enqueue", 2),
        invoke(1, "dequeue", None), ok(1, "dequeue", 2),  # out of order
    )
    assert wgl.check(h_bad, FIFOQueue())["valid?"] is False


@pytest.mark.parametrize("seed", range(6))
def test_synth_register_linearizable(seed):
    h = synth.lin_register_history(n_ops=40, concurrency=3, seed=seed)
    assert wgl.check(h, cas_register())["valid?"] is True


def test_synth_register_stale_reads_detected():
    hits = 0
    for seed in range(8):
        h = synth.lin_register_history(n_ops=40, concurrency=3,
                                       stale_read_prob=0.4, seed=seed)
        if wgl.check(h, cas_register())["valid?"] is False:
            hits += 1
    assert hits >= 4  # stale reads usually break linearizability


@pytest.mark.parametrize("seed", range(8))
def test_device_vs_host_differential(seed):
    h = synth.lin_register_history(
        n_ops=30, concurrency=3,
        stale_read_prob=0.3 if seed % 2 else 0.0,
        info_prob=0.1, seed=seed)
    ops = prepare(h)
    r_host = wgl.check(ops, cas_register())
    r_dev = device_wgl.check(ops, cas_register(), max_frontier=4096)
    assert r_host["valid?"] == r_dev["valid?"], (seed, r_host, r_dev)


def test_analysis_competition():
    h = synth.lin_register_history(n_ops=30, concurrency=3, seed=1)
    res = analysis(h, cas_register())
    assert res["valid?"] is True


# ---- blocked device WGL: host-spilled frontier (SURVEY §7 host spill) ----

def test_device_wgl_blocked_above_singlejit_cap():
    # past the single-jit cutoff the blocked (host-spill) path must give
    # a definitive verdict (round-2 VERDICT item 7: the 4096-op wall)
    h = synth.lin_register_history(n_ops=1400, concurrency=3,
                                   info_prob=0.0, seed=5)
    ops = prepare(h)
    assert len(ops) > 1024
    r = device_wgl.check(ops, cas_register())
    assert r["valid?"] is True, r
    assert r.get("blocked") is True


@pytest.mark.slow  # ~106 s on this box — tier-1 budget hog (ISSUE 3)
def test_device_wgl_crash_heavy_dominance_prune():
    """VERDICT r03 item 8: crashed (`info`) ops used to multiply BFS
    frontiers until the device path ceded the regime to the host DFS.
    The crashed-op dominance prune (see device_wgl module doc) bounds
    it: a large crash-heavy history now completes on the device path
    with the host verdict."""
    h = synth.lin_register_history(n_ops=300, concurrency=6,
                                   info_prob=0.15, cas_prob=0.2, seed=5)
    ops = prepare(h)
    n_info = sum(1 for o in ops if o.is_info)
    assert n_info >= 20  # genuinely crash-heavy
    r_host = wgl.check(list(ops), cas_register())
    r_dev = device_wgl._blocked_and_check(list(ops), cas_register())
    assert r_dev["valid?"] == r_host["valid?"], (r_host, r_dev)


@pytest.mark.parametrize("seed", range(6))
def test_device_wgl_crash_heavy_differential(seed):
    # dominance prune differential: mixed info rates and stale reads,
    # device blocked search vs host DFS on every definitive verdict.
    # Each leg runs under a resilience deadline: seed 5's info-dense
    # history held the device leg >90s at the seed rev and blew the
    # tier-1 budget — a bounded leg returns unknown (skipping the
    # comparison) instead of stalling the suite.
    from jepsen_tpu.checkers.knossos.search import Search

    h = synth.lin_register_history(
        n_ops=120, concurrency=5,
        stale_read_prob=0.25 if seed % 2 else 0.0,
        info_prob=(0.1, 0.2, 0.3)[seed % 3], seed=seed)
    ops = prepare(h)
    r_host = wgl.check(list(ops), cas_register(),
                       ctl=Search(deadline_s=20))
    r_dev = device_wgl._blocked_and_check(list(ops), cas_register(),
                                          ctl=Search(deadline_s=20))
    for r in (r_host, r_dev):
        if r["valid?"] == "unknown" and r.get("reason") == "aborted":
            # a deadline-driven abort must say so (resilience contract)
            assert r.get("error") == "deadline-exceeded", r
    if r_host["valid?"] != "unknown" and r_dev["valid?"] != "unknown":
        assert r_dev["valid?"] == r_host["valid?"], (seed, r_host, r_dev)


def test_device_wgl_blocked_invalid_detected():
    h = synth.lin_register_history(n_ops=1400, concurrency=3,
                                   stale_read_prob=0.3, info_prob=0.0,
                                   seed=3)
    ops = prepare(h)
    r_host = wgl.check(ops, cas_register())
    r_dev = device_wgl.check(ops, cas_register())
    assert r_dev["valid?"] == r_host["valid?"], (r_host, r_dev)
    assert r_dev.get("blocked") is True


@pytest.mark.skipif(not os.environ.get("JT_SCALE_TESTS"),
                    reason="set JT_SCALE_TESTS=1: ~minutes; proves the "
                           "old 4096-op device-WGL wall is gone")
def test_device_wgl_blocked_beyond_old_4096_wall():
    h = synth.lin_register_history(n_ops=5000, concurrency=3,
                                   info_prob=0.0, seed=5)
    ops = prepare(h)
    assert len(ops) > 4096
    r = device_wgl.check(ops, cas_register())
    assert r["valid?"] is True, r
    assert r.get("blocked") is True


@pytest.mark.slow  # 4 legs x ~55-69 s each — tier-1 budget hogs (ISSUE 3)
@pytest.mark.parametrize("seed", range(4))
def test_device_wgl_blocked_differential_small_frontier(seed):
    # tiny max_frontier forces multi-block waves + host spill on a
    # history the single-jit path handles; verdicts must agree
    h = synth.lin_register_history(
        n_ops=60, concurrency=4,
        stale_read_prob=0.3 if seed % 2 else 0.0, seed=seed)
    ops = prepare(h)
    r_single = device_wgl.check(ops, cas_register(), max_frontier=16384)
    r_blocked = device_wgl._blocked_and_check(ops, cas_register(),
                                              max_frontier=64)
    assert r_blocked["valid?"] == r_single["valid?"], (seed, r_single,
                                                       r_blocked)
    assert r_blocked.get("blocked") is True


def test_device_wgl_blocked_matches_exact_bfs_frontiers():
    # exactness evidence stronger than verdict equality: the blocked
    # search's per-wave unique-config counts must equal an exact Python
    # set-BFS over (linearized-set, state) configs
    from jepsen_tpu.checkers.knossos.memo import memoize
    from jepsen_tpu.checkers.knossos.prep import NEVER

    h = synth.lin_register_history(n_ops=60, concurrency=3,
                                   info_prob=0.0, seed=7)
    ops = prepare(h)
    memo = memoize(cas_register(), ops)
    n = len(ops)
    invokes = [o.invoke_pos for o in ops]
    returns = [min(o.return_pos, 2 ** 29) for o in ops]
    level = {(0, memo.init_state)}
    ref_sizes = []
    for _ in range(n):
        nxt = set()
        for (S, st) in level:
            minret = min((returns[i] for i in range(n)
                          if not (S >> i) & 1), default=10 ** 9)
            for i in range(n):
                if (S >> i) & 1 or invokes[i] >= minret:
                    continue
                s2 = int(memo.table[st, memo.op_sym[i]])
                if s2 >= 0:
                    nxt.add((S | (1 << i), s2))
        if not nxt:
            break
        ref_sizes.append(len(nxt))
        level = nxt

    r = device_wgl._blocked_and_check(ops, cas_register())
    assert r["valid?"] is True
    # re-run wave-by-wave via the internal API to capture sizes: patch
    # the wave boundary by observing pad_block chunks is fragile; instead
    # verify total explored equals the BFS total via max_configs probing
    total_ref = sum(ref_sizes)
    r2 = device_wgl._blocked_and_check(ops, cas_register(),
                                       max_configs=total_ref + 10)
    assert r2["valid?"] is True  # succeeds within the exact BFS budget


def test_standalone_cli_json(tmp_path):
    import json as _json

    from jepsen_tpu.checkers.knossos import cli as kcli

    good = [
        {"type": "invoke", "process": 0, "f": "write", "value": 1},
        {"type": "ok", "process": 0, "f": "write", "value": 1},
        {"type": "invoke", "process": 1, "f": "read", "value": None},
        {"type": "ok", "process": 1, "f": "read", "value": 1},
    ]
    bad = good[:2] + [
        {"type": "invoke", "process": 1, "f": "read", "value": None},
        {"type": "ok", "process": 1, "f": "read", "value": 7},
    ]
    g = tmp_path / "good.json"
    b = tmp_path / "bad.json"
    g.write_text(_json.dumps(good))
    b.write_text(_json.dumps(bad))
    assert kcli.main([str(g), "--model", "register"]) == 0
    assert kcli.main([str(b), "--model", "register",
                      "--algorithm", "wgl"]) == 1


def test_competition_races_device_and_host_legs():
    """Large-history auto analysis races linear/wgl/device concurrently
    (reference competition semantics).  Regression: the pre-race design
    ran the device leg FIRST and sequentially, so this 1300-op 185-info
    history — where the crashed-op frontier blowup holds the device BFS
    for >25 min — stalled the whole analysis even though the host DFS
    answers in well under a second."""
    import time

    h = synth.lin_register_history(n_ops=1300, concurrency=6,
                                   info_prob=0.15, cas_prob=0.2, seed=5)
    t0 = time.time()
    r = analysis(h, cas_register(), deadline_s=300)
    wall = time.time() - t0
    assert r["valid?"] is True, r
    # the functional regression is the verdict above; the wall bound is
    # only meaningful with a core to spare — on a single-core box the
    # device leg's XLA compile competes with the host DFS for the one
    # core and the bound flakes (ADVICE r04)
    if (os.cpu_count() or 1) > 1:
        assert wall < 120, f"race should settle fast, took {wall:.0f}s"


def test_device_wgl_ctl_abort():
    """The blocked device search polls `ctl` between waves/blocks."""
    from jepsen_tpu.checkers.knossos.search import Search

    h = synth.lin_register_history(n_ops=1300, concurrency=6,
                                   info_prob=0.15, cas_prob=0.2, seed=5)
    ops = prepare(h)
    ctl = Search(deadline_s=5)
    r = device_wgl._blocked_and_check(list(ops), cas_register(), ctl=ctl)
    assert r["valid?"] == "unknown"
    assert r.get("reason") == "aborted"


def test_competition_ctl_reusable_across_analyses():
    """A caller-supplied ctl is never aborted by the race itself: one
    Search can bound a whole campaign of analyses."""
    from jepsen_tpu.checkers.knossos.search import Search

    ctl = Search(deadline_s=600)
    for seed in (1, 2):
        h = synth.lin_register_history(n_ops=400, concurrency=4,
                                       seed=seed)
        r = analysis(h, cas_register(), ctl=ctl)
        assert r["valid?"] is True, r
    assert not ctl.aborted()


def test_competition_deadline_covers_small_history_fallback():
    """deadline_s is anchored at analysis entry and reaches the device
    fallback on the <=256-op path (review finding: the fallback used to
    run unbounded after the host race burned the deadline).  An
    already-expired deadline must bound the WHOLE analysis — race AND
    fallback — to polling latency, not to a full blocked search."""
    import time

    from jepsen_tpu.checkers.knossos.search import ChildSearch, Search

    root = Search(deadline_s=600)
    child = ChildSearch(root)
    assert not child.aborted()
    root.abort()
    assert child.aborted()          # parent abort propagates
    assert root.aborted()
    # end-to-end: a tiny deadline on a small history returns promptly
    # from both the host race and the ctl-carrying device fallback
    h = synth.lin_register_history(n_ops=200, concurrency=4, seed=7)
    t0 = time.time()
    r = analysis(h, cas_register(), deadline_s=0.001)
    wall = time.time() - t0
    assert wall < 30, f"expired deadline should bound analysis, {wall:.0f}s"
    # a leg may legitimately WIN before the expired deadline is noticed
    # (wgl answers a valid 200-op history in under one poll interval);
    # the contract under test is boundedness, not which outcome
    assert r["valid?"] in (True, "unknown"), r


def test_child_search_explored_forwards_to_parent():
    """A campaign polling ITS Search handle sees progress made under
    derived children; attaching a child never resets the parent."""
    from jepsen_tpu.checkers.knossos.search import ChildSearch, Search

    p = Search()
    p.explored = 500
    c = ChildSearch(p)
    assert p.explored == 500
    c.explored += 100
    assert p.explored == 600 and c.explored == 600
    g = ChildSearch(c)
    g.explored += 1
    assert p.explored == 601
    solo = ChildSearch(None)
    solo.explored += 7
    assert solo.explored == 7
