"""rw-register checker tests (reference rw_register_test.clj style)."""

import pytest

from jepsen_tpu.checkers.elle import rw_register
from jepsen_tpu.history import history, invoke, ok, fail, info
from jepsen_tpu.workloads import synth


def concurrent_history(*txns):
    inv, comp = [], []
    for i, (mops_inv, mops_ok) in enumerate(txns):
        inv.append(invoke(i, "txn", mops_inv))
        if mops_ok == "fail":
            comp.append(fail(i, "txn", mops_inv))
        else:
            comp.append(ok(i, "txn", mops_ok))
    return history(inv + comp)


def test_valid_simple():
    h = concurrent_history(
        ([["w", "x", 1]], [["w", "x", 1]]),
        ([["r", "x", None]], [["r", "x", 1]]),
    )
    res = rw_register.check(h, ["serializable"])
    assert res["valid?"] is True, res


def test_g1a():
    h = concurrent_history(
        ([["w", "x", 1]], "fail"),
        ([["r", "x", None]], [["r", "x", 1]]),
    )
    res = rw_register.check(h, ["serializable"])
    assert res["valid?"] is False
    assert "G1a" in res["anomaly-types"]


def test_g1b_intermediate():
    h = concurrent_history(
        ([["w", "x", 1], ["w", "x", 2]], [["w", "x", 1], ["w", "x", 2]]),
        ([["r", "x", None]], [["r", "x", 1]]),
    )
    res = rw_register.check(h, ["serializable"])
    assert "G1b" in res["anomaly-types"]


def test_internal():
    h = concurrent_history(
        ([["w", "x", 1], ["r", "x", None]],
         [["w", "x", 1], ["r", "x", 9]]),
        ([["w", "x", 9]], [["w", "x", 9]]),
    )
    res = rw_register.check(h, ["serializable"])
    assert "internal" in res["anomaly-types"]


def test_lost_update():
    # T0 and T1 both read x=nil then write -> both updated the same version
    h = concurrent_history(
        ([["r", "x", None], ["w", "x", 1]],
         [["r", "x", None], ["w", "x", 1]]),
        ([["r", "x", None], ["w", "x", 2]],
         [["r", "x", None], ["w", "x", 2]]),
    )
    res = rw_register.check(h, ["snapshot-isolation"])
    assert res["valid?"] is False
    assert "lost-update" in res["anomaly-types"]


def test_g1c_wr_cycle():
    # T0 writes x=1 and reads y=9; T1 writes y=9 and reads x=1
    h = concurrent_history(
        ([["w", "x", 1], ["r", "y", None]],
         [["w", "x", 1], ["r", "y", 9]]),
        ([["w", "y", 9], ["r", "x", None]],
         [["w", "y", 9], ["r", "x", 1]]),
    )
    res = rw_register.check(h, ["read-committed"])
    assert res["valid?"] is False
    assert "G1c" in res["anomaly-types"]


def test_write_skew_g2():
    # classic write skew via rw edges from nil reads
    h = concurrent_history(
        ([["r", "x", None], ["w", "y", 10]],
         [["r", "x", None], ["w", "y", 10]]),
        ([["r", "y", None], ["w", "x", 1]],
         [["r", "y", None], ["w", "x", 1]]),
    )
    res = rw_register.check(h, ["serializable"])
    assert res["valid?"] is False
    assert "G2-item" in res["anomaly-types"]
    res_si = rw_register.check(h, ["snapshot-isolation"])
    assert res_si["valid?"] is True


def test_realtime_strict_only():
    # read of a value written by a txn that invoked after the reader done
    h = history([
        invoke(0, "txn", [["r", "x", None]]),
        ok(0, "txn", [["r", "x", 1]]),
        invoke(1, "txn", [["w", "x", 1]]),
        ok(1, "txn", [["w", "x", 1]]),
    ])
    res = rw_register.check(h, ["strict-serializable"])
    assert res["valid?"] is False
    assert "G1c-realtime" in res["anomaly-types"]
    res2 = rw_register.check(h, ["serializable"])
    assert res2["valid?"] is True


@pytest.mark.parametrize("seed", range(6))
def test_synth_valid(seed):
    h = synth.rw_history(n_txns=150, n_keys=6, concurrency=5,
                         fail_prob=0.05, info_prob=0.05, seed=seed)
    res = rw_register.check(h, ["strict-serializable"])
    assert res["valid?"] is True, (res["anomaly-types"], res["anomalies"])


def test_synth_device_host_same():
    for seed in range(4):
        h = synth.rw_history(n_txns=120, n_keys=5, seed=seed)
        r_dev = rw_register.check(h, ["strict-serializable"],
                                  use_device=True)
        r_host = rw_register.check(h, ["strict-serializable"],
                                   use_device=False)
        assert r_dev["valid?"] == r_host["valid?"]
        assert r_dev["anomaly-types"] == r_host["anomaly-types"]


def test_duplicate_writes_invalidate():
    # two committed writes of the same value break the unique-write
    # contract: the history must be invalid, not just annotated
    h = concurrent_history(
        ([["w", "x", 1]], [["w", "x", 1]]),
        ([["w", "x", 1]], [["w", "x", 1]]),
    )
    res = rw_register.check(h, ["serializable"])
    assert res["valid?"] is False
    assert "duplicate-writes" in res["anomaly-types"]


def test_aborted_duplicate_does_not_fabricate_g1a():
    # a FAILED duplicate of a committed write must not make readers of the
    # committed value look like aborted reads
    h = concurrent_history(
        ([["w", "x", 1]], "fail"),
        ([["w", "x", 1]], [["w", "x", 1]]),
        ([["r", "x", None]], [["r", "x", 1]]),
    )
    res = rw_register.check(h, ["serializable"])
    assert "G1a" not in res["anomaly-types"], res
    assert "duplicate-writes" in res["anomaly-types"]


def test_explainer_rw_register_edges_justified():
    h = concurrent_history(
        ([["w", "x", 1], ["r", "y", None]],
         [["w", "x", 1], ["r", "y", 9]]),
        ([["w", "y", 9], ["r", "x", None]],
         [["w", "y", 9], ["r", "x", 1]]),
    )
    res = rw_register.check(h, ["read-committed"])
    cyc = res["anomalies"]["G1c"][0]["cycle"]
    for e in cyc:
        assert e.get("why"), e
        if e["rel"] in ("ww", "wr", "rw"):
            assert e.get("key") is not None, e
    wr = [e for e in cyc if e["rel"] == "wr"]
    assert wr and wr[0]["value"] in (1, 9)


# ---- fused device rw check (device_rw.py) --------------------------------

def _host_flags(h):
    """Host-checker verdicts mapped to the device bit granularity."""
    res = rw_register.check(h, ["strict-serializable"], use_device=False)
    at = set(res["anomaly-types"])
    base = {"G0", "G1c", "G-single", "G2-item", "G-nonadjacent"}
    proc = {a + "-process" for a in base}
    rt_ = {a + "-realtime" for a in base}
    return res, {
        "counts": {n: (n in at) for n in
                   ("duplicate-writes", "internal", "G1a", "G1b",
                    "lost-update", "cyclic-versions")},
        "cycles": {
            "G0": "G0" in at,
            "G1c": bool({"G0", "G1c"} & at),
            "G2-family": bool(base & at),
            "G2-family-process": bool((base | proc) & at),
            "G2-family-realtime": bool((base | rt_) & at),
        },
    }


def _assert_device_matches_host(h):
    from jepsen_tpu.checkers.elle import device_rw
    from jepsen_tpu.history.soa import pack_txns

    res_host, want = _host_flags(h)
    got = device_rw.check(pack_txns(h, "rw-register"))
    assert got["exact"] is True
    assert got["valid?"] == res_host["valid?"], (got, res_host)
    for n, flag in want["counts"].items():
        assert (got["counts"][n] > 0) == flag, (n, got, res_host)
    for n, flag in want["cycles"].items():
        assert got["cycles"][n] == flag, (n, got, res_host)


@pytest.mark.parametrize("seed", range(6))
def test_device_rw_differential_valid(seed):
    h = synth.rw_history(n_txns=150, n_keys=6, concurrency=5,
                         fail_prob=0.05, info_prob=0.05, seed=seed)
    _assert_device_matches_host(h)


def test_device_rw_differential_anomalies():
    cases = [
        # wr cycle (G1c)
        concurrent_history(
            ([["w", "x", 1], ["r", "y", None]],
             [["w", "x", 1], ["r", "y", 9]]),
            ([["w", "y", 9], ["r", "x", None]],
             [["w", "y", 9], ["r", "x", 1]]),
        ),
        # write skew (G2-item via rw edges)
        concurrent_history(
            ([["r", "x", None], ["w", "y", 10]],
             [["r", "x", None], ["w", "y", 10]]),
            ([["r", "y", None], ["w", "x", 1]],
             [["r", "y", None], ["w", "x", 1]]),
        ),
        # G1a: read of failed write
        concurrent_history(
            ([["w", "x", 5]], "fail"),
            ([["r", "x", None]], [["r", "x", 5]]),
        ),
        # internal: read contradicts own write
        concurrent_history(
            ([["w", "x", 7], ["r", "x", None]],
             [["w", "x", 7], ["r", "x", 3]]),
            ([["w", "x", 3]], [["w", "x", 3]]),
        ),
        # lost update: two txns read same version then write
        concurrent_history(
            ([["r", "x", None], ["w", "x", 1]],
             [["r", "x", None], ["w", "x", 1]]),
            ([["r", "x", None], ["w", "x", 2]],
             [["r", "x", None], ["w", "x", 2]]),
        ),
        # duplicate writes
        concurrent_history(
            ([["w", "x", 1]], [["w", "x", 1]]),
            ([["w", "x", 1]], [["w", "x", 1]]),
        ),
    ]
    for i, h in enumerate(cases):
        try:
            _assert_device_matches_host(h)
        except AssertionError as e:
            raise AssertionError(f"case {i}: {e}") from e


def test_device_rw_realtime_cycle():
    # read-before-write in real time: strict-serializable violation only
    h = history([
        invoke(0, "txn", [["r", "x", None]]),
        ok(0, "txn", [["r", "x", 1]]),
        invoke(1, "txn", [["w", "x", 1]]),
        ok(1, "txn", [["w", "x", 1]]),
    ])
    _assert_device_matches_host(h)


def test_packed_rw_history_valid_and_matches_host():
    from jepsen_tpu.checkers.elle import device_rw

    p = synth.packed_rw_history(n_txns=2000, n_keys=50, seed=3)
    got = device_rw.check(p)
    assert got["valid?"] is True, got
    res_host = rw_register.check(p, ["strict-serializable"],
                                 use_device=False)
    assert res_host["valid?"] is True, res_host["anomaly-types"]


def test_fused_fast_path_on_large_history(monkeypatch):
    # above the threshold a clean history returns via the fused device
    # path without host inference; a seeded anomaly still gets the full
    # host report
    from jepsen_tpu.checkers.elle import rw_register as rw

    monkeypatch.setattr(rw, "FUSED_MIN_TXNS", 1000)
    p = synth.packed_rw_history(n_txns=2000, n_keys=50, seed=4)
    res = rw.check(p, ["strict-serializable"])
    assert res["valid?"] is True
    assert res.get("fused-device") is True

    h = concurrent_history(
        ([["w", "x", 1], ["r", "y", None]],
         [["w", "x", 1], ["r", "y", 9]]),
        ([["w", "y", 9], ["r", "x", None]],
         [["w", "y", 9], ["r", "x", 1]]),
    )
    # small history: host path with full anomaly report regardless
    res_bad = rw.check(h, ["read-committed"])
    assert res_bad["valid?"] is False
    assert "G1c" in res_bad["anomalies"]
