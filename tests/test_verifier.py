"""Always-on verifier tests (ISSUE 7).

The contracts under test:

- **Equality**: for every workload shape (valid and invalid histories,
  fail/info-laden chaos ones included), sealing a streamed session
  yields the same ``valid?`` and anomaly set as the batch checker on
  the concatenated history — and the dependency-edge counts agree, so
  the incremental graph IS the batch graph.
- **Segmentation independence**: the rolling state is a function of
  the op sequence, not of how it was chopped — any segmentation
  reaches the identical verdict digest.
- **Durability / rudeness**: kill -9 the serve daemon mid-session and
  restart → journal replay reaches the identical digest; a torn final
  journal line is dropped; a client re-append after a stale cursor ack
  is idempotent.
- **Resilience**: the sweep honors deadlines (unknown +
  deadline-exceeded, never a hang) and the guarded ingest/sweep seams
  retry injected transients.
- **Speed** (slow-marked): incremental re-check of a +1k segment on a
  100k-txn session is >= 10x faster than a full batch re-check,
  span-cited.
"""

import json
import os
import signal
import socket
import subprocess
import sys
import time
import urllib.error
import urllib.request

import pytest

from jepsen_tpu import telemetry, web
from jepsen_tpu.checkers.elle import oracle
from jepsen_tpu.history.soa import pack_txns
from jepsen_tpu.resilience import Deadline, faults
from jepsen_tpu.verifier import (
    SessionJournal,
    VerdictMismatch,
    VerifierService,
    VerifierSession,
    iter_packed_segments,
    split_segment,
    verdict_digest,
)
from jepsen_tpu.workloads import synth

MODELS = ("strict-serializable",)


# ------------------------------------------------------------ helpers

def _ops(h):
    return [op.to_dict() for op in h]


def _jsonl(h) -> bytes:
    return b"".join(json.dumps(d).encode() + b"\n" for d in _ops(h))


def _feed(ses, ops, seg, rolling=True):
    for i in range(0, len(ops), seg):
        ses.append_ops(ops[i:i + seg])
        if rolling:
            ses.verdict()
    return ses


def _assert_equal(batch, inc, edges=True):
    assert batch["valid?"] == inc["valid?"]
    assert batch["anomaly-types"] == inc["anomaly-types"]
    if edges:
        assert batch.get("edge-counts") == inc.get("edge-counts")


# ------------------------------------------- incremental == batch

@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_valid_history_equality(seed):
    h = synth.la_history(n_txns=200, n_keys=6, concurrency=5, seed=seed)
    batch = oracle.check(pack_txns(h, "list-append"), MODELS)
    ses = _feed(VerifierSession("t", MODELS), _ops(h), 37)
    _assert_equal(batch, ses.verdict())
    assert ses.seal()["equal"] is True


@pytest.mark.parametrize("inject", ["inject_g1a", "inject_g1b",
                                    "inject_wr_cycle", "inject_rw_cycle"])
@pytest.mark.parametrize("seed", [0, 4])
def test_invalid_history_equality(inject, seed):
    h = synth.la_history(n_txns=200, n_keys=5, concurrency=5, seed=seed,
                         fail_prob=0.05)
    assert getattr(synth, inject)(h)
    batch = oracle.check(pack_txns(h, "list-append"), MODELS)
    assert batch["valid?"] is False
    ses = _feed(VerifierSession("t", MODELS), _ops(h), 23)
    _assert_equal(batch, ses.verdict())
    assert ses.seal()["equal"] is True


def test_chaos_faulted_history_equality():
    """Fail/info-dense histories (the fault-injected workload shape):
    same verdict through the stream as through the batch checker."""
    for seed in (0, 1, 2):
        h = synth.la_history(n_txns=250, n_keys=4, concurrency=8,
                             seed=seed, fail_prob=0.15, info_prob=0.15)
        if seed == 1:
            synth.inject_rw_cycle(h)
        batch = oracle.check(pack_txns(h, "list-append"), MODELS)
        ses = _feed(VerifierSession("t", MODELS), _ops(h), 11)
        _assert_equal(batch, ses.verdict())
        ses.seal()


def test_segmentation_independence_digest():
    """The rolling state is a function of the op SEQUENCE: any
    segmentation (1-op, 7-op, one-shot) reaches the same digest."""
    h = synth.la_history(n_txns=120, n_keys=4, seed=5)
    synth.inject_wr_cycle(h)
    digests = set()
    for seg in (1, 7, 10_000):
        ses = _feed(VerifierSession("t", MODELS), _ops(h), seg)
        digests.add(verdict_digest(ses.verdict()))
    assert len(digests) == 1


def test_replaced_version_order_retraction():
    """A later, longer-but-incompatible read replaces a key's inferred
    version order; edges derived from the old order are retracted and
    the full-resweep path converges on the batch verdict."""
    from jepsen_tpu.history.ops import INVOKE, OK, History, Op

    def txn(p, mops):
        return [Op(type=INVOKE, process=p, f="txn", value=mops),
                Op(type=OK, process=p, f="txn", value=mops)]

    ops = []
    ops += txn(0, [["append", "x", 1], ["append", "x", 2]])
    ops += txn(1, [["r", "x", [1, 2]]])
    ops += txn(0, [["append", "x", 3], ["append", "x", 4]])
    ops += txn(1, [["r", "x", [1, 3, 4]]])  # incompatible with [1,2]
    h = History(ops)
    batch = oracle.check(pack_txns(h, "list-append"), MODELS)
    assert "incompatible-order" in batch["anomaly-types"]
    ses = _feed(VerifierSession("t", MODELS), _ops(h), 2)
    _assert_equal(batch, ses.verdict())
    ses.seal()


def test_rolling_deltas_and_first_seen():
    h = synth.la_history(n_txns=100, n_keys=5, seed=3)
    ses = VerifierSession("t", MODELS)
    ses.append_ops(_ops(h))
    v0 = ses.verdict()
    assert v0["anomaly-types"] == [] and v0["new"] == []
    # a fresh wr cycle appended later: A reads B's write, B reads A's
    a = [["append", "zz", 9001], ["r", "zz2", [9002]]]
    b = [["append", "zz2", 9002], ["r", "zz", [9001]]]
    ses.append_ops([
        {"type": "invoke", "process": 0, "f": "txn", "value": a},
        {"type": "ok", "process": 0, "f": "txn", "value": a},
        {"type": "invoke", "process": 1, "f": "txn", "value": b},
        {"type": "ok", "process": 1, "f": "txn", "value": b},
    ])
    v1 = ses.verdict()
    assert "G1c" in v1["anomaly-types"]
    assert set(v1["new"]) == set(v1["anomaly-types"])  # all first-seen now
    first = dict(v1["first-seen"])
    v2 = ses.verdict()
    assert v2["new"] == [] and v2["first-seen"] == first
    ses.seal()  # and the delta-bearing state still equals batch


def test_packed_columns_path_and_seal():
    p = synth.packed_la_history(n_txns=4000, n_keys=500, seed=2)
    batch = oracle.check(p, MODELS)
    ses = VerifierSession("pk", MODELS)
    for cols, rd, base in iter_packed_segments(p, 512):
        ses.append_columns(cols, rd_elems=rd, rd_base=base)
    _assert_equal(batch, ses.verdict())
    sealed = ses.seal()
    assert sealed["equal"] is True and sealed["txns"] == 4000


def test_seal_raises_on_mismatch():
    h = synth.la_history(n_txns=50, n_keys=3, seed=0)
    ses = _feed(VerifierSession(
        "t", MODELS,
        batch_check=lambda p: {"valid?": False,
                               "anomaly-types": ["G1c"]}), _ops(h), 10)
    with pytest.raises(VerdictMismatch):
        ses.seal()
    assert ses.sealed is None


def test_sweep_deadline_returns_unknown():
    h = synth.la_history(n_txns=100, n_keys=4, seed=1)
    ses = VerifierSession("t", MODELS)
    ses.append_ops(_ops(h))
    v = ses.verdict(deadline=Deadline(0.0))
    assert v["valid?"] == "unknown" and v["error"] == "deadline-exceeded"
    # budget restored: the backlog is intact and sweeps to the verdict
    v2 = ses.verdict()
    assert v2["valid?"] is True


def test_sweep_transient_faults_retried_and_failure_keeps_backlog():
    h = synth.la_history(n_txns=80, n_keys=4, seed=2)
    synth.inject_wr_cycle(h)
    batch = oracle.check(pack_txns(h, "list-append"), MODELS)
    # transient fault on the first sweep dispatch: retried, same verdict
    plan = faults.FaultPlan(seed=1, at={0: "oom"},
                            sites=("verifier.sweep",))
    ses = VerifierSession("t", MODELS, plan=plan)
    ses.append_ops(_ops(h))
    _assert_equal(batch, ses.verdict())
    assert plan.injected
    # persistent fault: sweep raises, backlog survives, next sweep wins
    plan2 = faults.FaultPlan(seed=1, persistent=("verifier.sweep",),
                             kinds=("oom",), max_faults=3)
    ses2 = VerifierSession("t2", MODELS, plan=plan2)
    ses2.append_ops(_ops(h))
    with pytest.raises(Exception):
        ses2.sweep()
    _assert_equal(batch, ses2.verdict())  # max_faults exhausted: clean


# ------------------------------------------------------- journal

def test_split_segment_torn_corrupt_and_unfeedable():
    good = b'{"type": "invoke"}\n{"type": "ok"}\n'
    acc, n, ops = split_segment(good + b'{"type": "in')  # torn tail
    assert acc == good and n == 2 and len(ops) == 2
    # a parseable-but-unfeedable dict must NOT be accepted: journaled,
    # it would brick every replay of the session (review finding)
    for bad in (b'{"a": 1}\n',                      # no type
                b'{"type": "nope"}\n',              # unknown type
                b'{"type": "ok", "process": 0, "value": 3}\n',
                b'{"type": "ok", "process": 0, '
                b'"value": [["r", [1], null]]}\n',  # unhashable key
                b'{"type": "ok", "process": 0, '
                b'"value": [["append", "k", [1]]]}\n'):
        acc, n, _ = split_segment(good + bad + good)
        assert acc == good and n == 2, bad
    acc, n, _ = split_segment(b'not json\n{"type": "ok"}\n')
    assert acc == b"" and n == 0  # stops at the corrupt line
    # non-client ops (nemesis etc) pass through — the packer skips them
    acc, n, _ = split_segment(
        b'{"type": "info", "process": ":nemesis", "f": "start"}\n')
    assert n == 1


def test_unfeedable_ingest_refused_session_survives(tmp_path):
    """Review regression: a malformed-but-JSON op line must be refused
    BEFORE the fsync — never journaled, never bricking replay."""
    svc = VerifierService(str(tmp_path))
    h = synth.la_history(n_txns=40, n_keys=3, seed=1)
    body = _jsonl(h)
    code, r = svc.ingest("s", b'{"foo": 1}\n' + body, cursor=0)
    assert code == 200 and r["cursor"] == 0 and r["ops"] == 0
    code, r = svc.ingest("s", body, cursor=0)
    assert code == 200 and r["cursor"] == len(body)
    _code, v1 = svc.verdict("s")
    svc.close()
    # restart replays cleanly to the same digest (nothing poisoned)
    svc2 = VerifierService(str(tmp_path))
    code, v2 = svc2.verdict("s")
    assert code == 200 and v2["digest"] == v1["digest"]
    assert svc2.seal("s")[1]["equal"] is True


def test_restart_preserves_first_seen_and_deltas(tmp_path):
    """Review regression: a restarted session must not re-report every
    standing anomaly as 'new' with a reset first-seen timestamp."""
    svc = VerifierService(str(tmp_path))
    h = synth.la_history(n_txns=80, n_keys=4, seed=2)
    synth.inject_g1a(h)
    svc.ingest("s", _jsonl(h), cursor=0)
    _code, v1 = svc.verdict("s")
    assert v1["anomaly-types"] and v1["new"]
    first = dict(v1["first-seen"])
    svc.close()
    svc2 = VerifierService(str(tmp_path))
    _code, v2 = svc2.verdict("s")
    assert v2["new"] == [] and v2["first-seen"] == first


def test_session_gauge_series_dropped_on_expire_and_seal(tmp_path):
    from jepsen_tpu import telemetry

    svc = VerifierService(str(tmp_path))
    h = synth.la_history(n_txns=30, n_keys=3, seed=0)
    svc.ingest("ga", _jsonl(h), cursor=0)
    svc.ingest("gb", _jsonl(h), cursor=0)

    def series():
        return {tuple(sorted(g["labels"].items()))
                for g in telemetry.registry().snapshot()["gauges"]
                if g["name"] == "verifier-verdict-freshness-s"}

    assert (("session", "ga"),) in series()
    svc.expire("ga")
    assert (("session", "ga"),) not in series()
    svc.seal("gb")
    assert (("session", "gb"),) not in series()


def test_bad_session_name_is_400_everywhere(tmp_path):
    svc = VerifierService(str(tmp_path))
    for fn in (lambda: svc.verdict("../evil"),
               lambda: svc.seal("../evil"),
               lambda: svc.ingest("../evil", b"{}\n", cursor=0),
               lambda: svc.open("../evil")):
        code, doc = fn()
        assert code == 400 and "bad session name" in doc["error"]


def test_readonly_verdict_rejects_traversal(tmp_path):
    """Review regression: the no-service /verdict path joined the raw
    name into a filesystem path — a traversal name must 400, never
    read a file outside the store."""
    outside = tmp_path / "outside"
    outside.mkdir()
    (outside / "session.json").write_text('{"secret": 1}')
    base = tmp_path / "store"
    base.mkdir()
    srv = web.serve(port=0, base=str(base), background=True)
    try:
        port = srv.server_address[1]
        code, raw = _get(port,
                         "/verdict/..%2F..%2Foutside")
        assert code == 400 and b"secret" not in raw
        code, _raw = _get(port, "/verifier/..%2F..%2Foutside")
        assert code == 404
    finally:
        srv.server_close()


def test_expired_zombie_handle_not_used(tmp_path):
    """Review regression: a handler holding a _Live fetched before
    expire() must re-resolve instead of appending through the retired
    object's journal next to the recovered replacement."""
    svc = VerifierService(str(tmp_path))
    h = synth.la_history(n_txns=40, n_keys=3, seed=4)
    body = _jsonl(h)
    half = len(body) // 2
    code, r = svc.ingest("z", body[:half], cursor=0)
    acked = r["cursor"]
    zombie = svc._get("z")
    assert svc.expire("z")[0] == 200
    assert zombie.dead is True
    # the public path recovers a FRESH live and continues correctly
    code, r = svc.ingest("z", body[acked:], cursor=acked)
    assert code == 200 and r["cursor"] == len(body)
    assert svc._get("z") is not zombie
    assert svc.seal("z")[1]["equal"] is True


def test_session_page_is_side_effect_free(verifier_server):
    """Review regression: an auto-refreshing browser tab on
    /verifier/<s> must not run sweeps, grow events.jsonl, or reset the
    freshness gauge — only GET /verdict mutates."""
    base, port, _svc = verifier_server
    h = synth.la_history(n_txns=40, n_keys=3, seed=5)
    _post(port, "/ingest/pg?cursor=0", _jsonl(h))
    _get(port, "/verdict/pg")  # one real verdict so the page has data
    ev = os.path.join(base, "verifier", "pg", "events.jsonl")
    size0 = os.path.getsize(ev)
    for _ in range(3):
        code, page = _get(port, "/verifier/pg")
        assert code == 200
    assert os.path.getsize(ev) == size0


def test_journal_recover_truncates_torn_tail(tmp_path):
    d = str(tmp_path / "s")
    j = SessionJournal(d)
    j.append(b'{"type": "invoke", "process": 0, "f": "txn"}\n')
    cur = j.cursor
    j.close()
    with open(j.path, "ab") as f:
        f.write(b'{"type": "ok", "proc')  # kill -9 mid-append
    j2 = SessionJournal(d)
    assert j2.cursor == cur
    assert os.path.getsize(j2.path) == cur  # debris truncated
    assert sum(len(c) for c in j2.read_ops()) == 1


# ------------------------------------------------------- service

def test_service_ingest_ack_resume_idempotent(tmp_path):
    svc = VerifierService(str(tmp_path))
    h = synth.la_history(n_txns=100, n_keys=4, seed=9)
    body = _jsonl(h)
    code, r = svc.ingest("s", body[:1000], cursor=0)
    assert code == 200 and 0 < r["cursor"] <= 1000
    acked = r["cursor"]
    # lost-ack resend: overlapping bytes from an older cursor
    code, r = svc.ingest("s", body[:2000], cursor=0)
    assert code == 200 and r["cursor"] > acked
    acked = r["cursor"]
    # pure replay of acked bytes: a no-op ack
    code, r = svc.ingest("s", body[:acked], cursor=0)
    assert code == 200 and r["cursor"] == acked and r["ops"] == 0
    # gap refused, nothing accepted
    code, r = svc.ingest("s", body[acked + 10:], cursor=acked + 10)
    assert code == 409 and r["cursor"] == acked
    # finish + seal
    code, r = svc.ingest("s", body[acked:], cursor=acked)
    assert code == 200 and r["cursor"] == len(body)
    code, sealed = svc.seal("s")
    assert code == 200 and sealed["equal"] is True
    code, r = svc.ingest("s", b"{}\n", cursor=len(body))
    assert code == 409 and "sealed" in r["error"]


def test_service_restart_replays_to_identical_digest(tmp_path):
    h = synth.la_history(n_txns=120, n_keys=4, seed=3)
    synth.inject_g1a(h)
    body = _jsonl(h)
    svc = VerifierService(str(tmp_path))
    svc.ingest("s", body, cursor=0)
    _code, v1 = svc.verdict("s")
    svc.close()
    svc2 = VerifierService(str(tmp_path))
    _code, v2 = svc2.verdict("s")
    assert v2["digest"] == v1["digest"]
    assert v2["anomaly-types"] == v1["anomaly-types"]
    code, sealed = svc2.seal("s")
    assert code == 200 and sealed["equal"] is True
    # expire drops it from memory; a later touch recovers the seal
    assert svc2.expire("s")[0] == 200
    code, listed = 200, svc2.sessions()
    assert [s["state"] for s in listed] == ["sealed"]


def test_service_ingest_chaos_transient_then_ok(tmp_path):
    plan = faults.FaultPlan(seed=7, at={0: "oom"},
                            sites=("verifier.ingest",))
    h = synth.la_history(n_txns=60, n_keys=3, seed=1)
    body = _jsonl(h)
    svc = VerifierService(str(tmp_path))
    with faults.use(plan):
        code, r = svc.ingest("s", body, cursor=0)
    assert code == 200 and r["cursor"] == len(body)  # retried through
    assert plan.injected
    code, sealed = svc.seal("s")
    assert code == 200 and sealed["equal"] is True


# ------------------------------------------------- web surfaces

@pytest.fixture()
def verifier_server(tmp_path):
    svc = VerifierService(str(tmp_path))
    srv = web.serve(port=0, base=str(tmp_path), background=True,
                    verifier=svc)
    yield str(tmp_path), srv.server_address[1], svc
    srv.server_close()
    svc.close()


def _post(port, path, data=b""):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", data=data, method="POST")
    try:
        with urllib.request.urlopen(req) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _get(port, path):
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}") as r:
            return r.status, r.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


def test_http_ingest_verdict_seal_pages(verifier_server):
    _base, port, _svc = verifier_server
    h = synth.la_history(n_txns=100, n_keys=4, seed=11)
    synth.inject_rw_cycle(h)
    body = _jsonl(h)
    code, r = _post(port, "/verifier/s1/open",
                    json.dumps({"consistency-models":
                                ["strict-serializable"]}).encode())
    assert code == 200 and r["state"] == "open"
    cur = 0
    while cur < len(body):
        code, r = _post(port, f"/ingest/s1?cursor={cur}",
                        body[cur:cur + 4096])
        assert code == 200
        cur = r["cursor"]
    assert cur == len(body)
    code, raw = _get(port, "/verdict/s1")
    v = json.loads(raw)
    assert code == 200 and v["valid?"] is False and v["anomaly-types"]
    code, sealed = _post(port, "/verifier/s1/seal")
    assert code == 200 and sealed["equal"] is True
    # re-seal is idempotent
    assert _post(port, "/verifier/s1/seal")[0] == 200
    code, page = _get(port, "/verifier")
    assert code == 200 and b"s1" in page and b"sealed" in page
    code, page = _get(port, "/verifier/s1")
    assert code == 200 and b"incremental == batch" in page
    code, page = _get(port, "/live/verifier/s1")
    assert code == 200  # the per-session events.jsonl renders as /live
    code, page = _get(port, "/")
    assert code == 200 and b"/verifier" in page
    code, m = _get(port, "/metrics")
    assert b"jepsen_verifier_ops_ingested_total" in m
    assert b"jepsen_verifier_sweep_s_bucket" in m


def test_http_read_only_pages_without_service(tmp_path):
    """`serve` without --ingest still renders sessions from their
    session.json snapshots (and 404s POSTs)."""
    svc = VerifierService(str(tmp_path))
    h = synth.la_history(n_txns=50, n_keys=3, seed=0)
    svc.ingest("ro", _jsonl(h), cursor=0)
    svc.verdict("ro")
    svc.close()
    srv = web.serve(port=0, base=str(tmp_path), background=True)
    try:
        port = srv.server_address[1]
        code, page = _get(port, "/verifier")
        assert code == 200 and b"ro" in page
        code, raw = _get(port, "/verdict/ro")
        assert code == 200 and json.loads(raw)["valid?"] is True
        code, _doc = _post(port, "/ingest/ro?cursor=0", b"{}\n")
        assert code == 404
    finally:
        srv.server_close()


# ------------------------------------------------- kill -9 the daemon

_SERVER = """\
import sys
from jepsen_tpu import web
from jepsen_tpu.verifier import VerifierService
base, port = sys.argv[1], int(sys.argv[2])
svc = VerifierService(base)
web.serve(port=port, base=base, verifier=svc)
"""


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _wait_up(port, timeout=30.0):
    t0 = time.time()
    while time.time() - t0 < timeout:
        try:
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/verifier", timeout=2)
            return True
        except Exception:  # noqa: BLE001
            time.sleep(0.2)
    return False


def _spawn_server(base, port):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(
        [sys.executable, "-c", _SERVER, base, str(port)],
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    assert _wait_up(port), "serve daemon did not come up"
    return proc


def test_kill9_serve_daemon_replay_and_client_resume(tmp_path):
    """THE crash/rudeness contract: kill -9 the serve daemon
    mid-session; restart; the journal replays to the identical verdict
    digest, and the client's resume from its last acked cursor is
    idempotent — the sealed verdict equals the batch checker's."""
    base = str(tmp_path)
    h = synth.la_history(n_txns=150, n_keys=5, seed=13)
    synth.inject_wr_cycle(h)
    body = _jsonl(h)
    port = _free_port()
    proc = _spawn_server(base, port)
    cur = 0
    try:
        # stream roughly half, then SIGKILL mid-session
        while cur < len(body) // 2:
            code, r = _post(port, f"/ingest/k9?cursor={cur}",
                            body[cur:cur + 2048])
            assert code == 200
            cur = r["cursor"]
    finally:
        os.kill(proc.pid, signal.SIGKILL)
        proc.wait(timeout=10)
    # restart; the replayed session must equal a fresh one fed the
    # same journaled prefix (digest-pinned)
    port2 = _free_port()
    proc2 = _spawn_server(base, port2)
    try:
        code, raw = _get(port2, "/verdict/k9")
        assert code == 200
        recovered = json.loads(raw)
        # the service default config checks "serializable" — the
        # reference replay must use the same want set
        ref = VerifierSession("ref", ("serializable",))
        for chunk in SessionJournal(
                os.path.join(base, "verifier", "k9")).read_ops():
            ref.append_ops(chunk)
        assert recovered["digest"] == verdict_digest(ref.verdict())
        # client resumes from its last acked cursor (possibly behind
        # the journal: overlap skipped idempotently), then seals
        while cur < len(body):
            code, r = _post(port2, f"/ingest/k9?cursor={cur}",
                            body[cur:cur + 2048])
            assert code == 200
            cur = r["cursor"]
        assert cur == len(body)
        code, sealed = _post(port2, "/verifier/k9/seal")
        assert code == 200 and sealed["equal"] is True
        batch = oracle.check(pack_txns(h, "list-append"),
                             ("serializable",))
        # default service config checks serializable; anomaly SET of
        # the sealed verdict matches the batch checker's
        assert sealed["verdict"]["valid?"] == batch["valid?"]
        assert sealed["verdict"]["anomaly-types"] == \
            batch["anomaly-types"]
    finally:
        proc2.kill()
        proc2.wait(timeout=10)


# ------------------------------------------------- soak smoke (CI)

def _run_soak(args, timeout):
    script = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "scripts", "soak_verifier.py")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run([sys.executable, script, *args],
                          capture_output=True, text=True,
                          timeout=timeout, env=env)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "soak OK" in proc.stdout
    return proc.stdout


def test_soak_verifier_fast_smoke():
    """scripts/soak_verifier.py --fast in a subprocess: concurrent
    clients + FaultPlan chaos on the ingest path; every session seals
    with incremental == batch."""
    _run_soak(["--fast"], timeout=300)


@pytest.mark.slow
def test_soak_verifier_long():
    """The long soak: 8 clients x 16 segments x 400 txns with 10%
    chaos on every guarded verifier seam — every session must still
    seal incremental == batch."""
    out = _run_soak(["--clients", "8", "--segments", "16",
                     "--txns", "400", "--fault-p", "0.1",
                     "--seed", "1"], timeout=560)
    assert '"sessions_peak": 8' in out


# ----------------------- journal compaction + checkpoint (ISSUE 13)

def _ref_verdict(h, models=("serializable",)):
    ref = _feed(VerifierSession("ref", models), _ops(h), 10_000,
                rolling=False)
    return ref.verdict()


def test_auto_compaction_bounds_journal_and_recovery_digest(tmp_path):
    """A month-long session's journal must be BOUNDED, not monotone:
    with ``compact-bytes`` set, streaming far more jsonl than the
    budget keeps the on-disk journal under budget + one segment, the
    logical cursor keeps ordinary resend semantics, and a restarted
    service recovers checkpoint + suffix to the identical verdict
    digest a fresh session reaches on the same ops."""
    base = str(tmp_path)
    h = synth.la_history(n_txns=400, n_keys=6, seed=7, fail_prob=0.05)
    synth.inject_wr_cycle(h)
    body = _jsonl(h)
    budget, seg = 8192, 4096
    svc = VerifierService(base, default_config={"compact-bytes": budget})
    jpath = os.path.join(base, "verifier", "cp", "journal.jsonl")
    sizes, cur = [], 0
    while cur < len(body):
        code, r = svc.ingest("cp", body[cur:cur + seg], cursor=cur)
        assert code == 200
        cur = r["cursor"]
        sizes.append(os.path.getsize(jpath))
    assert cur == len(body)  # the logical cursor ignores compaction
    assert max(sizes) <= budget + seg
    assert any(b < a for a, b in zip(sizes, sizes[1:]))  # not monotone
    assert os.path.exists(os.path.join(base, "verifier", "cp",
                                       "checkpoint.npz"))
    # resend below the logical cursor: still an idempotent no-op even
    # though those bytes were compacted off the disk journal
    code, r = svc.ingest("cp", body[-seg:], cursor=len(body) - seg)
    assert code == 200 and r["ops"] == 0 and r["cursor"] == len(body)
    _code, v_live = svc.verdict("cp")
    svc.close()
    # restart: vectorized checkpoint restore + suffix replay
    svc2 = VerifierService(base)
    try:
        code, v = svc2.verdict("cp")
        assert code == 200
        ref = _ref_verdict(h)
        assert v["digest"] == verdict_digest(ref) == \
            verdict_digest(v_live)
        assert v["valid?"] is ref["valid?"] is False
        # and the restored session keeps ACCEPTING: seal equals batch
        assert svc2.seal("cp")[1]["equal"] is True
    finally:
        svc2.close()


def test_compaction_crash_window_checkpoint_without_truncate(tmp_path):
    """kill -9 BETWEEN the checkpoint write and the journal truncate
    leaves both the full journal and a checkpoint; recovery must
    replay only the suffix past the checkpoint cursor — nothing
    doubles, digest identical."""
    base = str(tmp_path)
    h = synth.la_history(n_txns=240, n_keys=5, seed=11)
    synth.inject_rw_cycle(h)
    body = _jsonl(h)
    half = len(body) // 2
    svc = VerifierService(base)
    code, r = svc.ingest("w1", body[:half], cursor=0)
    assert code == 200
    acked = r["cursor"]
    live = svc._get("w1")
    with live.lock:  # the first half of _Live.compact, then "kill -9"
        cols, meta = live.session.checkpoint_state()
        meta["cursor"] = live.journal.cursor
        live.journal.write_checkpoint(cols, meta)
    svc.close()
    svc2 = VerifierService(base)
    try:
        # client resumes from its acked cursor; overlap skipped
        code, r = svc2.ingest("w1", body[acked:], cursor=acked)
        assert code == 200 and r["cursor"] == len(body)
        code, v = svc2.verdict("w1")
        assert v["digest"] == verdict_digest(_ref_verdict(h))
        assert svc2.seal("w1")[1]["equal"] is True
    finally:
        svc2.close()


def test_compaction_crash_window_torn_tail_after_compact(tmp_path):
    """kill -9 mid-append on an already-compacted journal: recovery
    truncates the torn tail back past the compaction header and
    replays checkpoint + intact suffix to the identical digest."""
    base = str(tmp_path)
    h = synth.la_history(n_txns=200, n_keys=5, seed=3)
    synth.inject_g1a(h)
    body = _jsonl(h)
    cut = (2 * len(body)) // 3
    svc = VerifierService(base)
    code, r = svc.ingest("w2", body[:cut], cursor=0)
    assert code == 200
    acked = r["cursor"]
    code, out = svc.compact("w2")
    assert code == 200
    assert out["journal-bytes-after"] < out["journal-bytes-before"]
    svc.close()
    jpath = os.path.join(base, "verifier", "w2", "journal.jsonl")
    with open(jpath, "ab") as f:
        f.write(b'{"type": "ok", "proc')  # the torn line
    svc2 = VerifierService(base)
    try:
        # the torn debris was never acked: cursor is still the acked
        # logical offset, and the client's resend completes the stream
        code, r = svc2.ingest("w2", body[acked:], cursor=acked)
        assert code == 200 and r["cursor"] == len(body)
        code, v = svc2.verdict("w2")
        assert v["digest"] == verdict_digest(_ref_verdict(h))
    finally:
        svc2.close()


def test_compact_endpoint_rejects_unknown_and_packed(tmp_path):
    svc = VerifierService(str(tmp_path))
    code, doc = svc.compact("nope")
    assert code == 404
    code, doc = svc.compact("../evil")
    assert code == 400


def test_session_names_cannot_shadow_infrastructure_dirs(tmp_path):
    """Leading ``_``/``.`` are load-bearing prefixes (``_archive/``
    retention, dot-prefixed staging) skipped by every scan — a session
    there would journal into the retention subtree or be invisible to
    listings and gc, so open must refuse them."""
    svc = VerifierService(str(tmp_path))
    for bad in ("_archive", "_mine", ".hidden"):
        assert svc.open(bad)[0] == 400, bad


def test_compacted_session_with_lost_checkpoint_quarantines(tmp_path):
    """A compacted journal whose checkpoint is corrupt/missing cannot
    rebuild the truncated prefix: recovery must QUARANTINE the session
    (410 on ingest/verdict/seal/compact, ``recovery-error`` in the
    snapshot) instead of serving normal-looking verdicts over a
    suffix-only replay."""
    base = str(tmp_path)
    h = synth.la_history(n_txns=200, n_keys=5, seed=3)
    body = _jsonl(h)
    svc = VerifierService(base)
    assert svc.ingest("qr", body, cursor=0)[0] == 200
    assert svc.compact("qr")[0] == 200
    # an uncompacted sibling for the control below
    assert svc.ingest("whole", body, cursor=0)[0] == 200
    svc.close()
    with open(os.path.join(base, "verifier", "qr", "checkpoint.npz"),
              "wb") as f:
        f.write(b"garbage")
    svc2 = VerifierService(base)
    try:
        assert svc2.verdict("qr")[0] == 410
        assert svc2.ingest("qr", body)[0] == 410
        assert svc2.seal("qr")[0] == 410
        assert svc2.compact("qr")[0] == 410
        code, snap = svc2.open("qr")
        assert code == 200 and "recovery-error" in snap
        # control: an uncompacted session with no checkpoint replays
        # the whole journal and keeps serving
        assert svc2.verdict("whole")[0] == 200
    finally:
        svc2.close()


# ----------------------------------- GC / retention / archival

def test_gc_expires_idle_and_archives_sealed(tmp_path):
    """The retention pass: open sessions idle past ``gc-idle-s``
    expire (journal stays — a later touch recovers them), sealed ones
    idle past ``archive-sealed-s`` move under ``_archive/`` and leave
    every listing surface; per-session gauge series retire with
    them — the month-long daemon's /metrics cardinality is bounded."""
    from jepsen_tpu.verifier import scan_sessions
    from jepsen_tpu.verifier.service import ARCHIVE_DIR

    base = str(tmp_path)
    svc = VerifierService(base, default_config={
        "gc-idle-s": 5.0, "archive-sealed-s": 5.0})
    h = synth.la_history(n_txns=40, n_keys=3, seed=1)
    svc.ingest("keep", _jsonl(h), cursor=0)
    svc.ingest("idle", _jsonl(h), cursor=0)
    svc.ingest("done", _jsonl(h), cursor=0)
    assert svc.seal("done")[1]["equal"] is True
    # "keep" stays fresh; the others idle past their budgets
    live = svc._get("keep")
    live.last_ingest = live.last_verdict_ts = time.time() + 60
    stats = svc.gc(now=time.time() + 30)
    assert stats == {"expired": 1, "archived": 1}
    names = {n for n, _ in scan_sessions(base)}
    assert "done" not in names          # archived out of the listings
    assert {"keep", "idle"} <= names    # idle expired but on disk
    assert os.path.isdir(os.path.join(base, "verifier", ARCHIVE_DIR,
                                      "done"))
    def series():
        return {g["labels"].get("session")
                for g in telemetry.registry().snapshot()["gauges"]
                if g["name"] == "verifier-verdict-freshness-s"}

    assert "idle" not in series() and "done" not in series()
    # a later touch recovers the expired session by replay
    code, v = svc.verdict("idle")
    assert code == 200 and v["txns"] > 0
    # sealed sessions already on disk from a PREVIOUS process life
    # archive too: restart, re-seal nothing, just gc
    svc.expire("idle")
    svc.close()
    svc2 = VerifierService(base, default_config={
        "archive-sealed-s": 5.0})
    try:
        svc2.seal("keep")
        svc2.expire("keep")  # sealed + on disk only
        stats = svc2.gc(now=time.time() + 30)
        assert stats["archived"] == 1
        assert "keep" not in {n for n, _ in scan_sessions(base)}
    finally:
        svc2.close()


# ----------------------------------- multi-tenant batched sweep

def test_batched_sweep_matches_per_session_verdicts(tmp_path):
    """Tentpole (d): many sessions' dirty regions through ONE
    ``ops.cycle_sweep`` dispatch — sessions with cycle witnesses fall
    back to their own exact sweep, clean ones commit without a
    dispatch, and every verdict digest equals the per-session path's
    bit for bit."""
    base = str(tmp_path)
    svc = VerifierService(base)
    injections = [None, "inject_wr_cycle", None, "inject_rw_cycle",
                  "inject_g1a", None]
    hs = []
    for i, inj in enumerate(injections):
        h = synth.la_history(n_txns=120, n_keys=4, concurrency=4,
                             seed=20 + i, fail_prob=0.05)
        if inj:
            getattr(synth, inj)(h)
        hs.append(h)
        # ingest WITHOUT a verdict: the dirty backlog stays pending
        code, _r = svc.ingest(f"mt{i}", _jsonl(h), cursor=0)
        assert code == 200
    coll = telemetry.activate()
    try:
        stats = svc.sweep_dirty()
        doc = telemetry.snapshot(coll)
    finally:
        telemetry.deactivate(coll)
    assert stats["dirty"] == len(injections)
    assert stats["dispatched"] == 1
    assert stats["clean"] + stats["classified"] + stats["rebuild"] == \
        stats["dirty"]
    assert stats["classified"] >= 1  # the injected cycles classify
    # the batched dispatch ran under ONE verifier.sweep span with
    # batched=True — the span `cli obs gate` regression-gates
    batched = [s for r in doc.get("spans", []) for s in _walk_spans(r)
               if s["name"] == "verifier.sweep"
               and (s.get("attrs") or {}).get("batched")]
    assert len(batched) == 1
    for i, h in enumerate(hs):
        code, v = svc.verdict(f"mt{i}")
        assert code == 200
        assert v["digest"] == verdict_digest(_ref_verdict(h)), f"mt{i}"
        assert svc.seal(f"mt{i}")[1]["equal"] is True
    svc.close()


def _walk_spans(sp):
    yield sp
    for c in sp.get("children") or []:
        yield from _walk_spans(c)


def test_batched_sweep_stale_snapshot_resweeps_not_commits(
        tmp_path, monkeypatch):
    """Race guard: a per-session sweep (an HTTP verdict) plus a fresh
    ingest landing BETWEEN the batched snapshot and its commit (the
    dispatch runs off-lock) makes the snapshot stale — the commit must
    fall back to that session's exact sweep instead of blindly marking
    the post-snapshot dirty edges as swept (which could silently skip
    a cycle through them forever)."""
    from jepsen_tpu.verifier import sweep as sweep_mod

    svc = VerifierService(str(tmp_path))
    h1 = synth.la_history(n_txns=150, n_keys=5, concurrency=4, seed=31)
    body = _jsonl(h1)
    cut = (3 * len(body)) // 5
    h2 = synth.la_history(n_txns=100, n_keys=4, seed=32)
    synth.inject_wr_cycle(h2)  # guarantees a region -> dispatch runs
    code, r = svc.ingest("s1", body[:cut], cursor=0)
    assert code == 200
    acked = r["cursor"]
    svc.ingest("s2", _jsonl(h2), cursor=0)
    live1 = svc._get("s1")
    real_dispatch = sweep_mod._dispatch
    raced = {}

    def hijack(regions, deadline, n_sessions):
        out = real_dispatch(regions, deadline, n_sessions)
        # while the batched pass holds no session locks: a concurrent
        # verdict sweeps+commits s1's backlog, then new ops arrive
        _c, v = svc.verdict("s1")
        _c, r = svc.ingest("s1", body[acked:], cursor=acked)
        raced["ok"] = r["cursor"] == len(body)
        return out

    monkeypatch.setattr(sweep_mod, "_dispatch", hijack)
    stats = svc.sweep_dirty()
    assert raced.get("ok") is True
    assert stats["dispatched"] == 1
    # s1's snapshot went stale: it must NOT be blind-committed
    assert stats["clean"] == 0
    assert stats["classified"] == 2  # s2 (witness) + s1 (stale)
    for name, h in (("s1", h1), ("s2", h2)):
        code, v = svc.verdict(name)
        assert v["digest"] == verdict_digest(_ref_verdict(h)), name
        assert svc.seal(name)[1]["equal"] is True
    svc.close()


# ----------------------------------- live checking (ISSUE 13)

def _append_cell(base, opts):
    from jepsen_tpu.campaign import core as ccore
    from jepsen_tpu.campaign.plan import expand

    spec = {"name": "lc", "workloads": ["append"], "seeds": [0],
            "opts": dict({"ops": 80, "time-limit": None,
                          "concurrency": 3}, **opts)}
    [rs] = expand(spec)
    rec = ccore.execute_run(rs, base)
    with open(os.path.join(base, rec["dir"], "results.json")) as f:
        return rec, json.load(f)


def test_live_check_inproc_run_seals_equal(tmp_path):
    """Tentpole (a), the happy path: a campaign cell with
    ``live-check: {inproc: true}`` streams its interpreter's ops into
    a verifier session DURING the run; at finish the rolling verdict
    seals incremental == batch and the stamp carries the digest."""
    rec, res = _append_cell(str(tmp_path), {"live-check": {"inproc": True}})
    lc = res["live-check"]
    assert lc["state"] == "ok"
    assert lc["ops"] > 0 and lc["ops-dropped"] == 0
    assert lc["seal"]["equal"] is True
    assert lc["digest"] == lc["seal"]["digest"]
    assert rec["valid?"] is True and lc["valid?"] is True
    # the live session journaled + sealed under the run's store
    from jepsen_tpu.verifier import scan_sessions

    metas = dict(scan_sessions(str(tmp_path)))
    assert metas[lc["session"]]["state"] == "sealed"


def test_live_check_dead_verifier_degrades_run_unharmed(tmp_path):
    """Graceful degradation at open: an unreachable verifier URL
    degrades the live client immediately — the run completes normally
    and the stored-history check stands alone."""
    rec, res = _append_cell(str(tmp_path), {"live-check": {
        "url": "http://127.0.0.1:9", "timeout-s": 0.5,
        "budget-s": 0.5}})
    lc = res["live-check"]
    assert lc["state"] == "degraded" and lc.get("reason")
    assert rec["valid?"] is True  # the stored-history verdict stands


def test_live_check_partition_midrun_degrades_within_budget(tmp_path):
    """Graceful degradation mid-run: a persistent fault on the
    ``verifier.live`` seam (the chaos-tooling partition site) pushes
    the client past its outage budget — feeding flips to a no-op, the
    run completes, the stamp says degraded."""
    plan = faults.FaultPlan(seed=0, sites=("verifier.live",),
                            persistent=("verifier.live",))
    with faults.use(plan):
        rec, res = _append_cell(str(tmp_path), {"live-check": {
            "inproc": True, "budget-s": 0.2, "flush-interval-s": 0.05}})
    lc = res["live-check"]
    assert lc["state"] == "degraded"
    assert rec["valid?"] is True  # stored-history authority unharmed
    assert plan.injected  # the partition actually fired


# ------------------------------------------------- telemetry spans

def test_verifier_spans_emitted():
    coll = telemetry.activate()
    try:
        h = synth.la_history(n_txns=80, n_keys=4, seed=2)
        ses = _feed(VerifierSession("t", MODELS), _ops(h), 20)
        ses.seal()
        doc = telemetry.snapshot(coll)
    finally:
        telemetry.deactivate(coll)
    names = set()

    def walk(sp):
        names.add(sp["name"])
        for c in sp.get("children") or []:
            walk(c)

    for r in doc.get("spans", []):
        walk(r)
    assert {"verifier.append", "verifier.sweep",
            "verifier.seal-batch-check"} <= names


# ------------------------------------------------- the 10x criterion

@pytest.mark.slow
def test_incremental_recheck_10x_faster_than_batch():
    """Acceptance: +1k txns appended to a 100k-txn session re-checks
    >= 10x faster than a full batch re-check of the concatenated
    history.  Span-cited: both sides run under telemetry and the
    asserted ratio comes from the recorded span durations."""
    p = synth.packed_la_history(n_txns=101_000, n_keys=12_000, seed=4)
    segs = list(iter_packed_segments(p, 10_000))
    warm, extra = segs[:-1], segs[-1]  # the +1k tail segment
    assert sum(len(c[0]["txn_type"]) for c in warm) == 100_000
    ses = VerifierSession("big", MODELS)
    for cols, rd, base in warm:
        ses.append_columns(cols, rd_elems=rd, rd_base=base)
    ses.verdict()  # steady state: swept through 100k txns

    coll = telemetry.activate()
    try:
        with telemetry.span("verifier.incremental-recheck"):
            cols, rd, base = extra
            ses.append_columns(cols, rd_elems=rd, rd_base=base)
            v = ses.verdict()
        assert v["valid?"] is True and v["txns"] == 101_000
        with telemetry.span("verifier.batch-recheck"):
            batch = oracle.check(ses.to_packed(), MODELS)
        assert batch["valid?"] is True
        doc = telemetry.snapshot(coll)
    finally:
        telemetry.deactivate(coll)
    durs = {}

    def walk(sp):
        durs.setdefault(sp["name"], 0)
        durs[sp["name"]] += sp.get("dur_ns") or 0
        for c in sp.get("children") or []:
            walk(c)

    for r in doc.get("spans", []):
        walk(r)
    inc_s = durs["verifier.incremental-recheck"] / 1e9
    batch_s = durs["verifier.batch-recheck"] / 1e9
    assert batch_s >= 10 * inc_s, \
        f"incremental {inc_s:.2f}s vs batch {batch_s:.2f}s " \
        f"({batch_s / max(inc_s, 1e-9):.1f}x, need >= 10x)"
