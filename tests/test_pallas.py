"""Differential tests for the Pallas segmented-scan kernel.

The kernel must be bitwise-identical to the lax reference scans
(`segments._seg_scan` / `_seg_scan_loop`) — checkers are oracles, so the
kernel's only acceptance bar is exact equality on adversarial segment
layouts.  The block-scan math + grid/carry schedule are exercised here
via `seg_or_blocked_reference` (the pure-JAX emulator sharing
`_block_scan` verbatim with the kernel) on the CPU test backend; the
compiled `pallas_call` itself is tested when the TPU backend is present
(`test_compiled_kernel_on_tpu`, skipped on CPU — the axon tunnel
registers platform "tpu", and the CPU env cannot interpret Mosaic).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from jepsen_tpu.ops import pallas_scan
from jepsen_tpu.ops.segments import _seg_scan, _seg_scan_loop


def _random_case(n, k, p_start, seed):
    rng = np.random.default_rng(seed)
    vals = (rng.random((n, k)) < 0.08).astype(np.int8)
    starts = rng.random(n) < p_start
    starts[0] = True
    return jnp.asarray(vals), jnp.asarray(starts)


@pytest.mark.parametrize("n,k,p_start,block", [
    (8, 128, 0.3, 8),          # single tiny block
    (256, 128, 0.1, 64),       # multiple blocks, carries cross boundaries
    (300, 128, 0.05, 64),      # n not a block multiple (pad path)
    (1024, 128, 0.0, 128),     # one segment spanning every block
    (512, 128, 1.0, 128),      # every row its own segment
    (2048, 16, 0.02, 512),     # narrow lanes (sharded k_local shape)
    (777, 128, 0.3, 256),      # block > n collapses to one block
])
def test_block_schedule_matches_lax(n, k, p_start, block):
    vals, starts = _random_case(n, k, p_start, seed=n + k)
    want = np.asarray(_seg_scan(vals, starts))
    got = np.asarray(pallas_scan.seg_or_blocked_reference(
        vals, starts, block=block))
    np.testing.assert_array_equal(got, want)


def test_block_schedule_matches_loop_scan():
    # the loop scan is the large-shape lax path the kernel replaces on TPU
    vals, starts = _random_case(4096, 128, 0.01, seed=5)
    want = np.asarray(_seg_scan_loop(vals, starts))
    got = np.asarray(pallas_scan.seg_or_blocked_reference(
        vals, starts, block=1024))
    np.testing.assert_array_equal(got, want)


def test_carry_crosses_many_blocks():
    # one segment start at row 0, value only at row 0: every later row
    # (across 8 blocks) must see it through the carry
    n, k, block = 512, 128, 64
    vals = np.zeros((n, k), np.int8)
    vals[0, 3] = 1
    starts = np.zeros(n, bool)
    starts[0] = True
    got = np.asarray(pallas_scan.seg_or_blocked_reference(
        jnp.asarray(vals), jnp.asarray(starts), block=block))
    assert (got[:, 3] == 1).all()
    assert got.sum() == n


def test_start_resets_carry_mid_block():
    n, k, block = 256, 128, 64
    vals = np.zeros((n, k), np.int8)
    vals[0, 0] = 1
    starts = np.zeros(n, bool)
    starts[0] = True
    starts[130] = True  # mid-block-3 start: rows >= 130 must NOT see col 0
    got = np.asarray(pallas_scan.seg_or_blocked_reference(
        jnp.asarray(vals), jnp.asarray(starts), block=block))
    assert (got[:130, 0] == 1).all()
    assert (got[130:, 0] == 0).all()


def test_dispatch_respects_env(monkeypatch):
    vals = jnp.zeros((4, 128), jnp.int8)
    monkeypatch.setenv("JT_PALLAS", "0")
    assert not pallas_scan.pallas_scan_enabled(vals)
    monkeypatch.setenv("JT_PALLAS", "1")
    assert pallas_scan.pallas_scan_enabled(vals)
    assert not pallas_scan.pallas_scan_enabled(jnp.zeros((4, 4, 4), jnp.int8))


@pytest.mark.skipif(jax.default_backend() != "tpu",
                    reason="compiled Mosaic kernel needs the TPU backend")
def test_compiled_kernel_on_tpu():
    for n, k, p, blk, seed in [(300, 128, 0.05, 64, 1),
                               (4096, 128, 0.01, 1024, 2),
                               (1024, 16, 0.3, 256, 3)]:
        vals, starts = _random_case(n, k, p, seed)
        want = np.asarray(_seg_scan(vals, starts))
        got = np.asarray(pallas_scan.seg_or_pallas(vals, starts, block=blk))
        np.testing.assert_array_equal(got, want)


def _batch_case(b, n, k, p, seed0):
    vals = np.stack([np.asarray(_random_case(n, k, p, seed=seed0 + s)[0])
                     for s in range(b)])
    starts = np.stack([np.asarray(_random_case(n, k, p, seed=seed0 + s)[1])
                       for s in range(b)])
    return jnp.asarray(vals), jnp.asarray(starts)


def test_flatten_batch_is_exact():
    """The custom_vmap rule's flattening (one long scan with forced
    segment boundaries) must equal B independent scans — including when
    a history does NOT start with a segment flag (carry from the
    previous history must be cut by the forced boundary)."""
    vals, starts = _batch_case(3, 64, 128, 0.2, seed0=0)
    starts = starts.at[:, 0].set(False)  # adversarial: no natural starts
    fv, fs = pallas_scan.flatten_batch(vals, starts)
    flat = np.asarray(_seg_scan(fv, fs))
    for b in range(3):
        want = np.asarray(_seg_scan(vals[b], starts[b]))
        np.testing.assert_array_equal(flat[b * 64:(b + 1) * 64], want)


def test_custom_vmap_rule_under_jit_nesting():
    """check_batch's real nesting is jit(vmap(jit(core_check))): the
    inner trace bakes the dispatch into the jaxpr BEFORE the outer vmap
    batches it, so the only sound protection is seg_or_auto's
    custom_vmap rule.  Drive that exact nesting (with the emulator
    standing in for the Mosaic body, which CPU cannot lower) and demand
    bitwise equality with per-history scans — this fails if the default
    grid-prepend batching rule ever handles the kernel."""
    from jax import custom_batching

    @custom_batching.custom_vmap
    def auto(v, s):
        return pallas_scan.seg_or_blocked_reference(v, s, block=16)

    auto.def_vmap(lambda axis_size, in_batched, v, s: (
        pallas_scan.seg_or_blocked_reference(
            *pallas_scan.flatten_batch(v, s), block=16).reshape(v.shape),
        True))

    vals, starts = _batch_case(4, 32, 128, 0.3, seed0=9)
    got = np.asarray(jax.jit(jax.vmap(jax.jit(auto)))(vals, starts))
    for b in range(4):
        want = np.asarray(_seg_scan(vals[b], starts[b]))
        np.testing.assert_array_equal(got[b], want)


def test_seg_or_auto_vmap_rule_wiring():
    """The shipped seg_or_auto must reach _seg_or_auto_vmap under vmap
    (not the default pallas batching rule).  On CPU the kernel body
    cannot lower, so patch the body call and assert the rule fired and
    produced the flattened call shape."""
    calls = []
    import jepsen_tpu.ops.pallas_scan as ps_mod

    orig = ps_mod.seg_or_pallas

    def spy(v, s, block=2048):
        calls.append(tuple(v.shape))
        return ps_mod.seg_or_blocked_reference(v, s, block=16)

    ps_mod.seg_or_pallas = spy
    try:
        vals, starts = _batch_case(2, 32, 128, 0.3, seed0=3)
        got = np.asarray(jax.vmap(ps_mod.seg_or_auto)(vals, starts))
    finally:
        ps_mod.seg_or_pallas = orig
    # custom_vmap first traces the unbatched primal ((32,128), abstract
    # eval only); the executed path is the flattened (B*n, K) call
    assert calls[-1] == (64, 128), calls
    for b in range(2):
        want = np.asarray(_seg_scan(vals[b], starts[b]))
        np.testing.assert_array_equal(got[b], want)
