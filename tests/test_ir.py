"""HistoryIR (ISSUE 12): one packed-history IR for every checker
family, and the sharded-by-default checking path built on it.

Pins:
- IR round-trip: checking THROUGH the IR == checking the raw history,
  verdict-and-anomaly-set, for every family (elle la/rw, bank,
  long-fork, write-skew, session, knossos).
- section caching: a composed check derives each packing once.
- IR derived columns / capacity facts: the padded layout with columns
  stripped (legacy in-program derivation) produces bitwise-identical
  core-check results.
- packed-only IRs degrade exactly like bare PackedTxns.
"""

import dataclasses

import numpy as np
import pytest

from jepsen_tpu.checkers import api as checker_api
from jepsen_tpu.history.ir import IR_VERSION, HistoryIR
from jepsen_tpu.history.ops import INVOKE, OK, History, Op
from jepsen_tpu.history.soa import pack_txns
from jepsen_tpu.workloads import synth


def _txn(ops, p, filled):
    ops.append(Op(type=INVOKE, process=p, f="txn",
                  value=[[m[0], m[1], None if m[0] == "r" else m[2]]
                         for m in filled]))
    ops.append(Op(type=OK, process=p, f="txn", value=filled))


def _la_history(invalid=False):
    h = synth.la_history(n_txns=80, n_keys=4, concurrency=5,
                         multi_append_prob=0.2, seed=11)
    if invalid:
        synth.inject_wr_cycle(h)
        synth.inject_g1a(h)
    return h


def _rw_history():
    ops = []
    _txn(ops, 0, [["r", 0, None], ["w", 0, 1]])
    _txn(ops, 1, [["r", 0, 1], ["w", 1, 5]])
    _txn(ops, 0, [["r", 1, 5]])
    return History(ops)


def _bank_history():
    ops = []
    ops.append(Op(type=INVOKE, process=0, f="read", value=None))
    ops.append(Op(type=OK, process=0, f="read", value={0: 5, 1: 5}))
    ops.append(Op(type=INVOKE, process=1, f="transfer",
                  value={"from": 0, "to": 1, "amount": 2}))
    ops.append(Op(type=OK, process=1, f="transfer",
                  value={"from": 0, "to": 1, "amount": 2}))
    ops.append(Op(type=INVOKE, process=0, f="read", value=None))
    ops.append(Op(type=OK, process=0, f="read", value={0: 3, 1: 7}))
    return History(ops)


# ------------------------------------------------- round-trip per family

def test_ir_roundtrip_list_append():
    from jepsen_tpu.checkers.elle import list_append

    for invalid in (False, True):
        h = _la_history(invalid)
        raw = list_append.check(h, ("strict-serializable",))
        via = list_append.check(HistoryIR.of(h),
                                ("strict-serializable",))
        assert via["valid?"] == raw["valid?"]
        assert sorted(via["anomaly-types"]) == sorted(raw["anomaly-types"])


def test_ir_roundtrip_rw_register():
    from jepsen_tpu.checkers.elle import rw_register

    h = _rw_history()
    raw = rw_register.check(h)
    via = rw_register.check(HistoryIR.of(h))
    assert via["valid?"] == raw["valid?"]
    assert sorted(via["anomaly-types"]) == sorted(raw["anomaly-types"])


def test_ir_roundtrip_invariants_families():
    from jepsen_tpu.checkers.invariants import bank as inv_bank
    from jepsen_tpu.checkers.invariants import predicate as inv_pred
    from jepsen_tpu.checkers.invariants import session as inv_sess

    hb = _bank_history()
    raw = inv_bank.check(hb, {"accounts": {0: 5, 1: 5}})
    via = inv_bank.check(HistoryIR.of(hb), {"accounts": {0: 5, 1: 5}})
    assert via["valid?"] == raw["valid?"]
    assert via.get("anomaly-types") == raw.get("anomaly-types")

    hr = _rw_history()
    for mod in (inv_pred, inv_sess):
        raw = mod.check(hr, use_device=False)
        via = mod.check(HistoryIR.of(hr), use_device=False)
        assert via["valid?"] == raw["valid?"]
        assert via.get("anomaly-types") == raw.get("anomaly-types")


def test_ir_roundtrip_knossos():
    from jepsen_tpu.checkers.knossos import analysis
    from jepsen_tpu.models import register

    ops = [
        Op(type=INVOKE, process=0, f="write", value=1),
        Op(type=OK, process=0, f="write", value=1),
        Op(type=INVOKE, process=1, f="read", value=None),
        Op(type=OK, process=1, f="read", value=1),
    ]
    h = History(ops)
    ir = HistoryIR.of(h)
    raw = analysis(h, register())
    via = analysis(ir, register())
    assert via["valid?"] == raw["valid?"] is True
    # the entry table is the memoized IR section
    assert ir.lin_ops() is ir.lin_ops()


# ------------------------------------------------------ caching contract

def test_ir_sections_memoized_and_shared():
    h = _rw_history()
    ir = HistoryIR.of(h)
    assert HistoryIR.of(ir) is ir
    assert ir.packed("rw-register") is ir.packed("rw-register")
    assert ir.rw_inference() is ir.rw_inference()
    # the IR is a History: plain consumers see the same ops
    assert len(ir) == len(h)
    assert list(ir) == list(h.ops)

    la = HistoryIR.of(_la_history())
    assert la.padded("list-append") is la.padded("list-append")
    lay = la.layout()
    assert lay["version"] == IR_VERSION
    assert lay["derived_columns"] is True


def test_compose_wraps_history_in_one_ir():
    """A composed check hands every sub-checker the SAME IR (each
    family's packing derives once)."""
    seen = []

    class Probe(checker_api.Checker):
        def check(self, test, history, opts=None):
            seen.append(history)
            return {"valid?": True}

    comp = checker_api.compose({"a": Probe(), "b": Probe()})
    h = _rw_history()
    res = comp.check({}, h, {})
    assert res["valid?"] is True
    assert len(seen) == 2
    assert isinstance(seen[0], HistoryIR)
    assert seen[0] is seen[1]
    assert seen[0].ops is h.ops


def test_packed_only_ir_degrades_like_packed():
    from jepsen_tpu.checkers.invariants import session as inv_sess

    p = pack_txns(_rw_history(), "rw-register")
    ir = HistoryIR.of(p)
    assert ir.packed_only
    res = inv_sess.check(ir)
    raw = inv_sess.check(p)
    assert res["valid?"] == raw["valid?"]


# ------------------------------- derived columns == in-program derivation

def _strip_ir(h):
    return dataclasses.replace(
        h, v_cap=0, o_cap=0, app_val_mono=False, rd_start_mono=False,
        proc_seq=False, run_sort=None, inv_run=None, key_ord_len=None,
        key_ord_read=None, proc_order=None, barrier_order=None,
        barrier_bi=None)


@pytest.mark.parametrize("seed", [0, 5, 9])
def test_ir_columns_bitwise_equal_to_legacy_layout(seed):
    """pad_packed's capacity facts + derived-order columns change the
    program, never the bits: stripping every v2 fact (the legacy
    R-sized, in-program-derivation layout) yields identical core-check
    results on valid AND corrupted histories."""
    from jepsen_tpu.checkers.elle.device_core import core_check
    from jepsen_tpu.checkers.elle.device_infer import pad_packed

    h = synth.la_history(n_txns=100, n_keys=5, concurrency=6,
                         multi_append_prob=0.25, seed=seed)
    if seed % 2:
        synth.inject_rw_cycle(h)
        synth.inject_g1b(h)
    p = pack_txns(h, "list-append")
    hp = pad_packed(p)
    assert hp.run_sort is not None and hp.v_cap and hp.o_cap
    bits_v2, over_v2 = core_check(hp, p.n_keys)
    bits_v1, over_v1 = core_check(_strip_ir(hp), p.n_keys)
    assert np.array_equal(np.asarray(bits_v2), np.asarray(bits_v1))
    assert int(np.asarray(over_v2)) == int(np.asarray(over_v1))
