"""checkers/queue/ — packed queue/kafka anomaly passes (ISSUE 19).

Covers the tentpole contracts:

- **completeness**: every adversarial-client shape produces a history
  the matching checker attributes — dup-send and zombie-resend to
  ``duplicate``, torn-send to ``lost-write``, reorder-send to
  ``int-send-skip``/``nonmonotonic-send``, frozen offset commits to
  ``stale-consumer-group`` — and clean traffic stays valid;
- **differential twins**: on every corpus (including adversarial
  ones) the packed host path, the device path, and the legacy scan
  checkers (`workloads.kafka.KafkaChecker`,
  `checkers.api.TotalQueueChecker`) agree verdict for verdict;
- **resilience**: chaos on the ``queue.check`` seam degrades to the
  host path with the identical verdict, never a changed one;
- the **golden queue witness**: the checked-in minimal witness for a
  seeded torn-send history (tests/data/witness-queue-lost-golden.json)
  — shrinking reproduces the digest and the witness NAMES the lost
  message;
- the **acceptance pin**: an invalid kafka campaign cell (torn-send
  adversary) auto-shrinks to a witness whose re-check names the
  lost message's key, value, and acked offset.
"""

import json
import os
import random

import pytest

from jepsen_tpu import core as jcore
from jepsen_tpu import minimize, store
from jepsen_tpu.checkers import api as checker_api
from jepsen_tpu.checkers.queue import fifo as q_fifo
from jepsen_tpu.checkers.queue import kafka as q_kafka
from jepsen_tpu.history.ops import history as mk_history
from jepsen_tpu.resilience import Deadline, FaultPlan, RetryPolicy
from jepsen_tpu.workloads import kafka as wk
from jepsen_tpu.workloads.mem import MemClient, MemStore

GOLDEN = os.path.join(os.path.dirname(__file__), "data",
                      "witness-queue-lost-golden.json")


# ---------------------------------------------------------- helpers

def _sim_kafka(seed, *, ops=80, n_clients=3, freeze=False,
               gen_kw=None, **knobs):
    """A deterministic single-threaded kafka sim: seeded generator,
    seeded per-client adversary rngs, no scheduler noise — the
    corpus IS a function of (seed, knobs)."""
    rng = random.Random(seed)
    st = wk.KafkaStore()
    st.freeze_commits = freeze
    clients = [wk.KafkaClient(st, rng=random.Random(seed * 100 + i),
                              **knobs)
               for i in range(n_clients)]
    for c in clients:
        c.member = st.new_member()
    g = wk.gen(rng=rng, **(gen_kw or dict(
        key_count=3, crash_frac=0.05, subscribe_frac=0.5,
        txn_frac=0.3)))
    raw, idx = [], 0
    for i in range(ops):
        c = clients[i % n_clients]
        op = dict(g(None, None), process=i % n_clients,
                  index=idx, type="invoke")
        idx += 1
        raw.append(op)
        done = dict(c.invoke(None, dict(op)), index=idx)
        idx += 1
        raw.append(done)
    return mk_history(raw, reindex=False)


def _triple(h):
    """(legacy scan twin, packed host, packed device) verdicts —
    device stripped of its ``degraded`` flag for comparability."""
    twin = wk.KafkaChecker().check(None, h, {})
    host = q_kafka.check(h, use_device=False)
    dev = q_kafka.check(h, use_device=True)
    dev.pop("degraded", None)
    return twin, host, dev


def _sim_mem_queue(seed, *, ops=60, drain=True, **knobs):
    rng = random.Random(seed)
    mc = MemClient(MemStore(), rng=random.Random(seed + 1),
                   **knobs).open(None, "n1")
    raw, idx, counter = [], 0, 0
    for i in range(ops):
        if rng.random() < 0.5:
            op = {"f": "enqueue", "value": counter}
            counter += 1
        else:
            op = {"f": "dequeue", "value": None}
        op = dict(op, process=i % 3, index=idx, type="invoke")
        idx += 1
        raw.append(op)
        out = dict(mc.invoke(None, dict(op)), index=idx)
        idx += 1
        raw.append(out)
    while drain:
        op = {"f": "dequeue", "value": None, "process": 3,
              "index": idx, "type": "invoke"}
        idx += 1
        raw.append(op)
        out = dict(mc.invoke(None, dict(op)), index=idx)
        idx += 1
        raw.append(out)
        if out["type"] == "fail":
            break
    return mk_history(raw, reindex=False)


# --------------------------------------------------- drift pins

def test_stale_min_polls_pinned_to_twin():
    """The packed checker and the scan twin must agree on when a
    consumer group counts as observed-then-stale, or the differential
    contract silently breaks."""
    assert wk.STALE_MIN_POLLS == q_kafka.STALE_MIN_POLLS


def test_adversary_sites_cover_every_shape():
    assert sorted(wk.ADVERSARY_SITES.values()) == \
        ["dup-send", "reorder-send", "torn-send", "zombie-resend"]


# ----------------------------------------- completeness + parity

SHAPES = [
    ("dup-send", dict(dup_send_p=0.3), {"duplicate"}),
    ("zombie-resend", dict(zombie_p=0.3), {"duplicate"}),
    ("torn-send", dict(torn_p=0.5), {"lost-write"}),
    ("reorder-send", dict(reorder_p=0.5),
     {"int-send-skip", "nonmonotonic-send"}),
]


@pytest.mark.parametrize("shape,knobs,expected",
                         SHAPES, ids=[s[0] for s in SHAPES])
def test_injected_anomaly_detected_and_twins_agree(
        shape, knobs, expected):
    """Each adversarial-client shape is ATTRIBUTED (the expected
    anomaly appears across the seeded corpus) and every corpus —
    clean or broken — keeps twin == packed host == packed device."""
    seen = set()
    for seed in range(10):
        h = _sim_kafka(seed, **knobs)
        twin, host, dev = _triple(h)
        assert host == twin, f"{shape} s{seed}: host != twin"
        assert dev == twin, f"{shape} s{seed}: device != twin"
        seen.update(twin.get("anomaly-types") or [])
    assert seen & expected, \
        f"{shape}: expected one of {expected}, corpus showed {seen}"


def test_stale_consumer_group_detected_and_twins_agree():
    seen = set()
    for seed in range(8):
        h = _sim_kafka(seed, ops=60, n_clients=2, freeze=True,
                       gen_kw=dict(key_count=2, subscribe_frac=0.2))
        twin, host, dev = _triple(h)
        assert host == twin and dev == twin
        seen.update(twin.get("anomaly-types") or [])
    assert "stale-consumer-group" in seen


def test_clean_controls_stay_valid():
    for seed in range(4):
        h = _sim_kafka(seed, gen_kw=dict(
            key_count=3, crash_frac=0.0, subscribe_frac=0.5,
            txn_frac=0.3))
        twin, host, dev = _triple(h)
        assert twin["valid?"] is True
        assert host == twin and dev == twin


def test_chaos_on_check_seam_degrades_to_host_verdict():
    """queue.check faults flip the device pass to the host scan —
    same verdict, ``degraded`` flagged, injections logged."""
    h = _sim_kafka(2, dup_send_p=0.2, torn_p=0.3)
    host = q_kafka.check(h, use_device=False)
    plan = FaultPlan(seed=5, p=1.0, kinds=("oom",),
                     sites="queue.check")
    pol = RetryPolicy(max_attempts=2, base_delay_s=0.0,
                      max_delay_s=0.0)
    dev = q_kafka.check(h, plan=plan, policy=pol,
                        deadline=Deadline(30.0))
    assert plan.injected, "the chaos plan never fired"
    assert dev.pop("degraded", None) == "host-fallback"
    assert dev == host


# ------------------------------------ mem-store queue adversaries

def test_mem_queue_lose_enqueue_attributed_as_lost():
    h = _sim_mem_queue(0, lose_enqueue_p=1.0)
    twin = checker_api.TotalQueueChecker().check(None, h, {})
    host = q_fifo.check(h, fifo=True, use_device=False)
    assert twin["valid?"] is False
    assert "queue-lost" in host["anomaly-types"]
    for k, v in twin.items():
        assert host[k] == v


def test_mem_queue_dup_enqueue_attributed_as_phantom():
    h = _sim_mem_queue(1, dup_enqueue_p=1.0)
    twin = checker_api.TotalQueueChecker().check(None, h, {})
    host = q_fifo.check(h, fifo=True, use_device=False)
    assert twin["valid?"] is False
    assert "queue-phantom" in host["anomaly-types"]
    for k, v in twin.items():
        assert host[k] == v


def test_mem_queue_reorder_trips_fifo_mode_only():
    """The reorder knob reorders deliveries without losing or
    duplicating anything: the total-queue contract stays valid, the
    stricter FIFO pass attributes the violation."""
    hit = False
    for seed in range(6):
        h = _sim_mem_queue(seed, reorder_dequeue_p=0.5)
        total = q_fifo.check(h, fifo=False, use_device=False)
        fifo = q_fifo.check(h, fifo=True, use_device=False)
        twin = checker_api.TotalQueueChecker().check(None, h, {})
        for k, v in twin.items():
            assert total[k] == v
        if "queue-fifo-violation" in (fifo.get("anomaly-types") or []):
            hit = True
            assert total["valid?"] is True
    assert hit, "reorder knob never produced a FIFO violation"


def test_mem_queue_device_matches_host():
    for seed in range(4):
        h = _sim_mem_queue(seed, dup_enqueue_p=0.2,
                           lose_enqueue_p=0.1,
                           reorder_dequeue_p=0.3)
        host = q_fifo.check(h, fifo=True, use_device=False)
        dev = q_fifo.check(h, fifo=True, use_device=True)
        dev.pop("degraded", None)
        assert dev == host


# ---------------------------------------------------------- golden

def _save_run(tmp_path, h, name="queue-inv"):
    base = str(tmp_path / "s")
    test = jcore.noop_test(name=name)
    test["store-dir"] = base
    test["history"] = h
    store.save_0(test)
    test["results"] = q_kafka.check(h, use_device=False)
    store.save_1(test)
    return base, store.test_dir(test)


def test_golden_queue_witness(tmp_path):
    """The checked-in minimal witness for the canonical seeded
    torn-send history: shrinking must reproduce the golden digest and
    ops, and the witness names WHICH message was lost (key, value,
    acked offset)."""
    with open(GOLDEN) as f:
        golden = json.load(f)
    g = golden["generator"]
    h = _sim_kafka(g["seed"], ops=g["ops"], torn_p=g["torn_p"])
    assert q_kafka.check(h, use_device=False)["valid?"] is False
    base, d = _save_run(tmp_path, h)
    s = minimize.shrink(d, host_oracle=True, anomalies="lost-write")
    assert s["digest"] == golden["digest"]
    got = json.loads(json.dumps(
        [[op.type, op.process, op.f, op.value]
         for op in s["witness-history"]], default=str))
    assert got == golden["ops"]
    res = q_kafka.check(s["witness-history"], use_device=False)
    lost = [list(e) for e in res["anomalies"]["lost-write"]]
    assert lost == golden["lost"]


# ------------------------------------------------- acceptance pin

def test_campaign_kafka_cell_autoshrinks_naming_lost_message(
        tmp_path):
    """ISSUE 19 acceptance: an invalid queue campaign cell (torn-send
    adversary) auto-shrinks to a witness that names the lost
    message."""
    from jepsen_tpu import campaign

    base = str(tmp_path / "s")
    spec = {"name": "queue-accept",
            "workloads": [{"name": "kafka", "label": "kafka-torn",
                           "opts": {"queue-adversary":
                                    {"torn-p": 0.6},
                                    "kafka-txn-frac": 0.6,
                                    "kafka-subscribe-frac": 0.3,
                                    "kafka-crash-frac": 0.0}}],
            "seeds": [3],
            "opts": {"ops": 150, "concurrency": 2,
                     "time-limit": 1.0, "client-latency": 0.0,
                     "shrink": {"host-oracle": True,
                                "probe-deadline": 20}}}
    summary = campaign.run_campaign(spec, base, workers=1)
    row = summary["rows"][0]
    assert row["valid?"] is False
    w = row["witness"]
    assert w and not w.get("error"), row
    assert "lost-write" in w["anomaly-types"]
    wit = minimize.load_witness(os.path.join(base, row["dir"]))
    res = q_kafka.check(wit["history"], use_device=False)
    lost = res["anomalies"]["lost-write"]
    assert lost, "witness re-check lost the lost-write attribution"
    k, off, v = lost[0]
    # the named message was really acked at that offset by a send
    # mop inside the witness itself
    acked = {(m[1], tuple(m[2]) if isinstance(m[2], list)
              else m[2])
             for op in wit["history"]
             if op.type == "ok" and isinstance(op.value, list)
             for m in op.value
             if isinstance(m, (list, tuple)) and m
             and m[0] == "send" and m[2] is not None}
    assert (k, (off, v)) in acked, \
        f"lost message {(k, off, v)} not acked in the witness"
