"""History substrate tests (mirrors the reference's history test strategy)."""

import numpy as np
import pytest

from jepsen_tpu.history import History, Op, history, invoke, ok, fail, info
from jepsen_tpu.history.soa import (
    MOP_APPEND, MOP_READ, TXN_FAIL, TXN_INFO, TXN_OK, pack_txns,
)


def test_pair_index_basic():
    h = history([
        invoke(0, "txn", [["r", 0, None]]),
        invoke(1, "txn", [["append", 0, 1]]),
        ok(1, "txn", [["append", 0, 1]]),
        ok(0, "txn", [["r", 0, [1]]]),
    ])
    assert h.pair_index(0) == 3
    assert h.pair_index(3) == 0
    assert h.pair_index(1) == 2
    assert h.completion(h[0]).index == 3
    assert h.invocation(h[2]).index == 1


def test_info_stays_unpaired_after_crash():
    h = history([
        invoke(0, "txn", [["append", 0, 1]]),
        info(0, "txn", None),       # crash: pairs with the invoke
        invoke(1, "txn", [["r", 0, None]]),
        ok(1, "txn", [["r", 0, [1]]]),
    ])
    assert h.pair_index(0) == 1
    assert h[1].is_info()


def test_double_invoke_raises():
    with pytest.raises(ValueError):
        history([
            invoke(0, "txn", None),
            invoke(0, "txn", None),
        ])


def test_filters_preserve_indices():
    h = history([
        invoke(0, "txn", None),
        ok(0, "txn", None),
        invoke(0, "txn", None),
        fail(0, "txn", None),
    ])
    oks = h.oks()
    assert [o.index for o in oks] == [1]
    assert [o.index for o in h.fails()] == [3]


def test_pack_txns_list_append():
    h = history([
        invoke(0, "txn", [["append", "x", 1], ["r", "y", None]]),
        ok(0, "txn", [["append", "x", 1], ["r", "y", [9]]]),
        invoke(1, "txn", [["append", "y", 9]]),
        fail(1, "txn", [["append", "y", 9]]),
        invoke(2, "txn", [["append", "x", 2]]),
        info(2, "txn", None),
    ])
    p = pack_txns(h)
    assert p.n_txns == 3
    assert list(p.txn_type) == [TXN_OK, TXN_FAIL, TXN_INFO]
    # ok txn: 2 mops, read filled
    assert p.mop_kind[0] == MOP_APPEND and p.mop_kind[1] == MOP_READ
    assert p.mop_rd_len[1] == 1
    # fail txn: append known, from invocation
    assert p.mop_kind[2] == MOP_APPEND
    # info txn: mops from invocation
    assert p.mop_kind[3] == MOP_APPEND
    # key/value interning round-trips
    assert p.key_names[p.mop_key[0]] == "x"
    ki, v = p.val_names[p.mop_val[0]]
    assert (p.key_names[ki], v) == ("x", 1)
    # the ok read of y observes the failed append's value id
    assert p.rd_elems[0] == p.mop_val[2]


def test_pack_txns_rw_register():
    h = history([
        invoke(0, "txn", [["w", "x", 1], ["r", "x", None]]),
        ok(0, "txn", [["w", "x", 1], ["r", "x", 1]]),
        invoke(1, "txn", [["r", "y", None]]),
        ok(1, "txn", [["r", "y", None]]),  # nil read (unborn)
    ])
    p = pack_txns(h, workload="rw-register")
    assert p.n_txns == 2
    assert p.mop_val[1] == p.mop_val[0]  # read sees the write's value id
    assert p.mop_val[2] == -1            # nil read
    assert p.mop_rd_len[2] == 0          # known read


def test_save_load_packed_roundtrip(tmp_path):
    """Prestaged bench inputs (utils/prestage.py) round-trip bit-exactly,
    including the lazy dense val_names map."""
    import numpy as np

    from jepsen_tpu.history.soa import load_packed, save_packed
    from jepsen_tpu.workloads import synth

    p = synth.packed_la_history(n_txns=300, n_keys=32, mops_per_txn=4,
                                read_frac=0.25, seed=7)
    path = str(tmp_path / "t.npz")
    save_packed(path, p)
    q = load_packed(path)
    for c in ("txn_type", "txn_process", "txn_invoke_pos",
              "txn_complete_pos", "txn_orig_index", "mop_txn", "mop_kind",
              "mop_key", "mop_val", "mop_rd_start", "mop_rd_len",
              "rd_elems"):
        assert np.array_equal(getattr(p, c), getattr(q, c)), c
    assert (q.n_keys, q.n_vals, q.n_events) == (p.n_keys, p.n_vals,
                                                p.n_events)
    assert q.val_names[5] == p.val_names[5]
    assert len(q.val_names) == len(p.val_names)


def test_prestage_generate_then_load(tmp_path, monkeypatch):
    import numpy as np

    monkeypatch.setenv("JT_PRESTAGE_DIR", str(tmp_path))
    from jepsen_tpu.utils import prestage

    a = prestage.rw_history(n_txns=200, n_keys=16, save=True, verbose=False)
    assert len(list(tmp_path.glob("rw_v*.npz"))) == 1
    b = prestage.rw_history(n_txns=200, n_keys=16, verbose=False)
    assert np.array_equal(a.mop_val, b.mop_val)
    assert b.n_vals == a.n_vals
