"""Fold tests: parallel result == serial reference, fold fusion runs one
pass, non-associative stays serial (reference history.fold's generative
strategy, SURVEY.md §2.2/§4)."""

import random

import pytest

from jepsen_tpu.history import fold as F
from jepsen_tpu.history.ops import History, Op, history, invoke, ok


def _mk(n, seed=0):
    rng = random.Random(seed)
    ops = []
    for i in range(n // 2):
        p = rng.randrange(5)
        f = rng.choice(["read", "write"])
        ops.append(Op(type="invoke", process=p, f=f, value=i))
        ops.append(Op(type=rng.choice(["ok", "fail", "info"]),
                      process=p, f=f, value=i))
    return history(ops)


def test_count_parallel_equals_serial():
    h = _mk(50_000, seed=1)
    folder = F.Folder(h)
    assert folder.fold(F.count_fold()) == 50_000
    ok_count = folder.fold(F.count_fold(lambda o: o.type == "ok"))
    assert ok_count == sum(1 for o in h if o.type == "ok")


def test_group_count_matches():
    h = _mk(30_000, seed=2)
    folder = F.Folder(h)
    got = folder.fold(F.group_count_fold(lambda o: o.f))
    want = {}
    for o in h:
        want[o.f] = want.get(o.f, 0) + 1
    assert got == want


def test_collect_preserves_order():
    h = _mk(40_000, seed=3)
    folder = F.Folder(h)
    got = folder.fold(F.collect_fold(lambda o: o.type == "ok",
                                     lambda o: o.index))
    want = [o.index for o in h if o.type == "ok"]
    assert got == want  # ordered combine keeps chunk order


def test_fusion_single_pass():
    h = _mk(5000, seed=4)
    seen = []

    def make_counting_fold(name):
        def red(acc, op):
            seen.append(name)
            return acc + 1
        return F.fold_spec(name=name, reducer_identity=lambda: 0,
                           reducer=red, combiner_identity=lambda: 0,
                           combiner=lambda a, b: a + b)

    folder = F.Folder(h, max_workers=1)
    r = folder.fold_many([make_counting_fold("a"), make_counting_fold("b")])
    assert r == [5000, 5000]
    # fused: both reducers saw each op exactly once -> 2 * n total calls
    assert len(seen) == 10_000


def test_non_associative_serial():
    h = _mk(40_000, seed=5)
    # a deliberately order-sensitive fold: build a "hash" of indices
    f = F.fold_spec(
        name="order-hash", associative=False,
        reducer_identity=lambda: 0,
        reducer=lambda acc, op: (acc * 31 + op.index) % (2 ** 61 - 1))
    got = F.Folder(h).fold(f)
    want = 0
    for op in h:
        want = (want * 31 + op.index) % (2 ** 61 - 1)
    assert got == want


def test_associative_without_combiner_raises():
    f = F.fold_spec(reducer_identity=lambda: 0,
                    reducer=lambda a, o: a + 1)
    with pytest.raises(TypeError):
        F.Folder(_mk(10)).fold(f)


def test_folder_over_lazy_history(tmp_path):
    from jepsen_tpu.store.format import CHUNK_SIZE, JepsenFile

    n = CHUNK_SIZE + 100
    ops = []
    for i in range(n // 2):
        ops.append(invoke(i % 5, "read", None))
        ops.append(ok(i % 5, "read", i))
    p = str(tmp_path / "t.jepsen")
    JepsenFile(p).write_test({"name": "f"}, History(ops))
    lh = JepsenFile(p).read_history()
    folder = F.Folder(lh)
    assert folder.fold(F.count_fold()) == len(ops)
    assert len(folder._thunks) == 2


def test_folder_lazy_history_not_materialized(tmp_path):
    # binding a Folder to a LazyHistory must not decode every chunk up
    # front (ADVICE round 1): decode happens inside the pass, bounded by
    # the LazyHistory's own LRU
    from jepsen_tpu.store.format import CHUNK_SIZE, JepsenFile

    n = 3 * CHUNK_SIZE
    ops = []
    for i in range(n // 2):
        ops.append(invoke(i % 5, "read", None))
        ops.append(ok(i % 5, "read", i))
    p = str(tmp_path / "t.jepsen")
    JepsenFile(p).write_test({"name": "f"}, History(ops))
    lh = JepsenFile(p).read_history()
    folder = F.Folder(lh)
    assert len(lh._cache) == 0  # nothing decoded yet
    assert folder.fold(F.count_fold()) == len(ops)
    assert len(lh._cache) > 0


def test_folder_empty_lazy_columnar(tmp_path):
    from jepsen_tpu.store.format import JepsenFile

    p = str(tmp_path / "e.jepsen")
    JepsenFile(p).write_test({"name": "e"}, History([]))
    lh = JepsenFile(p).read_history()
    assert F.Folder(lh, columnar=True).fold(F.count_fold()) == 0


def test_folder_rejects_raw_dict_chunks():
    # a history passed as raw op dicts must error, not fold garbage
    with pytest.raises(TypeError):
        F.Folder([{"type": "ok", "f": "read"}, {"type": "ok", "f": "w"}])


def test_concurrent_submit_fusion():
    import concurrent.futures as fut

    h = _mk(20_000, seed=7)
    with F.Folder(h) as folder:
        futures = [folder.submit(F.count_fold()) for _ in range(6)]
        futures.append(folder.submit(F.group_count_fold(lambda o: o.f)))
        done = fut.wait(futures, timeout=30)
        assert not done.not_done
        assert all(f.result() == 20_000 for f in futures[:6])
        want = {}
        for o in h:
            want[o.f] = want.get(o.f, 0) + 1
        assert futures[6].result() == want


def test_submit_error_delivered():
    h = _mk(1000, seed=8)

    def boom(acc, op):
        raise RuntimeError("bad reducer")

    f = F.fold_spec(name="boom", reducer_identity=lambda: 0, reducer=boom,
                    combiner_identity=lambda: 0,
                    combiner=lambda a, b: a + b)
    with F.Folder(h) as folder:
        with pytest.raises(RuntimeError):
            folder.submit(f).result(timeout=30)


def test_columnar_folds_match_per_op():
    h = _mk(30_000, seed=9)
    per_op = F.Folder(h)
    col = F.Folder(h, columnar=True)
    assert col.fold(F.count_fold()) == per_op.fold(F.count_fold())
    assert col.fold(F.type_count_fold()) == per_op.fold(F.type_count_fold())
    assert col.fold(F.group_count_fold(column="f")) == \
        per_op.fold(F.group_count_fold(column="f"))


def test_columnar_throughput_1m():
    # The absolute >=1e6 ops/s bar lives in PROFILE.md / bench territory;
    # an absolute wall-clock assert in the unit suite is flaky under
    # machine load (it failed full-suite runs while passing alone in
    # round 2).  Here the gate is machine-RELATIVE: the fused columnar
    # pass must beat a plain per-op Python fold measured on the same
    # machine in the same process, by a margin far larger than noise.
    import time

    h = _mk(1_000_000, seed=10)
    t0 = time.perf_counter()
    folder = F.Folder(h, columnar=True)
    n, by_type = folder.fold_many([F.count_fold(), F.type_count_fold()])
    dt_col = time.perf_counter() - t0
    assert n == 1_000_000
    assert sum(by_type.values()) == 1_000_000

    # same-machine reference: the generic per-op Folder path on the same
    # history (the machinery the columnar fast path replaces), measured
    # in the same process so machine load cancels out.  The first
    # columnar pass pays a one-time Python column-extraction build, so
    # the gate is on the design claim that actually matters for repeated
    # checking: once columns exist, folds are numpy-speed — the memoized
    # pass must beat the per-op path by far more than timing noise.
    t0 = time.perf_counter()
    n2, by2 = F.Folder(h).fold_many([F.count_fold(), F.type_count_fold()])
    dt_per_op = time.perf_counter() - t0
    assert (n2, by2) == (n, by_type)

    t0 = time.perf_counter()
    by3 = folder.fold(F.type_count_fold())
    dt_memo = time.perf_counter() - t0
    assert by3 == by_type
    assert dt_memo * 3 < dt_per_op, (
        f"memoized columnar {n / dt_memo:.0f} ops/s not >=3x per-op "
        f"Folder {n / dt_per_op:.0f} ops/s")
    # (no absolute bound on the one-time column build: it is a
    # single-threaded Python pass whose constant factor vs the threaded
    # per-op path varies with machine load — cost lives in PROFILE.md)


def test_stats_checker_columnar_matches_loop():
    from jepsen_tpu.checkers.api import Stats

    h = _mk(80_000, seed=11)  # above COLUMNAR_MIN -> columnar path
    st = Stats()
    got = st.check({}, h)
    by_f, total = Stats._loop_counts(h)
    assert got["count"] == sum(total.values())
    assert got["ok-count"] == total["ok"]
    for f, c in by_f.items():
        assert got["by-f"][f]["count"] == sum(c.values())
