"""Fold tests: parallel result == serial reference, fold fusion runs one
pass, non-associative stays serial (reference history.fold's generative
strategy, SURVEY.md §2.2/§4)."""

import random

import pytest

from jepsen_tpu.history import fold as F
from jepsen_tpu.history.ops import History, Op, history, invoke, ok


def _mk(n, seed=0):
    rng = random.Random(seed)
    ops = []
    for i in range(n // 2):
        p = rng.randrange(5)
        f = rng.choice(["read", "write"])
        ops.append(Op(type="invoke", process=p, f=f, value=i))
        ops.append(Op(type=rng.choice(["ok", "fail", "info"]),
                      process=p, f=f, value=i))
    return history(ops)


def test_count_parallel_equals_serial():
    h = _mk(50_000, seed=1)
    folder = F.Folder(h)
    assert folder.fold(F.count_fold()) == 50_000
    ok_count = folder.fold(F.count_fold(lambda o: o.type == "ok"))
    assert ok_count == sum(1 for o in h if o.type == "ok")


def test_group_count_matches():
    h = _mk(30_000, seed=2)
    folder = F.Folder(h)
    got = folder.fold(F.group_count_fold(lambda o: o.f))
    want = {}
    for o in h:
        want[o.f] = want.get(o.f, 0) + 1
    assert got == want


def test_collect_preserves_order():
    h = _mk(40_000, seed=3)
    folder = F.Folder(h)
    got = folder.fold(F.collect_fold(lambda o: o.type == "ok",
                                     lambda o: o.index))
    want = [o.index for o in h if o.type == "ok"]
    assert got == want  # ordered combine keeps chunk order


def test_fusion_single_pass():
    h = _mk(5000, seed=4)
    seen = []

    def make_counting_fold(name):
        def red(acc, op):
            seen.append(name)
            return acc + 1
        return F.fold_spec(name=name, reducer_identity=lambda: 0,
                           reducer=red, combiner_identity=lambda: 0,
                           combiner=lambda a, b: a + b)

    folder = F.Folder(h, max_workers=1)
    r = folder.fold_many([make_counting_fold("a"), make_counting_fold("b")])
    assert r == [5000, 5000]
    # fused: both reducers saw each op exactly once -> 2 * n total calls
    assert len(seen) == 10_000


def test_non_associative_serial():
    h = _mk(40_000, seed=5)
    # a deliberately order-sensitive fold: build a "hash" of indices
    f = F.fold_spec(
        name="order-hash", associative=False,
        reducer_identity=lambda: 0,
        reducer=lambda acc, op: (acc * 31 + op.index) % (2 ** 61 - 1))
    got = F.Folder(h).fold(f)
    want = 0
    for op in h:
        want = (want * 31 + op.index) % (2 ** 61 - 1)
    assert got == want


def test_associative_without_combiner_raises():
    f = F.fold_spec(reducer_identity=lambda: 0,
                    reducer=lambda a, o: a + 1)
    with pytest.raises(TypeError):
        F.Folder(_mk(10)).fold(f)


def test_folder_over_lazy_history(tmp_path):
    from jepsen_tpu.store.format import CHUNK_SIZE, JepsenFile

    n = CHUNK_SIZE + 100
    ops = []
    for i in range(n // 2):
        ops.append(invoke(i % 5, "read", None))
        ops.append(ok(i % 5, "read", i))
    p = str(tmp_path / "t.jepsen")
    JepsenFile(p).write_test({"name": "f"}, History(ops))
    lh = JepsenFile(p).read_history()
    folder = F.Folder(lh)
    assert folder.fold(F.count_fold()) == len(ops)
    assert len(folder._chunks) == 2
