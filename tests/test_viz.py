"""Elle viz tests: cycle witnesses -> SVG files (SURVEY.md §2.3 viz.clj)."""

import os

from jepsen_tpu.checkers.elle import oracle, viz
from jepsen_tpu.workloads import synth


def test_render_cycle_basic(tmp_path):
    cycle = [{"src": 0, "rel": "ww", "dst": 4},
             {"src": 4, "rel": "rw", "dst": 8},
             {"src": 8, "rel": "wr", "dst": 0}]
    p = str(tmp_path / "c.svg")
    out = viz.render_cycle(cycle, p, title="G2 demo")
    svg = open(out).read()
    assert svg.startswith("<svg")
    assert svg.count("<circle") == 3
    assert "ww" in svg and "rw" in svg and "wr" in svg
    assert "G2 demo" in svg


def test_write_anomalies_from_real_check(tmp_path):
    h = synth.la_history(n_txns=120, n_keys=5, concurrency=5, seed=13)
    synth.inject_wr_cycle(h)
    res = oracle.check(h, ["serializable"])
    assert res["valid?"] is False
    out_dir = str(tmp_path / "elle")
    written = viz.write_anomalies(res, out_dir, history=h)
    assert written, "no SVGs written for a failing check"
    for p in written:
        assert os.path.exists(p)
        content = open(p).read()
        assert content.startswith("<svg") and "cycle" in content
    assert res["viz-files"] == written


def test_write_anomalies_noop_for_non_cycles(tmp_path):
    res = {"anomalies": {"duplicate-elements": [{"count": 3}]}}
    assert viz.write_anomalies(res, str(tmp_path / "e")) == []
    assert "viz-files" not in res


def test_viz_for_test_only_on_invalid(tmp_path):
    res = {"valid?": True, "anomalies": {}}
    assert viz.viz_for_test(res, {"name": "x",
                                  "store-dir": str(tmp_path)}) == []


def test_append_checker_writes_viz(tmp_path):
    from jepsen_tpu.workloads.append import AppendChecker

    h = synth.la_history(n_txns=120, n_keys=5, concurrency=5, seed=17)
    synth.inject_wr_cycle(h)
    test = {"name": "viz-run", "store-dir": str(tmp_path / "s")}
    res = AppendChecker().check(test, h)
    assert res["valid?"] is False
    files = res.get("viz-files") or []
    assert files and all("elle" in os.path.dirname(f) for f in files)


def test_render_cycle_includes_explainer_legend(tmp_path):
    cyc = [{"src": 0, "rel": "wr", "dst": 2, "key": "x", "value": 1,
            "why": "T0 read x ending in 1, which T2 appended"},
           {"src": 2, "rel": "rw", "dst": 0, "key": "x", "value'": 2,
            "why": "T2 read x up to 1, before T0's append of 2"}]
    p = str(tmp_path / "c.svg")
    viz.render_cycle(cyc, p, title="G-single")
    svg = open(p).read()
    assert "which T2 appended" in svg          # legend line
    assert "<title>" in svg                    # hover tooltip
    assert "wr &#x27;x&#x27;" in svg or "wr 'x'" in svg  # key on label
