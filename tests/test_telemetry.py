"""Telemetry subsystem tests (ISSUE 1): span tree, metrics registry,
Chrome-trace export, core.run wiring, and the off-by-default-cheap
contract."""

import json
import os
import threading
import time

import pytest

from jepsen_tpu import core, store, telemetry
from jepsen_tpu.checkers import api as checker_api
from jepsen_tpu.generator import core as g
from jepsen_tpu.workloads.mem import MemClient


# ---------------------------------------------------------------- spans

def test_span_nesting_and_attrs():
    c = telemetry.Collector()
    with c.span("a", x=1) as a:
        with c.span("b") as b:
            b.set_attr(y=2)
    assert [r.name for r in c.roots] == ["a"]
    assert a.attrs == {"x": 1}
    assert a.children[0] is b and b.attrs == {"y": 2}
    assert a.duration_ns >= b.duration_ns >= 0


def test_span_threads_get_own_roots():
    c = telemetry.activate()
    try:
        def worker():
            with telemetry.span("w"):
                pass
        with telemetry.span("main"):
            t = threading.Thread(target=worker, name="w-thread")
            t.start()
            t.join()
    finally:
        telemetry.deactivate(c)
    names = sorted(r.name for r in c.roots)
    assert names == ["main", "w"]
    w = next(r for r in c.roots if r.name == "w")
    assert w.thread_name == "w-thread"


def test_traced_decorator_and_current():
    c = telemetry.activate()
    try:
        @telemetry.traced("deco", kind="t")
        def fn():
            assert telemetry.current().name == "deco"
            return 7

        assert fn() == 7
    finally:
        telemetry.deactivate(c)
    assert c.roots[0].name == "deco"
    assert c.roots[0].attrs == {"kind": "t"}


def test_phase_timer_sequential_siblings():
    c = telemetry.Collector()
    with c.span("parent"):
        ph = telemetry.PhaseTimer(c)
        ph.start("p1")
        ph.start("p2", n=3)
        ph.end()
        ph.end()  # idempotent
    (parent,) = c.roots
    assert [s.name for s in parent.children] == ["p1", "p2"]
    assert all(s.duration_ns is not None for s in parent.children)


def test_disabled_is_noop_singleton():
    assert telemetry.active() is telemetry.NOOP
    assert not telemetry.enabled()
    s1 = telemetry.span("x", a=1)
    s2 = telemetry.span("y")
    assert s1 is s2  # one shared object, nothing allocated
    with s1 as sp:
        sp.set_attr(z=2)  # no-op, no error
    assert telemetry.current() is None
    ph = telemetry.phases()
    ph.start("p")
    ph.end()


def test_activate_restores_previous():
    a = telemetry.activate()
    b = telemetry.activate()
    assert telemetry.active() is b
    telemetry.deactivate(b)
    assert telemetry.active() is a
    telemetry.deactivate(a)
    assert telemetry.active() is telemetry.NOOP


def test_open_span_gets_provisional_close():
    c = telemetry.Collector()
    ctx = c.span("never-closed")
    ctx.__enter__()
    c.close_open_spans()
    (root,) = c.roots
    assert root.t1 is not None
    assert root.attrs.get("open") is True


# -------------------------------------------------------------- metrics

def test_metrics_counter_gauge_histogram():
    reg = telemetry.Registry()
    reg.counter("ops", worker="0").inc()
    reg.counter("ops", worker="0").inc(2)
    reg.counter("ops", worker="1").inc()
    reg.gauge("speed").set(3.5)
    h = reg.histogram("lat", buckets=(1.0, 10.0))
    for v in (0.5, 5.0, 50.0, 0.1):
        h.observe(v)
    snap = reg.snapshot()
    counters = {(c["name"], c["labels"].get("worker")): c["value"]
                for c in snap["counters"]}
    assert counters[("ops", "0")] == 3
    assert counters[("ops", "1")] == 1
    assert snap["gauges"][0]["value"] == 3.5
    (hist,) = snap["histograms"]
    assert hist["counts"] == [2, 1, 1]  # <=1, <=10, +inf
    assert hist["count"] == 4 and hist["sum"] == pytest.approx(55.6)


def test_metrics_same_instrument_cached_and_type_checked():
    reg = telemetry.Registry()
    assert reg.counter("x") is reg.counter("x")
    assert reg.counter("x", a=1) is not reg.counter("x", a=2)
    with pytest.raises(TypeError):
        reg.gauge("x")


def test_metrics_thread_safety():
    reg = telemetry.Registry()

    def hammer():
        for _ in range(1000):
            reg.counter("n").inc()

    ts = [threading.Thread(target=hammer) for _ in range(4)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert reg.counter("n").value == 4000


# --------------------------------------------------------------- export

def _collect_sample():
    c = telemetry.Collector()
    with c.span("run", name="s"):
        with c.span("workload") as w:
            time.sleep(0.001)
            w.set_attr(ops=4)
    return c


def test_chrome_trace_shape():
    c = _collect_sample()
    doc = telemetry.chrome_trace(c)
    assert set(doc) == {"traceEvents", "displayTimeUnit"}
    evs = doc["traceEvents"]
    assert all(e["ph"] in ("X", "M") for e in evs)
    xs = [e for e in evs if e["ph"] == "X"]
    assert [e["name"] for e in xs] == ["run", "workload"]
    run, wl = xs
    # nesting holds on the timeline: child contained within parent
    assert run["ts"] <= wl["ts"]
    assert wl["ts"] + wl["dur"] <= run["ts"] + run["dur"] + 1e-3
    # round-trips through json
    json.loads(json.dumps(doc))


def test_snapshot_jsonable_attrs():
    import numpy as np

    c = telemetry.Collector()
    with c.span("s", arr=np.int64(3), st={"a"}, obj=object()):
        pass
    doc = telemetry.snapshot(c, telemetry.Registry())
    attrs = doc["spans"][0]["attrs"]
    assert attrs["arr"] == 3 and attrs["st"] == ["a"]
    assert isinstance(attrs["obj"], str)
    json.dumps(doc)


def test_write_run_and_summarize(tmp_path):
    c = _collect_sample()
    reg = telemetry.Registry()
    reg.counter("interpreter-ops", worker="0", type="ok").inc(4)
    paths = telemetry.write_run(str(tmp_path), c, reg, meta={"name": "s"})
    assert os.path.exists(paths["telemetry"])
    assert os.path.exists(paths["trace"])
    out = telemetry.summarize(str(tmp_path))
    assert "run" in out and "workload" in out
    assert "interpreter-ops" in out


# ------------------------------------------------- core.run integration

def _mem_test(tmp_path, n_ops=12, **kw):
    t = dict(
        name="tel-test",
        client=MemClient(),
        concurrency=2,
        generator=g.clients(g.limit(
            n_ops, lambda t, c: {"f": "write", "value": 1})),
        checker=checker_api.Stats(),
        telemetry=True,
        **{"store-dir": str(tmp_path / "s")},
    )
    t.update(kw)
    return t


def test_noop_test_run_writes_valid_telemetry(tmp_path):
    """Tier-1 smoke (ISSUE 1 satellite): a noop_test run with telemetry
    writes a valid telemetry.json."""
    done = core.run(core.noop_test(
        telemetry=True, **{"store-dir": str(tmp_path / "s")}))
    d = store.test_dir(done)
    doc = json.load(open(os.path.join(d, "telemetry.json")))
    assert doc["version"] == 1
    names = [r["name"] for r in doc["spans"]]
    assert "run" in names
    run = next(r for r in doc["spans"] if r["name"] == "run")
    child_names = [c["name"] for c in run["children"]]
    assert "workload" in child_names
    assert "store.save_0" in child_names and "store.save_1" in child_names
    # trace.json is valid Chrome trace-event JSON
    tr = json.load(open(os.path.join(d, "trace.json")))
    assert isinstance(tr["traceEvents"], list) and tr["traceEvents"]
    assert all(e["ph"] in ("X", "M") for e in tr["traceEvents"])


def test_run_span_tree_matches_phases(tmp_path):
    done = core.run(_mem_test(tmp_path))
    d = store.test_dir(done)
    doc = json.load(open(os.path.join(d, "telemetry.json")))
    run = next(r for r in doc["spans"] if r["name"] == "run")
    kids = [c["name"] for c in run["children"]]
    # phase order: workload before save_0 before check before save_1
    assert kids.index("workload") < kids.index("store.save_0") \
        < kids.index("check:Stats") < kids.index("store.save_1")
    wl = next(c for c in run["children"] if c["name"] == "workload")
    assert wl["attrs"]["ops"] == 24  # 12 invokes + 12 completions
    chk = next(c for c in run["children"] if c["name"] == "check:Stats")
    assert chk["attrs"]["checker"] == "Stats"
    assert chk["attrs"]["valid"] is True
    # interpreter metrics flushed: per-worker invoke/ok counts
    counters = {(c["name"], c["labels"].get("worker"),
                 c["labels"].get("type")): c["value"]
                for c in doc["metrics"]["counters"]}
    # ops are handed to whichever worker asks first, so the per-worker
    # split is scheduling-dependent — assert the labeled totals instead
    op_keys = [k for k in counters if k[0] == "interpreter-ops"]
    assert all(w in ("0", "1") for _, w, _ in op_keys)
    assert sum(counters[k] for k in op_keys if k[2] == "invoke") == 12
    assert sum(counters[k] for k in op_keys if k[2] == "ok") == 12
    assert ("generator-stall-ns", None, None) in counters
    gauges = {c["name"]: c["value"] for c in doc["metrics"]["gauges"]}
    assert gauges["interpreter-concurrency"] == 2
    assert gauges.get("checker-ops-per-s", 0) > 0
    # the collector is deactivated after the run
    assert telemetry.active() is telemetry.NOOP


def test_run_without_telemetry_writes_nothing(tmp_path):
    t = _mem_test(tmp_path)
    t.pop("telemetry")
    done = core.run(t)
    d = store.test_dir(done)
    assert not os.path.exists(os.path.join(d, "telemetry.json"))
    assert not os.path.exists(os.path.join(d, "trace.json"))
    assert telemetry.active() is telemetry.NOOP


def test_composed_checkers_get_named_spans(tmp_path):
    done = core.run(_mem_test(tmp_path, checker=checker_api.compose({
        "stats": checker_api.Stats(),
        "uids": checker_api.UniqueIds()})))
    d = store.test_dir(done)
    doc = json.load(open(os.path.join(d, "telemetry.json")))
    run = next(r for r in doc["spans"] if r["name"] == "run")
    comp = next(c for c in run["children"]
                if c["name"] == "check:Compose")
    sub = sorted(c["name"] for c in comp["children"])
    assert sub == ["check:Stats", "check:UniqueIds"]


def test_analyze_writes_suffixed_telemetry_keeps_run_artifacts(tmp_path):
    t = _mem_test(tmp_path)
    done = core.run(t)
    d = store.test_dir(done)
    run_doc_before = json.load(open(os.path.join(d, "telemetry.json")))
    re = core.analyze(d, checker=checker_api.Stats())
    assert re["results"]["valid?"] is True
    # the original run's artifacts are untouched ...
    run_doc_after = json.load(open(os.path.join(d, "telemetry.json")))
    assert run_doc_after == run_doc_before
    assert os.path.exists(os.path.join(d, "trace.json"))
    # ... and the re-check got its own suffixed set
    doc = json.load(open(os.path.join(d, "telemetry-analyze.json")))
    names = [r["name"] for r in doc["spans"]]
    assert "analyze" in names
    assert os.path.exists(os.path.join(d, "trace-analyze.json"))


def test_consecutive_runs_have_independent_metrics(tmp_path):
    """Two telemetric runs in one process: each run's telemetry.json
    reports only its own counters (per-collector registry)."""
    d1 = store.test_dir(core.run(_mem_test(tmp_path, n_ops=4)))
    d2 = store.test_dir(core.run(_mem_test(tmp_path, n_ops=6)))

    def invokes(d):
        doc = json.load(open(os.path.join(d, "telemetry.json")))
        return sum(c["value"] for c in doc["metrics"]["counters"]
                   if c["name"] == "interpreter-ops"
                   and c["labels"].get("type") == "invoke")

    assert invokes(d1) == 4
    assert invokes(d2) == 6  # not 4 + 6


def test_check_safe_crash_attributes_checker_name():
    """Satellite: composed-checker failures are attributable."""
    class Exploder(checker_api.Checker):
        def check(self, test, history, opts=None):
            raise RuntimeError("kaboom")

    from jepsen_tpu.history.ops import history
    res = checker_api.check_safe(Exploder(), {}, history([]))
    assert res["valid?"] == "unknown"
    assert res["checker"] == "Exploder"
    assert "kaboom" in res["error"]
    # composed: the sub-result carries the failing sub-checker's name
    comp = checker_api.compose({"bad": Exploder(),
                               "ok": checker_api.NoopChecker()})
    res = checker_api.check_safe(comp, {}, history([]))
    assert res["bad"]["checker"] == "Exploder"
    assert res["ok"]["valid?"] is True


def test_check_safe_crash_attribution_with_telemetry_enabled():
    class Exploder(checker_api.Checker):
        def check(self, test, history, opts=None):
            raise RuntimeError("pow")

    from jepsen_tpu.history.ops import history
    c = telemetry.activate()
    try:
        res = checker_api.check_safe(Exploder(), {}, history([]))
    finally:
        telemetry.deactivate(c)
    assert res["valid?"] == "unknown" and res["checker"] == "Exploder"
    (sp,) = c.roots
    assert sp.name == "check:Exploder"
    assert sp.attrs.get("crashed") is True


def test_elle_checker_child_spans(tmp_path):
    from jepsen_tpu.checkers.elle import list_append
    from jepsen_tpu.history.ops import Op, history

    def txn(p, t, mops):
        return [Op(type="invoke", process=p, f="txn", value=mops, time=t),
                Op(type="ok", process=p, f="txn", value=mops,
                   time=t + 1000)]

    ops = txn(0, 0, [["append", "x", 1]]) + \
        txn(1, 5000, [["r", "x", [1]]])
    c = telemetry.activate()
    try:
        with telemetry.span("check:elle"):
            res = list_append.check(history(ops))
    finally:
        telemetry.deactivate(c)
    assert res["valid?"] is True
    (root,) = c.roots
    names = [s["name"] for s in
             [telemetry.export.span_to_dict(x) for x in root.children]]
    assert "elle.infer" in names
    assert "elle.graph-build" in names and "elle.cycle-sweep" in names
    infer = next(x for x in root.children if x.name == "elle.infer")
    assert infer.attrs["device"] is True


# -------------------------------------------------------------- cli/web

def test_cli_trace_command(tmp_path, capsys):
    from jepsen_tpu import cli

    def fn(opts):
        return _mem_test(tmp_path, **{k: v for k, v in opts.items()
                                      if k in ("store-dir", "telemetry")})

    rc = cli.run(cli.single_test_cmd(fn),
                 ["--store-dir", str(tmp_path / "s"), "test",
                  "--telemetry", "--time-limit", "5"])
    assert rc == 0
    capsys.readouterr()
    d = store.latest("tel-test", base=str(tmp_path / "s"))
    rc = cli.run(cli.single_test_cmd(fn), ["trace", d])
    assert rc == 0
    out = capsys.readouterr().out
    assert "run" in out and "workload" in out and "interpreter-ops" in out


def test_cli_trace_no_telemetry(tmp_path, capsys):
    from jepsen_tpu import cli
    t = _mem_test(tmp_path)
    t.pop("telemetry")
    done = core.run(t)
    d = store.test_dir(done)
    rc = cli.run(cli.single_test_cmd(lambda o: t), ["trace", d])
    assert rc == 2
    assert "telemetry" in capsys.readouterr().err


def test_web_telemetry_page(tmp_path):
    import urllib.request

    from jepsen_tpu import web

    base = str(tmp_path / "s")
    done = core.run(_mem_test(tmp_path))
    srv = web.serve(port=0, base=base, background=True)
    try:
        port = srv.server_address[1]
        rel = os.path.relpath(store.test_dir(done), base)

        def get(path):
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}{path}") as r:
                return r.status, r.read()

        status, body = get("/")
        assert status == 200 and b"/telemetry/" in body
        from urllib.parse import quote
        status, body = get(f"/telemetry/{quote(rel)}")
        assert status == 200
        assert b"workload" in body and b"trace.json" in body
    finally:
        srv.shutdown()
        srv.server_close()


# ------------------------------------------------------------- overhead

@pytest.mark.slow
def test_enabled_overhead_under_two_percent(tmp_path):
    """ISSUE 1 acceptance: enabled-collector overhead <2% on a 100k-op
    in-memory run vs disabled.  Slow (two 100k-op runs); excluded from
    tier-1 by the `not slow` marker filter."""
    n = 50_000  # 100k history ops: 50k invokes + 50k completions

    def run_once(with_tel):
        t = _mem_test(tmp_path, n_ops=n)
        if not with_tel:
            t.pop("telemetry")
        t0 = time.perf_counter()
        core.run(t)
        return time.perf_counter() - t0

    run_once(False)  # warm caches/imports
    off = min(run_once(False) for _ in range(2))
    on = min(run_once(True) for _ in range(2))
    assert on <= off * 1.02 + 0.05, (on, off)
