"""Resilience layer tests (ISSUE 2): deterministic fault injection,
retry/backoff, checker deadlines, and device -> host graceful
degradation.  The acceptance contract: under an injected persistent
device fault an elle list-append check degrades to the host oracle with
the fault-free verdict and a ``"degraded": "host-fallback"`` stamp;
under a short deadline a knossos WGL check returns unknown with
``error: deadline-exceeded`` instead of hanging."""

import time

import pytest

from jepsen_tpu import telemetry
from jepsen_tpu.resilience import (
    DEGRADED_HOST,
    Deadline,
    DeadlineExceeded,
    FaultInjected,
    FaultPlan,
    RetryPolicy,
    deadline_result,
    device_call,
    is_transient,
    parse_spec,
    plan_for,
    use,
    with_fallback,
)
from jepsen_tpu.workloads import synth


class _XlaRuntimeError(RuntimeError):
    """Stand-in named like jaxlib's error (the classifier matches on
    type NAME, jaxlib's de-facto ABI)."""


_XlaRuntimeError.__name__ = "XlaRuntimeError"


# ---------------------------------------------------------------- classifier

def test_transient_classifier_xla_taxonomy():
    assert is_transient(_XlaRuntimeError("RESOURCE_EXHAUSTED: oom"))
    assert is_transient(_XlaRuntimeError("UNAVAILABLE: device lost"))
    assert is_transient(_XlaRuntimeError("INTERNAL: failed to compile"))
    # python-side bugs are never transient
    assert not is_transient(TypeError("bad shape"))
    assert not is_transient(RuntimeError("RESOURCE_EXHAUSTED"))  # wrong type
    assert not is_transient(DeadlineExceeded("x"))


def test_synthetic_faults_carry_transience():
    assert is_transient(FaultInjected("oom", "s", 0, transient=True))
    assert not is_transient(FaultInjected("device-lost", "s", 0,
                                          transient=False))


# ---------------------------------------------------------------- FaultPlan

def _fire_seq(plan, n=40, site="site"):
    out = []
    for _ in range(n):
        try:
            plan.fire(site)
            out.append(None)
        except FaultInjected as e:
            out.append(e.kind)
    return out


def test_fault_plan_deterministic():
    # same seed -> same injected faults; different seed -> different
    a = _fire_seq(FaultPlan(seed=7, p=0.3, kinds=("oom", "xla")))
    b = _fire_seq(FaultPlan(seed=7, p=0.3, kinds=("oom", "xla")))
    assert a == b
    assert any(a), "p=0.3 over 40 calls should inject"
    seqs = {tuple(_fire_seq(FaultPlan(seed=s, p=0.3))) for s in range(8)}
    assert len(seqs) > 1, "seed must drive the schedule"


def test_fault_plan_explicit_indices_and_cap():
    plan = FaultPlan(at={1: "xla", 3: "oom"}, max_faults=1)
    seq = _fire_seq(plan, n=6)
    assert seq == [None, "xla", None, None, None, None]  # capped after 1
    assert plan.injected == [(1, "site", "xla")]


def test_fault_plan_site_filter_and_persistent():
    plan = FaultPlan(persistent=("elle.infer",))
    assert _fire_seq(plan, 3, site="other") == [None] * 3
    assert _fire_seq(plan, 2, site="elle.infer") == ["oom", "oom"]


def test_fault_plan_stall_sleeps_not_raises():
    plan = FaultPlan(at={0: "stall"}, stall_s=0.01)
    t0 = time.monotonic()
    plan.fire("s")  # must not raise
    assert time.monotonic() - t0 >= 0.009


def test_parse_spec_env_string():
    d = parse_spec("seed=7, p=0.1, kinds=oom|stall")
    plan = FaultPlan.from_spec(d)
    assert plan.seed == 7 and plan.p == 0.1
    assert plan.kinds == ("oom", "stall")
    assert parse_spec("") is None
    with pytest.raises(ValueError):
        parse_spec("whatisthis")


def test_plan_resolution_order(monkeypatch):
    monkeypatch.setenv("JEPSEN_FAULTS", "seed=3,p=0.5")
    env_plan = plan_for(None)
    assert env_plan is not None and env_plan.seed == 3
    explicit = FaultPlan(seed=9)
    with use(explicit):
        assert plan_for(None) is explicit
        # test-map spec still wins over the installed plan for that run
        t = {"faults": {"seed": 4}}
        assert plan_for(t).seed == 4
        assert plan_for(t) is t["faults-plan"]  # cached: one counter/run
    monkeypatch.delenv("JEPSEN_FAULTS")
    assert plan_for(None) is None


def test_nemesis_style_faults_set_is_not_a_resilience_spec():
    # nemesis/combined.py uses test["faults"] as a set of package names;
    # the resilience resolver must not misread it as an injection spec
    assert plan_for({"faults": {"partition", "kill"}}) is None


# ---------------------------------------------------------------- retry/guard

def test_retry_then_succeed_with_counters():
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise _XlaRuntimeError("RESOURCE_EXHAUSTED: transient")
        return 42

    col = telemetry.activate()
    try:
        pol = RetryPolicy(max_attempts=3, base_delay_s=0.0)
        assert device_call("t.flaky", flaky, policy=pol) == 42
    finally:
        telemetry.deactivate(col)
    retries = [c for c in col.registry.snapshot()["counters"]
               if c["name"] == "resilience-retries"]
    assert retries and retries[0]["value"] == 2


def test_retry_exhaustion_reraises_original_error():
    def always():
        raise _XlaRuntimeError("RESOURCE_EXHAUSTED: persistent")

    with pytest.raises(_XlaRuntimeError):
        device_call("t.persistent", always,
                    policy=RetryPolicy(max_attempts=2, base_delay_s=0.0))


def test_non_transient_raises_immediately():
    calls = []

    def buggy():
        calls.append(1)
        raise TypeError("actual bug")

    with pytest.raises(TypeError):
        device_call("t.bug", buggy,
                    policy=RetryPolicy(max_attempts=5, base_delay_s=0.0))
    assert len(calls) == 1, "non-transient errors must not retry"


def test_retry_policy_delays_seeded():
    p = RetryPolicy(max_attempts=4, base_delay_s=0.1, seed=11)
    assert list(p.delays()) == list(p.delays())
    assert list(p.delays()) != list(
        RetryPolicy(max_attempts=4, base_delay_s=0.1, seed=12).delays())


def test_with_fallback_degrades_and_counts():
    col = telemetry.activate()
    try:
        res, degraded = with_fallback(
            "t.fb", lambda: (_ for _ in ()).throw(
                _XlaRuntimeError("RESOURCE_EXHAUSTED: dead")),
            lambda: "host-answer",
            policy=RetryPolicy(max_attempts=1))
    finally:
        telemetry.deactivate(col)
    assert (res, degraded) == ("host-answer", DEGRADED_HOST)
    names = [c["name"] for c in col.registry.snapshot()["counters"]]
    assert "resilience-fallbacks" in names


# ---------------------------------------------------------------- deadline

def test_deadline_basics():
    assert Deadline(None).remaining() is None
    assert not Deadline(None).expired()
    dl = Deadline(0.0)
    assert dl.expired() and dl.remaining() == 0.0
    with pytest.raises(DeadlineExceeded):
        dl.check("here")
    assert Deadline(60.0).bound_sleep(0.5) == 0.5
    assert Deadline(0.0).bound_sleep(0.5) == 0.0
    assert deadline_result(x=1) == {"valid?": "unknown",
                                    "error": "deadline-exceeded", "x": 1}


def test_deadline_resolution_order():
    shared = Deadline(5.0)
    assert Deadline.resolve({"deadline": shared}) is shared
    assert Deadline.resolve({"time-limit": 1.0}).remaining() <= 1.0
    assert Deadline.resolve({}, {"checker-time-limit": 2.0}) is not None
    assert Deadline.resolve({}, {}) is None
    assert Deadline.resolve(None, None) is None


# --------------------------------------------- elle: degrade + deadline

def test_elle_persistent_fault_degrades_to_host_same_verdict():
    from jepsen_tpu.checkers.elle import list_append

    h = synth.la_history(n_txns=60, seed=3)
    col = telemetry.activate()
    try:
        clean = list_append.check(h)
        faulted = list_append.check(
            h, plan=FaultPlan(persistent=("elle.infer",)),
            policy=RetryPolicy(max_attempts=2, base_delay_s=0.0))
    finally:
        telemetry.deactivate(col)
    assert faulted["valid?"] == clean["valid?"]
    assert faulted["degraded"] == DEGRADED_HOST
    assert "FaultInjected" in faulted["device-error"]
    counters = {c["name"] for c in col.registry.snapshot()["counters"]}
    assert {"resilience-faults-injected", "resilience-retries",
            "resilience-fallbacks"} <= counters


def test_elle_invalid_history_same_verdict_through_fallback():
    # degradation must preserve INVALID verdicts too, not just valid ones
    from jepsen_tpu.checkers.elle import list_append

    h = synth.la_history(n_txns=60, seed=5)
    assert synth.inject_wr_cycle(h), "injector must land for this seed"
    clean = list_append.check(h)
    faulted = list_append.check(
        h, plan=FaultPlan(persistent=("elle.infer",)),
        policy=RetryPolicy(max_attempts=1))
    assert clean["valid?"] is False
    assert faulted["valid?"] is False
    assert faulted["degraded"] == DEGRADED_HOST
    assert faulted["anomaly-types"] == clean["anomaly-types"]


def test_elle_transient_fault_recovers_on_device():
    from jepsen_tpu.checkers.elle import list_append

    h = synth.la_history(n_txns=60, seed=3)
    faulted = list_append.check(
        h, plan=FaultPlan(at={0: "oom"}),
        policy=RetryPolicy(max_attempts=3, base_delay_s=0.0))
    assert faulted["valid?"] is True
    assert "degraded" not in faulted  # retry succeeded, no fallback


def test_elle_deadline_returns_unknown():
    from jepsen_tpu.checkers.elle import list_append

    h = synth.la_history(n_txns=60, seed=3)
    res = list_append.check(h, deadline=Deadline(0.0))
    assert res["valid?"] == "unknown"
    assert res["error"] == "deadline-exceeded"


def test_expired_deadline_blocks_host_fallback():
    # an expired budget must not buy an unbounded host-oracle run: the
    # deadline trips during the retry backoff, so the result is the
    # canonical deadline unknown — NOT a degraded host verdict
    from jepsen_tpu.checkers.elle import list_append

    h = synth.la_history(n_txns=40, seed=3)
    res = list_append.check(
        h, plan=FaultPlan(persistent=("elle.infer",)),
        policy=RetryPolicy(max_attempts=2, base_delay_s=0.15, jitter=0.0),
        deadline=Deadline(0.05))
    assert res["valid?"] == "unknown"
    assert res["error"] == "deadline-exceeded"
    assert "degraded" not in res


def test_degrade_to_host_stamps_dict_results():
    from jepsen_tpu.resilience import degrade_to_host

    res = degrade_to_host("t.site", lambda: {"valid?": True},
                          _XlaRuntimeError("RESOURCE_EXHAUSTED: x"))
    assert res["degraded"] == DEGRADED_HOST
    assert "RESOURCE_EXHAUSTED" in res["device-error"]
    with pytest.raises(DeadlineExceeded):
        degrade_to_host("t.site", lambda: {"valid?": True},
                        _XlaRuntimeError("RESOURCE_EXHAUSTED: x"),
                        deadline=Deadline(0.0))


def test_rw_register_fault_degrades_to_host(monkeypatch):
    from jepsen_tpu.checkers.elle import rw_register
    from jepsen_tpu.workloads.synth import rw_history

    # shrink the fused-device threshold so the fast path engages
    monkeypatch.setattr(rw_register, "FUSED_MIN_TXNS", 1)
    h = rw_history(n_txns=50, seed=2)
    clean = rw_register.check(h)
    faulted = rw_register.check(
        h, plan=FaultPlan(persistent=("elle.rw-core-check",)),
        policy=RetryPolicy(max_attempts=1))
    assert faulted["valid?"] == clean["valid?"]
    assert faulted.get("degraded") == DEGRADED_HOST


# --------------------------------------------- knossos: deadline

def test_knossos_wgl_deadline_returns_unknown_fast():
    # the tier-1 hog: seed 5's info-dense history held the device
    # blocked search >90s; a 1s deadline must bound it with the
    # canonical verdict shape
    from jepsen_tpu.checkers.knossos import device_wgl
    from jepsen_tpu.checkers.knossos.prep import prepare
    from jepsen_tpu.checkers.knossos.search import Search
    from jepsen_tpu.models import cas_register

    h = synth.lin_register_history(n_ops=120, concurrency=5,
                                   stale_read_prob=0.25, info_prob=0.3,
                                   seed=5)
    ops = prepare(h)
    t0 = time.monotonic()
    res = device_wgl._blocked_and_check(
        list(ops), cas_register(), ctl=Search(deadline=Deadline(1.0)))
    dt = time.monotonic() - t0
    assert res["valid?"] == "unknown"
    assert res["error"] == "deadline-exceeded"
    assert res.get("explored", 0) >= 0  # partial stats ride along
    assert dt < 15, f"deadline did not bound the search ({dt:.1f}s)"


def test_knossos_analysis_deadline_plumbs_through():
    from jepsen_tpu.checkers.knossos import analysis
    from jepsen_tpu.models import cas_register

    h = synth.lin_register_history(n_ops=120, concurrency=5,
                                   stale_read_prob=0.25, info_prob=0.3,
                                   seed=5)
    res = analysis(h, cas_register(), algorithm="device",
                   deadline=Deadline(1.0))
    assert res["valid?"] == "unknown"
    assert res["error"] == "deadline-exceeded"


# --------------------------------------------- check_safe integration

def test_check_safe_creates_deadline_from_test_map():
    from jepsen_tpu.checkers import api as checker_api

    seen = {}

    class Slow(checker_api.Checker):
        def check(self, test, history, opts=None):
            seen["deadline"] = (opts or {}).get("deadline")
            seen["deadline"].check("slow-checker")
            return {"valid?": True}

    res = checker_api.check_safe(Slow(), {"checker-time-limit": 0.0},
                                 [], None)
    assert isinstance(seen["deadline"], Deadline)
    assert res == {"valid?": "unknown", "checker": "Slow",
                   "error": "deadline-exceeded"}


def test_check_safe_composed_checkers_share_one_deadline():
    from jepsen_tpu.checkers import api as checker_api

    seen = []

    class Probe(checker_api.Checker):
        def check(self, test, history, opts=None):
            seen.append((opts or {}).get("deadline"))
            return {"valid?": True}

    chk = checker_api.compose({"a": Probe(), "b": Probe()})
    res = checker_api.check_safe(chk, {"checker-time-limit": 30.0}, [],
                                 None)
    assert res["valid?"] is True
    assert len(seen) == 2 and seen[0] is seen[1] is not None


def test_check_safe_no_limit_no_deadline():
    from jepsen_tpu.checkers import api as checker_api

    seen = {}

    class Probe(checker_api.Checker):
        def check(self, test, history, opts=None):
            seen["opts"] = opts
            return {"valid?": True}

    checker_api.check_safe(Probe(), {}, [], None)
    assert not (seen["opts"] or {}).get("deadline")


def test_append_checker_deadline_via_checker_time_limit():
    # end-to-end: test map "checker-time-limit" -> check_safe ->
    # AppendChecker -> list_append deadline poll
    from jepsen_tpu.checkers import api as checker_api
    from jepsen_tpu.workloads.append import AppendChecker

    h = synth.la_history(n_txns=40, seed=1)
    res = checker_api.check_safe(AppendChecker(),
                                 {"checker-time-limit": 0.0}, h, None)
    assert res["valid?"] == "unknown"
    assert res["error"] == "deadline-exceeded"


# --------------------------------------------- nemesis satellites

def test_partitioner_works_without_net_key():
    # nemesis/core.py:164 used to KeyError on tests without "net"
    from jepsen_tpu.nemesis.core import Partitioner, partition_halves

    t = {"nodes": ["n1", "n2"]}
    nem = Partitioner(partition_halves).setup(t)
    comp = nem.invoke(t, {"f": "start-partition", "value": None})
    assert comp["type"] == "info"
    nem.invoke(t, {"f": "stop-partition", "value": None})
    nem.teardown(t)


def test_noop_test_has_net():
    from jepsen_tpu import core, net

    assert isinstance(core.noop_test()["net"], net.Net)


def test_traffic_shaper_drives_net_protocol():
    from jepsen_tpu import net as net_
    from jepsen_tpu.nemesis.core import TrafficShaper

    t = {"nodes": ["n1", "n2"], "net": net_.SimNet()}
    nem = TrafficShaper().setup(t)
    nem.invoke(t, {"f": "slow", "value": {"mean_ms": 100.0}})
    assert t["net"].shaping == ["slow", {"mean_ms": 100.0}]
    nem.invoke(t, {"f": "flaky", "value": None})
    assert t["net"].shaping[0] == "flaky"
    nem.invoke(t, {"f": "shape", "value": ["delay", "50ms"]})
    assert t["net"].shaping == ["delay", "50ms"]
    comp = nem.invoke(t, {"f": "fast", "value": None})
    assert comp["type"] == "info" and t["net"].shaping is None
    with pytest.raises(ValueError):
        nem.invoke(t, {"f": "nonsense"})
    nem.teardown(t)


def test_traffic_package_composes():
    from jepsen_tpu import net as net_
    from jepsen_tpu.nemesis import combined

    pkg = combined.nemesis_package({"faults": {"traffic"}, "interval": 0})
    assert pkg["generator"] is not None
    t = {"nodes": ["n1"], "net": net_.SimNet()}
    nem = pkg["nemesis"].setup(t)
    comp = nem.invoke(t, {"f": "slow", "value": {"mean_ms": 10.0}})
    assert comp["type"] == "info"
    assert t["net"].shaping is not None
    nem.invoke(t, {"f": "fast", "value": None})
    assert t["net"].shaping is None
    assert combined.traffic_package({"faults": {"partition"}}) is None


# ------------------------------------------------- interpreter fault site

def _interp_test(concurrency, plan, seed=0, ops=24):
    import random

    from jepsen_tpu import core as jcore
    from jepsen_tpu.generator import core as g
    from jepsen_tpu.workloads.mem import MemClient

    return jcore.noop_test(
        name="interp-faults", concurrency=concurrency,
        client=MemClient(),
        generator=g.clients(g.limit(ops, synth.la_generator(
            n_keys=3, rng=random.Random(seed)))),
        faults=plan)


def test_interpreter_fault_site_is_opt_in():
    """A checker-chaos plan that does not NAME the interpreter site
    must never touch the workload — even at p=1 (ISSUE 4 satellite:
    client-side chaos is requested by naming the site)."""
    from jepsen_tpu.generator import interpreter

    plan = FaultPlan(p=1.0, kinds=("oom",))
    assert not plan.targets_site(interpreter.FAULT_SITE)
    h = interpreter.run(_interp_test(2, plan))
    assert len(plan.injected) == 0
    assert all(op.type != "info" for op in h), \
        "opt-out plan crashed client ops"


def test_interpreter_stalls_and_infos_deterministic():
    """sites=("interpreter",): crash kinds complete ops as attributed
    :info (process re-opened), stalls just add latency; a single-worker
    run pair injects and completes identically (seeded determinism)."""
    from jepsen_tpu.generator import interpreter

    def run_once():
        plan = FaultPlan(seed=5, p=0.4, kinds=("oom", "stall"),
                         stall_s=0.001, sites=("interpreter",))
        h = interpreter.run(_interp_test(1, plan, seed=5))
        return plan, h

    p1, h1 = run_once()
    p2, h2 = run_once()
    assert p1.injected == p2.injected and p1.injected, p1.injected
    shape = lambda h: [(op.type, op.process, op.f, op.value, op.error)
                       for op in h]
    assert shape(h1) == shape(h2)
    infos = [op for op in h1 if op.type == "info"]
    assert infos, "no crash-kind faults landed (raise p or ops)"
    assert all(str(op.error).startswith("fault-injected") for op in infos)
    # crashed processes were re-opened on a fresh process id
    # (concurrency=1: process 0 crashes -> next incarnation is 1)
    assert any(isinstance(op.process, int) and op.process >= 1
               for op in h1)


def test_interpreter_fault_site_persistent_form():
    """persistent=("interpreter",) also targets the site: EVERY op
    info-completes, and the run still terminates with a history."""
    from jepsen_tpu.generator import interpreter

    plan = FaultPlan(persistent=("interpreter",), kinds=("oom",))
    assert plan.targets_site(interpreter.FAULT_SITE)
    h = interpreter.run(_interp_test(2, plan, ops=10))
    infos = [op for op in h if op.type == "info"]
    assert len(infos) == 10
    assert len(plan.injected) == 10
