"""Control-plane integration suite: real OS processes (VERDICT r04
item 6).  regd daemons are installed, started, crashed, restarted, and
log-snarfed exclusively through `jepsen_tpu.control` — the reference's
`jepsen.control` usage pattern — with a checker verdict at the end."""

import os

import pytest

from jepsen_tpu import core
from jepsen_tpu import db as db_proto
from jepsen_tpu.dbs import regd_suite as rs
from jepsen_tpu.generator import core as g
from jepsen_tpu.nemesis import core as nem


def _opts(tmp_path, base_port):
    return {
        "store-dir": str(tmp_path / "store"),
        "concurrency": 4,
        "base-port": base_port,
    }


def _run(test, limit):
    test["generator"] = g.limit(limit, test["generator"])
    return core.run(test)


def test_regd_append_valid_real_processes(tmp_path):
    """Happy path: 3 real daemon processes, real TCP, checker valid —
    and the artifacts prove the control plane did the work."""
    t = rs.append_test(_opts(tmp_path, 7620))
    done = _run(t, 120)
    res = done["results"]
    assert res["valid?"] is True, res
    oks = [op for op in done["history"]
           if op.type == "ok" and op.f == "txn"]
    # absolute ok counts are load-dependent on a single-core box (the
    # crash test below says the same): writes serialize through the
    # primary's commit+forward lock, and slow daemons surface as client
    # timeouts -> fail, which the checker tolerates.  Under ambient
    # load this box completes as few as 6 of the 120 ops (measured
    # 2026-08-03, flaked at the old >= 10 margin); the semantic claim —
    # real TCP commits happened and were checked — needs only a few.
    assert len(oks) >= 3, len(oks)
    # daemons really ran as OS processes: logs exist (use `done`, the
    # completed test map — it holds the run's store timestamp)
    db = done["db"]
    for node in done["nodes"]:
        paths = db._paths(done, node)
        assert os.path.exists(paths["log"]), paths["log"]
        assert "listening" in open(paths["log"]).read()
    # log download landed the files in the store dir, one dir per node
    from jepsen_tpu import store

    for node in done["nodes"]:
        d = store.path(done, node)
        assert os.path.exists(os.path.join(d, "regd.log")), d


def test_regd_primary_crash_recovery(tmp_path):
    """Kill -9 the primary mid-run via grepkill, restart it via
    start_daemon; WAL replay keeps the history strict-serializable."""
    t = rs.append_test(_opts(tmp_path, 7630))
    db = t["db"]

    killer = nem.node_start_stopper(
        lambda test, nodes: [nodes[0]],       # always the primary
        lambda test, node: db.kill(test, node),
        # restart completes only when the daemon answers pings again,
        # so the post-restart phase always has a live primary
        lambda test, node: db.start_and_await(test, node),
        start_f="kill-primary", stop_f="restart-primary")
    t["nemesis"] = killer
    # progress-driven phases, not wall-clock: commits -> crash -> ops
    # against the dead primary -> awaited restart -> commits again.
    # synchronize() barriers make each phase wait for the previous
    # one's IN-FLIGHT ops (a nemesis gen is exhausted when its op is
    # EMITTED, not completed — without the barrier the post-restart
    # phase races the restart itself)
    wl = t["generator"]
    # g.clients keeps txn ops off the nemesis thread: without it a busy
    # moment routes a txn to the NodeStartStopper, which raises
    t["generator"] = g.then(
        g.clients(g.limit(60, wl)),
        g.then(
            g.synchronize(
                g.nemesis([{"type": "invoke", "f": "kill-primary"}])),
            g.then(
                g.clients(g.limit(60, wl)),
                g.then(
                    g.synchronize(g.nemesis(
                        [{"type": "invoke", "f": "restart-primary"}])),
                    g.synchronize(g.clients(g.limit(60, wl)))))))
    done = core.run(t)
    res = done["results"]
    assert res["valid?"] is True, res
    hist = done["history"]
    oks = [op for op in hist if op.type == "ok" and op.f == "txn"]
    # commits happened on BOTH sides of the crash — the semantic claim;
    # absolute counts are load-dependent on a single-core box
    restart_idx = next(op.index for op in hist
                       if op.f == "restart-primary")
    assert any(op.index < restart_idx for op in oks), "no pre-crash oks"
    assert any(op.index > restart_idx for op in oks), "no post-restart oks"
    # the crash really happened: some client ops failed or went info
    non_ok = [op for op in hist
              if op.type in ("fail", "info") and op.f == "txn"]
    assert non_ok, "kill window produced no failures — nemesis inert?"
    # and the nemesis ops themselves are in the history
    assert any(op.f == "kill-primary" for op in hist)


@pytest.mark.slow  # ~80 s real-daemon soak on this box — tier-1 budget
# hog, and load-flaky under concurrent suites (PR 5 note)
def test_regd_stale_reads_caught(tmp_path):
    """--stale-reads + a blocked backup: local backup reads diverge and
    the checker must find realtime anomalies (the deliberate hole)."""
    opts = _opts(tmp_path, 7640)
    opts["consistency-models"] = ("strict-serializable",)
    t = rs.append_test(opts, stale_reads=True)
    db = t["db"]

    class BlockBackups(nem.Nemesis):
        def invoke(self, test, op):
            if op["f"] == "block":
                # backups drop replication from the primary: their local
                # reads freeze while the primary keeps committing
                for node in test["nodes"][1:]:
                    rs.request(db.port(test, node),
                               {"op": "block",
                                "peers": [test["nodes"][0]]})
            elif op["f"] == "heal":
                for node in test["nodes"][1:]:
                    rs.request(db.port(test, node), {"op": "heal"})
            else:
                raise ValueError(f"unexpected nemesis op {op['f']!r}")
            return dict(op, type="info")

    t["nemesis"] = BlockBackups()
    # block FIRST, hold it across the WHOLE workload (progress-driven,
    # no wall-clock), heal after the last client op completes; clients()
    # keeps txn ops off the nemesis thread (a mis-routed txn would hit
    # BlockBackups and previously healed mid-run — review r05)
    t["generator"] = g.then(
        g.synchronize(g.nemesis([{"type": "invoke", "f": "block"}])),
        g.then(
            g.synchronize(g.clients(g.limit(250, t["generator"]))),
            g.nemesis([{"type": "invoke", "f": "heal"}])))
    done = core.run(t)
    res = done["results"]
    assert res["valid?"] is False, res


def test_regdb_supports_expected_facets():
    db = rs.RegDB()
    assert db_proto.supports(db, db_proto.Process)
    assert db_proto.supports(db, db_proto.Primary)
    assert db_proto.supports(db, db_proto.LogFiles)
    assert not db_proto.supports(db, db_proto.Pause)


def test_regd_wal_torn_tail_recovery(tmp_path):
    """A torn (partial) final WAL line must not swallow later commits on
    the NEXT restart: the store truncates the torn tail before
    appending (review r05 finding — reproduced data loss)."""
    from jepsen_tpu.dbs.regd import Store

    wal = str(tmp_path / "wal.jsonl")
    s1 = Store(wal)
    s1.commit([["append", "x", 1]])
    s1.commit([["append", "x", 2]])
    # simulate a crash mid-write: torn partial record, no newline
    with open(wal, "ab") as f:
        f.write(b'{"txn": [["append", "x", 3')
    s2 = Store(wal)                       # restart 1: drops torn tail
    assert s2.data["x"] == [1, 2]
    s2.commit([["append", "x", 4]])
    s2.commit([["append", "x", 5]])
    s3 = Store(wal)                       # restart 2: 4 and 5 survive
    assert s3.data["x"] == [1, 2, 4, 5], s3.data
