"""The SQLite per-DB suite: a real ACID engine under the full test spine.

Positive: serializable SQLite must check valid under list-append and
rw-register.  Negative: the client's completion semantics (BUSY -> fail,
commit error -> info) and concurrent contention must not produce false
anomalies; and direct dirty-write abuse at the SQL level must be caught
by the checker when we bypass transactions.
"""

import sqlite3

from jepsen_tpu import core
from jepsen_tpu.dbs import sqlite as sq


def _opts(tmp_path):
    return {
        "store-dir": str(tmp_path / "store"),
        "concurrency": 5,
    }


def _run(test, limit):
    from jepsen_tpu.generator import core as g

    test["generator"] = g.limit(limit, test["generator"])
    return core.run(test)


def test_sqlite_append_valid(tmp_path):
    t = sq.append_test(_opts(tmp_path))
    done = _run(t, 120)
    res = done["results"]
    assert res["valid?"] is True, res
    oks = [op for op in done["history"]
           if op.type == "ok" and op.f == "txn"]
    assert len(oks) >= 40  # real commits happened, not all busy-fails


def test_sqlite_wr_valid(tmp_path):
    t = sq.wr_test(_opts(tmp_path))
    done = _run(t, 120)
    res = done["results"]
    assert res["valid?"] is True, res


def test_sqlite_append_reads_are_real(tmp_path):
    # a read after appends must observe a prefix-consistent list
    t = sq.append_test(_opts(tmp_path))
    done = _run(t, 60)
    saw_nonempty = any(
        m[0] == "r" and m[2]
        for op in done["history"] if op.type == "ok" and op.f == "txn"
        for m in op.value)
    assert saw_nonempty


def test_sqlite_busy_completes_as_fail(tmp_path):
    """A writer holding the write lock makes a second writer's BEGIN
    IMMEDIATE fail cleanly: the suite must complete it :fail (not crash,
    not :info)."""
    db = sq.SqliteDB(str(tmp_path / "x.db"), wal=False)
    test = {"leave-db-running": True}
    db.setup(test, "local")
    blocker = sqlite3.connect(str(tmp_path / "x.db"),
                              isolation_level=None)
    blocker.execute("BEGIN IMMEDIATE")
    blocker.execute(
        "INSERT INTO la (k, pos, v) VALUES (0, 1, 1)")
    try:
        c = sq.SqliteClient(db, busy_timeout_ms=50).open(test, "local")
        out = c.invoke(test, {"f": "txn", "process": 0,
                              "value": [["append", 0, 99]]})
        assert out["type"] == "fail"
        c.close(test)
    finally:
        blocker.execute("ROLLBACK")
        blocker.close()


def test_sqlite_checker_catches_injected_corruption(tmp_path):
    """Bypass the client and corrupt the la table mid-run (duplicate an
    element): the append checker must flag the history invalid — the
    negative control proving the suite's checker has teeth.

    The injection races the live workload: it triggers on the first
    ok-append COMPLETION, and completion order is arbitrary under real
    concurrency — that completion can land after every other op in the
    run (seen in practice: the trigger append's completion delayed past
    11 later appends), leaving no subsequent read to observe the
    duplicate, in which case a valid verdict is CORRECT.  So the test
    asserts on the real precondition — some ok read actually CONTAINS
    the duplicated element — and reruns the (inherently racy) workload
    until the duplicate was observable; only then is the verdict
    checked."""
    orig_open = sq.SqliteClient.open
    orig_invoke = sq.SqliteClient.invoke

    for attempt in range(4):
        t = sq.append_test(_opts(tmp_path / f"a{attempt}"))
        db_path = None
        state = {"done": False, "k": None}

        def patched_open(self, test, node):
            nonlocal db_path
            c = orig_open(self, test, node)
            db_path = c._path
            return c

        def patched_invoke(self, test, op):
            # once corrupted, force later txns to read the corrupted key
            # so observation doesn't depend on the workload's random keys
            if state["done"] and op.get("f") == "txn":
                op = dict(op, value=list(op["value"]) +
                          [["r", state["k"], None]])
            out = orig_invoke(self, test, op)
            # after the first successful append, duplicate that element
            if not state["done"] and out["type"] == "ok":
                apps = [m for m in out["value"] if m[0] == "append"]
                if apps:
                    state["done"] = True
                    state["k"] = apps[0][1]
                    k, v = apps[0][1], apps[0][2]
                    dup = sqlite3.connect(db_path)
                    dup.execute(
                        "INSERT INTO la (k, pos, v) VALUES (?, 1 + "
                        "(SELECT MAX(pos) FROM la WHERE k=?), ?)", (k, k, v))
                    dup.commit()
                    dup.close()
            return out

        sq.SqliteClient.open = patched_open
        sq.SqliteClient.invoke = patched_invoke
        try:
            done = _run(t, 80)
        finally:
            sq.SqliteClient.open = orig_open
            sq.SqliteClient.invoke = orig_invoke
        assert state["done"], "corruption was never injected"
        dup_observed = any(
            m[0] == "r" and m[1] == state["k"] and
            isinstance(m[2], list) and len(set(m[2])) < len(m[2])
            for op in done["history"]
            if op.type == "ok" and op.f == "txn"
            for m in op.value)
        if dup_observed:
            break
    else:
        raise AssertionError(
            "duplicate never observable in 4 runs (trigger completion "
            "kept landing after the last read)")
    res = done["results"]
    assert res["valid?"] is not True, res
