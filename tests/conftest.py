"""Test configuration.

Tests run on CPU with 8 virtual devices so multi-chip sharding paths
(jax.sharding.Mesh / shard_map) are exercised without TPU hardware, per
the project's environment contract.  Must run before jax initializes —
the canonical axon-factory-drop workaround lives in
jepsen_tpu.utils.backend (which imports no jax at module scope).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from jepsen_tpu.utils.backend import force_cpu_backend

force_cpu_backend(8)
