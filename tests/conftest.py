"""Test configuration.

Tests run on CPU with 8 virtual devices so multi-chip sharding paths
(jax.sharding.Mesh / shard_map) are exercised without TPU hardware, per the
project's environment contract.  Must run before jax initializes.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
