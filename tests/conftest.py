"""Test configuration.

Tests run on CPU with 8 virtual devices so multi-chip sharding paths
(jax.sharding.Mesh / shard_map) are exercised without TPU hardware, per
the project's environment contract.  Must run before jax initializes —
the canonical axon-factory-drop workaround lives in
jepsen_tpu.utils.backend (which imports no jax at module scope).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from jepsen_tpu.utils.backend import enable_compile_cache, force_cpu_backend

force_cpu_backend(8)

# Persistent test-scoped XLA compile cache: the suite compiles several
# hundred CPU executables and the inter-module jit-cache purge below
# re-compiles shared helpers; pointing jax at an on-disk cache makes both
# the purge re-compiles and full suite re-runs disk hits instead of XLA
# invocations (only compiles > 1 s are persisted, so the dir stays small).
# Disable with JT_NO_TEST_CACHE=1 when chasing a suspected stale-cache bug.
if not os.environ.get("JT_NO_TEST_CACHE"):
    os.environ.setdefault(
        "BENCH_CACHE_DIR",
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), ".jax_cache_tests"))
    enable_compile_cache()

# AOT compile cache: memory-only for the suite. The default resolution
# ("<store>/compilecache when ./store exists") would make persistence
# depend on which earlier test happened to create a default-BASE store
# dir — ordering-dependent disk churn. Tests that exercise persistence
# pin a tmp dir via compilecache.set_cache_dir (overrides this env).
os.environ.setdefault("JT_COMPILECACHE", "mem")

import pytest


def pytest_sessionfinish(session, exitstatus):
    """Record the compile budget (VERDICT r04 item 7): how many XLA
    executables the suite compiled fresh vs served from the persistent
    cache this run.  Printed in the terminal summary."""
    d = os.environ.get("BENCH_CACHE_DIR")
    if not d or not os.path.isdir(d):
        return
    entries = os.listdir(d)
    t0 = getattr(session, "_jt_t0", None)
    fresh = 0
    if t0 is not None:
        for e in entries:
            try:
                if os.path.getmtime(os.path.join(d, e)) >= t0:
                    fresh += 1
            except OSError:
                pass
    print(f"\n[jepsen-tpu] persistent compile cache: {len(entries)} "
          f"entries, {fresh} written this run ({d})")


def pytest_sessionstart(session):
    import time

    session._jt_t0 = time.time()


@pytest.fixture(autouse=True, scope="module")
def _clear_jax_caches_between_modules():
    """Release compiled executables between test modules.

    The full suite compiles several hundred XLA:CPU executables in one
    process; with all of them held live, a late large compile segfaults
    inside `backend_compile_and_load` (reproducible at the same test
    with and without background load).  Dropping the jit caches between
    modules caps live executable memory and keeps the suite green; the
    cost is re-compiling shared helpers a few times (~1 min over the
    whole suite).
    """
    yield
    import jax

    from jepsen_tpu import compilecache

    # the AOT executable table holds Compiled objects jax.clear_caches
    # doesn't see — drop it alongside or it defeats the memory cap
    compilecache.clear()
    jax.clear_caches()
