"""Test configuration.

Tests run on CPU with 8 virtual devices so multi-chip sharding paths
(jax.sharding.Mesh / shard_map) are exercised without TPU hardware, per the
project's environment contract.  Must run before jax initializes.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The axon (TPU tunnel) PJRT plugin is registered at interpreter startup by
# sitecustomize — before this conftest runs.  Backend *initialization* would
# dial the TPU relay even under JAX_PLATFORMS=cpu, so tests must drop the
# factory before any jax backend init.
try:
    import jax
    import jax._src.xla_bridge as _xb

    for _name in ("axon", "tpu"):
        getattr(_xb, "_backend_factories", {}).pop(_name, None)
    # a pytest plugin may have imported jax before this conftest, binding
    # jax_platforms to the outer env's "axon" — override it too
    jax.config.update("jax_platforms", "cpu")
except Exception:
    pass
