"""fleet/autopilot.py — continuous verification as a self-healing,
self-scaling service (ISSUE 17).

Covers the tentpole contracts:

- the **journal**: replay reaches the identical digest, a torn final
  line is ignored by readers and healed writer-side only, scale audit
  events are digest-excluded;
- the **crash window**: kill -9 between the ``gen-open`` journal
  append and the queue enqueue — a restarted autopilot re-admits the
  journaled generation with ZERO duplicate cells and an identical
  journal digest, and a second restart changes nothing;
- **gate rc 2 degrades gracefully**: a streak of unevaluable
  generations (no gateable spans) closes every generation and never
  quarantines;
- **gate rc 1 reacts**: a seeded span regression is gate-caught,
  attributed to the regressing cell key, quarantined (gauge + future
  plans exclude it), auto-shrunk to a witness record in the campaign
  index, with an ``obs diff`` forensics artifact on disk;
- **chaos**: a seeded FaultPlan on every ``autopilot.*`` decision seam
  never wedges the loop — generations still close with attributable
  verdicts;
- the satellites: queue claim-latency p95, ``obs gc`` retention
  archival, `jepsen_fleet_host_info` cardinality, and the
  ``scripts/soak_autopilot.py --fast`` acceptance (kill -9 resume +
  rolling upgrade) as a subprocess smoke.
"""

import json
import os
import subprocess
import sys
import threading
import time

import pytest

from jepsen_tpu import resilience, store, telemetry
from jepsen_tpu.fleet import (
    Autopilot,
    AutopilotJournal,
    WorkQueue,
    autopilot_path,
)

SPEC = {"name": "ap", "workloads": ["bank"], "seeds": [0, 1, 2],
        "opts": {"time-limit": 0.2}}


# ---------------------------------------------------------- helpers

def _drainer(ap, spans_for=None):
    """A synthetic fleet: claim + complete every cell with a verdict
    record (no real execution).  `spans_for(spec) -> dict | None`
    shapes the telemetry the gate sees."""
    stop = threading.Event()

    def loop():
        while not stop.is_set():
            code, out = ap.coordinator.claim({"worker": "syn"})
            if code != 200 or not out.get("spec"):
                time.sleep(0.01)
                continue
            sp = out["spec"]
            key = (f'{sp["workload_label"]}|{sp["fault_label"]}'
                   f'|s{sp["seed"]}')
            rec = {"run": sp["run_id"], "key": key,
                   "workload": sp["workload_label"],
                   "fault": sp["fault_label"], "seed": sp["seed"],
                   "valid?": True, "dir": None}
            if spans_for is not None:
                extra = spans_for(sp)
                if extra:
                    rec.update(extra)
            ap.coordinator.complete({"worker": "syn",
                                     "run": sp["run_id"],
                                     "record": rec})

    t = threading.Thread(target=loop, daemon=True)
    t.start()
    return stop, t


def _run(ap, spans_for=None):
    stop, t = _drainer(ap, spans_for)
    try:
        return ap.run()
    finally:
        ap.stop.set()
        stop.set()
        t.join(timeout=5)
        ap.coordinator.close()


# ---------------------------------------------------------- journal

def test_journal_replay_and_torn_tail(tmp_path):
    p = str(tmp_path / "a.autopilot.jsonl")
    j = AutopilotJournal(p)
    j.open_gen("g0000", seeds=[0, 1], runs=2)
    j.close_gen("g0000", [{"span": None, "status": "insufficient-data",
                           "rc": 2}])
    j.quarantine("bank|nofault|s1", gen="g0001", span="workload",
                 rel_delta=0.6)
    j.shrink("bank|nofault|s1", gen="g0001", outcome={"ops": 3})
    j.scale("spawn", worker="w1", version="v1")
    d = j.digest()
    # replay = identical state; scale events are audit, not state
    r = AutopilotJournal(p)
    assert r.digest() == d
    assert r.scale_events == 1
    assert r.closed_labels() == ["g0000"]
    assert "bank|nofault|s1" in r.quarantined
    # torn tail (crash mid-append): readers ignore it...
    with open(p, "ab") as f:
        f.write(b'{"ev": "quarantine", "key": "to')
    torn = AutopilotJournal(p)
    assert torn.digest() == d
    # ...and only the WRITER heals — the reader left the file alone
    assert open(p, "rb").read().endswith(b'"to')
    torn.scale("drain", worker="w1")
    for line in open(p, "rb").read().splitlines():
        json.loads(line)  # every line whole again
    assert AutopilotJournal(p).digest() == d


# ------------------------------------------------------ crash window

def test_crash_between_gen_open_and_enqueue_resumes_zero_dupes(tmp_path):
    base = str(tmp_path / "store")
    ap1 = Autopilot(SPEC, base, generations=1, poll_s=0.02)
    out = _run(ap1, lambda sp: {"spans": {"workload": 0.1}})
    assert out["generations"] == 1
    # kill -9 window: gen-open journaled, cells never enqueued
    ap1.journal.open_gen("g0001", seeds=[1, 2, 0], runs=3)
    d = AutopilotJournal(autopilot_path("ap", base)).digest()

    # restart: re-admit heals the window — g0000 counts done from the
    # index, g0001 enqueues fresh, nothing duplicates
    ap2 = Autopilot(SPEC, base, poll_s=0.02)
    c = ap2.coordinator.queue.counts()
    assert c["duplicates"] == 0
    assert c["done"] == 3 and c["queued"] == 3
    assert ap2.journal.digest() == d
    ap2.coordinator.close()

    # a second restart is a no-op: enqueue is idempotent on run ids
    ap3 = Autopilot(SPEC, base, poll_s=0.02)
    c = ap3.coordinator.queue.counts()
    assert c["duplicates"] == 0 and c["queued"] == 3 \
        and c["cells"] == 6
    assert ap3.journal.digest() == d
    ap3.coordinator.close()


# -------------------------------------------------- gate rc 2 streak

def test_rc2_streak_closes_generations_never_quarantines(tmp_path):
    base = str(tmp_path / "store")
    ap = Autopilot(SPEC, base, generations=3, poll_s=0.02)
    out = _run(ap, None)  # records carry NO spans: nothing gateable
    assert out["generations"] == 3
    assert out["quarantined"] == []
    for label in ap.journal.closed_labels():
        for v in ap.journal.gens[label]["verdicts"]:
            assert v["rc"] == 2
            assert v["status"] in ("insufficient-data", "gate-error")


# ------------------------------------- regression -> quarantine+shrink

def _regressing_spans(sp):
    """g0001 regresses every cell, seed 2 hardest — attribution is
    deterministic (largest relative delta)."""
    gen = (sp.get("opts") or {}).get("autopilot-gen")
    s = int(sp["seed"])
    dur = (0.3 + 0.01 * s) if gen == "g0001" else (0.1 + 0.001 * s)
    return {"spans": {"workload": dur}, "valid?": gen != "g0001",
            "dir": f"runs/{sp['run_id']}"}


def test_regression_quarantined_and_autoshrunk(tmp_path, monkeypatch):
    from jepsen_tpu import minimize

    shrunk = {}

    def fake_shrink(run_dir, **kw):
        shrunk["dir"] = run_dir
        return {"ops": 3, "source-ops": 12, "digest": "abc123",
                "anomaly-types": ["G-single"], "probes": 5,
                "cached": 1, "fault-windows": []}

    monkeypatch.setattr(minimize, "shrink", fake_shrink)
    base = str(tmp_path / "store")
    ap = Autopilot(SPEC, base, generations=2, spans=("workload",),
                   poll_s=0.02)
    out = _run(ap, _regressing_spans)
    key = "bank|nofault|s2"
    assert out["quarantined"] == [key]
    v = ap.journal.gens["g0001"]["verdicts"][0]
    assert v["status"] == "regression" and v["rc"] == 1
    assert v["key"] == key and v["key-rel-delta"] > 2.0
    # the shrink ran on the quarantined cell's g0001 run dir and its
    # witness record landed in the campaign index
    assert shrunk["dir"].startswith(os.path.join(base, "runs"))
    sk = ap.journal.shrinks[key]
    assert sk["gen"] == "g0001"
    assert sk["outcome"]["digest"] == "abc123"
    wit = [r for r in ap.coordinator.idx.records if r.get("witness")]
    assert len(wit) == 1 and wit[0]["key"] == key
    assert wit[0]["autopilot"]["quarantined"] == "g0001"
    assert wit[0]["witness"]["anomaly-types"] == ["G-single"]
    # forensics artifact on disk, referenced from the witness
    art = wit[0]["autopilot"]["forensics"]
    assert art and os.path.exists(os.path.join(base, art))
    rep = json.load(open(os.path.join(base, art)))
    assert rep["status"] in ("regression", "pass",
                             "insufficient-data")
    # gauge + future plans exclude the cell
    g = {m["name"]: m["value"]
         for m in telemetry.registry().snapshot()["gauges"]}
    assert g["fleet-quarantined-cells"] == 1
    assert [rs.key for rs in ap._plan(2)] == \
        ["bank|nofault|s0", "bank|nofault|s1"]
    # ...but a REPLAY of g0001 (quarantined AT g0001) still plans it
    assert key in [rs.key for rs in ap._plan(1)]
    # the satellites' status surface
    st = ap.coordinator._status()[1]
    assert "queue-depth" in st and "claim-latency-p95-s" in st
    assert st["autopilot"]["quarantined"][key]["span"] == "workload"
    assert st["autopilot"]["journal-digest"] == ap.journal.digest()


# ------------------------------------------- quarantine parole (5d)

def _fixed_then_reoffending_spans(sp):
    """g0001 regresses every cell (seed 2 hardest), the bug is
    'fixed' for two clean generations, then g0005 regresses again —
    the paroled cell re-offends."""
    gen = (sp.get("opts") or {}).get("autopilot-gen")
    s = int(sp["seed"])
    bad = gen in ("g0001", "g0005")
    dur = (0.3 + 0.01 * s) if bad else (0.1 + 0.001 * s)
    return {"spans": {"workload": dur}, "valid?": not bad,
            "dir": f"runs/{sp['run_id']}"}


def test_quarantine_parole_readmits_then_requarantines(
        tmp_path, monkeypatch):
    from jepsen_tpu import minimize

    monkeypatch.setattr(minimize, "shrink", lambda run_dir, **kw: {
        "ops": 3, "source-ops": 12, "digest": "abc123",
        "anomaly-types": ["G-single"], "probes": 5, "cached": 1,
        "fault-windows": []})
    # the shrink above is synthetic (no witness on disk): stand in a
    # passing host-twin verdict so the parole path itself is exercised
    # (the twin gate has its own denial tests below)
    monkeypatch.setattr(
        Autopilot, "_twin_recheck",
        lambda self, key, digest: (True, {"digest": digest,
                                          "checker": "stub",
                                          "valid?": True}))
    base = str(tmp_path / "store")
    ap = Autopilot(SPEC, base, generations=6, spans=("workload",),
                   poll_s=0.02, parole_after=2)
    out = _run(ap, _fixed_then_reoffending_spans)
    key = "bank|nofault|s2"
    assert out["generations"] == 6

    # g0001: quarantined; g0002+g0003 close clean without it ->
    # paroled at g0003's close, back in the plan from g0004 on
    v = ap.journal.quarantined[key]
    assert v["history"] == [{"gen": "g0001", "paroled-gen": "g0003"}]
    assert [g["runs"] for g in
            (ap.journal.gens[l] for l in
             ("g0000", "g0001", "g0002", "g0003", "g0004"))] == \
        [3, 3, 2, 2, 3]

    # g0005 regresses again: the re-offender is re-quarantined with
    # the prior stint archived, and is NOT paroled anew
    assert v["gen"] == "g0005" and "paroled-gen" not in v
    g5 = ap.journal.gens["g0005"]["verdicts"][0]
    assert g5["status"] == "regression" and g5["key"] == key

    # plan membership per generation honors BOTH stints on replay
    plans = {i: [rs.key for rs in ap._plan(i)] for i in range(7)}
    assert key in plans[1]          # quarantined AT g0001's close
    assert key not in plans[2] and key not in plans[3]
    assert key in plans[4] and key in plans[5]
    assert key not in plans[6]      # second stint

    # gauges split active vs paroled; journal replay reaches the
    # identical digest with parole + re-quarantine events applied
    g = {m["name"]: m["value"]
         for m in telemetry.registry().snapshot()["gauges"]}
    assert g["fleet-quarantined-cells"] == 1
    assert g["fleet-paroled-cells"] == 0
    assert AutopilotJournal(ap.journal.path).digest() == \
        ap.journal.digest()


# ------------------------------------------------------------- chaos

def test_chaos_on_every_seam_never_wedges(tmp_path):
    base = str(tmp_path / "store")
    plan = resilience.FaultPlan(
        seed=7, p=0.35, kinds=("oom", "stall"), stall_s=0.005,
        sites="autopilot.enqueue|autopilot.gate|autopilot.shrink"
              "|autopilot.scale")
    ap = Autopilot(SPEC, base, generations=2, spans=("workload",),
                   poll_s=0.02)
    with resilience.use(plan):
        out = _run(ap, lambda sp: {"spans": {"workload": 0.1}})
    assert out["generations"] == 2
    for label in ap.journal.closed_labels():
        for v in ap.journal.gens[label]["verdicts"]:
            assert v["to-gen"] == label  # attributable
            assert v["rc"] in (0, 1, 2)
    # same plan, same call sequence -> the injections were real
    assert plan.injected or plan.p == 0.0


# -------------------------------------------------------- satellites

def test_queue_claim_latency_p95(tmp_path):
    q = WorkQueue(str(tmp_path / "q.jsonl"))
    assert q.claim_latency_p95() is None
    for i in range(4):
        q.enqueue({"run_id": f"r{i}", "campaign": "q",
                   "workload": "set", "seed": i, "opts": {},
                   "fault": None, "fault_label": "nofault",
                   "workload_label": "set", "device": False})
    for _ in range(3):
        q.claim("w", lease_s=9.0)
    lats = q.claim_latencies()
    assert len(lats) == 3 and all(l >= 0 for l in lats)
    assert q.claim_latency_p95() == sorted(lats)[-1]


def test_obs_gc_archives_landed_runs_only(tmp_path):
    base = str(tmp_path / "store")
    now = time.time()

    def mk(name, age_s, landed):
        d = os.path.join(base, name, store.timestamp(now - age_s))
        os.makedirs(d)
        if landed:
            with open(os.path.join(d, "results.json"), "w") as f:
                f.write("{}")
        return d

    old = mk("t", 5000, landed=True)
    fresh = mk("t", 10, landed=True)
    crashed = mk("u", 5000, landed=False)
    stats = store.gc_runs(base, retention_s=3600, now=now)
    assert stats == {"archived": 1, "kept": 1, "skipped": 1}
    assert not os.path.exists(old) and os.path.exists(crashed)
    arch = os.path.join(store.archive_dir(base), "t",
                        os.path.basename(old))
    assert os.path.exists(os.path.join(arch, "results.json"))
    # archived runs leave every live scan (store.tests + warehouse)
    live = store.tests(base=base)
    assert fresh in live and old not in live
    assert all("_archive" not in os.path.relpath(d, base)
               for d in live)
    # idempotent second sweep
    assert store.gc_runs(base, retention_s=3600,
                         now=now)["archived"] == 0


def test_host_info_series_pinned_to_alive_versioned_workers():
    from jepsen_tpu.telemetry import prometheus

    class Fleet:
        name = "f"

        def federated_metrics(self):
            return {"w2": {"version": "v2", "rows": []},
                    "w1": {"version": "v1", "rows": []},
                    "old": {"rows": []}}  # pre-17 worker: no series

        def counts(self):
            return {}

    lines = prometheus.render_fleet(Fleet())
    info = [l for l in lines if "jepsen_fleet_host_info" in l
            and not l.startswith("#")]
    assert info == [
        'jepsen_fleet_host_info{host="w1",version="v1"} 1',
        'jepsen_fleet_host_info{host="w2",version="v2"} 1']


def test_soak_autopilot_fast():
    """The unattended acceptance: generations streamed, a seeded
    regression gate-caught -> quarantined -> auto-shrunk, the
    gate-regression alert walking pending -> firing -> resolved with a
    second kill -9 landing MID-FIRING (alert journal replays to the
    identical digest, zero duplicate notifications), coordinator
    kill -9 resume with zero duplicate cells, rolling worker upgrade
    with flat /metrics cardinality."""
    script = os.path.join(os.path.dirname(__file__), "..",
                          "scripts", "soak_autopilot.py")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run([sys.executable, script, "--fast"],
                         capture_output=True, text=True, timeout=420,
                         env=env)
    sys.stdout.write(out.stdout[-3000:])
    sys.stderr.write(out.stderr[-3000:])
    assert out.returncode == 0
    assert "SOAK PASS" in out.stdout
    assert "duplicates=0" in out.stdout
    assert "quarantined=" in out.stdout
    assert "alert-arc=pending->firing->resolved" in out.stdout
